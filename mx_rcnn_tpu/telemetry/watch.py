"""Watchtower: fleet-wide alerting, error budgets and metric history
(ISSUE 20).

Four PRs of *emitters* — PR-1 telemetry, PR-5 live /metrics + flight
recorder, PR-6 latency hists + SLO controller, PR-16 distributed
tracing — and zero *consumers*: nothing watched the signals, so a
recompile storm or a p99 burn was only discovered when an operator
curled /metrics.  :class:`Watchtower` closes the loop.  It is a control
loop in the PR-6/PR-18 mold: one injectable ``tick(now=None)`` step that
tests drive with a fake clock and production wraps in a daemon monitor
thread.

Each tick samples every registered counter/gauge (plus, on the router,
the folded fleet view) into a bounded, downsampled
:class:`MetricHistory` ring (raw → 10s → 60s tiers), then evaluates a
declarative **alert-rule pack** against it.  Four rule kinds, all
computed from signals that already exist:

- **threshold** — any counter/gauge/hist-quantile vs a bound, as a raw
  value, a windowed ``rate`` or a windowed ``delta``, with an optional
  ``guard`` clause (fire only while another series also holds);
- **burn_rate** — real error-budget semantics: a tick *violates* when
  the windowed p-quantile (PR-6 hist snapshots) exceeds ``target_ms``
  AND the histogram advanced (no traffic burns no budget); the rule
  fires on dual-window burn (``fast_burn``× budget over the fast window
  and ``slow_burn``× over the slow one — the classic page-worthy
  fast/slow pair);
- **absence** — staleness: a fleet member not serving, or a local
  series that *stopped changing* (armed only after it changed once, so
  a feature that never ran cannot fire its stall alert; likewise a
  fleet member arms only once it has been ready — a cold boot still
  warming up is not a page);
- **trend** — the PR-6/PR-18 least-squares slope over any series
  (recompiles must be flat after warmup).

Every rule may set ``scope: "fleet"`` (router only): threshold/trend
evaluate per member over ``member/<name>/<metric>`` series, absence
watches membership itself, burn_rate diffs per-member summary
histograms — each instance labeled ``{"member": ...}``.

Alert lifecycle: ``pending`` →(held ``for_s``)→ ``firing`` →
``resolved``, deduplicated by a stable fingerprint of (name, labels).
Every transition is first-class telemetry: an ``alert_transition`` meta
event, an atomic ``alerts_<member>.jsonl`` record (new JSONL kind
``alert`` — additive, old readers ignore it), and — the forensic
payoff — a firing alert dumps the flight ring with the PR-16
tail-sampled trace ids from the breach window attached, so "p99 alert"
arrives with the slow-request span trees that explain it.  Silences
(by alertname, with expiry) mute the noise without losing the record.

Watchtower-off (the default) constructs nothing: no thread, no ring
growth, /metrics byte-for-byte unchanged — pinned by test, the same
contract as every prior plane.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.telemetry import tracectx
from mx_rcnn_tpu.telemetry.sink import quantile_from_counts


def _slope(points) -> float:
    """Least-squares slope of [(t, y)] — the same estimator as the PR-6
    SLO controller's queue trend, re-stated here because the telemetry
    layer must stay stdlib-only (importing ``serve.controller`` would
    pull the whole serve package, jax included, into every watch-less
    tool that reads alert logs)."""
    n = len(points)
    if n < 2:
        return 0.0
    t0 = points[0][0]
    xs = [t - t0 for t, _ in points]
    ys = [float(y) for _, y in points]
    mx = sum(xs) / n
    my = sum(ys) / n
    denom = sum((x - mx) ** 2 for x in xs)
    if denom <= 0:
        return 0.0
    return sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom

# the pack shipped with the repo — serve p99 burn, shed rate, steady-
# state recompile, member staleness, parked-fleet-under-load, flywheel
# generation stall (see README "Alerting & error budgets")
DEFAULT_RULES_PATH = os.path.join(os.path.dirname(__file__),
                                  "rules_default.json")

ALERTS_PREFIX = "alerts_"        # alerts_<member>.jsonl transition log
TRANSITION_KEEP = 1000           # transitions kept (and rewritten) per log


@dataclass(frozen=True)
class WatchOptions:
    interval_s: float = 1.0      # monitor tick period
    raw_keep: int = 256          # raw samples kept per series
    mid_keep: int = 360          # 10s buckets kept (~1 h)
    coarse_keep: int = 1440      # 60s buckets kept (~1 day)
    mid_step_s: float = 10.0     # mid-tier bucket width
    coarse_step_s: float = 60.0  # coarse-tier bucket width
    resolved_keep: int = 64      # resolved alerts kept for /alerts + Prom
    max_series: int = 512        # history ring hard cap (drop + count past)

    def __post_init__(self):
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if self.raw_keep < 2 or self.mid_keep < 2 or self.coarse_keep < 2:
            raise ValueError("history tiers need at least 2 slots each")
        if not 0 < self.mid_step_s < self.coarse_step_s:
            raise ValueError("need 0 < mid_step_s < coarse_step_s — the "
                             "tiers downsample, they don't overlap")
        if self.resolved_keep < 1:
            raise ValueError("resolved_keep must be >= 1")
        if self.max_series < 1:
            raise ValueError("max_series must be >= 1")


class _Series:
    """One metric's history: a raw ring plus two downsampled tiers.

    Each tier accumulates into a current bucket ``{t, last, min, max,
    count}`` and flushes it to the tier's deque when ``now`` crosses the
    bucket edge — O(1) per sample, bounded memory, and the merge in
    :meth:`MetricHistory.series` stitches the tiers into one timeline
    (raw where it reaches, mid beyond it, coarse beyond that)."""

    __slots__ = ("raw", "mid", "coarse", "mid_cur", "coarse_cur",
                 "last_value", "last_change_t", "changed_ever")

    def __init__(self, opts: WatchOptions):
        self.raw: collections.deque = collections.deque(
            maxlen=opts.raw_keep)
        self.mid: collections.deque = collections.deque(
            maxlen=opts.mid_keep)
        self.coarse: collections.deque = collections.deque(
            maxlen=opts.coarse_keep)
        self.mid_cur: Optional[dict] = None
        self.coarse_cur: Optional[dict] = None
        self.last_value: Optional[float] = None
        self.last_change_t: Optional[float] = None
        self.changed_ever = False


def _bucket_add(cur: Optional[dict], ring: collections.deque,
                step: float, now: float, value: float) -> dict:
    start = (now // step) * step
    if cur is None or cur["t"] != start:
        if cur is not None:
            ring.append(cur)
        cur = {"t": start, "last": value, "min": value, "max": value,
               "count": 0}
    cur["last"] = value
    cur["min"] = min(cur["min"], value)
    cur["max"] = max(cur["max"], value)
    cur["count"] += 1
    return cur


class MetricHistory:
    """Bounded in-process history for every registered series.

    Powers rule windows, the ``/history?metric=&window=`` endpoint and
    ``scripts/alert_query.py`` sparklines.  Thread-safe; at most
    ``max_series`` series are tracked (extras are dropped and counted —
    a runaway label cardinality must not eat the server's heap)."""

    def __init__(self, opts: Optional[WatchOptions] = None):
        self.opts = opts or WatchOptions()
        self._series: Dict[str, _Series] = {}
        self._lock = threading.Lock()
        self.dropped = 0

    def record(self, name: str, value: float, now: float):
        value = float(value)
        with self._lock:
            s = self._series.get(name)
            if s is None:
                if len(self._series) >= self.opts.max_series:
                    self.dropped += 1
                    return
                s = self._series[name] = _Series(self.opts)
            s.raw.append((now, value))
            s.mid_cur = _bucket_add(s.mid_cur, s.mid,
                                    self.opts.mid_step_s, now, value)
            s.coarse_cur = _bucket_add(s.coarse_cur, s.coarse,
                                       self.opts.coarse_step_s, now, value)
            if s.last_value is None:
                s.last_value, s.last_change_t = value, now
            elif value != s.last_value:
                s.last_value, s.last_change_t = value, now
                s.changed_ever = True

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def value(self, name: str) -> Optional[float]:
        with self._lock:
            s = self._series.get(name)
            return None if s is None else s.last_value

    def last_change_age(self, name: str,
                        now: float) -> Tuple[Optional[float], bool]:
        """``(seconds since the series last changed value, has it ever
        changed)`` — the absence rule's arming pair."""
        with self._lock:
            s = self._series.get(name)
            if s is None or s.last_change_t is None:
                return None, False
            return now - s.last_change_t, s.changed_ever

    def series(self, name: str, window_s: float,
               now: float) -> List[Tuple[float, float]]:
        """``[(t, value)]`` over the trailing window, stitched across
        tiers: raw points where the raw ring reaches, mid buckets
        (``last``) before that, coarse buckets before the mid tier."""
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return []
            raw = list(s.raw)
            mid = list(s.mid) + ([s.mid_cur] if s.mid_cur else [])
            coarse = list(s.coarse) + ([s.coarse_cur] if s.coarse_cur
                                       else [])
        cutoff = now - window_s
        raw_floor = raw[0][0] if raw else now
        mid_floor = mid[0]["t"] if mid else raw_floor
        pts = [(b["t"], b["last"]) for b in coarse
               if b["t"] < mid_floor]
        pts += [(b["t"], b["last"]) for b in mid if b["t"] < raw_floor]
        pts += raw
        return [(t, v) for t, v in pts if t >= cutoff]

    def mean(self, name: str, window_s: float, now: float,
             default: float = 0.0) -> float:
        pts = self.series(name, window_s, now)
        if not pts:
            return default
        return sum(v for _, v in pts) / len(pts)

    def to_doc(self, name: str, window_s: float, now: float) -> dict:
        pts = self.series(name, window_s, now)
        doc = {"metric": name, "window_s": window_s,
               "points": [[round(t, 3), v] for t, v in pts]}
        if pts:
            vals = [v for _, v in pts]
            doc.update(last=vals[-1], min=min(vals), max=max(vals),
                       mean=sum(vals) / len(vals))
        return doc

    def stats(self) -> dict:
        with self._lock:
            return {"series": len(self._series), "dropped": self.dropped}


# -- rule pack -----------------------------------------------------------

class RuleError(ValueError):
    """An invalid alert rule — the message names the offending rule."""


_KINDS = ("threshold", "burn_rate", "absence", "trend")
_COMMON_KEYS = {"name", "kind", "severity", "for_s", "labels", "scope"}
_KIND_KEYS = {
    "threshold": {"metric", "op", "value", "mode", "window_s", "guard"},
    "burn_rate": {"metric", "quantile", "target_ms", "budget",
                  "fast_window_s", "slow_window_s", "fast_burn",
                  "slow_burn"},
    "absence": {"metric", "value"},
    "trend": {"metric", "window_s", "slope_gt", "warmup_s", "min_points"},
}


def _num(rule_id, raw, key, default=None, required=False, gt=None,
         ge=None, lt=None, le=None):
    v = raw.get(key, default)
    if v is None:
        if required:
            raise RuleError(f"{rule_id}: missing required key {key!r}")
        return None
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise RuleError(f"{rule_id}: {key} must be a number, got {v!r}")
    v = float(v)
    if gt is not None and not v > gt:
        raise RuleError(f"{rule_id}: {key} must be > {gt}, got {v}")
    if ge is not None and not v >= ge:
        raise RuleError(f"{rule_id}: {key} must be >= {ge}, got {v}")
    if lt is not None and not v < lt:
        raise RuleError(f"{rule_id}: {key} must be < {lt}, got {v}")
    if le is not None and not v <= le:
        raise RuleError(f"{rule_id}: {key} must be <= {le}, got {v}")
    return v


def _check_guard(rule_id, guard):
    if not isinstance(guard, dict):
        raise RuleError(f"{rule_id}: guard must be an object")
    extra = set(guard) - {"metric", "op", "value"}
    if extra:
        raise RuleError(f"{rule_id}: guard has unknown keys "
                        f"{sorted(extra)}")
    if not isinstance(guard.get("metric"), str) or not guard["metric"]:
        raise RuleError(f"{rule_id}: guard.metric must be a non-empty "
                        "string")
    if guard.get("op", ">") not in (">", "<"):
        raise RuleError(f"{rule_id}: guard.op must be '>' or '<'")
    _num(rule_id, guard, "value", required=True)
    return {"metric": guard["metric"], "op": guard.get("op", ">"),
            "value": float(guard["value"])}


def validate_rules(doc) -> List[dict]:
    """Validate + normalize a rule pack (``{"version": 1, "rules":
    [...]}`` or a bare list).  Raises :class:`RuleError` naming the
    offending rule; returns rules with every default filled in."""
    if isinstance(doc, dict):
        if doc.get("version", 1) != 1:
            raise RuleError(f"unsupported rule pack version "
                            f"{doc.get('version')!r} (expected 1)")
        rules = doc.get("rules")
    else:
        rules = doc
    if not isinstance(rules, list):
        raise RuleError("rule pack must be a list of rules or "
                        '{"version": 1, "rules": [...]}')
    out: List[dict] = []
    seen = set()
    for i, raw in enumerate(rules):
        rule_id = f"rule {i}"
        if not isinstance(raw, dict):
            raise RuleError(f"{rule_id}: must be an object, got "
                            f"{type(raw).__name__}")
        name = raw.get("name")
        if not isinstance(name, str) or not name:
            raise RuleError(f"{rule_id}: missing required key 'name'")
        rule_id = f"rule {i} ({name!r})"
        if name in seen:
            raise RuleError(f"{rule_id}: duplicate rule name")
        seen.add(name)
        kind = raw.get("kind")
        if kind not in _KINDS:
            raise RuleError(f"{rule_id}: kind must be one of "
                            f"{list(_KINDS)}, got {kind!r}")
        extra = set(raw) - _COMMON_KEYS - _KIND_KEYS[kind]
        if extra:
            raise RuleError(f"{rule_id}: unknown keys {sorted(extra)} "
                            f"for kind {kind!r}")
        labels = raw.get("labels", {})
        if not isinstance(labels, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in labels.items()):
            raise RuleError(f"{rule_id}: labels must map strings to "
                            "strings")
        scope = raw.get("scope", "local")
        if scope not in ("local", "fleet"):
            raise RuleError(f"{rule_id}: scope must be 'local' or "
                            f"'fleet', got {scope!r}")
        rule = {"name": name, "kind": kind,
                "severity": str(raw.get("severity", "warning")),
                "for_s": _num(rule_id, raw, "for_s", default=0.0, ge=0.0),
                "labels": dict(labels), "scope": scope}
        metric = raw.get("metric")
        if not isinstance(metric, str) or not metric:
            raise RuleError(f"{rule_id}: missing required key 'metric'")
        rule["metric"] = metric
        if kind == "threshold":
            op = raw.get("op")
            if op not in (">", "<"):
                raise RuleError(f"{rule_id}: op must be '>' or '<', "
                                f"got {op!r}")
            rule["op"] = op
            rule["value"] = _num(rule_id, raw, "value", required=True)
            mode = raw.get("mode", "value")
            if mode not in ("value", "rate", "delta"):
                raise RuleError(f"{rule_id}: mode must be 'value', "
                                f"'rate' or 'delta', got {mode!r}")
            rule["mode"] = mode
            rule["window_s"] = _num(rule_id, raw, "window_s",
                                    default=60.0, gt=0.0)
            rule["guard"] = (_check_guard(rule_id, raw["guard"])
                            if raw.get("guard") is not None else None)
        elif kind == "burn_rate":
            rule["quantile"] = _num(rule_id, raw, "quantile",
                                    default=0.99, gt=0.0, lt=1.0)
            rule["target_ms"] = _num(rule_id, raw, "target_ms",
                                     required=True, gt=0.0)
            rule["budget"] = _num(rule_id, raw, "budget", default=0.05,
                                  gt=0.0, le=1.0)
            rule["fast_window_s"] = _num(rule_id, raw, "fast_window_s",
                                         default=60.0, gt=0.0)
            rule["slow_window_s"] = _num(rule_id, raw, "slow_window_s",
                                         default=300.0, gt=0.0)
            if rule["slow_window_s"] < rule["fast_window_s"]:
                raise RuleError(f"{rule_id}: slow_window_s must be >= "
                                "fast_window_s — the slow window is the "
                                "sustained check")
            rule["fast_burn"] = _num(rule_id, raw, "fast_burn",
                                     default=6.0, gt=0.0)
            rule["slow_burn"] = _num(rule_id, raw, "slow_burn",
                                     default=2.0, gt=0.0)
        elif kind == "absence":
            rule["value"] = _num(rule_id, raw, "value", required=True,
                                 gt=0.0)
        else:  # trend
            rule["window_s"] = _num(rule_id, raw, "window_s",
                                    default=120.0, gt=0.0)
            rule["slope_gt"] = _num(rule_id, raw, "slope_gt",
                                    required=True)
            rule["warmup_s"] = _num(rule_id, raw, "warmup_s",
                                    default=0.0, ge=0.0)
            rule["min_points"] = int(_num(rule_id, raw, "min_points",
                                          default=3, ge=2))
        out.append(rule)
    return out


def load_rules(path: str) -> List[dict]:
    """Load + validate a rule pack file (``--alert-rules``)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise RuleError(f"alert rules {path}: {e}") from e
    try:
        return validate_rules(doc)
    except RuleError as e:
        raise RuleError(f"alert rules {path}: {e}") from e


def default_rules() -> List[dict]:
    return load_rules(DEFAULT_RULES_PATH)


def fingerprint(name: str, labels: Dict[str, str]) -> str:
    """Stable dedup key for one alert instance: same (rule, labels) →
    same fingerprint across fire/resolve/refire cycles and processes."""
    blob = name + "|" + "|".join(f"{k}={v}"
                                 for k, v in sorted(labels.items()))
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:12]


def fleet_from_pool(pool, now: Optional[float] = None) -> dict:
    """The router's fleet view for rule evaluation, normalized from
    :meth:`ReplicaPool.metrics` — per-member serving state plus the
    fleet aggregates the default pack watches (``fleet/demand``,
    ``fleet/parked``, ``fleet/generation``)."""
    now = time.monotonic() if now is None else now
    doc = pool.metrics(now=now)
    parked_addrs = set(pool.parked_members())
    members = {}
    for name, m in doc["members"].items():
        members[name] = {
            "state": m["state"],
            "ready": bool(m["routable"]),
            "parked": m["address"] in parked_addrs,
            "age_s": m["queue_depth_age_s"],
            "queue_depth": float(m["queue_depth"] or 0),
            "inflight": float(m["inflight"]),
            "generation": float(m["generation"]),
        }
    return {"members": members,
            "fleet/members": float(len(members)),
            "fleet/ready": float(doc["ready"]),
            "fleet/parked": float(len(parked_addrs)),
            "fleet/demand": float(pool.demand(now)),
            "fleet/generation": float(doc["generation"])}


# -- the watchtower ------------------------------------------------------

class Watchtower:
    """The alerting control loop over one process's telemetry (and, on
    the router, the folded fleet).

    ``tick(now=None)`` is one evaluation step and returns the list of
    transition records it emitted (empty on a quiet tick) so tests can
    assert the lifecycle without threads.  ``start()`` wraps it in the
    standard daemon monitor; ``stop()`` joins it.

    Providers are injectable (the deterministic-test surface):
    ``summary_fn`` → a :meth:`Telemetry.summary`-shaped dict sampled
    into history each tick; ``hists_fn`` → live :class:`Hist` objects
    for burn/quantile rules; ``fleet_fn`` → a :func:`fleet_from_pool`
    doc (router only); ``summaries_fn`` → per-member summary dicts for
    fleet-scoped burn rules."""

    def __init__(self, rules: Optional[List[dict]] = None,
                 opts: Optional[WatchOptions] = None,
                 member: str = "rank0", out_dir: Optional[str] = None,
                 summary_fn: Optional[Callable[[], dict]] = None,
                 hists_fn: Optional[Callable[[], dict]] = None,
                 fleet_fn: Optional[Callable[[], dict]] = None,
                 summaries_fn: Optional[Callable[[], dict]] = None):
        self.opts = opts or WatchOptions()
        self.rules = validate_rules(rules if rules is not None
                                    else default_rules())
        self.member = str(member)
        self.out_dir = out_dir
        self._summary_fn = summary_fn
        self._hists_fn = hists_fn
        self._fleet_fn = fleet_fn
        self._summaries_fn = summaries_fn
        self.history = MetricHistory(self.opts)
        self._lock = threading.Lock()
        self._instances: Dict[str, dict] = {}   # fingerprint → instance
        self._resolved: collections.deque = collections.deque(
            maxlen=self.opts.resolved_keep)
        self._transitions: collections.deque = collections.deque(
            maxlen=TRANSITION_KEEP)
        self._silences: List[dict] = []
        self._silence_seq = 0
        self._burn_count: Dict[str, float] = {}  # hist-count watermarks
        self._hist_snaps: Dict[str, collections.deque] = {}
        self._last_fleet: Optional[dict] = None
        self._armed_members: set = set()        # fleet members seen ready
        self._last_summaries: Dict[str, dict] = {}
        self._first_tick_t: Optional[float] = None
        self._last_firing_gauge: Optional[int] = None
        self.ticks = 0
        self.counters = {"ticks": 0, "evals": 0, "rule_errors": 0,
                         "transitions": 0, "fired": 0, "resolved": 0,
                         "silenced": 0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if self.out_dir:
            os.makedirs(self.out_dir, exist_ok=True)

    def count(self, key: str, inc: int = 1):
        """Watch counter + the matching ``watch/*`` telemetry counter —
        one source for ``state()`` and the report table."""
        self.counters[key] = self.counters.get(key, 0) + inc
        telemetry.get().counter(f"watch/{key}", inc)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Watchtower":
        assert self._thread is None, "watchtower already started"

        def monitor():
            while not self._stop.wait(self.opts.interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — alerting must survive
                    logger.exception("watchtower tick failed")

        self._thread = threading.Thread(target=monitor, name="watchtower",
                                        daemon=True)
        self._thread.start()
        logger.info("watchtower: up — %d rule(s), tick %.1fs, member %s",
                    len(self.rules), self.opts.interval_s, self.member)
        return self

    def stop(self, timeout: float = 10.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # -- sampling --------------------------------------------------------

    def _hists(self) -> dict:
        if self._hists_fn is not None:
            try:
                return self._hists_fn() or {}
            except Exception:  # noqa: BLE001 — a dying engine is not news
                logger.exception("watchtower: hists provider failed")
                return {}
        tel = telemetry.get()
        return tel.live_hists() if tel.enabled else {}

    def _sample(self, now: float):
        """One history sample: every counter (raw value) and gauge
        (last) in the summary, plus the fleet aggregates and per-member
        series on the router."""
        summary = None
        try:
            if self._summary_fn is not None:
                summary = self._summary_fn()
            elif telemetry.get().enabled:
                summary = telemetry.get().summary()
        except Exception:  # noqa: BLE001
            logger.exception("watchtower: summary provider failed")
        if isinstance(summary, dict):
            for k, v in (summary.get("counters") or {}).items():
                self.history.record(k, v, now)
            for k, g in (summary.get("gauges") or {}).items():
                last = g.get("last") if isinstance(g, dict) else g
                if last is not None:
                    self.history.record(k, last, now)
        self._last_fleet = None
        if self._fleet_fn is not None:
            try:
                self._last_fleet = self._fleet_fn()
            except Exception:  # noqa: BLE001
                logger.exception("watchtower: fleet provider failed")
        if self._last_fleet:
            for k, v in self._last_fleet.items():
                if k == "members":
                    continue
                self.history.record(k, v, now)
            for m, info in self._last_fleet["members"].items():
                for k in ("queue_depth", "inflight", "generation"):
                    if info.get(k) is not None:
                        self.history.record(f"member/{m}/{k}",
                                            info[k], now)
        self._last_summaries = {}
        if self._summaries_fn is not None:
            try:
                self._last_summaries = self._summaries_fn() or {}
            except Exception:  # noqa: BLE001
                logger.exception("watchtower: summaries provider failed")

    # -- the evaluation step ---------------------------------------------

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation: sample → evaluate every rule → fold the
        lifecycle → emit telemetry.  Returns the transition records."""
        now = time.monotonic() if now is None else now
        self.ticks += 1
        self.counters["ticks"] += 1
        if self._first_tick_t is None:
            self._first_tick_t = now
        self._sample(now)
        self._prune_silences(now)
        transitions: List[dict] = []
        for rule in self.rules:
            try:
                for labels, active, value in self._eval(rule, now):
                    self.counters["evals"] += 1
                    self._fold(rule, labels, active, value, now,
                               transitions)
            except Exception:  # noqa: BLE001 — one bad rule must not
                logger.exception("watchtower: rule %s failed",
                                 rule["name"])  # silence the others
                self.counters["rule_errors"] += 1
        firing = sum(1 for i in self._instances.values()
                     if i["state"] == "firing")
        if firing != self._last_firing_gauge:
            telemetry.get().gauge("watch/firing", firing)
            self._last_firing_gauge = firing
        return transitions

    def _eval(self, rule: dict, now: float):
        kind = rule["kind"]
        if kind == "threshold":
            return self._eval_threshold(rule, now)
        if kind == "burn_rate":
            return self._eval_burn(rule, now)
        if kind == "absence":
            return self._eval_absence(rule, now)
        return self._eval_trend(rule, now)

    # threshold ----------------------------------------------------------

    @staticmethod
    def _cmp(v: float, op: str, bound: float) -> bool:
        return v > bound if op == ">" else v < bound

    def _series_value(self, name: str, rule: dict,
                      now: float) -> Optional[float]:
        """The threshold operand: a hist quantile (``metric@p99``, in
        ms), the series' last value, or a windowed rate/delta."""
        if "@p" in name:
            base, _, digits = name.rpartition("@p")
            if digits.isdigit():
                h = self._hists().get(base)
                if h is None:
                    return None
                q = float(digits) / (10 ** len(digits))
                qv = h.window_quantile(q, rule["window_s"], now=now)
                if qv is None:
                    return None
                ms = qv * 1000.0
                self.history.record(name, ms, now)  # sparkline source
                return ms
        if rule["mode"] == "value":
            return self.history.value(name)
        pts = self.history.series(name, rule["window_s"], now)
        if len(pts) < 2:
            return None
        delta = pts[-1][1] - pts[0][1]
        if rule["mode"] == "delta":
            return delta
        span = pts[-1][0] - pts[0][0]
        return delta / span if span > 0 else None

    def _guard_holds(self, rule: dict) -> bool:
        g = rule.get("guard")
        if g is None:
            return True
        v = self.history.value(g["metric"])
        return v is not None and self._cmp(v, g["op"], g["value"])

    def _eval_threshold(self, rule: dict, now: float):
        targets = [(rule["metric"], {})]
        if rule["scope"] == "fleet" and self._last_fleet:
            targets = [(f"member/{m}/{rule['metric']}", {"member": m})
                       for m in sorted(self._last_fleet["members"])]
        out = []
        guard_ok = self._guard_holds(rule)
        for series, labels in targets:
            v = self._series_value(series, rule, now)
            active = (v is not None and guard_ok
                      and self._cmp(v, rule["op"], rule["value"]))
            out.append((labels, active, v))
        return out

    # burn rate ----------------------------------------------------------

    def _violation_bit(self, key: str, rule: dict, now: float,
                       qv: Optional[float], advanced: bool) -> float:
        """One tick's budget spend: 1 when the windowed quantile broke
        target while the histogram advanced (no traffic → no burn —
        windowed quantiles never decay to None on an idle hist, so the
        advance gate is what lets a fired burn alert resolve)."""
        bit = 1.0 if (advanced and qv is not None
                      and qv * 1000.0 > rule["target_ms"]) else 0.0
        self.history.record(key, bit, now)
        return bit

    def _burn_state(self, key: str, rule: dict,
                    now: float) -> Tuple[bool, float]:
        fast = self.history.mean(key, rule["fast_window_s"], now)
        slow = self.history.mean(key, rule["slow_window_s"], now)
        burn_fast = fast / rule["budget"]
        burn_slow = slow / rule["budget"]
        active = (burn_fast >= rule["fast_burn"]
                  and burn_slow >= rule["slow_burn"])
        return active, round(burn_fast, 4)

    def _eval_burn(self, rule: dict, now: float):
        out = []
        if rule["scope"] == "fleet":
            for m in sorted(self._last_summaries):
                d = ((self._last_summaries[m] or {}).get("hists") or
                     {}).get(rule["metric"])
                key = f"alert/{rule['name']}/{m}/violation"
                qv, advanced = self._summary_quantile(key, rule, d, now)
                self._violation_bit(key, rule, now, qv, advanced)
                active, burn = self._burn_state(key, rule, now)
                out.append(({"member": m}, active, burn))
            return out
        h = self._hists().get(rule["metric"])
        key = f"alert/{rule['name']}/violation"
        cnt = float(h.count) if h is not None else 0.0
        advanced = cnt > self._burn_count.get(key, 0.0)
        self._burn_count[key] = cnt
        qv = (h.window_quantile(rule["quantile"], rule["fast_window_s"],
                                now=now) if h is not None else None)
        self._violation_bit(key, rule, now, qv, advanced)
        active, burn = self._burn_state(key, rule, now)
        out.append(({}, active, burn))
        return out

    def _summary_quantile(self, key: str, rule: dict, d: Optional[dict],
                          now: float) -> Tuple[Optional[float], bool]:
        """Fleet burn operand: the windowed quantile of one member's
        summary histogram, from the delta between the current dict and
        the retained snapshot at the fast-window edge."""
        snaps = self._hist_snaps.setdefault(
            key, collections.deque(maxlen=512))
        if not isinstance(d, dict) or "buckets" not in d:
            return None, False
        count = int(d.get("count", 0))
        buckets = [int(c) for c in d["buckets"]]
        le = d.get("le") or []
        prev_count = snaps[-1][1] if snaps else 0
        base = None
        cutoff = now - rule["fast_window_s"]
        for t, c, b in reversed(snaps):
            if t <= cutoff:
                base = (c, b)
                break
        snaps.append((now, count, tuple(buckets)))
        advanced = count > prev_count
        if base is None:
            n, counts = count, buckets
        else:
            n = count - base[0]
            counts = [max(x - y, 0)
                      for x, y in zip(buckets, base[1])]
        if n <= 0:
            return None, advanced
        return quantile_from_counts(le, counts, n, rule["quantile"]), \
            advanced

    # absence ------------------------------------------------------------

    def _eval_absence(self, rule: dict, now: float):
        if rule["scope"] == "fleet":
            out = []
            members = (self._last_fleet or {}).get("members") or {}
            for m in sorted(members):
                info = members[m]
                if info.get("parked"):
                    # a parked member is intentionally idle spare
                    # capacity, not a stale member
                    continue
                if info.get("ready"):
                    self._armed_members.add(m)
                if m not in self._armed_members:
                    # never-yet-ready: a cold boot still warming up is a
                    # scale-up in progress, not a stale member — the
                    # fleet mirror of the local arming gate below; it
                    # arms the first time it serves (and stays armed
                    # across kill/evict/rejoin under the same name)
                    continue
                age = info.get("age_s")
                stale = (not info.get("ready")) or (
                    age is not None and age > rule["value"])
                out.append(({"member": m}, stale, age))
            return out
        age, changed = self.history.last_change_age(rule["metric"], now)
        active = bool(changed) and age is not None \
            and age > rule["value"]
        return [({}, active, age)]

    # trend --------------------------------------------------------------

    def _eval_trend(self, rule: dict, now: float):
        warm = (self._first_tick_t is not None
                and now - self._first_tick_t >= rule["warmup_s"])
        targets = [(rule["metric"], {})]
        if rule["scope"] == "fleet" and self._last_fleet:
            targets = [(f"member/{m}/{rule['metric']}", {"member": m})
                       for m in sorted(self._last_fleet["members"])]
        out = []
        for series, labels in targets:
            pts = self.history.series(series, rule["window_s"], now)
            if not warm or len(pts) < rule["min_points"]:
                out.append((labels, False, None))
                continue
            slope = _slope(pts)
            out.append((labels, slope > rule["slope_gt"],
                        round(slope, 6)))
        return out

    # -- lifecycle fold ---------------------------------------------------

    def _fold(self, rule: dict, labels: Dict[str, str], active: bool,
              value, now: float, transitions: List[dict]):
        all_labels = dict(rule["labels"], **labels)
        fp = fingerprint(rule["name"], all_labels)
        with self._lock:
            inst = self._instances.get(fp)
        if active:
            if inst is None:
                inst = {"rule": rule, "alert": rule["name"],
                        "severity": rule["severity"],
                        "labels": all_labels, "fingerprint": fp,
                        "state": "pending", "since": now,
                        "fired_at": None, "value": value,
                        "trace_ids": []}
                with self._lock:
                    self._instances[fp] = inst
                transitions.append(
                    self._transition(inst, "pending", now))
            inst["value"] = value
            if inst["state"] == "pending" \
                    and now - inst["since"] >= rule["for_s"]:
                inst["state"] = "firing"
                inst["fired_at"] = now
                inst["trace_ids"] = self._breach_traces()
                self.count("fired")
                rec = self._transition(
                    inst, "firing", now,
                    held_s=round(now - inst["since"], 3),
                    trace_ids=inst["trace_ids"])
                transitions.append(rec)
                if not rec.get("silenced"):
                    telemetry.get().dump_flight(
                        "alert_firing", alert=inst["alert"],
                        severity=inst["severity"],
                        fingerprint=fp, labels=all_labels,
                        value=value, trace_ids=inst["trace_ids"])
                    logger.warning(
                        "ALERT firing: %s (%s) %s value=%s",
                        inst["alert"], inst["severity"], all_labels,
                        value)
        elif inst is not None:
            with self._lock:
                self._instances.pop(fp, None)
            if inst["state"] == "firing":
                firing_s = round(now - inst["fired_at"], 3)
                self.count("resolved")
                transitions.append(
                    self._transition(inst, "resolved", now,
                                     firing_s=firing_s))
                with self._lock:
                    self._resolved.append(
                        {"alert": inst["alert"],
                         "severity": inst["severity"],
                         "labels": inst["labels"], "fingerprint": fp,
                         "resolved_at": now, "firing_s": firing_s})
                logger.info("ALERT resolved: %s %s after %.1fs",
                            inst["alert"], all_labels, firing_s)
            # a pending that clears before the hold is not an incident:
            # no resolved record, the pending record stands alone

    def _breach_traces(self) -> List[str]:
        tracer = tracectx.get()
        if not getattr(tracer, "enabled", False):
            return []
        try:
            return tracer.tail_trace_ids()
        except Exception:  # noqa: BLE001 — forensics are best-effort
            return []

    def _transition(self, inst: dict, state: str, now: float,
                    **extra) -> dict:
        rec = {"v": 1, "t": time.time(), "kind": "alert",
               "member": self.member, "alert": inst["alert"],
               "severity": inst["severity"], "state": state,
               "fingerprint": inst["fingerprint"],
               "labels": inst["labels"], "value": inst["value"]}
        rec.update(extra)
        if self._is_silenced(inst["alert"], now):
            rec["silenced"] = True
            if state == "firing":
                self.count("silenced")
        self.count("transitions")
        with self._lock:
            self._transitions.append(rec)
        self._write_log()
        telemetry.get().meta(
            "alert_transition", alert=rec["alert"], state=state,
            severity=rec["severity"], fingerprint=rec["fingerprint"],
            labels=rec["labels"], value=rec["value"])
        return rec

    def _write_log(self):
        """Atomic rewrite of the bounded transition log — transitions
        are rare, and a reader never sees a torn line."""
        if not self.out_dir:
            return
        path = os.path.join(self.out_dir,
                            f"{ALERTS_PREFIX}{self.member}.jsonl")
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with self._lock:
            recs = list(self._transitions)
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                for rec in recs:
                    f.write(json.dumps(rec) + "\n")
            os.replace(tmp, path)
        except OSError:
            logger.exception("watchtower: alert log write failed")
            try:
                os.unlink(tmp)
            except OSError:
                pass

    # -- silences --------------------------------------------------------

    def silence(self, alertname: str, duration_s: float,
                now: Optional[float] = None) -> int:
        """Mute one alertname for ``duration_s`` seconds.  A silenced
        alert still runs its full lifecycle and still logs transitions
        (marked ``silenced``) — it is excluded from the firing list,
        the Prometheus family and the flight dump, not from history."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._silence_seq += 1
            sid = self._silence_seq
            self._silences.append({"id": sid, "alertname": str(alertname),
                                   "until": now + float(duration_s)})
        logger.info("watchtower: silence #%d on %s for %.0fs", sid,
                    alertname, duration_s)
        return sid

    def unsilence(self, sid: int) -> bool:
        with self._lock:
            before = len(self._silences)
            self._silences = [s for s in self._silences
                              if s["id"] != sid]
            return len(self._silences) < before

    def _prune_silences(self, now: float):
        with self._lock:
            self._silences = [s for s in self._silences
                              if s["until"] > now]

    def _is_silenced(self, alertname: str, now: float) -> bool:
        with self._lock:
            return any(s["alertname"] == alertname and s["until"] > now
                       for s in self._silences)

    # -- introspection ---------------------------------------------------

    def _instance_doc(self, inst: dict, now: float) -> dict:
        doc = {"alert": inst["alert"], "severity": inst["severity"],
               "labels": inst["labels"],
               "fingerprint": inst["fingerprint"],
               "state": inst["state"],
               "since_s": round(now - inst["since"], 3),
               "value": inst["value"]}
        if inst["state"] == "firing":
            doc["trace_ids"] = list(inst["trace_ids"])
        return doc

    def firing(self, now: Optional[float] = None) -> List[dict]:
        """Currently-firing, unsilenced alert instances."""
        now = time.monotonic() if now is None else now
        with self._lock:
            insts = list(self._instances.values())
        return [self._instance_doc(i, now) for i in insts
                if i["state"] == "firing"
                and not self._is_silenced(i["alert"], now)]

    def alerts_doc(self, now: Optional[float] = None) -> dict:
        """The ``/alerts`` endpoint document."""
        now = time.monotonic() if now is None else now
        with self._lock:
            insts = list(self._instances.values())
            resolved = list(self._resolved)
            silences = [dict(s) for s in self._silences]
        firing, pending, silenced = [], [], []
        for i in insts:
            doc = self._instance_doc(i, now)
            if self._is_silenced(i["alert"], now):
                silenced.append(doc)
            elif i["state"] == "firing":
                firing.append(doc)
            else:
                pending.append(doc)
        for s in silences:
            s["expires_in_s"] = round(s.pop("until") - now, 3)
        return {"v": 1, "member": self.member, "ticks": self.ticks,
                "rules": len(self.rules), "firing": firing,
                "pending": pending, "silenced": silenced,
                "resolved": [dict(r, age_s=round(now - r["resolved_at"],
                                                 3))
                             for r in resolved],
                "silences": silences,
                "counters": dict(self.counters)}

    def history_doc(self, metric: str, window_s: float = 300.0,
                    now: Optional[float] = None) -> dict:
        """The ``/history?metric=&window=`` endpoint document."""
        now = time.monotonic() if now is None else now
        return self.history.to_doc(metric, window_s, now)

    def state(self) -> dict:
        """JSON-able watch state for the ``/metrics`` pane."""
        with self._lock:
            firing = sum(1 for i in self._instances.values()
                         if i["state"] == "firing")
            pending = sum(1 for i in self._instances.values()
                          if i["state"] == "pending")
            silences = len(self._silences)
        return {"rules": len(self.rules), "ticks": self.ticks,
                "firing": firing, "pending": pending,
                "silences": silences,
                "history": self.history.stats(),
                "counters": dict(self.counters)}


# -- Prometheus exposition ----------------------------------------------

def _esc(s: str) -> str:
    return (str(s).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def alert_state_lines(watch: Optional[Watchtower],
                      now: Optional[float] = None) -> List[str]:
    """The ``mxr_alert_state`` family: 1 firing, 0.5 pending, 0 for the
    retained resolved set — appended to the serve/fabric Prometheus
    text the same way ``fabric_member_count`` is.  Empty (not an empty
    family) when the watchtower is off: byte parity."""
    if watch is None:
        return []
    now = time.monotonic() if now is None else now
    lines = ["# HELP mxr_alert_state Alert lifecycle state "
             "(1=firing, 0.5=pending, 0=recently resolved).",
             "# TYPE mxr_alert_state gauge"]
    with watch._lock:
        insts = list(watch._instances.values())
        resolved = list(watch._resolved)
    live = set()

    def label_str(alert, severity, labels):
        parts = [f'alertname="{_esc(alert)}"',
                 f'severity="{_esc(severity)}"',
                 f'member="{_esc(labels.get("member", watch.member))}"']
        parts += [f'{k}="{_esc(v)}"' for k, v in sorted(labels.items())
                  if k != "member"]
        return "{" + ",".join(parts) + "}"

    for i in insts:
        if watch._is_silenced(i["alert"], now):
            continue
        live.add(i["fingerprint"])
        v = "1" if i["state"] == "firing" else "0.5"
        lines.append("mxr_alert_state"
                     + label_str(i["alert"], i["severity"], i["labels"])
                     + f" {v}")
    seen = set()
    for r in reversed(resolved):
        if r["fingerprint"] in live or r["fingerprint"] in seen:
            continue
        seen.add(r["fingerprint"])
        lines.append("mxr_alert_state"
                     + label_str(r["alert"], r["severity"], r["labels"])
                     + " 0")
    return lines
