"""Distributed request tracing: cross-hop trace context over the
telemetry substrate (ISSUE 16 tentpole).

The serving plane is distributed — fabric routing with hedges and
breakers, pool scheduling, stream gates — but PR-1/5/6 observability
stops at per-process aggregates: histograms say *that* p99 degraded and
nothing can say *why* for any single request.  This module adds the
request-scoped layer:

* :class:`TraceContext` — a 128-bit trace id + 64-bit parent span id +
  sampled flag, minted at whichever frontend first sees the request (or
  accepted from an ``X-Mxr-Trace`` header / ``"trace"`` doc field) and
  propagated through every hop: fabric router pick/hedge/retry/breaker
  decisions, pool model scheduling, stream skip-vs-forward verdicts, and
  the engine batcher's **batch-causality** spans (each dispatch span
  records the rids of every request that shared it; each request span
  records its batch peers, queue position, and pad fraction — so "my
  request was slow" resolves to "it waited behind another tenant's burst
  in bucket (600, 800) at occupancy 3/8").
* :class:`Tracer` — the live sink.  Spans ride the existing telemetry
  JSONL schema (``kind: "span"`` records, schema v1) with ADDITIVE
  fields (``trace``/``sid``/``psid``/``member``/``attrs``) that old
  readers ignore, written to ``spans_<member>.jsonl`` under the
  telemetry dir — one file per fabric member, merged by trace id in
  ``scripts/trace_query.py``.  Counters (``trace/spans_emitted`` /
  ``trace/spans_dropped`` / ``trace/tail_kept``) mirror into whatever
  telemetry sink is active, so Prometheus grows ``mxr_trace_*`` families
  for free.
* **Tail sampling** — every span is buffered per live trace; when the
  trace's ROOT span ends, the full tree is kept only when the request
  was slow (root duration at or above the windowed-p99 of roots seen in
  the trailing window), errored, or was hedged/retried/shed.  Kept trees
  land in a budget-bounded ring dumped to ``trace_tail_<member>.jsonl``
  (atomic tmp+rename, the flight-recorder contract) so the forensics for
  the requests that matter survive even when the spans stream didn't.
* :class:`NullTracer` — the disabled default, the
  ``NULL_CAPTURE.record_batch`` contract enforced the same hard way:
  every recording method RAISES, so tests can pin that a tracing-off
  engine adds zero work on the hot path (one ``tracer.enabled``
  attribute check per batch, nothing else).

Stdlib only — no jax import; safe in frontends, routers, and the
loader's producer threads.
"""

from __future__ import annotations

import json
import os
import random
import re
import threading
import time
from collections import deque
from typing import Optional

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.telemetry.sink import Hist, SCHEMA_VERSION

# the one propagation header, hop to hop: "trace[-span[-flags]]"
# (32 hex chars - 16 hex chars - 2 hex chars; flags 01 = sampled)
TRACE_HEADER = "X-Mxr-Trace"

# per-member file names under the telemetry dir (the query tool globs
# both; members sharing a dir never collide — one file per member name)
SPANS_PREFIX = "spans_"
TAIL_PREFIX = "trace_tail_"

# env opt-in: subprocess members (tests/fabric_worker.py, smoke scripts)
# enable tracing without new CLI plumbing
ENV_TRACE_DIR = "MXR_TRACE_DIR"
ENV_TRACE_MEMBER = "MXR_TRACE_MEMBER"
ENV_TRACE_SAMPLE = "MXR_TRACE_SAMPLE"

# budget bounds: spans per live trace (a runaway loop must not hold one
# trace's list forever) and concurrently-live traces (roots that never
# finalize — crashed hops — are evicted oldest-first, unkept)
MAX_SPANS_PER_TRACE = 64
MAX_LIVE_TRACES = 1024

_ID_RE = re.compile(r"^[0-9a-f]{8,32}$")
_SID_RE = re.compile(r"^[0-9a-f]{1,16}$")


def _trace_id() -> str:
    return os.urandom(16).hex()


def _span_id() -> str:
    return os.urandom(8).hex()


class TraceContext:
    """One hop's view of a trace: which trace, which parent span, and
    whether spans should be recorded at all.  ``span_id`` is the span
    any child recorded under this context hangs from — ``None`` marks a
    context with no parent yet (freshly minted, or a bare client-sent
    trace id), whose first span is the trace's ROOT."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: str, span_id: Optional[str] = None,
                 sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled

    @classmethod
    def mint(cls, sampled: bool = True) -> "TraceContext":
        return cls(_trace_id(), None, sampled)

    @classmethod
    def parse(cls, value) -> Optional["TraceContext"]:
        """Accept ``trace``, ``trace-span``, or ``trace-span-flags``
        (the header grammar); None on anything malformed — a frontend
        mints fresh rather than serving a garbage id downstream."""
        if not isinstance(value, str):
            return None
        parts = value.strip().lower().split("-")
        if not parts or not _ID_RE.match(parts[0]):
            return None
        span = None
        sampled = True
        if len(parts) >= 2:
            if not _SID_RE.match(parts[1]):
                return None
            # all-zero span id = "no parent" (the client-mint idiom)
            span = None if set(parts[1]) == {"0"} else parts[1]
        if len(parts) >= 3:
            sampled = parts[2] != "00"
        if len(parts) > 3:
            return None
        return cls(parts[0], span, sampled)

    def to_header(self) -> str:
        return (f"{self.trace_id}-{self.span_id or '0' * 16}-"
                f"{'01' if self.sampled else '00'}")

    def child(self) -> "TraceContext":
        """A downstream context parented on a fresh span id."""
        return TraceContext(self.trace_id, _span_id(), self.sampled)

    def __repr__(self):
        return (f"TraceContext({self.trace_id!r}, {self.span_id!r}, "
                f"sampled={self.sampled})")


class _NullTraceSpan:
    """The no-op span: hops call ``.set()`` and read ``.ctx``
    unconditionally, so the unsampled path needs an inert twin."""

    __slots__ = ()
    ctx = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        pass


NULL_SPAN = _NullTraceSpan()


class TraceSpan:
    """Context manager timing one hop.  ``.ctx`` is the context to hand
    downstream (same trace, this span as parent); ``.set(**attrs)``
    attaches hop decisions (picked member, hedged, skipped, status...)
    to the record."""

    __slots__ = ("_tracer", "_pctx", "name", "attrs", "ctx", "_t0", "_w0")

    def __init__(self, tracer: "Tracer", pctx: TraceContext, name: str,
                 attrs: dict):
        self._tracer = tracer
        self._pctx = pctx
        self.name = name
        self.attrs = dict(attrs)
        self.ctx = TraceContext(pctx.trace_id, _span_id(), True)
        self._t0 = self._w0 = None

    def __enter__(self):
        self._w0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def set(self, **attrs):
        self.attrs.update(attrs)

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - (self._t0 or time.perf_counter())
        if exc_type is not None:
            self.attrs.setdefault("error",
                                  f"{exc_type.__name__}: {exc}"[:200])
        self._tracer.record(self._pctx, self.name, dur, ts=self._w0,
                            attrs=self.attrs, sid=self.ctx.span_id)
        return False


class NullTracer:
    """Tracing disabled: one ``enabled`` attribute check on hot paths,
    and — the :data:`~mx_rcnn_tpu.flywheel.capture.NULL_CAPTURE`
    contract enforced the same hard way — recording methods RAISE, so a
    round-trip with tracing off proves the hot path never reached the
    sink."""

    enabled = False
    member = "0"
    rank = 0
    counters: dict = {}

    def mint(self, sampled: bool = True):
        raise RuntimeError("tracing is disabled; hot paths must not mint")

    def span(self, ctx, name, **attrs):
        raise RuntimeError("tracing is disabled; hot paths must not record")

    def record(self, ctx, name, dur_s, ts=None, attrs=None, sid=None):
        raise RuntimeError("tracing is disabled; hot paths must not record")

    def dump_tail(self):
        return None

    def tail_trace_ids(self, since=None, limit=16):
        return []

    def flush(self):
        pass

    def close(self):
        pass


NULL_TRACER = NullTracer()


class Tracer:
    """The live tracing sink for one process (one fabric member).

    Spans stream to ``spans_<member>.jsonl`` under ``out_dir`` in the
    telemetry JSONL schema (``kind: "span"`` + additive trace fields);
    full trees of slow/errored/hedged/retried/shed requests are kept in
    a bounded ring and dumped to ``trace_tail_<member>.jsonl``."""

    enabled = True

    def __init__(self, out_dir: str, member: str = "0", rank: int = 0,
                 sample: float = 1.0, tail_budget: int = 256,
                 tail_window_s: float = 60.0, tail_quantile: float = 0.99):
        self.out_dir = out_dir
        self.member = re.sub(r"[^A-Za-z0-9._-]", "_", str(member)) or "0"
        self.rank = int(rank)
        self.sample = min(max(float(sample), 0.0), 1.0)
        self.tail_quantile = float(tail_quantile)
        self.tail_window_s = float(tail_window_s)
        self._rng = random.Random(os.urandom(8))
        self._lock = threading.Lock()
        self._file = None
        self._live: "dict[str, list]" = {}   # trace_id -> [span rec]
        self._tail: deque = deque(maxlen=max(int(tail_budget), 1))
        self._root_hist = Hist()  # root durations → windowed-p99 gate
        self.counters = {"spans_emitted": 0, "spans_dropped": 0,
                         "tail_kept": 0}
        if out_dir:
            os.makedirs(out_dir, exist_ok=True)
            self.spans_path = os.path.join(
                out_dir, f"{SPANS_PREFIX}{self.member}.jsonl")
            self._file = open(self.spans_path, "w")

    # -- recording -------------------------------------------------------

    def mint(self, sampled: Optional[bool] = None) -> TraceContext:
        """A fresh root context, honoring the configured sample rate."""
        if sampled is None:
            sampled = self.sample >= 1.0 or self._rng.random() < self.sample
        return TraceContext.mint(sampled=bool(sampled))

    def span(self, ctx: Optional[TraceContext], name: str, **attrs):
        """Timed-block form.  ``ctx`` may be None or unsampled — the
        caller gets the inert :data:`NULL_SPAN` and pays nothing."""
        if ctx is None or not ctx.sampled:
            return NULL_SPAN
        return TraceSpan(self, ctx, name, attrs)

    def record(self, ctx: Optional[TraceContext], name: str,
               dur_s: float, ts: Optional[float] = None,
               attrs: Optional[dict] = None,
               sid: Optional[str] = None) -> Optional[str]:
        """Already-measured form (the engine batcher's: durations are
        computed after the batch resolves).  Returns the span id (the
        parent for sub-spans) or None when nothing was recorded."""
        if ctx is None or not ctx.sampled:
            return None
        sid = sid or _span_id()
        rec = {"v": SCHEMA_VERSION, "t": time.time(), "rank": self.rank,
               "kind": "span", "name": name, "dur_s": float(dur_s),
               "trace": ctx.trace_id, "sid": sid, "member": self.member}
        if ts is not None:
            rec["ts"] = ts
        if ctx.span_id is not None:
            rec["psid"] = ctx.span_id
        if attrs:
            rec["attrs"] = {k: v for k, v in attrs.items()
                            if v is not None}
        root = ctx.span_id is None
        with self._lock:
            spans = self._live.get(ctx.trace_id)
            if spans is None:
                if len(self._live) >= MAX_LIVE_TRACES:
                    # a trace whose root never finalized (crashed hop)
                    evicted, dead = self._live.popitem()
                    self.counters["spans_dropped"] += len(dead)
                spans = self._live[ctx.trace_id] = []
            if len(spans) >= MAX_SPANS_PER_TRACE:
                self.counters["spans_dropped"] += 1
                telemetry.get().counter("trace/spans_dropped")
                return None
            spans.append(rec)
            self.counters["spans_emitted"] += 1
            if self._file is not None:
                self._file.write(json.dumps(rec) + "\n")
                self._file.flush()
        telemetry.get().counter("trace/spans_emitted")
        if root:
            self._finalize(ctx.trace_id, float(dur_s), attrs or {})
        return sid

    # -- tail sampling ---------------------------------------------------

    def _keep(self, dur_s: float, attrs: dict) -> bool:
        """The tail verdict: errors and hedged/retried/shed requests are
        always forensic material; otherwise keep only roots at or above
        the windowed-p99 of recent root durations (with few samples the
        estimate degrades toward the max — the slowest request of a
        young run is still kept, which is the right cold-start bias)."""
        if attrs.get("error"):
            return True
        status = attrs.get("status")
        if isinstance(status, int) and status != 200:
            return True
        if any(attrs.get(k) for k in ("hedged", "retried", "shed")):
            return True
        thresh = self._root_hist.window_quantile(self.tail_quantile,
                                                 self.tail_window_s)
        return thresh is not None and dur_s >= thresh
    # NOTE: observe AFTER the verdict — a lone first request must not
    # compare against itself and auto-keep every cold-start trace... it
    # actually SHOULD be kept (it is the current p99), which observing
    # after preserves only from the second request on; the first trace
    # has no window yet and is dropped, bounding cold-start noise.

    def _finalize(self, trace_id: str, dur_s: float, attrs: dict):
        keep = self._keep(dur_s, attrs)
        self._root_hist.observe(dur_s)
        with self._lock:
            spans = self._live.pop(trace_id, None)
        if not keep or not spans:
            return
        with self._lock:
            self._tail.append(spans)
            self.counters["tail_kept"] += 1
        telemetry.get().counter("trace/tail_kept")
        self.dump_tail()

    def dump_tail(self) -> Optional[str]:
        """Atomically write the kept-trees ring to
        ``trace_tail_<member>.jsonl`` (tmp + rename — the flight
        recorder's torn-dump-proof contract)."""
        if not self.out_dir:
            return None
        with self._lock:
            trees = [list(t) for t in self._tail]
        path = os.path.join(self.out_dir,
                            f"{TAIL_PREFIX}{self.member}.jsonl")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for tree in trees:
                for rec in tree:
                    f.write(json.dumps(rec) + "\n")
        os.replace(tmp, path)
        return path

    def tail_trace_ids(self, since: Optional[float] = None,
                       limit: int = 16) -> "list[str]":
        """Unique trace ids from the kept-trees ring, newest first —
        what a firing alert attaches so the page arrives with the
        slow-request span trees that explain it.  ``since`` filters to
        trees whose newest span landed at/after that wall time."""
        with self._lock:
            trees = [list(t) for t in self._tail]
        out: "list[str]" = []
        seen = set()
        for tree in reversed(trees):
            if not tree:
                continue
            if since is not None and max(r.get("t", 0)
                                         for r in tree) < since:
                continue
            tid = tree[0].get("trace")
            if tid and tid not in seen:
                seen.add(tid)
                out.append(tid)
            if len(out) >= limit:
                break
        return out

    # -- introspection / lifecycle ---------------------------------------

    def metrics(self) -> dict:
        with self._lock:
            out = dict(self.counters)
            out["live_traces"] = len(self._live)
            out["tail_trees"] = len(self._tail)
        out["sample"] = self.sample
        return out

    def flush(self):
        with self._lock:
            if self._file is not None:
                self._file.flush()

    def close(self):
        try:
            self.dump_tail()
        except OSError:
            pass
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


# -- module-global lifecycle (the telemetry.configure/get twin) ----------

_active: "NullTracer | Tracer" = NULL_TRACER


def configure(out_dir: str, member: str = "0", rank: int = 0,
              sample: float = 1.0, tail_budget: int = 256,
              tail_window_s: float = 60.0,
              tail_quantile: float = 0.99) -> Tracer:
    """Open a tracer and make it the active one (one per process — the
    ``spans_<member>.jsonl`` layout's writer contract)."""
    global _active
    if _active.enabled:
        _active.close()
    _active = Tracer(out_dir, member=member, rank=rank, sample=sample,
                     tail_budget=tail_budget, tail_window_s=tail_window_s,
                     tail_quantile=tail_quantile)
    return _active


def configure_from_env(member: Optional[str] = None,
                       rank: int = 0) -> Optional[Tracer]:
    """Enable tracing when ``MXR_TRACE_DIR`` is set — how subprocess
    fabric members (tests, smoke scripts) opt in without CLI plumbing.
    No-op (returns None) when the env var is absent or a tracer is
    already active."""
    out_dir = os.environ.get(ENV_TRACE_DIR, "").strip()
    if not out_dir or _active.enabled:
        return None
    member = os.environ.get(ENV_TRACE_MEMBER, "").strip() or member
    sample = float(os.environ.get(ENV_TRACE_SAMPLE, "") or 1.0)
    return configure(out_dir, member=member if member is not None else "0",
                     rank=rank, sample=sample)


def get() -> "NullTracer | Tracer":
    """The active tracer (:data:`NULL_TRACER` when tracing is off)."""
    return _active


def reset_null():
    """Drop the active tracer WITHOUT closing it (forked children that
    inherit the parent's open spans stream — the telemetry twin)."""
    global _active
    _active = NULL_TRACER


def shutdown():
    """Close the active tracer and restore the no-op default."""
    global _active
    _active.close()
    _active = NULL_TRACER
