"""The telemetry sink: monotonic-clock spans, counters, gauges, and a
rank-aware JSONL event stream with an end-of-run aggregated summary.

SURVEY §5 calls a profiling subsystem "the free win" the MXNet reference
never had; until now every perf claim in the ledger was reconstructed by
hand from session logs (BASELINE.md's r4_tpu_session*.log archaeology).
This layer makes the numbers a machine-readable artifact of every run:

* ``Telemetry`` — the live sink.  ``span(name)`` times a block on
  ``time.perf_counter`` (monotonic — wall-clock steps under NTP slew
  corrupt durations, the Speedometer bug this PR also fixes);
  ``counter``/``gauge`` record occurrences and sampled values.  Every
  record is appended to ``events_rank{N}.jsonl`` (one JSON object per
  line, schema below) and folded into in-memory aggregates that
  ``summary()``/``write_summary()`` expose without re-reading the file.
* ``NullTelemetry`` — the disabled sink.  All methods are no-ops and
  ``span`` returns one cached context manager, so an instrumented hot
  path pays a single attribute check and zero allocations per call.

Thread-safety: the loader's prefetch producer thread emits events
concurrently with the consumer loop, so the writer and the aggregate
dicts share one lock.  Events are buffered by the underlying file object
and flushed on ``close``/``write_summary`` — per-line fsyncs would put
disk latency on the step path.

JSONL event schema (``v`` = schema version, one object per line):

    {"v": 1, "t": <unix wall seconds>, "rank": <process index>,
     "kind": "span" | "counter" | "gauge" | "meta",
     "name": "<dotted/slashed metric name>",
     ...kind-specific fields}

  span    → "dur_s": float seconds (optionally "n": batched count)
  counter → "inc": int
  gauge   → "value": float
  meta    → free-form "fields" dict (run header: world size, argv, ...)

``summary()`` aggregates per name: spans → count/total_s/mean_s/min_s/
max_s, counters → total, gauges → count/mean/min/max/last.

Two additions for the live observability plane (``telemetry/obs.py``):

* **Flight recorder** — every emitted event also lands in a bounded
  in-memory ring (:data:`RING_SIZE` events).  ``dump_flight(reason)``
  writes the ring atomically to ``flight_{rank}.jsonl`` so the last
  seconds before a NaN halt / SIGTERM / loader systemic failure survive
  even when the buffered event stream didn't flush.  The dump path uses
  a timeout lock acquire: it may be called from a signal handler that
  interrupted a thread holding the sink lock, and must degrade (skip
  the stream write) rather than deadlock.
* **Trace timestamps** — with ``trace=True`` (or env
  ``MXR_TELEMETRY_TRACE=1``) span records carry ``"ts"``, the wall-clock
  START of the span, so ``telemetry/trace.py`` can place them exactly on
  a Chrome/Perfetto timeline.  Without it, trace export derives the
  start as ``t - dur_s`` (``t`` is recorded at span END).  Off by
  default: one extra ``time.time()`` per span is cheap but not free.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

SCHEMA_VERSION = 1
SUMMARY_NAME = "summary.json"
# flight-recorder ring bound: ~4k events ≈ the last few hundred steps of
# a fully-instrumented train loop, < 1 MB of dicts
RING_SIZE = 4096


class _NullSpan:
    """Zero-allocation context manager for the disabled sink."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTelemetry:
    """Disabled sink: one attribute check (``enabled``) on hot paths, no
    allocations (``span`` hands back one cached context manager)."""

    enabled = False
    rank = 0
    trace = False

    def span(self, name):
        return _NULL_SPAN

    def add(self, name, seconds, n=1, ts=None):
        pass

    def counter(self, name, inc=1):
        pass

    def gauge(self, name, value):
        pass

    def meta(self, name, **fields):
        pass

    def dump_flight(self, reason, **fields):
        return None

    def summary(self) -> dict:
        return {}

    def write_summary(self, extra: Optional[dict] = None) -> Optional[str]:
        return None

    def close(self):
        pass


NULL = NullTelemetry()


class _Span:
    """Context manager recording a perf_counter duration into its sink.
    Durations always come from the monotonic clock; when the sink is in
    trace mode the wall-clock START is captured too so the trace export
    can place the span exactly (rather than deriving start = end - dur
    from the emit-time ``t``)."""

    __slots__ = ("_tel", "_name", "_t0", "_w0")

    def __init__(self, tel: "Telemetry", name: str):
        self._tel = tel
        self._name = name

    def __enter__(self):
        self._w0 = time.time() if self._tel.trace else None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tel.add(self._name, time.perf_counter() - self._t0,
                      ts=self._w0)
        return False


class Telemetry:
    """Live sink writing ``events_rank{rank}.jsonl`` under ``out_dir``.

    ``rank``/``world`` mirror the multi-host contract of ``profile_dir``:
    every rank streams its own file (no cross-process writer collisions on
    a shared filesystem) and only process 0 calls ``write_summary``.
    """

    enabled = True

    def __init__(self, out_dir: str, rank: int = 0, world: int = 1,
                 run_meta: Optional[dict] = None, stream: bool = True,
                 trace: Optional[bool] = None, ring_size: int = RING_SIZE):
        self.out_dir = out_dir
        self.rank = int(rank)
        self.world = int(world)
        if trace is None:  # env opt-in so drivers need no new flag
            env = os.environ.get("MXR_TELEMETRY_TRACE", "")
            trace = env.strip().lower() in ("1", "true", "yes", "on")
        self.trace = bool(trace)
        self._lock = threading.Lock()
        self._spans: dict = {}     # name -> [count, total, min, max]
        self._counters: dict = {}  # name -> int
        self._gauges: dict = {}    # name -> [count, total, min, max, last]
        self._ring: collections.deque = collections.deque(
            maxlen=max(int(ring_size), 1))
        self._run_meta = dict(run_meta or {})
        self._file = None
        if stream:
            os.makedirs(out_dir, exist_ok=True)
            self.events_path = os.path.join(out_dir,
                                            f"events_rank{self.rank}.jsonl")
            self._file = open(self.events_path, "w")
        if self._run_meta or stream:
            self.meta("run", world=self.world, **self._run_meta)

    # -- recording -------------------------------------------------------

    def span(self, name: str) -> _Span:
        return _Span(self, name)

    def _emit(self, rec: dict):
        self._ring.append(rec)  # flight recorder: bounded, crash-readable
        if self._file is not None:
            self._file.write(json.dumps(rec) + "\n")

    def add(self, name: str, seconds: float, n: int = 1,
            ts: Optional[float] = None):
        """Record a measured duration (the non-context-manager span form —
        callers that already hold a perf_counter difference, e.g. the
        trainer's loader-wait accumulation, feed it here).  ``n`` lets one
        record stand for n back-to-back occurrences (group dispatches).
        ``ts`` is an optional wall-clock span START (trace mode)."""
        with self._lock:
            s = self._spans.get(name)
            if s is None:
                self._spans[name] = [n, seconds, seconds, seconds]
            else:
                s[0] += n
                s[1] += seconds
                s[2] = min(s[2], seconds)
                s[3] = max(s[3], seconds)
            rec = {"v": SCHEMA_VERSION, "t": time.time(), "rank": self.rank,
                   "kind": "span", "name": name, "dur_s": seconds}
            if n != 1:
                rec["n"] = n
            if ts is not None:
                rec["ts"] = ts
            self._emit(rec)

    def counter(self, name: str, inc: int = 1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + inc
            self._emit({"v": SCHEMA_VERSION, "t": time.time(),
                        "rank": self.rank, "kind": "counter", "name": name,
                        "inc": inc})

    def gauge(self, name: str, value: float):
        value = float(value)
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                self._gauges[name] = [1, value, value, value, value]
            else:
                g[0] += 1
                g[1] += value
                g[2] = min(g[2], value)
                g[3] = max(g[3], value)
                g[4] = value
            self._emit({"v": SCHEMA_VERSION, "t": time.time(),
                        "rank": self.rank, "kind": "gauge", "name": name,
                        "value": value})

    def meta(self, name: str, **fields):
        with self._lock:
            self._emit({"v": SCHEMA_VERSION, "t": time.time(),
                        "rank": self.rank, "kind": "meta", "name": name,
                        "fields": fields})

    def dump_flight(self, reason: str, **fields) -> Optional[str]:
        """Flight-recorder dump: append a ``flight_trigger`` meta event
        explaining WHY, then atomically write the event ring to
        ``flight_{rank}.jsonl`` under ``out_dir``.

        Callable from signal handlers and failure paths: the lock acquire
        is bounded, and when it times out (the handler interrupted a
        thread that holds the sink lock) the stream write is skipped but
        the ring still gets the trigger and the dump proceeds — a flight
        dump that deadlocks the dying process would be worse than a
        slightly torn one.  Returns the dump path (None without a dir).
        """
        rec = {"v": SCHEMA_VERSION, "t": time.time(), "rank": self.rank,
               "kind": "meta", "name": "flight_trigger",
               "fields": {"reason": reason, **fields}}
        got = self._lock.acquire(timeout=1.0)
        try:
            self._ring.append(rec)
            if got and self._file is not None:
                self._file.write(json.dumps(rec) + "\n")
                self._file.flush()
            events = None
            for _ in range(3):  # lockless list(deque) may race an append
                try:
                    events = list(self._ring)
                    break
                except RuntimeError:
                    continue
            if events is None:
                events = [rec]
        finally:
            if got:
                self._lock.release()
        if not self.out_dir:
            return None
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, f"flight_{self.rank}.jsonl")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")
        os.replace(tmp, path)
        return path

    # -- reading ---------------------------------------------------------

    def summary(self) -> dict:
        with self._lock:
            return {
                "schema": SCHEMA_VERSION,
                "rank": self.rank,
                "world": self.world,
                "meta": dict(self._run_meta),
                "spans": {
                    k: {"count": c, "total_s": t, "mean_s": t / max(c, 1),
                        "min_s": lo, "max_s": hi}
                    for k, (c, t, lo, hi) in sorted(self._spans.items())},
                "counters": dict(sorted(self._counters.items())),
                "gauges": {
                    k: {"count": c, "mean": t / max(c, 1), "min": lo,
                        "max": hi, "last": last}
                    for k, (c, t, lo, hi, last) in sorted(self._gauges.items())},
            }

    def write_summary(self, extra: Optional[dict] = None) -> Optional[str]:
        """Write the aggregated summary JSON (call from process 0 only —
        the multi-rank fold lives in ``scripts/telemetry_report.py``,
        which reads every rank's event file)."""
        doc = self.summary()
        if extra:
            doc.update(extra)
        self.flush()
        path = os.path.join(self.out_dir, SUMMARY_NAME)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    def flush(self):
        with self._lock:
            if self._file is not None:
                self._file.flush()

    def close(self):
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
