"""The telemetry sink: monotonic-clock spans, counters, gauges, and a
rank-aware JSONL event stream with an end-of-run aggregated summary.

SURVEY §5 calls a profiling subsystem "the free win" the MXNet reference
never had; until now every perf claim in the ledger was reconstructed by
hand from session logs (BASELINE.md's r4_tpu_session*.log archaeology).
This layer makes the numbers a machine-readable artifact of every run:

* ``Telemetry`` — the live sink.  ``span(name)`` times a block on
  ``time.perf_counter`` (monotonic — wall-clock steps under NTP slew
  corrupt durations, the Speedometer bug this PR also fixes);
  ``counter``/``gauge`` record occurrences and sampled values.  Every
  record is appended to ``events_rank{N}.jsonl`` (one JSON object per
  line, schema below) and folded into in-memory aggregates that
  ``summary()``/``write_summary()`` expose without re-reading the file.
* ``NullTelemetry`` — the disabled sink.  All methods are no-ops and
  ``span`` returns one cached context manager, so an instrumented hot
  path pays a single attribute check and zero allocations per call.

Thread-safety: the loader's prefetch producer thread emits events
concurrently with the consumer loop, so the writer and the aggregate
dicts share one lock.  Events are buffered by the underlying file object
and flushed on ``close``/``write_summary`` — per-line fsyncs would put
disk latency on the step path.

JSONL event schema (``v`` = schema version, one object per line):

    {"v": 1, "t": <unix wall seconds>, "rank": <process index>,
     "kind": "span" | "counter" | "gauge" | "meta",
     "name": "<dotted/slashed metric name>",
     ...kind-specific fields}

  span    → "dur_s": float seconds (optionally "n": batched count)
  counter → "inc": int
  gauge   → "value": float
  hist    → "value": float seconds (one observation into the named
            log-spaced histogram — see :class:`Hist`)
  meta    → free-form "fields" dict (run header: world size, argv, ...)

``summary()`` aggregates per name: spans → count/total_s/mean_s/min_s/
max_s, counters → total, gauges → count/mean/min/max/last, hists →
count/sum/le/buckets (the mergeable distribution — fold two ranks by
adding bucket counts, which is what ``report.aggregate`` and the obs
snapshot fold do).

Two additions for the live observability plane (``telemetry/obs.py``):

* **Flight recorder** — every emitted event also lands in a bounded
  in-memory ring (:data:`RING_SIZE` events).  ``dump_flight(reason)``
  writes the ring atomically to ``flight_{rank}.jsonl`` so the last
  seconds before a NaN halt / SIGTERM / loader systemic failure survive
  even when the buffered event stream didn't flush.  The dump path uses
  a timeout lock acquire: it may be called from a signal handler that
  interrupted a thread holding the sink lock, and must degrade (skip
  the stream write) rather than deadlock.
* **Trace timestamps** — with ``trace=True`` (or env
  ``MXR_TELEMETRY_TRACE=1``) span records carry ``"ts"``, the wall-clock
  START of the span, so ``telemetry/trace.py`` can place them exactly on
  a Chrome/Perfetto timeline.  Without it, trace export derives the
  start as ``t - dur_s`` (``t`` is recorded at span END).  Off by
  default: one extra ``time.time()`` per span is cheap but not free.
"""

from __future__ import annotations

import bisect
import collections
import json
import os
import threading
import time
from typing import List, Optional

SCHEMA_VERSION = 1
SUMMARY_NAME = "summary.json"
# flight-recorder ring bound: ~4k events ≈ the last few hundred steps of
# a fully-instrumented train loop, < 1 MB of dicts
RING_SIZE = 4096

# Histogram bucket upper bounds, in SECONDS: log-spaced at factor √2 from
# 0.1 ms to ~105 s (41 boundaries + implicit +Inf overflow).  Fixed and
# module-global on purpose: every rank and every process bins identically,
# so cross-rank merge is element-wise addition of bucket counts — the
# property the obs snapshot fold and report.aggregate rely on.  √2 keeps
# quantile interpolation error under ~20% of the estimate anywhere on the
# latency axis, fine for SLO control (a p99 of 40 ms vs 48 ms drives the
# same decision) at 41 buckets per family.
HIST_MIN_S = 1e-4
HIST_FACTOR = 2.0 ** 0.5
HIST_LE = tuple(round(HIST_MIN_S * HIST_FACTOR ** i, 10) for i in range(41))


def quantile_from_counts(le, buckets, count, q: float) -> Optional[float]:
    """Quantile estimate from (boundaries, per-bucket counts, total).
    Linear interpolation inside the bucket holding the q-th observation
    (lower edge 0 for the first bucket; the +Inf overflow bucket clamps to
    the last finite boundary).  None when the histogram is empty."""
    if count <= 0:
        return None
    target = max(min(q, 1.0), 0.0) * count
    cum = 0
    for i, c in enumerate(buckets):
        if c == 0:
            continue
        prev, cum = cum, cum + c
        if cum >= target:
            if i >= len(le):  # overflow bucket: no upper edge to lerp to
                return float(le[-1])
            lo = float(le[i - 1]) if i > 0 else 0.0
            hi = float(le[i])
            frac = min(max((target - prev) / c, 0.0), 1.0)
            return lo + (hi - lo) * frac
    return float(le[-1])


class Hist:
    """Streaming log-spaced histogram (fixed :data:`HIST_LE` boundaries).

    The distribution primitive behind ``Telemetry.observe`` — and usable
    standalone: ``ServeEngine`` keeps its own instances so the SLO
    controller can read quantiles with telemetry disabled, exactly like
    the engine's counter mirror.  Thread-safe; ``merge`` adds another
    histogram's buckets in (associative + commutative, so any fold order
    across ranks agrees).

    A bounded ring of periodic snapshots (one per ≥``SNAP_INTERVAL_S`` of
    observation traffic) backs ``window_quantile``: the windowed estimate
    is the quantile of (current − snapshot at the window edge), i.e. of
    roughly the last ``window_s`` seconds of observations — what an
    admission controller wants ("p99 *right now*"), where the lifetime
    quantile would be polluted by a cold start or an old burst.
    """

    SNAP_INTERVAL_S = 0.5
    SNAP_KEEP = 256  # × interval ⇒ ~2 min of window reach

    __slots__ = ("count", "sum", "buckets", "_lock", "_snaps",
                 "_last_snap_t")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.buckets: List[int] = [0] * (len(HIST_LE) + 1)
        self._lock = threading.Lock()
        self._snaps: collections.deque = collections.deque(
            maxlen=self.SNAP_KEEP)
        self._last_snap_t: Optional[float] = None

    def observe(self, value: float, now: Optional[float] = None):
        value = float(value)
        i = bisect.bisect_left(HIST_LE, value)
        with self._lock:
            now = time.monotonic() if now is None else now
            if (self._last_snap_t is None
                    or now - self._last_snap_t >= self.SNAP_INTERVAL_S):
                # state as of now⁻ (before this observation) — the window
                # delta then covers everything from this instant on
                self._snaps.append((now, self.count, tuple(self.buckets)))
                self._last_snap_t = now
            self.count += 1
            self.sum += value
            self.buckets[i] += 1

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            return quantile_from_counts(HIST_LE, self.buckets, self.count, q)

    def window_quantile(self, q: float, window_s: float,
                        now: Optional[float] = None) -> Optional[float]:
        """Quantile over roughly the trailing ``window_s`` seconds (the
        whole history when the run is younger than the window)."""
        with self._lock:
            now = time.monotonic() if now is None else now
            cutoff = now - window_s
            base = None
            for t, c, b in reversed(self._snaps):
                if t <= cutoff:
                    base = (c, b)
                    break
            if base is None:
                counts, n = self.buckets, self.count
            else:
                counts = [x - y for x, y in zip(self.buckets, base[1])]
                n = self.count - base[0]
            return quantile_from_counts(HIST_LE, counts, n, q)

    def merge(self, other) -> "Hist":
        """Fold another :class:`Hist` (or its ``to_dict``) into this one."""
        if isinstance(other, Hist):
            other = other.to_dict()
        if list(other.get("le", HIST_LE)) != list(HIST_LE):
            raise ValueError("histogram bucket boundaries disagree — "
                             "streams from different HIST_LE versions "
                             "cannot be merged")
        with self._lock:
            self.count += int(other["count"])
            self.sum += float(other["sum"])
            for i, c in enumerate(other["buckets"]):
                self.buckets[i] += int(c)
        return self

    def to_dict(self) -> dict:
        with self._lock:
            return {"count": self.count, "sum": self.sum,
                    "le": list(HIST_LE), "buckets": list(self.buckets)}

    @classmethod
    def from_dict(cls, d: dict) -> "Hist":
        return cls().merge(d)


class _NullSpan:
    """Zero-allocation context manager for the disabled sink."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTelemetry:
    """Disabled sink: one attribute check (``enabled``) on hot paths, no
    allocations (``span`` hands back one cached context manager)."""

    enabled = False
    rank = 0
    trace = False

    def span(self, name):
        return _NULL_SPAN

    def add(self, name, seconds, n=1, ts=None):
        pass

    def counter(self, name, inc=1):
        pass

    def gauge(self, name, value):
        pass

    def meta(self, name, **fields):
        pass

    def observe(self, name, value):
        pass

    def hist_quantile(self, name, q, window_s=None):
        return None

    def live_hists(self) -> dict:
        return {}

    def dump_flight(self, reason, **fields):
        return None

    def summary(self) -> dict:
        return {}

    def write_summary(self, extra: Optional[dict] = None) -> Optional[str]:
        return None

    def close(self):
        pass


NULL = NullTelemetry()


class _Span:
    """Context manager recording a perf_counter duration into its sink.
    Durations always come from the monotonic clock; when the sink is in
    trace mode the wall-clock START is captured too so the trace export
    can place the span exactly (rather than deriving start = end - dur
    from the emit-time ``t``)."""

    __slots__ = ("_tel", "_name", "_t0", "_w0")

    def __init__(self, tel: "Telemetry", name: str):
        self._tel = tel
        self._name = name

    def __enter__(self):
        self._w0 = time.time() if self._tel.trace else None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._tel.add(self._name, time.perf_counter() - self._t0,
                      ts=self._w0)
        return False


class Telemetry:
    """Live sink writing ``events_rank{rank}.jsonl`` under ``out_dir``.

    ``rank``/``world`` mirror the multi-host contract of ``profile_dir``:
    every rank streams its own file (no cross-process writer collisions on
    a shared filesystem) and only process 0 calls ``write_summary``.
    """

    enabled = True

    def __init__(self, out_dir: str, rank: int = 0, world: int = 1,
                 run_meta: Optional[dict] = None, stream: bool = True,
                 trace: Optional[bool] = None, ring_size: int = RING_SIZE):
        self.out_dir = out_dir
        self.rank = int(rank)
        self.world = int(world)
        if trace is None:  # env opt-in so drivers need no new flag
            env = os.environ.get("MXR_TELEMETRY_TRACE", "")
            trace = env.strip().lower() in ("1", "true", "yes", "on")
        self.trace = bool(trace)
        self._lock = threading.Lock()
        self._spans: dict = {}     # name -> [count, total, min, max]
        self._counters: dict = {}  # name -> int
        self._gauges: dict = {}    # name -> [count, total, min, max, last]
        self._hists: dict = {}     # name -> Hist
        self._ring: collections.deque = collections.deque(
            maxlen=max(int(ring_size), 1))
        self._run_meta = dict(run_meta or {})
        self._file = None
        if stream:
            os.makedirs(out_dir, exist_ok=True)
            self.events_path = os.path.join(out_dir,
                                            f"events_rank{self.rank}.jsonl")
            self._file = open(self.events_path, "w")
        if self._run_meta or stream:
            self.meta("run", world=self.world, **self._run_meta)

    # -- recording -------------------------------------------------------

    def span(self, name: str) -> _Span:
        return _Span(self, name)

    def _emit(self, rec: dict):
        self._ring.append(rec)  # flight recorder: bounded, crash-readable
        if self._file is not None:
            self._file.write(json.dumps(rec) + "\n")

    def add(self, name: str, seconds: float, n: int = 1,
            ts: Optional[float] = None):
        """Record a measured duration (the non-context-manager span form —
        callers that already hold a perf_counter difference, e.g. the
        trainer's loader-wait accumulation, feed it here).  ``n`` lets one
        record stand for n back-to-back occurrences (group dispatches).
        ``ts`` is an optional wall-clock span START (trace mode)."""
        with self._lock:
            s = self._spans.get(name)
            if s is None:
                self._spans[name] = [n, seconds, seconds, seconds]
            else:
                s[0] += n
                s[1] += seconds
                s[2] = min(s[2], seconds)
                s[3] = max(s[3], seconds)
            rec = {"v": SCHEMA_VERSION, "t": time.time(), "rank": self.rank,
                   "kind": "span", "name": name, "dur_s": seconds}
            if n != 1:
                rec["n"] = n
            if ts is not None:
                rec["ts"] = ts
            self._emit(rec)

    def counter(self, name: str, inc: int = 1):
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + inc
            self._emit({"v": SCHEMA_VERSION, "t": time.time(),
                        "rank": self.rank, "kind": "counter", "name": name,
                        "inc": inc})

    def gauge(self, name: str, value: float):
        value = float(value)
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                self._gauges[name] = [1, value, value, value, value]
            else:
                g[0] += 1
                g[1] += value
                g[2] = min(g[2], value)
                g[3] = max(g[3], value)
                g[4] = value
            self._emit({"v": SCHEMA_VERSION, "t": time.time(),
                        "rank": self.rank, "kind": "gauge", "name": name,
                        "value": value})

    def observe(self, name: str, value: float):
        """One observation into the named log-spaced histogram (seconds).
        The distribution complement to ``gauge``: answers "what is p99?"
        where gauges only keep last/min/max/mean."""
        value = float(value)
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Hist()
            h.observe(value)
            self._emit({"v": SCHEMA_VERSION, "t": time.time(),
                        "rank": self.rank, "kind": "hist", "name": name,
                        "value": value})

    def hist_quantile(self, name: str, q: float,
                      window_s: Optional[float] = None) -> Optional[float]:
        """Quantile of a named histogram — over the trailing ``window_s``
        seconds when given, else the whole run.  None when unknown/empty."""
        with self._lock:
            h = self._hists.get(name)
        if h is None:
            return None
        if window_s is not None:
            return h.window_quantile(q, window_s)
        return h.quantile(q)

    def live_hists(self) -> dict:
        """Name → live :class:`Hist` (the objects, not copies — Hist is
        internally locked).  The watchtower's window into every
        ``observe`` stream for quantile/burn-rate rules."""
        with self._lock:
            return dict(self._hists)

    def meta(self, name: str, **fields):
        with self._lock:
            self._emit({"v": SCHEMA_VERSION, "t": time.time(),
                        "rank": self.rank, "kind": "meta", "name": name,
                        "fields": fields})

    def dump_flight(self, reason: str, **fields) -> Optional[str]:
        """Flight-recorder dump: append a ``flight_trigger`` meta event
        explaining WHY, then atomically write the event ring to
        ``flight_{rank}.jsonl`` under ``out_dir``.

        Callable from signal handlers and failure paths: the lock acquire
        is bounded, and when it times out (the handler interrupted a
        thread that holds the sink lock) the stream write is skipped but
        the ring still gets the trigger and the dump proceeds — a flight
        dump that deadlocks the dying process would be worse than a
        slightly torn one.  Returns the dump path (None without a dir).
        """
        rec = {"v": SCHEMA_VERSION, "t": time.time(), "rank": self.rank,
               "kind": "meta", "name": "flight_trigger",
               "fields": {"reason": reason, **fields}}
        got = self._lock.acquire(timeout=1.0)
        try:
            self._ring.append(rec)
            if got and self._file is not None:
                self._file.write(json.dumps(rec) + "\n")
                self._file.flush()
            events = None
            for _ in range(3):  # lockless list(deque) may race an append
                try:
                    events = list(self._ring)
                    break
                except RuntimeError:
                    continue
            if events is None:
                events = [rec]
        finally:
            if got:
                self._lock.release()
        if not self.out_dir:
            return None
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir, f"flight_{self.rank}.jsonl")
        # tmp must be unique per CALL, not just per process: two threads
        # dumping concurrently (e.g. a partition declared while the
        # autoscaler freezes) would otherwise share one tmp and the
        # second os.replace finds it already consumed
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "w") as f:
                for ev in events:
                    f.write(json.dumps(ev) + "\n")
            os.replace(tmp, path)
        except OSError:  # out_dir torn down mid-shutdown; ring has it
            return None
        return path

    # -- reading ---------------------------------------------------------

    def summary(self) -> dict:
        with self._lock:
            return {
                "schema": SCHEMA_VERSION,
                "rank": self.rank,
                "world": self.world,
                "meta": dict(self._run_meta),
                "spans": {
                    k: {"count": c, "total_s": t, "mean_s": t / max(c, 1),
                        "min_s": lo, "max_s": hi}
                    for k, (c, t, lo, hi) in sorted(self._spans.items())},
                "counters": dict(sorted(self._counters.items())),
                "gauges": {
                    k: {"count": c, "mean": t / max(c, 1), "min": lo,
                        "max": hi, "last": last}
                    for k, (c, t, lo, hi, last) in sorted(self._gauges.items())},
                "hists": {
                    k: h.to_dict() for k, h in sorted(self._hists.items())},
            }

    def write_summary(self, extra: Optional[dict] = None) -> Optional[str]:
        """Write the aggregated summary JSON (call from process 0 only —
        the multi-rank fold lives in ``scripts/telemetry_report.py``,
        which reads every rank's event file)."""
        doc = self.summary()
        if extra:
            doc.update(extra)
        self.flush()
        path = os.path.join(self.out_dir, SUMMARY_NAME)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    def flush(self):
        with self._lock:
            if self._file is not None:
                self._file.flush()

    def close(self):
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
