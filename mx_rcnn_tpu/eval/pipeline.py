"""Overlapped eval pipeline: device forward N+1 runs while host
post-process N decodes/NMSes/pastes.

The serial ``pred_eval`` loop interleaves two resources that have no data
dependency across batches: the device (forward) and the host (decode +
per-class NMS + mask paste).  Each is idle while the other works, so the
eval rate is the SUM of the two costs.  This module saturates both:

* ``dispatch`` — jax's async dispatch queues batch N+1's forward
  immediately; ``copy_to_host_async`` starts the d2h transfer of batch
  N's outputs in the background.
* a bounded in-flight window (``inflight``) throttles dispatch so device
  memory holds at most that many batches' outputs (plus their captured
  pyramids on mask configs).
* host post-process runs on a ``host_workers``-wide thread pool; results
  are INDEX-addressed into ``all_boxes[k][image_index]``, so completion
  order cannot change the output — ``all_boxes``/``all_masks`` are
  bit-identical to the serial loop at any depth (pinned by
  ``tests/test_eval_pipeline.py``).

Mask configs: ``Predictor.predict`` caches one batch's pyramid and the
next dispatch overwrites it — the classic stale-cache hazard under
overlap.  ``Predictor.capture_feats()`` takes a per-batch handle
``(feats, token)`` right after each dispatch; the host task hands it back
via ``predict_masks_*(..., feats=...)`` so batch N's mask pass reads
batch N's pyramid even while N+1 owns the cache.  Predictors without
``capture_feats`` (duck-typed test stubs) fall back to the token
discipline, which fails loudly — never silently wrong masks.

``inflight=1`` degenerates to the serial structure (forward N+1 waits for
N's host work); ``inflight=2`` is classic double-buffering and is the
default (``cfg.tpu.EVAL_INFLIGHT``).
"""

from __future__ import annotations

import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.ops.postprocess import (decode_image_boxes,
                                         device_dets_to_per_class,
                                         per_class_nms)


class _InFlight:
    """One dispatched-but-not-yet-post-processed batch."""

    __slots__ = ("batch", "arrays", "feats", "token", "n_valid")

    def __init__(self, batch, arrays, feats, token, n_valid):
        self.batch = batch
        self.arrays = arrays      # device arrays; dropped after readback
        self.feats = feats        # captured pyramid (mask configs) or None
        self.token = token
        self.n_valid = n_valid


def run_pipelined(predictor, test_loader, *, all_boxes, all_masks, imdb,
                  num_classes, max_per_image, thresh, nms_thresh, vis,
                  with_masks, device_postprocess, inflight, host_workers,
                  progress) -> dict:
    """Drive the overlapped loop; fills ``all_boxes``/``all_masks`` in
    place and returns the overlap-accounting stats dict ``pred_eval``
    folds into the ``eval_pipeline`` telemetry meta record."""
    from mx_rcnn_tpu.eval.tester import _mask_pass, save_vis

    tel = telemetry.get()
    mode = "pipelined+devpost" if device_postprocess else "pipelined"
    inflight = max(int(inflight), 1)
    can_capture = with_masks and hasattr(predictor, "capture_feats")
    window: deque = deque()   # dispatched, outputs still on device
    pending: deque = deque()  # host futures, submission order
    done = 0
    loader_wait = 0.0
    readback_wait = 0.0
    host_post = 0.0
    post_wait = 0.0
    pool = ThreadPoolExecutor(max_workers=max(int(host_workers), 1),
                              thread_name_prefix="eval-post")

    def dispatch(batch) -> None:
        with tel.span("eval/forward"):
            if device_postprocess:
                arrays = predictor.predict_detections(
                    batch["images"], batch["im_info"], max_per_image,
                    thresh)
            else:
                arrays = predictor.predict(batch["images"],
                                           batch["im_info"])[:4]
        if can_capture:
            feats, token = predictor.capture_feats()
        else:
            feats, token = None, getattr(predictor, "feats_token", None)
        arrays = tuple(arrays)
        for a in arrays:
            try:
                a.copy_to_host_async()  # d2h overlaps the next forward
            except AttributeError:
                pass  # duck-typed stubs may return plain numpy
        bv = batch.get("batch_valid")
        n_valid = (int(np.sum(bv)) if bv is not None
                   else int(arrays[0].shape[0]))
        window.append(_InFlight(batch, arrays, feats, token, n_valid))

    def host_task(entry: _InFlight, host) -> tuple:
        t_start = time.perf_counter()
        batch = entry.batch
        indices = batch["indices"]
        im_info = np.asarray(batch["im_info"])
        rows = []
        t_dec = 0.0
        t_nms = 0.0
        for b in range(entry.n_valid):
            i = int(indices[b])
            if device_postprocess:
                t = time.perf_counter()
                dets_pc = device_dets_to_per_class(host[0][b], host[1][b],
                                                   num_classes)
                t_dec += time.perf_counter() - t
            else:
                rois, roi_valid, cls_prob, deltas = host
                t = time.perf_counter()
                boxes = decode_image_boxes(rois[b], deltas[b], im_info[b])
                t_mid = time.perf_counter()
                t_dec += t_mid - t
                dets_pc = per_class_nms(cls_prob[b], boxes, roi_valid[b],
                                        num_classes, thresh, nms_thresh,
                                        max_per_image)
                t_nms += time.perf_counter() - t_mid
            for k in range(1, num_classes):
                all_boxes[k][i] = dets_pc[k]
            if vis:
                save_vis(test_loader.roidb[i], all_boxes, num_classes,
                         imdb.classes, i)
            rows.append(dets_pc)
        # same span names as the serial loop (pinned by the telemetry
        # test) — measured here, recorded via the non-context form
        tel.add("eval/decode", t_dec, n=max(entry.n_valid, 1))
        tel.add("eval/nms", t_nms, n=max(entry.n_valid, 1))
        if with_masks:
            with tel.span("eval/mask_pass"):
                _mask_pass(predictor, batch, rows, all_boxes, all_masks,
                           test_loader.roidb, max_per_image, num_classes,
                           token=entry.token, feats=entry.feats)
        return entry.n_valid, time.perf_counter() - t_start

    def finish_oldest() -> None:
        """Readback the oldest in-flight batch (the only place the main
        thread blocks on the device) and hand it to the pool."""
        nonlocal readback_wait
        entry = window.popleft()
        t = time.perf_counter()
        with tel.span("eval/readback"):
            host = tuple(np.asarray(a) for a in entry.arrays)
        readback_wait += time.perf_counter() - t
        entry.arrays = None  # release the device buffers
        pending.append(pool.submit(host_task, entry, host))

    def account(res) -> None:
        nonlocal done, host_post
        n, dt = res
        done += n
        host_post += dt
        progress.update(done, tel)

    def reap_done() -> None:
        while pending and pending[0].done():
            account(pending.popleft().result())

    def wait_oldest() -> None:
        nonlocal post_wait
        t = time.perf_counter()
        res = pending.popleft().result()
        post_wait += time.perf_counter() - t
        account(res)

    try:
        it = iter(test_loader)
        while True:
            t_wait = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                break
            dt_wait = time.perf_counter() - t_wait
            loader_wait += dt_wait
            tel.add("eval/loader_wait", dt_wait)
            reap_done()
            # bounded window: count both device-resident batches and
            # not-yet-finished host work against the in-flight budget
            while len(window) + len(pending) >= inflight:
                if window:
                    finish_oldest()
                else:
                    wait_oldest()
            dispatch(batch)
            # eagerly hand all but the newest batch to the pool: its
            # readback only waits on an already-dispatched forward, and
            # host work starts while the newest forward runs
            while len(window) > 1:
                finish_oldest()
            tel.gauge("eval/inflight_depth", len(window) + len(pending))
        while window:
            finish_oldest()
        while pending:
            wait_oldest()
    finally:
        pool.shutdown(wait=True)
    return {"mode": mode, "images": done, "loader_wait_s": loader_wait,
            "readback_wait_s": readback_wait, "host_post_s": host_post,
            "post_wait_s": post_wait, "inflight": inflight,
            "host_workers": int(host_workers)}
