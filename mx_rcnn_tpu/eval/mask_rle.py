"""COCO RLE mask ops (reference: vendored ``rcnn/pycocotools/_mask.pyx`` +
``maskApi.c``), re-derived in numpy with the same external behavior:

* column-major (Fortran) run-length encoding starting with a 0-run;
* the COCO compressed string format (LEB128-style with sign-folded deltas);
* ``rle_iou`` with crowd semantics (crowd gt → det area denominator);
* polygons rasterized via cv2.fillPoly (the reference uses its own scanline
  rasterizer in C; cv2's matches on interior pixels).

Off the training hot path (eval only).  ``native_mask.py`` swaps in the C++
extension for the O(N·M) run-merge loops when built; this module is the
behavioral oracle.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Union

import cv2
import numpy as np


def encode(mask: np.ndarray) -> Dict:
    """Binary (H, W) uint8 mask → RLE dict {'size': [H, W], 'counts': list}.

    Column-major scan; counts alternate 0-runs / 1-runs, starting with the
    count of leading zeros (possibly 0).
    """
    h, w = mask.shape
    flat = np.asfortranarray(mask).reshape(-1, order="F").astype(np.int8)
    # run boundaries
    diff = np.nonzero(flat[1:] != flat[:-1])[0]
    ends = np.concatenate([diff + 1, [flat.size]])
    lengths = np.diff(np.concatenate([[0], ends]))
    counts = lengths.tolist()
    if flat.size and flat[0] == 1:
        counts = [0] + counts
    elif flat.size == 0:
        counts = [0]
    return {"size": [h, w], "counts": counts}


def decode(rle: Dict) -> np.ndarray:
    """RLE dict → binary (H, W) uint8 mask."""
    h, w = rle["size"]
    counts = rle["counts"]
    if isinstance(counts, (str, bytes)):
        counts = string_to_counts(counts)
    flat = np.zeros(h * w, np.uint8)
    pos = 0
    val = 0
    for c in counts:
        if val:
            flat[pos:pos + c] = 1
        pos += c
        val ^= 1
    return flat.reshape((h, w), order="F")


def area(rle: Dict) -> int:
    counts = rle["counts"]
    if isinstance(counts, (str, bytes)):
        counts = string_to_counts(counts)
    return int(sum(counts[1::2]))


def counts_to_string(counts: Sequence[int]) -> str:
    """COCO compressed RLE: 6-bit groups, delta-coded from the 3rd count on
    (maskApi.c ``rleToString``)."""
    out = []
    for i, c in enumerate(counts):
        x = int(c)
        if i > 2:
            x -= int(counts[i - 2])
        more = True
        while more:
            c6 = x & 0x1F
            x >>= 5
            more = not (x == 0 and not (c6 & 0x10)) and \
                   not (x == -1 and (c6 & 0x10))
            if more:
                c6 |= 0x20
            out.append(chr(c6 + 48))
    return "".join(out)


def string_to_counts(s: Union[str, bytes]) -> List[int]:
    """Inverse of counts_to_string (maskApi.c ``rleFrString``)."""
    if isinstance(s, bytes):
        s = s.decode("ascii")
    counts: List[int] = []
    i = 0
    while i < len(s):
        x = 0
        k = 0
        more = True
        while more:
            c = ord(s[i]) - 48
            x |= (c & 0x1F) << (5 * k)
            more = bool(c & 0x20)
            i += 1
            if not more and (c & 0x10):
                x |= -1 << (5 * (k + 1))  # sign extend
            k += 1
        if len(counts) > 2:
            x += counts[-2]
        counts.append(x)
    return counts


def merge(rles: List[Dict]) -> Dict:
    """Union of masks (reference ``rleMerge`` with intersect=0) — used to
    fuse multi-polygon objects into one RLE."""
    if not rles:
        return {"size": [0, 0], "counts": [0]}
    if len(rles) == 1:
        return rles[0]
    m = decode(rles[0])
    for r in rles[1:]:
        m |= decode(r)
    return encode(m)


def poly_to_rle(polys: List[Sequence[float]], h: int, w: int) -> Dict:
    """Polygon list ([[x1,y1,x2,y2,...], ...]) → RLE (reference
    ``frPoly``)."""
    mask = np.zeros((h, w), np.uint8)
    pts = [np.asarray(p, np.float64).reshape(-1, 2).round().astype(np.int32)
           for p in polys if len(p) >= 6]
    if pts:
        cv2.fillPoly(mask, pts, 1)
    return encode(mask)


def ann_to_rle(seg, h: int, w: int) -> Dict:
    """COCO 'segmentation' field (polygons | uncompressed RLE | compressed
    RLE) → RLE dict (reference ``annToRLE``)."""
    if isinstance(seg, list):
        return poly_to_rle(seg, h, w)
    if isinstance(seg, dict):
        if isinstance(seg["counts"], (str, bytes)):
            return {"size": seg["size"], "counts": string_to_counts(seg["counts"])}
        return seg
    raise TypeError(f"bad segmentation: {type(seg)}")


def _intersect_runs(a_counts, b_counts, n: int) -> int:
    """|A ∧ B| via run-merge (the maskApi ``rleArea``-style two-pointer walk);
    O(runs) without decoding."""
    ia = ib = 0
    ca = a_counts[0] if a_counts else n
    cb = b_counts[0] if b_counts else n
    va = vb = 0
    pos = 0
    inter = 0
    while pos < n:
        step = min(ca, cb)
        if va and vb:
            inter += step
        ca -= step
        cb -= step
        pos += step
        if ca == 0:
            ia += 1
            ca = a_counts[ia] if ia < len(a_counts) else n
            va ^= 1
        if cb == 0:
            ib += 1
            cb = b_counts[ib] if ib < len(b_counts) else n
            vb ^= 1
    return inter


def rle_iou(dts: List[Dict], gts: List[Dict], iscrowd: np.ndarray) -> np.ndarray:
    """(D, G) mask IoU; crowd gt use det area as union (maskApi ``rleIou``)."""
    D, G = len(dts), len(gts)
    out = np.zeros((D, G))
    for di, d in enumerate(dts):
        n = d["size"][0] * d["size"][1]
        da = area(d)
        for gi, g in enumerate(gts):
            ga = area(g)
            inter = _intersect_runs(d["counts"], g["counts"], n)
            union = da if iscrowd[gi] else da + ga - inter
            out[di, gi] = inter / union if union > 0 else 0.0
    return out


def masks_to_boxes(rle: Dict) -> np.ndarray:
    """Tight xywh bbox of an RLE (reference ``rleToBbox``)."""
    m = decode(rle)
    ys, xs = np.nonzero(m)
    if ys.size == 0:
        return np.zeros(4)
    return np.asarray([xs.min(), ys.min(), xs.max() - xs.min() + 1,
                       ys.max() - ys.min() + 1], np.float64)
