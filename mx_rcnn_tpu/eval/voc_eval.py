"""PASCAL VOC AP (reference ``rcnn/dataset/pascal_voc_eval.py``).

Pure numpy; both the VOC07 11-point interpolated AP and the later
area-under-monotone-PR metric, with difficult-object exclusion and the
greedy one-detection-per-gt matching of the official devkit.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def voc_ap(rec: np.ndarray, prec: np.ndarray, use_07_metric: bool = False) -> float:
    """AP from recall/precision curves (reference ``voc_ap``)."""
    if use_07_metric:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = float(np.max(prec[rec >= t])) if np.any(rec >= t) else 0.0
            ap += p / 11.0
        return ap
    # correct AP: envelope + area under PR
    mrec = np.concatenate(([0.0], rec, [1.0]))
    mpre = np.concatenate(([0.0], prec, [0.0]))
    for i in range(mpre.size - 1, 0, -1):
        mpre[i - 1] = np.maximum(mpre[i - 1], mpre[i])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))


def voc_eval(class_dets: List[np.ndarray], recs: Dict[int, list], classname: str,
             ovthresh: float = 0.5, use_07_metric: bool = False) -> float:
    """AP for one class.

    Args:
      class_dets: per-image (N, 5) [x1,y1,x2,y2,score] arrays (index =
        image id), the reference ``all_boxes[cls]`` layout.
      recs: image id → list of parsed annotation objects
        ({'name','difficult','bbox'}).
      classname: VOC class name.

    Matching: detections sorted by score desc; a detection is TP if its best
    IoU vs unclaimed, non-difficult gt of this class ≥ ovthresh; difficult
    gt neither count as fp nor add to npos (official devkit rule).
    """
    # per-image gt for this class
    class_recs = {}
    npos = 0
    for img_id, objects in recs.items():
        objs = [o for o in objects if o["name"] == classname]
        bbox = np.array([o["bbox"] for o in objs], np.float32).reshape(-1, 4)
        difficult = np.array([o["difficult"] for o in objs], bool)
        npos += int((~difficult).sum())
        class_recs[img_id] = {"bbox": bbox, "difficult": difficult,
                              "det": np.zeros(len(objs), bool)}

    # flatten detections
    image_ids, confidence, boxes = [], [], []
    for img_id, dets in enumerate(class_dets):
        if dets is None or len(dets) == 0:
            continue
        for d in dets:
            image_ids.append(img_id)
            confidence.append(d[4])
            boxes.append(d[:4])
    if not image_ids:
        return 0.0
    confidence = np.asarray(confidence, np.float32)
    boxes = np.asarray(boxes, np.float32)
    order = np.argsort(-confidence)
    image_ids = [image_ids[i] for i in order]
    boxes = boxes[order]

    nd = len(image_ids)
    tp = np.zeros(nd)
    fp = np.zeros(nd)
    for d in range(nd):
        rec_ = class_recs.get(image_ids[d])
        if rec_ is None:
            fp[d] = 1.0
            continue
        bb = boxes[d]
        ovmax, jmax = -np.inf, -1
        gt = rec_["bbox"]
        if gt.size:
            ixmin = np.maximum(gt[:, 0], bb[0])
            iymin = np.maximum(gt[:, 1], bb[1])
            ixmax = np.minimum(gt[:, 2], bb[2])
            iymax = np.minimum(gt[:, 3], bb[3])
            iw = np.maximum(ixmax - ixmin + 1.0, 0.0)
            ih = np.maximum(iymax - iymin + 1.0, 0.0)
            inter = iw * ih
            union = ((bb[2] - bb[0] + 1.0) * (bb[3] - bb[1] + 1.0)
                     + (gt[:, 2] - gt[:, 0] + 1.0) * (gt[:, 3] - gt[:, 1] + 1.0)
                     - inter)
            overlaps = inter / np.maximum(union, 1e-12)
            jmax = int(np.argmax(overlaps))
            ovmax = float(overlaps[jmax])
        if ovmax >= ovthresh:
            if not rec_["difficult"][jmax]:
                if not rec_["det"][jmax]:
                    tp[d] = 1.0
                    rec_["det"][jmax] = True
                else:
                    fp[d] = 1.0  # duplicate detection
        else:
            fp[d] = 1.0

    fp = np.cumsum(fp)
    tp = np.cumsum(tp)
    rec = tp / max(float(npos), 1.0)
    prec = tp / np.maximum(tp + fp, np.finfo(np.float64).eps)
    return voc_ap(rec, prec, use_07_metric)
