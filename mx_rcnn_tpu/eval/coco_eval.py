"""COCO detection evaluation, re-derived in-repo (no pycocotools).

The reference vendors pycocotools (``rcnn/pycocotools/cocoeval.py`` +
C mask ops) and calls ``COCOeval`` from ``rcnn/dataset/coco.py``.  This
module re-implements the COCOeval protocol in pure numpy:

* IoU thresholds 0.50:0.05:0.95, 101 recall points, area ranges
  all/small/medium/large, maxDets (1, 10, 100);
* greedy per-image/category matching, score-descending, each gt claimed
  once, crowd gt matchable many times with IoU = inter/det_area;
* ignore semantics: crowd or out-of-area gt don't count as npos, dets
  matched to them (or unmatched dets out of area) are neither TP nor FP;
* AP = mean interpolated precision over valid (category, IoU) cells;
  AR = mean max-recall.

``iou_type='segm'`` scores masks via RLE IoU (``eval/mask_rle.py``).
Headline keys: AP, AP50, AP75, APs, APm, APl, AR1, AR10, AR100.
"""

from __future__ import annotations

import json
from collections import defaultdict
from typing import Dict, List, Optional, Sequence

import numpy as np

IOU_THRS = np.linspace(0.5, 0.95, 10)
REC_THRS = np.linspace(0.0, 1.0, 101)
AREA_RNGS = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0 ** 2),
    "medium": (32.0 ** 2, 96.0 ** 2),
    "large": (96.0 ** 2, 1e10),
}
MAX_DETS = (1, 10, 100)


def bbox_iou_xywh(dt: np.ndarray, gt: np.ndarray,
                  iscrowd: np.ndarray) -> np.ndarray:
    """(D, G) IoU over xywh boxes; crowd gt use det area as denominator
    (pycocotools ``maskApi bbIou`` semantics, no +1 convention)."""
    if dt.size == 0 or gt.size == 0:
        return np.zeros((len(dt), len(gt)))
    dx1, dy1 = dt[:, 0:1], dt[:, 1:2]
    dx2, dy2 = dt[:, 0:1] + dt[:, 2:3], dt[:, 1:2] + dt[:, 3:4]
    gx1, gy1 = gt[None, :, 0], gt[None, :, 1]
    gx2, gy2 = gt[None, :, 0] + gt[None, :, 2], gt[None, :, 1] + gt[None, :, 3]
    iw = np.minimum(dx2, gx2) - np.maximum(dx1, gx1)
    ih = np.minimum(dy2, gy2) - np.maximum(dy1, gy1)
    inter = np.clip(iw, 0, None) * np.clip(ih, 0, None)
    da = (dt[:, 2:3] * dt[:, 3:4])
    ga = (gt[None, :, 2] * gt[None, :, 3])
    union = np.where(iscrowd[None, :], da, da + ga - inter)
    return inter / np.maximum(union, 1e-12)


class COCOEval:
    """Evaluate results (COCO results-json records) against an annotation
    file.  One-shot: construct, then ``evaluate()``."""

    def __init__(self, ann_file: str, results: List[dict],
                 iou_type: str = "bbox",
                 img_ids: Optional[Sequence[int]] = None):
        if iou_type not in ("bbox", "segm"):
            raise ValueError(iou_type)
        self.iou_type = iou_type
        with open(ann_file) as f:
            ann = json.load(f)
        self.imgs = {im["id"]: im for im in ann["images"]}
        self.img_ids = sorted(self.imgs if img_ids is None else img_ids)
        self.cat_ids = sorted(c["id"] for c in ann["categories"])

        self._gts = defaultdict(list)
        for g in ann["annotations"]:
            if g["image_id"] in self.imgs:
                self._gts[g["image_id"], g["category_id"]].append(g)
        self._dts = defaultdict(list)
        for d in results:
            self._dts[d["image_id"], d["category_id"]].append(d)
        self._cache: dict = {}

    # -- per (image, category) matching --------------------------------------
    def _prepared(self, img_id: int, cat_id: int):
        """Score-sorted dets, gts, IoU matrix and det areas for one
        (image, category) — computed ONCE and reused across all
        (area_rng, max_det) cells (pycocotools computeIoU does the same)."""
        key = (img_id, cat_id)
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        gts = self._gts[key]
        dts = self._dts[key]
        d_order = np.argsort([-d["score"] for d in dts], kind="stable")
        dts = [dts[i] for i in d_order]
        iscrowd = np.asarray([g.get("iscrowd", 0) for g in gts], bool)
        if self.iou_type == "bbox":
            dt = np.asarray([d["bbox"] for d in dts], np.float64).reshape(-1, 4)
            gt = np.asarray([g["bbox"] for g in gts], np.float64).reshape(-1, 4)
            ious = bbox_iou_xywh(dt, gt, iscrowd)
            d_area = dt[:, 2] * dt[:, 3]
        else:
            from mx_rcnn_tpu.eval.mask_rle import ann_to_rle, area
            from mx_rcnn_tpu.native import rle_iou  # C++ run-merge fast path

            im = self.imgs[img_id]
            h, w = im["height"], im["width"]
            dr = [ann_to_rle(d["segmentation"], h, w) for d in dts]
            gr = [ann_to_rle(g["segmentation"], h, w) for g in gts]
            ious = rle_iou(dr, gr, iscrowd)
            # pycocotools loadRes materializes det area from the mask
            d_area = np.asarray([d.get("area") or area(r)
                                 for d, r in zip(dts, dr)], np.float64)
        out = (dts, gts, ious, d_area)
        self._cache[key] = out
        return out

    def _evaluate_img(self, img_id: int, cat_id: int, area_rng, max_det: int):
        dts_all, gts, ious_all, d_area_all = self._prepared(img_id, cat_id)
        if not gts and not dts_all:
            return None
        gt_ignore = np.asarray(
            [g.get("iscrowd", 0) or g.get("ignore", 0)
             or g["area"] < area_rng[0] or g["area"] > area_rng[1]
             for g in gts], bool)
        # gt order: non-ignored first (matching preference)
        g_order = np.argsort(gt_ignore, kind="stable")
        gts = [gts[i] for i in g_order]
        gt_ignore = gt_ignore[g_order]
        iscrowd = np.asarray([g.get("iscrowd", 0) for g in gts], bool)

        dts = dts_all[:max_det]
        d_area = d_area_all[:max_det]
        ious = ious_all[:max_det][:, g_order] if len(gts) else ious_all[:max_det]

        T, D, G = len(IOU_THRS), len(dts), len(gts)
        dt_match = np.zeros((T, D), np.int64)
        gt_match = np.zeros((T, G), np.int64)
        dt_ignore = np.zeros((T, D), bool)
        for ti, t in enumerate(IOU_THRS):
            for di in range(D):
                best = min(t, 1 - 1e-10)
                m = -1
                for gi in range(G):
                    if gt_match[ti, gi] > 0 and not iscrowd[gi]:
                        continue
                    # gt are sorted non-ignored first: stop at the ignored
                    # block if a real match is already in hand
                    if m > -1 and not gt_ignore[m] and gt_ignore[gi]:
                        break
                    if ious[di, gi] < best:
                        continue
                    best = ious[di, gi]
                    m = gi
                if m == -1:
                    continue
                dt_ignore[ti, di] = gt_ignore[m]
                dt_match[ti, di] = 1
                gt_match[ti, m] = di + 1
        # unmatched dets outside the area range are ignored, not FP
        out_of_rng = (d_area < area_rng[0]) | (d_area > area_rng[1])
        dt_ignore |= (dt_match == 0) & out_of_rng[None, :]
        return {
            "scores": np.asarray([d["score"] for d in dts]),
            "dt_match": dt_match, "dt_ignore": dt_ignore,
            "num_gt": int((~gt_ignore).sum()),
        }

    # -- accumulate + summarize ----------------------------------------------
    def evaluate(self) -> Dict[str, float]:
        T, R = len(IOU_THRS), len(REC_THRS)
        K, A, M = len(self.cat_ids), len(AREA_RNGS), len(MAX_DETS)
        precision = -np.ones((T, R, K, A, M))
        recall = -np.ones((T, K, A, M))

        area_items = list(AREA_RNGS.items())
        for ki, cat_id in enumerate(self.cat_ids):
            for ai, (_, rng) in enumerate(area_items):
                for mi, max_det in enumerate(MAX_DETS):
                    evs = [self._evaluate_img(i, cat_id, rng, max_det)
                           for i in self.img_ids]
                    evs = [e for e in evs if e is not None]
                    if not evs:
                        continue
                    scores = np.concatenate([e["scores"] for e in evs])
                    order = np.argsort(-scores, kind="mergesort")
                    dtm = np.concatenate([e["dt_match"] for e in evs], axis=1)[:, order]
                    dti = np.concatenate([e["dt_ignore"] for e in evs], axis=1)[:, order]
                    npig = sum(e["num_gt"] for e in evs)
                    if npig == 0:
                        continue
                    tps = (dtm == 1) & ~dti
                    fps = (dtm == 0) & ~dti
                    tp_sum = np.cumsum(tps, axis=1).astype(np.float64)
                    fp_sum = np.cumsum(fps, axis=1).astype(np.float64)
                    for ti in range(T):
                        tp, fp = tp_sum[ti], fp_sum[ti]
                        nd = len(tp)
                        rc = tp / npig
                        pr = tp / np.maximum(tp + fp, np.spacing(1))
                        recall[ti, ki, ai, mi] = rc[-1] if nd else 0.0
                        # precision envelope (monotone decreasing)
                        q = np.zeros(R)
                        pr = pr.tolist()
                        for i in range(nd - 1, 0, -1):
                            if pr[i] > pr[i - 1]:
                                pr[i - 1] = pr[i]
                        inds = np.searchsorted(rc, REC_THRS, side="left")
                        for ri, pi in enumerate(inds):
                            if pi < nd:
                                q[ri] = pr[pi]
                        precision[ti, :, ki, ai, mi] = q
        self.precision = precision
        self.recall = recall

        def _ap(iou=None, area="all", max_det=100):
            ai = list(AREA_RNGS).index(area)
            mi = MAX_DETS.index(max_det)
            p = precision[:, :, :, ai, mi]
            if iou is not None:
                p = p[[int(round((iou - 0.5) / 0.05))]]
            p = p[p > -1]
            return float(np.mean(p)) if p.size else -1.0

        def _ar(area="all", max_det=100):
            ai = list(AREA_RNGS).index(area)
            mi = MAX_DETS.index(max_det)
            r = recall[:, :, ai, mi]
            r = r[r > -1]
            return float(np.mean(r)) if r.size else -1.0

        return {
            "AP": _ap(), "AP50": _ap(iou=0.5), "AP75": _ap(iou=0.75),
            "APs": _ap(area="small"), "APm": _ap(area="medium"),
            "APl": _ap(area="large"),
            "AR1": _ar(max_det=1), "AR10": _ar(max_det=10),
            "AR100": _ar(max_det=100),
            "ARs": _ar(area="small"), "ARm": _ar(area="medium"),
            "ARl": _ar(area="large"),
        }
