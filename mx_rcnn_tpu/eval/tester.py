"""Inference/eval driver (reference ``rcnn/core/tester.py``).

``Predictor`` binds params to the jitted test graph; ``im_detect`` applies
the bbox decode on device and maps boxes back to the original image frame;
``pred_eval`` runs the dataset loop with per-class threshold → NMS →
max_per_image cap (all host numpy, off the hot path, exactly like the
reference); ``generate_proposals`` dumps RPN proposals for 4-step alternate
training.
"""

from __future__ import annotations

import pickle
import time
from typing import List, Optional

import jax
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.data.loader import TestLoader
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.native import nms  # C++ fast path, numpy fallback inside
from mx_rcnn_tpu.ops.boxes import bbox_pred as decode_boxes, clip_boxes


class Predictor:
    """Bound jitted forward (reference ``Predictor`` wraps a bound executor;
    here the 'binding' is a jit cache keyed on the bucket shape)."""

    def __init__(self, model, params, cfg: Config):
        self.model = model
        self.params = params
        self.cfg = cfg
        self._predict = jax.jit(
            lambda p, images, im_info: model.apply(
                {"params": p}, images, im_info, method=model.predict))
        self._predict_rpn = jax.jit(
            lambda p, images, im_info: model.apply(
                {"params": p}, images, im_info, method=model.predict_rpn))

    def predict(self, images, im_info):
        return self._predict(self.params, images, im_info)

    def predict_rpn(self, images, im_info):
        return self._predict_rpn(self.params, images, im_info)


def im_detect(predictor: Predictor, batch: dict):
    """Forward one batch → per-image (scores, boxes) in ORIGINAL image
    coordinates (reference ``im_detect``: bbox_pred + clip_boxes, then
    divide by im_scale).

    Returns list of (scores (R, K), boxes (R, 4K), valid (R,)) numpy
    triples, one per valid batch row.

    Contract: ``predictor.params`` must predict RAW deltas — i.e. params
    from a saved checkpoint (the de-normalize-at-save fold,
    train/checkpoint.py) or live training params passed through
    ``denormalize_for_save`` first.
    """
    rois, roi_valid, cls_prob, bbox_deltas, _ = predictor.predict(
        batch["images"], batch["im_info"])
    rois, roi_valid, cls_prob, bbox_deltas = jax.device_get(
        (rois, roi_valid, cls_prob, bbox_deltas))
    im_info = np.asarray(batch["im_info"])

    out = []
    n = int(np.sum(batch.get("batch_valid", np.ones(len(rois), bool))))
    for b in range(n):
        eh, ew, s = im_info[b]
        boxes = decode_boxes(rois[b], bbox_deltas[b])  # (R, 4K)
        boxes = clip_boxes(boxes, eh, ew)
        boxes = np.asarray(boxes) / s                  # original frame
        out.append((cls_prob[b], boxes, roi_valid[b]))
    return out


def pred_eval(predictor: Predictor, test_loader: TestLoader, imdb,
              max_per_image: Optional[int] = None,
              thresh: Optional[float] = None,
              vis: bool = False) -> dict:
    """Dataset eval loop (reference ``pred_eval``): all_boxes[cls][image] =
    (N, 5) [x1,y1,x2,y2,score]; per-class score threshold + NMS; global
    per-image cap; then ``imdb.evaluate_detections``."""
    cfg = predictor.cfg
    if max_per_image is None:
        max_per_image = cfg.TEST.MAX_PER_IMAGE
    if thresh is None:
        thresh = cfg.TEST.THRESH
    num_classes = imdb.num_classes
    num_images = imdb.num_images

    all_boxes: List[List] = [[None for _ in range(num_images)]
                             for _ in range(num_classes)]
    t0 = time.time()
    done = 0
    for batch in test_loader:
        dets = im_detect(predictor, batch)
        indices = batch["indices"]
        for b, (scores, boxes, valid) in enumerate(dets):
            i = int(indices[b])
            v = np.asarray(valid, bool)
            for k in range(1, num_classes):
                sel = (scores[:, k] > thresh) & v
                cls_scores = scores[sel, k]
                cls_boxes = boxes[sel, 4 * k:4 * (k + 1)]
                cls_dets = np.hstack([cls_boxes, cls_scores[:, None]]).astype(
                    np.float32)
                keep = nms(cls_dets, cfg.TEST.NMS)
                all_boxes[k][i] = cls_dets[keep]
            # cap total detections per image (reference max_per_image block)
            if max_per_image > 0:
                scores_all = np.concatenate(
                    [all_boxes[k][i][:, 4] for k in range(1, num_classes)])
                if len(scores_all) > max_per_image:
                    th = np.sort(scores_all)[-max_per_image]
                    for k in range(1, num_classes):
                        keep = all_boxes[k][i][:, 4] >= th
                        all_boxes[k][i] = all_boxes[k][i][keep]
            done += 1
        if done % 100 < len(dets):
            logger.info("im_detect: %d/%d  %.3fs/im", done, num_images,
                        (time.time() - t0) / max(done, 1))
    return imdb.evaluate_detections(all_boxes)


def generate_proposals(predictor: Predictor, test_loader: TestLoader,
                       imdb, roidb: list,
                       cache_path: Optional[str] = None) -> list:
    """RPN-only pass dumping per-image proposals in ORIGINAL coordinates
    into the roidb (reference ``generate_proposals`` → .pkl for
    train_alternate steps 2/5)."""
    for batch in test_loader:
        rois, scores, valid = jax.device_get(
            predictor.predict_rpn(batch["images"], batch["im_info"]))
        im_info = np.asarray(batch["im_info"])
        indices = batch["indices"]
        n = int(np.sum(batch["batch_valid"]))
        for b in range(n):
            i = int(indices[b])
            v = np.asarray(valid[b], bool)
            props = np.asarray(rois[b])[v] / im_info[b, 2]
            order = np.argsort(-np.asarray(scores[b])[v])
            roidb[i]["proposals"] = props[order].astype(np.float32)
    if cache_path:
        with open(cache_path, "wb") as f:
            pickle.dump([r.get("proposals") for r in roidb], f,
                        pickle.HIGHEST_PROTOCOL)
        logger.info("wrote proposals to %s", cache_path)
    return roidb
