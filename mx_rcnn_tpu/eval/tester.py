"""Inference/eval driver (reference ``rcnn/core/tester.py``).

``Predictor`` binds params to the jitted test graph; ``im_detect`` applies
the bbox decode on device and maps boxes back to the original image frame;
``pred_eval`` runs the dataset loop with per-class threshold → NMS →
max_per_image cap (all host numpy, off the hot path, exactly like the
reference); ``generate_proposals`` dumps RPN proposals for 4-step alternate
training.
"""

from __future__ import annotations

import os
import pickle
import time
from functools import partial
from typing import List, Optional

import jax
import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.compile import ProgramRegistry
from mx_rcnn_tpu.compile.registry import INFER_DTYPES
from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.data.loader import TestLoader
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.ops.postprocess import (decode_image_boxes,
                                         device_dets_to_per_class,
                                         per_class_nms)


def _variant_params(params, dtype: str):
    """Transform a float32 param tree into the requested inference
    variant.  ``bfloat16`` halves param memory/bandwidth (compute already
    runs in ``cfg.tpu.COMPUTE_DTYPE``); ``int8`` stores per-leaf
    symmetric-quantized weights as ``(int8 values, f32 scale)`` tuples,
    dequantized inside the jitted program — a memory-bound-serving
    variant, tolerance-tested more loosely than bf16.
    ``int8-activation`` quantizes weights identically AND fake-quantizes
    the network-input activations against calibrated per-tensor scales
    (see :func:`calibrate_activation_scales`)."""
    import jax.numpy as jnp

    if dtype == "float32":
        return params
    if dtype == "bfloat16":
        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            params)

    def q(x):
        x = np.asarray(x)
        if x.dtype.kind != "f" or x.size == 0:
            return x
        s = float(np.max(np.abs(x))) / 127.0 or 1.0
        qv = np.clip(np.rint(x / s), -127, 127).astype(np.int8)
        return (qv, np.float32(s))

    return jax.tree.map(q, params)


def _make_unpack(dtype: str):
    """The in-program half of :func:`_variant_params` (traced under jit):
    int8 tuples dequantize back to f32 right before ``model.apply``; the
    other variants pass through."""
    import jax.numpy as jnp

    if dtype not in ("int8", "int8-activation"):
        return lambda p: p

    def dq(t):
        if isinstance(t, tuple):
            qv, s = t
            return qv.astype(jnp.float32) * s
        return t

    return lambda p: jax.tree.map(dq, p,
                                  is_leaf=lambda t: isinstance(t, tuple))


def _make_quant_in(dtype: str, act_scales):
    """Activation fake-quant for the ``int8-activation`` variant (traced
    under jit): the normalized image tensor entering the network is
    symmetric-quantized to 8 bits against its calibrated per-tensor scale
    and immediately dequantized — the forward then sees exactly the
    values an int8 activation path would, so the parity pin measures the
    real quantization error, not a kernel substitution.  Without a
    calibrated ``"images"`` scale (no calibration ran and none persisted)
    the variant degrades to weight-only int8 — safe, just unquantized
    activations."""
    import jax.numpy as jnp

    if dtype != "int8-activation":
        return lambda x: x
    info = (act_scales or {}).get("images") or {}
    s = float(info.get("scale", 0.0) or 0.0)
    if s <= 0.0:
        logger.warning("int8-activation without calibrated scales: "
                       "activations stay float (run --calibrate-shard "
                       "or persist scales in the program cache)")
        return lambda x: x

    def fq(x):
        q = jnp.clip(jnp.round(x.astype(jnp.float32) / s), -127.0, 127.0)
        return (q * s).astype(x.dtype)

    return fq


def _make_cast_out(dtype: str):
    """Low-precision variants cast floating outputs back to f32 inside
    the program, so the host post-process (numpy NMS, box decode) never
    sees bf16 — f32 keeps its outputs byte-identical to before."""
    import jax.numpy as jnp

    if dtype == "float32":
        return lambda out: out
    return lambda out: jax.tree.map(
        lambda x: x.astype(jnp.float32)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, out)


class Predictor:
    """Bound jitted forward (reference ``Predictor`` wraps a bound executor;
    here the 'binding' is a jit cache keyed on the bucket shape).

    ``plan``: optional ``MeshPlan`` — data-parallel eval (an upgrade over
    the reference's single-GPU ``pred_eval`` loop): params replicate, each
    batch row lives on its data-axis shard, every forward runs SPMD over
    the mesh.  The host loop is unchanged — ``jax.device_get`` gathers the
    sharded outputs.  Batch size must be a multiple of ``plan.n_data``
    (TestLoader pads the tail with repeats already).

    ``dtype``: inference variant — ``"float32"`` (params as loaded, the
    exact pre-registry behavior), ``"bfloat16"`` (params cast to bf16,
    outputs cast back to f32 in-program) or ``"int8"`` (symmetric
    per-leaf weight quantization, dequantized in-program).  Every jitted
    program routes through a dtype-keyed :class:`ProgramRegistry`, so a
    bf16 replica's compile bookkeeping and persistent-cache dir are
    disjoint from f32's.
    """

    def __init__(self, model, params, cfg: Config, plan=None,
                 dtype: str = "float32", cache_base=None, act_scales=None):
        if dtype not in INFER_DTYPES:
            raise ValueError(f"infer dtype must be one of {INFER_DTYPES}, "
                             f"got {dtype!r}")
        self.model = model
        self.cfg = cfg
        self.plan = plan
        self.infer_dtype = dtype
        self.registry = ProgramRegistry(cfg, dtype=dtype, plan=plan,
                                        cache_base=cache_base)
        if dtype == "int8-activation" and act_scales is None:
            # calibration persists next to the AOT marker manifest — a
            # warm boot of the same config digest finds the scales the
            # cached executables were traced against
            act_scales = self.registry.load_act_scales()
        self.act_scales = act_scales
        # eval-side device prep (cfg.tpu.DEVICE_PREP + TestLoader
        # device_prep=True): batch_put consumes the staged-uint8 sidecars
        # through the same jitted kernel train uses.  maybe_device_prep
        # raises the explicit ValueError under a mesh plan — the prep
        # output would need the plan's input sharding.
        from mx_rcnn_tpu.data.device_prep import maybe_device_prep

        self._device_prep = maybe_device_prep(cfg, registry=self.registry,
                                              plan=plan)
        params = _variant_params(params, dtype)
        unpack = _make_unpack(dtype)
        cast_out = _make_cast_out(dtype)
        quant_in = _make_quant_in(dtype, act_scales)
        if plan is not None:
            from mx_rcnn_tpu.parallel import check_spatial
            from mx_rcnn_tpu.parallel.distributed import is_multiprocess_mesh

            if is_multiprocess_mesh(plan.mesh):
                # enforced, not implicit (round-4 VERDICT weakness 4):
                # batch_put does a plain LOCAL device_put against the
                # global-mesh sharding and im_detect device_gets the
                # sharded outputs — both single-controller operations.
                raise NotImplementedError(
                    "Predictor/pred_eval are single-controller only: run "
                    "eval on a single-process mesh (e.g. each host "
                    "evaluates its own roidb slice on its local devices, "
                    "like the reference's per-GPU pred_eval loop), or "
                    "gate eval on process 0 with a local mesh.  For "
                    "online traffic, the serve subsystem "
                    "(mx_rcnn_tpu/serve, `python serve.py`) wraps this "
                    "same single-process Predictor behind a dynamic "
                    "batcher — scale out by running one serve.py replica "
                    "per host behind a load balancer, not by widening "
                    "the mesh across processes")
            check_spatial(plan, cfg)  # thin-shard guard (mesh.py rationale)
            params = jax.device_put(params, plan.replicated())
            repl, bsh = plan.replicated(), plan.batch()
            # images() additionally height-shards over a space axis when
            # the mesh has one (spatial-parallel eval for oversized
            # inputs); identical to batch() on a (data, model) mesh
            jit2 = partial(jax.jit, in_shardings=(repl, plan.images(), bsh))
        else:
            bsh = None
            jit2 = jax.jit
        self.params = params
        self._has_mask = bool(cfg.network.HAS_MASK)
        self._feats = None  # pyramid cache: set by predict(), same batch only
        # cache-identity token: (images shape, monotonic predict counter).
        # predict() stamps it; the cached-mask entry points assert it so a
        # reordered caller gets a loud error, never stale masks (VERDICT
        # round-2 weakness 6 / round-3 weakness 4).
        self._feats_token = None
        self._predict_count = 0

        # every jitted callable the eval/serve path can dispatch lives in
        # the registry (lazy, built-once, shared bookkeeping) — these
        # builders replace the four independent shape-keyed dicts
        reg = self.registry

        def fwd(method):
            def f(p, images, im_info):
                return cast_out(model.apply({"params": unpack(p)},
                                            quant_in(images),
                                            im_info, method=method))
            return f

        reg.register("predict", lambda: jit2(fwd(model.predict)))
        reg.register("predict_rpn", lambda: jit2(fwd(model.predict_rpn)))
        reg.register("pyramid", lambda: jax.jit(
            lambda p, x: model.apply({"params": unpack(p)}, x,
                                     method=model._pyramid)))
        if self._has_mask:
            def fwd_wf(p, images, im_info):
                out, feats = model.apply({"params": unpack(p)},
                                         quant_in(images), im_info,
                                         method=model.predict_with_feats)
                # feats stay in native compute dtype: they only feed the
                # mask programs below, never the host
                return cast_out(out), feats

            reg.register("predict_wf", lambda: jit2(fwd_wf))
            # feats sharding is None = inherit from the committed arrays:
            # on a space mesh the cached pyramid comes out of predict()
            # height-sharded, and pinning it to batch() here would make
            # jit reject the mismatch instead of resharding
            mjit = (jax.jit if plan is None else
                    partial(jax.jit,
                            in_shardings=(plan.replicated(), None, bsh, bsh)))
            reg.register("masks_from_feats", lambda: mjit(
                lambda p, feats, boxes, labels: cast_out(model.apply(
                    {"params": unpack(p)}, feats, boxes, labels,
                    method=model.masks_from_feats))))

            def build_packed(hp, wp):
                from mx_rcnn_tpu.ops.mask_paste import paste_masks

                def chain(p, feats, bxs, lbl, bxo):
                    probs = model.apply({"params": unpack(p)}, feats, bxs,
                                        lbl, method=model.masks_from_feats)
                    return paste_masks(probs, bxo, hp, wp)

                if plan is None:
                    return jax.jit(chain)
                bsh_ = plan.batch()
                return jax.jit(chain, in_shardings=(
                    plan.replicated(), None, bsh_, bsh_, bsh_))

            reg.register("masks_packed", build_packed)

        # fused forward + decode + per-class NMS ("--device-postprocess"):
        # the host reads back (B, cap, 6) final detections instead of the
        # full (R, K) scores + (R, 4K) deltas.  The statics
        # (max_per_image, thresh) are baked into the executable, so
        # predict_detections folds them into the registry shape key too —
        # two evals differing only in those flags are different programs.
        has_mask = self._has_mask

        def build_post(max_per_image, thresh):
            import jax.numpy as jnp

            from mx_rcnn_tpu.ops.postprocess import device_postprocess

            def f(p, images, im_info):
                if has_mask:
                    out, feats = model.apply({"params": unpack(p)},
                                             quant_in(images), im_info,
                                             method=model.predict_with_feats)
                else:
                    out = model.apply({"params": unpack(p)},
                                      quant_in(images), im_info,
                                      method=model.predict)
                    feats = None
                # cast BEFORE the decode: low-precision variants must not
                # run the box math (or NMS IoUs) in bf16 — parity with the
                # host path is pinned per-dtype by the f32 cast here
                rois, roi_valid, cls_prob, bbox_deltas = cast_out(
                    out[:4])
                dets, dvalid = device_postprocess(
                    rois, roi_valid, cls_prob, bbox_deltas,
                    jnp.asarray(im_info, jnp.float32),
                    num_classes=cfg.NUM_CLASSES, thresh=thresh,
                    nms_thresh=cfg.TEST.NMS, max_per_image=max_per_image,
                    use_pallas=cfg.TEST.CXX_PROPOSAL)
                if has_mask:
                    return (dets, dvalid), feats
                return dets, dvalid

            return jit2(f)

        reg.register("predict_post", build_post)

        # fused prep + forward + decode + NMS ("--serve-e2e"): the serve
        # engine ships staged raw uint8 (data/image.py stage_raw_to_bucket)
        # plus the raw_hw/ratio/flip sidecars and reads back only the
        # (B, cap, 6) detections — one uint8 h2d transfer, one dispatch,
        # one tiny readback per request batch.  Prep constants mirror
        # data/device_prep.DevicePrep exactly (same _prep_one kernel), so
        # the fused path inherits its host-bilinear parity pin.
        net = cfg.network

        def build_serve_e2e(max_per_image, thresh):
            import jax.numpy as jnp

            from mx_rcnn_tpu.data.device_prep import _prep_one
            from mx_rcnn_tpu.ops.postprocess import device_postprocess

            mean = jnp.asarray(net.PIXEL_MEANS, jnp.float32)
            std = jnp.asarray(net.PIXEL_STDS, jnp.float32)
            s2d = bool(net.HOST_S2D)

            def one(raw, hw, rt, ii, fl):
                return _prep_one(raw, hw, rt, ii, fl, mean, std, s2d,
                                 jnp.float32)

            def f(p, staged, raw_hw, ratio, im_info, flip):
                images = quant_in(jax.vmap(one)(staged, raw_hw, ratio,
                                                im_info, flip))
                if has_mask:
                    out, _ = model.apply({"params": unpack(p)}, images,
                                         im_info,
                                         method=model.predict_with_feats)
                else:
                    out = model.apply({"params": unpack(p)}, images,
                                      im_info, method=model.predict)
                rois, roi_valid, cls_prob, bbox_deltas = cast_out(out[:4])
                return device_postprocess(
                    rois, roi_valid, cls_prob, bbox_deltas,
                    jnp.asarray(im_info, jnp.float32),
                    num_classes=cfg.NUM_CLASSES, thresh=thresh,
                    nms_thresh=cfg.TEST.NMS, max_per_image=max_per_image,
                    use_pallas=cfg.TEST.CXX_PROPOSAL)

            return jax.jit(f)

        reg.register("serve_e2e", build_serve_e2e)

    def batch_put(self, batch: dict) -> dict:
        """The TestLoader ``put`` hook: move ``images`` (the only large
        buffer) onto the mesh (or chip) from the prefetch thread so the
        transfer overlaps the previous batch's forward.  Host-consumed
        keys (``im_info``, ``indices``, ``batch_valid``) stay numpy —
        ``im_detect``/``_mask_pass`` read them back every batch, and a
        device-resident copy would add a blocked d2h round-trip per batch
        (~100-300 ms on the tunnel); jit ships the 12-byte ``im_info``
        per call for free.

        Under eval device prep (``--device-prep``) the batch arrives as
        staged raw uint8 + sidecars; the hook transfers those and runs the
        jitted prep program (registry kind ``"device_prep"``), so the
        batch leaves this hook in exactly the host-path layout — float
        ``images`` on device, ``im_info``/``indices``/``batch_valid``
        still numpy."""
        if self._device_prep is not None and "raw_hw" in batch:
            out = dict(batch)
            raw = jax.device_put(out.pop("images"))
            raw_hw = jax.device_put(out.pop("raw_hw"))
            ratio = jax.device_put(out.pop("prep_ratio"))
            flip = jax.device_put(out.pop("flip"))
            ii = jax.device_put(np.asarray(out["im_info"], np.float32))
            out["images"] = self._device_prep._run(raw, raw_hw, ratio, ii,
                                                   flip)
            return out
        sh = self.plan.images() if self.plan is not None else None
        out = dict(batch)
        out["images"] = (jax.device_put(batch["images"], sh)
                         if sh is not None else jax.device_put(batch["images"]))
        return out

    def update_params(self, params) -> None:
        """Swap the bound weights in place — the serving hot-reload
        primitive.  Applies the same variant cast + device placement as
        construction; because every registered program takes ``params``
        as a RUNTIME argument (see :meth:`_dispatch`), the registry's
        compiled executables are reused as-is: a weight swap costs zero
        recompiles.  The caller (serve drain) must ensure no forward is
        in flight — ``self.params`` is rebound atomically but a batch
        straddling the swap would mix generations."""
        params = _variant_params(params, self.infer_dtype)
        if self.plan is not None:
            params = jax.device_put(params, self.plan.replicated())
        self.params = params
        self._feats = None  # cached pyramid belongs to the old weights
        self._feats_token = None

    def note_dispatch(self, shape, kind: Optional[str] = None) -> bool:
        """Registry first-seen accounting for the program that will
        dispatch on ``shape`` — True exactly once per (kind, shape) per
        process (the serve engine's recompile-counter signal).  ``kind``
        defaults to the legacy forward program; the fused serve path
        passes ``"serve_e2e"`` so its programs are labeled apart."""
        if kind is None:
            kind = "predict_wf" if self._has_mask else "predict"
        return self.registry.note_dispatch(kind, shape)

    def record_compile_seconds(self, shape, seconds: float,
                               kind: Optional[str] = None) -> None:
        """Companion to :meth:`note_dispatch` for callers (the serve
        engine) that own the first-dispatch timing themselves."""
        if kind is None:
            kind = "predict_wf" if self._has_mask else "predict"
        self.registry.record_compile_seconds(kind, shape, seconds)

    @staticmethod
    def serve_e2e_shape(staged_shape, max_per_image, thresh):
        """The registry shape key of the fused serve program for a staged
        uint8 batch — the baked-in statics ride along as string tokens
        (two configs differing only in cap/threshold are different
        executables).  The serve engine uses this for its first-dispatch
        accounting so its counter and :meth:`predict_serve_e2e` agree on
        program identity."""
        return tuple(staged_shape) + (f"mpi={int(max_per_image)}",
                                      f"th={float(thresh):g}")

    def _dispatch(self, kind, shape, fn, *args):
        """Run one registered program; on its first dispatch, block and
        feed the wall time (compile + first run) to the registry's
        compile-seconds histogram."""
        first = self.registry.note_dispatch(kind, shape)
        t0 = time.perf_counter()
        out = fn(self.params, *args)
        if first:
            jax.block_until_ready(out)
            self.registry.record_compile_seconds(
                kind, shape, time.perf_counter() - t0)
        return out

    def predict(self, images, im_info):
        self._predict_count += 1
        self._feats_token = (tuple(images.shape), self._predict_count)
        if self._has_mask:
            out, feats = self._dispatch(
                "predict_wf", images.shape,
                self.registry.lookup("predict_wf"), images, im_info)
            self._feats = feats  # reused by predict_masks for this batch
            return out
        return self._dispatch("predict", images.shape,
                              self.registry.lookup("predict"),
                              images, im_info)

    def predict_detections(self, images, im_info, max_per_image, thresh):
        """Fused forward + device post-process (``--device-postprocess``):
        → ((B, cap, 6) [x1..y2,score,cls] dets, (B, cap) valid), both still
        on device.  Readback is ``max_per_image`` rows per image instead of
        the full (R, K) scores + (R, 4K) deltas.  On mask configs the
        pyramid is cached exactly like ``predict`` (same token
        discipline)."""
        mpi = int(max_per_image)
        th = float(thresh)
        self._predict_count += 1
        self._feats_token = (tuple(images.shape), self._predict_count)
        fn = self.registry.lookup("predict_post", static=(mpi, th))
        # string tokens carry the baked-in statics into the program key —
        # a different cap/threshold is a different executable
        shape = tuple(images.shape) + (f"mpi={mpi}", f"th={th:g}")
        if self._has_mask:
            (dets, dvalid), feats = self._dispatch(
                "predict_post", shape, fn, images, im_info)
            self._feats = feats
            return dets, dvalid
        return self._dispatch("predict_post", shape, fn, images, im_info)

    def predict_serve_e2e(self, staged, raw_hw, ratio, im_info, flip,
                          max_per_image, thresh):
        """Single-dispatch serving program: staged raw uint8 + sidecars in,
        ``((B, cap, 6) dets, (B, cap) valid)`` out, both still on device.
        Device prep, the forward, and decode+NMS run fused — the caller
        (serve engine) does one ``device_put`` of the argument tuple, one
        call here, one ``device_get`` of the return."""
        mpi = int(max_per_image)
        th = float(thresh)
        fn = self.registry.lookup("serve_e2e", static=(mpi, th))
        shape = self.serve_e2e_shape(staged.shape, mpi, th)
        return self._dispatch("serve_e2e", shape, fn, staged, raw_hw,
                              ratio, im_info, flip)

    @property
    def feats_token(self):
        """Identity of the batch whose pyramid is cached — capture right
        after ``predict`` and hand to the ``predict_masks_*`` cached entry
        points to pin them to that batch."""
        return self._feats_token

    def capture_feats(self):
        """Overlap-safe handle on the pyramid the last ``predict`` cached:
        ``(feats, token)``.  The pipelined evaluator calls this right after
        dispatching batch N's forward, BEFORE dispatching batch N+1 — the
        captured pair stays valid after the cache is overwritten, so the
        mask pass for N can run while N+1 is in flight (pass ``feats=`` to
        the ``predict_masks_*`` entry points)."""
        return self._feats, self._feats_token

    def _check_token(self, token):
        if token != self._feats_token:
            raise AssertionError(
                f"stale pyramid cache: predict() was last called on batch "
                f"{self._feats_token}, not {token}; re-run predict() on "
                f"the batch whose masks you want (pass "
                f"predictor.feats_token captured right after predict())")

    def predict_rpn(self, images, im_info):
        return self._dispatch("predict_rpn", images.shape,
                              self.registry.lookup("predict_rpn"),
                              images, im_info)

    def predict_masks(self, images, im_info, boxes, labels):
        """boxes in the SCALED frame; → (B, R, 28, 28) probabilities.
        Runs the full forward — correct for any batch."""
        assert self.cfg.network.HAS_MASK, "model has no mask head"
        del im_info
        feats = self._pyramid(images)
        return self._dispatch("masks_from_feats", boxes.shape,
                              self.registry.lookup("masks_from_feats"),
                              feats, boxes, labels)

    def predict_masks_cached(self, boxes, labels, token, feats=None):
        """Mask branch over the pyramid cached by the immediately preceding
        ``predict`` — ONLY valid for that same batch.  ``token`` (required:
        capture :attr:`feats_token` right after the ``predict`` call) pins
        the call to its batch; a reordered caller fails loudly.  An
        explicitly passed ``feats`` (from :meth:`capture_feats`) bypasses
        the cache AND the token check — the captured pair already
        identifies its batch, which is what makes the pipelined
        evaluator's overlapped mask pass safe."""
        assert self._has_mask, "model has no mask head"
        if feats is None:
            assert self._feats is not None, \
                "call predict() on this batch first"
            self._check_token(token)
            feats = self._feats
        return self._dispatch("masks_from_feats", boxes.shape,
                              self.registry.lookup("masks_from_feats"),
                              feats, boxes, labels)

    def predict_masks_packed(self, boxes, labels, orig_boxes, hp, wp,
                             token, feats=None):
        """Mask branch + on-device paste over the cached pyramid: SCALED-
        frame ``boxes`` feed RoIAlign, ORIGINAL-frame ``orig_boxes`` place
        the masks in the padded (hp, wp) original frame.  One fused jit
        call → (B, R, wp, hp//8) packed bitplanes; the host's only work is
        the C++ RLE encode (``native.rle_encode_packed``).  ``feats``
        semantics as in :meth:`predict_masks_cached`."""
        assert self._has_mask, "model has no mask head"
        if feats is None:
            assert self._feats is not None, \
                "call predict() on this batch first"
            self._check_token(token)
            feats = self._feats
        fn = self.registry.lookup("masks_packed", static=(hp, wp))
        return self._dispatch("masks_packed",
                              tuple(boxes.shape) + (hp, wp), fn,
                              feats, boxes, labels, orig_boxes)

    def _pyramid(self, images):
        return self._dispatch("pyramid", images.shape,
                              self.registry.lookup("pyramid"), images)


def calibrate_activation_scales(model, params, cfg: Config, raw_images,
                                max_images: int = 8,
                                capture: bool = True) -> dict:
    """Activation-calibration pass for ``--infer-dtype int8-activation``:
    run the FLOAT model over a held-out shard of raw uint8 images and
    record a per-tensor symmetric absmax scale for every activation the
    pass can observe — the normalized network input plus (when
    ``capture`` and the model supports flax intermediate capture) every
    module output.  Returns ``{tensor: {"absmax", "scale"}}``; persist it
    with :meth:`ProgramRegistry.save_act_scales` so warm boots of the
    same config digest reuse the calibration their AOT executables were
    traced against.

    ``params`` must be the float32 tree (calibration observes the model
    the quantized variant approximates, not the variant itself)."""
    from mx_rcnn_tpu.data.loader import prepare_image

    scale = cfg.tpu.SCALES[0]
    absmax: dict = {}

    def acc(name, x):
        x = np.asarray(x)
        if x.dtype.kind != "f" or x.size == 0:
            return
        absmax[name] = max(absmax.get(name, 0.0),
                           float(np.max(np.abs(x))))

    seen = 0
    for im in raw_images:
        if seen >= max_images:
            break
        padded, info = prepare_image(np.asarray(im), cfg, scale)
        acc("images", padded)
        if capture:
            try:
                _, state = model.apply(
                    {"params": params}, padded[None],
                    np.asarray(info, np.float32)[None],
                    method=model.predict, capture_intermediates=True)
                leaves = jax.tree_util.tree_flatten_with_path(
                    dict(state).get("intermediates", {}))[0]
                for path, leaf in leaves:
                    name = "/".join(str(getattr(k, "key", k))
                                    for k in path)
                    acc(name, jax.device_get(leaf))
            except Exception as e:
                logger.warning("calibration: intermediate capture "
                               "unavailable (%s); input-tensor scale only",
                               e)
                capture = False
        seen += 1
    if seen == 0:
        raise ValueError("calibration shard is empty")
    logger.info("calibrated %d activation tensor(s) over %d image(s)",
                len(absmax), seen)
    return {name: {"absmax": round(a, 6),
                   "scale": round(a / 127.0, 9) if a > 0 else 1.0}
            for name, a in absmax.items()}


def paste_mask(prob: np.ndarray, box: np.ndarray, h: int, w: int) -> np.ndarray:
    """Paste one (M, M) mask probability map into a (h, w) binary mask at
    ``box`` (original-frame [x1,y1,x2,y2]) — the standard Mask R-CNN
    inference paste (resize to box, threshold 0.5)."""
    import cv2

    x1 = int(np.floor(box[0]))
    y1 = int(np.floor(box[1]))
    x2 = int(np.ceil(box[2]))
    y2 = int(np.ceil(box[3]))
    bw = max(x2 - x1 + 1, 1)
    bh = max(y2 - y1 + 1, 1)
    resized = cv2.resize(prob.astype(np.float32), (bw, bh),
                         interpolation=cv2.INTER_LINEAR)
    out = np.zeros((h, w), np.uint8)
    ox1, oy1 = max(x1, 0), max(y1, 0)
    ox2, oy2 = min(x2 + 1, w), min(y2 + 1, h)
    if ox2 > ox1 and oy2 > oy1:
        out[oy1:oy2, ox1:ox2] = (
            resized[oy1 - y1:oy2 - y1, ox1 - x1:ox2 - x1] >= 0.5)
    return out


def im_detect(predictor: Predictor, batch: dict):
    """Forward one batch → per-image (scores, boxes) in ORIGINAL image
    coordinates (reference ``im_detect``: bbox_pred + clip_boxes, then
    divide by im_scale).

    Returns list of (scores (R, K), boxes (R, 4K), valid (R,)) numpy
    triples, one per valid batch row.

    Contract: ``predictor.params`` must predict RAW deltas — i.e. params
    from a saved checkpoint (the de-normalize-at-save fold,
    train/checkpoint.py) or live training params passed through
    ``denormalize_for_save`` first.
    """
    tel = telemetry.get()
    # phase split: "forward" is the async dispatch (cheap unless compile),
    # "readback" is where the host actually waits on the device
    with tel.span("eval/forward"):
        rois, roi_valid, cls_prob, bbox_deltas, _ = predictor.predict(
            batch["images"], batch["im_info"])
    with tel.span("eval/readback"):
        rois, roi_valid, cls_prob, bbox_deltas = jax.device_get(
            (rois, roi_valid, cls_prob, bbox_deltas))
    im_info = np.asarray(batch["im_info"])

    out = []
    n = int(np.sum(batch.get("batch_valid", np.ones(len(rois), bool))))
    with tel.span("eval/decode"):
        for b in range(n):
            # shared post-process path (ops/postprocess.py): (R, 4K)
            # boxes in the original image frame
            boxes = decode_image_boxes(rois[b], bbox_deltas[b], im_info[b])
            out.append((cls_prob[b], boxes, roi_valid[b]))
    return out


def _im_detect_device(predictor, batch, max_per_image, thresh, num_classes):
    """``im_detect`` + ``per_class_nms`` fused on device
    (``--device-postprocess``): forward one batch through the
    ``predict_post`` program and read back only the top-``max_per_image``
    detections per image.  Returns a list of per-class detection lists
    (the ``per_class_nms`` shape), one per valid batch row — so the caller
    fills ``all_boxes`` identically on either path."""
    tel = telemetry.get()
    with tel.span("eval/forward"):
        dets, dvalid = predictor.predict_detections(
            batch["images"], batch["im_info"], max_per_image, thresh)
    with tel.span("eval/readback"):
        dets, dvalid = jax.device_get((dets, dvalid))
    n = int(np.sum(batch.get("batch_valid", np.ones(len(dets), bool))))
    out = []
    with tel.span("eval/decode"):
        for b in range(n):
            out.append(device_dets_to_per_class(dets[b], dvalid[b],
                                                num_classes))
    return out


class _Progress:
    """Monotonic eval progress reporter.  The old inline check
    (``done % 100 < len(dets)``) could fire several batches in a row or
    skip a century entirely depending on how the batch size strides the
    modulus; this keeps an explicit next-threshold, so exactly one line
    (and one rate gauge) is emitted per ``every`` images regardless of
    batch size or completion order."""

    def __init__(self, total: int, n_chips: int, every: int = 100):
        self.total = total
        self.n_chips = max(int(n_chips), 1)
        self.every = max(int(every), 1)
        self._next = self.every
        self.t0 = time.perf_counter()

    def update(self, done: int, tel) -> None:
        if done < self._next:
            return
        self._next = (done // self.every + 1) * self.every
        rate = max(done, 1) / max(time.perf_counter() - self.t0, 1e-9)
        tel.gauge("eval/imgs_per_sec", rate)
        logger.info("im_detect: %d/%d  %.3fs/im  %.1f imgs/s (%.1f/chip)",
                    done, self.total, 1.0 / rate, rate, rate / self.n_chips)


def save_vis(rec: dict, all_boxes, num_classes: int, class_names,
             i: int) -> None:
    """Write one image's detection visualization under ``vis/`` — shared
    by the serial loop and the pipelined host tasks."""
    vis_dir = "vis"
    os.makedirs(vis_dir, exist_ok=True)
    vis_all_detection(
        rec, [all_boxes[k][i] if k else None for k in range(num_classes)],
        class_names, os.path.join(vis_dir, f"{i:06d}.jpg"))


def pred_eval(predictor: Predictor, test_loader: TestLoader, imdb,
              max_per_image: Optional[int] = None,
              thresh: Optional[float] = None,
              vis: bool = False,
              with_masks: bool = False,
              det_cache: Optional[str] = None,
              inflight: Optional[int] = None,
              host_workers: Optional[int] = None,
              device_postprocess: bool = False) -> dict:
    """Dataset eval loop (reference ``pred_eval``): all_boxes[cls][image] =
    (N, 5) [x1,y1,x2,y2,score]; per-class score threshold + NMS; global
    per-image cap; then ``imdb.evaluate_detections``.

    ``with_masks`` (Mask R-CNN configs): runs the mask branch on the final
    detections, pastes 28×28 probabilities into full-image RLEs, and scores
    segm alongside bbox (``imdb.evaluate_sds``).

    ``det_cache``: pickle the final ``all_boxes`` there (the reference
    writes ``detections.pkl`` into the imdb cache; ``tools/reeval.py``
    re-scores it without a model or device).

    ``inflight`` (default ``cfg.tpu.EVAL_INFLIGHT``): dispatch window of
    the overlapped evaluator (``eval/pipeline.py``) — batch N+1's forward
    runs on device while batch N decodes/NMSes on a ``host_workers``-wide
    thread pool.  Results are index-addressed, so ``all_boxes`` (and the
    det_cache / scoring downstream) is bit-identical to the serial loop at
    any depth.  ``inflight=0`` forces the serial reference loop — the
    oracle the identity test pins the pipeline against.

    ``device_postprocess``: route the fused forward+decode+NMS program
    (``Predictor.predict_detections``) so the host reads back only the
    top-``max_per_image`` detections per image instead of the full
    (R, K) + (R, 4K) tensors.  Opt-in: exact score ties at thresholds may
    resolve differently from the host path (see
    ``ops.postprocess.device_postprocess``).

    Phase telemetry (whatever sink is active — ``mx_rcnn_tpu/telemetry``):
    per-batch ``eval/loader_wait`` / ``eval/forward`` / ``eval/readback``
    / ``eval/decode`` / ``eval/nms`` (+ ``eval/mask_pass``) spans, an
    ``eval/imgs_per_sec`` gauge, an ``eval/images`` counter and one
    ``eval_pipeline`` meta record with the overlap breakdown — the same
    JSONL schema as the train stream, so one report folds both.
    """
    cfg = predictor.cfg
    if max_per_image is None:
        max_per_image = cfg.TEST.MAX_PER_IMAGE
    if thresh is None:
        thresh = cfg.TEST.THRESH
    tpu_cfg = getattr(cfg, "tpu", None)
    if inflight is None:
        inflight = int(getattr(tpu_cfg, "EVAL_INFLIGHT", 2))
    if host_workers is None:
        host_workers = int(getattr(tpu_cfg, "EVAL_HOST_WORKERS", 2))
    num_classes = imdb.num_classes
    num_images = imdb.num_images
    with_masks = with_masks and cfg.network.HAS_MASK
    if with_masks and not hasattr(imdb, "evaluate_sds"):
        logger.warning("%s has no segm evaluation; scoring boxes only",
                       type(imdb).__name__)
        with_masks = False
    if device_postprocess and not hasattr(predictor, "predict_detections"):
        logger.warning("--device-postprocess needs a Predictor with "
                       "predict_detections; falling back to host NMS")
        device_postprocess = False

    if det_cache:
        # fail on an unwritable path BEFORE the inference loop, not after
        # hours of forward passes — probe with a throwaway temp file so a
        # crash mid-eval can't leave a zero-byte/stale file at det_cache
        # for tools/reeval.py to trip over
        if os.path.isdir(det_cache):
            raise IsADirectoryError(f"det_cache is a directory: {det_cache}")
        d = os.path.dirname(det_cache)
        if d:
            os.makedirs(d, exist_ok=True)
        probe = f"{det_cache}.probe.{os.getpid()}"
        open(probe, "wb").close()
        os.remove(probe)

    # duck-typed predictors (test stubs) may lack the hook/plan attributes
    batch_put = getattr(predictor, "batch_put", None)
    if batch_put is not None and getattr(test_loader, "put", False) is None:
        test_loader.put = batch_put  # prefetch-thread transfer
    plan = getattr(predictor, "plan", None)
    n_chips = plan.n_data if plan is not None else 1

    all_boxes: List[List] = [[None for _ in range(num_images)]
                             for _ in range(num_classes)]
    all_masks: Optional[List[List]] = (
        [[None for _ in range(num_images)] for _ in range(num_classes)]
        if with_masks else None)
    tel = telemetry.get()
    progress = _Progress(num_images, n_chips)
    stats = {}
    if inflight and int(inflight) > 0:
        from mx_rcnn_tpu.eval.pipeline import run_pipelined
        stats = run_pipelined(
            predictor, test_loader, all_boxes=all_boxes,
            all_masks=all_masks, imdb=imdb, num_classes=num_classes,
            max_per_image=max_per_image, thresh=thresh,
            nms_thresh=cfg.TEST.NMS, vis=vis, with_masks=with_masks,
            device_postprocess=device_postprocess, inflight=int(inflight),
            host_workers=int(host_workers), progress=progress)
        done = stats["images"]
        loader_wait = stats["loader_wait_s"]
        mode = stats["mode"]
    else:
        mode = "serial+devpost" if device_postprocess else "serial"
        done = 0
        loader_wait = 0.0
        it = iter(test_loader)
        while True:
            t_wait = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                break
            dt_wait = time.perf_counter() - t_wait
            loader_wait += dt_wait
            tel.add("eval/loader_wait", dt_wait)
            if device_postprocess:
                dets = _im_detect_device(predictor, batch, max_per_image,
                                         thresh, num_classes)
            else:
                dets = im_detect(predictor, batch)
            # the pyramid predict() just cached belongs to THIS batch; the
            # token pins the mask pass to it (stale-cache guard)
            tok = getattr(predictor, "feats_token", None)
            indices = batch["indices"]
            t_nms = time.perf_counter()
            for b, row in enumerate(dets):
                i = int(indices[b])
                if device_postprocess:
                    dets_pc = row  # already per-class from the device
                else:
                    scores, boxes, valid = row
                    # shared post-process path (ops/postprocess.py) — the
                    # serve engine runs the identical block, pinned by a
                    # parity test
                    dets_pc = per_class_nms(scores, boxes, valid,
                                            num_classes, thresh,
                                            cfg.TEST.NMS, max_per_image)
                for k in range(1, num_classes):
                    all_boxes[k][i] = dets_pc[k]
                if vis:
                    save_vis(test_loader.roidb[i], all_boxes, num_classes,
                             imdb.classes, i)
                done += 1
            tel.add("eval/nms", time.perf_counter() - t_nms, n=len(dets))
            if with_masks:
                with tel.span("eval/mask_pass"):
                    _mask_pass(predictor, batch, dets, all_boxes, all_masks,
                               test_loader.roidb, max_per_image, num_classes,
                               token=tok)
            progress.update(done, tel)
    wall = time.perf_counter() - progress.t0
    rate = done / max(wall, 1e-9)
    tel.gauge("eval/imgs_per_sec", rate)
    tel.counter("eval/images", done)
    host_post = stats.get("host_post_s", 0.0)
    post_wait = stats.get("post_wait_s", 0.0)
    overlap = (max(0.0, 1.0 - post_wait / host_post)
               if host_post > 0 else 0.0)
    tel.meta("eval_pipeline", mode=mode, images=done,
             imgs_per_sec=round(rate, 3), wall_s=round(wall, 3),
             loader_wait_s=round(loader_wait, 3),
             readback_wait_s=round(stats.get("readback_wait_s", 0.0), 3),
             host_post_s=round(host_post, 3),
             post_wait_s=round(post_wait, 3),
             overlap_frac=round(overlap, 4),
             inflight=int(inflight), host_workers=int(host_workers),
             device_postprocess=bool(device_postprocess))
    logger.info("pred_eval[%s]: %d images  Wall=%.1fs  LoaderWait=%.1fs  "
                "%.1f imgs/s (%.1f/chip)", mode, done, wall, loader_wait,
                rate, rate / n_chips)
    if det_cache:
        # write-then-rename so det_cache is only ever complete or absent;
        # pid-suffixed tmp so concurrent evals can't interleave, unlinked
        # on failure so a full disk doesn't strand a partial file
        tmp = f"{det_cache}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                pickle.dump(all_boxes, f, pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, det_cache)
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
        logger.info("cached detections to %s", det_cache)
    if with_masks:
        return imdb.evaluate_sds(all_boxes, all_masks)
    return imdb.evaluate_detections(all_boxes)


def draw_detections(img, labeled_dets) -> None:
    """Draw (label, (5,) det) pairs onto a BGR image in place — the one
    drawing routine shared by demo.py and vis_all_detection."""
    import cv2

    for name, d in labeled_dets:
        x1, y1, x2, y2 = (int(round(c)) for c in d[:4])
        cv2.rectangle(img, (x1, y1), (x2, y2), (0, 220, 0), 2)
        cv2.putText(img, f"{name} {d[4]:.2f}", (x1, max(y1 - 4, 10)),
                    cv2.FONT_HERSHEY_SIMPLEX, 0.5, (0, 220, 0), 1)


def vis_all_detection(rec: dict, dets_per_class, class_names,
                      out_path: str, thresh: float = 0.3) -> None:
    """Draw one image's post-NMS detections (reference
    ``vis_all_detection``, matplotlib → cv2 here) and write to disk."""
    import cv2

    if "image_array" in rec:
        img = rec["image_array"][:, :, ::-1].copy()
    else:
        img = cv2.imread(rec["image"], cv2.IMREAD_COLOR)
    labeled = [(class_names[k], d)
               for k, dets in enumerate(dets_per_class)
               if k and dets is not None
               for d in dets if d[4] >= thresh]
    draw_detections(img, labeled)
    cv2.imwrite(out_path, img)


def _round_up(x: int, mult: int) -> int:
    return -(-x // mult) * mult


def _mask_pass(predictor, batch, dets, all_boxes, all_masks, roidb,
               max_per_image, num_classes, token=None, feats=None):
    """Run the mask branch for one batch's FINAL detections and fill
    ``all_masks`` with full-image RLEs aligned row-for-row with
    ``all_boxes``.

    Three strategies (``cfg.TEST.MASK_PASTE``; measured trade-offs in the
    config docstring): ``"native"`` (default) ships only the (R, 28, 28)
    probabilities and runs the fused C++ paste+RLE on host; ``"device"``
    pastes on the MXU (ops/mask_paste.py) and ships packed bitplanes — one
    readback per drain pass, C++ RLE; ``"host"`` is the reference's
    per-detection cv2 paste (~150 ms/img at the 100-det cap) — the oracle
    the other two are tested against, and the automatic fallback when the
    native library or a duck-typed predictor lacks the fast entry points."""
    from mx_rcnn_tpu.eval.mask_rle import encode
    from mx_rcnn_tpu.native import paste_rle, rle_encode_packed

    if not dets:
        return
    # the feats kwarg is only forwarded when a captured pyramid was
    # actually handed over: duck-typed test predictors predate it
    mask_kw = {"token": token}
    if feats is not None:
        mask_kw["feats"] = feats
    im_info = np.asarray(batch["im_info"])
    indices = batch["indices"]
    B = batch["images"].shape[0]  # full (padded) batch; dets covers valid rows
    # static chunk size for the jitted mask forward; uncapped eval
    # (max_per_image == 0) and score-tie overflows are handled by chunking
    R = max_per_image if max_per_image > 0 else 100
    mode = getattr(predictor.cfg.TEST, "MASK_PASTE", "native")
    if mode not in ("native", "device", "host"):
        raise ValueError(f"TEST.MASK_PASTE must be native|device|host, "
                         f"got {mode!r}")
    if mode == "device" and not hasattr(predictor, "predict_masks_packed"):
        logger.warning("MASK_PASTE='device' but the predictor has no "
                       "predict_masks_packed; using 'native'")
        mode = "native"
    use_device = mode == "device"
    if use_device:
        # padded ORIGINAL frame covering every image in the batch; 128-
        # multiples bound the jit-shape count (and satisfy the encoder's
        # 64-bit column stride)
        hp = _round_up(max(roidb[int(indices[b])]["height"]
                           for b in range(len(dets))), 128)
        wp = _round_up(max(roidb[int(indices[b])]["width"]
                           for b in range(len(dets))), 128)

    # per-image queues of every final detection row (no silent drops; ties
    # and uncapped eval can exceed R — drained in extra passes)
    queues = [[] for _ in range(B)]  # entries: (k, i, det_row)
    for b in range(len(dets)):
        i = int(indices[b])
        for k in range(1, num_classes):
            for di in range(len(all_boxes[k][i])):
                queues[b].append((k, i, di))
    while any(queues):
        mboxes = np.zeros((B, R, 4), np.float32)   # scaled frame (RoIAlign)
        morig = np.zeros((B, R, 4), np.float32)    # original frame (paste)
        mlabels = np.zeros((B, R), np.int32)
        taken = [[] for _ in range(B)]
        for b in range(B):
            taken[b] = queues[b][:R]
            queues[b] = queues[b][R:]
            for r, (k, i, di) in enumerate(taken[b]):
                morig[b, r] = all_boxes[k][i][di][:4]
                mboxes[b, r] = morig[b, r] * im_info[b, 2]
                mlabels[b, r] = k
        if use_device:
            packed = np.asarray(jax.device_get(predictor.predict_masks_packed(
                mboxes, mlabels, morig, hp, wp, **mask_kw)))

            def rle_for(b, r, box, h, w):
                return {"size": [h, w],
                        "counts": rle_encode_packed(packed[b, r], h, w)}
        else:
            probs = np.asarray(jax.device_get(
                predictor.predict_masks_cached(mboxes, mlabels, **mask_kw)),
                np.float32)

            def rle_for(b, r, box, h, w):
                counts = (paste_rle(probs[b, r], box, h, w)
                          if mode == "native" else None)
                if counts is not None:
                    return {"size": [h, w], "counts": counts}
                return encode(  # "host" mode, or native lib unavailable
                    paste_mask(probs[b, r], box, h, w))

        for b in range(B):
            for r, (k, i, di) in enumerate(taken[b]):
                if all_masks[k][i] is None:
                    all_masks[k][i] = [None] * len(all_boxes[k][i])
                h, w = roidb[i]["height"], roidb[i]["width"]
                all_masks[k][i][di] = rle_for(b, r, all_boxes[k][i][di][:4],
                                              h, w)


def generate_proposals(predictor: Predictor, test_loader: TestLoader,
                       imdb, roidb: list,
                       cache_path: Optional[str] = None) -> list:
    """RPN-only pass dumping per-image proposals in ORIGINAL coordinates
    into the roidb (reference ``generate_proposals`` → .pkl for
    train_alternate steps 2/5)."""
    for batch in test_loader:
        rois, scores, valid = jax.device_get(
            predictor.predict_rpn(batch["images"], batch["im_info"]))
        im_info = np.asarray(batch["im_info"])
        indices = batch["indices"]
        n = int(np.sum(batch["batch_valid"]))
        for b in range(n):
            i = int(indices[b])
            v = np.asarray(valid[b], bool)
            props = np.asarray(rois[b])[v] / im_info[b, 2]
            order = np.argsort(-np.asarray(scores[b])[v])
            roidb[i]["proposals"] = props[order].astype(np.float32)
    if cache_path:
        with open(cache_path, "wb") as f:
            pickle.dump([r.get("proposals") for r in roidb], f,
                        pickle.HIGHEST_PROTOCOL)
        logger.info("wrote proposals to %s", cache_path)
    return roidb
