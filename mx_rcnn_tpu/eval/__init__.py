"""Evaluation layer — the reference's ``rcnn/core/tester.py`` +
``rcnn/dataset/*_eval`` tier: device-batched inference, host post-process
(per-class NMS, caps), and the VOC/COCO scoring math re-implemented in-repo
(no pycocotools dependency; SURVEY §7 preamble).
"""

from mx_rcnn_tpu.eval.voc_eval import voc_eval, voc_ap
from mx_rcnn_tpu.eval.tester import Predictor, im_detect, pred_eval, generate_proposals
