"""Repo-wide logger (reference: ``rcnn/logger.py`` — module-level logging setup)."""

import logging

logging.basicConfig(
    format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    level=logging.INFO,
)
logger = logging.getLogger("mx_rcnn_tpu")
logger.setLevel(logging.INFO)

# orbax/absl emit per-checkpoint INFO spam; keep driver output readable
logging.getLogger("absl").setLevel(logging.WARNING)
