"""Repo-wide logger (reference: ``rcnn/logger.py`` — module-level logging
setup, made idempotent and rank-aware).

The reference calls ``logging.basicConfig`` unconditionally at import,
which silently does nothing when the embedding application configured
logging first, and stacks duplicate handlers under repeated re-imports in
some harnesses.  Here ``setup_logging`` owns exactly one stream handler:
it is installed only if the root logger has none (an application's own
configuration is never stomped), and repeated calls just refresh the
formatter — so calling it again with ``rank=jax.process_index()`` after a
multi-host rendezvous (``parallel.distributed.init_distributed`` does
this) prefixes every record with ``rank{N}``, making interleaved
multi-host logs attributable to their process.
"""

import logging
from typing import Optional

_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"
_handler: Optional[logging.StreamHandler] = None

logger = logging.getLogger("mx_rcnn_tpu")


def setup_logging(rank: Optional[int] = None) -> None:
    """Idempotent handler/formatter setup; ``rank`` adds a ``rank{N}``
    record prefix (multi-host attribution).  Safe to call any number of
    times from any driver."""
    global _handler
    root = logging.getLogger()
    if _handler is None and not root.handlers:
        _handler = logging.StreamHandler()
        root.addHandler(_handler)
    if root.level > logging.INFO or root.level == logging.NOTSET:
        root.setLevel(logging.INFO)
    if _handler is not None:
        fmt = _FORMAT if rank is None else f"rank{rank} {_FORMAT}"
        _handler.setFormatter(logging.Formatter(fmt))
    logger.setLevel(logging.INFO)
    # orbax/absl emit per-checkpoint INFO spam; keep driver output readable
    logging.getLogger("absl").setLevel(logging.WARNING)


setup_logging()
