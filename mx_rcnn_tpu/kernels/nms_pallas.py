"""Pallas TPU NMS — the reference's CUDA bitmask kernel
(``rcnn/cython/nms_kernel.cu``), re-tiled for the TPU memory system.

The CUDA kernel computes a 64-bit suppression bitmask per (box, block) pair
on device and does the greedy sweep on host.  Here both phases stay on
device:

* **Phase A** (``_suppress_kernel``): grid over (row, col) tiles; each tile
  computes the IoU of a (BR, BC) box block pair on the VPU and writes
  ``iou > thresh`` as an int8 suppression matrix tile to HBM.  O(N²) pairs,
  fully parallel, bandwidth-bound (N² bytes ≈ 150 MB at N=12k ≈ ~0.2 ms of
  HBM traffic).
* **Phase B** (``_sweep_kernel``): the greedy sweep.  Sequential by nature,
  but each step is tiny: grid over row blocks (Pallas auto-double-buffers
  the HBM→VMEM tile stream); scratch holds the ``removed`` vector across
  grid steps (TPU grids are sequential); per row: scalar alive-check +
  predicated vector OR.

Boxes must arrive score-sorted (the ``propose`` contract — jax.lax.top_k
upstream).  Same greedy tie/threshold semantics as ``ops.nms.nms_padded``
(suppress when IoU > thresh, legacy +1 areas), which remains the oracle in
tests (tests/test_nms_pallas.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BR = 256    # row tile (int8 sublane multiple)
_BC = 2048   # col tile (lane multiple)


def _suppress_kernel(thresh_ref, rbox_ref, cx1_ref, cy1_ref, cx2_ref,
                     cy2_ref, out_ref):
    rb = rbox_ref[:]                     # (BR, 4) f32
    rx1, ry1 = rb[:, 0:1], rb[:, 1:2]    # (BR, 1)
    rx2, ry2 = rb[:, 2:3], rb[:, 3:4]
    cx1, cy1 = cx1_ref[:], cy1_ref[:]    # (1, BC)
    cx2, cy2 = cx2_ref[:], cy2_ref[:]

    iw = jnp.minimum(rx2, cx2) - jnp.maximum(rx1, cx1) + 1.0
    ih = jnp.minimum(ry2, cy2) - jnp.maximum(ry1, cy1) + 1.0
    iw = jnp.maximum(iw, 0.0)
    ih = jnp.maximum(ih, 0.0)
    inter = iw * ih
    ra = (rx2 - rx1 + 1.0) * (ry2 - ry1 + 1.0)
    ca = (cx2 - cx1 + 1.0) * (cy2 - cy1 + 1.0)
    union = jnp.maximum(ra + ca - inter, 1e-14)
    out_ref[:] = (inter / union > thresh_ref[0]).astype(jnp.int8)


def _sweep_kernel(max_out_ref, sup_ref, valid_ref, keep_ref, removed_ref,
                  kept_ref):
    """Greedy sweep.  Mosaic forbids dynamic lane-indexed scalar access, so
    per-row state reads/writes are lane-vectorized: select-by-iota + full
    reduce (a few vregs of VMEM traffic per row — VMEM-bandwidth cheap).

    Early termination: selection order is score order (sorted input), so
    once ``max_out`` boxes are kept the remaining rows cannot appear in the
    output — their work is predicated off (kept count in SMEM scratch).
    """
    pid = pl.program_id(0)
    n_pad = sup_ref.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, n_pad), 1)
    sub_iota = jax.lax.broadcasted_iota(jnp.int32, (8, n_pad), 0)

    @pl.when(pid == 0)
    def _():
        removed_ref[:] = jnp.zeros_like(removed_ref)
        keep_ref[:] = jnp.zeros_like(keep_ref)
        kept_ref[0] = 0

    def body(i0, _):
        # dynamic sublane access must be 8-aligned: load 8 rows, then
        # select each row by sublane-onehot reduction
        base = pl.multiple_of(i0 * 8, 8)

        @pl.when(kept_ref[0] < max_out_ref[0])
        def _():
            rows8 = sup_ref[pl.ds(base, 8), :].astype(jnp.int32)  # (8, N_pad)

            def inner(j, _):
                g = pid * _BR + i0 * 8 + j
                onehot = iota == g
                rm = jnp.sum(jnp.where(onehot, removed_ref[:], 0))
                vd = jnp.sum(jnp.where(onehot, valid_ref[:], 0))
                alive = (rm == 0) & (vd != 0) & \
                        (kept_ref[0] < max_out_ref[0])
                keep_ref[:] = jnp.where(onehot & alive, 1, keep_ref[:])
                row = jnp.sum(jnp.where(sub_iota == j, rows8, 0), axis=0,
                              keepdims=True)                   # (1, N_pad)
                removed_ref[:] = jnp.where(alive, removed_ref[:] | row,
                                           removed_ref[:])
                kept_ref[0] = kept_ref[0] + alive.astype(jnp.int32)
                return 0

            jax.lax.fori_loop(0, 8, inner, 0)

        return 0

    jax.lax.fori_loop(0, _BR // 8, body, 0)


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


@partial(jax.jit, static_argnames=("max_out", "iou_thresh"))
def nms_pallas(boxes: jnp.ndarray, scores: jnp.ndarray, max_out: int,
               iou_thresh: float, valid: jnp.ndarray | None = None):
    """Drop-in replacement for ``ops.nms.nms_padded`` (same signature and
    return contract: (keep_idx (max_out,) i32, keep_mask (max_out,) bool),
    selection order score-descending given score-sorted input).

    On non-TPU backends (the CPU test mesh) this delegates to the pure-JAX
    oracle — Mosaic kernels only lower on TPU; kernel-vs-oracle equivalence
    runs on the real chip (scripts/check_pallas.py, and bench exercises it
    every round via CXX_PROPOSAL).
    """
    if jax.default_backend() != "tpu":
        from mx_rcnn_tpu.ops.nms import nms_padded

        return nms_padded(boxes, scores, max_out=max_out,
                          iou_thresh=iou_thresh, valid=valid)
    n = boxes.shape[0]
    n_pad = _pad_to(n, _BC)   # lane-aligned and divisible by _BR

    boxes_p = jnp.zeros((n_pad, 4), jnp.float32).at[:n].set(
        boxes.astype(jnp.float32))
    if valid is None:
        valid_p = (jnp.arange(n_pad) < n)
    else:
        valid_p = jnp.zeros((n_pad,), bool).at[:n].set(valid)

    cols = boxes_p.T.reshape(4, 1, n_pad)  # x1,y1,x2,y2 as (1, N) rows
    thresh = jnp.asarray([iou_thresh], jnp.float32)

    sup = pl.pallas_call(
        _suppress_kernel,
        grid=(n_pad // _BR, n_pad // _BC),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_BR, 4), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BC), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BC), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BC), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BC), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_BR, _BC), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, n_pad), jnp.int8),
    )(thresh, boxes_p, cols[0], cols[1], cols[2], cols[3])

    keep = pl.pallas_call(
        _sweep_kernel,
        grid=(n_pad // _BR,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_BR, n_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        scratch_shapes=[pltpu.VMEM((1, n_pad), jnp.int32),
                        pltpu.SMEM((1,), jnp.int32)],
    )(jnp.asarray([max_out], jnp.int32), sup,
      valid_p.astype(jnp.int32).reshape(1, n_pad))

    keep_mask_full = keep[0, :n] > 0
    # kept boxes in index order == score order; compact to max_out slots
    # (pad when n < max_out so the output shape contract always holds)
    order = jnp.argsort(jnp.where(keep_mask_full, 0, 1), stable=True)
    if n < max_out:
        pad = max_out - n
        keep_idx = jnp.concatenate(
            [order, jnp.zeros((pad,), order.dtype)]).astype(jnp.int32)
        keep_mask = jnp.concatenate(
            [keep_mask_full[order], jnp.zeros((pad,), bool)])
    else:
        keep_idx = order[:max_out].astype(jnp.int32)
        keep_mask = keep_mask_full[keep_idx]
    keep_idx = jnp.where(keep_mask, keep_idx, 0)
    return keep_idx, keep_mask
