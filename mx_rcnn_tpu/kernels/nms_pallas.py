"""Pallas TPU NMS — the reference's CUDA bitmask kernel
(``rcnn/cython/nms_kernel.cu``), re-tiled for the TPU memory system.

The CUDA kernel computes a 64-bit suppression bitmask per (box, block) pair
on device and does the greedy sweep on host.  Here both phases stay on
device, and — like the CUDA original — the suppression matrix is BIT-PACKED
(32 consecutive columns per int32 word; signed because Mosaic lacks
unsigned reduces — bit ops are two's-complement safe and extraction masks
after the shift):

* **Phase A** (``_suppress_kernel``): 2D grid over (row tile, col-word
  tile); each step computes the IoU of its (BR) rows against its column
  words and packs ``iou > thresh`` into (BR, CW) words.  The kernel
  iterates 32 unrolled "bit lanes": pass j compares the rows against the
  column set {32w + j : w}, whose boxes are pre-gathered OUTSIDE the
  kernel into row j of a (32, N/32) array — so in-kernel access is a
  contiguous slice, never strided.  Tiles strictly below the diagonal are
  skipped entirely (the sweep only ever reads a row's bits at its own
  block's word and above, and the word-aligned row tiling keeps skipped
  garbage out of every later read).  The packed write is ≤ N²/8 bytes
  (18 MB at N=12k vs 147 MB unpacked), and ~⅓ of the IoU work is skipped
  at this tile shape.
* **Phase B** (``_sweep_kernel``): the greedy sweep, ``_BS``=8 rows per
  step.  Sequential by nature, and the expensive part of earlier versions
  was vector→scalar latency (~16 cross-lane reductions per block).  The
  packed layout kills that: a block's 8 columns are 8-aligned bits of ONE
  word, so suppressed-by-earlier/valid state is read with ONE masked
  reduce each; the 8×8 intra-block dependency table arrives bit-packed in
  SMEM (two words per block, scalar-indexed), so the serial greedy
  resolution runs entirely in scalar registers; ``keep`` is written once
  per block and ``removed`` is updated with one masked OR over the
  (_BS, N/32) row words.  Early termination: selection order is score
  order (sorted input), so once ``max_out`` boxes are kept the remaining
  blocks are predicated off (kept count in SMEM scratch).

Boxes must arrive score-sorted.  Two callers honor that contract: RPN
``propose`` (jax.lax.top_k upstream) and the fused eval post-process
(``ops.nms.nms_ranked`` argsorts per class before delegating here — the
``--device-postprocess`` readback-shrink path, where per-class NMS runs
inside the ``predict_post`` program instead of on the host).  Same greedy
tie/threshold semantics as ``ops.nms.nms_padded`` (suppress when IoU >
thresh, legacy +1 areas), which remains the oracle in tests
(tests/test_nms.py) and on-chip (scripts/check_pallas.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BR = 256    # row tile (sublane multiple)
_BS = 8      # sweep block: rows resolved per step (8-aligned, divides 32)
_PL = 32     # bits per packed word
# n_pad must satisfy: n_pad % _BR == 0 and (n_pad // _PL) % 128 == 0
_PAD = 4096


def _suppress_kernel(thresh_ref, rbox_ref, cx1_ref, cy1_ref, cx2_ref,
                     cy2_ref, out_ref):
    # 2D grid (row tile, col-word tile).  Tiles strictly below the diagonal
    # are skipped: the sweep reads sup[g, col] only for col ≥ the block's
    # own columns, and stale VMEM in a skipped tile's output only lands in
    # words no later block ever reads (row tiles are word-aligned, so a
    # row's garbage words all lie strictly below every later block's word).
    r = pl.program_id(0)
    c = pl.program_id(1)
    cw = out_ref.shape[1]                # col-word tile width

    @pl.when((c + 1) * cw * _PL > r * _BR)
    def _():
        rb = rbox_ref[:]                     # (BR, 4) f32
        rx1, ry1 = rb[:, 0:1], rb[:, 1:2]    # (BR, 1)
        rx2, ry2 = rb[:, 2:3], rb[:, 3:4]
        ra = (rx2 - rx1 + 1.0) * (ry2 - ry1 + 1.0)
        t = thresh_ref[0]

        acc = jnp.zeros(out_ref.shape, jnp.int32)
        for j in range(_PL):             # unrolled bit-lane loop
            cx1 = cx1_ref[j:j + 1, :]    # (1, CW) — contiguous slice; row j
            cy1 = cy1_ref[j:j + 1, :]    # holds the boxes of columns 32w+j
            cx2 = cx2_ref[j:j + 1, :]
            cy2 = cy2_ref[j:j + 1, :]
            iw = jnp.maximum(
                jnp.minimum(rx2, cx2) - jnp.maximum(rx1, cx1) + 1.0, 0.0)
            ih = jnp.maximum(
                jnp.minimum(ry2, cy2) - jnp.maximum(ry1, cy1) + 1.0, 0.0)
            inter = iw * ih
            ca = (cx2 - cx1 + 1.0) * (cy2 - cy1 + 1.0)
            union = jnp.maximum(ra + ca - inter, 1e-14)
            bits = (inter / union > t).astype(jnp.int32)
            acc = acc | (bits << j)
        out_ref[:] = acc


def _sweep_kernel(max_out_ref, diagp_ref, sup_ref, valid_ref, keep_ref,
                  removed_ref, kept_ref):
    pid = pl.program_id(0)
    w32 = sup_ref.shape[1]
    iota_w = jax.lax.broadcasted_iota(jnp.int32, (1, w32), 1)
    rowid = jax.lax.broadcasted_iota(jnp.int32, (_BS, 1), 0)

    @pl.when(pid == 0)
    def _():
        removed_ref[:] = jnp.zeros_like(removed_ref)
        keep_ref[:] = jnp.zeros_like(keep_ref)
        kept_ref[0] = 0

    def body(i0, _):
        # dynamic sublane access must be 8-aligned: _BS-row slice at _BS·i0
        base = pl.multiple_of(i0 * _BS, _BS)

        @pl.when(kept_ref[0] < max_out_ref[0])
        def _():
            rows8 = sup_ref[pl.ds(base, _BS), :]                  # (_BS, W32)
            g0 = pid * _BR + base
            w0 = g0 // _PL                 # the block's word lane
            j0 = g0 % _PL                  # its first bit (8-aligned)
            blk = g0 // _BS
            wordsel = iota_w == w0                                # (1, W32)
            # ONE vector->scalar reduce each: the word holding all 8
            # column bits of this block
            rm_w = jnp.sum(jnp.where(wordsel, removed_ref[:], 0))
            vd_w = jnp.sum(jnp.where(wordsel, valid_ref[:], 0))
            # 8x8 intra-block table, bit-packed two words per block in
            # SMEM: word k, byte j' (j = 4k + j'), bit i = "accepting row
            # i suppresses row j".  Scalar-indexed loads.
            d_lo = diagp_ref[2 * blk]
            d_hi = diagp_ref[2 * blk + 1]

            # serial greedy resolution, entirely in scalar registers
            acc_bits = 0
            cnt = kept_ref[0]
            for j in range(_BS):                                  # unrolled
                dw = d_hi if j >= 4 else d_lo
                colbits = (dw >> (8 * (j % 4))) & 0xFF
                a_j = (((rm_w >> (j0 + j)) & 1) == 0) & \
                      (((vd_w >> (j0 + j)) & 1) != 0) & \
                      ((colbits & acc_bits) == 0) & \
                      (cnt < max_out_ref[0])
                aji = a_j.astype(jnp.int32)
                acc_bits = acc_bits | (aji << j)
                cnt = cnt + aji

            keep_ref[:] = keep_ref[:] | jnp.where(
                wordsel, acc_bits << j0, 0)
            accv = (jnp.full((_BS, 1), acc_bits, jnp.int32) >> rowid) & 1
            masked = jnp.where(accv != 0, rows8, 0)               # (_BS, W32)
            orred = masked[0:1]
            for j in range(1, _BS):                # OR-reduce (not max: these
                orred = orred | masked[j:j + 1]    # are packed words)
            removed_ref[:] = removed_ref[:] | orred
            kept_ref[0] = cnt

        return 0

    jax.lax.fori_loop(0, _BR // _BS, body, 0)


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


@partial(jax.jit, static_argnames=("max_out", "iou_thresh"))
def nms_pallas(boxes: jnp.ndarray, scores: jnp.ndarray, max_out: int,
               iou_thresh: float, valid: jnp.ndarray | None = None):
    """Drop-in replacement for ``ops.nms.nms_padded`` (same signature and
    return contract: (keep_idx (max_out,) i32, keep_mask (max_out,) bool),
    selection order score-descending given score-sorted input).

    vmap-safe: batched callers (the detector vmaps ``propose`` over images)
    hit a ``custom_vmap`` rule that lowers to ``lax.map`` over single-image
    kernel calls — Mosaic cannot lower auto-batched SMEM block specs (a
    squeezed leading dim violates the (8, 128) block-shape rule), and the
    sweep is sequential per image anyway.

    On non-TPU backends (the CPU test mesh) this delegates to the pure-JAX
    oracle — Mosaic kernels only lower on TPU; kernel-vs-oracle equivalence
    runs on the real chip (scripts/check_pallas.py, and bench exercises it
    every round via CXX_PROPOSAL).
    """
    if jax.default_backend() != "tpu":
        from mx_rcnn_tpu.ops.nms import nms_padded

        return nms_padded(boxes, scores, max_out=max_out,
                          iou_thresh=iou_thresh, valid=valid)
    n = boxes.shape[0]
    if valid is None:
        valid = jnp.ones((n,), bool)
    return _nms_vmappable(max_out, iou_thresh)(boxes, scores, valid)


def _nms_vmappable(max_out: int, iou_thresh: float):
    fn = _VMAP_CACHE.get((max_out, iou_thresh))
    if fn is not None:
        return fn

    @jax.custom_batching.custom_vmap
    def fn(boxes, scores, valid):
        return _nms_core(boxes, scores, valid, max_out, iou_thresh)

    @fn.def_vmap
    def _rule(axis_size, in_batched, boxes, scores, valid):
        boxes, scores, valid = (
            a if b else jnp.broadcast_to(a[None], (axis_size,) + a.shape)
            for a, b in zip((boxes, scores, valid), in_batched)
        )
        # The Mosaic kernels can't auto-batch (SMEM specs), so each batch
        # level becomes one serial lax.map.  The map body calls the
        # custom_vmap-wrapped fn — NOT _nms_core — so a nested vmap batches
        # the inner call, re-enters this rule, and gets its own lax.map
        # instead of pushing batching into the pallas_call (the lowering
        # failure this rule exists to avoid).  Glue (prep/post) inside vs
        # outside the scan measured perf-neutral at B=8: the scan's
        # residual cost is kernel sequencing, not glue.
        out = jax.lax.map(lambda t: fn(*t), (boxes, scores, valid))
        return out, (True, True)

    _VMAP_CACHE[(max_out, iou_thresh)] = fn
    return fn


_VMAP_CACHE: dict = {}


def _nms_core(boxes: jnp.ndarray, scores: jnp.ndarray, valid: jnp.ndarray,
              max_out: int, iou_thresh: float):
    del scores  # selection order is index order (callers pass sorted boxes)
    n = boxes.shape[0]
    keep_words = _nms_kernels(*_nms_prep(boxes, valid, iou_thresh),
                              max_out=max_out, iou_thresh=iou_thresh)
    return _nms_post(keep_words, n=n, max_out=max_out)


def _nms_prep(boxes: jnp.ndarray, valid: jnp.ndarray, iou_thresh: float):
    """Host-of-kernel data prep (pure jnp, vmappable): pad, regroup column
    boxes for the bit-lane loop, pack the 8×8 block-diagonal + validity."""
    n = boxes.shape[0]
    n_pad = _pad_to(n, _PAD)   # (n_pad/_PL) lane-aligned, divisible by _BR
    w32 = n_pad // _PL

    boxes_p = jnp.zeros((n_pad, 4), jnp.float32).at[:n].set(
        boxes.astype(jnp.float32))
    valid_p = jnp.zeros((n_pad,), bool).at[:n].set(valid)

    # column boxes regrouped so bit-lane j of the pack loop reads columns
    # {32w + j} as a contiguous row: (4, W32, 32) -> (4, 32, W32)
    cols = boxes_p.T.reshape(4, w32, _PL).transpose(0, 2, 1)

    # 8x8 block-diagonal, bit-packed 2 words per block for SMEM scalar
    # loads: word k of block r, byte j' (col j = 4k + j'), bit i =
    # sup[8r+i, 8r+j].  Recomputed via boxes.bbox_overlaps: consistency is
    # structural — every same-block pair is decided solely by this table
    # and every cross-block pair solely by sup, so a ULP divergence
    # between the lowerings cannot produce contradictory decisions.
    from mx_rcnn_tpu.ops.boxes import bbox_overlaps

    gb = boxes_p.reshape(-1, _BS, 4)                     # (N/8, 8, 4)
    iou_blk = jax.vmap(bbox_overlaps)(gb, gb)            # (N/8, 8, 8) [i, j]
    dbits = (iou_blk > iou_thresh).astype(jnp.int32)
    rowsh = jnp.arange(_BS, dtype=jnp.int32)[None, :, None]   # bit i
    colgrp = jnp.sum(dbits << rowsh, axis=1)             # (N/8, 8) per-col j
    bytesh = (jnp.arange(_BS, dtype=jnp.int32) % 4) * 8  # byte within word
    packed = colgrp << bytesh[None, :]                   # (N/8, 8)
    diagp = jnp.stack([
        packed[:, 0] | packed[:, 1] | packed[:, 2] | packed[:, 3],
        packed[:, 4] | packed[:, 5] | packed[:, 6] | packed[:, 7],
    ], axis=1).reshape(-1)                               # (N/8 * 2,)

    # classic packing for valid: word w bit j = valid[32w + j]
    valid_words = jnp.sum(
        valid_p.astype(jnp.int32).reshape(w32, _PL) <<
        jnp.arange(_PL, dtype=jnp.int32)[None, :], axis=1).reshape(1, w32)
    return boxes_p, cols, diagp, valid_words


def _nms_kernels(boxes_p, cols, diagp, valid_words, *, max_out: int,
                 iou_thresh: float):
    """The two Mosaic kernels (phase A + sweep) — the only part the batched
    rule must run per-image under lax.map."""
    n_pad = boxes_p.shape[0]
    w32 = n_pad // _PL
    thresh = jnp.asarray([iou_thresh], jnp.float32)

    cw = 128                       # col-word tile: 128 lanes = 4096 columns
    sup = pl.pallas_call(
        _suppress_kernel,
        grid=(n_pad // _BR, w32 // cw),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_BR, 4), lambda r, c: (r, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_PL, cw), lambda r, c: (0, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_PL, cw), lambda r, c: (0, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_PL, cw), lambda r, c: (0, c),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_PL, cw), lambda r, c: (0, c),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_BR, cw), lambda r, c: (r, c),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, w32), jnp.int32),
    )(thresh, boxes_p, cols[0], cols[1], cols[2], cols[3])

    keep_words = pl.pallas_call(
        _sweep_kernel,
        grid=(n_pad // _BR,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_BR, w32), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, w32), jnp.int32),
        scratch_shapes=[pltpu.VMEM((1, w32), jnp.int32),
                        pltpu.SMEM((1,), jnp.int32)],
    )(jnp.asarray([max_out], jnp.int32), diagp, sup, valid_words)
    return keep_words


def _nms_post(keep_words, *, n: int, max_out: int):
    """Unpack the kept-bit words and compact to max_out slots (pure jnp,
    vmappable)."""
    n_pad = keep_words.shape[1] * _PL
    # unpack: word w bit j = column 32w + j, C-order reshape restores it
    keep_bits = ((keep_words[0][:, None] >>
                  jnp.arange(_PL, dtype=jnp.int32)[None, :]) & 1)
    keep_mask_full = keep_bits.reshape(n_pad)[:n] > 0
    # kept boxes in index order == score order; compact to max_out slots
    # (pad when n < max_out so the output shape contract always holds)
    order = jnp.argsort(jnp.where(keep_mask_full, 0, 1), stable=True)
    if n < max_out:
        pad = max_out - n
        keep_idx = jnp.concatenate(
            [order, jnp.zeros((pad,), order.dtype)]).astype(jnp.int32)
        keep_mask = jnp.concatenate(
            [keep_mask_full[order], jnp.zeros((pad,), bool)])
    else:
        keep_idx = order[:max_out].astype(jnp.int32)
        keep_mask = keep_mask_full[keep_idx]
    keep_idx = jnp.where(keep_mask, keep_idx, 0)
    return keep_idx, keep_mask
