"""Blocked-bitmask NMS Pallas kernel (reference: rcnn/cython/nms_kernel.cu).

Status: fallback wrapper — delegates to the exact pure-JAX greedy NMS in
``ops.nms.nms_padded`` until the Pallas kernel lands.  The planned kernel
follows the CUDA bitmask algorithm re-tiled for the TPU VPU: boxes in
128-wide lanes, per-block pairwise IoU → suppression bitmask in VMEM,
sequential block scan in SMEM.  Callers must not depend on anything beyond
the shared signature.
"""

from mx_rcnn_tpu.ops.nms import nms_padded


def nms_pallas(boxes, scores, max_out, iou_thresh, valid=None):
    return nms_padded(boxes, scores, max_out=max_out, iou_thresh=iou_thresh, valid=valid)
