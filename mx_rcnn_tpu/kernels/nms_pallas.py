"""Pallas TPU NMS — the reference's CUDA bitmask kernel
(``rcnn/cython/nms_kernel.cu``), re-tiled for the TPU memory system.

The CUDA kernel computes a 64-bit suppression bitmask per (box, block) pair
on device and does the greedy sweep on host.  Here both phases stay on
device:

* **Phase A** (``_suppress_kernel``): grid over (row, col) tiles; each tile
  computes the IoU of a (BR, BC) box block pair on the VPU and writes
  ``iou > thresh`` as an int8 suppression matrix tile to HBM.  O(N²) pairs,
  fully parallel, bandwidth-bound (N² bytes ≈ 150 MB at N=12k ≈ ~0.2 ms of
  HBM traffic).
* **Phase B** (``_sweep_kernel``): the greedy sweep.  Sequential by nature,
  but resolved ``_BS`` rows at a time: grid over row blocks (Pallas
  auto-double-buffers the HBM→VMEM tile stream); scratch holds the
  ``removed`` vector across grid steps (TPU grids are sequential);
  intra-block dependencies come from a precomputed block-diagonal
  (see the kernel docstring).

Boxes must arrive score-sorted (the ``propose`` contract — jax.lax.top_k
upstream).  Same greedy tie/threshold semantics as ``ops.nms.nms_padded``
(suppress when IoU > thresh, legacy +1 areas), which remains the oracle in
tests (tests/test_nms_pallas.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_BR = 256    # row tile (int8 sublane multiple)
_BC = 2048   # col tile (lane multiple)
_BS = 8      # sweep block: rows resolved per step (8-aligned, divides _BR)


def _suppress_kernel(thresh_ref, rbox_ref, cx1_ref, cy1_ref, cx2_ref,
                     cy2_ref, out_ref):
    rb = rbox_ref[:]                     # (BR, 4) f32
    rx1, ry1 = rb[:, 0:1], rb[:, 1:2]    # (BR, 1)
    rx2, ry2 = rb[:, 2:3], rb[:, 3:4]
    cx1, cy1 = cx1_ref[:], cy1_ref[:]    # (1, BC)
    cx2, cy2 = cx2_ref[:], cy2_ref[:]

    iw = jnp.minimum(rx2, cx2) - jnp.maximum(rx1, cx1) + 1.0
    ih = jnp.minimum(ry2, cy2) - jnp.maximum(ry1, cy1) + 1.0
    iw = jnp.maximum(iw, 0.0)
    ih = jnp.maximum(ih, 0.0)
    inter = iw * ih
    ra = (rx2 - rx1 + 1.0) * (ry2 - ry1 + 1.0)
    ca = (cx2 - cx1 + 1.0) * (cy2 - cy1 + 1.0)
    union = jnp.maximum(ra + ca - inter, 1e-14)
    out_ref[:] = (inter / union > thresh_ref[0]).astype(jnp.int8)


def _sweep_kernel(max_out_ref, sup_ref, diag8_ref, valid_ref, keep_ref,
                  removed_ref, kept_ref):
    """Greedy sweep, ``_BS`` rows per step.  Mosaic forbids dynamic
    lane-indexed scalar access, so per-row state is extracted by iota-mask
    + reduce — the expensive part of a naive one-row-at-a-time sweep (~10
    full-width vector ops per row).  Here each step resolves a ``_BS``-row
    block:

    * the block's cross-row dependencies (does accepting row i suppress
      row j, i<j within the block) come from ``diag8`` — the _BS×_BS
      block-diagonal of the suppression matrix, precomputed outside the
      kernel in a sublane-friendly (N, _BS) layout so the block is one
      8-aligned sublane load instead of _BS full-width extractions;
    * suppression by earlier blocks is one masked reduce of ``removed``;
    * the serial intra-block resolution runs unrolled on (_BS, 1) vectors
      (one vreg each), then ``keep``/``removed`` update with two
      full-width ops for the whole block.

    ``_BS=8`` measured fastest on v5-lite (vs 16/32: the (_BS, N_pad)
    masked reduces grow with _BS faster than the per-row savings).

    Early termination: selection order is score order (sorted input), so
    once ``max_out`` boxes are kept the remaining rows cannot appear in the
    output — whole blocks are predicated off (kept count in SMEM scratch).
    """
    pid = pl.program_id(0)
    n_pad = sup_ref.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, n_pad), 1)
    rowid = jax.lax.broadcasted_iota(jnp.int32, (_BS, 1), 0)

    @pl.when(pid == 0)
    def _():
        removed_ref[:] = jnp.zeros_like(removed_ref)
        keep_ref[:] = jnp.zeros_like(keep_ref)
        kept_ref[0] = 0

    def body(i0, _):
        # dynamic sublane access must be 8-aligned: both loads below are
        # _BS-row slices at _BS·i0
        base = pl.multiple_of(i0 * _BS, _BS)

        @pl.when(kept_ref[0] < max_out_ref[0])
        def _():
            rows8 = sup_ref[pl.ds(base, _BS), :].astype(jnp.int32)
            d8 = diag8_ref[pl.ds(base, _BS), :]                   # (_BS, _BS)
            g0 = pid * _BR + base
            blockmask = iota == (g0 + rowid)                      # (_BS, N_pad)
            rm8 = jnp.sum(jnp.where(blockmask, removed_ref[:], 0),
                          axis=1, keepdims=True)                  # (_BS, 1)
            vd8 = jnp.sum(jnp.where(blockmask, valid_ref[:], 0),
                          axis=1, keepdims=True)
            pre = ((rm8 == 0) & (vd8 != 0)).astype(jnp.int32)     # (_BS, 1)

            acc = jnp.zeros((_BS, 1), jnp.int32)
            cnt = kept_ref[0]
            for j in range(_BS):                                  # unrolled
                sup_intra = jnp.sum(acc * d8[:, j:j + 1])
                pre_j = jnp.sum(jnp.where(rowid == j, pre, 0))
                a_j = ((pre_j != 0) & (sup_intra == 0) &
                       (cnt < max_out_ref[0])).astype(jnp.int32)
                acc = acc + jnp.where(rowid == j, a_j, 0)
                cnt = cnt + a_j

            accb = acc != 0                                       # (_BS, 1)
            keep_ref[:] = keep_ref[:] | jnp.max(
                jnp.where(blockmask & accb, 1, 0), axis=0, keepdims=True)
            removed_ref[:] = removed_ref[:] | jnp.max(
                jnp.where(accb, rows8, 0), axis=0, keepdims=True)
            kept_ref[0] = cnt

        return 0

    jax.lax.fori_loop(0, _BR // _BS, body, 0)


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


@partial(jax.jit, static_argnames=("max_out", "iou_thresh"))
def nms_pallas(boxes: jnp.ndarray, scores: jnp.ndarray, max_out: int,
               iou_thresh: float, valid: jnp.ndarray | None = None):
    """Drop-in replacement for ``ops.nms.nms_padded`` (same signature and
    return contract: (keep_idx (max_out,) i32, keep_mask (max_out,) bool),
    selection order score-descending given score-sorted input).

    On non-TPU backends (the CPU test mesh) this delegates to the pure-JAX
    oracle — Mosaic kernels only lower on TPU; kernel-vs-oracle equivalence
    runs on the real chip (scripts/check_pallas.py, and bench exercises it
    every round via CXX_PROPOSAL).
    """
    if jax.default_backend() != "tpu":
        from mx_rcnn_tpu.ops.nms import nms_padded

        return nms_padded(boxes, scores, max_out=max_out,
                          iou_thresh=iou_thresh, valid=valid)
    n = boxes.shape[0]
    n_pad = _pad_to(n, _BC)   # lane-aligned and divisible by _BR

    boxes_p = jnp.zeros((n_pad, 4), jnp.float32).at[:n].set(
        boxes.astype(jnp.float32))
    if valid is None:
        valid_p = (jnp.arange(n_pad) < n)
    else:
        valid_p = jnp.zeros((n_pad,), bool).at[:n].set(valid)

    cols = boxes_p.T.reshape(4, 1, n_pad)  # x1,y1,x2,y2 as (1, N) rows
    thresh = jnp.asarray([iou_thresh], jnp.float32)

    sup = pl.pallas_call(
        _suppress_kernel,
        grid=(n_pad // _BR, n_pad // _BC),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_BR, 4), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BC), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BC), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BC), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, _BC), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_BR, _BC), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_pad, n_pad), jnp.int8),
    )(thresh, boxes_p, cols[0], cols[1], cols[2], cols[3])

    # _BS×_BS block-diagonal of the suppression matrix in (N, _BS) layout:
    # diag8[g, j] = sup[g, _BS*(g//_BS) + j].  Recomputed via
    # boxes.bbox_overlaps rather than gathered from sup: a take_along_axis
    # over the (N, N) int8 sup measures ~2 ms slower on v5-lite (TPU
    # gathers serialize), while the O(N·_BS) IoU recompute fuses into the
    # surrounding graph.  Consistency is structural, not numeric: every
    # same-block pair is decided solely by diag8 and every cross-block
    # pair solely by sup, so a ULP divergence between the two lowerings
    # cannot produce contradictory suppression decisions.
    from mx_rcnn_tpu.ops.boxes import bbox_overlaps

    gb = boxes_p.reshape(-1, _BS, 4)                     # (N/_BS, _BS, 4)
    iou_blk = jax.vmap(bbox_overlaps)(gb, gb)            # (N/_BS, _BS, _BS)
    diag8 = (iou_blk > iou_thresh).astype(jnp.int32).reshape(n_pad, _BS)

    keep = pl.pallas_call(
        _sweep_kernel,
        grid=(n_pad // _BR,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((_BR, n_pad), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_BR, _BS), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        scratch_shapes=[pltpu.VMEM((1, n_pad), jnp.int32),
                        pltpu.SMEM((1,), jnp.int32)],
    )(jnp.asarray([max_out], jnp.int32), sup, diag8,
      valid_p.astype(jnp.int32).reshape(1, n_pad))

    keep_mask_full = keep[0, :n] > 0
    # kept boxes in index order == score order; compact to max_out slots
    # (pad when n < max_out so the output shape contract always holds)
    order = jnp.argsort(jnp.where(keep_mask_full, 0, 1), stable=True)
    if n < max_out:
        pad = max_out - n
        keep_idx = jnp.concatenate(
            [order, jnp.zeros((pad,), order.dtype)]).astype(jnp.int32)
        keep_mask = jnp.concatenate(
            [keep_mask_full[order], jnp.zeros((pad,), bool)])
    else:
        keep_idx = order[:max_out].astype(jnp.int32)
        keep_mask = keep_mask_full[keep_idx]
    keep_idx = jnp.where(keep_mask, keep_idx, 0)
    return keep_idx, keep_mask
