"""Pallas TPU kernels for the hot non-matmul ops.

Each kernel shares a signature with (and is tested against) its pure-JAX
fallback in ``mx_rcnn_tpu.ops``.  Until a kernel lands, the module exports
the fallback so every ``use_pallas=True`` call site stays functional.
"""

from mx_rcnn_tpu.kernels.nms_pallas import nms_pallas
