"""Pallas TPU fused assign-IoU reductions — the RPN anchor-assignment
analogue of the reference's ``bbox_overlaps_cython`` + numpy reductions
(``rcnn/io/rpn.py: assign_anchor``), re-designed for the TPU memory system.

``ops/assign_anchor.py`` needs four reductions of the (N, G) anchor×gt IoU
matrix: per-anchor max and argmax, per-gt max over inside anchors, and the
"anchor ties some gt's max" predicate.  The dense path materializes the
matrix once and reads it three times — at FPN's N=155 520 concatenated
anchors that is ~250 MB of HBM traffic per image and ~2.6 ms/step of the
profiled 21.8 ms (BASELINE.md FPN floor; round-3 confirmed XLA cannot fuse
it further — the traffic is real, not rematerialization).

This kernel never materializes the matrix: IoU is recomputed on the fly
from the (N, 4) anchors and the tiny (G, 4) gt set (the FLOPs are ~300
MFLOP — noise next to 250 MB of bandwidth), so HBM traffic drops to the
anchor reads + (N,) outputs (~2.5 MB, ~100× less).  Two sequential grid
phases share one VMEM scratch:

* **phase 0** sweeps anchor tiles accumulating the per-gt max over INSIDE
  anchors (``gt_max``) — it must finish before the tie predicate exists;
* **phase 1** re-sweeps computing per-anchor max/argmax (first-index tie
  semantics, matching ``jnp.argmax``) and the tie predicate
  ``any_j(iou[i,j] == gt_max[j] & valid[j] & gt_max[j] > 0)``.

Arithmetic is the exact expression tree of ``ops/boxes.bbox_overlaps``
(legacy +1 areas, eps-clamped union, f32).  Parity with the dense path is
ULP-level, not bitwise: compilers may contract mul+add chains into FMAs
differently per fusion context (measured on CPU: jitted vs eager versions
of the SAME expression differ in the last mantissa bit on ~20% of
entries).  The ``==`` tie predicate is computed INSIDE the kernel from
its own iou values, so it is exactly self-consistent; cross-path label
flips are confined to anchors whose IoU sits within ~1 ULP of a
threshold or per-gt tie (tests/test_assign_fused.py bounds this).

Non-TPU backends fall back to the dense path (Mosaic only lowers on TPU);
CI parity runs this kernel in Pallas interpret mode
(tests/test_assign_fused.py), and the on-chip gate is
scripts/check_pallas.py + tests/test_tpu_kernels.py.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_TILE_N = 2048   # anchors per grid step ((TILE_N, 128) f32 tile = 1 MB VMEM)
_G_PAD = 128     # gt padded to one lane width


def _assign_kernel(anchors_ref, gtt_ref, gtv_ref, inside_ref,
                   maxov_ref, argmax_ref, tie_ref, gtmax_ref, acc_ref):
    p = pl.program_id(0)          # 0: accumulate gt_max; 1: per-anchor outs
    i = pl.program_id(1)
    nt = pl.num_programs(1)

    ab = anchors_ref[:]                       # (TILE_N, 4) f32
    ax1, ay1 = ab[:, 0:1], ab[:, 1:2]         # (TILE_N, 1)
    ax2, ay2 = ab[:, 2:3], ab[:, 3:4]
    gx1 = gtt_ref[0:1, :]                     # (1, G) — gt transposed
    gy1 = gtt_ref[1:2, :]
    gx2 = gtt_ref[2:3, :]
    gy2 = gtt_ref[3:4, :]
    gv = gtv_ref[0:1, :]                      # (1, G) f32 1/0 validity

    # bbox_overlaps' exact expression tree (ops/boxes.py:96-105)
    iw = jnp.maximum(
        jnp.minimum(ax2, gx2) - jnp.maximum(ax1, gx1) + 1.0, 0.0)
    ih = jnp.maximum(
        jnp.minimum(ay2, gy2) - jnp.maximum(ay1, gy1) + 1.0, 0.0)
    inter = iw * ih                           # (TILE_N, G)
    area_a = (ax2 - ax1 + 1.0) * (ay2 - ay1 + 1.0)
    area_g = (gx2 - gx1 + 1.0) * (gy2 - gy1 + 1.0)
    union = jnp.maximum(area_a + area_g - inter, 1e-14)
    iou = jnp.where(gv > 0, inter / union, -1.0)   # invalid gt never wins

    @pl.when(p == 0)
    def _():                                  # accumulate per-gt max
        @pl.when(i == 0)
        def _():
            acc_ref[:] = jnp.full_like(acc_ref, -1.0)

        ins = inside_ref[:]                   # (TILE_N, 1) f32 1/0
        ov_in = jnp.where(ins > 0, iou, -1.0)
        acc_ref[:] = jnp.maximum(acc_ref[:], jnp.max(ov_in, axis=0,
                                                     keepdims=True))

    @pl.when(p == 1)
    def _():                                  # per-anchor outputs
        gt_max = acc_ref[:]                   # (1, G) — final after phase 0
        rowmax = jnp.max(iou, axis=1, keepdims=True)          # (TILE_N, 1)
        eq = iou == rowmax                                    # ties → min id
        colid = jax.lax.broadcasted_iota(jnp.int32, iou.shape, 1)
        argmax = jnp.min(jnp.where(eq, colid, _G_PAD), axis=1,
                         keepdims=True)
        ins = inside_ref[:]
        ov_in = jnp.where(ins > 0, iou, -1.0)
        tie = (ov_in == gt_max) & (gv > 0) & (gt_max > 0)
        maxov_ref[:] = rowmax
        argmax_ref[:] = argmax
        tie_ref[:] = jnp.max(tie.astype(jnp.int32), axis=1, keepdims=True)
        @pl.when(i == nt - 1)
        def _():
            gtmax_ref[:] = gt_max


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _assign_core(anchors, gt_boxes, gt_valid, inside, *, interpret=False):
    n = anchors.shape[0]
    g = gt_boxes.shape[0]
    assert g <= _G_PAD, f"MAX_GT {g} > kernel lane width {_G_PAD}"
    n_pad = _pad_to(n, _TILE_N)

    anchors_p = jnp.zeros((n_pad, 4), jnp.float32).at[:n].set(
        anchors.astype(jnp.float32))
    inside_p = jnp.zeros((n_pad, 1), jnp.float32).at[:n, 0].set(
        inside.astype(jnp.float32))
    gtt = jnp.zeros((4, _G_PAD), jnp.float32).at[:, :g].set(
        gt_boxes.astype(jnp.float32).T)
    gtv = jnp.zeros((1, _G_PAD), jnp.float32).at[0, :g].set(
        gt_valid.astype(jnp.float32))

    grid = (2, n_pad // _TILE_N)
    maxov, argmax, tie, gt_max = pl.pallas_call(
        _assign_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TILE_N, 4), lambda p, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE_N, 1), lambda p, i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((_TILE_N, 1), lambda p, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE_N, 1), lambda p, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TILE_N, 1), lambda p, i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, _G_PAD), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, _G_PAD), jnp.float32)],
        interpret=interpret,
    )(anchors_p, gtt, gtv, inside_p)

    return (maxov[:n, 0], argmax[:n, 0], gt_max[0, :g],
            tie[:n, 0].astype(bool))


_VMAP_CACHE: dict = {}


def _assign_vmappable(interpret: bool):
    """custom_vmap wrapper: Mosaic can't auto-batch the scratch/constant
    block specs, and per-image sweeps are sequential anyway — batch levels
    lower to lax.map over single-image kernel calls (the recursive-rule
    pattern from kernels/nms_pallas.py)."""
    fn = _VMAP_CACHE.get(interpret)
    if fn is not None:
        return fn

    @jax.custom_batching.custom_vmap
    def fn(anchors, gt_boxes, gt_valid, inside):
        return _assign_core(anchors, gt_boxes, gt_valid, inside,
                            interpret=interpret)

    @fn.def_vmap
    def _rule(axis_size, in_batched, anchors, gt_boxes, gt_valid, inside):
        anchors, gt_boxes, gt_valid, inside = (
            a if b else jnp.broadcast_to(a[None], (axis_size,) + a.shape)
            for a, b in zip((anchors, gt_boxes, gt_valid, inside), in_batched)
        )
        # map body calls fn (not _assign_core) so nested vmaps re-enter
        # this rule instead of pushing batching into pallas_call
        out = jax.lax.map(lambda t: fn(*t),
                          (anchors, gt_boxes, gt_valid, inside))
        return out, (True, True, True, True)

    _VMAP_CACHE[interpret] = fn
    return fn


@partial(jax.jit, static_argnames=("interpret",))
def assign_reduce_pallas(anchors, gt_boxes, gt_valid, inside,
                         interpret: bool = False):
    """Fused replacement for the dense IoU reductions in
    ``ops/assign_anchor.py``.

    Returns ``(max_overlap (N,) f32, argmax_gt (N,) i32, gt_max (G,) f32,
    is_gt_argmax (N,) bool)`` with the dense path's exact semantics:
    invalid gt columns masked to −1, per-anchor argmax breaking ties at the
    smallest gt index, ``gt_max`` over inside anchors only, and the tie
    predicate requiring a valid gt with positive max.
    """
    return _assign_vmappable(interpret)(anchors, gt_boxes, gt_valid, inside)
