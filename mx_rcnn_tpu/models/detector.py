"""Faster R-CNN detector assembly — the reference's train/test symbol graphs.

Maps the reference graphs (``rcnn/symbol/symbol_resnet.py:get_resnet_train``
/ ``get_resnet_test``, ``symbol_vgg.py`` equivalents) onto one flax module:

    backbone conv body → RPN head
      → propose (the ``Proposal`` op — jitted in-graph, stop_gradient)
      → sample_rois (the ``ProposalTarget`` CustomOp — jitted in-graph,
        on-device; kills the reference's per-step device→host→device sync,
        SURVEY §3.1 hot-loop stall)
      → roi_align (the CUDA ``ROIPooling`` — here a dense static-grid
        bilinear gather, Pallas kernel optional)
      → head body (VGG fc6/7 or ResNet stage5) → cls_score / bbox_pred
      → masked losses (losses.py)

Everything is batched per-image with ``jax.vmap`` — static shapes
throughout: post-NMS RoI count and sampled-RoI count are the reference's
own padding contract (2000 train / 300 test / 128 sampled).

Train-time RNG: one key per step, split per image, for anchor subsampling
and RoI sampling (reference used host numpy RNG — SURVEY §7 hard-part 3:
parity is statistical, not bitwise).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models import losses as L
from mx_rcnn_tpu.models.backbones import ResNetConv, ResNetStage5, VGGConv, VGGFC
from mx_rcnn_tpu.models.heads import RCNNOutput, RPNHead
from mx_rcnn_tpu.ops import all_anchors, generate_anchors, assign_anchor, propose, sample_rois
from mx_rcnn_tpu.ops.roi_align import roi_align


class FasterRCNN(nn.Module):
    """Single-level (non-FPN) Faster R-CNN: resnet50/101 or vgg16."""

    cfg: Config

    def setup(self):
        net = self.cfg.network
        dtype = jnp.bfloat16 if self.cfg.tpu.COMPUTE_DTYPE == "bfloat16" else jnp.float32
        self._dtype = dtype
        if net.NETWORK.startswith("resnet"):
            self.backbone = ResNetConv(depth=net.NETWORK, dtype=dtype,
                                       remat=self.cfg.tpu.REMAT_BACKBONE)
            self.head_body = ResNetStage5(depth=net.NETWORK, dtype=dtype)
            self._pooled = 14  # reference: ROIPooling 14×14 → stage5 stride 2 → 7×7
        elif net.NETWORK == "vgg16":
            self.backbone = VGGConv(dtype=dtype)
            self.head_body = VGGFC(dtype=dtype)
            self._pooled = 7
        else:
            raise ValueError(f"unknown backbone {net.NETWORK}")
        self.rpn = RPNHead(num_anchors=net.NUM_ANCHORS, dtype=dtype)
        self.rcnn_out = RCNNOutput(num_classes=self.cfg.NUM_CLASSES, dtype=dtype)

    # ---- shared pieces -----------------------------------------------------

    def _anchors_for(self, feat_h: int, feat_w: int) -> jnp.ndarray:
        """All anchors for a (static) feature shape — numpy at trace time,
        a constant in the compiled program."""
        net = self.cfg.network
        base = generate_anchors(base_size=net.RPN_FEAT_STRIDE,
                                ratios=net.ANCHOR_RATIOS, scales=net.ANCHOR_SCALES)
        return jnp.asarray(all_anchors(feat_h, feat_w, net.RPN_FEAT_STRIDE, base))

    def _rcnn_head(self, feat: jnp.ndarray, rois: jnp.ndarray, deterministic: bool = True):
        """feat: (B, Hf, Wf, C); rois: (B, R, 4) image coords → (B, R, K), (B, R, 4K)."""
        scale = 1.0 / self.cfg.network.RCNN_FEAT_STRIDE
        sr = self.cfg.tpu.ROI_SAMPLING_RATIO
        pooled = jax.vmap(
            lambda f, r: roi_align(f.astype(self._dtype), r, spatial_scale=scale,
                                   pooled_size=self._pooled, sampling_ratio=sr,
                                   mode=self.cfg.tpu.ROI_MODE)
        )(feat, rois)  # (B, R, P, P, C)
        if isinstance(self.head_body, VGGFC):
            emb = self.head_body(pooled, deterministic=deterministic)
        else:
            emb = self.head_body(pooled)
        return self.rcnn_out(emb)

    # ---- train graph (reference get_*_train) -------------------------------

    def __call__(self, images, im_info, gt_boxes, gt_classes, gt_valid, key,
                 gt_masks=None):
        """One training forward pass.

        Args:
          images: (B, H, W, 3) float32, pixel-mean subtracted, padded.
          im_info: (B, 3) float32 — (effective_h, effective_w, scale).
          gt_boxes: (B, G, 4); gt_classes: (B, G) int32; gt_valid: (B, G) bool.
          key: PRNG key for in-graph sampling.
          gt_masks: accepted for loader compatibility; the classic graph has
            no mask head and ignores it (FPN variant consumes it).

        Returns (total_loss, aux) with the six reference metrics' raw pieces.
        """
        del gt_masks
        cfg = self.cfg
        tr = cfg.TRAIN
        B = images.shape[0]

        feat = self.backbone(images)
        fh, fw = feat.shape[1], feat.shape[2]
        anchors = self._anchors_for(fh, fw)
        rpn_cls, rpn_bbox = self.rpn(feat)  # (B, N, 2), (B, N, 4)

        keys = jax.random.split(key, (B, 2))  # works for typed and legacy keys

        # --- RPN targets (in-graph assign_anchor) ---
        assign = jax.vmap(
            lambda gtb, gtv, info, k: assign_anchor(
                anchors, gtb, gtv, info[0], info[1], k,
                batch_size=tr.RPN_BATCH_SIZE, fg_fraction=tr.RPN_FG_FRACTION,
                pos_overlap=tr.RPN_POSITIVE_OVERLAP, neg_overlap=tr.RPN_NEGATIVE_OVERLAP,
                allowed_border=tr.RPN_ALLOWED_BORDER,
                clobber_positives=tr.RPN_CLOBBER_POSITIVES,
                iou_bf16=tr.RPN_ASSIGN_IOU_BF16,
                fused=self.cfg.tpu.ASSIGN_FUSED)
        )(gt_boxes, gt_valid, im_info, keys[:, 0])

        # --- proposals (Proposal op; non-differentiable by contract) ---
        fg_score = L.fg_prob(rpn_cls)
        fg_score = jax.lax.stop_gradient(fg_score)
        rpn_bbox_sg = jax.lax.stop_gradient(rpn_bbox)
        rois, _, roi_valid = jax.vmap(
            lambda s, d, info: propose(
                s, d, anchors, info[0], info[1], info[2],
                pre_nms_top_n=tr.RPN_PRE_NMS_TOP_N, post_nms_top_n=tr.RPN_POST_NMS_TOP_N,
                nms_thresh=tr.RPN_NMS_THRESH, min_size=tr.RPN_MIN_SIZE,
                use_pallas=tr.CXX_PROPOSAL)
        )(fg_score, rpn_bbox_sg, im_info)

        # --- ProposalTarget: append gt, sample 128 RoIs with targets ---
        rois_aug = jnp.concatenate([rois, gt_boxes], axis=1)
        valid_aug = jnp.concatenate([roi_valid, gt_valid], axis=1)
        tgt = jax.vmap(
            lambda r, v, gtb, gtc, gtv, k: sample_rois(
                r, v, gtb, gtc, gtv, k,
                num_classes=cfg.NUM_CLASSES, batch_rois=tr.BATCH_ROIS,
                fg_fraction=tr.FG_FRACTION, fg_thresh=tr.FG_THRESH,
                bg_thresh_hi=tr.BG_THRESH_HI, bg_thresh_lo=tr.BG_THRESH_LO,
                bbox_means=tr.BBOX_MEANS, bbox_stds=tr.BBOX_STDS)
        )(rois_aug, valid_aug, gt_boxes, gt_classes, gt_valid, keys[:, 1])
        tgt = jax.tree.map(jax.lax.stop_gradient, tgt)

        # --- RCNN head ---
        cls_logits, bbox_out = self._rcnn_head(feat, tgt["rois"], deterministic=False)

        # --- losses (reference loss-op semantics, explicit masks) ---
        rpn_cls_loss = L.softmax_ce_ignore(rpn_cls, assign["label"])
        rpn_bbox_loss = L.smooth_l1(rpn_bbox, assign["bbox_target"],
                                    assign["bbox_weight"], sigma=3.0,
                                    norm=float(tr.RPN_BATCH_SIZE) * B)
        rcnn_cls_loss = L.softmax_ce_weighted(cls_logits, tgt["label"], tgt["label_weight"])
        rcnn_bbox_loss = L.smooth_l1(bbox_out, tgt["bbox_target"], tgt["bbox_weight"],
                                     sigma=1.0, norm=float(tr.BATCH_ROIS) * B)
        total = rpn_cls_loss + rpn_bbox_loss + rcnn_cls_loss + rcnn_bbox_loss

        aux = {
            "rpn_cls_loss": rpn_cls_loss,
            "rpn_bbox_loss": rpn_bbox_loss,
            "rcnn_cls_loss": rcnn_cls_loss,
            "rcnn_bbox_loss": rcnn_bbox_loss,
            # raw pieces for the six reference metrics (core/metric.py)
            "rpn_label": assign["label"],
            "rpn_pred": jnp.argmax(rpn_cls, axis=-1),
            "rcnn_label": tgt["label"],
            "rcnn_pred": jnp.argmax(cls_logits, axis=-1),
            "rcnn_label_weight": tgt["label_weight"],
        }
        return total, aux

    # ---- test graph (reference get_*_test) ---------------------------------

    def predict(self, images, im_info):
        """Inference forward:
        (rois, roi_valid, cls_prob, bbox_deltas, roi_scores).

        rois are in the *scaled* image frame, like the reference's test
        symbol; the eval layer divides by im_scale (tester.py im_detect).
        """
        cfg = self.cfg
        te = cfg.TEST
        feat = self.backbone(images)
        anchors = self._anchors_for(feat.shape[1], feat.shape[2])
        rpn_cls, rpn_bbox = self.rpn(feat)
        fg_score = L.fg_prob(rpn_cls)
        rois, roi_scores, roi_valid = jax.vmap(
            lambda s, d, info: propose(
                s, d, anchors, info[0], info[1], info[2],
                pre_nms_top_n=te.RPN_PRE_NMS_TOP_N, post_nms_top_n=te.RPN_POST_NMS_TOP_N,
                nms_thresh=te.RPN_NMS_THRESH, min_size=te.RPN_MIN_SIZE,
                use_pallas=te.CXX_PROPOSAL)
        )(fg_score, rpn_bbox, im_info)
        cls_logits, bbox_deltas = self._rcnn_head(feat, rois, deterministic=True)
        cls_prob = jax.nn.softmax(cls_logits, axis=-1)
        return rois, roi_valid, cls_prob, bbox_deltas, roi_scores

    def predict_rpn(self, images, im_info):
        """RPN-only inference (reference ``get_*_rpn_test``) — proposal
        generation for 4-step alternate training (tester.generate_proposals)."""
        te = self.cfg.TEST
        feat = self.backbone(images)
        anchors = self._anchors_for(feat.shape[1], feat.shape[2])
        rpn_cls, rpn_bbox = self.rpn(feat)
        fg_score = L.fg_prob(rpn_cls)
        return jax.vmap(
            lambda s, d, info: propose(
                s, d, anchors, info[0], info[1], info[2],
                pre_nms_top_n=te.RPN_PRE_NMS_TOP_N, post_nms_top_n=te.RPN_POST_NMS_TOP_N,
                nms_thresh=te.RPN_NMS_THRESH, min_size=te.RPN_MIN_SIZE,
                use_pallas=te.CXX_PROPOSAL)
        )(fg_score, rpn_bbox, im_info)

    def rpn_train(self, images, im_info, gt_boxes, gt_valid, key):
        """RPN-only training graph (reference ``get_*_rpn`` — alternate
        training steps 1 and 4)."""
        tr = self.cfg.TRAIN
        B = images.shape[0]
        feat = self.backbone(images)
        anchors = self._anchors_for(feat.shape[1], feat.shape[2])
        rpn_cls, rpn_bbox = self.rpn(feat)
        keys = jax.random.split(key, B)
        assign = jax.vmap(
            lambda gtb, gtv, info, k: assign_anchor(
                anchors, gtb, gtv, info[0], info[1], k,
                batch_size=tr.RPN_BATCH_SIZE, fg_fraction=tr.RPN_FG_FRACTION,
                pos_overlap=tr.RPN_POSITIVE_OVERLAP, neg_overlap=tr.RPN_NEGATIVE_OVERLAP,
                allowed_border=tr.RPN_ALLOWED_BORDER,
                clobber_positives=tr.RPN_CLOBBER_POSITIVES,
                iou_bf16=tr.RPN_ASSIGN_IOU_BF16,
                fused=self.cfg.tpu.ASSIGN_FUSED)
        )(gt_boxes, gt_valid, im_info, keys)
        rpn_cls_loss = L.softmax_ce_ignore(rpn_cls, assign["label"])
        rpn_bbox_loss = L.smooth_l1(rpn_bbox, assign["bbox_target"],
                                    assign["bbox_weight"], sigma=3.0,
                                    norm=float(tr.RPN_BATCH_SIZE) * B)
        total = rpn_cls_loss + rpn_bbox_loss
        aux = {"rpn_cls_loss": rpn_cls_loss, "rpn_bbox_loss": rpn_bbox_loss,
               "rpn_label": assign["label"], "rpn_pred": jnp.argmax(rpn_cls, axis=-1)}
        return total, aux

    def rcnn_train(self, images, im_info, rois, roi_valid, gt_boxes, gt_classes,
                   gt_valid, key):
        """Fast-RCNN training graph on externally supplied proposals
        (reference ``get_*_rcnn`` + ROIIter — alternate training steps 3/6)."""
        cfg = self.cfg
        tr = cfg.TRAIN
        B = images.shape[0]
        feat = self.backbone(images)
        keys = jax.random.split(key, B)
        rois_aug = jnp.concatenate([rois, gt_boxes], axis=1)
        valid_aug = jnp.concatenate([roi_valid, gt_valid], axis=1)
        tgt = jax.vmap(
            lambda r, v, gtb, gtc, gtv, k: sample_rois(
                r, v, gtb, gtc, gtv, k,
                num_classes=cfg.NUM_CLASSES, batch_rois=tr.BATCH_ROIS,
                fg_fraction=tr.FG_FRACTION, fg_thresh=tr.FG_THRESH,
                bg_thresh_hi=tr.BG_THRESH_HI, bg_thresh_lo=tr.BG_THRESH_LO,
                bbox_means=tr.BBOX_MEANS, bbox_stds=tr.BBOX_STDS)
        )(rois_aug, valid_aug, gt_boxes, gt_classes, gt_valid, keys)
        tgt = jax.tree.map(jax.lax.stop_gradient, tgt)
        cls_logits, bbox_out = self._rcnn_head(feat, tgt["rois"], deterministic=False)
        rcnn_cls_loss = L.softmax_ce_weighted(cls_logits, tgt["label"], tgt["label_weight"])
        rcnn_bbox_loss = L.smooth_l1(bbox_out, tgt["bbox_target"], tgt["bbox_weight"],
                                     sigma=1.0, norm=float(tr.BATCH_ROIS) * B)
        total = rcnn_cls_loss + rcnn_bbox_loss
        aux = {"rcnn_cls_loss": rcnn_cls_loss, "rcnn_bbox_loss": rcnn_bbox_loss,
               "rcnn_label": tgt["label"], "rcnn_pred": jnp.argmax(cls_logits, axis=-1),
               "rcnn_label_weight": tgt["label_weight"]}
        return total, aux


def build_model(cfg: Config) -> FasterRCNN:
    """Factory — the analogue of the reference's ``get_<net>_train/test``
    symbol selectors (dispatch in train_end2end.py / test.py)."""
    if cfg.network.HAS_FPN:
        try:
            from mx_rcnn_tpu.models.fpn import FPNFasterRCNN
        except ImportError as e:
            raise NotImplementedError(
                "FPN model variants are not built yet (models/fpn.py pending)"
            ) from e
        return FPNFasterRCNN(cfg=cfg)
    return FasterRCNN(cfg=cfg)


def init_params(model: FasterRCNN, cfg: Config, key, batch_size: int = 1,
                image_hw: Optional[tuple] = None):
    """Initialize parameters with a dummy batch (shapes from the first scale
    bucket).  Returns the params pytree."""
    if image_hw is None:
        s = cfg.tpu.SCALES[0]
        stride = max(cfg.network.IMAGE_STRIDE, cfg.network.RPN_FEAT_STRIDE)
        image_hw = (int(np.ceil(s[0] / stride) * stride),
                    int(np.ceil(s[1] / stride) * stride))
    h, w = image_hw
    g = cfg.tpu.MAX_GT
    k1, k2 = jax.random.split(key)
    kwargs = {}
    if cfg.network.HAS_MASK:
        from mx_rcnn_tpu.data.mask import GT_MASK_SIZE

        # mask_head params only materialize if the mask branch traces at init
        kwargs["gt_masks"] = jnp.zeros(
            (batch_size, g, GT_MASK_SIZE, GT_MASK_SIZE), jnp.float32)
    dummy = dict(
        images=jnp.zeros((batch_size, h, w, 3), jnp.float32),
        im_info=jnp.tile(jnp.asarray([[h, w, 1.0]], jnp.float32), (batch_size, 1)),
        gt_boxes=jnp.zeros((batch_size, g, 4), jnp.float32),
        gt_classes=jnp.zeros((batch_size, g), jnp.int32),
        gt_valid=jnp.zeros((batch_size, g), bool),
    )
    # jit the init: eager flax init dispatches the whole train graph op by
    # op — minutes at full image scale on a tunneled device
    init_fn = jax.jit(partial(model.init, **kwargs))
    variables = init_fn({"params": k1, "dropout": k2}, dummy["images"],
                        dummy["im_info"], dummy["gt_boxes"],
                        dummy["gt_classes"], dummy["gt_valid"], k2)
    return variables["params"]
