"""RPN and R-CNN output heads.

Reference graph pieces (``rcnn/symbol/symbol_resnet.py`` /
``symbol_vgg.py``):

* RPN: 3×3 conv (512 ch) + relu → two sibling 1×1 convs:
  ``rpn_cls_score`` (2A ch) and ``rpn_bbox_pred`` (4A ch).
* RCNN: head body (VGG fc6/7 or ResNet stage5 pool) → two FCs:
  ``cls_score`` (K) and ``bbox_pred`` (4K).
* Mask (capability target, Mask R-CNN): 4×[3×3 conv 256] → 2× deconv →
  1×1 conv K channels, per-class 28×28 sigmoid masks.

Channel layout note (documented divergence): MXNet lays RPN outputs as
(B, 2A, H, W) with softmax over a reshaped axis; here NHWC convs emit
(B, H, W, 2A) reshaped to (B, H·W·A, 2) so that the flattened anchor index
equals ``(y·W + x)·A + a`` — the exact order `ops.anchors.all_anchors`
emits.  The layouts are permutations of each other; the math is identical.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class RPNHead(nn.Module):
    num_anchors: int = 9
    channels: int = 512
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, feat):
        """feat: (B, H, W, C) → (cls_logits (B, H·W·A, 2),
        bbox_deltas (B, H·W·A, 4))."""
        a = self.num_anchors
        # reference init: Normal(0.01) for all new RPN layers
        init = nn.initializers.normal(0.01)
        x = nn.Conv(self.channels, (3, 3), padding=[(1, 1), (1, 1)],
                    kernel_init=init, dtype=self.dtype, name="rpn_conv_3x3")(feat)
        x = nn.relu(x)
        cls = nn.Conv(2 * a, (1, 1), kernel_init=init, dtype=self.dtype,
                      name="rpn_cls_score")(x)
        bbox = nn.Conv(4 * a, (1, 1), kernel_init=init, dtype=self.dtype,
                       name="rpn_bbox_pred")(x)
        b, h, w, _ = cls.shape
        cls = cls.reshape(b, h * w * a, 2).astype(jnp.float32)
        bbox = bbox.reshape(b, h * w * a, 4).astype(jnp.float32)
        return cls, bbox


class RCNNOutput(nn.Module):
    """cls_score / bbox_pred FCs on the head-body embedding."""

    num_classes: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # reference init: cls_score Normal(0.01), bbox_pred Normal(0.001)
        cls = nn.Dense(self.num_classes, kernel_init=nn.initializers.normal(0.01),
                       dtype=self.dtype, name="cls_score")(x)
        bbox = nn.Dense(4 * self.num_classes, kernel_init=nn.initializers.normal(0.001),
                        dtype=self.dtype, name="bbox_pred")(x)
        return cls.astype(jnp.float32), bbox.astype(jnp.float32)


class MaskHead(nn.Module):
    """Mask R-CNN head: 4 convs + deconv ×2 + per-class 1×1 (28×28 out from
    14×14 RoI features)."""

    num_classes: int
    channels: int = 256
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        """x: (R, 14, 14, C) → (R, 28, 28, K) logits."""
        for i in range(1, 5):
            x = nn.Conv(self.channels, (3, 3), padding=[(1, 1), (1, 1)],
                        dtype=self.dtype, name=f"mask_conv{i}")(x)
            x = nn.relu(x)
        x = nn.ConvTranspose(self.channels, (2, 2), strides=(2, 2),
                             dtype=self.dtype, name="mask_deconv")(x)
        x = nn.relu(x)
        x = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype, name="mask_out")(x)
        return x.astype(jnp.float32)
