"""Model definitions — the reference's ``rcnn/symbol`` layer, flax-native."""

from mx_rcnn_tpu.models.detector import FasterRCNN, build_model, init_params
from mx_rcnn_tpu.models.backbones import ResNetConv, VGGConv
from mx_rcnn_tpu.models.heads import RPNHead, RCNNOutput, MaskHead
