"""Backbone networks — flax, NHWC, bfloat16 compute, frozen-BN.

Behavioral contracts from the reference's symbol builders:

* ResNet-50/101 (``rcnn/symbol/symbol_resnet.py``): ``residual_unit``
  bottlenecks, conv body = stages 1–4 (stride 16 output, 1024 ch), BN with
  ``use_global_stats=True`` (running stats always, never batch stats) and
  all gamma/beta frozen via ``fixed_param_prefix``; stage 5 is the RCNN
  head (see heads.py).
* VGG-16 (``rcnn/symbol/symbol_vgg.py``): conv1–5 body (stride 16, 512 ch),
  conv1–2 frozen.

TPU-first: NHWC layout (XLA's native conv layout on TPU), bfloat16 activations
with float32 params, no BN stat updates.  Frozen BN reduces to a per-channel
affine, but XLA does NOT fuse that affine into the adjacent conv (measured
~2 ms/stage of standalone elementwise passes on v5-lite) — so conv→BN pairs
run in folded form instead: the scale rides the conv kernel and the shift
becomes a bias (see FrozenBN/ScaledConv).
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class StemConvS2D(nn.Module):
    """The ResNet 7×7/2 stem conv, computed via space-to-depth.

    A direct 7×7 stride-2 conv on a 3-channel image contracts only
    7·7·3 = 147 values per output but feeds the MXU 3-channel-deep input
    tiles — measured ~0.5 TFLOP/s on v5-lite, making the stem nearly half
    of the whole ResNet-101 body's fwd+bwd time.  Rewriting x as 2×2
    space-to-depth blocks (H/2, W/2, 12) turns the same math into a 4×4
    stride-1 conv with a 4·4·12 = 192-deep contraction that tiles onto the
    MXU properly.  Derivation: with a = 2A + di − 1 (a the original tap,
    A the s2d tap, di the in-block offset), the 7×7 kernel left-padded to
    8×8 and regrouped as (4, 2, 4, 2, 3) gives
    y[p,q,o] = Σ_{A,B,di,dj,c} X[p+A−2, q+B−2… pad (2,1)] · W — exact,
    not an approximation (the padded row/col multiplies zeros only).

    The parameter keeps the reference layout (7, 7, 3, 64) under the same
    ``conv1/kernel`` path as ``nn.Conv(name="conv1")``, so checkpoints and
    the torch converter are unaffected.  Odd input sizes fall back to the
    direct conv (scale buckets are all even, but ``demo.py`` accepts
    arbitrary images).
    """

    features: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, scale=None, shift=None):
        k = self.param("kernel", nn.initializers.lecun_normal(),
                       (7, 7, 3, self.features), jnp.float32)
        if scale is not None:  # folded FrozenBN (output-channel affine
            k = k * scale[None, None, None, :]  # commutes with the regroup)
        k = k.astype(self.dtype)
        x = x.astype(self.dtype)
        b, h, w, c = x.shape
        if c == 12:
            # input arrived space-to-depth'd on the host (config
            # network.HOST_S2D — data/image.py:space_to_depth2, same
            # (di, dj, ch) channel order): skip the device-side regroup,
            # whose lane-hostile transpose costs ~1 ms/step
            xs = x
        elif h % 2 or w % 2:
            y = jax.lax.conv_general_dilated(
                x, k, window_strides=(2, 2), padding=[(3, 3), (3, 3)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            if shift is not None:
                y = y + shift.astype(self.dtype)
            return y
        else:
            xs = (x.reshape(b, h // 2, 2, w // 2, 2, c)
                  .transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c))
        kp = jnp.pad(k, ((1, 0), (1, 0), (0, 0), (0, 0)))  # 8×8, zero tap 0
        kp = kp.reshape(4, 2, 4, 2, 3, self.features).transpose(0, 2, 1, 3, 4, 5)
        kp = kp.reshape(4, 4, 12, self.features)
        y = jax.lax.conv_general_dilated(
            xs, kp, window_strides=(1, 1), padding=[(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if shift is not None:
            y = y + shift.astype(self.dtype)
        return y


class FrozenBN(nn.Module):
    """BatchNorm with ``use_global_stats=True`` semantics.

    Running mean/var are parameters (loaded from pretrained checkpoints,
    never updated by the optimizer — see train/optim.py's fixed-param mask,
    which freezes ``gamma``/``beta``/``mean``/``var`` by name).  The whole op
    is an affine y = x·scale + shift computed from the four params.

    Called with ``x=None`` it returns the (scale, shift) pair instead of
    applying it — the conv+BN fold: because the affine is per *output
    channel* and the BN params are frozen, ``BN(conv(x, W)) ≡
    conv(x, W·scale) + shift`` exactly (gradients included: W's grad picks
    up the same constant scale either way).  Measured on v5-lite, the
    standalone affine pass costs ~2 ms per stage-3-sized stage and fwd
    because XLA does not fuse it into the conv; folding removes it.
    ``features`` is only needed for the ``x=None`` form (no input to infer
    the channel count from).
    """

    epsilon: float = 2e-5
    dtype: jnp.dtype = jnp.bfloat16
    features: int | None = None

    @nn.compact
    def __call__(self, x=None):
        c = x.shape[-1] if x is not None else self.features
        assert c is not None, "FrozenBN(features=...) required for x=None"
        gamma = self.param("gamma", nn.initializers.ones, (c,), jnp.float32)
        beta = self.param("beta", nn.initializers.zeros, (c,), jnp.float32)
        mean = self.param("mean", nn.initializers.zeros, (c,), jnp.float32)
        var = self.param("var", nn.initializers.ones, (c,), jnp.float32)
        scale = gamma / jnp.sqrt(var + self.epsilon)
        shift = beta - mean * scale
        if x is None:
            return scale, shift
        return (x * scale.astype(self.dtype) + shift.astype(self.dtype)).astype(self.dtype)


class ScaledConv(nn.Module):
    """Conv whose kernel is scaled per output channel and whose output gets
    a per-channel shift — the folded form of conv→FrozenBN.  Parameter
    layout matches ``nn.Conv`` (``kernel`` (kh, kw, cin, f), f32, lecun
    normal, no bias), so checkpoints and the torch converter see no
    difference from the conv it replaces.
    """

    features: int
    kernel_size: int = 1
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, scale=None, shift=None):
        k = self.kernel_size
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (k, k, x.shape[-1], self.features), jnp.float32)
        if scale is not None:
            kernel = kernel * scale[None, None, None, :]
        lead = x.shape[:-3]  # like nn.Conv, fold extra batch dims (RoI heads
        if len(lead) != 1:   # run stage-5 over (B, R, 7, 7, C) features)
            x = x.reshape((-1,) + x.shape[-3:])
        y = jax.lax.conv_general_dilated(
            x.astype(self.dtype), kernel.astype(self.dtype),
            window_strides=(self.strides, self.strides),
            padding=[(k // 2, k // 2)] * 2,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if shift is not None:
            y = y + shift.astype(self.dtype)
        if len(lead) != 1:
            y = y.reshape(lead + y.shape[1:])
        return y


class Bottleneck(nn.Module):
    """ResNet bottleneck (reference ``residual_unit``: BN-before-add variant
    used by mx-rcnn — conv→bn→relu ×2, conv→bn, projection shortcut, add,
    relu)."""

    filters: int  # bottleneck (inner) width; output is 4×
    strides: int = 1
    project: bool = False
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # conv→BN pairs run in the folded form (see FrozenBN): the BN
        # affine rides the conv kernel/output instead of a separate
        # elementwise pass over the activations
        def cbn(h, f, k, s, conv_name, bn_name):
            sc, sh = FrozenBN(dtype=self.dtype, features=f, name=bn_name)()
            return ScaledConv(f, k, s, dtype=self.dtype,
                              name=conv_name)(h, sc, sh)

        out = nn.relu(cbn(x, self.filters, 1, 1, "conv1", "bn1"))
        out = nn.relu(cbn(out, self.filters, 3, self.strides, "conv2", "bn2"))
        out = cbn(out, self.filters * 4, 1, 1, "conv3", "bn3")
        if self.project:
            sc = cbn(x, self.filters * 4, 1, self.strides, "sc_conv", "sc_bn")
        else:
            sc = x
        return nn.relu(out + sc)


class ResNetStage(nn.Module):
    """One ResNet stage: first unit downsamples/projects, rest are identity."""

    units: int
    filters: int
    strides: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = Bottleneck(self.filters, self.strides, project=True,
                       dtype=self.dtype, name="unit1")(x)
        for i in range(2, self.units + 1):
            x = Bottleneck(self.filters, 1, dtype=self.dtype, name=f"unit{i}")(x)
        return x


RESNET_UNITS = {
    "resnet50": (3, 4, 6, 3),
    "resnet101": (3, 4, 23, 3),
    "resnet152": (3, 8, 36, 3),
}


class ResNetConv(nn.Module):
    """ResNet conv body, stages 1–4 → stride-16 / 1024-channel feature map
    (reference ``get_resnet_conv``).  If ``all_stages`` is True, also returns
    the per-stage C2..C5 pyramid (for FPN; C5 at stride 32)."""

    depth: str = "resnet50"
    dtype: jnp.dtype = jnp.bfloat16
    all_stages: bool = False
    # remat: recompute each stage's activations in the backward pass
    # (cfg.tpu.REMAT_BACKBONE) — only stage INPUTS are saved, so the
    # large relu/add activations never round-trip HBM between fwd and
    # bwd; params and numerics are identical (nn.remat is a lifted
    # transform — scope names pass through)
    remat: bool = False

    @nn.compact
    def __call__(self, x):
        units = RESNET_UNITS[self.depth]
        Stage = nn.remat(ResNetStage) if self.remat else ResNetStage
        x = x.astype(self.dtype)
        sc1, sh1 = FrozenBN(dtype=self.dtype, features=64, name="bn1")()
        x = StemConvS2D(dtype=self.dtype, name="conv1")(x, sc1, sh1)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        c2 = Stage(units[0], 64, 1, dtype=self.dtype, name="stage1")(x)
        c3 = Stage(units[1], 128, 2, dtype=self.dtype, name="stage2")(c2)
        c4 = Stage(units[2], 256, 2, dtype=self.dtype, name="stage3")(c3)
        if not self.all_stages:
            return c4  # stride 16, 1024 ch — the classic single-level feature
        c5 = Stage(units[3], 512, 2, dtype=self.dtype, name="stage4")(c4)
        return c2, c3, c4, c5


class ResNetStage5(nn.Module):
    """ResNet stage 5 as the RCNN head body (reference: stage 5 units applied
    to the 14×14 pooled RoI features, stride 2 → 7×7, then global avg pool)."""

    depth: str = "resnet50"
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        units = RESNET_UNITS[self.depth][3]
        x = ResNetStage(units, 512, 2, dtype=self.dtype, name="stage4")(x)
        return jnp.mean(x, axis=(-3, -2))  # global average pool → (…, 2048)


class VGGConv(nn.Module):
    """VGG-16 conv body (reference ``get_vgg_conv``): 13 convs in 5 blocks,
    max-pool after blocks 1–4 (not 5) → stride-16 / 512-channel feature."""

    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        cfg: Sequence = ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512))
        for b, (n, f) in enumerate(cfg, start=1):
            for i in range(1, n + 1):
                x = nn.Conv(f, (3, 3), padding=[(1, 1), (1, 1)], dtype=self.dtype,
                            name=f"conv{b}_{i}")(x)
                x = nn.relu(x)
            if b < 5:
                # reduce_window form kept: the reshape+max alternative
                # (ops/pool.py) measured device-neutral on-chip — XLA's
                # select-and-scatter bwd costs the same as the equality-
                # select bwd here (17.34 vs 17.33 ms step; BASELINE.md
                # round-4 ledger) — so reference-exact tie routing wins.
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
        return x


class VGGFC(nn.Module):
    """VGG fc6/fc7 head body on 7×7 pooled RoIs (reference ``get_vgg_rcnn``);
    dropout omitted at the reference's inference setting (train uses 0.5 —
    applied when ``deterministic=False``)."""

    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        x = x.reshape(x.shape[:-3] + (-1,))
        x = nn.Dense(4096, dtype=self.dtype, name="fc6")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=deterministic)(x)
        x = nn.Dense(4096, dtype=self.dtype, name="fc7")(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=deterministic)(x)
        return x
