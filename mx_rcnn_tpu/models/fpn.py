"""FPN Faster/Mask R-CNN (BASELINE.json configs 4–5).

Not part of classic mx-rcnn (SURVEY §0 item 3 — capability target, patterns
from the FPN/Mask R-CNN papers and their standard implementations):

* neck: lateral 1×1 on C2–C5 + nearest top-down + 3×3 smoothing → P2–P5
  (256 ch), P6 = stride-2 subsample of P5 (RPN only).
* RPN: one shared head over all levels; per-level anchors (one scale ×
  3 ratios per level, FPN_ANCHOR_SCALES), per-level top-k then joint NMS.
* RoI features: level assignment k = floor(k0 + log2(√area/224)) clamped to
  P2–P5; static-shape trick — pool every level, select by one-hot (4 cheap
  gathers beat dynamic partitions on TPU).
* head: 2×FC-1024 (the standard FPN box head), cls + bbox.
* mask head (HAS_MASK): 14×14 ROIAlign on the assigned level → 4 convs +
  deconv → 28×28 per-class logits; targets are gt masks resampled into the
  RoI frame in-graph (ops/mask_target.py) from host-rasterized gt-box crops.

Sampling/targets/losses reuse the exact same ops as the classic graph —
behavioral contracts unchanged.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models import losses as L
from mx_rcnn_tpu.models.backbones import ResNetConv
from mx_rcnn_tpu.models.heads import MaskHead, RCNNOutput, RPNHead
from mx_rcnn_tpu.ops import (all_anchors, assign_anchor, generate_anchors,
                             propose, sample_rois)
from mx_rcnn_tpu.ops.mask_target import mask_targets_for_rois
from mx_rcnn_tpu.ops.proposal import propose_fpn
from mx_rcnn_tpu.ops.roi_align import roi_align


class FPNNeck(nn.Module):
    out_channels: int = 256
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, c2, c3, c4, c5):
        lat = lambda i, x: nn.Conv(  # noqa: E731
            self.out_channels, (1, 1), dtype=self.dtype, name=f"lateral{i}")(x)
        out = lambda i, x: nn.Conv(  # noqa: E731
            self.out_channels, (3, 3), padding=[(1, 1), (1, 1)],
            dtype=self.dtype, name=f"post{i}")(x)

        p5 = lat(5, c5)
        p4 = lat(4, c4) + _upsample2(p5)
        p3 = lat(3, c3) + _upsample2(p4)
        p2 = lat(2, c2) + _upsample2(p3)
        p2, p3, p4, p5 = out(2, p2), out(3, p3), out(4, p4), out(5, p5)
        p6 = nn.max_pool(p5, (1, 1), strides=(2, 2))  # stride-2 subsample
        return p2, p3, p4, p5, p6


def _upsample2(x):
    b, h, w, c = x.shape
    return jax.image.resize(x, (b, 2 * h, 2 * w, c), method="nearest")


class FPNBoxHead(nn.Module):
    """2×FC-1024 box head (standard FPN head; VGG-style but shared-width)."""

    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.reshape(x.shape[:-3] + (-1,))
        x = nn.relu(nn.Dense(1024, dtype=self.dtype, name="fc6")(x))
        x = nn.relu(nn.Dense(1024, dtype=self.dtype, name="fc7")(x))
        return x


class FPNFasterRCNN(nn.Module):
    """Multi-level two-stage detector; optionally with a mask head."""

    cfg: Config

    def setup(self):
        net = self.cfg.network
        dtype = jnp.bfloat16 if self.cfg.tpu.COMPUTE_DTYPE == "bfloat16" else jnp.float32
        self._dtype = dtype
        assert net.NETWORK.startswith("resnet"), "FPN requires a ResNet body"
        self.backbone = ResNetConv(depth=net.NETWORK, dtype=dtype,
                                   all_stages=True,
                                   remat=self.cfg.tpu.REMAT_BACKBONE)
        self.neck = FPNNeck(out_channels=net.FPN_OUT_CHANNELS, dtype=dtype)
        # FPN's shared RPN head is FPN_OUT_CHANNELS (256) wide — the FPN
        # paper/Detectron convention (the classic C4 RPN uses 512); at P2
        # resolution the 3×3 hidden conv is the single most expensive op in
        # the whole step (3.4 ms fwd at 512ch, profiled), so width follows
        # the convention, not the classic default
        self.rpn = RPNHead(num_anchors=net.NUM_ANCHORS,
                           channels=net.FPN_OUT_CHANNELS, dtype=dtype)
        self.head_body = FPNBoxHead(dtype=dtype)
        self.rcnn_out = RCNNOutput(num_classes=self.cfg.NUM_CLASSES, dtype=dtype)
        if net.HAS_MASK:
            self.mask_head = MaskHead(num_classes=self.cfg.NUM_CLASSES,
                                      dtype=dtype)

    # ---- shared pieces -----------------------------------------------------

    @property
    def _strides(self):
        return self.cfg.network.FPN_FEAT_STRIDES  # (4, 8, 16, 32, 64)

    def _pyramid(self, images):
        c2, c3, c4, c5 = self.backbone(images)
        return self.neck(c2, c3, c4, c5)

    def _anchors_for_level(self, feat_h: int, feat_w: int, stride: int,
                           scale: int) -> jnp.ndarray:
        net = self.cfg.network
        base = generate_anchors(base_size=stride, ratios=net.ANCHOR_RATIOS,
                                scales=(scale,))
        return jnp.asarray(all_anchors(feat_h, feat_w, stride, base))

    def _rpn_over_levels(self, feats):
        """Shared RPN over P2–P6 → per-level (cls, bbox, anchors)."""
        net = self.cfg.network
        out = []
        for lvl, feat in enumerate(feats):
            stride = self._strides[lvl]
            scale = net.FPN_ANCHOR_SCALES[0]
            cls, bbox = self.rpn(feat)
            anchors = self._anchors_for_level(feat.shape[1], feat.shape[2],
                                              stride, scale)
            out.append((cls, bbox, anchors))
        return out

    def _assign_level(self, rois):
        """(…, 4) rois → level index 0..3 (P2..P5), FPN paper eq. 1."""
        w = rois[..., 2] - rois[..., 0] + 1.0
        h = rois[..., 3] - rois[..., 1] + 1.0
        k = jnp.floor(4.0 + jnp.log2(jnp.sqrt(w * h) / 224.0 + 1e-8))
        return jnp.clip(k, 2.0, 5.0).astype(jnp.int32) - 2

    def _pool_levels(self, feats, rois, pooled: int):
        """Pool rois from their assigned pyramid level (static shapes: pool
        all 4 RoI levels, one-hot select).  feats: P2..P5 (B, H, W, C);
        rois: (B, R, 4) image coords → (B, R, P, P, C)."""
        lvl = self._assign_level(rois)  # (B, R)
        acc = None
        for li in range(4):
            scale = 1.0 / self._strides[li]
            p = jax.vmap(lambda f, r, s=scale: roi_align(
                f.astype(self._dtype), r, spatial_scale=s, pooled_size=pooled,
                sampling_ratio=self.cfg.tpu.ROI_SAMPLING_RATIO,
                mode=self.cfg.tpu.ROI_MODE))(feats[li], rois)
            sel = (lvl == li).astype(p.dtype)[..., None, None, None]
            acc = p * sel if acc is None else acc + p * sel
        return acc

    def _box_head(self, feats, rois):
        pooled = self._pool_levels(feats, rois, pooled=7)
        return self.rcnn_out(self.head_body(pooled))

    # ---- shared training pieces (used by end2end AND the stage graphs) -----

    def _rpn_losses(self, levels, im_info, gt_boxes, gt_valid, keys):
        """Anchor assignment + RPN losses over the concatenated level set
        (one assign per image across all levels — standard FPN training).
        Returns (total, aux)."""
        tr = self.cfg.TRAIN
        B = gt_boxes.shape[0]
        all_cls = jnp.concatenate([c for c, _, _ in levels], axis=1)
        all_bbox = jnp.concatenate([b for _, b, _ in levels], axis=1)
        all_anc = jnp.concatenate([a for _, _, a in levels], axis=0)
        assign = jax.vmap(
            lambda gtb, gtv, info, k: assign_anchor(
                all_anc, gtb, gtv, info[0], info[1], k,
                batch_size=tr.RPN_BATCH_SIZE, fg_fraction=tr.RPN_FG_FRACTION,
                pos_overlap=tr.RPN_POSITIVE_OVERLAP,
                neg_overlap=tr.RPN_NEGATIVE_OVERLAP,
                allowed_border=tr.RPN_ALLOWED_BORDER,
                clobber_positives=tr.RPN_CLOBBER_POSITIVES,
                iou_bf16=tr.RPN_ASSIGN_IOU_BF16,
                fused=self.cfg.tpu.ASSIGN_FUSED)
        )(gt_boxes, gt_valid, im_info, keys)
        rpn_cls_loss = L.softmax_ce_ignore(all_cls, assign["label"])
        rpn_bbox_loss = L.smooth_l1(all_bbox, assign["bbox_target"],
                                    assign["bbox_weight"], sigma=3.0,
                                    norm=float(tr.RPN_BATCH_SIZE) * B)
        aux = {"rpn_cls_loss": rpn_cls_loss, "rpn_bbox_loss": rpn_bbox_loss,
               "rpn_label": assign["label"],
               "rpn_pred": jnp.argmax(all_cls, axis=-1)}
        return rpn_cls_loss + rpn_bbox_loss, aux

    def _propose_train(self, levels, im_info):
        """Training-config proposals: per-level top-k + joint NMS (non-
        differentiable by the Proposal-op contract)."""
        tr = self.cfg.TRAIN
        level_scores = [jax.lax.stop_gradient(L.fg_prob(c))
                        for c, _, _ in levels]
        level_deltas = [jax.lax.stop_gradient(b) for _, b, _ in levels]
        anchors_l = [a for _, _, a in levels]
        rois, _, roi_valid = jax.vmap(
            lambda ls, ld, info: propose_fpn(
                list(ls), list(ld), anchors_l, info[0], info[1], info[2],
                pre_nms_top_n=tr.RPN_PRE_NMS_TOP_N,
                post_nms_top_n=tr.RPN_POST_NMS_TOP_N,
                nms_thresh=tr.RPN_NMS_THRESH, min_size=tr.RPN_MIN_SIZE,
                use_pallas=tr.CXX_PROPOSAL),
        )(tuple(level_scores), tuple(level_deltas), im_info)
        return rois, roi_valid

    def _rcnn_losses(self, feats, rois, roi_valid, gt_boxes, gt_classes,
                     gt_valid, keys):
        """RoI sampling (ProposalTarget contract) + box-head losses.
        Returns (total, aux, tgt)."""
        cfg = self.cfg
        tr = cfg.TRAIN
        B = gt_boxes.shape[0]
        rois_aug = jnp.concatenate([rois, gt_boxes], axis=1)
        valid_aug = jnp.concatenate([roi_valid, gt_valid], axis=1)
        tgt = jax.vmap(
            lambda r, v, gtb, gtc, gtv, k: sample_rois(
                r, v, gtb, gtc, gtv, k,
                num_classes=cfg.NUM_CLASSES, batch_rois=tr.BATCH_ROIS,
                fg_fraction=tr.FG_FRACTION, fg_thresh=tr.FG_THRESH,
                bg_thresh_hi=tr.BG_THRESH_HI, bg_thresh_lo=tr.BG_THRESH_LO,
                bbox_means=tr.BBOX_MEANS, bbox_stds=tr.BBOX_STDS)
        )(rois_aug, valid_aug, gt_boxes, gt_classes, gt_valid, keys)
        tgt = jax.tree.map(jax.lax.stop_gradient, tgt)
        cls_logits, bbox_out = self._box_head(feats, tgt["rois"])
        rcnn_cls_loss = L.softmax_ce_weighted(cls_logits, tgt["label"],
                                              tgt["label_weight"])
        rcnn_bbox_loss = L.smooth_l1(bbox_out, tgt["bbox_target"],
                                     tgt["bbox_weight"], sigma=1.0,
                                     norm=float(tr.BATCH_ROIS) * B)
        aux = {"rcnn_cls_loss": rcnn_cls_loss, "rcnn_bbox_loss": rcnn_bbox_loss,
               "rcnn_label": tgt["label"],
               "rcnn_pred": jnp.argmax(cls_logits, axis=-1),
               "rcnn_label_weight": tgt["label_weight"]}
        return rcnn_cls_loss + rcnn_bbox_loss, aux, tgt

    # ---- train graph -------------------------------------------------------

    def __call__(self, images, im_info, gt_boxes, gt_classes, gt_valid, key,
                 gt_masks: Optional[jnp.ndarray] = None):
        cfg = self.cfg
        B = images.shape[0]
        feats = self._pyramid(images)
        levels = self._rpn_over_levels(feats)
        keys = jax.random.split(key, (B, 2))

        rpn_total, rpn_aux = self._rpn_losses(levels, im_info, gt_boxes,
                                              gt_valid, keys[:, 0])
        rois, roi_valid = self._propose_train(levels, im_info)
        rcnn_total, rcnn_aux, tgt = self._rcnn_losses(
            feats, rois, roi_valid, gt_boxes, gt_classes, gt_valid, keys[:, 1])
        total = rpn_total + rcnn_total
        aux = {**rpn_aux, **rcnn_aux}

        if cfg.network.HAS_MASK and gt_masks is not None:
            pooled14 = self._pool_levels(feats, tgt["rois"], pooled=14)
            mask_logits = self.mask_head(pooled14)  # (B, R, 28, 28, K)
            m = self.cfg.TRAIN.MASK_SIZE
            targets = jax.vmap(
                lambda gm, gtb, r, gi: mask_targets_for_rois(
                    gm, gtb, r, gi, out_size=m)
            )(gt_masks, gt_boxes, tgt["rois"], tgt["gt_index"])
            # per-class logits: pick the sampled label's channel
            sel = jax.nn.one_hot(tgt["label"], cfg.NUM_CLASSES,
                                 dtype=mask_logits.dtype)
            logit = jnp.einsum("brhwk,brk->brhw", mask_logits, sel)
            w = tgt["is_fg"].astype(jnp.float32) * (tgt["label"] > 0)
            mask_loss = jax.vmap(L.mask_bce)(logit, targets, w).mean()
            total = total + mask_loss
            aux["mask_loss"] = mask_loss

        return total, aux

    # ---- test graph --------------------------------------------------------

    def predict(self, images, im_info):
        out, _ = self.predict_with_feats(images, im_info)
        return out

    def predict_with_feats(self, images, im_info):
        """predict + the pyramid features, so the mask branch can reuse them
        (eval runs mask chunks per batch without re-running the backbone)."""
        cfg = self.cfg
        te = cfg.TEST
        feats = self._pyramid(images)
        levels = self._rpn_over_levels(feats)
        level_scores = [L.fg_prob(c) for c, _, _ in levels]
        level_deltas = [b for _, b, _ in levels]
        anchors_l = [a for _, _, a in levels]
        rois, roi_scores, roi_valid = jax.vmap(
            lambda ls, ld, info: propose_fpn(
                list(ls), list(ld), anchors_l, info[0], info[1], info[2],
                pre_nms_top_n=te.RPN_PRE_NMS_TOP_N,
                post_nms_top_n=te.RPN_POST_NMS_TOP_N,
                nms_thresh=te.RPN_NMS_THRESH, min_size=te.RPN_MIN_SIZE,
                use_pallas=te.CXX_PROPOSAL),
        )(tuple(level_scores), tuple(level_deltas), im_info)
        cls_logits, bbox_deltas = self._box_head(feats, rois)
        cls_prob = jax.nn.softmax(cls_logits, axis=-1)
        return (rois, roi_valid, cls_prob, bbox_deltas, roi_scores), feats

    def masks_from_feats(self, feats, boxes, labels):
        """Mask branch over precomputed pyramid features: (B, R, 4) boxes +
        (B, R) labels → (B, R, 28, 28) sigmoid probabilities."""
        pooled14 = self._pool_levels(feats, boxes, pooled=14)
        mask_logits = self.mask_head(pooled14)
        sel = jax.nn.one_hot(labels, self.cfg.NUM_CLASSES,
                             dtype=mask_logits.dtype)
        logit = jnp.einsum("brhwk,brk->brhw", mask_logits, sel)
        return jax.nn.sigmoid(logit)

    def predict_masks(self, images, im_info, boxes, labels):
        """Mask branch from raw images (standalone use; eval prefers
        predict_with_feats + masks_from_feats)."""
        del im_info
        return self.masks_from_feats(self._pyramid(images), boxes, labels)

    # ---- alternate-training stage graphs (classic pipeline on FPN) ---------

    def rpn_train(self, images, im_info, gt_boxes, gt_valid, key):
        """RPN-only training over the pyramid (alternate steps 1/4)."""
        B = images.shape[0]
        feats = self._pyramid(images)
        levels = self._rpn_over_levels(feats)
        return self._rpn_losses(levels, im_info, gt_boxes, gt_valid,
                                jax.random.split(key, B))

    def rcnn_train(self, images, im_info, rois, roi_valid, gt_boxes,
                   gt_classes, gt_valid, key):
        """Box-head training on supplied proposals (alternate steps 3/6).

        Mask configs must train end2end — the stage pipeline has no mask
        targets, and silently leaving the mask head at init would produce
        garbage masks at eval."""
        if self.cfg.network.HAS_MASK:
            raise NotImplementedError(
                "alternate training has no mask-target path; train mask "
                "configs end2end (train_end2end.py)")
        B = images.shape[0]
        feats = self._pyramid(images)
        total, aux, _ = self._rcnn_losses(
            feats, rois, roi_valid, gt_boxes, gt_classes, gt_valid,
            jax.random.split(key, B))
        return total, aux

    def predict_rpn(self, images, im_info):
        te = self.cfg.TEST
        feats = self._pyramid(images)
        levels = self._rpn_over_levels(feats)
        level_scores = [L.fg_prob(c) for c, _, _ in levels]
        level_deltas = [b for _, b, _ in levels]
        anchors_l = [a for _, _, a in levels]
        return jax.vmap(
            lambda ls, ld, info: propose_fpn(
                list(ls), list(ld), anchors_l, info[0], info[1], info[2],
                pre_nms_top_n=te.RPN_PRE_NMS_TOP_N,
                post_nms_top_n=te.RPN_POST_NMS_TOP_N,
                nms_thresh=te.RPN_NMS_THRESH, min_size=te.RPN_MIN_SIZE,
                use_pallas=te.CXX_PROPOSAL),
        )(tuple(level_scores), tuple(level_deltas), im_info)
