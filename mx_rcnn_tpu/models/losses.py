"""Detection losses — explicit-mask equivalents of the reference's loss ops.

The reference uses MXNet loss ops with in-op masking
(``rcnn/symbol/symbol_resnet.py`` / ``symbol_vgg.py``):

* RPN cls:  ``SoftmaxOutput(use_ignore=True, ignore_label=-1,
  normalization='valid')`` — cross-entropy over {bg, fg}, ignoring −1
  labels, normalized by the count of non-ignored anchors.
* RPN bbox: ``smooth_l1(sigma=3)`` · ``MakeLoss(grad_scale=1/RPN_BATCH_SIZE)``.
* RCNN cls: ``SoftmaxOutput(normalization='batch')`` over classes.
* RCNN bbox: ``smooth_l1(sigma=1)`` · ``MakeLoss(grad_scale=1/BATCH_ROIS)``.

Here they are pure-JAX scalar losses with explicit masks (SURVEY §2.2 —
"pure-JAX losses with explicit masks, no kernel needed").  All reductions
in float32 regardless of compute dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def softmax_ce_ignore(logits: jnp.ndarray, label: jnp.ndarray,
                      ignore_label: int = -1) -> jnp.ndarray:
    """Cross-entropy with ignored labels, ``normalization='valid'``.

    logits: (..., K); label: (...) int32, entries == ignore_label excluded
    from both numerator and denominator.
    Returns a scalar.
    """
    logits = logits.astype(jnp.float32)
    valid = (label != ignore_label)
    safe_label = jnp.where(valid, label, 0)
    ce = _ce_rows(logits, safe_label)
    num = jnp.sum(jnp.where(valid, ce, 0.0))
    den = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    return num / den


def _ce_rows(logits: jnp.ndarray, label: jnp.ndarray) -> jnp.ndarray:
    """Per-row cross-entropy −logp[label] without ``take_along_axis``.

    TPU gathers serialize (profiled 1.2 ms/step on the FPN graph's 155 520
    RPN rows vs ~0.05 ms for the replacements), and a trailing K=2 axis
    wastes 126 of 128 lanes in every op that touches it.  K == 2 uses the
    binary logit-difference form on (N,)-shaped data; K > 2 contracts
    log-softmax against a one-hot — lane-parallel compute XLA fuses into
    the surrounding loss graph.
    """
    k = logits.shape[-1]
    if k == 2:
        z = logits[..., 1] - logits[..., 0]
        # −logp1 = softplus(−z), −logp0 = softplus(z)
        return jnp.where(label == 1, jax.nn.softplus(-z), jax.nn.softplus(z))
    logp = jax.nn.log_softmax(logits, axis=-1)
    onehot = jax.nn.one_hot(label, k, dtype=logp.dtype)
    return -jnp.sum(logp * onehot, axis=-1)


def softmax_ce_weighted(logits: jnp.ndarray, label: jnp.ndarray,
                        weight: jnp.ndarray) -> jnp.ndarray:
    """Cross-entropy normalized by batch size (``normalization='batch'``),
    with per-row weights (0 drops degenerate rows).  Returns a scalar."""
    logits = logits.astype(jnp.float32)
    ce = _ce_rows(logits, label)
    # normalization='batch': divide by the static row count (B·BATCH_ROIS)
    return jnp.sum(ce * weight) / float(weight.size)


def smooth_l1(pred: jnp.ndarray, target: jnp.ndarray, weight: jnp.ndarray,
              sigma: float, norm: float) -> jnp.ndarray:
    """Masked smooth-L1, summed and divided by ``norm``.

    Matches the reference's ``mx.symbol.smooth_l1(scalar=sigma)`` followed by
    ``MakeLoss(grad_scale=1/norm)``: elementwise
      0.5·(σx)²        if |x| < 1/σ²
      |x| − 0.5/σ²     otherwise
    with x = weight · (pred − target).
    """
    pred = pred.astype(jnp.float32)
    target = target.astype(jnp.float32)
    x = weight * (pred - target)
    s2 = sigma * sigma
    ax = jnp.abs(x)
    val = jnp.where(ax < 1.0 / s2, 0.5 * s2 * x * x, ax - 0.5 / s2)
    return jnp.sum(val) / norm


def mask_bce(logits: jnp.ndarray, target: jnp.ndarray,
             weight: jnp.ndarray) -> jnp.ndarray:
    """Per-pixel sigmoid BCE for the mask head (Mask R-CNN), averaged over
    the pixels of weighted (fg) RoIs only.  logits/target: (R, M, M);
    weight: (R,) 1 on fg rois."""
    logits = logits.astype(jnp.float32)
    per_pix = jnp.maximum(logits, 0) - logits * target + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    per_roi = per_pix.mean(axis=(-1, -2))
    num = jnp.sum(per_roi * weight)
    den = jnp.maximum(jnp.sum(weight), 1.0)
    return num / den


def fg_prob(cls_logits: jnp.ndarray) -> jnp.ndarray:
    """``softmax(logits, -1)[..., 1]`` for the K=2 RPN objectness head,
    computed as ``sigmoid(l1 − l0)`` on (N,)-shaped data — algebraically
    identical (softmax2[1] = e^{l1}/(e^{l0}+e^{l1})), but avoids every
    pass over a trailing K=2 axis that wastes 126 of 128 lanes (the same
    layout tax `_ce_rows` documents)."""
    logits = cls_logits.astype(jnp.float32)
    return jax.nn.sigmoid(logits[..., 1] - logits[..., 0])
