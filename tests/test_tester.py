"""End-to-end eval-loop smoke: Predictor → im_detect → pred_eval on the
synthetic dataset (random params — checks plumbing and layouts, not mAP),
plus generate_proposals for the alternate-training path."""

import dataclasses

import jax
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import SyntheticDataset, TestLoader
from mx_rcnn_tpu.eval import Predictor, generate_proposals, im_detect, pred_eval
from mx_rcnn_tpu.models import build_model, init_params


def tiny_cfg():
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TEST__RPN_PRE_NMS_TOP_N=300, TEST__RPN_POST_NMS_TOP_N=32,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((96, 128),), MAX_GT=8)
    return cfg.replace(network=net, tpu=tpu)


def test_pred_eval_synthetic_smoke():
    cfg = tiny_cfg()
    ds = SyntheticDataset(num_images=3, height=96, width=128)
    roidb = ds.gt_roidb()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (96, 128))
    pred = Predictor(model, params, cfg)
    loader = TestLoader(roidb, cfg, batch_size=2)

    # im_detect layout
    batch = next(iter(loader))
    dets = im_detect(pred, batch)
    assert len(dets) == 2
    scores, boxes, valid = dets[0]
    R, K = cfg.TEST.RPN_POST_NMS_TOP_N, cfg.NUM_CLASSES
    assert scores.shape == (R, K) and boxes.shape == (R, 4 * K)
    # boxes mapped back to original frame: within original image bounds
    eh, ew, s = np.asarray(batch["im_info"][0])
    assert boxes.max() <= max(eh, ew) / s + 1

    stats = pred_eval(pred, TestLoader(roidb, cfg, batch_size=2), ds)
    assert "mAP" in stats and 0.0 <= stats["mAP"] <= 1.0


def test_generate_proposals_fills_roidb():
    cfg = tiny_cfg()
    ds = SyntheticDataset(num_images=3, height=96, width=128)
    roidb = ds.gt_roidb()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (96, 128))
    pred = Predictor(model, params, cfg)
    out = generate_proposals(pred, TestLoader(roidb, cfg, batch_size=2), ds, roidb)
    for rec in out:
        assert "proposals" in rec
        p = rec["proposals"]
        assert p.ndim == 2 and p.shape[1] == 4
        # original-frame coords
        assert p[:, 2].max() <= rec["width"] + 1
