"""Train-layer tests: optimizer mask/schedule, checkpoint fold contract, and
the sharded train step on the virtual 8-device CPU mesh (SURVEY §4 pyramid
item 4 — mesh exercised without a pod)."""

import dataclasses
import pytest

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.parallel import make_mesh, shard_batch
from mx_rcnn_tpu.train import (MetricBank, create_train_state, fixed_param_mask,
                               make_lr_schedule, make_train_step)
from mx_rcnn_tpu.train.checkpoint import (denormalize_for_save, load_params_npz,
                                          normalize_for_train, save_params_npz)


def tiny_cfg():
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4)
    return cfg.replace(network=net, tpu=tpu)


def make_batch(B, H=64, W=96, G=4, seed=0):
    rng = np.random.RandomState(seed)
    gtb = np.zeros((B, G, 4), np.float32)
    gtv = np.zeros((B, G), bool)
    gtc = np.zeros((B, G), np.int32)
    for b in range(B):
        for g in range(2):
            x1, y1 = rng.randint(0, W - 40), rng.randint(0, H - 40)
            gtb[b, g] = (x1, y1, x1 + rng.randint(20, 39), y1 + rng.randint(20, 39))
            gtc[b, g] = rng.randint(1, 21)
            gtv[b, g] = True
    return dict(
        images=rng.randn(B, H, W, 3).astype(np.float32),
        im_info=np.tile(np.asarray([[H, W, 1.0]], np.float32), (B, 1)),
        gt_boxes=gtb, gt_classes=gtc, gt_valid=gtv,
    )


def test_fixed_param_mask_prefixes():
    cfg = tiny_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    mask = fixed_param_mask(params, cfg.network.FIXED_PARAMS)
    flat = jax.tree_util.tree_flatten_with_path(mask)[0]
    by_path = {"/".join(getattr(e, "key", str(e)) for e in p): v for p, v in flat}
    # conv1 / bn1 / stage1 frozen; all gamma/beta/mean/var frozen everywhere
    assert not by_path["backbone/conv1/kernel"]
    assert not by_path["backbone/bn1/gamma"]
    assert not any(v for k, v in by_path.items() if k.startswith("backbone/stage1/"))
    assert not any(v for k, v in by_path.items()
                   if k.rsplit("/", 1)[-1] in ("gamma", "beta", "mean", "var"))
    # stage2+ convs, rpn, heads trainable
    assert by_path["backbone/stage2/unit1/conv1/kernel"]
    assert by_path["rpn/rpn_conv_3x3/kernel"]
    assert by_path["rcnn_out/bbox_pred/kernel"]


def test_lr_schedule_multifactor_and_warmup():
    cfg = tiny_cfg()
    tr = dataclasses.replace(cfg.TRAIN, LR=0.01, LR_STEP=(2, 4), LR_FACTOR=0.1)
    sched = make_lr_schedule(cfg.replace(TRAIN=tr), steps_per_epoch=10)
    assert np.isclose(float(sched(0)), 0.01)
    assert np.isclose(float(sched(19)), 0.01)
    assert np.isclose(float(sched(20)), 1e-3)
    assert np.isclose(float(sched(40)), 1e-4)
    tr2 = dataclasses.replace(tr, WARMUP=True, WARMUP_LR=1e-4, WARMUP_STEP=5)
    sched2 = make_lr_schedule(cfg.replace(TRAIN=tr2), steps_per_epoch=10)
    assert float(sched2(0)) < 0.001
    assert np.isclose(float(sched2(5)), 0.01)
    # LR_STEP drops stay on GLOBAL steps even with warmup in front
    assert np.isclose(float(sched2(19)), 0.01)
    assert np.isclose(float(sched2(20)), 1e-3)
    assert np.isclose(float(sched2(40)), 1e-4)


def test_bbox_fold_roundtrip():
    cfg = tiny_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    saved = denormalize_for_save(params, cfg)
    # kernel scaled by stds tiled per class
    k0 = np.asarray(params["rcnn_out"]["bbox_pred"]["kernel"])
    k1 = np.asarray(saved["rcnn_out"]["bbox_pred"]["kernel"])
    stds = np.tile(np.asarray(cfg.TRAIN.BBOX_STDS), cfg.NUM_CLASSES)
    np.testing.assert_allclose(k1, k0 * stds[None, :], rtol=1e-6)
    # other layers untouched
    np.testing.assert_array_equal(
        np.asarray(params["rpn"]["rpn_conv_3x3"]["kernel"]),
        np.asarray(saved["rpn"]["rpn_conv_3x3"]["kernel"]))
    back = normalize_for_train(saved, cfg)
    np.testing.assert_allclose(
        np.asarray(back["rcnn_out"]["bbox_pred"]["kernel"]), k0, rtol=1e-5)


def test_params_npz_roundtrip(tmp_path):
    cfg = tiny_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    path = str(tmp_path / "p.npz")
    save_params_npz(path, params)
    back = load_params_npz(path)
    a = jax.tree_util.tree_flatten_with_path(params)[0]
    b = jax.tree_util.tree_flatten_with_path(back)[0]
    assert len(a) == len(b)
    for (pa, la), (pb, lb) in zip(a, b):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_checkpoint_save_resume_roundtrip(tmp_path):
    """save_epoch → load_epoch(abstract) returns an opt_state optax can
    actually consume (true state classes, not raw dicts)."""
    import jax.numpy as jnp
    from mx_rcnn_tpu.train.checkpoint import CheckpointManager

    cfg = tiny_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    state, tx, _ = create_train_state(cfg, params, steps_per_epoch=10)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save_epoch(1, state.params, cfg, opt_state=state.opt_state, step=7)

    abstract = jax.device_get(
        {"params": state.params, "opt_state": state.opt_state, "step": 0})
    r_params, r_opt, r_step = mgr.load_epoch(1, cfg, for_training=True,
                                             abstract_payload=abstract)
    assert r_step == 7
    np.testing.assert_allclose(
        np.asarray(r_params["rcnn_out"]["bbox_pred"]["kernel"]),
        np.asarray(state.params["rcnn_out"]["bbox_pred"]["kernel"]), rtol=1e-5)
    # restored opt_state must be consumable by tx.update
    grads = jax.tree.map(jnp.zeros_like, r_params)
    updates, _ = tx.update(grads, r_opt, r_params)
    assert jax.tree_util.tree_structure(updates) == \
        jax.tree_util.tree_structure(r_params)


def test_sharded_train_step_updates_and_freezes():
    """Data-parallel step over the 8-device CPU mesh: loss finite, trainable
    params move, frozen params don't, and the six metrics come out."""
    cfg = tiny_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    plan = make_mesh(data=8)
    state, tx, mask = create_train_state(cfg, params, steps_per_epoch=10)
    step = make_train_step(model, tx, plan=plan, trainable_mask=mask)

    frozen_before = np.asarray(params["backbone"]["conv1"]["kernel"])
    train_before = np.asarray(params["rpn"]["rpn_conv_3x3"]["kernel"])

    batch = make_batch(B=8)
    state = jax.device_put(state, plan.replicated())
    losses = []
    for i in range(2):
        sb = shard_batch(plan, batch)
        state, metrics = step(state, sb, jax.random.PRNGKey(i))
        m = jax.device_get(metrics)
        assert np.isfinite(m["total_loss"])
        losses.append(float(m["total_loss"]))
    for k in ("RPNAcc", "RPNLogLoss", "RPNL1Loss", "RCNNAcc", "RCNNLogLoss",
              "RCNNL1Loss"):
        assert k in m and np.isfinite(m[k])

    new_params = jax.device_get(state.params)
    np.testing.assert_array_equal(
        np.asarray(new_params["backbone"]["conv1"]["kernel"]), frozen_before)
    assert np.abs(np.asarray(new_params["rpn"]["rpn_conv_3x3"]["kernel"])
                  - train_before).max() > 0

    bank = MetricBank()
    bank.update(m)
    assert "RPNAcc" in bank.get()


def test_multislice_mesh_matches_flat_dp():
    """Hierarchical (dcn=2, data=4) multi-slice DP must produce the same
    step as the flat 8-way mesh: the global gradient mean is mesh-layout
    invariant, XLA just schedules the reduce as ICI-within-slice +
    DCN-across-slices."""
    from mx_rcnn_tpu.parallel import make_multislice_mesh

    cfg = tiny_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    batch = make_batch(B=8)

    results = []
    for plan in (make_mesh(data=8),
                 make_multislice_mesh(slices=2, data_per_slice=4)):
        state, tx, mask = create_train_state(cfg, params, steps_per_epoch=10)
        step = make_train_step(model, tx, plan=plan, trainable_mask=mask)
        state = jax.device_put(state, plan.replicated())
        for i in range(2):
            sb = shard_batch(plan, batch)
            state, metrics = step(state, sb, jax.random.PRNGKey(i))
        results.append((float(jax.device_get(metrics["total_loss"])),
                        np.asarray(state.params["rpn"]["rpn_conv_3x3"]["kernel"])))

    assert results[1][0] == pytest.approx(results[0][0], rel=1e-5)
    np.testing.assert_allclose(results[1][1], results[0][1], rtol=1e-4,
                               atol=1e-6)


def test_multislice_mesh_shapes():
    from mx_rcnn_tpu.parallel import make_multislice_mesh

    plan = make_multislice_mesh(slices=2)
    assert plan.mesh.axis_names == ("dcn", "data", "model")
    assert plan.mesh.shape["dcn"] == 2 and plan.mesh.shape["data"] == 4
    assert plan.n_data == 8 and plan.batch_axes == ("dcn", "data")
    with pytest.raises(ValueError):
        make_multislice_mesh()  # no topology and no slice count


def test_multislice_mesh_validation():
    from mx_rcnn_tpu.parallel import make_multislice_mesh

    with pytest.raises(ValueError):
        make_multislice_mesh(slices=3)  # 8 devices don't divide into 3
    with pytest.raises(ValueError):
        make_multislice_mesh(slices=0)
    with pytest.raises(ValueError, match="uses only"):
        # explicit data_per_slice smaller than the slice must not silently
        # idle chips (round-1 advisor finding)
        make_multislice_mesh(slices=2, data_per_slice=2)


def test_bf16_momentum_accumulator():
    """TRAIN.OPT_ACC_DTYPE=bfloat16 stores the momentum trace in bf16 (half
    the optimizer's HBM traffic on the momentum buffers) while params stay
    f32 master weights and the first-step update matches f32 momentum
    closely (math is f32; only the stored trace rounds)."""
    from mx_rcnn_tpu.train import make_optimizer

    cfg = tiny_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    grads = jax.tree.map(lambda p: jnp.full_like(p, 0.01), params)

    outs = {}
    for dtype in ("float32", "bfloat16"):
        c = cfg.replace(TRAIN=dataclasses.replace(cfg.TRAIN,
                                                  OPT_ACC_DTYPE=dtype))
        tx, _, _ = make_optimizer(c, steps_per_epoch=10, params=params)
        opt_state = tx.init(params)
        # TWO steps: step 1's trace is zero, so only step 2 reads the
        # stored (possibly rounded) trace back into g + mu*t
        updates, opt_state = tx.update(grads, opt_state, params)
        updates, opt_state = tx.update(grads, opt_state, params)
        outs[dtype] = jax.device_get(updates)
        traces = [l for l in jax.tree.leaves(opt_state)
                  if hasattr(l, "dtype") and l.ndim > 0]
        want = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
        assert traces and all(t.dtype == want for t in traces), dtype
        # updates (and therefore params) stay f32
        assert all(u.dtype == jnp.float32
                   for u in jax.tree.leaves(outs[dtype]))

    flat32 = jax.tree.leaves(outs["float32"])
    flat16 = jax.tree.leaves(outs["bfloat16"])
    for a, b in zip(flat32, flat16):
        np.testing.assert_allclose(a, b, rtol=1e-2, atol=1e-6)


def test_multi_step_matches_sequential():
    """The inductive contract: make_multi_train_step(k=1) must equal one
    single-step call on the same batch/key (same _build_step body; only
    the scan driver differs), and k=3 must advance the carried state
    sanely.  k>1 NUMERIC parity with a sequential driver is chaotic by
    design and deliberately not asserted: the scan body is a different
    compiled program, an ulp difference can flip a discrete top-k/NMS/
    sampling choice in step 2+ and amplify (measured: 2.7e-5 params
    drift at step 1 grows to 1.6e-3 on the bbox head by step 3)."""
    from mx_rcnn_tpu.train import make_multi_train_step, make_train_step

    cfg = tiny_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    state0, tx, mask = create_train_state(cfg, params, steps_per_epoch=10)
    batches = [make_batch(1, seed=s) for s in range(3)]
    key = jax.random.PRNGKey(42)

    step = make_train_step(model, tx, trainable_mask=mask, donate=False)
    seq1, _ = step(state0, batches[0], jax.random.fold_in(key, 0))

    multi1 = make_multi_train_step(model, tx, 1, trainable_mask=mask,
                                   donate=False)
    got1, m1 = multi1(
        state0, jax.tree.map(lambda x: np.stack([x]), batches[0]), key)
    assert int(got1.step) == int(seq1.step) == 1
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-4, atol=1e-5),
        got1.params, seq1.params)

    multi3 = make_multi_train_step(model, tx, 3, trainable_mask=mask,
                                   donate=False)
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *batches)
    got3, metrics = multi3(state0, stacked, key)
    assert int(got3.step) == 3
    assert np.isfinite(float(metrics["total_loss"]))
    moved = np.asarray(got3.params["rpn"]["rpn_conv_3x3"]["kernel"])
    assert not np.allclose(
        moved, np.asarray(state0.params["rpn"]["rpn_conv_3x3"]["kernel"]))


def test_fit_steps_per_dispatch_smoke():
    """fit(steps_per_dispatch=2) over a MIXED-ORIENTATION 8-step epoch:
    scanned dispatches, the shape-change bucket flush (groups must be
    shape-homogeneous — a landscape→portrait boundary flushes a partial
    group through the single-step program), and the epoch remainder.
    Step counter advances by exactly steps_per_epoch and training
    updates the trainable params."""
    from mx_rcnn_tpu.data import AnchorLoader, SyntheticDataset

    cfg = tiny_cfg()
    cfg = cfg.replace(TRAIN=dataclasses.replace(cfg.TRAIN, FLIP=False))
    cfg = cfg.replace(network=dataclasses.replace(
        cfg.network, PIXEL_STDS=(127.0, 127.0, 127.0)))
    land = SyntheticDataset(num_images=5, num_classes=cfg.NUM_CLASSES,
                            height=64, width=96, seed=0).gt_roidb()
    port = SyntheticDataset(num_images=3, num_classes=cfg.NUM_CLASSES,
                            height=96, width=64, seed=1).gt_roidb()
    roidb = land + port
    loader = AnchorLoader(roidb, cfg, batch_size=1, shuffle=True, seed=0)
    assert len({b["images"].shape[1:3] for b in loader}) == 2  # both buckets
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    before = np.asarray(params["rpn"]["rpn_conv_3x3"]["kernel"]).copy()

    from mx_rcnn_tpu.train import fit

    state = fit(cfg, model, params, loader, begin_epoch=0, end_epoch=1,
                frequent=1, steps_per_dispatch=2)
    assert int(jax.device_get(state.step)) == loader.steps_per_epoch
    after = np.asarray(jax.device_get(
        state.params["rpn"]["rpn_conv_3x3"]["kernel"]))
    assert not np.allclose(before, after)
