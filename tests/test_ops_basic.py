"""Oracle tests for anchors, box codecs, IoU (SURVEY §4 pyramid level 1)."""

import numpy as np
import jax.numpy as jnp

from mx_rcnn_tpu.ops import anchors as A
from mx_rcnn_tpu.ops import boxes as B
from tests import oracles


def test_generate_anchors_matches_oracle():
    got = A.generate_anchors(16, (0.5, 1.0, 2.0), (8, 16, 32))
    want = oracles.generate_anchors_oracle(16, (0.5, 1.0, 2.0), (8, 16, 32))
    assert got.shape == (9, 4)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_generate_anchors_known_values():
    # the canonical base-16 anchors: first anchor (ratio .5, scale 8)
    a = A.generate_anchors()
    # widths/heights follow w*h ≈ (16*scale)^2 with aspect ratio
    w = a[:, 2] - a[:, 0] + 1
    h = a[:, 3] - a[:, 1] + 1
    np.testing.assert_allclose((w * h)[4], (16 * 16) ** 2, rtol=0.1)  # ratio 1 scale 16
    # centers identical for all
    cx = a[:, 0] + 0.5 * (w - 1)
    np.testing.assert_allclose(cx, cx[0])


def test_all_anchors_grid():
    base = A.generate_anchors()
    grid = A.all_anchors(2, 3, 16, base)
    assert grid.shape == (2 * 3 * 9, 4)
    # cell (0,0) anchors = base anchors
    np.testing.assert_allclose(grid[:9], base)
    # cell (y=1, x=2) offset by (32, 16)
    np.testing.assert_allclose(grid[(1 * 3 + 2) * 9], base[0] + np.array([32, 16, 32, 16]))


def test_bbox_transform_roundtrip(rng):
    ex = rng.rand(50, 4) * 100
    ex[:, 2:] += ex[:, :2] + 5
    gt = rng.rand(50, 4) * 100
    gt[:, 2:] += gt[:, :2] + 5
    deltas = B.bbox_transform(jnp.asarray(ex), jnp.asarray(gt))
    np.testing.assert_allclose(deltas, oracles.bbox_transform_oracle(ex, gt), rtol=1e-4, atol=1e-4)
    # decode(encode) == identity
    rec = B.bbox_pred(jnp.asarray(ex), deltas)
    np.testing.assert_allclose(rec, gt, rtol=1e-3, atol=1e-2)


def test_bbox_pred_multiclass(rng):
    boxes = rng.rand(20, 4) * 50
    boxes[:, 2:] += boxes[:, :2] + 3
    deltas = rng.randn(20, 12) * 0.2
    got = B.bbox_pred(jnp.asarray(boxes), jnp.asarray(deltas))
    want = oracles.bbox_pred_oracle(boxes, deltas)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-3)


def test_clip_boxes(rng):
    boxes = rng.randn(30, 8) * 300
    got = B.clip_boxes(jnp.asarray(boxes), 200, 300)
    assert (np.asarray(got[:, 0::4]) <= 299).all() and (np.asarray(got) >= 0).all()
    assert (np.asarray(got[:, 1::4]) <= 199).all()


def test_bbox_overlaps(rng):
    boxes = rng.rand(40, 4) * 100
    boxes[:, 2:] += boxes[:, :2] + 1
    query = rng.rand(7, 4) * 100
    query[:, 2:] += query[:, :2] + 1
    got = B.bbox_overlaps(jnp.asarray(boxes), jnp.asarray(query))
    want = oracles.iou_oracle(boxes, query)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_max_pool_2x2_matches_reduce_window(rng):
    from flax import linen as nn

    from mx_rcnn_tpu.ops.pool import max_pool_2x2

    for shape in [(1, 8, 12, 3), (2, 7, 9, 4), (1, 1, 1, 2)]:
        x = jnp.asarray(rng.randn(*shape).astype(np.float32))
        want = nn.max_pool(x, (2, 2), strides=(2, 2))
        got = max_pool_2x2(x)
        assert got.shape == want.shape, (shape, got.shape, want.shape)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_max_pool_2x2_grad_ties_split():
    """Documented divergence (ops/pool.py): tie gradients split evenly
    (reduce_window's select-and-scatter routes all to the first max)."""
    import jax

    from mx_rcnn_tpu.ops.pool import max_pool_2x2

    x = jnp.full((1, 2, 2, 1), 3.0)  # one window, all four tied
    g = jax.grad(lambda v: max_pool_2x2(v).sum())(x)
    np.testing.assert_allclose(np.asarray(g).ravel(), [0.25] * 4)
    # no ties: gradient lands on the unique argmax
    x2 = jnp.asarray(np.arange(4, dtype=np.float32).reshape(1, 2, 2, 1))
    g2 = jax.grad(lambda v: max_pool_2x2(v).sum())(x2)
    np.testing.assert_allclose(np.asarray(g2).ravel(), [0, 0, 0, 1])
