"""Data-layer + VOC-eval tests: image bucketing, imdb contract, loaders
over the synthetic dataset, voc_eval oracle cases, COCO json roidb."""

import json
import os

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import SyntheticDataset
from mx_rcnn_tpu.data.image import bucket_shape, compute_scale, resize_to_bucket
from mx_rcnn_tpu.data.loader import AnchorLoader, ROIIter, TestLoader
from mx_rcnn_tpu.eval.voc_eval import voc_ap, voc_eval


def small_cfg(**kw):
    cfg = generate_config("resnet50", "PascalVOC", **kw)
    import dataclasses
    return cfg.replace(tpu=dataclasses.replace(cfg.tpu, SCALES=((128, 256),),
                                               MAX_GT=8))


# --- image geometry ---------------------------------------------------------

def test_compute_scale_reference_rule():
    # short side to 600 unless long side would exceed 1000
    assert np.isclose(compute_scale(480, 640, (600, 1000)), 600 / 480)
    # elongated: long side caps
    assert np.isclose(compute_scale(300, 900, (600, 1000)), 1000 / 900)


def test_bucket_shape_orientation_and_stride():
    assert bucket_shape((600, 1000), 32, landscape=True) == (608, 1024)
    assert bucket_shape((600, 1000), 32, landscape=False) == (1024, 608)
    assert bucket_shape((600, 1000), 16, landscape=True) == (608, 1008)


def test_resize_to_bucket_pads_and_reports_effective():
    im = np.ones((480, 640, 3), np.float32)
    out, s, (eh, ew) = resize_to_bucket(im, (128, 256), 32)
    assert out.shape == (128, 256, 3)
    assert np.isclose(s, 128 / 480)
    assert eh == 128 and ew == int(round(640 * s))
    # padding is zero, content is nonzero
    assert out[:eh, :ew].min() > 0
    assert np.all(out[:, ew:] == 0)


# --- synthetic dataset + loaders -------------------------------------------

def test_synthetic_roidb_contract_and_flip():
    ds = SyntheticDataset(num_images=6, height=120, width=160)
    roidb = ds.gt_roidb()
    assert len(roidb) == 6
    r = roidb[0]
    for k in ("image", "height", "width", "boxes", "gt_classes",
              "gt_overlaps", "max_classes", "max_overlaps", "flipped"):
        assert k in r
    flipped = ds.append_flipped_images(roidb)
    assert len(flipped) == 12
    f = flipped[6]
    assert f["flipped"]
    # x-mirror: x1' = W - x2 - 1
    np.testing.assert_allclose(f["boxes"][:, 0],
                               r["width"] - roidb[0]["boxes"][:, 2] - 1)
    assert (f["boxes"][:, 2] >= f["boxes"][:, 0]).all()


def test_anchor_loader_batches():
    cfg = small_cfg()
    ds = SyntheticDataset(num_images=10, height=120, width=160)
    roidb = ds.gt_roidb()
    loader = AnchorLoader(roidb, cfg, batch_size=4, shuffle=True, seed=0)
    assert len(loader) == 3  # ceil(10/4) with wrap
    batches = list(loader)
    assert len(batches) == 3
    b = batches[0]
    # resnet presets ship images host-space-to-depth'd (HOST_S2D):
    # (128, 256, 3) bucket -> (64, 128, 12)
    assert b["images"].shape == (4, 64, 128, 12)
    assert b["im_info"].shape == (4, 3)
    assert b["gt_boxes"].shape == (4, 8, 4)
    assert b["gt_valid"].any()
    # gt scaled into the resized frame and inside effective extent
    for i in range(4):
        eh, ew, s = b["im_info"][i]
        gb = b["gt_boxes"][i][b["gt_valid"][i]]
        assert (gb[:, 2] <= ew - 1 + 1e-3).all()
        assert (gb[:, 3] <= eh - 1 + 1e-3).all()


def test_test_loader_padding_and_indices():
    cfg = small_cfg()
    ds = SyntheticDataset(num_images=5, height=120, width=160)
    loader = TestLoader(ds.gt_roidb(), cfg, batch_size=2)
    batches = list(loader)
    assert len(batches) == 3
    last = batches[-1]
    assert last["batch_valid"].tolist() == [True, False]
    assert last["indices"].tolist() == [4, 4]


def test_roi_iter_ships_proposals():
    cfg = small_cfg()
    ds = SyntheticDataset(num_images=4, height=120, width=160)
    roidb = ds.gt_roidb()
    for r in roidb:
        r["proposals"] = r["boxes"].copy()  # perfect proposals
    loader = ROIIter(roidb, cfg, batch_size=2, shuffle=False)
    b = next(iter(loader))
    P = cfg.TRAIN.RPN_POST_NMS_TOP_N
    assert b["rois"].shape == (2, P, 4)
    assert b["roi_valid"].sum() > 0


# --- voc_eval oracles -------------------------------------------------------

def test_voc_ap_known_curves():
    # perfect detector: P=1 at all recalls
    rec = np.array([0.5, 1.0])
    prec = np.array([1.0, 1.0])
    assert np.isclose(voc_ap(rec, prec, use_07_metric=False), 1.0)
    assert np.isclose(voc_ap(rec, prec, use_07_metric=True), 1.0)


def _recs_one_gt():
    return {0: [{"name": "car", "difficult": 0, "bbox": [10, 10, 50, 50]}]}


def test_voc_eval_perfect_and_miss():
    # one gt, one perfect det
    dets = [np.array([[10, 10, 50, 50, 0.9]], np.float32)]
    assert np.isclose(voc_eval(dets, _recs_one_gt(), "car"), 1.0)
    # detection elsewhere -> AP 0
    dets = [np.array([[200, 200, 240, 240, 0.9]], np.float32)]
    assert voc_eval(dets, _recs_one_gt(), "car") == 0.0


def test_voc_eval_duplicate_is_fp():
    # two dets on the same gt: second is a duplicate FP -> precision drops
    dets = [np.array([[10, 10, 50, 50, 0.9],
                      [11, 11, 51, 51, 0.8]], np.float32)]
    ap = voc_eval(dets, _recs_one_gt(), "car", use_07_metric=False)
    assert np.isclose(ap, 1.0)  # recall 1 reached at rank 1; dup after
    # reversed scores: dup ranked first consumes nothing (same gt), still
    # recall 1 at rank 2 but precision 0.5 there
    dets = [np.array([[11, 11, 51, 51, 0.95],
                      [10, 10, 50, 50, 0.9]], np.float32)]
    ap2 = voc_eval(dets, _recs_one_gt(), "car", use_07_metric=False)
    assert np.isclose(ap2, 1.0)


def test_voc_eval_difficult_excluded():
    recs = {0: [{"name": "car", "difficult": 1, "bbox": [10, 10, 50, 50]},
                {"name": "car", "difficult": 0, "bbox": [100, 100, 150, 150]}]}
    # det on the difficult gt: neither TP nor FP; det on normal gt: TP
    dets = [np.array([[10, 10, 50, 50, 0.9],
                      [100, 100, 150, 150, 0.8]], np.float32)]
    assert np.isclose(voc_eval(dets, recs, "car"), 1.0)


# --- COCO dataset from a fake json -----------------------------------------

@pytest.fixture
def fake_coco(tmp_path):
    root = tmp_path / "coco"
    (root / "annotations").mkdir(parents=True)
    (root / "val2017").mkdir()
    ann = {
        "images": [{"id": 7, "file_name": "a.jpg", "height": 100, "width": 120},
                   {"id": 3, "file_name": "b.jpg", "height": 80, "width": 90}],
        "categories": [{"id": 18, "name": "dog"}, {"id": 1, "name": "person"}],
        "annotations": [
            {"id": 1, "image_id": 7, "category_id": 18,
             "bbox": [10, 10, 30, 40], "area": 1200, "iscrowd": 0},
            {"id": 2, "image_id": 7, "category_id": 1,
             "bbox": [50, 5, 20, 20], "area": 400, "iscrowd": 0},
            {"id": 3, "image_id": 3, "category_id": 18,
             "bbox": [0, 0, 50, 50], "area": 2500, "iscrowd": 1},
        ],
    }
    with open(root / "annotations" / "instances_val2017.json", "w") as f:
        json.dump(ann, f)
    return str(root)


def test_coco_dataset_roidb(fake_coco):
    from mx_rcnn_tpu.data.coco_dataset import COCODataset

    ds = COCODataset("val2017", fake_coco, fake_coco)
    assert ds.num_images == 2
    assert ds.classes == ["__background__", "person", "dog"]
    roidb = ds._build_gt_roidb()
    # images sorted by id: index 0 is id 3 (crowd-only -> no boxes)
    assert len(roidb[0]["boxes"]) == 0
    assert len(roidb[1]["boxes"]) == 2
    # xywh -> xyxy
    np.testing.assert_allclose(roidb[1]["boxes"][0], [10, 10, 39, 49])
    assert roidb[1]["gt_classes"].tolist() == [2, 1]

    dets = [None,
            [np.zeros((0, 5)), np.array([[50, 5, 69, 24, 0.7]])],
            [np.zeros((0, 5)), np.array([[10, 10, 39, 49, 0.9]])]]
    res = ds.detections_to_coco(dets)
    assert len(res) == 2
    by_cat = {r["category_id"]: r for r in res}
    assert by_cat[18]["image_id"] == 7
    np.testing.assert_allclose(by_cat[18]["bbox"], [10, 10, 30, 40])
