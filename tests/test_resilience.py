"""Fault-tolerance subsystem (train/resilience.py + its trainer/
checkpoint/loader wiring), driven by the tests/faults.py injectors:
step checkpoints + exact mid-epoch auto-resume, NaN sentinel policies,
loader fault isolation, prefetch watchdog, preemption, I/O retry."""

import dataclasses
import glob
import json
import threading

import jax
import numpy as np
import pytest

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import AnchorLoader, SyntheticDataset
from mx_rcnn_tpu.data.loader import _load_record_isolated, _Prefetcher
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.telemetry.report import (RECOVERY_COUNTERS, aggregate,
                                          load_events, render_table)
from mx_rcnn_tpu.train import NonFiniteLossError, ResilienceOptions, fit
from mx_rcnn_tpu.train.checkpoint import CheckpointManager
from mx_rcnn_tpu.train.resilience import (decode_step_key, encode_step_key,
                                          retry_io)

from .faults import (NanBatchLoader, SignalAtBatchLoader, corrupt_record,
                     flaky_saves, hang_until)


def tiny_cfg():
    # test_fit_resume's config, verbatim — the persistent compile cache
    # makes every fit() here reuse its compiled step programs
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4),
                              PIXEL_STDS=(127.0, 127.0, 127.0))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4)
    return cfg.replace(network=net, tpu=tpu)


def tiny_data(n_images=8, seed=0, shuffle=False, cfg=None):
    cfg = cfg or tiny_cfg()
    ds = SyntheticDataset(num_images=n_images, num_classes=cfg.NUM_CLASSES,
                          height=64, width=96)
    roidb = ds.gt_roidb()
    loader = AnchorLoader(roidb, cfg, batch_size=2, shuffle=shuffle,
                          seed=seed)
    return cfg, roidb, loader


def tiny_model(cfg):
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    return model, params


def leaves(params):
    return [np.asarray(jax.device_get(x))
            for x in jax.tree_util.tree_leaves(params)]


# -- unit level ------------------------------------------------------------


def test_step_key_roundtrip():
    assert decode_step_key(encode_step_key(3, 1234)) == (3, 1234)
    assert decode_step_key(encode_step_key(0, 0)) == (0, 0)
    with pytest.raises(ValueError):
        encode_step_key(1, 10 ** 7)  # an epoch can't run that many batches


def test_resilience_options_validation():
    with pytest.raises(ValueError):
        ResilienceOptions(nan_policy="explode")
    with pytest.raises(ValueError):
        ResilienceOptions(save_every_n_steps=-1)
    assert not ResilienceOptions().enabled
    ropt = ResilienceOptions(nan_policy="skip")
    assert ropt.enabled and ropt.sentinel and ropt.skip_nonfinite
    # from_args tolerates namespaces without the flags (alternate stages)
    assert not ResilienceOptions.from_args(object()).enabled


def test_retry_io_backoff_and_exhaustion():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert retry_io(flaky, what="t", retries=3, backoff_s=0.001) == "ok"
    assert calls["n"] == 3
    with pytest.raises(OSError):
        retry_io(lambda: (_ for _ in ()).throw(OSError("always")),
                 what="t", retries=1, backoff_s=0.001)
    with pytest.raises(KeyError):  # programming errors are NOT retried
        retry_io(lambda: {}["x"], what="t", retries=3, backoff_s=0.001)


def test_load_epoch_missing_lists_present(tmp_path):
    cfg = tiny_cfg()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    with pytest.raises(FileNotFoundError, match=r"epochs present: none"):
        mgr.load_epoch(5, cfg)
    mgr.save_epoch(1, {"w": np.ones(2, np.float32)}, cfg)
    with pytest.raises(FileNotFoundError, match=r"epochs present: \[1\]"):
        mgr.load_epoch(5, cfg)


def test_step_checkpoint_roundtrip_and_resume_point(tmp_path):
    cfg = tiny_cfg()
    mgr = CheckpointManager(str(tmp_path / "ck"), step_keep=2)
    assert mgr.latest_resume_point() is None
    params = {"w": np.arange(4, dtype=np.float32)}
    key = np.asarray(jax.random.PRNGKey(7))
    mgr.save_step(1, 5, params, cfg, opt_state={"m": np.ones(4, np.float32)},
                  step=9, rng_key=key)
    assert mgr.latest_step_checkpoint() == (1, 5)
    out = mgr.load_step_checkpoint(1, 5)
    np.testing.assert_array_equal(out["params"]["w"], params["w"])
    np.testing.assert_array_equal(out["rng_key"], key)
    assert (out["step"], out["epoch"], out["consumed"]) == (9, 1, 5)
    with pytest.raises(FileNotFoundError, match="present"):
        mgr.load_step_checkpoint(2, 2)
    # a finished epoch beats its own mid-epoch saves; a newer step wins
    mgr.save_epoch(2, params, cfg)
    assert mgr.latest_resume_point() == ("epoch", 2, 0)
    mgr.save_step(2, 3, params, cfg)
    assert mgr.latest_resume_point() == ("step", 2, 3)
    # rolling window: a third step save evicts the oldest (step_keep=2)
    mgr.save_step(2, 6, params, cfg)
    assert mgr.latest_step_checkpoint() == (2, 6)
    with pytest.raises(FileNotFoundError):
        mgr.load_step_checkpoint(1, 5)


# -- loader fault isolation + watchdog + close -----------------------------


def test_bad_record_substituted_and_counted(tmp_path):
    cfg, roidb, loader = tiny_data(n_images=8)
    corrupt_record(roidb, 2)
    telemetry.configure(str(tmp_path), rank=0, world=1)
    try:
        batches = list(loader)
    finally:
        telemetry.shutdown()
    assert len(batches) == loader.steps_per_epoch
    for b in batches:
        assert np.isfinite(b["images"]).all()
    summary = aggregate(load_events([str(tmp_path)]))
    assert summary["counters"]["loader/bad_record"] == 1
    # the recovery section of the report names it
    assert "loader/bad_record" in render_table(summary)
    assert "loader/bad_record" in RECOVERY_COUNTERS


def test_systemic_breakage_raises():
    cfg, roidb, loader = tiny_data(n_images=8)
    for i in range(len(roidb)):
        corrupt_record(roidb, i)
    with pytest.raises(RuntimeError, match="systemic"):
        list(loader)


def test_load_record_isolated_consecutive_state():
    cfg, roidb, _ = tiny_data(n_images=8)
    corrupt_record(roidb, 0)
    state = [0]
    j, sample = _load_record_isolated(roidb, 0, cfg, (64, 96), state=state)
    assert j == 1  # deterministic neighbor substitution
    assert state[0] == 0  # success resets the consecutive count
    assert sample["images"].shape[0] > 0


def test_prefetcher_close_joins_thread():
    p = _Prefetcher(iter(range(100)), depth=2)
    it = iter(p)
    assert next(it) == 0
    p.close()
    assert not p._t.is_alive()


def test_prefetcher_watchdog_diagnostic():
    release = threading.Event()
    p = _Prefetcher(hang_until(release, [1, 2]), depth=2, watchdog_s=0.3)
    try:
        assert p._get() == 1
        assert p._get() == 2
        with pytest.raises(RuntimeError, match="producer thread alive"):
            p._get()
    finally:
        release.set()
        p.close()
    assert not p._t.is_alive()


def test_epoch_plan_fast_forward_exact():
    """advance_epochs + skip_next replay the identical (indices, scale)
    tail the uninterrupted loader would have produced."""
    cfg = tiny_cfg()
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96), (96, 64)))
    cfg = cfg.replace(tpu=tpu)  # >1 scale: the plan draws scale RNG too
    cfg, roidb, _ = tiny_data(n_images=12, cfg=cfg)
    a = AnchorLoader(roidb, cfg, batch_size=2, shuffle=True, seed=7)
    plans = [a._take_epoch_plan() for _ in range(2)]
    b = AnchorLoader(roidb, cfg, batch_size=2, shuffle=True, seed=7)
    b.advance_epochs(1)
    b.skip_next(3)
    tail = b._take_epoch_plan()
    want = plans[1][3:]
    assert len(tail) == len(want)
    for (got_idx, got_scale), (want_idx, want_scale) in zip(tail, want):
        np.testing.assert_array_equal(got_idx, want_idx)
        assert got_scale == want_scale
    # the skip is one-shot: the next epoch is full length again
    assert len(b._take_epoch_plan()) == len(plans[0])
    b.skip_next(10 ** 6)
    with pytest.raises(ValueError, match="exceeds"):
        b._take_epoch_plan()


# -- fit()-level: sentinel policies, preemption, exact resume --------------


def test_nan_halt_dumps_and_raises(tmp_path):
    cfg, _, loader = tiny_data(n_images=8)
    model, params = tiny_model(cfg)
    prefix = str(tmp_path / "ck")
    with pytest.raises(NonFiniteLossError, match="policy=halt"):
        fit(cfg, model, params, NanBatchLoader(loader, 1),
            begin_epoch=0, end_epoch=1, prefix=prefix, frequent=1,
            resilience=ResilienceOptions(nan_policy="halt"))
    dumps = glob.glob(str(tmp_path / "ck" / "nan_dump_*.json"))
    assert dumps, "halt policy must leave a diagnostic dump"
    doc = json.load(open(dumps[0]))
    assert doc["epoch"] == 0 and "metrics" in doc


def test_nan_skip_keeps_params_finite(tmp_path):
    cfg, _, loader = tiny_data(n_images=8)
    model, params = tiny_model(cfg)
    state = fit(cfg, model, params, NanBatchLoader(loader, 1),
                begin_epoch=0, end_epoch=1, frequent=1,
                telemetry_dir=str(tmp_path / "tel"),
                resilience=ResilienceOptions(nan_policy="skip"))
    for leaf in leaves(state.params):
        assert np.isfinite(leaf).all()
    summary = json.load(open(glob.glob(str(tmp_path / "tel" /
                                           "summary*.json"))[0]))
    assert summary["counters"]["train/nan_detected"] >= 1
    assert summary["counters"]["train/nan_skipped"] >= 1


def test_nan_rollback_restores_last_good(tmp_path):
    cfg, _, loader = tiny_data(n_images=8)
    model, params = tiny_model(cfg)
    prefix = str(tmp_path / "ck")
    state = fit(cfg, model, params, NanBatchLoader(loader, 2),
                begin_epoch=0, end_epoch=1, prefix=prefix, frequent=1,
                telemetry_dir=str(tmp_path / "tel"),
                resilience=ResilienceOptions(nan_policy="rollback",
                                             save_every_n_steps=1))
    for leaf in leaves(state.params):
        assert np.isfinite(leaf).all()
    summary = json.load(open(glob.glob(str(tmp_path / "tel" /
                                           "summary*.json"))[0]))
    assert summary["counters"]["train/nan_rollback"] >= 1


def test_flaky_epoch_save_retried(tmp_path):
    cfg, _, loader = tiny_data(n_images=4)
    model, params = tiny_model(cfg)
    prefix = str(tmp_path / "ck")
    with flaky_saves(1):
        fit(cfg, model, params, loader, begin_epoch=0, end_epoch=1,
            prefix=prefix, frequent=100,
            resilience=ResilienceOptions(io_backoff_s=0.01))
    assert CheckpointManager(prefix).available_epochs() == [1]


def test_preempt_then_auto_resume_matches_uninterrupted(tmp_path):
    """The acceptance path: SIGTERM mid-epoch saves a step checkpoint and
    exits cleanly; a fresh fit with auto_resume (zero manual flags)
    fast-forwards the loader, restores params/opt/rng, and finishes with
    EXACTLY the params of a run that was never interrupted."""
    n_images, end_epoch = 8, 2
    # uninterrupted reference (auto_resume on an empty prefix = fresh
    # start — pinning that contract rides along for free)
    cfg, _, loader = tiny_data(n_images=n_images)
    model, params = tiny_model(cfg)
    ref = fit(cfg, model, params, loader, begin_epoch=0,
              end_epoch=end_epoch, prefix=str(tmp_path / "ref"), frequent=1,
              resilience=ResilienceOptions(auto_resume=True))

    # interrupted run: SIGTERM while batch 2 of epoch 0 is being pulled →
    # step checkpoint at consumed=3, clean return
    cfg2, _, loader2 = tiny_data(n_images=n_images)
    model2, params2 = tiny_model(cfg2)
    prefix = str(tmp_path / "ck")
    ropt = ResilienceOptions(auto_resume=True, save_every_n_steps=100)
    mid = fit(cfg2, model2, params2, SignalAtBatchLoader(loader2, 2),
              begin_epoch=0, end_epoch=end_epoch, prefix=prefix, frequent=1,
              resilience=ropt)
    mgr = CheckpointManager(prefix)
    assert mgr.latest_resume_point() == ("step", 0, 3)
    assert int(jax.device_get(mid.step)) == 3

    # resumed run: fresh loader (fresh RandomState — a process restart),
    # same CLI surface, auto_resume picks the step checkpoint
    cfg3, _, loader3 = tiny_data(n_images=n_images)
    model3, params3 = tiny_model(cfg3)
    out = fit(cfg3, model3, params3, loader3, begin_epoch=0,
              end_epoch=end_epoch, prefix=prefix, frequent=1,
              resilience=ropt)
    assert int(jax.device_get(out.step)) == int(jax.device_get(ref.step))
    for got, want in zip(leaves(out.params), leaves(ref.params)):
        np.testing.assert_array_equal(got, want)
    # both epochs finished after resume → epoch checkpoints exist
    assert CheckpointManager(prefix).available_epochs() == [1, 2]
