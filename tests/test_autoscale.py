"""Elastic autoscaling tests (ISSUE 18).

Three layers, mirroring tests/test_fabric.py:

* **Authority decision loop** — deterministic unit tests with injected
  clock (``tick(now=...)``) over a scripted pool: predictive scale-up on
  a rising trend (the forecast acts while current demand is still under
  target), capacity-source preference (parked member → standby address →
  supervisor fork), graceful scale-down through the drain, hysteresis
  dead band, consecutive-low-tick streaks, thrash freeze, and the
  zero-recompile verification with an injected compile probe.
* **Actuation surfaces** — supervisor on-demand ``add_replica`` /
  ``retire_replica`` over fake procs (slot templating, the
  ``build_child_argv`` tail contract, drain-then-reap), the pool's
  ``adopt_handle``/``release_local`` doors, and THE satellite-3 race:
  ``/admin/register`` landing mid-park-drain must end fully routable or
  fully parked, never half-routable.
* **End-to-end chaos** — a REAL pool over REAL localhost-TCP
  subprocesses: fleet drains to min when idle, a flash crowd unparks the
  spare, routing holds throughout, and the registry counters certify
  zero recompiles across the scale events.

Plus the satellite pins: Prometheus ``fabric_member_count{state=...}``
gauges, loadgen ``--profile`` schedules, perf_gate autoscale rows, and
dormancy (autoscale off = the fabric byte-for-byte unchanged).
"""

import json
import threading
import time

import pytest

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.serve import autoscaler as ac
from mx_rcnn_tpu.serve import fabric as fb
from mx_rcnn_tpu.serve import supervisor as sv
from tests.test_fabric import (A, B, C, PoolHarness, _cleanup, _e2e_opts,
                               _free_port, _load_script, _member_proc,
                               _predict_body, _ready_pool, _wait)


@pytest.fixture(autouse=True)
def _restore_sink():
    yield
    telemetry.shutdown()


def _opts(**kw):
    base = dict(min_members=1, max_members=4, target_depth=4.0,
                forecast_s=0.0, up_cooldown_s=0.0, down_cooldown_s=0.0,
                down_after_ticks=1)
    base.update(kw)
    return ac.AutoscalerOptions(**base)


def _depth(hz, name, depth, now):
    m = hz.pool.members[name]
    m.depth = depth
    m.depth_t = now


# -- options ----------------------------------------------------------------


def test_options_validation():
    ac.AutoscalerOptions()  # defaults are a legal configuration
    with pytest.raises(ValueError):
        ac.AutoscalerOptions(min_members=-1)
    with pytest.raises(ValueError):
        ac.AutoscalerOptions(min_members=3, max_members=2)
    with pytest.raises(ValueError):
        ac.AutoscalerOptions(target_depth=0.0)
    with pytest.raises(ValueError):
        ac.AutoscalerOptions(down_headroom=1.0)  # bands must not touch
    with pytest.raises(ValueError):
        ac.AutoscalerOptions(down_after_ticks=0)


# -- decision loop (fake clock, scripted pool) ------------------------------


def test_predictive_scale_up_on_rising_trend():
    """THE forecast pin: demand 2 is comfortably under target 4, but a
    +1/s slope through a 10s look-ahead forecasts 12 — the authority
    must scale BEFORE the queue is deep, because capacity takes seconds
    a flash crowd doesn't grant."""
    hz = _ready_pool({A: 1}, now=100.0)
    a = ac.CapacityAuthority(
        hz.pool, standby=[B], compile_probe=lambda: 0,
        opts=_opts(max_members=2, forecast_s=10.0))
    assert a.tick(now=100.0) == []          # flat: no trend yet
    _depth(hz, A, 2, 101.0)
    decisions = a.tick(now=101.0)
    assert [d["action"] for d in decisions] == ["scale_up:admit_standby"]
    assert decisions[0]["reason"] == "forecast_over_target"
    assert decisions[0]["demand"] == 2.0    # still under target — the
    assert decisions[0]["forecast"] == 12.0  # forecast did the scaling
    assert hz.pool.members[B].state == fb.JOINING
    assert a.counters["scale_up"] == 1 and a.counters["admit_standby"] == 1
    assert a.state()["pending_verify"] == 1  # recompile check armed


def test_scale_up_prefers_parked_member():
    """Capacity-source order: a parked member is a warm process that
    costs nothing to bring back — it must win over the standby list and
    the fork spawn."""
    hz = _ready_pool({A: 20, B: 0}, now=100.0)
    mb = hz.pool.members[B]
    mb.state = fb.PARKED
    mb.routable = False
    a = ac.CapacityAuthority(hz.pool, standby=[C],
                             compile_probe=lambda: 0, opts=_opts())
    decisions = a.tick(now=100.0)
    assert [d["action"] for d in decisions] == ["scale_up:unpark"]
    assert mb.state == fb.JOINING
    assert C not in hz.pool.members         # standby untouched
    assert a.counters["unpark"] == 1
    assert hz.pool.counters["member_unparked"] == 1


def test_scale_up_blocked_without_capacity_source():
    hz = _ready_pool({A: 20}, now=100.0)
    a = ac.CapacityAuthority(hz.pool, compile_probe=lambda: 0,
                             opts=_opts())
    decisions = a.tick(now=100.0)
    assert [d["action"] for d in decisions] == ["blocked"]
    assert a.counters["blocked"] == 1 and a.counters["scale_up"] == 0
    assert hz.pool.capacity_count() == 1    # nothing changed


def test_below_min_scales_up_regardless_of_demand():
    hz = PoolHarness()
    a = ac.CapacityAuthority(hz.pool, standby=[A],
                             compile_probe=lambda: 0,
                             opts=_opts(min_members=1))
    decisions = a.tick(now=0.0)             # zero demand, zero fleet
    assert decisions and decisions[0]["reason"] == "below_min"
    assert A in hz.pool.members


def test_shed_pressure_scales_up():
    """A shedding SLO controller is immediate pressure — no forecast
    needed, the engine is already refusing work."""
    class Shedding:
        def capacity_signal(self):
            return {"queue_depth": 0, "shedding": True}

    hz = _ready_pool({A: 0}, now=100.0)
    a = ac.CapacityAuthority(hz.pool, standby=[B],
                             controllers=[Shedding()],
                             compile_probe=lambda: 0, opts=_opts())
    decisions = a.tick(now=100.0)
    assert decisions and decisions[0]["reason"] == "shed_pressure"


def test_scale_down_parks_least_loaded_after_streak():
    hz = _ready_pool({A: 1, B: 0}, now=100.0)
    a = ac.CapacityAuthority(hz.pool, compile_probe=lambda: 0,
                             opts=_opts(down_after_ticks=3))
    for t in (100.0, 101.0):
        _depth(hz, A, 1, t)
        _depth(hz, B, 0, t)
        assert a.tick(now=t) == []          # streak still building
    _depth(hz, A, 1, 102.0)
    _depth(hz, B, 0, 102.0)
    decisions = a.tick(now=102.0)
    assert [d["action"] for d in decisions] == ["scale_down:park"]
    assert decisions[0]["member"] == B      # least (depth + inflight)
    mb = hz.pool.members[B]
    assert mb.state == fb.PARKED and not mb.routable
    assert mb.depth_t is None               # its gauge is history now
    assert hz.pool.ready_count() == 1
    assert a.counters["scale_down"] == 1 and a.counters["park"] == 1
    assert hz.pool.counters["member_parked"] == 1


def test_scale_down_never_below_min_members():
    hz = _ready_pool({A: 0}, now=100.0)
    a = ac.CapacityAuthority(hz.pool, compile_probe=lambda: 0,
                             opts=_opts(min_members=1))
    for t in (100.0, 101.0, 102.0, 103.0):
        _depth(hz, A, 0, t)
        assert a.tick(now=t) == []
    assert hz.pool.members[A].state == fb.MEMBER_READY


def test_hysteresis_holds_in_the_dead_band():
    """THE no-flap pin: demand oscillating between the down band
    (< 0.5×target per member) and the up threshold (> target) must
    produce zero scale actions — noise is not a trend."""
    hz = _ready_pool({A: 0, B: 0}, now=100.0)
    a = ac.CapacityAuthority(hz.pool, standby=[C],
                             compile_probe=lambda: 0, opts=_opts())
    for i in range(20):
        t = 100.0 + i
        _depth(hz, A, 5 if i % 2 == 0 else 7, t)  # per-member 2.5..3.5
        _depth(hz, B, 0, t)
        assert a.tick(now=t) == []
    assert a.counters["scale_up"] == 0 and a.counters["scale_down"] == 0
    assert a.counters["hold"] == 20


def test_down_streak_resets_when_load_returns():
    hz = _ready_pool({A: 0, B: 0}, now=100.0)
    a = ac.CapacityAuthority(hz.pool, compile_probe=lambda: 0,
                             opts=_opts(down_after_ticks=3))
    # the blip resets the streak AND holds the slope positive one more
    # tick — both gates have to re-earn the scale-down
    lows_then_blip = (0, 0, 6, 0, 0, 0)
    for i, d in enumerate(lows_then_blip):
        t = 100.0 + i
        _depth(hz, A, d, t)
        _depth(hz, B, 0, t)
        assert a.tick(now=t) == []          # streak never reaches 3
    assert a.counters["scale_down"] == 0
    _depth(hz, A, 0, 106.0)
    _depth(hz, B, 0, 106.0)
    decisions = a.tick(now=106.0)           # third consecutive low
    assert decisions and decisions[0]["action"] == "scale_down:park"


def test_up_cooldown_spaces_scale_ups():
    hz = _ready_pool({A: 20}, now=100.0)
    a = ac.CapacityAuthority(hz.pool, standby=[B, C],
                             compile_probe=lambda: 0,
                             opts=_opts(up_cooldown_s=5.0))
    assert a.tick(now=100.0)                # first up
    _depth(hz, A, 40, 101.0)
    assert a.tick(now=101.0) == []          # cooling down
    _depth(hz, A, 40, 105.0)
    assert a.tick(now=105.0)                # cooled: second up
    assert a.counters["scale_up"] == 2


def test_thrash_guard_freezes_and_flight_dumps(tmp_path):
    telemetry.configure(str(tmp_path), rank=0)
    hz = _ready_pool({A: 0}, now=100.0)
    a = ac.CapacityAuthority(
        hz.pool, standby=[B], compile_probe=lambda: 0,
        opts=_opts(thrash_flips=2, thrash_window_s=60.0, freeze_s=30.0))
    a._note_direction(100.0, +1)
    a._note_direction(101.0, -1)            # flip 1
    a._note_direction(102.0, +1)            # flip 2 → freeze
    assert a.counters["thrash_freeze"] == 1
    assert a._frozen_until == 132.0
    assert (tmp_path / "flight_0.jsonl").exists()
    # frozen: even hard over-target pressure holds
    _depth(hz, A, 50, 103.0)
    assert a.tick(now=103.0) == []
    # thawed: the same pressure acts again
    _depth(hz, A, 50, 140.0)
    decisions = a.tick(now=140.0)
    assert decisions and decisions[0]["action"].startswith("scale_up")


# -- zero-recompile invariant ----------------------------------------------


def test_zero_recompile_violation_detected(tmp_path):
    """A scale-up that causes the fleet's compiled-program count to grow
    broke the contract that new capacity warms from the shared AOT
    cache — counter + flight dump, not a silent regression."""
    telemetry.configure(str(tmp_path), rank=0)
    probes = iter([5, 8])                   # baseline, then verify: +3
    hz = _ready_pool({A: 20}, now=100.0)
    a = ac.CapacityAuthority(hz.pool, standby=[B],
                             compile_probe=lambda: next(probes),
                             opts=_opts(max_members=2))
    assert a.tick(now=100.0)                # scale up, baseline probed
    assert a.counters["recompile_check"] == 1
    hz.up(A, depth=20)
    hz.up(B)
    hz.pool.poll(now=100.5)                 # standby joins → ready
    assert hz.pool.ready_count() == 2
    a.tick(now=101.0)                       # check ripe → verify
    assert a.counters["recompile_violation"] == 3
    assert a.state()["pending_verify"] == 0
    flights = json.loads(
        (tmp_path / "flight_0.jsonl").read_text().splitlines()[-1])
    assert flights["fields"]["reason"] == "autoscale_recompile"


def test_zero_recompile_clean_scale_event():
    hz = _ready_pool({A: 20}, now=100.0)
    a = ac.CapacityAuthority(hz.pool, standby=[B],
                             compile_probe=lambda: 7,  # flat: no compiles
                             opts=_opts(max_members=2))
    assert a.tick(now=100.0)
    hz.up(A, depth=20)
    hz.up(B)
    hz.pool.poll(now=100.5)
    a.tick(now=101.0)
    assert a.counters["recompile_check"] == 1
    assert a.counters["recompile_violation"] == 0


def test_fleet_compiled_programs_sums_registry_misses():
    class M:
        def __init__(self, name, answer):
            self.name = name
            self.answer = answer

        def http(self, method, path, timeout=5.0):
            if isinstance(self.answer, Exception):
                raise self.answer
            return self.answer

    class P:
        def __init__(self, members):
            self._members = members

        def routable_members(self):
            return self._members

    pool = P([M(A, (200, {"compile": {"counters": {"aot_miss": 2,
                                                   "aot_hit": 9}}})),
              M(B, (200, {"counters": {}})),  # no registry: contributes 0
              M(C, (503, {})),                # warming: skipped
              M("10.0.0.9:8000", OSError("mid-death"))])  # unreachable
    assert ac.fleet_compile_counters(pool) == {A: 2}
    assert ac.fleet_compiled_programs(pool) == 2


def test_unpark_boot_history_is_not_a_recompile_violation():
    """The per-member baseline regression pin: a member that COMPILED at
    its own boot (cold cache) and was later parked must not trip the
    zero-recompile verify when it is unparked — its counter is history,
    not a scale-caused compile.  A fleet-wide sum gets this wrong: the
    unpark adds the member's old misses to the sum."""
    hz = _ready_pool({A: 20, B: 0}, now=100.0)
    hz.pool.park_member(B)
    # per-member probes as the default probe would see them: B carries 3
    # boot-time misses the whole way through; nobody compiles anything
    probes = iter([{A: 1, B: 3},       # baseline (B probed via extra)
                   {A: 1, B: 3}])      # verify: unchanged per member
    a = ac.CapacityAuthority(hz.pool, compile_probe=lambda: next(probes),
                             opts=_opts(max_members=2))
    decisions = a.tick(now=100.0)
    assert decisions[0]["action"] == "scale_up:unpark"
    hz.up(A, depth=20)
    hz.up(B)
    hz.pool.poll(now=100.5)
    a.tick(now=101.0)                  # check ripe → per-member diff
    assert a.counters["recompile_check"] == 1
    assert a.counters["recompile_violation"] == 0


def test_spawned_member_compiles_are_event_caused():
    """The flip side: a member absent from the baseline map (capacity
    this event created) owns every miss it reports — a spawn that
    compiles instead of warming from the shared cache is a violation."""
    hz = _ready_pool({A: 20}, now=100.0)
    probes = iter([{A: 1},             # baseline: fleet before the event
                   {A: 1, B: 2}])      # verify: the newcomer compiled
    a = ac.CapacityAuthority(hz.pool, standby=[B],
                             compile_probe=lambda: next(probes),
                             opts=_opts(max_members=2))
    assert a.tick(now=100.0)
    hz.up(A, depth=20)
    hz.up(B)
    hz.pool.poll(now=100.5)
    a.tick(now=101.0)
    assert a.counters["recompile_violation"] == 2


# -- actuation: supervisor on-demand capacity -------------------------------


class _FakeProc:
    def __init__(self):
        self.pid = 4242
        self.terminated = False
        self.killed = False

    def poll(self):
        return 0 if (self.terminated or self.killed) else None

    def terminate(self):
        self.terminated = True

    def wait(self, timeout=None):
        return 0

    def kill(self):
        self.killed = True


def _scale_sup(tmp_path, n=1):
    spawned = []

    def spawn(spec):
        p = _FakeProc()
        spawned.append(spec)
        return p

    specs = sv.replica_specs(["serve.py", "--serve-batch", "4"], n,
                             str(tmp_path))
    sup = sv.ReplicaSupervisor(specs, sv.SupervisorOptions(),
                               spawn_fn=spawn,
                               probe_fn=lambda h, p: (200, {}))
    return sup, spawned


def test_add_replica_templates_the_next_slot(tmp_path):
    sup, spawned = _scale_sup(tmp_path)
    sup.spawn_all(now=0.0)
    h = sup.add_replica(now=1.0)
    assert len(sup.handles) == 2 and h.index == 1
    assert h.spec.sock.endswith("replica_1.sock")
    # the build_child_argv tail contract held through templating
    assert h.spec.argv[-4:] == ["--unix-socket", h.spec.sock,
                                "--replica-index", "1"]
    assert "--serve-batch" in h.spec.argv    # serving flags inherited
    assert h.spec.env["MXR_REPLICA_INDEX"] == "1"
    assert spawned[-1] is h.spec             # spawned immediately
    assert sup.counters["scale_spawn"] == 1


def test_add_replica_on_empty_supervisor_needs_a_spec():
    sup = sv.ReplicaSupervisor([], sv.SupervisorOptions(),
                               spawn_fn=lambda s: _FakeProc(),
                               probe_fn=lambda h, p: (200, {}))
    with pytest.raises(RuntimeError, match="explicit spec"):
        sup.add_replica()


def test_retire_replica_drains_and_drops_the_slot(tmp_path):
    sup, _ = _scale_sup(tmp_path, n=2)
    sup.spawn_all(now=0.0)
    h = sup.handles[1]
    proc = h.proc
    assert sup.retire_replica(h)
    assert h not in sup.handles and len(sup.handles) == 1
    assert h.state == sv.STOPPED and not h.routable
    assert proc.terminated                  # graceful SIGTERM, not kill
    assert sup.counters["scale_retire"] == 1
    assert not sup.retire_replica(h)        # foreign/stale handle: False


def test_pool_adopts_and_releases_runtime_replicas(tmp_path):
    sup, _ = _scale_sup(tmp_path)
    sup.spawn_all(now=0.0)
    hz = PoolHarness()
    hz.pool.adopt_supervisor(sup)
    h = sup.add_replica(now=1.0)
    m = hz.pool.adopt_handle(h)
    assert m.name == "local/1" and m.name in hz.pool.members
    assert hz.pool.adopt_handle(h) is m     # idempotent
    assert hz.pool.release_local(m.name)
    assert m.name not in hz.pool.members
    assert not hz.pool.release_local(m.name)


# -- satellite 3: register racing a scale-down drain ------------------------


def test_register_mid_park_drain_defers_readmit():
    """THE half-routable pin: a register landing while the park drain is
    waiting out in-flight requests must not flip routing state mid-drain
    — the drain settles first, then the readmit wins and the member is
    FULLY back in rotation (ready + routable), never parked."""
    hz = _ready_pool({A: 0}, now=100.0)
    m = hz.pool.members[A]
    m.inflight = 1                          # the drain will block on this
    result = {}

    def park():
        result["parked"] = hz.pool.park_member(A)

    th = threading.Thread(target=park, daemon=True)
    th.start()
    _wait(lambda: m.scale_drain, timeout=10.0, what="drain to begin")
    assert not m.routable and m.reloading   # unrouted, drain in progress
    hz.pool.register(A, now=101.0)
    assert m.readmit_pending
    assert m.state == fb.MEMBER_READY       # register touched NO routing
    assert not m.routable                   # still drained-out
    m.inflight = 0                          # in-flight work completes
    th.join(timeout=10.0)
    assert not th.is_alive()
    assert result["parked"] is False        # the park was abandoned
    assert m.state == fb.MEMBER_READY and m.routable
    assert not m.reloading and not m.scale_drain and not m.readmit_pending
    assert hz.pool.counters["member_parked"] == 0


def test_register_after_park_is_a_clean_unpark():
    hz = _ready_pool({A: 0}, now=100.0)
    assert hz.pool.park_member(A)           # no in-flight: parks at once
    m = hz.pool.members[A]
    assert m.state == fb.PARKED and not m.routable
    assert hz.pool.counters["member_parked"] == 1
    hz.pool.register(A, now=101.0)
    assert m.state == fb.JOINING
    assert hz.pool.counters["member_unparked"] == 1
    hz.up(A)
    hz.pool.poll(now=101.5)                 # probe completes the rejoin
    assert m.state == fb.MEMBER_READY and m.routable


def test_parked_member_is_not_probed():
    hz = _ready_pool({A: 0}, now=100.0)
    assert hz.pool.park_member(A)
    hz.up(A)                                # a probe WOULD see it ready
    hz.pool.poll(now=105.0)
    assert hz.pool.members[A].state == fb.PARKED  # parked stays parked
    assert "10.0.0.1:8000" not in hz.probes


# -- satellite 1: Prometheus fleet-size gauges ------------------------------


def test_prometheus_member_count_by_state():
    hz = _ready_pool({A: 0, B: 0}, now=100.0)
    mb = hz.pool.members[B]
    mb.state = fb.PARKED
    mb.routable = False
    text = fb.fabric_prometheus(fb.FabricRouter(hz.pool))
    assert "# TYPE fabric_member_count gauge" in text
    assert 'fabric_member_count{state="ready"} 1' in text
    assert 'fabric_member_count{state="parked"} 1' in text
    # zeros are emitted, not omitted: absent-state asserts read 0
    assert 'fabric_member_count{state="evicted"} 0' in text
    assert text.endswith("\n")


def test_prometheus_autoscale_pane_when_enabled():
    hz = _ready_pool({A: 0}, now=100.0)
    router = fb.FabricRouter(hz.pool)
    text = fb.fabric_prometheus(router)
    assert "mxr_autoscale" not in text      # dormant: no series at all
    router.autoscaler = ac.CapacityAuthority(hz.pool,
                                             compile_probe=lambda: 0,
                                             opts=_opts())
    router.autoscaler.tick(now=100.0)
    text = fb.fabric_prometheus(router)
    assert "mxr_autoscale_demand" in text
    assert "mxr_autoscale_hold_total" in text


# -- satellite 2: loadgen profiles ------------------------------------------


def test_loadgen_profile_schedules():
    lg = _load_script("loadgen")
    assert set(lg.PROFILES) == {"diurnal", "flashcrowd"}
    offs, segs = lg.profile_schedule("diurnal", 100, 10.0)
    assert len(offs) == 100 and offs == sorted(offs)
    assert sum(s["requests"] for s in segs) == 100
    assert [s["rate"] for s in segs] == [4.0, 8.0, 16.0, 8.0, 4.0]
    assert segs[0]["t0_s"] == 0.0
    offs, segs = lg.profile_schedule("flashcrowd", 50, 20.0)
    assert len(offs) == 50
    assert segs[1]["rate"] == 160.0         # the 8× spike
    assert segs[1]["rate"] / segs[0]["rate"] == 16.0
    # rate 0 degenerates to fire-at-once, not a division crash
    offs, _ = lg.profile_schedule("flashcrowd", 10, 0.0)
    assert offs == [0.0] * 10


# -- satellite 5: perf_gate autoscale rows ----------------------------------


def _autoscale_doc(**row_extra):
    row = {"name": "default", "profile": "flashcrowd",
           "p99_ms": 120.0, "p99_ceiling_ms": 400.0, "error_rate": 0.0,
           "fleet": {"start": 1, "peak": 2, "end": 1},
           "time_to_scale_s": 2.4, "time_to_scale_ceiling_s": 20.0,
           "scale_floor": 1.0, "recompiles_during_run": 0,
           "recompile_ceiling": 0.0}
    row.update(row_extra)
    return {"schema": "mxr_autoscale_report", "version": 1,
            "fleet_excess_recompiles": 0, "scenarios": [row]}


def test_perf_gate_autoscale_rows(tmp_path):
    pg = _load_script("perf_gate")
    path = tmp_path / "AUTOSCALE_r01.json"
    path.write_text(json.dumps(_autoscale_doc()))
    rows = {r["metric"]: r for r in pg.load_rows(str(path))}
    assert rows["autoscale_default_p99_ms"]["ceiling"] == 400.0
    assert rows["autoscale_default_scale_up"] == {
        "metric": "autoscale_default_scale_up", "value": 1.0,
        "unit": "members", "floor": 1.0}
    assert rows["autoscale_default_time_to_scale_s"]["ceiling"] == 20.0
    assert rows["autoscale_default_recompiles"]["ceiling"] == 0.0
    assert rows["autoscale_fleet_excess_recompiles"]["value"] == 0.0
    assert pg.main(["--dir", str(tmp_path)]) == 0
    assert pg.main(["--dir", str(tmp_path), "--check-format"]) == 0
    # one program compiled during the scale event → the gate fails
    path.write_text(json.dumps(_autoscale_doc(recompiles_during_run=1)))
    assert pg.main(["--dir", str(tmp_path)]) == 1
    # the fleet never grew under the flash crowd → the gate fails
    path.write_text(json.dumps(_autoscale_doc(
        fleet={"start": 1, "peak": 1, "end": 1})))
    assert pg.main(["--dir", str(tmp_path)]) == 1
    # p99 through the scale events over the pinned ceiling → fails
    path.write_text(json.dumps(_autoscale_doc(p99_ms=900.0)))
    assert pg.main(["--dir", str(tmp_path)]) == 1


# -- dormant-by-default: autoscale off = fleet unchanged --------------------


def test_build_child_argv_strips_autoscale_flags():
    argv = ["serve.py", "--network", "resnet50", "--autoscale",
            "--autoscale-min", "1", "--autoscale-max", "4",
            "--autoscale-target-depth", "8",
            "--autoscale-interval-s", "0.5",
            "--autoscale-standby", "h:1,h:2", "--serve-batch", "4"]
    out = sv.build_child_argv(argv, "/tmp/r0.sock", 0)
    joined = " ".join(out)
    assert "--autoscale" not in joined      # children never self-scale
    assert "h:1,h:2" not in joined
    assert "--serve-batch 4" in joined


def test_autoscale_off_leaves_fabric_untouched():
    """The dormancy pin: without --autoscale no authority exists, the
    metrics pane has no autoscale key, and even CONSTRUCTING one (never
    started, never ticked) perturbs nothing in the pool."""
    hz = PoolHarness()
    hz.pool.register(A, now=0.0)
    hz.pool.register(B, now=0.0)
    router = fb.FabricRouter(hz.pool)
    assert router.autoscaler is None
    before = dict(hz.pool.counters)
    states = {n: m.state for n, m in hz.pool.members.items()}
    a = ac.CapacityAuthority(hz.pool, compile_probe=lambda: 0)
    assert hz.pool.counters == before
    assert {n: m.state for n, m in hz.pool.members.items()} == states
    assert a.ticks == 0
    doc = router.metrics()
    assert "autoscale" not in doc
    router.autoscaler = a
    assert "autoscale" in router.metrics()  # opt-in only


# -- end-to-end: real pool, real TCP members, member count tracks load ------


def test_e2e_fleet_tracks_load_with_zero_recompiles():
    """The ISSUE-18 chaos e2e over REAL localhost-TCP subprocesses: an
    idle two-member fleet drains to min (park through the in-flight
    drain), a flash crowd unparks the warm spare (scale-up through the
    register path), routing answers 2xx throughout, the load dropping
    drains it back down — and the registry counters certify the whole
    dance compiled NOTHING."""
    ports = [_free_port(), _free_port()]
    procs = [_member_proc(ports[0], 0), _member_proc(ports[1], 1)]
    pool = fb.ReplicaPool(_e2e_opts())
    for port in ports:
        pool.register(f"127.0.0.1:{port}")
    pool.start()
    try:
        _wait(lambda: pool.ready_count() == 2, what="both members ready")

        class Pressure:  # an injectable SLO-controller-shaped signal
            q = 0.0

            def capacity_signal(self):
                return {"queue_depth": self.q, "shedding": False}

        sig = Pressure()
        a = ac.CapacityAuthority(
            pool, controllers=[sig],
            opts=_opts(min_members=1, max_members=2, down_after_ticks=2,
                       thrash_flips=10))
        compiled_before = ac.fleet_compiled_programs(pool)

        # phase 1: idle → the authority drains the fleet back to min
        decisions = []
        for _ in range(4):
            decisions += a.tick()
            time.sleep(0.05)
        assert any(d["action"] == "scale_down:park" for d in decisions)
        assert pool.ready_count() == 1
        assert pool.member_state_counts().get(fb.PARKED) == 1
        router = fb.FabricRouter(pool, timeout_s=30.0)
        status, _, _ = router.route_predict(_predict_body())
        assert status == 200                # the shrunken fleet serves

        # phase 2: flash crowd → the warm spare is unparked
        sig.q = 50.0
        up = a.tick()
        assert any(d["action"] == "scale_up:unpark" for d in up)
        _wait(lambda: pool.ready_count() == 2,
              what="unparked member to rejoin")
        for _ in range(3):                  # let the verify checks close
            a.tick()
            time.sleep(0.05)
        assert a.counters["recompile_check"] >= 1
        assert a.counters["recompile_violation"] == 0
        assert ac.fleet_compiled_programs(pool) == compiled_before
        assert a.state()["pending_verify"] == 0
        status, _, _ = router.route_predict(_predict_body())
        assert status == 200

        # phase 3: the crowd passes → drain back down to min (the spike
        # still in the trend window holds the slope positive for a few
        # ticks — scale-down correctly waits it out)
        sig.q = 0.0
        down = []
        for _ in range(12):
            down += a.tick()
            if any(d["action"] == "scale_down:park" for d in down):
                break
            time.sleep(0.05)
        assert any(d["action"] == "scale_down:park" for d in down)
        assert pool.ready_count() == 1
        assert pool.member_state_counts().get(fb.PARKED) == 1
    finally:
        _cleanup(pool, procs)
