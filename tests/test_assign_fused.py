"""Fused Pallas assign-IoU reductions vs the dense XLA path — parity in
Pallas interpret mode on CPU (the on-chip gate is scripts/check_pallas.py).

Parity is ULP-level, not bitwise: compilers contract the kernel's FMA
chains differently per fusion context (the pallas interpreter jit-compiles
the kernel body, so even "eager" kernel calls see contraction), so float
outputs are compared to ~1 ULP and discrete outputs (argmax, tie, labels)
must agree except where the decision is within ~1 ULP of a boundary.
EXACT ties (duplicate gt boxes) are layout-stable and asserted exactly."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from mx_rcnn_tpu.kernels.assign_pallas import assign_reduce_pallas
from mx_rcnn_tpu.ops.anchors import all_anchors, generate_anchors
from mx_rcnn_tpu.ops.assign_anchor import assign_anchor
from mx_rcnn_tpu.ops.boxes import bbox_overlaps

MAX_GT = 16
ULP = 3e-7  # ~2 f32 ulp at iou scale (≤1.0)


def _dense(anchors, gt, valid, inside):
    ov = np.asarray(bbox_overlaps(jnp.asarray(anchors), jnp.asarray(gt)))
    ov = np.where(valid[None, :], ov, -1.0)
    mx = ov.max(axis=1)
    am = ov.argmax(axis=1)
    ov_in = np.where(inside[:, None], ov, -1.0)
    gm = ov_in.max(axis=0)
    tie = ((ov_in == gm[None, :]) & valid[None, :] & (gm[None, :] > 0)).any(1)
    return ov, mx, am, gm, tie


def _case(rng, n_gt, fh=10, fw=12, stride=16):
    anchors = all_anchors(fh, fw, stride, generate_anchors(scales=(1, 2, 4)))
    im_h, im_w = fh * stride, fw * stride
    gt = np.zeros((MAX_GT, 4), np.float32)
    for i in range(n_gt):
        x1, y1 = rng.rand(2) * np.array([im_w - 80, im_h - 80])
        gt[i] = [x1, y1, x1 + 20 + rng.rand() * 60, y1 + 20 + rng.rand() * 60]
    valid = np.arange(MAX_GT) < n_gt
    inside = ((anchors[:, 0] >= 0) & (anchors[:, 1] >= 0)
              & (anchors[:, 2] < im_w) & (anchors[:, 3] < im_h))
    return anchors, gt, valid, inside


def _check_discrete(ov, gm, valid, ref_disc, got_disc, name):
    """Discrete outputs must match except where the deciding comparison is
    within ~1 ULP (ties between columns, or against gt_max).  Distances are
    taken over VALID gt columns only: padded columns carry the sentinel
    -1.0 in both ov and gm, whose distance-0 'tie' would mark every anchor
    marginal and make the assertion vacuous (the test_assign_sample.py
    bf16-test pitfall)."""
    ovv = ov[:, valid]
    gmv = gm[valid]
    near_tie = (np.abs(ovv - ov.max(1, keepdims=True)) < ULP).sum(1) > 1
    near_gtmax = (np.abs(ovv - gmv[None, :]) < ULP).any(1) if valid.any() \
        else np.zeros(ov.shape[0], bool)
    marginal = near_tie | near_gtmax
    bad = (ref_disc != got_disc) & ~marginal
    assert not bad.any(), f"{name}: {bad.sum()} non-marginal mismatches"


def test_jitted_matches_dense_to_ulp(rng):
    for n_gt in (0, 1, 5, MAX_GT):
        anchors, gt, valid, inside = _case(rng, n_gt)
        ov, mx, am, gm, tie = _dense(anchors, gt, valid, inside)
        k_mx, k_am, k_gm, k_tie = assign_reduce_pallas(
            jnp.asarray(anchors), jnp.asarray(gt), jnp.asarray(valid),
            jnp.asarray(inside), interpret=True)
        np.testing.assert_allclose(np.asarray(k_mx), mx, rtol=0, atol=ULP)
        np.testing.assert_allclose(np.asarray(k_gm), gm, rtol=0, atol=ULP)
        _check_discrete(ov, gm, valid, am, np.asarray(k_am), "argmax")
        _check_discrete(ov, gm, valid, tie, np.asarray(k_tie), "tie")


def test_duplicate_gt_tie_breaks_like_argmax(rng):
    """Two identical gt boxes: argmax must pick the smaller index and BOTH
    columns' tie predicate must fire — an EXACT tie is layout-stable (the
    two columns share identical arithmetic), so equality is required."""
    anchors, gt, valid, inside = _case(rng, 2)
    gt[1] = gt[0]
    ov, mx, am, gm, tie = _dense(anchors, gt, valid, inside)
    k_mx, k_am, k_gm, k_tie = assign_reduce_pallas(
        jnp.asarray(anchors), jnp.asarray(gt), jnp.asarray(valid),
        jnp.asarray(inside), interpret=True)
    np.testing.assert_array_equal(np.asarray(k_am), am.astype(np.int32))
    np.testing.assert_array_equal(np.asarray(k_tie), tie)


def test_assign_anchor_fused_path_matches_dense(rng):
    """Whole-op parity: labels agree except ULP-marginal anchors; on rows
    where both paths say fg, targets are close (same gt unless ULP-tied)."""
    anchors, gt, valid, inside = _case(rng, 5)
    im_h, im_w = 160, 192
    args = (jnp.asarray(anchors), jnp.asarray(gt), jnp.asarray(valid),
            jnp.float32(im_h), jnp.float32(im_w), jax.random.PRNGKey(3))
    kw = dict(batch_size=100000, fg_fraction=1.0)  # no subsample noise
    dense = assign_anchor(*args, fused=False, **kw)
    fusedk = assign_anchor(*args, fused=True, _fused_interpret=True, **kw)
    ov, mx, am, gm, tie = _dense(anchors, gt, valid, inside)
    l_d = np.asarray(dense["label"])
    l_k = np.asarray(fusedk["label"])
    near_thr = (np.abs(mx - 0.7) < ULP) | (np.abs(mx - 0.3) < ULP)
    near_gtmax = (np.abs(ov[:, valid] - gm[valid][None, :]) < ULP).any(1)
    bad = (l_d != l_k) & ~(near_thr | near_gtmax)
    assert not bad.any(), f"{bad.sum()} non-marginal label flips"
    stable = ((np.sort(ov, 1)[:, -1] - np.sort(ov, 1)[:, -2]) > ULP)
    both_fg = (l_d == 1) & (l_k == 1) & stable
    np.testing.assert_array_equal(
        np.asarray(dense["bbox_target"])[both_fg],
        np.asarray(fusedk["bbox_target"])[both_fg])


def test_fused_vmap_batches_via_map(rng):
    """Batched (vmapped) call lowers through the custom_vmap rule and
    matches per-image jitted results to ULP."""
    anchors, gt0, valid0, inside = _case(rng, 3)
    _, gt1, valid1, _ = _case(rng, 6)
    gts = jnp.stack([jnp.asarray(gt0), jnp.asarray(gt1)])
    valids = jnp.stack([jnp.asarray(valid0), jnp.asarray(valid1)])
    out = jax.vmap(
        lambda g, v: assign_reduce_pallas(
            jnp.asarray(anchors), g, v, jnp.asarray(inside), interpret=True)
    )(gts, valids)
    for b, (g, v) in enumerate([(gt0, valid0), (gt1, valid1)]):
        ov, mx, am, gm, tie = _dense(anchors, g, np.asarray(v), inside)
        np.testing.assert_allclose(np.asarray(out[0][b]), mx, rtol=0, atol=ULP)
        np.testing.assert_allclose(np.asarray(out[2][b]), gm, rtol=0, atol=ULP)
        _check_discrete(ov, gm, np.asarray(v), am, np.asarray(out[1][b]), f"argmax[{b}]")
        _check_discrete(ov, gm, np.asarray(v), tie, np.asarray(out[3][b]), f"tie[{b}]")
