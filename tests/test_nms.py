"""NMS contract tests: jittable padded NMS vs independent greedy oracle."""

import numpy as np
import jax.numpy as jnp

from mx_rcnn_tpu.ops.nms import nms_padded, nms
from tests import oracles


def _rand_dets(rng, n, span=100.0):
    boxes = rng.rand(n, 4) * span
    boxes[:, 2:] = boxes[:, :2] + rng.rand(n, 2) * span * 0.3 + 1
    scores = rng.rand(n).astype(np.float64)
    return boxes.astype(np.float32), scores.astype(np.float32)


def test_nms_padded_matches_oracle(rng):
    boxes, scores = _rand_dets(rng, 120)
    keep_idx, keep_mask = nms_padded(jnp.asarray(boxes), jnp.asarray(scores),
                                     max_out=120, iou_thresh=0.5)
    got = list(np.asarray(keep_idx)[np.asarray(keep_mask)])
    want = oracles.nms_oracle(boxes, scores, 0.5)
    assert got == want


def test_nms_padded_truncates(rng):
    boxes, scores = _rand_dets(rng, 200)
    keep_idx, keep_mask = nms_padded(jnp.asarray(boxes), jnp.asarray(scores),
                                     max_out=5, iou_thresh=0.7)
    got = list(np.asarray(keep_idx)[np.asarray(keep_mask)])
    want = oracles.nms_oracle(boxes, scores, 0.7)[:5]
    assert got == want


def test_nms_padded_respects_valid(rng):
    boxes, scores = _rand_dets(rng, 50)
    valid = np.ones(50, bool)
    valid[scores.argmax()] = False
    keep_idx, keep_mask = nms_padded(jnp.asarray(boxes), jnp.asarray(scores),
                                     max_out=50, iou_thresh=0.5,
                                     valid=jnp.asarray(valid))
    got = set(np.asarray(keep_idx)[np.asarray(keep_mask)].tolist())
    assert int(scores.argmax()) not in got


def test_nms_padded_all_invalid(rng):
    boxes, scores = _rand_dets(rng, 10)
    keep_idx, keep_mask = nms_padded(jnp.asarray(boxes), jnp.asarray(scores),
                                     max_out=10, iou_thresh=0.5,
                                     valid=jnp.zeros(10, bool))
    assert not np.asarray(keep_mask).any()


def test_host_nms_matches_oracle(rng):
    boxes, scores = _rand_dets(rng, 80)
    dets = np.hstack([boxes, scores[:, None]]).astype(np.float32)
    got = nms(dets, 0.3)
    want = oracles.nms_oracle(boxes, scores, 0.3)
    assert got == want


def test_nms_identical_boxes():
    boxes = np.tile(np.array([[10, 10, 50, 50]], np.float32), (5, 1))
    scores = np.array([0.9, 0.8, 0.7, 0.6, 0.5], np.float32)
    keep_idx, keep_mask = nms_padded(jnp.asarray(boxes), jnp.asarray(scores),
                                     max_out=5, iou_thresh=0.5)
    assert np.asarray(keep_mask).sum() == 1
    assert int(keep_idx[0]) == 0
