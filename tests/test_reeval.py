"""tools/reeval.py: re-score a pickled all_boxes without model/device
(reference ``rcnn/tools/reeval.py``), fed by pred_eval's ``det_cache``
(the reference's detections.pkl contract)."""

from __future__ import annotations

import pickle

import numpy as np

from mx_rcnn_tpu.data.synthetic import SyntheticDataset


def test_reeval_cli_roundtrip(tmp_path):
    # constructor args must mirror tools/common.get_imdb's synthetic branch
    # (num_classes=cfg.NUM_CLASSES, size=SCALES[0], default seed) so the
    # CLI rebuilds the SAME gt this test made detections from
    ds = SyntheticDataset(num_images=3, num_classes=21, height=600,
                          width=1000)
    roidb = ds.gt_roidb()
    # perfect detections straight from gt → mAP must be 1 for present
    # classes
    all_boxes = [[np.zeros((0, 5), np.float32) for _ in range(3)]
                 for _ in range(ds.num_classes)]
    present = set()
    for i, rec in enumerate(roidb):
        for b, c in zip(rec["boxes"], rec["gt_classes"]):
            det = np.concatenate([b, [0.9]]).astype(np.float32)[None]
            all_boxes[int(c)][i] = np.concatenate(
                [all_boxes[int(c)][i], det])
            present.add(int(c))
    cache = tmp_path / "dets.pkl"
    with open(cache, "wb") as f:
        pickle.dump(all_boxes, f)

    from mx_rcnn_tpu.tools import reeval as reeval_mod
    from tests.fixtures import run_tool

    stats = run_tool(reeval_mod, reeval_mod.reeval,
                     ["--synthetic", "--synthetic_images", "3",
                      "--detections", str(cache)])
    for c in present:
        assert stats[ds.classes[c]] > 0.99, (c, stats)


def test_pred_eval_writes_det_cache(tmp_path):
    """pred_eval(det_cache=...) writes a pickle reeval can consume."""
    from tests.test_eval_edges import (RecordingIMDB, StubLoader,
                                       StubPredictor, _setup)

    cfg, batch, boxes, roidb = _setup()
    scores = np.zeros((1, 12, 3), np.float32)
    scores[0, :4, 1] = [0.9, 0.8, 0.7, 0.6]
    from mx_rcnn_tpu.eval.tester import pred_eval

    imdb = RecordingIMDB(num_classes=3, num_images=1)
    cache = tmp_path / "dets.pkl"
    pred_eval(StubPredictor(cfg, scores, boxes), StubLoader(batch, roidb),
              imdb, max_per_image=10, thresh=0.05, det_cache=str(cache))
    with open(cache, "rb") as f:
        cached = pickle.load(f)
    assert len(cached) == 3
    assert len(cached[1][0]) == 4
