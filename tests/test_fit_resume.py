"""fit()-level integration: train → checkpoint → resume continues with
restored params/optimizer and the LR schedule on global steps."""

import dataclasses

import jax
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import AnchorLoader, SyntheticDataset
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.train import fit


def tiny_cfg():
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4),
                              PIXEL_STDS=(127.0, 127.0, 127.0))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4)
    return cfg.replace(network=net, tpu=tpu)


def test_fit_checkpoint_resume(tmp_path):
    cfg = tiny_cfg()
    ds = SyntheticDataset(num_images=4, num_classes=cfg.NUM_CLASSES,
                          height=64, width=96)
    roidb = ds.gt_roidb()
    loader = AnchorLoader(roidb, cfg, batch_size=2, shuffle=False, seed=0)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    prefix = str(tmp_path / "ckpt")

    s1 = fit(cfg, model, params, loader, begin_epoch=0, end_epoch=1,
             prefix=prefix, frequent=100)
    w1 = np.asarray(jax.device_get(s1.params["rpn"]["rpn_conv_3x3"]["kernel"]))

    # resume from epoch 1: params come from the checkpoint, training continues
    s2 = fit(cfg, model, params, loader, begin_epoch=1, end_epoch=2,
             prefix=prefix, frequent=100, resume=True)
    assert int(jax.device_get(s2.step)) > int(jax.device_get(s1.step)) - 1
    w2 = np.asarray(jax.device_get(s2.params["rpn"]["rpn_conv_3x3"]["kernel"]))
    # epoch 2 actually trained: weights moved from the restored point
    assert np.abs(w2 - w1).max() > 0
    # frozen params still frozen through resume
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(s2.params["backbone"]["conv1"]["kernel"])),
        np.asarray(jax.device_get(s1.params["backbone"]["conv1"]["kernel"])))
