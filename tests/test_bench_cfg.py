"""bench.py --cfg plumbing: overrides must reach the generated config
(the round-4 lever A/B rides on this) without touching any device."""

import sys


def test_bench_cfg_overrides_reach_config():
    import bench

    bench.CFG_OVERRIDES["TRAIN__RPN_ASSIGN_IOU_BF16"] = True
    try:
        cfg = bench.make_cfg("resnet101_fpn")
        assert cfg.TRAIN.RPN_ASSIGN_IOU_BF16 is True
        assert cfg.network.HAS_FPN
    finally:
        bench.CFG_OVERRIDES.clear()
    assert bench.make_cfg("resnet101_fpn").TRAIN.RPN_ASSIGN_IOU_BF16 is False


def test_bench_cfg_cli_parse_and_metric_suffix(monkeypatch, capsys):
    """--cfg flows through the shared parser and marks the metric _ab so an
    overridden run can never be mistaken for a headline number."""
    import bench

    monkeypatch.setattr(
        sys, "argv",
        ["bench.py", "--mode", "train", "--cfg",
         "TRAIN__RPN_ASSIGN_IOU_BF16=True"])
    # patch BOTH train methods: main() dispatches to the one-dispatch
    # chain by default (round 4) and to staged under --legacy-dispatch
    monkeypatch.setattr(bench, "bench_train_chain",
                        lambda batch, network: 42.0)
    monkeypatch.setattr(bench, "bench_train_staged",
                        lambda batch, network: 42.0)
    try:
        bench.main()
    finally:
        bench.CFG_OVERRIDES.clear()
    out = capsys.readouterr().out.strip().splitlines()[-1]
    import json

    rec = json.loads(out)
    assert rec["metric"].endswith("_ab")
    assert rec["vs_baseline"] is None  # override runs never set the ratio


def test_bench_vs_baseline_is_method_consistent(monkeypatch, capsys,
                                                tmp_path):
    """Round-4 VERDICT weakness 3: the headline ratio must divide by the
    SAME-method baseline — chain runs by value_chain, --legacy-dispatch
    runs by value — and name the denominator's method in the output."""
    import json

    import bench

    base = tmp_path / "BENCH_BASELINE.json"
    base.write_text(json.dumps(
        {"metric": "train_imgs_per_sec_per_chip", "value": 5.0,
         "value_chain": 80.0}))
    monkeypatch.setattr(bench, "BASELINE_FILE", str(base))
    monkeypatch.setattr(bench, "bench_train_chain",
                        lambda batch, network: 88.0)
    monkeypatch.setattr(bench, "bench_train_staged",
                        lambda batch, network: 10.0)

    def run(argv):
        monkeypatch.setattr(sys, "argv", argv)
        bench.main()
        return json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    rec = run(["bench.py", "--mode", "train"])
    assert rec["vs_baseline"] == round(88.0 / 80.0, 3)
    assert rec["baseline_method"] == "chain"

    rec = run(["bench.py", "--mode", "train", "--legacy-dispatch"])
    assert rec["vs_baseline"] == round(10.0 / 5.0, 3)
    assert rec["baseline_method"] == "staged"


def test_differenced_rate_protocol(monkeypatch):
    """The shared chain-timing protocol (_differenced_rate): differenced
    pairs, inverted-pair skip, lower-median, and the staged fallback when
    every pair inverts — now load-bearing for BOTH chain benches."""
    import bench

    monkeypatch.setattr(bench, "CHAIN_N1", 10)
    monkeypatch.setattr(bench, "CHAIN_N2", 30)
    t = {"now": 0.0}
    monkeypatch.setattr(bench.time, "time", lambda: t["now"])

    # run(n) costs 0.1 s fixed dispatch + n*0.05 s: rate = 20*1/(1.0) = 20
    def run(n):
        t["now"] += 0.1 + n * 0.05

    assert bench._differenced_rate(run, 1, lambda: -1.0) == 20.0

    # one inverted pair (hiccup on the long leg) is skipped, not
    # averaged, and with the two survivors at DIFFERENT rates the
    # LOWER-middle is returned (upper-middle would be max-of-noise —
    # the round-4 selection bias the protocol exists to kill)
    calls = {"i": 0}

    def run_hiccup(n):
        calls["i"] += 1
        if calls["i"] == 2:  # first pair's n2 leg: absurdly fast (invert)
            t["now"] += 0.01
        elif calls["i"] <= 4:  # second pair: per-step 0.05 -> rate 20.0
            t["now"] += 0.1 + n * 0.05
        else:  # third pair: per-step 0.04 -> rate 25.0
            t["now"] += 0.1 + n * 0.04

    assert bench._differenced_rate(run_hiccup, 1, lambda: -1.0) == 20.0

    # every pair inverted -> staged fallback
    def run_bad(n):
        t["now"] += 0.5 if n == 10 else 0.1

    assert bench._differenced_rate(run_bad, 1, lambda: -1.0) == -1.0
