"""Cascade serving tier-1 tests (CPU) — the ISSUE-19 contracts.

The :class:`~mx_rcnn_tpu.serve.pool.CascadeRouter` pins from seven
angles: (1) the shared hardness definition — the jitted device gate
agrees with the miner's host scoring on identical detections, and the
miner imports the SAME function object (no drift possible); (2) the
threshold sweep — ``thresh=0`` escalates everything (and the escalated
answers equal direct big-model submits), ``thresh=1`` escalates
nothing, counts are monotone in between; (3) cascade-off byte parity —
a server without a router returns exactly the pre-cascade response
shape; (4) zero steady-state recompiles — post-warmup traffic with
escalations in the mix compiles nothing new on either engine or
registry; (5) escalated frames land in the capture ring tagged
``cascade_escalated`` with the big model's records; (6) a tenant with
``fidelity="full"`` pins to the big model (and a non-cascade sibling
bypasses untouched); (7) the whole thing end-to-end under
``scripts/loadgen.py --cascade`` over a unix socket, producing an
``mxr_cascade_report`` that ``scripts/perf_gate.py`` expands.

The real-model fixture is module-scoped: two synthetic-weight e2e
engines (distinct config digests — the realistic small/big deployment
shape on one chip) built once and shared by every gate-path test.
"""

import dataclasses
import importlib.util
import json
import os
import threading

import numpy as np
import pytest

from mx_rcnn_tpu.flywheel.capture import (CaptureOptions, RequestCapture,
                                          list_shards, score_stats)
from mx_rcnn_tpu.flywheel.hardness import (HARDNESS_MAX,
                                           build_device_hardness, hardness,
                                           hardness_from_records)
from mx_rcnn_tpu.serve import (CascadeRouter, ModelPool, ServeEngine,
                               ServeOptions, encode_image_payload,
                               make_server, unix_http_request, warmup)
from tests.test_multimodel import add_fake_model
from tests.test_serve import make_engine, tiny_cfg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def set_thresh(router, t):
    """Retune a live router (what a config push would do); rebuilding a
    router would re-register the gate program, so tests retune."""
    router.thresh = float(t)
    router._thresh_raw = float(t) * HARDNESS_MAX


# -- (1) shared hardness: device gate == host miner ------------------------


def test_device_hardness_matches_host_reference():
    cases = [
        [],                                  # failed/empty frame
        [0.9],                               # one confident detection
        [0.5, 0.5, 0.5, 0.5],                # uniform mass: entropy = 1
        [0.95, 0.6, 0.35, 0.12, 0.05],       # mixed bands
        [0.31, 0.69, 0.71, 0.29, 0.5, 0.5],  # scores straddling bands
    ]
    cap = 8
    dets = np.zeros((len(cases), cap, 6), np.float32)
    valid = np.zeros((len(cases), cap), bool)
    for b, scores in enumerate(cases):
        for j, s in enumerate(scores):
            dets[b, j, 4] = s
            valid[b, j] = True
    dev = np.asarray(build_device_hardness()(dets, valid))
    assert dev.shape == (len(cases),)
    for b, scores in enumerate(cases):
        records = [{"cls": 1, "score": s, "bbox": [0.0, 0.0, 4.0, 4.0]}
                   for s in scores]
        host = hardness_from_records(records)
        # float32 device vs float64 host
        assert abs(float(dev[b]) - host) < 5e-5, (b, float(dev[b]), host)
        assert 0.0 <= float(dev[b]) < HARDNESS_MAX


def test_miner_and_gate_share_one_hardness():
    from mx_rcnn_tpu.flywheel import miner

    # the miner scores with the SAME function object the shared module
    # exports — a fork would break this identity, not just a tolerance
    assert miner.hardness is hardness
    records = [{"cls": 2, "score": s, "bbox": [0, 0, 1, 1]}
               for s in (0.8, 0.45, 0.2)]
    score, parts = hardness(score_stats(records))
    assert score == pytest.approx(hardness_from_records(records))
    assert set(parts) == {"entropy", "disagreement", "low_max"}


# -- the real-model cascade pair (module-scoped, built once) ---------------


@pytest.fixture(scope="module")
def cascade_pool():
    import jax

    from mx_rcnn_tpu.compile import config_digest
    from mx_rcnn_tpu.eval import Predictor
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

    cfg_small = tiny_cfg()
    # the big model is a different deployment of the same network
    # (distinct digest, same SCALES so bucket geometry agrees — the
    # router's escalation precondition)
    cfg_big = tiny_cfg().replace(
        TEST=dataclasses.replace(tiny_cfg().TEST, NMS=0.31))
    assert config_digest(cfg_small) != config_digest(cfg_big)

    pool = ModelPool().start()
    for i, (mid, cfg) in enumerate((("small", cfg_small), ("big", cfg_big))):
        model = build_model(cfg)
        params = denormalize_for_save(
            init_params(model, cfg, jax.random.PRNGKey(i), 2, (96, 128)),
            cfg)
        pred = Predictor(model, params, cfg)
        engine = ServeEngine(pred, cfg, ServeOptions(
            batch_size=2, max_delay_ms=5.0, max_queue=32, serve_e2e=True))
        engine.start(external=True)
        pool.add_model(mid, cfg, pred, engine)
        assert warmup(engine) == 2  # one fused program per orientation
    router = CascadeRouter(pool, "small", "big", thresh=0.5)
    assert router.warmup() == 1     # the gate program, compiled pre-traffic
    pool.cascade = router
    yield pool, router
    pool.stop()


def _mixed_images(rng, n=4):
    shapes = ((60, 100), (100, 60), (48, 90), (90, 48))
    return [rng.randint(0, 255, shapes[i % 4] + (3,), dtype=np.uint8)
            for i in range(n)]


# -- (2) threshold sweep ---------------------------------------------------


def test_threshold_sweep_monotonic(cascade_pool):
    pool, router = cascade_pool
    rng = np.random.RandomState(3)
    imgs = _mixed_images(rng, 4)
    counts, records = {}, {}
    try:
        for t in (0.0, 0.5, 1.0):
            set_thresh(router, t)
            base = dict(router.counters)
            futs = [router.submit(img) for img in imgs]
            records[t] = [f.result(timeout=300) for f in futs]
            esc = router.counters["escalated"] - base["escalated"]
            small = (router.counters["answered_small"]
                     - base["answered_small"])
            assert esc + small == len(imgs)
            counts[t] = esc
            for f in futs:
                prov = f.provenance()
                assert prov["thresh"] == t
                assert prov["escalated"] == (prov["model"] == "big")
                assert 0.0 <= prov["hardness"] < HARDNESS_MAX
    finally:
        set_thresh(router, 0.5)

    # thresh 0 escalates everything, 1 nothing, monotone in between
    assert counts[0.0] == len(imgs)
    assert counts[1.0] == 0
    assert counts[0.0] >= counts[0.5] >= counts[1.0]

    # thresh=0 answers ARE the big model's: identical to direct submits
    # of the same raw images (escalation reuses the staged pixels)
    big = pool.engine_for("big")
    for img, got in zip(imgs, records[0.0]):
        ref = big.submit(img).result(timeout=300)
        assert len(got) == len(ref)
        for d, e in zip(got, ref):
            assert d["cls"] == e["cls"]
            assert abs(d["score"] - e["score"]) < 1e-3
            assert np.allclose(d["bbox"], e["bbox"], atol=0.1)


# -- (3) cascade-off byte parity -------------------------------------------


def test_cascade_off_response_byte_parity(tmp_path):
    eng = make_engine(tiny_cfg()).start()
    sock = str(tmp_path / "plain.sock")
    server = make_server(eng, unix_socket=sock)
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    try:
        img = np.full((60, 100, 3), 7, np.uint8)
        status, resp = unix_http_request(
            sock, "POST", "/predict", encode_image_payload(img), timeout=60)
        assert status == 200
        # EXACTLY the pre-cascade shape: no "cascade" provenance field
        assert set(resp) == {"detections", "queue_wait_ms"}
        status, m = unix_http_request(sock, "GET", "/metrics")
        assert status == 200 and "cascade" not in m
    finally:
        server.shutdown()
        server.server_close()
        eng.stop()


# -- (4) zero steady-state recompiles --------------------------------------


def test_zero_recompiles_with_escalations(cascade_pool):
    pool, router = cascade_pool
    regs = {mid: pool.engine_for(mid).registry for mid in ("small", "big")}
    programs = {mid: regs[mid].counters["programs"] for mid in regs}
    engines = {mid: dict(pool.engine_for(mid).counters)
               for mid in ("small", "big")}
    gate_batches = router.counters["gate_batches"]

    rng = np.random.RandomState(7)
    set_thresh(router, 0.0)  # force escalations into the steady state
    try:
        for _ in range(2):
            futs = [router.submit(img) for img in _mixed_images(rng, 4)]
            for f in futs:
                assert f.result(timeout=300) is not None
    finally:
        set_thresh(router, 0.5)

    assert router.counters["gate_batches"] > gate_batches
    for mid in ("small", "big"):
        assert regs[mid].counters["programs"] == programs[mid], mid
        c = pool.engine_for(mid).counters
        assert c["recompiles"] == engines[mid]["recompiles"], mid
        assert c["recompiles"] == c["warmup_programs"], mid
    # the gate is a registry citizen: kind-labeled beside the fused
    # serving programs in the small model's compile snapshot
    rows = pool.engine_for("small").metrics()["compile"]["programs"]
    assert sum(p["kind"] == CascadeRouter.KIND for p in rows) == 1


# -- (5) capture-ring tagging ----------------------------------------------


def test_escalated_frames_feed_capture_tagged(cascade_pool, tmp_path):
    pool, router = cascade_pool
    cap_dir = str(tmp_path / "cap")
    cap = RequestCapture(CaptureOptions(
        capture_dir=cap_dir, sample_every=1, shard_records=4,
        member="cascade_test"))
    old_cap = router.capture
    rng = np.random.RandomState(5)
    set_thresh(router, 0.0)  # every frame escalates
    try:
        router.capture = cap
        futs = [router.submit(img) for img in _mixed_images(rng, 4)]
        for f in futs:
            f.result(timeout=300)
        cap.flush()
    finally:
        router.capture = old_cap
        set_thresh(router, 0.5)

    shards = list_shards(cap_dir)
    assert shards, "escalated frames must spill capture shards"
    rows = [json.loads(line)
            for s in shards for line in open(s["jsonl"]) if line.strip()]
    assert len(rows) == 4
    big_gen = pool.engine_for("big").generation
    for r in rows:
        # additively tagged: the legacy meta fields all still present
        assert r["tags"] == ["cascade_escalated"]
        assert r["generation"] == big_gen  # big model's pseudo-labels
        assert "stats" in r and "detections" in r and "bucket" in r


# -- (6) per-tenant fidelity pin -------------------------------------------


def test_fidelity_full_pins_tenant_to_big(cascade_pool):
    pool, router = cascade_pool
    cfg = tiny_cfg()
    add_fake_model(pool, cfg, "vip", fidelity="full")
    add_fake_model(pool, cfg, "bystander")  # default fidelity="cascade"

    img = np.full((60, 100, 3), 9, np.uint8)
    big = pool.engine_for("big")
    base_forced = router.counters["forced_big"]
    base_big_requests = big.counters["requests"]

    fut = router.submit(img, model_id="vip")
    assert fut.result(timeout=300) is not None
    assert fut.provenance() == {"model": "big", "escalated": False,
                                "reason": "fidelity"}
    assert router.counters["forced_big"] == base_forced + 1
    assert big.counters["requests"] == base_big_requests + 1

    # a pool sibling outside the pair bypasses the cascade untouched
    bys = pool.engine_for("bystander")
    base_bys = bys.counters["requests"]
    fut = router.submit(img, model_id="bystander")
    assert fut.result(timeout=60) is not None
    assert fut.provenance() == {"model": "bystander", "escalated": False,
                                "reason": "bypass"}
    assert bys.counters["requests"] == base_bys + 1
    assert big.counters["requests"] == base_big_requests + 1

    # addressing the big model directly is served, not re-gated
    fut = router.submit(img, model_id="big")
    assert fut.result(timeout=300) is not None
    assert fut.provenance() == {"model": "big", "escalated": False,
                                "reason": "addressed"}
    assert router.counters["forced_big"] == base_forced + 1


# -- (7) two real models e2e under loadgen ---------------------------------


def test_loadgen_cascade_e2e_report(cascade_pool, tmp_path):
    pool, router = cascade_pool
    sock = str(tmp_path / "cascade.sock")
    server = make_server(pool.engine_for(), unix_socket=sock, pool=pool,
                         cascade=router)
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    report = str(tmp_path / "CASCADE_r01.json")
    lg = _load_script("loadgen")
    try:
        lg.main(["--unix-socket", sock, "--cascade", "--n", "6",
                 "--rate", "0", "--short", "60", "--long", "100",
                 "--speedup-floor", "0.05", "--report", report,
                 "--assert-2xx"])
    finally:
        server.shutdown()
        server.server_close()

    with open(report) as f:
        doc = json.load(f)
    assert doc["schema"] == "mxr_cascade_report"
    by_name = {s["name"]: s for s in doc["scenarios"]}
    assert set(by_name) == {"big_only", "cascade"}
    assert by_name["big_only"]["model"] == "big"
    casc = by_name["cascade"]
    assert casc["small"] == "small" and casc["big"] == "big"
    assert casc["requests"] == 6 and casc["error_rate"] == 0.0
    assert 0.0 <= casc["escalation_rate"] <= 1.0
    assert casc["agreement"] is not None
    assert 0.0 <= casc["agreement"] <= 1.0
    assert casc["speedup_vs_big"] > 0
    assert casc["speedup_floor"] == 0.05
    assert set(casc["classes"]) == {"answered_small", "escalated"}

    # the gate consumes the report: floors present, escalation_rate
    # validated (bare row — a traffic property, not a build property)
    pg = _load_script("perf_gate")
    rows = pg.cascade_report_rows(doc)
    by_metric = {r["metric"]: r for r in rows}
    assert by_metric["cascade_speedup_vs_big"]["floor"] == 0.05
    assert by_metric["cascade_cascade_p99_ms"]["direction"] == "down"
    assert by_metric["cascade_big_only_p99_ms"]["direction"] == "down"
    assert "cascade_cascade_escalation_rate" in by_metric
    assert "floor" not in by_metric["cascade_cascade_escalation_rate"]
    assert "direction" not in by_metric["cascade_cascade_escalation_rate"]
