"""Worker for tests/test_multiprocess.py — one process of a REAL
two-process CPU run (Gloo collectives), or the single-process control.

Runs three ``fit`` phases on deterministic synthetic data over an
8-device global mesh and prints a digest of the final state after each.
Invoked as:

    python tests/mp_worker.py <process_id> <num_processes> <port> <ckpt_dir>

num_processes=1 is the control: same global mesh (8 local devices), same
data, no distributed runtime.  Every RNG input is pinned (loader seed,
fit seed, init key), so the multi-process run must reproduce the control
up to collective reduction order (asserted allclose by the test; the two
worker ranks must match each other bit-for-bit).

Phases (each a round-4 VERDICT/ADVICE gap — paths that existed but had
never run across OS processes):

1. ``fit`` one epoch at k=1 WITH an epoch checkpoint save (orbax save
   barriers on all ranks).
2. ``fit(resume=True)`` from that checkpoint for one more epoch — orbax
   multi-host RESTORE runs its own cross-process barriers, previously
   untested (the documented save-side failure modes made this the
   highest-risk untested path).
3. Fresh ``fit(steps_per_dispatch=2)`` — exercises the producer-thread
   group assembler + ``global_from_local(..., stacked=True)`` across
   processes (the stacked global-array assembly path).
"""

from __future__ import annotations

import os
import sys

# 4 local devices per process in the 2-process run, 8 in the control —
# the GLOBAL mesh is 8 devices either way
N_LOCAL = {2: 4, 1: 8}


def main(pid: int, nproc: int, port: int, ckpt_dir: str):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={N_LOCAL[nproc]}")
    import jax

    jax.config.update("jax_platforms", "cpu")
    # PER-RANK compile cache: a shared cache makes hit/miss asymmetric
    # between ranks, skewing their compile finish times; the Gloo clique
    # rendezvous (first collective) tolerates only ~30 s of skew on top
    # of the init_distributed warmup barrier.  A per-rank dir keeps every
    # rank's cache behavior identical run to run.
    cache = os.environ.get("JAX_TEST_CACHE", "/tmp/jax_test_cache")
    jax.config.update("jax_compilation_cache_dir", f"{cache}_mp{nproc}_{pid}")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    if nproc > 1:
        from mx_rcnn_tpu.parallel import init_distributed

        init_distributed(coordinator_address=f"localhost:{port}",
                         num_processes=nproc, process_id=pid)
    import dataclasses

    import numpy as np

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.data import AnchorLoader, SyntheticDataset
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.parallel import assert_loader_partition, make_mesh
    from mx_rcnn_tpu.train import fit

    assert len(jax.devices()) == 8, jax.devices()

    cfg = generate_config(
        "resnet50", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16, TRAIN__FLIP=False,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4),
                              PIXEL_STDS=(127.0, 127.0, 127.0))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4)
    cfg = cfg.replace(network=net, tpu=tpu)

    roidb = SyntheticDataset(num_images=16, num_classes=cfg.NUM_CLASSES,
                             height=64, width=96, seed=0).gt_roidb()

    def make_loader():
        loader = AnchorLoader(roidb, cfg, batch_size=8, shuffle=True, seed=0,
                              num_parts=nproc, part_index=pid)
        return loader

    plan = make_mesh(data=8)
    assert_loader_partition(plan, 8, nproc, pid)

    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))

    def emit(tag, state):
        flat, _ = jax.tree_util.tree_flatten(jax.device_get(state.params))
        digest = float(sum(np.float64(np.abs(x).sum()) for x in flat))
        probe = np.asarray(
            state.params["rpn"]["rpn_conv_3x3"]["kernel"]).ravel()[:4]
        probe = np.asarray(jax.device_get(probe))
        print(f"{tag} DIGEST {digest:.10e}", flush=True)
        print(f"{tag} PROBE " + " ".join(f"{v:.10e}" for v in probe),
              flush=True)
        print(f"{tag} STEP {int(jax.device_get(state.step))}", flush=True)

    prefix = os.path.join(ckpt_dir, "mp")

    # phase 1: one epoch, k=1, epoch-end orbax save on ALL ranks
    state = fit(cfg, model, params, make_loader(), begin_epoch=0,
                end_epoch=1, plan=plan, frequent=1, seed=0, prefix=prefix)
    emit("PHASE1", state)

    # phase 2: restart from the saved epoch-1 checkpoint and train one
    # more epoch — orbax multi-host RESTORE barriers under two processes
    state = fit(cfg, model, params, make_loader(), begin_epoch=1,
                end_epoch=2, plan=plan, frequent=1, seed=0, prefix=prefix,
                resume=True)
    emit("PHASE2", state)

    # phase 3: fresh state, steps_per_dispatch=2 — the two 8-row batches
    # of the epoch form ONE stacked (2, local_rows, ...) group, assembled
    # on the prefetch thread and globalized via
    # global_from_local(stacked=True) on the 2-process mesh
    state = fit(cfg, model, params, make_loader(), begin_epoch=0,
                end_epoch=1, plan=plan, frequent=1, seed=0,
                steps_per_dispatch=2)
    emit("PHASE3", state)

    if nproc > 1:
        from mx_rcnn_tpu.parallel import sync

        # the digest work above runs per-rank unsynchronized; align before
        # interpreter teardown so the atexit shutdown barrier sees both
        # ranks together even on a heavily loaded host
        sync("worker_done")


if __name__ == "__main__":
    main(int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
