"""Multi-model serving tier-1 tests (CPU).

The :class:`~mx_rcnn_tpu.serve.pool.ModelPool` contract from three
angles: (1) registry + frontend routing — ``?model=``/doc-field
resolution, default-model fallback, 404s for unknown ids and for
explicit ids on a pool-less server; (2) device weight residency — the
byte budget holds through a paging stress loop (device bytes asserted
under budget after EVERY operation), LRU picks the coldest victim,
pinned models are never paged out, and a paged-out model still answers
correctly (params are runtime args — zero recompiles by construction);
(3) the real thing — two synthetic-weight models with distinct config
digests behind one socket, per-model warmup, mixed cross-model traffic,
and the acceptance assert: each model's engine recompile counter stays
equal to its warmup_programs (zero steady-state recompiles per model).
"""

import dataclasses
import threading
import time

import numpy as np
import pytest

from mx_rcnn_tpu.serve import (ModelPool, ServeEngine, ServeOptions,
                               encode_image_payload, make_server,
                               param_nbytes, unix_http_request, warmup)
from tests.test_serve import FakePredictor, raw_image, tiny_cfg


def make_pool_engine(cfg, **opts):
    defaults = dict(batch_size=2, max_delay_ms=1.0, max_queue=32)
    defaults.update(opts)
    eng = ServeEngine(FakePredictor(cfg), cfg, ServeOptions(**defaults))
    eng.start(external=True)
    return eng


def add_fake_model(pool, cfg, mid, params=None, **kw):
    pred = FakePredictor(cfg)
    if params is not None:
        pred.params = params
    eng = ServeEngine(pred, cfg, ServeOptions(
        batch_size=2, max_delay_ms=1.0, max_queue=32))
    eng.start(external=True)
    pool.add_model(mid, cfg, pred, eng, **kw)
    return pred, eng


def mib_params(n_mib):
    return {"w": np.zeros((n_mib, 1 << 18), np.float32)}  # n MiB


# -- registry + routing ----------------------------------------------------


def test_pool_registry_defaults_and_bad_ids():
    cfg = tiny_cfg()
    pool = ModelPool()
    with pytest.raises(KeyError):
        pool.entry()  # empty pool
    add_fake_model(pool, cfg, "a")
    add_fake_model(pool, cfg, "b")
    assert pool.model_ids() == ["a", "b"]
    assert pool.default_model == "a"
    assert pool.entry().model_id == "a"          # None -> default
    assert pool.entry("b").model_id == "b"
    with pytest.raises(KeyError):
        pool.entry("zzz")
    with pytest.raises(ValueError):
        add_fake_model(pool, cfg, "a")           # duplicate id
    with pytest.raises(ValueError):
        add_fake_model(pool, cfg, "x/y")         # path-hostile id
    pool.stop()


def test_pool_frontend_routing_and_404s(tmp_path):
    cfg = tiny_cfg()
    pool = ModelPool().start()
    pred_a, _ = add_fake_model(pool, cfg, "a")
    pred_b, _ = add_fake_model(pool, cfg, "b")
    sock = str(tmp_path / "pool.sock")
    server = make_server(pool.engine_for(), unix_socket=sock, pool=pool)
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    try:
        status, h = unix_http_request(sock, "GET", "/healthz")
        assert status == 200 and h["models"] == ["a", "b"]

        # default (no selector) -> model "a"; ?model= and doc field route
        img = raw_image(60, 100, 9)
        assert unix_http_request(sock, "POST", "/predict",
                                 encode_image_payload(img),
                                 timeout=60)[0] == 200
        assert unix_http_request(sock, "POST", "/predict?model=b",
                                 encode_image_payload(img),
                                 timeout=60)[0] == 200
        doc = encode_image_payload(img)
        doc["model"] = "b"
        assert unix_http_request(sock, "POST", "/predict", doc,
                                 timeout=60)[0] == 200
        assert len(pred_a.batches) == 1 and len(pred_b.batches) == 2

        # unknown model: 404 with the id echoed, traffic unharmed
        status, err = unix_http_request(
            sock, "POST", "/predict?model=zzz",
            encode_image_payload(img), timeout=60)
        assert status == 404 and "zzz" in err["error"]

        # pool-mode /metrics: multimodel doc with per-model engines,
        # aggregated counters, and the pool scheduling/residency block
        status, m = unix_http_request(sock, "GET", "/metrics")
        assert status == 200 and m["multimodel"] is True
        assert m["default_model"] == "a"
        assert set(m["models"]) == {"a", "b"}
        # routing 404s never reach an engine: 3 served requests only
        assert m["counters"]["requests"] == 3
        assert m["pool"]["counters"]["sched_batches"] >= 3
        assert m["residency"]["resident_models"] == 2

        # prometheus exposition carries one rank per model + "pool"
        status, raw = unix_http_request(
            sock, "GET", "/metrics?format=prometheus")
        text = raw if isinstance(raw, str) else raw.get("raw", "")
        assert 'rank="a"' in text and 'rank="b"' in text
        assert 'rank="pool"' in text
        assert "mxr_serve_sched_batches_total" in text
    finally:
        server.shutdown()
        server.server_close()
        pool.stop()


def test_explicit_model_without_pool_is_404(tmp_path):
    # single-model boot: the pool-less server must refuse explicit model
    # selectors loudly instead of silently serving the wrong weights
    cfg = tiny_cfg()
    engine = ServeEngine(FakePredictor(cfg), cfg, ServeOptions(
        batch_size=2, max_delay_ms=1.0, max_queue=8)).start()
    sock = str(tmp_path / "single.sock")
    server = make_server(engine, unix_socket=sock)
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    try:
        img = raw_image(60, 100, 5)
        status, err = unix_http_request(
            sock, "POST", "/predict?model=a",
            encode_image_payload(img), timeout=60)
        assert status == 404 and "routing not enabled" in err["error"]
        # no selector: byte-for-byte the old single-model path
        assert unix_http_request(sock, "POST", "/predict",
                                 encode_image_payload(img),
                                 timeout=60)[0] == 200
    finally:
        server.shutdown()
        server.server_close()
        engine.stop()


# -- cross-model scheduling ------------------------------------------------


def test_pool_interleaves_models_and_counts_switches():
    cfg = tiny_cfg()
    pool = ModelPool().start()
    pred_a, eng_a = add_fake_model(pool, cfg, "a", weight=2.0)
    pred_b, eng_b = add_fake_model(pool, cfg, "b", weight=1.0)
    try:
        futs = []
        for i in range(8):
            eng = eng_a if i % 2 else eng_b
            futs.append(eng.submit(raw_image(60, 100, i)))
        for f in futs:
            f.result(timeout=60)
        assert pred_a.batches and pred_b.batches  # both models served
        m = pool.metrics()
        assert m["pool"]["counters"]["sched_batches"] >= 4
        assert m["pool"]["counters"]["sched_switches"] >= 1
        assert m["pool"]["batches"]["a"] >= 1
        assert m["pool"]["batches"]["b"] >= 1
        assert m["counters"]["requests"] == 8
        assert m["queue_depth"] == 0
    finally:
        pool.stop()


def test_pool_slo_controller_per_model_labels():
    from mx_rcnn_tpu.serve import ControllerOptions, SLOController

    cfg = tiny_cfg()
    pool = ModelPool().start()
    pred_a, eng_a = add_fake_model(pool, cfg, "a")
    ctrl = SLOController(eng_a, ControllerOptions(
        target_p99_ms=150.0, label="a"))
    pool.entry("a").controller = ctrl
    try:
        assert ctrl.state()["label"] == "a"
        # controller acts on ITS engine only — the pool only wires one
        # controller per entry, there is no shared admission state
        assert ctrl.engine is eng_a
    finally:
        pool.stop()  # stops the controller too (idempotent if unstarted)


# -- weight residency ------------------------------------------------------


def test_paging_budget_stress_lru_and_pinned(caplog):
    cfg = tiny_cfg()
    budget = 9 * (1 << 20)
    pool = ModelPool(budget_bytes=budget).start()
    # pin = 4 MiB always resident; a/b/c = 4 MiB each, only ONE fits
    # beside the pinned set at a time
    preds = {}
    preds["pin"], _ = add_fake_model(pool, cfg, "pin",
                                     params=mib_params(4), pinned=True)
    for mid in ("a", "b", "c"):
        preds[mid], _ = add_fake_model(pool, cfg, mid,
                                       params=mib_params(4))
    try:
        assert pool.resident_bytes() <= budget

        # stress: 30 interleaved residency demands; the budget must hold
        # after EVERY step and the pinned model must never page out
        rng = np.random.RandomState(0)
        for i in range(30):
            mid = ("a", "b", "c")[rng.randint(3)]
            pool.ensure_resident(mid)
            assert pool.entry(mid).resident
            assert pool.resident_bytes() <= budget, (i, mid)
            assert pool.entry("pin").resident
        assert pool.entry("pin").page_outs == 0
        assert pool.counters["weight_page_out"] >= 1
        assert pool.counters["weight_page_in"] >= 1

        # LRU: touch order a, b -> demanding c must evict a (coldest)
        pool.ensure_resident("a")
        time.sleep(0.002)
        pool.ensure_resident("b")  # pages a out already (budget of one)
        time.sleep(0.002)
        pool.ensure_resident("c")
        assert not pool.entry("a").resident
        assert pool.entry("c").resident

        # a paged-out model still answers (params travel as runtime
        # args) — and dispatch pages it back in via ensure_resident
        eng_a = pool.engine_for("a")
        assert eng_a.submit(raw_image(60, 100, 3)).result(timeout=60)
        assert pool.entry("a").resident

        # residency doc shape: budget, live bytes, per-model gauges
        res = pool.residency()
        assert res["budget_bytes"] == budget
        assert res["device_bytes"] <= budget
        assert set(res["models"]) == {"pin", "a", "b", "c"}
        assert res["models"]["pin"]["pinned"] is True
        assert res["models"]["pin"]["page_outs"] == 0
    finally:
        pool.stop()


def test_paging_restores_identical_weights():
    # page-out snapshots to host, page-in device_puts the snapshot: the
    # values a model serves with must survive the round trip exactly
    import jax

    cfg = tiny_cfg()
    rng = np.random.RandomState(3)
    w = {"k": rng.rand(256, 256).astype(np.float32)}
    pool = ModelPool(budget_bytes=2 * w["k"].nbytes
                     + (1 << 16)).start()
    pred_a, _ = add_fake_model(
        pool, cfg, "a", params=jax.device_put(dict(w)))
    pred_b, _ = add_fake_model(
        pool, cfg, "b", params=jax.device_put(
            {"k": np.zeros((256, 256), np.float32)}))
    pred_c, _ = add_fake_model(
        pool, cfg, "c", params=jax.device_put(
            {"k": np.ones((256, 256), np.float32)}))
    try:
        assert not pool.entry("a").resident  # evicted by b+c arriving
        pool.ensure_resident("a")            # ...and paged back in
        assert pool.entry("a").resident
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(pred_a.params["k"])), w["k"])
        assert param_nbytes(pred_a.params) == w["k"].nbytes
    finally:
        pool.stop()


def test_pinned_set_over_budget_is_refused():
    cfg = tiny_cfg()
    pool = ModelPool(budget_bytes=6 * (1 << 20))
    add_fake_model(pool, cfg, "p1", params=mib_params(4), pinned=True)
    with pytest.raises(ValueError):
        add_fake_model(pool, cfg, "p2", params=mib_params(4), pinned=True)
    pool.stop()


# -- the real thing --------------------------------------------------------


def test_multimodel_e2e_two_real_models_zero_recompiles(tmp_path):
    """Two synthetic-weight models (distinct config digests, hence
    disjoint program keys and AOT subtrees) behind one socket: per-model
    warmup compiles one program per orientation EACH, mixed cross-model
    traffic serves with zero further recompiles per model (the
    acceptance counter assert), and the pool scheduler interleaves both
    engines."""
    import jax

    from mx_rcnn_tpu import telemetry
    from mx_rcnn_tpu.compile import config_digest
    from mx_rcnn_tpu.eval import Predictor
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

    cfg_a = tiny_cfg()
    # a digest-changing knob: model b is a different deployment of the
    # same network — the realistic multi-tenant shape on one chip
    cfg_b = tiny_cfg().replace(
        TEST=dataclasses.replace(tiny_cfg().TEST, NMS=0.31))
    assert config_digest(cfg_a) != config_digest(cfg_b)

    telemetry.configure(str(tmp_path / "tel"), run_meta={"driver": "test"})
    pool = ModelPool().start()
    for mid, cfg in (("a", cfg_a), ("b", cfg_b)):
        model = build_model(cfg)
        params = denormalize_for_save(
            init_params(model, cfg, jax.random.PRNGKey(0), 2, (96, 128)),
            cfg)
        pred = Predictor(model, params, cfg)
        engine = ServeEngine(pred, cfg, ServeOptions(
            batch_size=2, max_delay_ms=5.0, max_queue=16))
        engine.start(external=True)
        pool.add_model(mid, cfg, pred, engine)
        assert warmup(engine) == 2  # one program per orientation

    sock = str(tmp_path / "mm.sock")
    server = make_server(pool.engine_for(), unix_socket=sock, pool=pool)
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    try:
        status, r = unix_http_request(sock, "GET", "/readyz")
        assert status == 200 and r["ready"] is True
        assert set(r["models"]) == {"a", "b"}

        rng = np.random.RandomState(11)
        shapes = ((60, 100), (100, 60), (48, 90), (90, 48))
        for i, (h, w) in enumerate(shapes * 2):
            img = rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
            doc = encode_image_payload(img)
            doc["model"] = "ab"[i % 2]
            status, resp = unix_http_request(sock, "POST", "/predict",
                                             doc, timeout=300)
            assert status == 200, resp
            assert resp["detections"] is not None

        # the acceptance assert: per-model recompile counters — every
        # model's engine saw exactly its warmup compiles and not one more
        status, m = unix_http_request(sock, "GET", "/metrics")
        assert status == 200
        for mid in ("a", "b"):
            c = m["models"][mid]["counters"]
            assert c["warmup_programs"] == 2, (mid, c)
            assert c["recompiles"] == c["warmup_programs"], (mid, c)
        assert m["counters"]["recompiles"] == 4  # 2 models x 2 buckets
        assert m["pool"]["batches"]["a"] >= 1
        assert m["pool"]["batches"]["b"] >= 1
        summ = telemetry.get().summary()
        assert (summ["counters"]["serve/recompile"]
                == summ["counters"]["serve/warmup_programs"] == 4)
    finally:
        server.shutdown()
        server.server_close()
        pool.stop()
        telemetry.shutdown()
