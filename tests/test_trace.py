"""Distributed request tracing (ISSUE 16): cross-hop trace context,
batch-causality spans, tail-sampled forensics.

Four layers, mirroring the subsystem split:

* **Context** — the ``X-Mxr-Trace`` header grammar round trip
  (trace / trace-span / trace-span-flags, all-zero span = no parent,
  flags 00 = unsampled), child derivation, malformed → None.
* **Tracer** — span records in the telemetry JSONL schema (additive
  ``kind: "span"`` fields), the tail verdict (errored / non-200 /
  hedged-retried-shed always kept; slow kept against the windowed-p99
  of ROOT durations with the observe-after-verdict cold-start rule),
  atomic tail dumps, per-trace span budget, and the NULL-tracer
  zero-overhead pin (a tracing-off hot path that ever mints or records
  RAISES — the ``NULL_CAPTURE`` contract).
* **Hot-path inertness** — tracing off, a real engine round trip via
  ``handle_request_doc`` produces a response identical to the traced
  shape minus exactly the ``"trace"`` echo key, emits zero span events,
  and exposes no ``trace`` metrics section.
* **End to end** — one client-minted trace id through a REAL two-member
  TCP fabric (``tests/fabric_worker.py`` subprocesses with
  ``MXR_TRACE_DIR`` opt-in + an in-process router tracer): the id is
  queryable across ≥3 hop types and ≥2 members by merging the
  per-member span files, exactly as ``scripts/trace_query.py`` does.
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.serve import encode_image_payload
from mx_rcnn_tpu.serve import fabric as fb
from mx_rcnn_tpu.serve.frontend import handle_request_doc
from mx_rcnn_tpu.telemetry import tracectx
from mx_rcnn_tpu.telemetry.tracectx import (NULL_SPAN, NULL_TRACER,
                                            SPANS_PREFIX, TAIL_PREFIX,
                                            TraceContext, Tracer)
from tests.test_serve import make_engine, raw_image, tiny_cfg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "fabric_worker.py")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _restore_tracer():
    yield
    tracectx.shutdown()
    telemetry.shutdown()


# -- context grammar --------------------------------------------------------


def test_context_parse_grammar_and_header_round_trip():
    t = "ab" * 16
    s = "cd" * 8
    full = TraceContext.parse(f"{t}-{s}-01")
    assert (full.trace_id, full.span_id, full.sampled) == (t, s, True)
    assert TraceContext.parse(full.to_header()).span_id == s
    # bare id and all-zero span id both mean "no parent yet": the first
    # span recorded under them is the trace's ROOT
    assert TraceContext.parse(t).span_id is None
    assert TraceContext.parse(f"{t}-{'0' * 16}-01").span_id is None
    # flags 00 = unsampled propagation
    assert TraceContext.parse(f"{t}-{s}-00").sampled is False
    # malformed → None (a frontend mints fresh, never serves garbage)
    for bad in ("", "xyz", "12", f"{t}-GG", f"{t}-{s}-01-extra", 7, None):
        assert TraceContext.parse(bad) is None
    child = full.child()
    assert child.trace_id == t and child.span_id != s
    assert len(child.span_id) == 16


def test_null_tracer_raises_and_null_span_is_inert():
    """The zero-overhead pin: the disabled tracer's recording methods
    RAISE, so surviving a tracing-off round trip proves the hot path
    paid only the ``enabled`` check."""
    assert tracectx.get() is NULL_TRACER
    assert not NULL_TRACER.enabled
    with pytest.raises(RuntimeError, match="disabled"):
        NULL_TRACER.mint()
    with pytest.raises(RuntimeError, match="disabled"):
        NULL_TRACER.span(None, "x")
    with pytest.raises(RuntimeError, match="disabled"):
        NULL_TRACER.record(None, "x", 0.0)
    with NULL_SPAN as sp:
        sp.set(anything="goes")
    assert NULL_SPAN.ctx is None


# -- tracer sink ------------------------------------------------------------


def test_spans_stream_in_telemetry_schema_with_parentage(tmp_path):
    tr = tracectx.configure(str(tmp_path), member="m0", sample=1.0)
    ctx = tr.mint()
    with tr.span(ctx, "fabric/route") as sp:
        child_ctx = sp.ctx
        with tr.span(child_ctx, "frontend/predict") as sp2:
            sp2.set(status=200)
        sp.set(member="m1", status=200)
    path = os.path.join(str(tmp_path), f"{SPANS_PREFIX}m0.jsonl")
    with open(path) as f:
        recs = [json.loads(line) for line in f]
    assert [r["name"] for r in recs] == ["frontend/predict", "fabric/route"]
    inner, root = recs
    for r in recs:
        # additive fields on the v1 schema: old readers key on "kind"
        assert r["kind"] == "span" and r["v"] == 1
        assert r["trace"] == ctx.trace_id and r["member"] == "m0"
        assert r["dur_s"] >= 0.0 and "ts" in r
    assert "psid" not in root                  # minted ctx → true root
    assert inner["psid"] == root["sid"] == child_ctx.span_id
    assert inner["attrs"]["status"] == 200
    assert root["attrs"]["member"] == "m1"
    m = tr.metrics()
    assert m["spans_emitted"] == 2 and m["live_traces"] == 0


def test_unsampled_context_records_nothing(tmp_path):
    tr = tracectx.configure(str(tmp_path), member="m0", sample=0.0)
    ctx = tr.mint()                            # sample=0 → unsampled mint
    assert not ctx.sampled
    assert tr.span(ctx, "fabric/route") is NULL_SPAN
    assert tr.record(ctx, "x", 0.1) is None
    assert tr.record(None, "x", 0.1) is None
    assert tr.metrics()["spans_emitted"] == 0


def test_span_exception_lands_as_error_attr_and_is_tail_kept(tmp_path):
    tr = tracectx.configure(str(tmp_path), member="m0")
    with pytest.raises(ValueError):
        with tr.span(tr.mint(), "frontend/predict"):
            raise ValueError("boom")
    tail = os.path.join(str(tmp_path), f"{TAIL_PREFIX}m0.jsonl")
    with open(tail) as f:
        rec = json.loads(f.readline())
    assert rec["attrs"]["error"].startswith("ValueError: boom")
    assert tr.metrics()["tail_kept"] == 1


def test_tail_verdict_slow_errored_and_flagged_roots(tmp_path):
    """Cold-start observe-after-verdict: the FIRST clean root has no
    window yet and is dropped; after a fast population, a slow root (≥
    the windowed p99) is kept, as are non-200 and hedged roots at any
    speed."""
    tr = tracectx.configure(str(tmp_path), member="m0")
    tr.record(tr.mint(), "root", 0.001, attrs={"status": 200})
    assert tr.metrics()["tail_kept"] == 0      # no window on request #1
    for _ in range(8):
        tr.record(tr.mint(), "root", 0.001, attrs={"status": 200})
    kept_before = tr.metrics()["tail_kept"]
    tr.record(tr.mint(), "root", 2.0, attrs={"status": 200})   # slow
    assert tr.metrics()["tail_kept"] == kept_before + 1
    tr.record(tr.mint(), "root", 0.0001, attrs={"status": 503})
    tr.record(tr.mint(), "root", 0.0001, attrs={"hedged": True})
    assert tr.metrics()["tail_kept"] == kept_before + 3
    # the dump is a complete, parseable snapshot of the kept ring
    tail = os.path.join(str(tmp_path), f"{TAIL_PREFIX}m0.jsonl")
    with open(tail) as f:
        rows = [json.loads(line) for line in f]
    assert len(rows) == kept_before + 3
    assert not [n for n in os.listdir(str(tmp_path)) if ".tmp." in n]


def test_per_trace_span_budget_drops_not_grows(tmp_path):
    tr = tracectx.configure(str(tmp_path), member="m0")
    ctx = tr.mint().child()                    # non-root: never finalizes
    for _ in range(tracectx.MAX_SPANS_PER_TRACE + 5):
        tr.record(ctx, "loop", 0.001)
    m = tr.metrics()
    assert m["spans_emitted"] == tracectx.MAX_SPANS_PER_TRACE
    assert m["spans_dropped"] == 5 and m["live_traces"] == 1


def test_configure_from_env_opt_in_and_no_op(tmp_path, monkeypatch):
    monkeypatch.delenv(tracectx.ENV_TRACE_DIR, raising=False)
    assert tracectx.configure_from_env(member="m9") is None
    assert tracectx.get() is NULL_TRACER
    monkeypatch.setenv(tracectx.ENV_TRACE_DIR, str(tmp_path))
    monkeypatch.setenv(tracectx.ENV_TRACE_SAMPLE, "0.25")
    tr = tracectx.configure_from_env(member="m9", rank=3)
    assert tr is tracectx.get() and tr.enabled
    assert tr.member == "m9" and tr.rank == 3
    assert tr.sample == pytest.approx(0.25)
    # second call is a no-op while a tracer is live (serve.py configures
    # first; serve_replica's env hook must not clobber it)
    assert tracectx.configure_from_env(member="other") is None
    assert tracectx.get() is tr


# -- hot-path inertness (tracing off) ---------------------------------------


def test_tracing_off_predict_is_byte_identical_minus_echo(tmp_path):
    """The acceptance pin: with tracing off, a /predict response with a
    client-minted id differs from the untraced response by EXACTLY the
    ``"trace"`` echo key; no span file is written, no trace metrics
    section appears, and the engine's hot path never reached the (raising)
    NULL tracer."""
    assert tracectx.get() is NULL_TRACER
    engine = make_engine(tiny_cfg()).start()
    try:
        doc = encode_image_payload(raw_image(60, 100, 40))
        status_a, resp_a = handle_request_doc(engine, dict(doc))
        tid = "ab" * 16
        status_b, resp_b = handle_request_doc(engine, dict(doc, trace=tid))
        assert status_a == status_b == 200
        assert "trace" not in resp_a
        assert resp_b.pop("trace") == tid
        assert resp_a["detections"] == resp_b["detections"]
        assert set(resp_a) == set(resp_b)
        # header form echoes just the trace id, not the span suffix
        _, resp_c = handle_request_doc(
            engine, dict(doc), trace_header=f"{tid}-{'cd' * 8}-01")
        assert resp_c["trace"] == tid
        assert "trace" not in engine.metrics()
    finally:
        engine.stop()
    assert not [n for n in os.listdir(str(tmp_path))
                if n.startswith((SPANS_PREFIX, TAIL_PREFIX))]


# -- engine batch-causality -------------------------------------------------


def test_engine_batch_causality_spans(tmp_path):
    """Three same-bucket requests coalesced into one batch: each traced
    request's ``engine/request`` span names its batch peers, queue
    position, and pad fraction; the ``engine/dispatch`` child names every
    rid that shared the program run; phase children hang below it."""
    tr = tracectx.configure(str(tmp_path), member="m0")
    engine = make_engine(tiny_cfg(), batch_size=4, max_delay_ms=200,
                         max_queue=16).start()
    try:
        ctxs = [tr.mint() for _ in range(3)]
        futs = [engine.submit(raw_image(60, 100, 30 + 5 * i), trace=c)
                for i, c in enumerate(ctxs)]
        for f in futs:
            assert f.result(timeout=30.0)
        # spans land on the flush tail AFTER the futures resolve: wait
        # for every request's engine/request + engine/dispatch pair
        _wait(lambda: tr.metrics()["spans_emitted"] >= 6,
              timeout=30.0, what="batch-causality spans")
        assert engine.metrics()["trace"]["spans_emitted"] >= 6
    finally:
        engine.stop()
    with open(os.path.join(str(tmp_path), f"{SPANS_PREFIX}m0.jsonl")) as f:
        recs = [json.loads(line) for line in f]
    by_trace = {}
    for r in recs:
        by_trace.setdefault(r["trace"], {})[r["name"]] = r
    assert set(by_trace) == {c.trace_id for c in ctxs}
    all_rids = set()
    for ctx in ctxs:
        tree = by_trace[ctx.trace_id]
        req = tree["engine/request"]
        disp = tree["engine/dispatch"]
        a = req["attrs"]
        all_rids.add(a["rid"])
        assert set(a["peers"]) == {r2["attrs"]["rid"]
                                   for t2, r2 in (
                                       (t, by_trace[t]["engine/request"])
                                       for t in by_trace)
                                   if t2 != ctx.trace_id}
        assert 0 <= a["queue_pos"] < 3 and a["queue_wait_ms"] >= 0.0
        assert a["pad_frac"] == pytest.approx(0.25)    # 3 of 4 rows live
        assert a["occupancy"] == "3/4" and a["bucket"]
        # dispatch is the request span's child and names the whole batch
        assert disp["psid"] == req["sid"]
        assert set(disp["attrs"]["batch_rids"]) >= {a["rid"], *a["peers"]}
        # at least one measured phase child hangs off the dispatch
        phases = [r for r in recs if r["trace"] == ctx.trace_id
                  and r.get("psid") == disp["sid"]]
        assert {p["name"] for p in phases} <= {
            "engine/h2d", "engine/forward", "engine/readback",
            "engine/postprocess"}
        assert phases
    assert len(all_rids) == 3


# -- query tool -------------------------------------------------------------


def test_trace_query_merges_dedupes_and_renders(tmp_path):
    tq = _load_script("trace_query")
    tr = tracectx.configure(str(tmp_path), member="m0")
    ctx = tr.mint()
    with tr.span(ctx, "fabric/route") as sp:
        with tr.span(sp.ctx, "frontend/predict") as sp2:
            sp2.set(status=503)                # non-200 root → tail kept
        sp.set(status=503)
    fast = tr.mint()
    tr.record(fast, "fabric/route", 0.0001, attrs={"status": 200})
    tracectx.shutdown()

    spans = tq.load_spans(str(tmp_path))
    traces = tq.group_traces(spans)
    # the kept trace appears in BOTH streams but dedupes to one tree
    assert len(traces[ctx.trace_id]) == 2
    lines = [tq.summary_line(ctx.trace_id, traces[ctx.trace_id])]
    tq.render_tree(traces[ctx.trace_id], lines)
    text = "\n".join(lines)
    assert "fabric/route" in text and "frontend/predict" in text
    assert "status=503" in text and "[m0]" in text
    # prefix resolution: unique prefix hits, ambiguous/missing raise
    assert tq.resolve_ids(traces, [ctx.trace_id[:10]]) == [ctx.trace_id]
    with pytest.raises(SystemExit, match="no trace"):
        tq.resolve_ids(traces, ["ffffffffff"])
    # an orphan (parent span never landed) surfaces as an extra root
    orphan = {"trace": ctx.trace_id, "sid": "aa" * 8, "psid": "bb" * 8,
              "name": "engine/request", "dur_s": 0.1, "member": "m1",
              "kind": "span"}
    roots = tq.roots_of(traces[ctx.trace_id] + [orphan])
    assert orphan in roots and len(roots) == 2


def test_loadgen_trace_helpers_and_perf_gate_rows(tmp_path):
    lg = _load_script("loadgen")
    ok = (200, 0.01, 0.0, None, 0.1)
    bad = (200, 0.01, 0.0,
           "trace echo mismatch: sent aa, got None", 0.1)
    assert lg.trace_echo_failure([ok, ok]) is None
    msg = lg.trace_echo_failure([ok, bad])
    assert msg and "trace echo assertion failed" in msg
    pg = _load_script("perf_gate")
    doc = {"schema": "mxr_slo_report",
           "scenarios": [{"name": "steady", "p50_ms": 10.0, "p99_ms": 30.0,
                          "error_rate": 0.0, "traced": 12, "tail_kept": 2}]}
    rows = {r["metric"]: r for r in pg.slo_report_rows(doc)}
    assert rows["slo_steady_traced"]["value"] == 12
    assert rows["slo_steady_tail_kept"]["value"] == 2
    # the report file passes --check-format with the additive fields
    path = tmp_path / "SLO_r01.json"
    path.write_text(json.dumps(doc))
    assert pg.check_format([str(path)]) == []


# -- end to end: one trace id across a real two-member fabric ---------------


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait(cond, timeout=90.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


def test_e2e_one_trace_id_across_router_and_members(tmp_path):
    """The acceptance pin: a client-minted trace id sent through a REAL
    router + two REAL TCP member subprocesses (tracing opted in via
    ``MXR_TRACE_DIR``) is queryable end to end — ≥3 hop types across ≥2
    members under ONE id — by merging the per-member span files the way
    ``scripts/trace_query.py`` does."""
    trace_dir = str(tmp_path / "traces")
    ports = [_free_port(), _free_port()]
    procs = [subprocess.Popen(
        [sys.executable, WORKER, "--port", str(ports[i]),
         "--replica-index", str(i)],
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             tracectx.ENV_TRACE_DIR: trace_dir,
             tracectx.ENV_TRACE_MEMBER: f"member{i}"})
        for i in range(2)]
    tracectx.configure(trace_dir, member="router")
    pool = fb.ReplicaPool(fb.FabricOptions(
        probe_interval_s=0.2, probe_timeout_s=2.0, evict_probes=2,
        start_timeout_s=120.0, backoff_base_s=0.2, backoff_max_s=1.0,
        stable_s=5.0, drain_timeout_s=15.0, reload_timeout_s=60.0))
    for port in ports:
        pool.register(f"127.0.0.1:{port}")
    pool.start()
    tq = _load_script("trace_query")
    try:
        _wait(lambda: pool.ready_count() == 2, what="both members ready")
        router = fb.FabricRouter(pool, timeout_s=30.0)
        doc = encode_image_payload(raw_image(60, 100, 50))
        tids = []
        for i in range(4):
            tid = os.urandom(16).hex()
            body = json.dumps(dict(doc, trace=tid)).encode()
            status, raw, _ = router.route_predict(body)
            assert status == 200, raw
            # the member echoes the SAME id back through the router: the
            # cross-host correlation handle the client keys on
            assert json.loads(raw)["trace"] == tid
            tids.append(tid)

        def landed():
            traces = tq.group_traces(tq.load_spans(trace_dir))
            return all(
                t in traces
                and len({r["name"] for r in traces[t]}) >= 3
                and len({r["member"] for r in traces[t]}) >= 2
                for t in tids)

        # member span files flush per record but land asynchronously
        # with the response
        _wait(landed, timeout=30.0, what="spans from every hop on disk")
        traces = tq.group_traces(tq.load_spans(trace_dir))
        for tid in tids:
            recs = traces[tid]
            names = {r["name"] for r in recs}
            assert {"fabric/route", "frontend/predict",
                    "engine/request"} <= names
            members = {r["member"] for r in recs}
            assert "router" in members
            assert members & {"member0", "member1"}
            # parentage is a single connected tree: the router's route
            # span is the ONE true root
            roots = tq.roots_of(recs)
            assert [r["name"] for r in roots] == ["fabric/route"]
            # the member-side frontend span hangs off the router's span
            route = roots[0]
            fronts = [r for r in recs if r["name"] == "frontend/predict"]
            assert any(r.get("psid") == route["sid"] for r in fronts)
        # the tree renders as one indented multi-member hop tree
        lines = []
        tq.render_tree(traces[tids[0]], lines)
        text = "\n".join(lines)
        assert "fabric/route" in text and "engine/request" in text
    finally:
        pool.stop()
        for p in procs:
            p.kill()
            p.wait(timeout=30)
