"""Numeric parity of the torch→npz converter against a REAL torch model.

Round-1 only checked tree coverage (every expected path present); this
executes an actual ``torch.nn`` ResNet-50 / VGG-16 — built with
torchvision's exact module naming so the state_dict keys are the real
checkpoint keys — and asserts our flax models produce the SAME features
from the converted weights.  This is the strongest pretrained-weights
evidence available offline: when a genuine torchvision .pth appears, the
only untested delta is the download.

Covers the subtle conversion paths: OIHW→HWIO, frozen-BN fold (scale into
kernel + shift), the space-to-depth stem regroup (vs torch's direct 7×7/2),
downsample→sc_conv/sc_bn, stage-4-as-RoI-head, and VGG's CHW→HWC fc6
flatten permute.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn as nn

from mx_rcnn_tpu.models.backbones import ResNetConv, ResNetStage5, VGGConv, VGGFC
from mx_rcnn_tpu.utils.convert_torch import convert


# ---- torchvision-faithful torch models (exact state_dict keys) -----------

class Bottleneck(nn.Module):
    def __init__(self, cin, width, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, width, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(width)
        self.conv2 = nn.Conv2d(width, width, 3, stride=stride, padding=1,
                               bias=False)
        self.bn2 = nn.BatchNorm2d(width)
        self.conv3 = nn.Conv2d(width, width * 4, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(width * 4)
        self.relu = nn.ReLU(inplace=True)
        self.downsample = downsample

    def forward(self, x):
        idt = x if self.downsample is None else self.downsample(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return self.relu(out + idt)


def _layer(cin, width, units, stride):
    down = nn.Sequential(nn.Conv2d(cin, width * 4, 1, stride=stride,
                                   bias=False), nn.BatchNorm2d(width * 4))
    mods = [Bottleneck(cin, width, stride, down)]
    mods += [Bottleneck(width * 4, width) for _ in range(units - 1)]
    return nn.Sequential(*mods)


class TorchResNet50(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, stride=2, padding=3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.relu = nn.ReLU(inplace=True)
        self.maxpool = nn.MaxPool2d(3, stride=2, padding=1)
        self.layer1 = _layer(64, 64, 3, 1)
        self.layer2 = _layer(256, 128, 4, 2)
        self.layer3 = _layer(512, 256, 6, 2)
        self.layer4 = _layer(1024, 512, 3, 2)

    def forward(self, x):
        """→ the stride-16 c4 feature (conv1 through layer3), matching our
        ResNetConv's output; layer4 is exercised separately as the head."""
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        return self.layer3(self.layer2(self.layer1(x)))


def _randomize_bn(model, rng):
    """Non-trivial running stats so the frozen-BN fold is actually tested
    (fresh BN has mean=0, var=1 which a broken fold could pass)."""
    for m in model.modules():
        if isinstance(m, nn.BatchNorm2d):
            c = m.num_features
            m.running_mean.copy_(torch.from_numpy(
                rng.randn(c).astype(np.float32) * 0.3))
            m.running_var.copy_(torch.from_numpy(
                (rng.rand(c).astype(np.float32) * 0.8 + 0.6)))
            m.weight.data.copy_(torch.from_numpy(
                rng.rand(c).astype(np.float32) * 0.5 + 0.75))
            m.bias.data.copy_(torch.from_numpy(
                rng.randn(c).astype(np.float32) * 0.2))


def _nest(flat, prefix):
    """flat {'a/b/c': arr} under prefix → nested dict (converter output →
    flax params)."""
    out = {}
    for path, arr in flat.items():
        if not path.startswith(prefix + "/"):
            continue
        parts = path[len(prefix) + 1:].split("/")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = jnp.asarray(arr)
    return out


@pytest.fixture(scope="module")
def torch_r50(rng_seed=7):
    rng = np.random.RandomState(rng_seed)
    torch.manual_seed(rng_seed)
    m = TorchResNet50()
    with torch.no_grad():
        _randomize_bn(m, rng)
    m.eval()
    return m


def test_resnet50_backbone_parity(torch_r50):
    """torch conv1→layer3 (stride 16) vs our ResNetConv from converted
    weights, f32, same input."""
    rng = np.random.RandomState(0)
    x = rng.randn(1, 3, 64, 96).astype(np.float32)

    with torch.no_grad():
        c4_t = torch_r50(torch.from_numpy(x))
    want = c4_t.numpy().transpose(0, 2, 3, 1)  # NCHW → NHWC

    sd = {k: v.numpy() for k, v in torch_r50.state_dict().items()}
    flat = convert(sd, "resnet50")
    params = _nest(flat, "backbone")

    model = ResNetConv(depth="resnet50", dtype=jnp.float32)
    init = model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 64, 96, 3)))["params"]
    # converted tree must cover the init tree exactly (no stragglers)
    assert jax.tree_util.tree_structure(init) == \
        jax.tree_util.tree_structure(params)
    got = np.asarray(model.apply({"params": params},
                                 jnp.asarray(x.transpose(0, 2, 3, 1))))

    # eps differs (torch 1e-5 vs MXNet-contract 2e-5) → ~1e-5 relative on
    # the BN scale; everything else is f32 conv reassociation
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)
    assert np.abs(got - want).mean() < 2e-4


def test_resnet50_stage4_head_parity(torch_r50):
    """torch layer4 + global avgpool vs our ResNetStage5 (the RoI head
    body) from the same converted weights."""
    rng = np.random.RandomState(1)
    x = rng.randn(2, 1024, 14, 14).astype(np.float32)
    with torch.no_grad():
        y = torch_r50.layer4(torch.from_numpy(x))
        want = y.mean(dim=(2, 3)).numpy()  # global average pool

    sd = {k: v.numpy() for k, v in torch_r50.state_dict().items()}
    params = _nest(convert(sd, "resnet50"), "head_body")
    head = ResNetStage5(depth="resnet50", dtype=jnp.float32)
    got = np.asarray(head.apply({"params": params},
                                jnp.asarray(x.transpose(0, 2, 3, 1))))
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


class TorchVGG16(nn.Module):
    """torchvision vgg16 layout: features Sequential with convs at the
    canonical indices, classifier.0/.3 = fc6/fc7."""

    def __init__(self):
        super().__init__()
        cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
               512, 512, 512, "M", 512, 512, 512, "M"]
        layers, cin = [], 3
        for v in cfg:
            if v == "M":
                layers.append(nn.MaxPool2d(2, 2))
            else:
                layers += [nn.Conv2d(cin, v, 3, padding=1), nn.ReLU(True)]
                cin = v
        self.features = nn.Sequential(*layers)
        self.classifier = nn.Sequential(
            nn.Linear(512 * 7 * 7, 4096), nn.ReLU(True), nn.Dropout(),
            nn.Linear(4096, 4096), nn.ReLU(True), nn.Dropout(),
            nn.Linear(4096, 1000))

    def forward(self, x):
        return self.features(x)


def test_vgg16_parity():
    torch.manual_seed(3)
    m = TorchVGG16().eval()
    rng = np.random.RandomState(2)

    # conv body: VGGConv has no pool after block 5 → compare at features[:30]
    x = rng.randn(1, 3, 64, 96).astype(np.float32)
    with torch.no_grad():
        want_conv = m.features[:30](torch.from_numpy(x)).numpy()
    sd = {k: v.numpy() for k, v in m.state_dict().items()}
    flat = convert(sd, "vgg16")
    conv_params = _nest(flat, "backbone")
    got_conv = np.asarray(VGGConv(dtype=jnp.float32).apply(
        {"params": conv_params}, jnp.asarray(x.transpose(0, 2, 3, 1))))
    np.testing.assert_allclose(got_conv, want_conv.transpose(0, 2, 3, 1),
                               rtol=2e-3, atol=2e-3)

    # fc6/fc7 on a pooled 7×7 feature: checks the CHW→HWC flatten permute
    p = rng.randn(2, 512, 7, 7).astype(np.float32)
    with torch.no_grad():
        t = torch.from_numpy(p).flatten(1)
        want_fc = m.classifier[4](m.classifier[3](
            m.classifier[1](m.classifier[0](t)))).numpy()  # fc6→relu→fc7→relu
    fc_params = _nest(flat, "head_body")
    got_fc = np.asarray(VGGFC(dtype=jnp.float32).apply(
        {"params": fc_params}, jnp.asarray(p.transpose(0, 2, 3, 1)),
        deterministic=True))
    np.testing.assert_allclose(got_fc, want_fc, rtol=2e-3, atol=2e-3)
