"""REAL multi-process (multi-host) training — the `dist_sync` tier.

Spawns two OS processes, each owning 4 virtual CPU devices, joined into
one 8-device global mesh by ``jax.distributed`` (Gloo collectives), and
runs the full ``fit`` loop — AnchorLoader with the ``num_parts`` row
partition, global-array batch assembly (``global_from_local``), XLA
cross-process gradient all-reduce, process-0-only logging/checkpoint
gating — then checks against a single-process 8-device control run on
the SAME global data and seeds:

* the two ranks end bit-identical (replicated state really is replicated
  across processes);
* multi-process final params match the single-process control (allclose:
  cross-process Gloo all-reduce may round differently than the
  single-process reduction).

This is the strongest multi-host evidence the environment can produce
without a second TPU host; on a pod the same code path is
``train_end2end.py --dist-auto`` (reference: SURVEY §2.2 KVStore
``dist_sync`` row — upstream left it unscripted).
"""

from __future__ import annotations

import os
import re
import subprocess
import sys

import numpy as np

WORKER = os.path.join(os.path.dirname(__file__), "mp_worker.py")
# generous: the 2-process phase measured 860 s under heavy CPU load on a
# single-core host (both ranks compile the full train step concurrently)
TIMEOUT = 2400


def _run(pid: int, nproc: int, port: int) -> subprocess.Popen:
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    repo = os.path.dirname(os.path.dirname(__file__))
    prior = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = repo + (os.pathsep + prior if prior else "")
    return subprocess.Popen(
        [sys.executable, WORKER, str(pid), str(nproc), str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)


def _parse(out: str):
    digest = float(re.search(r"DIGEST (\S+)", out).group(1))
    probe = np.asarray(
        [float(v) for v in re.search(r"PROBE (.+)", out).group(1).split()])
    return digest, probe


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_fit_matches_single_process():
    port = _free_port()
    workers = [_run(i, 2, port) for i in range(2)]
    outs = []
    try:
        for i, p in enumerate(workers):
            out, _ = p.communicate(timeout=TIMEOUT)
            outs.append(out.decode())
        for i, p in enumerate(workers):
            assert p.returncode == 0, f"rank {i} failed:\n{outs[i][-4000:]}"
    finally:
        for p in workers:  # a crashed rank must not orphan its peer
            if p.poll() is None:
                p.kill()

    control_p = _run(0, 1, port)
    try:
        out, _ = control_p.communicate(timeout=TIMEOUT)
    finally:
        if control_p.poll() is None:
            control_p.kill()
    control_out = out.decode()
    assert control_p.returncode == 0, control_out[-4000:]

    d0, p0 = _parse(outs[0])
    d1, p1 = _parse(outs[1])
    dc, pc = _parse(control_out)

    # ranks are bit-identical (the state is one replicated global array)
    assert d0 == d1 and np.array_equal(p0, p1), (d0, d1, p0, p1)
    # multi-process == single-process control up to reduction order
    np.testing.assert_allclose(p0, pc, rtol=1e-5, atol=1e-7)
    assert abs(d0 - dc) / max(abs(dc), 1.0) < 1e-5, (d0, dc)
