"""REAL multi-process (multi-host) training — the `dist_sync` tier.

Spawns two OS processes, each owning 4 virtual CPU devices, joined into
one 8-device global mesh by ``jax.distributed`` (Gloo collectives), and
runs three full ``fit`` phases — AnchorLoader with the ``num_parts`` row
partition, global-array batch assembly (``global_from_local``, flat AND
stacked), XLA cross-process gradient all-reduce, orbax save AND restore
with every rank participating — then checks against a single-process
8-device control run on the SAME global data and seeds:

* the two ranks end bit-identical after EVERY phase (replicated state
  really is replicated across processes — including through a
  checkpoint restore);
* multi-process final params match the single-process control per phase
  (allclose: cross-process Gloo all-reduce may round differently than
  the single-process reduction).

Phases (see mp_worker.py): 1 = fit+save, 2 = resume (orbax multi-host
restore barriers), 3 = steps_per_dispatch=2 (stacked global assembly on
the producer thread).

This is the strongest multi-host evidence the environment can produce
without a second TPU host; on a pod the same code path is
``train_end2end.py --dist-auto`` (reference: SURVEY §2.2 KVStore
``dist_sync`` row — upstream left it unscripted).
"""

from __future__ import annotations

import os
import re
import subprocess
import sys

import numpy as np

WORKER = os.path.join(os.path.dirname(__file__), "mp_worker.py")
# generous: the round-4 single-phase run measured 860 s under heavy CPU
# load on a single-core host (both ranks compile the full train step
# concurrently); the three-phase worker adds two more train-step compiles
# per rank (resume reuses the phase-1 program via the per-rank persistent
# cache, k=2 compiles the scanned multi-step program)
TIMEOUT = 3600

PHASES = ("PHASE1", "PHASE2", "PHASE3")


def _run(pid: int, nproc: int, port: int, ckpt_dir: str) -> subprocess.Popen:
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    repo = os.path.dirname(os.path.dirname(__file__))
    prior = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = repo + (os.pathsep + prior if prior else "")
    return subprocess.Popen(
        [sys.executable, WORKER, str(pid), str(nproc), str(port), ckpt_dir],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env)


def _parse(out: str, phase: str):
    digest = float(re.search(rf"{phase} DIGEST (\S+)", out).group(1))
    probe = np.asarray(
        [float(v)
         for v in re.search(rf"{phase} PROBE (.+)", out).group(1).split()])
    step = int(re.search(rf"{phase} STEP (\d+)", out).group(1))
    return digest, probe, step


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_fit_matches_single_process(tmp_path):
    port = _free_port()
    mp_ckpt = str(tmp_path / "mp2")  # ranks SHARE this prefix (orbax
    # writes from the primary host, barriers on both)
    workers = [_run(i, 2, port, mp_ckpt) for i in range(2)]
    outs = []
    try:
        for i, p in enumerate(workers):
            out, _ = p.communicate(timeout=TIMEOUT)
            outs.append(out.decode())
        for i, p in enumerate(workers):
            assert p.returncode == 0, f"rank {i} failed:\n{outs[i][-4000:]}"
    finally:
        for p in workers:  # a crashed rank must not orphan its peer
            if p.poll() is None:
                p.kill()

    control_p = _run(0, 1, port, str(tmp_path / "ctl"))
    try:
        out, _ = control_p.communicate(timeout=TIMEOUT)
    finally:
        if control_p.poll() is None:
            control_p.kill()
    control_out = out.decode()
    assert control_p.returncode == 0, control_out[-4000:]

    # 16 imgs / global batch 8 = 2 steps per epoch in every phase
    want_step = {"PHASE1": 2, "PHASE2": 4, "PHASE3": 2}
    for phase in PHASES:
        d0, p0, s0 = _parse(outs[0], phase)
        d1, p1, s1 = _parse(outs[1], phase)
        dc, pc, sc = _parse(control_out, phase)

        # ranks are bit-identical (the state is one replicated global
        # array) — through save, restore and stacked dispatch alike
        assert d0 == d1 and np.array_equal(p0, p1), (phase, d0, d1, p0, p1)
        assert s0 == s1 == sc == want_step[phase], (phase, s0, s1, sc)
        if phase == "PHASE2":
            # resume starts from each run's OWN phase-1 checkpoint, and
            # multi vs control phase-1 params already differ by reduction-
            # order rounding (~1e-7) — which the detector's discrete
            # top-k/NMS can amplify chaotically over the resumed epoch, so
            # a tight control comparison would be flaky by construction.
            # The restore evidence is the bit-identity + step assertions
            # above (both ranks restored the same bytes and advanced in
            # lockstep) plus a finite digest.
            assert np.isfinite(d0), (phase, d0)
            continue
        # multi-process == single-process control up to reduction order
        np.testing.assert_allclose(p0, pc, rtol=1e-5, atol=1e-7,
                                   err_msg=phase)
        assert abs(d0 - dc) / max(abs(dc), 1.0) < 1e-5, (phase, d0, dc)
