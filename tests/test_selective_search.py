"""Selective-search roidb path (the one gap PARITY.md declared in round 1,
now closed): rbg-format .mat loading with the MATLAB (y1,x1,y2,x2) 1-based
→ (x1,y1,x2,y2) 0-based reorder, proposal mirroring under flip, and the
ROIIter → rcnn_train consumption of the attached proposals.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np
import pytest

sio = pytest.importorskip("scipy.io")  # scipy ships in this image but is
# not in the guaranteed-baked list; the SS path itself imports it lazily

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import ROIIter
from mx_rcnn_tpu.data.pascal_voc import PascalVOC
from tests.fixtures import make_mini_voc


def _write_ss_mat(root, imdb, seed=0):
    """Per-image random SS-style boxes in the rbg .mat format (cell array
    of (K, 4) MATLAB-order 1-based boxes)."""
    rng = np.random.RandomState(seed)
    os.makedirs(os.path.join(root, "selective_search_data"), exist_ok=True)
    cells = np.empty((1, imdb.num_images), object)
    truth = []
    for i in range(imdb.num_images):
        k = rng.randint(3, 7)
        x1 = rng.randint(0, 100, k)
        y1 = rng.randint(0, 80, k)
        x2 = x1 + rng.randint(5, 40, k)
        y2 = y1 + rng.randint(5, 30, k)
        # MATLAB order, 1-based
        cells[0, i] = np.stack([y1 + 1, x1 + 1, y2 + 1, x2 + 1],
                               axis=1).astype(np.float64)
        truth.append(np.stack([x1, y1, x2, y2], axis=1).astype(np.float32))
    sio.savemat(os.path.join(root, "selective_search_data",
                             "voc_2007_trainval.mat"), {"boxes": cells})
    return truth


def test_ss_roidb_reorder_flip_and_roiiter(tmp_path):
    make_mini_voc(str(tmp_path / "VOCdevkit"), n_train=6, n_test=2)
    imdb = PascalVOC("2007_trainval", str(tmp_path / "data"),
                     str(tmp_path / "VOCdevkit"))
    truth = _write_ss_mat(str(tmp_path / "data"), imdb)

    roidb = imdb.selective_search_roidb()
    assert len(roidb) == 6
    for rec, want in zip(roidb, truth):
        np.testing.assert_array_equal(rec["proposals"], want)

    # flip mirrors proposals on image width
    flipped = imdb.append_flipped_images(roidb)
    assert len(flipped) == 12
    for orig, flip in zip(roidb, flipped[6:]):
        w = orig["width"]
        np.testing.assert_array_equal(
            flip["proposals"][:, 0], w - orig["proposals"][:, 2] - 1)
        np.testing.assert_array_equal(
            flip["proposals"][:, 2], w - orig["proposals"][:, 0] - 1)
        np.testing.assert_array_equal(
            flip["proposals"][:, 1], orig["proposals"][:, 1])

    # ROIIter consumes the attached proposals (the rcnn_train contract)
    cfg = generate_config("resnet50", "PascalVOC",
                          TRAIN__RPN_POST_NMS_TOP_N=32, TRAIN__FLIP=False)
    cfg = cfg.replace(tpu=dataclasses.replace(cfg.tpu, SCALES=((64, 96),),
                                              MAX_GT=8))
    loader = ROIIter(flipped, cfg, batch_size=2, shuffle=False)
    batch = next(iter(loader))
    assert batch["rois"].shape == (2, 32, 4)
    assert batch["roi_valid"].any()
    assert {"images", "im_info", "gt_boxes", "gt_classes",
            "gt_valid"} <= set(batch)


def test_flip_handles_unsanitized_empty_proposals(tmp_path):
    """A legacy roidb record carrying a plain empty list for 'proposals'
    (never routed through sanitize_proposals, so np.asarray gives shape
    (0,)) must flip to an empty (0, 4) array, not crash on column
    indexing (round-3 advisor finding)."""
    make_mini_voc(str(tmp_path / "VOCdevkit"), n_train=2, n_test=2)
    imdb = PascalVOC("2007_trainval", str(tmp_path / "data"),
                     str(tmp_path / "VOCdevkit"))
    roidb = imdb.gt_roidb()
    roidb[0]["proposals"] = []          # legacy pickle shape
    roidb[1]["proposals"] = np.zeros((0,), np.float32)
    flipped = imdb.append_flipped_images(roidb)
    # both halves are repaired: the originals are sanitized in place so
    # original/flipped stay on identical geometry
    for rec in flipped:
        assert rec["proposals"].shape == (0, 4)


def test_ss_roidb_count_mismatch_raises(tmp_path):
    make_mini_voc(str(tmp_path / "VOCdevkit"), n_train=4, n_test=2)
    imdb = PascalVOC("2007_trainval", str(tmp_path / "data"),
                     str(tmp_path / "VOCdevkit"))
    cells = np.empty((1, 2), object)  # wrong count
    for i in range(2):
        cells[0, i] = np.asarray([[1.0, 1.0, 5.0, 5.0]])
    os.makedirs(str(tmp_path / "data" / "selective_search_data"),
                exist_ok=True)
    sio.savemat(str(tmp_path / "data" / "selective_search_data" /
                    "voc_2007_trainval.mat"), {"boxes": cells})
    with pytest.raises(ValueError, match="selective-search"):
        imdb.selective_search_roidb()
