"""Serving subsystem tier-1 tests (CPU).

Batcher mechanics (bucket routing, partial-batch padding + response
unmasking, full-beats-partial flush ordering, backpressure, per-request
deadlines) run against a shape-faithful fake predictor — no model, no
compile.  One end-to-end test runs the real thing: tiny synthetic-weight
model, warmup, Unix-socket HTTP round trip, zero post-warmup recompiles
(telemetry counter assert), and byte-parity between served detections
and the offline Predictor + shared-postprocess path.
"""

import dataclasses
import io
import json
import threading
import time

import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import prepare_image
from mx_rcnn_tpu.ops.postprocess import (decode_image_boxes,
                                         detections_to_records,
                                         per_class_nms)
from mx_rcnn_tpu.serve import (DeadlineExceededError, RejectedError,
                               ServeEngine, ServeOptions,
                               encode_image_payload, make_server, run_stdio,
                               unix_http_request, warmup)


def tiny_cfg():
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TEST__RPN_PRE_NMS_TOP_N=300, TEST__RPN_POST_NMS_TOP_N=32,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((96, 128),), MAX_GT=8)
    return cfg.replace(network=net, tpu=tpu)


class FakePredictor:
    """Shape-faithful Predictor stub.  One valid roi per row, scored by a
    smooth function of the row's mean activation — so a response's score
    identifies WHICH image filled its batch row, and the padding/unmasking
    tests read the row→request mapping straight off the detections."""

    R = 4

    def __init__(self, cfg, delay_s=0.0):
        self.cfg = cfg
        self.delay_s = delay_s
        self.batches = []  # input shape of every forward, in order

    @staticmethod
    def row_score(prepared):
        # bounded well inside (TEST.THRESH, 1), distinct for distinct means
        return float(np.tanh(np.asarray(prepared, np.float64).mean() / 100)
                     * 0.4 + 0.5)

    def predict(self, images, im_info):
        if self.delay_s:
            time.sleep(self.delay_s)
        images = np.asarray(images)
        self.batches.append(tuple(images.shape))
        B, (R, K) = images.shape[0], (self.R, self.cfg.NUM_CLASSES)
        rois = np.zeros((B, R, 4), np.float32)
        rois[:, :, 2:] = 16.0
        valid = np.zeros((B, R), bool)
        valid[:, 0] = True
        scores = np.zeros((B, R, K), np.float32)
        for b in range(B):
            scores[b, 0, 1] = self.row_score(images[b])
        deltas = np.zeros((B, R, 4 * K), np.float32)
        return rois, valid, scores, deltas, None


def make_engine(cfg, **opts):
    defaults = dict(batch_size=4, max_delay_ms=1.0, max_queue=16)
    defaults.update(opts)
    return ServeEngine(FakePredictor(cfg), cfg, ServeOptions(**defaults))


def raw_image(h, w, value):
    return np.full((h, w, 3), value, np.uint8)


# -- shared postprocess ----------------------------------------------------


def test_per_class_nms_thresh_valid_and_cap():
    R, K = 5, 3
    scores = np.zeros((R, K), np.float32)
    scores[:, 1] = [0.9, 0.8, 0.002, 0.0005, 0.7]
    boxes = np.zeros((R, 4 * K), np.float32)
    for i in range(R):  # well-separated boxes: NMS never merges them
        boxes[i, 4:8] = [i * 30, 0, i * 30 + 10, 10]
    valid = np.array([1, 1, 1, 1, 0], bool)

    dets = per_class_nms(scores, boxes, valid, K, thresh=1e-3,
                         nms_thresh=0.3, max_per_image=0)
    # row 3 under thresh, row 4 (0.7) invalid; class 2 has no scores at all
    assert len(dets[1]) == 3 and len(dets[2]) == 0
    assert sorted(dets[1][:, 4]) == [np.float32(0.002), np.float32(0.8),
                                     np.float32(0.9)]

    capped = per_class_nms(scores, boxes, valid, K, thresh=1e-3,
                           nms_thresh=0.3, max_per_image=2)
    assert len(capped[1]) == 2
    assert sorted(capped[1][:, 4]) == [np.float32(0.8), np.float32(0.9)]

    recs = detections_to_records(dets)
    assert [r["cls"] for r in recs] == [1, 1, 1]
    assert [r["score"] for r in recs] == sorted(
        (r["score"] for r in recs), reverse=True)
    assert len(recs[0]["bbox"]) == 4


# -- batcher mechanics (fake predictor, engine not necessarily started) ----


def test_bucket_routing_two_orientations():
    cfg = tiny_cfg()
    engine = make_engine(cfg)
    # orientation picks the bucket: transposed shapes
    land, port = engine.bucket_key(60, 100), engine.bucket_key(100, 60)
    assert land == (port[1], port[0])
    # not started: submissions park in their queues for inspection
    engine.submit(raw_image(60, 100, 50))
    engine.submit(raw_image(100, 60, 50))
    engine.submit(raw_image(50, 90, 50))  # another landscape
    m = engine.metrics()
    assert m["queue_depth"] == 3
    assert m["buckets"] == {f"{land[0]}x{land[1]}": 2,
                            f"{port[0]}x{port[1]}": 1}
    fut = engine.submit(raw_image(60, 100, 50))
    engine.stop()  # fails whatever is still queued
    try:
        fut.result(timeout=5)
        raise AssertionError("stopped engine should fail pending futures")
    except RejectedError:
        pass


def test_partial_batch_padded_and_responses_unmasked():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=4, max_delay_ms=1.0)
    fake = engine.predictor
    values = (40, 120, 200)
    imgs = [raw_image(60, 100, v) for v in values]
    futs = [engine.submit(im) for im in imgs]  # pre-start: deterministic
    engine.start()
    try:
        results = [f.result(timeout=30) for f in futs]
    finally:
        engine.stop()
    # one forward, padded to the full batch with repeats of the last image
    assert len(fake.batches) == 1 and fake.batches[0][0] == 4
    # each response carries ITS OWN image's score — row→request mapping
    # survives the padding (and the padded duplicate rows produce nothing)
    for img, dets in zip(imgs, results):
        prepared, _ = prepare_image(img, cfg, cfg.tpu.SCALES[0])
        assert len(dets) == 1
        assert abs(dets[0]["score"] - fake.row_score(prepared)) < 1e-5
    assert engine.counters["served"] == 3
    assert engine.counters["batches"] == 1


def test_full_bucket_flushes_before_older_partial():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=4, max_delay_ms=300.0)
    fake = engine.predictor
    older = engine.submit(raw_image(60, 100, 50))       # landscape, partial
    full = [engine.submit(raw_image(100, 60, 50)) for _ in range(4)]
    engine.start()
    try:
        for f in full:
            f.result(timeout=30)
        older.result(timeout=30)  # flushes at the max-delay deadline
    finally:
        engine.stop()
    land, _ = prepare_image(raw_image(60, 100, 50), cfg, cfg.tpu.SCALES[0])
    port, _ = prepare_image(raw_image(100, 60, 50), cfg, cfg.tpu.SCALES[0])
    # the FULL portrait bucket won the first flush although the landscape
    # request was enqueued first; the partial flushed on its deadline
    assert fake.batches == [(4,) + port.shape, (4,) + land.shape]


def test_backpressure_rejects_when_queue_full():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=2, max_queue=4)
    for _ in range(4):  # engine not started: nothing drains
        engine.submit(raw_image(60, 100, 50))
    try:
        engine.submit(raw_image(60, 100, 50))
        raise AssertionError("5th submit should be rejected")
    except RejectedError as e:
        assert "queue full" in str(e)
    assert engine.counters["rejected"] == 1
    assert engine.counters["requests"] == 4
    engine.stop()


def test_request_deadline_expires_without_forward():
    cfg = tiny_cfg()
    engine = make_engine(cfg)
    fake = engine.predictor
    fut = engine.submit(raw_image(60, 100, 50), deadline_ms=1.0)
    time.sleep(0.05)  # expire while the engine is not yet draining
    engine.start()
    try:
        try:
            fut.result(timeout=10)
            raise AssertionError("expired request should fail")
        except DeadlineExceededError:
            pass
        assert engine.counters["deadline_exceeded"] == 1
        # the expired request never cost a forward pass
        assert fake.batches == []
    finally:
        engine.stop()


# -- frontends -------------------------------------------------------------


def test_stdio_frontend_statuses():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=1, max_delay_ms=0.0).start()
    img = raw_image(40, 60, 120)
    inp = io.StringIO("this is not json\n"
                      + json.dumps({"pixels": img.tolist()}) + "\n"
                      + json.dumps({"shape": [2, 2]}) + "\n")
    out = io.StringIO()
    try:
        run_stdio(engine, inp, out)
    finally:
        engine.stop()
    lines = [json.loads(line) for line in out.getvalue().splitlines()]
    assert [d["status"] for d in lines] == [400, 200, 400]
    assert lines[1]["detections"] and "queue_wait_ms" in lines[1]


def test_serve_e2e_unix_socket_warm_and_parity(tmp_path):
    """The whole path on real (synthetic-weight) compute: warmup compiles
    exactly one program per orientation, mixed-size HTTP traffic over a
    Unix socket serves with ZERO further recompiles (telemetry counter
    assert), and the served detections are identical to the offline
    Predictor + shared-postprocess path for the same pixels."""
    import jax

    from mx_rcnn_tpu import telemetry
    from mx_rcnn_tpu.eval import Predictor
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

    cfg = tiny_cfg()
    model = build_model(cfg)
    params = denormalize_for_save(
        init_params(model, cfg, jax.random.PRNGKey(0), 2, (96, 128)), cfg)
    pred = Predictor(model, params, cfg)
    engine = ServeEngine(pred, cfg, ServeOptions(
        batch_size=2, max_delay_ms=5.0, max_queue=16)).start()
    telemetry.configure(str(tmp_path / "tel"), run_meta={"driver": "test"})
    sock = str(tmp_path / "serve.sock")
    server = make_server(engine, unix_socket=sock)
    th = threading.Thread(target=server.serve_forever, daemon=True)
    try:
        compiled = warmup(engine)
        assert compiled == 2  # one program per orientation bucket
        th.start()

        status, health = unix_http_request(sock, "GET", "/healthz")
        assert status == 200 and health["status"] == "ok"

        rng = np.random.RandomState(7)
        images = [rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
                  for h, w in ((60, 100), (100, 60), (48, 90), (90, 48))]
        served = []
        for img in images:
            status, resp = unix_http_request(
                sock, "POST", "/predict", encode_image_payload(img),
                timeout=300)
            assert status == 200, resp
            assert "queue_wait_ms" in resp
            served.append(resp["detections"])

        # parity: offline path (Predictor + shared postprocess) on the
        # same pixels — self-padded to the serve batch, like the engine
        for img, dets in zip(images, served):
            prepared, im_info = prepare_image(img, cfg, cfg.tpu.SCALES[0])
            rois, valid, scores, deltas, _ = [
                np.asarray(jax.device_get(x)) for x in pred.predict(
                    np.stack([prepared, prepared]),
                    np.stack([im_info, im_info]))]
            boxes = decode_image_boxes(rois[0], deltas[0], im_info)
            expect = detections_to_records(per_class_nms(
                scores[0], boxes, valid[0], cfg.NUM_CLASSES,
                cfg.TEST.THRESH, cfg.TEST.NMS, cfg.TEST.MAX_PER_IMAGE))
            assert len(dets) == len(expect)
            for d, e in zip(dets, expect):
                assert d["cls"] == e["cls"]
                assert abs(d["score"] - e["score"]) < 1e-5
                assert np.allclose(d["bbox"], e["bbox"], atol=1e-3)

        # zero recompiles after warmup — the subsystem's core guarantee
        status, m = unix_http_request(sock, "GET", "/metrics")
        assert status == 200
        assert m["counters"]["recompiles"] == m["counters"]["warmup_programs"]
        summ = telemetry.get().summary()
        assert (summ["counters"]["serve/recompile"]
                == summ["counters"]["serve/warmup_programs"] == 2)
        assert "serve/rejected" not in summ["counters"]
        assert summ["spans"]["serve/forward"]["count"] >= 3
    finally:
        if th.is_alive():
            server.shutdown()
        server.server_close()
        engine.stop()
        telemetry.shutdown()


def test_serve_e2e_fused_single_dispatch_contract():
    """``--serve-e2e`` acceptance: warmup registers kind-labeled fused
    programs (one per orientation), a request batch crosses the host↔device
    boundary exactly once in each direction (1 h2d / 1 dispatch /
    1 readback — counter assert), the detection readback is a fraction of
    the legacy fat path's, a hot param swap costs zero recompiles, and
    fused detections match the unfused engine's records at float
    tolerance (exact score ties at the MAX_PER_IMAGE cap may resolve
    differently — the documented device-postprocess divergence)."""
    import jax

    from mx_rcnn_tpu.eval import Predictor
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

    cfg = tiny_cfg()
    model = build_model(cfg)
    params = denormalize_for_save(
        init_params(model, cfg, jax.random.PRNGKey(0), 2, (96, 128)), cfg)
    pred = Predictor(model, params, cfg)

    rng = np.random.RandomState(11)
    land_a = rng.randint(0, 255, (60, 100, 3), dtype=np.uint8)
    land_b = rng.randint(0, 255, (48, 90, 3), dtype=np.uint8)
    port = rng.randint(0, 255, (100, 60, 3), dtype=np.uint8)
    images = [land_a, land_b, port]

    # unfused reference on the SAME predictor/registry: the legacy and
    # fused kinds coexist in one program key space
    legacy = ServeEngine(pred, cfg, ServeOptions(
        batch_size=2, max_delay_ms=5.0, max_queue=16)).start()
    try:
        expect = [legacy.submit(img).result(timeout=300) for img in images]
        lc = dict(legacy.counters)
    finally:
        legacy.stop()
    assert lc["h2d_transfers"] == 2 * lc["batches"]  # images + im_info
    legacy_readback_per_batch = lc["readback_bytes"] / lc["batches"]

    engine = ServeEngine(pred, cfg, ServeOptions(
        batch_size=2, max_delay_ms=200.0, max_queue=16,
        serve_e2e=True)).start()
    try:
        assert warmup(engine) == 2  # one fused program per orientation
        # /metrics compile snapshot labels programs by kind: the fused
        # programs are distinguishable from the legacy forwards
        rows = engine.metrics()["compile"]["programs"]
        kinds = {p["kind"] for p in rows}
        assert "serve_e2e" in kinds and "predict" in kinds
        assert sum(p["kind"] == "serve_e2e" for p in rows) == 2

        # one full batch = exactly one transfer/dispatch/readback
        base = dict(engine.counters)
        futs = [engine.submit(img) for img in (land_a, land_b)]
        got = [f.result(timeout=300) for f in futs]
        delta = {k: engine.counters[k] - base[k]
                 for k in ("h2d_transfers", "dispatches", "readbacks",
                           "batches")}
        assert delta == {"h2d_transfers": 1, "dispatches": 1,
                         "readbacks": 1, "batches": 1}
        # the (B, cap, 6) readback is far below the legacy scores+deltas
        e2e_readback = engine.counters["readback_bytes"] - \
            base["readback_bytes"]
        assert 0 < e2e_readback < legacy_readback_per_batch
        got.append(engine.submit(port).result(timeout=300))

        # fused vs unfused detection-record parity at float tolerance
        for dets, ref in zip(got, expect):
            assert len(dets) == len(ref)
            for d, e in zip(dets, ref):
                assert d["cls"] == e["cls"]
                assert abs(d["score"] - e["score"]) < 0.02
                assert np.allclose(d["bbox"], e["bbox"], atol=1.0)

        # hot-reload param swap: zero recompiles under the fused kind,
        # identical detections (same weights back in)
        before = engine.counters["recompiles"]
        pred.update_params(params)
        again = engine.submit(land_a).result(timeout=300)
        assert engine.counters["recompiles"] == before == \
            engine.counters["warmup_programs"]
        assert len(again) == len(got[0])
        for d, e in zip(again, got[0]):
            assert d["cls"] == e["cls"]
            assert abs(d["score"] - e["score"]) < 1e-5
    finally:
        engine.stop()
