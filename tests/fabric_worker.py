"""Subprocess TCP fabric-member entry for the e2e cross-host chaos
tests (NOT a test module — no ``test_`` prefix).

The localhost-TCP twin of ``tests/replica_worker.py``: the REAL member
main loop (``serve_replica``: TCP HTTP, warmup→ready, ``/admin/reload``
hot swap, ``--join`` self-registration, ``MXR_FAULT_NET_*`` injectors)
over the shape-faithful :class:`FakeServePredictor` — no model weights,
no XLA forward — so ``tests/test_fabric.py`` can drive a real
ReplicaPool + FabricRouter over real processes and real sockets
(kill -9, TCP resets, blackholes) in seconds.  ``script/fabric_smoke.sh``
exercises the same topology with the real model.
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mx_rcnn_tpu.serve import ServeEngine, ServeOptions, serve_replica  # noqa: E402
from tests.replica_worker import FakeServePredictor, load_params  # noqa: E402
from tests.test_serve import tiny_cfg  # noqa: E402


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--replica-index", type=int, default=0,
                    dest="replica_index")
    ap.add_argument("--params-file", default="", dest="params_file")
    ap.add_argument("--serve-batch", type=int, default=2, dest="serve_batch")
    ap.add_argument("--delay-s", type=float, default=0.0, dest="delay_s")
    ap.add_argument("--join", default="")
    ap.add_argument("--advertise", default="")
    # fleet-flywheel capture (ISSUE 17): members share one capture dir,
    # distinguished by --capture-member in shard/manifest names
    ap.add_argument("--capture-dir", default="", dest="capture_dir")
    ap.add_argument("--capture-member", default=None,
                    dest="capture_member")
    ap.add_argument("--capture-sample", type=int, default=1,
                    dest="capture_sample")
    ap.add_argument("--capture-shard-records", type=int, default=4,
                    dest="capture_shard_records")
    args = ap.parse_args(argv)

    cfg = tiny_cfg()
    params = {"scale": np.float32(1.0)}
    if args.params_file:
        params = load_params({"prefix": args.params_file}, cfg)
    pred = FakeServePredictor(cfg, params, delay_s=args.delay_s)
    engine = ServeEngine(pred, cfg, ServeOptions(
        batch_size=args.serve_batch, max_delay_ms=1.0,
        max_queue=32))
    if args.capture_dir:
        from mx_rcnn_tpu.flywheel import CaptureOptions, RequestCapture
        engine.capture = RequestCapture(CaptureOptions(
            capture_dir=args.capture_dir,
            sample_every=args.capture_sample,
            shard_records=args.capture_shard_records,
            member=args.capture_member))
    engine.start()
    serve_replica(engine, cfg, port=args.port, index=args.replica_index,
                  predictor=pred, load_params_fn=load_params,
                  join=args.join or None,
                  advertise=args.advertise or None)


if __name__ == "__main__":
    main()
