"""Deterministic fault-injection harness for the resilience subsystem
(ISSUE 2 tentpole).  NOT a test module — pytest ignores it (no ``test_``
prefix); tests/test_resilience.py drives every injector, and the
env-driven CLI-level injectors live in ``mx_rcnn_tpu/train/resilience.py``
(``MXR_FAULT_*``) for script/fault_smoke.sh.

Injectors:

* :func:`corrupt_record` — make one roidb record unloadable (exercises the
  loader's bad-record isolation).
* :class:`NanBatchLoader` — poison the images of one global batch with NaN
  (exercises the train-step sentinel + nan policies).
* :class:`SignalAtBatchLoader` — raise SIGTERM/SIGINT in the consumer
  thread while a chosen batch is being pulled (exercises graceful
  preemption at an exact, reproducible step boundary).
* :func:`flaky_saves` — fail the first N orbax saves with OSError
  (exercises checkpoint I/O retry).
* :func:`hang_until` — a producer generator that yields its items then
  blocks until released (exercises the prefetch-queue watchdog).

Serve-side chaos (ISSUE 8): the injectors themselves live in
``mx_rcnn_tpu/serve/replica.py`` (``MXR_FAULT_REPLICA_*``, parsed by
``ReplicaFaults`` — package code, same placement rule as the
``MXR_FAULT_*`` train injectors above); this module only provides
:func:`replica_fault_env`, the composer tests and
``script/replica_smoke.sh`` use to build the env dict for a chosen
replica index, so the var names have exactly one spelling.

Fabric-side network chaos (ISSUE 12) follows the same split:
``MXR_FAULT_NET_{DROP,DELAY_MS,RESET}`` are parsed by ``NetFaults`` in
``mx_rcnn_tpu/serve/replica.py`` and injected member-side at the HTTP
frontend; :func:`net_fault_env` is the composer for
tests/test_fabric.py and script/fabric_smoke.sh.

Flywheel capture chaos (ISSUE 13), same split again:
``MXR_FAULT_FLYWHEEL_{CORRUPT_SHARD,TRUNCATE_SPILL}`` (value = the
0-based index of the spilled shard to damage) are parsed by
``RequestCapture`` in ``mx_rcnn_tpu/flywheel/capture.py``;
:func:`flywheel_fault_env` is the composer for tests/test_flywheel.py
and script/flywheel_smoke.sh.  The damaged shard's replay records then
exercise the loader's PR-2 bad-record substitution path.

Fleet-flywheel chaos (ISSUE 17), same split: the fleet fault env vars
are parsed by package code (``MXR_FAULT_FLYWHEEL_DUP_MANIFEST`` in
``flywheel/capture.py``; ``MXR_FAULT_FLYWHEEL_{PARTITION_MINE,
KILL_TRAIN}`` in ``flywheel/fleet.py``); :func:`fleet_fault_env` is
the composer for tests/test_flywheel_fleet.py and
script/flywheel_fleet_smoke.sh."""

from __future__ import annotations

import contextlib
import signal
import time

import numpy as np


def corrupt_record(roidb: list, i: int) -> list:
    """Make ``roidb[i]`` unloadable: drop inline pixels, point the image
    path at nothing — ``_load_record`` raises on it."""
    rec = dict(roidb[i])
    rec.pop("image_array", None)
    rec["image"] = "/nonexistent/faults_harness_corrupt.jpg"
    roidb[i] = rec
    return roidb


class NanBatchLoader:
    """Wrap a train loader; the ``n``-th yielded batch (counted globally
    across epochs) gets all-NaN images."""

    def __init__(self, inner, n: int):
        self._inner = inner
        self._n = n
        self._count = 0
        self.batch_size = inner.batch_size

    @property
    def steps_per_epoch(self) -> int:
        return self._inner.steps_per_epoch

    def __iter__(self):
        for b in self._inner:
            if self._count == self._n:
                b = dict(b)
                b["images"] = np.full_like(b["images"], np.nan)
            self._count += 1
            yield b


class SignalAtBatchLoader:
    """Wrap a train loader; raise ``sig`` on the consumer thread right
    before yielding batch ``at`` (global count) — the trainer's handler
    sets its flag, batch ``at`` still dispatches, and the preemption save
    lands at the following boundary (``consumed = at + 1``), every run."""

    def __init__(self, inner, at: int, sig=signal.SIGTERM):
        self._inner = inner
        self._at = at
        self._sig = sig
        self._count = 0
        self.batch_size = inner.batch_size

    @property
    def steps_per_epoch(self) -> int:
        return self._inner.steps_per_epoch

    def __iter__(self):
        for b in self._inner:
            if self._count == self._at:
                signal.raise_signal(self._sig)
            self._count += 1
            yield b


@contextlib.contextmanager
def flaky_saves(n: int, exc=OSError):
    """Patch ``orbax.checkpoint.CheckpointManager.save`` to raise ``exc``
    for the first ``n`` calls, then behave normally — the transient-
    filesystem-error shape ``resilience.retry_io`` exists for.  Yields the
    mutable ``{"left": remaining}`` counter."""
    import orbax.checkpoint as ocp

    orig = ocp.CheckpointManager.save
    calls = {"left": n}

    def save(self, *a, **k):
        if calls["left"] > 0:
            calls["left"] -= 1
            raise exc("injected transient save failure (tests/faults.py)")
        return orig(self, *a, **k)

    ocp.CheckpointManager.save = save
    try:
        yield calls
    finally:
        ocp.CheckpointManager.save = orig


def replica_fault_env(index: int, kill_after=None, hang_after=None,
                      slow_start_s=None, corrupt_ckpt=False) -> dict:
    """Compose the ``MXR_FAULT_REPLICA_*`` env dict injecting the chosen
    faults into replica ``index`` (merge into the child's env, or the
    parent's — tokens are index-matched, so siblings are untouched)."""
    from mx_rcnn_tpu.serve.replica import (ENV_CORRUPT_CKPT,
                                           ENV_HANG_AFTER, ENV_KILL_AFTER,
                                           ENV_SLOW_START)

    env = {}
    if kill_after is not None:
        env[ENV_KILL_AFTER] = f"{index}:{int(kill_after)}"
    if hang_after is not None:
        env[ENV_HANG_AFTER] = f"{index}:{int(hang_after)}"
    if slow_start_s is not None:
        env[ENV_SLOW_START] = f"{index}:{float(slow_start_s)}"
    if corrupt_ckpt:
        env[ENV_CORRUPT_CKPT] = str(index)
    return env


def net_fault_env(index: int, drop_after=None, delay_ms=None,
                  reset_from=None, reset_to=None) -> dict:
    """Compose the ``MXR_FAULT_NET_*`` env dict injecting network faults
    into fabric member ``index`` (index-matched tokens, like
    :func:`replica_fault_env`):

    * ``drop_after=N`` — after serving N ``/predict`` requests the member
      blackholes EVERY path including probes (accepted connections hang):
      the network-partition shape, seen by the router as probe timeouts.
    * ``delay_ms=D`` — every ``/predict`` response is delayed by D ms
      (probes unaffected): the tail-latency shape request hedging exists
      for.
    * ``reset_from=N`` (optionally with ``reset_to=M``) — ``/predict``
      requests N..M (1-based, inclusive; open-ended without ``reset_to``)
      are answered with a hard TCP RST while probes stay healthy: the
      flaky-member shape that must trip the per-member circuit breaker
      (and, when bounded, let it close again after recovery)."""
    from mx_rcnn_tpu.serve.replica import (ENV_NET_DELAY, ENV_NET_DROP,
                                           ENV_NET_RESET)

    env = {}
    if drop_after is not None:
        env[ENV_NET_DROP] = f"{index}:{int(drop_after)}"
    if delay_ms is not None:
        env[ENV_NET_DELAY] = f"{index}:{float(delay_ms)}"
    if reset_from is not None:
        spec = (f"{int(reset_from)}" if reset_to is None
                else f"{int(reset_from)}-{int(reset_to)}")
        env[ENV_NET_RESET] = f"{index}:{spec}"
    return env


def flywheel_fault_env(corrupt_shard=None, truncate_spill=None) -> dict:
    """Compose the ``MXR_FAULT_FLYWHEEL_*`` env dict damaging a capture
    shard after its atomic spill (simulated torn disk):

    * ``corrupt_shard=N`` — shard index N's npz is overwritten with
      garbage bytes (np.load raises on every record).
    * ``truncate_spill=N`` — shard index N's npz is truncated to half
      its size (the torn-write shape)."""
    from mx_rcnn_tpu.flywheel.capture import (ENV_CORRUPT_SHARD,
                                              ENV_TRUNCATE_SPILL)

    env = {}
    if corrupt_shard is not None:
        env[ENV_CORRUPT_SHARD] = str(int(corrupt_shard))
    if truncate_spill is not None:
        env[ENV_TRUNCATE_SPILL] = str(int(truncate_spill))
    return env


def fleet_fault_env(partition_mine=None, dup_manifest=None,
                    kill_train=None) -> dict:
    """Compose the fleet-flywheel ``MXR_FAULT_FLYWHEEL_*`` env dict:

    * ``partition_mine="m1"`` (str or list of member ids) — those
      members are unreachable during the distributed mine; the fold
      proceeds without their rankings.
    * ``dup_manifest="m0"`` (member id, or ``"*"`` for every member) —
      each manifest write is delivered TWICE under distinct filenames
      (the at-least-once delivery shape the merge must fold to one
      member entry, highest seq winning).
    * ``kill_train=(round, seconds)`` — the trainer subprocess of the
      chosen round is SIGKILLed that many seconds in (mid-epoch)."""
    from mx_rcnn_tpu.flywheel.capture import ENV_DUP_MANIFEST
    from mx_rcnn_tpu.flywheel.fleet import (ENV_KILL_TRAIN,
                                            ENV_PARTITION_MINE)

    env = {}
    if partition_mine is not None:
        if isinstance(partition_mine, str):
            partition_mine = [partition_mine]
        env[ENV_PARTITION_MINE] = ",".join(partition_mine)
    if dup_manifest is not None:
        env[ENV_DUP_MANIFEST] = str(dup_manifest)
    if kill_train is not None:
        rnd, secs = kill_train
        env[ENV_KILL_TRAIN] = f"{int(rnd)}:{float(secs)}"
    return env


def hang_until(event, items):
    """Producer generator: yield ``items``, then spin until ``event`` is
    set — a stuck-but-alive producer (hung filesystem read) for the
    prefetch watchdog.  Set ``event`` in the test's cleanup so the
    producer thread exits promptly."""
    for it in items:
        yield it
    while not event.is_set():
        time.sleep(0.02)
