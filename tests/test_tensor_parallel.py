"""Tensor parallelism over the head FCs (the ``model`` mesh axis — our
extension beyond the reference's DP-only strategy, SURVEY §2.3).

VGG's fc6/fc7 (≈120M params, the bulk of the model) run Megatron-style:
fc6 column-parallel, fc7 row-parallel, XLA inserting the contraction psum.
Validated on the virtual CPU mesh: a (data=4, model=2) step must produce
the same loss as the unsharded step, actually lay the fc weights out
sharded, and keep momentum sharded like its param.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.parallel import make_mesh, shard_batch
from mx_rcnn_tpu.train import create_train_state, make_train_step

from tests.test_train import make_batch


def vgg_cfg():
    cfg = generate_config(
        "vgg16", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4),
                              PIXEL_STDS=(127.0, 127.0, 127.0))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4)
    return cfg.replace(network=net, tpu=tpu)


def fpn_cfg():
    # FPN's box head shares the fc6/fc7 names (1024-wide), so the Megatron
    # rules shard it too — round-2 VERDICT flagged the FPN dp×tp path as
    # untested on-mesh (only VGG was).  f32 compute: the sharded FPN
    # program re-fuses heavily and bf16 jitter (measured 3e-4) exceeds
    # the loss tolerance.
    cfg = generate_config(
        "resnet50_fpn", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16,
    )
    net = dataclasses.replace(cfg.network, FPN_ANCHOR_SCALES=(2,),
                              PIXEL_STDS=(127.0, 127.0, 127.0))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4,
                              COMPUTE_DTYPE="float32")
    return cfg.replace(network=net, tpu=tpu)


@pytest.mark.parametrize("cfg_factory", [vgg_cfg, fpn_cfg],
                         ids=["vgg16", "resnet50_fpn"])
def test_tp_step_matches_unsharded(cfg_factory):
    cfg = cfg_factory()
    seed = 0
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(seed), 1, (64, 96))
    batch = make_batch(4)
    key = jax.random.PRNGKey(7)

    # single-device reference step
    s_ref, tx_ref, mask = create_train_state(cfg, params, steps_per_epoch=10)
    step_ref = make_train_step(model, tx_ref, trainable_mask=mask)
    s_ref, m_ref = step_ref(s_ref, batch, key)

    # (data=4, model=2) TP step
    plan = make_mesh(data=4, model=2)
    assert plan.n_model == 2 and plan.n_data == 4
    s_tp, tx_tp, mask = create_train_state(cfg, params, steps_per_epoch=10)
    step_tp = make_train_step(model, tx_tp, plan=plan, trainable_mask=mask)
    s_tp, m_tp = step_tp(s_tp, shard_batch(plan, batch), key)

    np.testing.assert_allclose(float(m_tp["total_loss"]),
                               float(m_ref["total_loss"]), rtol=2e-4)

    # the fc weights are ACTUALLY laid out sharded on the model axis
    fc6 = s_tp.params["head_body"]["fc6"]["kernel"]
    fc7 = s_tp.params["head_body"]["fc7"]["kernel"]
    assert fc6.sharding.spec == P(None, "model")
    assert fc7.sharding.spec == P("model", None)
    # per-device shard is half the array
    assert fc6.addressable_shards[0].data.shape == (fc6.shape[0],
                                                    fc6.shape[1] // 2)

    # updated params stay numerically equal to the unsharded step's
    np.testing.assert_allclose(
        np.asarray(jax.device_get(fc6)),
        np.asarray(jax.device_get(s_ref.params["head_body"]["fc6"]["kernel"])),
        rtol=1e-4, atol=1e-5)

    # momentum rides the same sharding as its param (path-suffix matching)
    mom = [l for p, l in
           jax.tree_util.tree_flatten_with_path(s_tp.opt_state)[0]
           if any(getattr(e, "key", None) == "fc6" for e in p)
           and l.ndim == 2]
    assert mom and mom[0].sharding.spec == P(None, "model")


def test_tp_checkpoint_roundtrip(tmp_path):
    """Checkpointing a TP-sharded TrainState: orbax must save the sharded
    params and restore them loadable (the fit() epoch-end path with a
    model-axis mesh)."""
    from mx_rcnn_tpu.train.checkpoint import CheckpointManager

    cfg = vgg_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    plan = make_mesh(data=4, model=2)
    state, tx, mask = create_train_state(cfg, params, steps_per_epoch=10)
    step = make_train_step(model, tx, plan=plan, trainable_mask=mask)
    state, _ = step(state, shard_batch(plan, make_batch(4)),
                    jax.random.PRNGKey(0))
    assert state.params["head_body"]["fc6"]["kernel"].sharding.spec == \
        P(None, "model")

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save_epoch(1, state.params, cfg, opt_state=state.opt_state, step=1)
    restored, _, _ = mgr.load_epoch(1, cfg, for_training=False)
    np.testing.assert_allclose(
        np.asarray(restored["head_body"]["fc6"]["kernel"]),
        np.asarray(jax.device_get(state.params["head_body"]["fc6"]["kernel"])),
        rtol=1e-5)


def test_tp_plan_replicates_without_model_axis():
    cfg = vgg_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    plan = make_mesh(data=8)
    shs = plan.param_shardings(params)
    assert all(s.spec == P() for s in jax.tree.leaves(shs))
