"""Subprocess replica entry for the e2e multi-replica chaos tests (NOT a
test module — no ``test_`` prefix).

Runs the REAL replica main loop (``serve_replica``: Unix-socket HTTP,
warmup→ready, ``/admin/reload`` hot swap, ``MXR_FAULT_REPLICA_*``
injectors) over the shape-faithful :class:`FakeServePredictor` — no
model weights, no XLA forward — so ``tests/test_replica.py`` can drive a
real supervisor + router over real processes (kill -9, respawn, rolling
reload) in seconds.  ``script/replica_smoke.sh`` exercises the same
topology with the real model.

Hot-reload contract: ``--params-file`` points at a JSON dict of floats;
a reload target's ``prefix`` names such a file, and ``predict`` scales
its class scores by ``params["scale"]`` — so a swapped generation is
observable in responses and a NaN ``scale`` fails the canary probe.
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mx_rcnn_tpu.serve import ServeEngine, ServeOptions, serve_replica  # noqa: E402
from tests.test_serve import FakePredictor, tiny_cfg  # noqa: E402


class FakeServePredictor(FakePredictor):
    """FakePredictor + the hot-reload surface (``params`` /
    ``update_params``): scores scale with ``params["scale"]`` so weight
    swaps show up in outputs and NaN weights poison the canary."""

    def __init__(self, cfg, params, delay_s=0.0):
        super().__init__(cfg, delay_s=delay_s)
        self.params = params

    def update_params(self, params):
        self.params = params

    def predict(self, images, im_info):
        rois, valid, scores, deltas, extra = super().predict(images, im_info)
        s = np.float32(self.params.get("scale", 1.0))
        return rois, valid, scores * s, deltas * s, extra


def load_params(target, cfg):
    """Reload-target loader: ``target["prefix"]`` is a JSON params file."""
    with open(target["prefix"]) as f:
        doc = json.load(f)
    return {k: np.float32(v) for k, v in doc.items()}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--unix-socket", required=True, dest="unix_socket")
    ap.add_argument("--replica-index", type=int, default=0,
                    dest="replica_index")
    ap.add_argument("--params-file", default="", dest="params_file")
    ap.add_argument("--serve-batch", type=int, default=2, dest="serve_batch")
    ap.add_argument("--delay-s", type=float, default=0.0, dest="delay_s")
    args = ap.parse_args(argv)

    cfg = tiny_cfg()
    params = {"scale": np.float32(1.0)}
    if args.params_file:
        params = load_params({"prefix": args.params_file}, cfg)
    pred = FakeServePredictor(cfg, params, delay_s=args.delay_s)
    engine = ServeEngine(pred, cfg, ServeOptions(
        batch_size=args.serve_batch, max_delay_ms=1.0,
        max_queue=32)).start()
    serve_replica(engine, cfg, args.unix_socket, index=args.replica_index,
                  predictor=pred, load_params_fn=load_params)


if __name__ == "__main__":
    main()
