"""Cross-process AOT warm start: the second server boot over a warm
cache dir performs ZERO XLA warmup compiles.

Two sequential subprocesses (tests/aot_worker.py) share one
``MXR_PROGRAM_CACHE`` dir.  Boot 1 is cold: every warmup program is an
``aot_miss`` (markers + persistent-cache executables written).  Boot 2
must report ``aot_hit == warmup_programs`` and zero misses — the
registry recognized every program from the manifest and XLA loaded the
executables from disk.  Timing (cold start actually collapsing) is
asserted by script/aot_smoke.sh, not here — CI hosts are too noisy for
a wall-clock bound in tier-1.
"""

import os
import re
import subprocess
import sys

WORKER = os.path.join(os.path.dirname(__file__), "aot_worker.py")


def boot(cache_base: str) -> dict:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXR_PROGRAM_CACHE=cache_base)
    prior = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = repo + (os.pathsep + prior if prior else "")
    proc = subprocess.run(
        [sys.executable, WORKER, cache_base],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    m = re.search(r"WARM programs=(\d+) aot_hit=(\d+) aot_miss=(\d+) "
                  r"warmup_programs=(\d+) wall=([\d.]+)", proc.stdout)
    assert m, (proc.stdout, proc.stderr)
    return {"programs": int(m.group(1)), "aot_hit": int(m.group(2)),
            "aot_miss": int(m.group(3)), "warmup_programs": int(m.group(4)),
            "wall": float(m.group(5))}


def test_second_boot_warms_from_disk(tmp_path):
    cache = str(tmp_path / "programs")

    cold = boot(cache)
    # one program per orientation bucket, all cold
    assert cold["warmup_programs"] == 2
    assert cold["aot_miss"] == 2 and cold["aot_hit"] == 0

    warm = boot(cache)
    # the PR's acceptance bar: zero warmup compiles on the second boot
    assert warm["warmup_programs"] == 2
    assert warm["aot_hit"] == 2 == warm["warmup_programs"]
    assert warm["aot_miss"] == 0
