"""Cross-host serving fabric tests (ISSUE 12).

Three layers, mirroring tests/test_replica.py:

* **Pool state machine** — deterministic unit tests with injected clock
  (``poll(now=...)``) and scripted ``probe_fn``/``reload_fn``: join,
  probe-failure eviction, backoff re-probe, quarantine + re-register,
  partition declare/heal, rolling reload with rollback and re-admission
  catch-up.
* **Router** — least-loaded over fresh queue_depth gauges with the
  stale-sample pin (a stale depth-0 member must NOT beat a fresh
  depth-5 one), retry-once under the token-bucket budget, per-member
  circuit breakers, and hedging counted apart from retries.
* **End-to-end chaos** — a REAL pool + router over REAL localhost-TCP
  subprocesses (``tests/fabric_worker.py``): kill -9 → eviction +
  retry keeps availability; ``MXR_FAULT_NET_RESET`` trips a breaker
  that closes after recovery; ``MXR_FAULT_NET_DROP`` partitions the
  majority away and the reachable subset keeps serving; a rolling
  remote reload lands with zero non-2xx.  ``script/fabric_smoke.sh``
  repeats the topology with the real model.
"""

import argparse
import importlib.util
import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.serve import fabric as fb
from mx_rcnn_tpu.serve import replica as rp
from mx_rcnn_tpu.serve import supervisor as sv
from mx_rcnn_tpu.serve import encode_image_payload, parse_address
from tests.faults import net_fault_env

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "fabric_worker.py")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _restore_sink():
    yield
    telemetry.shutdown()


# -- addresses --------------------------------------------------------------


def test_parse_address_grammar():
    assert parse_address("127.0.0.1:8321") == ("tcp", "127.0.0.1", 8321)
    assert parse_address("hostA:80") == ("tcp", "hostA", 80)
    assert parse_address("/tmp/r0.sock") == ("unix", "/tmp/r0.sock", None)
    assert parse_address("unix:/tmp/r0.sock") == ("unix", "/tmp/r0.sock",
                                                  None)
    for bad in ("8321", "host:", ":80", "host:eighty"):
        with pytest.raises(ValueError):
            parse_address(bad)


def test_normalize_address_dedupes_spellings():
    assert fb.normalize_address(" 127.0.0.1:08321 ") == "127.0.0.1:8321"
    assert fb.normalize_address("/tmp/x.sock") == "unix:/tmp/x.sock"
    assert fb.normalize_address("unix:/tmp/x.sock") == "unix:/tmp/x.sock"
    with pytest.raises(ValueError):
        fb.normalize_address("nonsense")


# -- circuit breaker --------------------------------------------------------


def test_breaker_opens_half_opens_and_closes():
    br = fb.CircuitBreaker(threshold=3, cooldown_s=5.0)
    assert br.allow(now=0.0)
    assert not br.record_failure(now=0.0)
    assert not br.record_failure(now=0.0)
    assert br.record_failure(now=0.0)       # third failure OPENS (once)
    assert br.state == br.OPEN
    assert not br.allow(now=4.9)            # cooling down
    assert br.allow(now=5.0)                # the single half-open trial
    assert br.state == br.HALF_OPEN
    assert not br.allow(now=5.0)            # trial in flight: hold
    br.record_success()
    assert br.state == br.CLOSED and br.allow(now=5.1)


def test_breaker_half_open_failure_reopens():
    br = fb.CircuitBreaker(threshold=1, cooldown_s=2.0)
    assert br.record_failure(now=0.0)       # opens
    assert br.allow(now=2.0)                # trial
    assert br.record_failure(now=2.0)       # trial failed: re-opens
    assert br.state == br.OPEN
    assert not br.allow(now=3.9)
    assert br.allow(now=4.0)


# -- net fault parsing ------------------------------------------------------


def test_net_faults_parse_and_index_match():
    env = {rp.ENV_NET_DROP: "1:4", rp.ENV_NET_RESET: "0:2-5",
           rp.ENV_NET_DELAY: "2:150.5"}
    f0, f1, f2 = (rp.NetFaults(i, env) for i in range(3))
    assert f0.reset_from == 2 and f0.reset_to == 5
    assert f0.drop_after is None and f0.delay_ms == 0.0
    assert f1.drop_after == 4 and f1.reset_from is None
    assert f2.delay_ms == 150.5
    assert all(f.enabled for f in (f0, f1, f2))
    assert not rp.NetFaults(3, env).enabled
    # bare token = fault from the start; open-ended reset range
    f = rp.NetFaults(0, {rp.ENV_NET_DROP: "0", rp.ENV_NET_RESET: "0:3"})
    assert f.drop_after == 0
    assert f.reset_from == 3 and f.reset_to is None


def test_net_fault_env_composer_round_trips():
    env = {**net_fault_env(2, drop_after=3),
           **net_fault_env(1, delay_ms=25.0),
           **net_fault_env(0, reset_from=1, reset_to=6)}
    assert rp.NetFaults(2, env).drop_after == 3
    assert rp.NetFaults(1, env).delay_ms == 25.0
    f = rp.NetFaults(0, env)
    assert (f.reset_from, f.reset_to) == (1, 6)


def test_net_faults_reset_counts_only_predicts():
    class FakeConn:
        def setsockopt(self, *a):
            raise OSError("fake")

        def close(self):
            pass

    class FakeHandler:
        connection = FakeConn()
        close_connection = False

    f = rp.NetFaults(0, net_fault_env(0, reset_from=2))
    h = FakeHandler()
    assert not f.intercept("/readyz", h)      # probes never count
    assert not f.intercept("/predict", h)     # predict #1: before range
    assert not f.intercept("/healthz", h)
    assert f.intercept("/predict", h)         # predict #2: reset
    assert h.close_connection


# -- dormant-by-default: fork mode untouched --------------------------------


def test_build_child_argv_strips_fabric_flags():
    argv = ["serve.py", "--network", "resnet50", "--replicas", "2",
            "--fabric", "--join", "127.0.0.1:8320", "--pool-file", "/p",
            "--advertise", "h:1", "--hedge-after-ms", "50",
            "--partition-floor", "0.5", "--serve-batch", "4"]
    out = sv.build_child_argv(argv, "/tmp/r0.sock", 0)
    joined = " ".join(out)
    for flag in ("--fabric", "--join", "--pool-file", "--advertise",
                 "--hedge-after-ms", "--partition-floor"):
        assert flag not in joined, joined
    assert "--serve-batch 4" in joined
    assert out[-4:] == ["--unix-socket", "/tmp/r0.sock",
                        "--replica-index", "0"]


def test_choose_mode_dispatch_keeps_fork_plane_bit_identical():
    import serve

    def ns(**kw):
        base = dict(replica_index=-1, replicas=1, fabric=False,
                    pool_file="", join="")
        base.update(kw)
        return argparse.Namespace(**base)

    # with every fabric flag dormant, the pre-fabric decision tree
    assert serve.choose_mode(ns()) == "single"
    assert serve.choose_mode(ns(replicas=4)) == "plane"
    assert serve.choose_mode(ns(replicas=4, replica_index=2)) == "replica"
    # opt-in paths
    assert serve.choose_mode(ns(fabric=True)) == "fabric"
    assert serve.choose_mode(ns(pool_file="/p")) == "fabric"
    assert serve.choose_mode(ns(join="h:1")) == "member"
    assert serve.choose_mode(ns(fabric=True, replicas=2)) == "fabric"
    # child check stays FIRST even under fabric flags
    assert serve.choose_mode(ns(fabric=True, replica_index=0)) == "replica"


# -- pool state machine (scripted probes, fake clock) -----------------------


class PoolHarness:
    """A ReplicaPool with scriptable probe/reload answers per member."""

    def __init__(self, **opt_kw):
        self.answers = {}   # name -> (status, doc) | Exception
        self.probes = []    # member names in probe order
        self.reloads = []   # (name, target) in call order
        self.reload_status = 200

        def probe(member, path):
            self.probes.append(member.name)
            a = self.answers.get(member.name,
                                 OSError("connection refused"))
            if isinstance(a, Exception):
                raise a
            return a

        def reload_fn(member, target):
            self.reloads.append((member.name, dict(target)))
            st = (self.reload_status(member, target)
                  if callable(self.reload_status) else self.reload_status)
            if st == 200:
                return st, {"generation": target.get("generation"),
                            "recompiles_during_swap": 0}
            return st, {"error": "canary failed: injected"}

        self.pool = fb.ReplicaPool(fb.FabricOptions(**opt_kw),
                                   probe_fn=probe, reload_fn=reload_fn)

    def up(self, name, depth=0, generation=0):
        self.answers[name] = (200, {"ready": True, "queue_depth": depth,
                                    "generation": generation})

    def warming(self, name, depth=0):
        self.answers[name] = (503, {"ready": False, "queue_depth": depth})

    def down(self, name):
        self.answers[name] = OSError("connection refused")


A, B, C = "10.0.0.1:8000", "10.0.0.2:8000", "10.0.0.3:8000"


def test_register_probe_join():
    hz = PoolHarness()
    m, created = hz.pool.register(A, now=0.0)
    assert created and m.state == fb.JOINING and not m.routable
    _, created2 = hz.pool.register(A, now=0.0)
    assert not created2 and len(hz.pool.members) == 1
    hz.up(A, depth=3, generation=0)
    hz.pool.poll(now=1.0)
    assert m.state == fb.MEMBER_READY and m.routable
    assert m.depth == 3 and m.depth_t == 1.0
    assert hz.pool.counters["member_joined"] == 1


def test_warming_member_not_routable_not_evicted():
    hz = PoolHarness()
    m, _ = hz.pool.register(A, now=0.0)
    hz.warming(A)
    for t in (1.0, 2.0, 3.0, 4.0):
        hz.pool.poll(now=t)
    assert m.state == fb.JOINING and not m.routable  # alive, warming


def test_eviction_after_consecutive_probe_failures():
    hz = PoolHarness(evict_probes=3)
    m, _ = hz.pool.register(A, now=0.0)
    hz.up(A)
    hz.pool.poll(now=1.0)
    hz.down(A)
    hz.pool.poll(now=2.0)
    assert m.state == fb.MEMBER_READY and not m.routable  # suspect
    hz.pool.poll(now=3.0)
    assert m.state == fb.MEMBER_READY
    hz.pool.poll(now=4.0)                                 # third miss
    assert m.state == fb.EVICTED and m.depth_t is None
    assert hz.pool.counters["member_evicted"] == 1


def test_single_missed_probe_recovers_without_eviction():
    hz = PoolHarness(evict_probes=3)
    m, _ = hz.pool.register(A, now=0.0)
    hz.up(A)
    hz.pool.poll(now=1.0)
    hz.down(A)
    hz.pool.poll(now=2.0)
    assert not m.routable
    hz.up(A)
    hz.pool.poll(now=3.0)
    assert m.routable and m.probe_fails == 0
    assert hz.pool.counters["member_evicted"] == 0


def test_readmission_after_eviction_counts_as_join():
    hz = PoolHarness(evict_probes=1, backoff_base_s=0.5)
    m, _ = hz.pool.register(A, now=0.0)
    hz.up(A)
    hz.pool.poll(now=1.0)
    hz.down(A)
    hz.pool.poll(now=2.0)
    assert m.state == fb.EVICTED
    hz.up(A, generation=0)
    hz.pool.poll(now=2.1)            # backoff not elapsed: no probe yet
    assert m.state == fb.EVICTED
    hz.pool.poll(now=2.6)
    assert m.state == fb.MEMBER_READY and m.routable
    assert hz.pool.counters["member_joined"] == 2


def test_eviction_backoff_schedule_and_quarantine():
    hz = PoolHarness(evict_probes=1, backoff_base_s=0.5, backoff_max_s=4.0,
                     max_failures=100)
    m, _ = hz.pool.register(A, now=0.0)
    hz.up(A)
    hz.pool.poll(now=1.0)
    hz.down(A)
    now, delays = 1.0, []
    for _ in range(6):
        hz.pool.poll(now=now + 0.01)
        delays.append(round(m.next_probe_t - (now + 0.01), 3))
        now = m.next_probe_t
    assert delays == [0.5, 1.0, 2.0, 4.0, 4.0, 4.0]  # doubles, capped


def test_quarantine_stops_probing_until_reregister():
    hz = PoolHarness(evict_probes=1, backoff_base_s=0.1, max_failures=2)
    m, _ = hz.pool.register(A, now=0.0)
    hz.up(A)
    hz.pool.poll(now=1.0)
    hz.down(A)
    now = 1.0
    while m.state != fb.QUARANTINED:
        now = max(now + 0.2, m.next_probe_t)
        hz.pool.poll(now=now)
        assert now < 100.0
    assert hz.pool.counters["member_quarantined"] == 1
    n_probes = len(hz.probes)
    hz.pool.poll(now=now + 50.0)
    assert len(hz.probes) == n_probes    # quarantined: not probed
    # explicit re-register is the escape hatch
    _, created = hz.pool.register(A, now=now + 51.0)
    assert not created and m.state == fb.JOINING and m.failures == 0
    hz.up(A)
    hz.pool.poll(now=now + 52.0)
    assert m.state == fb.MEMBER_READY


def test_partition_declared_and_healed():
    hz = PoolHarness(evict_probes=1, partition_floor=0.5,
                     backoff_base_s=100.0)
    for name in (A, B, C):
        hz.pool.register(name, now=0.0)
        hz.up(name)
    hz.pool.poll(now=1.0)
    assert hz.pool.ready_count() == 3 and not hz.pool.partition
    hz.down(A)
    hz.down(B)
    hz.pool.poll(now=2.0)
    assert hz.pool.ready_count() == 1
    assert hz.pool.partition                   # 1/3 < 0.5
    assert hz.pool.counters["partition"] == 1
    hz.pool.poll(now=3.0)
    assert hz.pool.counters["partition"] == 1  # once per transition
    # heal: members answer again at their backoff instants
    hz.up(A)
    hz.up(B)
    for m in hz.pool.members.values():
        m.next_probe_t = 0.0
    hz.pool.poll(now=4.0)
    assert not hz.pool.partition and hz.pool.ready_count() == 3


def test_partition_alarm_gated_until_pool_ever_formed():
    hz = PoolHarness(partition_floor=0.5)
    hz.pool.register(A, now=0.0)
    hz.down(A)
    for t in (1.0, 2.0, 3.0):
        hz.pool.poll(now=t)
    assert not hz.pool.partition               # a boot, not a partition
    assert hz.pool.counters["partition"] == 0


def test_pool_file_seeds_members(tmp_path):
    pf = tmp_path / "pool.txt"
    pf.write_text(f"# fabric members\n{A}\n\n{B}  # rack 2\nunix:/tmp/x\n")
    hz = PoolHarness()
    assert hz.pool.load_pool_file(str(pf)) == 3
    assert set(hz.pool.members) == {A, B, "unix:/tmp/x"}


def test_rolling_reload_all_members_and_generation():
    hz = PoolHarness()
    for name in (A, B):
        hz.pool.register(name, now=0.0)
        hz.up(name)
    hz.pool.poll(now=1.0)
    assert hz.pool.reload_to({"prefix": "/ck", "kind": "file"})
    assert hz.pool.generation == 1
    assert [r[0] for r in hz.reloads] == [A, B]
    assert all(r[1]["generation"] == 1 for r in hz.reloads)
    m_a, m_b = hz.pool.members[A], hz.pool.members[B]
    assert m_a.generation == m_b.generation == 1
    assert m_a.routable and m_b.routable       # re-routed after the swap
    assert m_a.last_reload["recompiles_during_swap"] == 0
    assert hz.pool.counters["reload"] == 2
    assert hz.pool.counters["reload_rollback"] == 0


def test_rolling_reload_rejection_rolls_back_swapped_members():
    hz = PoolHarness()
    for name in (A, B):
        hz.pool.register(name, now=0.0)
        hz.up(name)
    hz.pool.poll(now=1.0)
    assert hz.pool.reload_to({"prefix": "/g1", "kind": "file"})
    hz.reloads.clear()
    # generation 2: B's canary rejects → A must roll BACK to gen 1
    hz.reload_status = lambda m, t: 409 if m.name == B else 200
    assert not hz.pool.reload_to({"prefix": "/g2", "kind": "file"})
    assert hz.pool.generation == 1             # monotonic, not advanced
    assert [(n, t["generation"], t["prefix"]) for n, t in hz.reloads] == \
        [(A, 2, "/g2"), (B, 2, "/g2"), (A, 1, "/g1")]
    assert hz.pool.counters["reload_rollback"] == 1
    assert hz.pool.members[A].generation == 1


def test_readmitted_member_catches_up_to_pool_generation():
    hz = PoolHarness(evict_probes=1, backoff_base_s=0.1)
    for name in (A, B):
        hz.pool.register(name, now=0.0)
        hz.up(name)
    hz.pool.poll(now=1.0)
    assert hz.pool.reload_to({"prefix": "/g1", "kind": "file"})
    hz.reloads.clear()
    hz.down(B)
    hz.pool.poll(now=2.0)
    assert hz.pool.members[B].state == fb.EVICTED
    # B restarts on its BOOT weights (generation 0) and is re-admitted:
    # the pool must catch it up to generation 1 before routing to it
    hz.up(B, generation=0)
    hz.pool.poll(now=3.0)
    assert hz.pool.members[B].state == fb.MEMBER_READY
    assert hz.reloads == [(B, dict({"prefix": "/g1", "kind": "file"},
                                   generation=1))]
    assert hz.pool.members[B].generation == 1


def test_reload_roll_survives_concurrent_register():
    """A /admin/register landing mid-roll (handler threads mutate the
    member dict while reload_to blocks inside _reload_one) must not
    abort the roll: the victim list and the post-roll catch-up loop
    both iterate a locked snapshot, never the live dict."""
    hz = PoolHarness()
    for name in (A, B):
        hz.pool.register(name, now=0.0)
        hz.up(name)
    hz.pool.poll(now=1.0)
    late_ready = []

    def reload_status(member, target):
        # every swap, a new member registers — the handler-thread race
        # run inline, so the dict mutates at the worst possible moment
        hz.pool.register(f"10.0.9.{len(hz.pool.members)}:8000", now=2.0)
        if member.name == A and not late_ready:
            # ... and one arrives READY at a stale generation, forcing
            # the catch-up loop itself to reload (and thus re-register)
            # mid-pass
            late, _ = hz.pool.register(C, now=2.0)
            late.state = fb.MEMBER_READY
            late.routable = True
            late_ready.append(late)
        return 200

    hz.reload_status = reload_status
    assert hz.pool.reload_to({"prefix": "/g1", "kind": "file"})
    assert hz.pool.generation == 1
    assert hz.pool.members[C].generation == 1  # straggler caught up


# -- router: least-loaded, the stale-gauge pin, retries, hedging ------------


def _ready_pool(depths, now=100.0, **opt_kw):
    """A pool with ready remote members at the given fresh depths."""
    hz = PoolHarness(**opt_kw)
    for name, depth in depths.items():
        m, _ = hz.pool.register(name, now=0.0)
        m.state = fb.MEMBER_READY
        m.routable = True
        if depth is not None:
            m.depth = depth
            m.depth_t = now
    return hz


def test_least_loaded_picks_min_depth_plus_inflight():
    hz = _ready_pool({A: 3, B: 1}, now=100.0)
    router = fb.FabricRouter(hz.pool)
    assert router._pick(now=100.1).name == B
    hz.pool.members[B].inflight = 5            # in-flight counts as load
    assert router._pick(now=100.1).name == A


def test_stale_gauge_ignored_by_least_loaded():
    """THE stale-gauge pin (ISSUE 12 satellite): a member whose depth-0
    sample is older than 2 probe intervals must NOT beat a member with a
    fresh depth-5 sample — a stale gauge is history, not load."""
    hz = _ready_pool({A: None, B: 5}, now=110.0, probe_interval_s=1.0,
                     stale_probe_intervals=2.0)
    m_a = hz.pool.members[A]
    m_a.depth = 0
    m_a.depth_t = 100.0                        # 10s old: stale
    router = fb.FabricRouter(hz.pool)
    for _ in range(4):                         # never the stale zero
        assert router._pick(now=110.5).name == B
    # metrics surface the same verdict the router acted on
    doc = hz.pool.metrics(now=110.5)
    assert doc["members"][A]["queue_depth_stale"]
    assert not doc["members"][B]["queue_depth_stale"]
    # ... and once EVERY sample is stale, round-robin over all routable
    hz.pool.members[B].depth_t = 100.0
    picked = {router._pick(now=110.5).name for _ in range(4)}
    assert picked == {A, B}


def test_depth_ties_rotate_round_robin():
    hz = _ready_pool({A: 0, B: 0}, now=100.0)
    router = fb.FabricRouter(hz.pool)
    picked = [router._pick(now=100.1).name for _ in range(4)]
    assert sorted(picked[:2]) == [A, B] and sorted(picked[2:]) == [A, B]


def test_open_breaker_excludes_member_from_picks():
    hz = _ready_pool({A: 0, B: 9}, now=100.0)
    hz.pool.members[A].breaker.state = fb.CircuitBreaker.OPEN
    hz.pool.members[A].breaker.open_until = 1e18
    router = fb.FabricRouter(hz.pool)
    assert router._pick(now=100.1).name == B


def test_unpicked_candidate_keeps_half_open_trial():
    """THE breaker-consumption pin: a cooled-down OPEN member that is a
    candidate but loses the least-loaded pick must KEEP its half-open
    trial — candidate filtering is can_attempt() (side-effect-free),
    and only the member actually picked pays allow().  Filtering with
    allow() burned the trial with no request behind it, leaving the
    member permanently unroutable after any transient failure burst."""
    hz = _ready_pool({A: 9, B: 0}, now=100.0)
    m_a = hz.pool.members[A]
    m_a.breaker.state = fb.CircuitBreaker.OPEN
    m_a.breaker.open_until = 99.0              # cooldown elapsed
    router = fb.FabricRouter(hz.pool)
    for _ in range(4):                         # B always wins on depth
        assert router._pick(now=100.1).name == B
    # A was a losing candidate 4 times over — its trial must survive
    assert m_a.breaker.state == fb.CircuitBreaker.OPEN
    assert m_a.breaker.can_attempt(100.2)
    # ... and the pick that finally lands on A consumes it for real
    hz.pool.members[B].routable = False
    assert router._pick(now=100.3).name == A
    assert m_a.breaker.state == fb.CircuitBreaker.HALF_OPEN
    m_a.breaker.record_success()
    assert m_a.breaker.state == fb.CircuitBreaker.CLOSED


def test_route_predict_retries_once_on_alternate():
    hz = _ready_pool({A: 0, B: 1}, now=time.monotonic())

    def forward(member, method, path, body, timeout):
        if member.name == A:
            raise ConnectionResetError("injected")
        return 200, b'{"ok": true}', "application/json"

    router = fb.FabricRouter(hz.pool, forward_fn=forward)
    status, raw, _ = router.route_predict(b"{}")
    assert status == 200 and b"ok" in raw
    c = hz.pool.counters
    assert c["transport_error"] == 1
    assert c["retry"] == 1 and c["retry_ok"] == 1
    assert c["hedge_fired"] == 0               # a retry is not a hedge
    assert not hz.pool.members[A].routable     # suspect until re-probed


def test_route_predict_retry_budget_exhausted_sheds():
    hz = _ready_pool({A: 0, B: 1}, now=time.monotonic(),
                     retry_budget=1, retry_refill_per_s=0.0)

    def forward(member, method, path, body, timeout):
        raise ConnectionResetError("injected")

    router = fb.FabricRouter(hz.pool, forward_fn=forward)
    # members become suspect as they fail; re-route them for each call
    status, _, _ = router.route_predict(b"{}")
    assert status in (502, 503)
    for m in hz.pool.members.values():
        m.routable = True
    status, _, _ = router.route_predict(b"{}")
    assert status == 503                       # budget gone: early shed
    assert hz.pool.counters["retry_budget_exhausted"] == 1


def test_route_predict_no_members_sheds():
    hz = PoolHarness()
    router = fb.FabricRouter(hz.pool)
    status, raw, ctype = router.route_predict(b"{}")
    assert status == 503 and ctype == "application/json"
    assert hz.pool.counters["no_ready"] == 1


def test_breaker_opens_after_consecutive_transport_failures():
    hz = _ready_pool({A: 0}, now=time.monotonic(), breaker_failures=2)

    def forward(member, method, path, body, timeout):
        raise ConnectionResetError("injected")

    router = fb.FabricRouter(hz.pool, forward_fn=forward)
    m = hz.pool.members[A]
    for _ in range(2):
        m.routable = True
        router.route_predict(b"{}")
    assert m.breaker.state == fb.CircuitBreaker.OPEN
    assert hz.pool.counters["breaker_open"] == 1
    m.routable = True
    status, _, _ = router.route_predict(b"{}")  # breaker holds the door
    assert status == 503
    assert hz.pool.counters["no_ready"] == 1


def test_member_503_is_breaker_neutral():
    hz = _ready_pool({A: 0}, now=time.monotonic(), breaker_failures=1)

    def forward(member, method, path, body, timeout):
        return 503, b'{"error": "shed"}', "application/json"

    router = fb.FabricRouter(hz.pool, forward_fn=forward)
    status, _, _ = router.route_predict(b"{}")
    assert status == 503                       # the lone member's own shed
    m = hz.pool.members[A]
    assert m.breaker.state == fb.CircuitBreaker.CLOSED


def test_hedge_fires_after_threshold_and_first_2xx_wins():
    now = time.monotonic()
    hz = _ready_pool({A: 0, B: 1}, now=now, hedge_after_ms=30.0)

    def forward(member, method, path, body, timeout):
        if member.name == A:
            time.sleep(0.4)                    # the slow primary
        return (200, json.dumps({"from": member.name}).encode(),
                "application/json")

    router = fb.FabricRouter(hz.pool, forward_fn=forward)
    t0 = time.monotonic()
    status, raw, _ = router.route_predict(b"{}")
    assert status == 200
    assert json.loads(raw)["from"] == B        # the hedge won
    assert time.monotonic() - t0 < 0.35        # did not wait out the slow
    c = hz.pool.counters
    assert c["hedge_fired"] == 1 and c["hedge_won"] == 1
    assert c["retry"] == 0                     # a hedge is not a retry


def test_fast_primary_never_hedges():
    hz = _ready_pool({A: 0, B: 1}, now=time.monotonic(),
                     hedge_after_ms=200.0)

    def forward(member, method, path, body, timeout):
        return 200, b'{"ok": 1}', "application/json"

    router = fb.FabricRouter(hz.pool, forward_fn=forward)
    status, _, _ = router.route_predict(b"{}")
    assert status == 200
    assert hz.pool.counters["hedge_fired"] == 0


def test_hedge_survives_primary_transport_death():
    now = time.monotonic()
    hz = _ready_pool({A: 0, B: 1}, now=now, hedge_after_ms=20.0)

    def forward(member, method, path, body, timeout):
        if member.name == A:
            time.sleep(0.1)
            raise ConnectionResetError("injected")
        return 200, b'{"ok": 1}', "application/json"

    router = fb.FabricRouter(hz.pool, forward_fn=forward)
    status, _, _ = router.route_predict(b"{}")
    assert status == 200
    assert hz.pool.counters["hedge_fired"] == 1


def test_pool_metrics_shape():
    hz = _ready_pool({A: 2}, now=100.0)
    doc = hz.pool.metrics(now=100.5)
    m = doc["members"][A]
    assert m["queue_depth"] == 2 and m["queue_depth_age_s"] == 0.5
    assert not m["queue_depth_stale"] and m["breaker"] == "closed"
    assert doc["ready"] == 1 and not doc["partition"]
    assert set(doc["counters"]) >= {"member_joined", "member_evicted",
                                    "breaker_open", "hedge_fired",
                                    "hedge_won", "partition"}


def test_fabric_prometheus_exposition():
    hz = _ready_pool({A: 2}, now=time.monotonic())
    hz.pool.count("hedge_fired")
    router = fb.FabricRouter(hz.pool)
    text = fb.fabric_prometheus(router)
    assert "fabric_hedge_fired" in text
    assert "fabric_ready_members" in text
    assert "fabric_partition_active" in text
    assert "fabric_queue_depth" in text


def test_fabric_prometheus_survives_evicted_member():
    """_evict clears depth_t but keeps depth; the Prometheus view must
    gate the age gauge on depth_t or /metrics?format=prom 500s whenever
    any member sits evicted awaiting re-probe."""
    hz = _ready_pool({A: 2, B: 1}, now=time.monotonic())
    hz.pool._evict(hz.pool.members[A], now=time.monotonic(),
                   reason="injected")
    text = fb.fabric_prometheus(fb.FabricRouter(hz.pool))  # must not raise
    # the evicted member's gauges drop; the survivor's still render
    assert "queue_depth_age_s_10_0_0_1:8000" not in text
    assert "queue_depth_age_s_10_0_0_2:8000" in text


# -- satellite gates: loadgen member share + perf_gate fabric rows ----------


def test_loadgen_member_share_diff():
    lg = _load_script("loadgen")
    share = lg.member_share({A: 10, B: 0}, {A: 30, B: 10, C: 5})
    assert share == {A: 0.5714, B: 0.2857, C: 0.1429}
    assert lg.member_share({}, {}) == {}


def test_perf_gate_fabric_floor_rows(tmp_path):
    pg = _load_script("perf_gate")

    def write(agg, per, n=3, **extra):
        doc = {"schema": "mxr_fabric_report", "version": 1,
               "members": n, "aggregate_imgs_per_sec": agg,
               "per_member_imgs_per_sec": per, **extra}
        (tmp_path / "FABRIC_r01.json").write_text(json.dumps(doc))

    write(27.0, 10.0)                        # linearity 0.9 ≥ 0.85
    assert pg.main(["--dir", str(tmp_path)]) == 0
    assert pg.main(["--dir", str(tmp_path), "--check-format"]) == 0
    write(18.0, 10.0)                        # 0.6 < 0.85 → fail
    assert pg.main(["--dir", str(tmp_path)]) == 1
    write(18.0, 10.0, linearity_floor=0.5)   # CPU smoke's own floor
    assert pg.main(["--dir", str(tmp_path)]) == 0
    # the fabric-specific property: availability UNDER partition
    write(27.0, 10.0, availability_under_partition=0.85)
    assert pg.main(["--dir", str(tmp_path)]) == 1   # < 0.90 default
    write(27.0, 10.0, availability_under_partition=0.95,
          availability=0.92, availability_floor=0.9)
    assert pg.main(["--dir", str(tmp_path)]) == 0
    write(27.0, 10.0, availability=0.85, availability_floor=0.9)
    assert pg.main(["--dir", str(tmp_path)]) == 1


def test_telemetry_report_fabric_health_section(tmp_path):
    from mx_rcnn_tpu.telemetry import report as trep
    tel = telemetry.configure(str(tmp_path), run_meta={"driver": "t"})
    tel.counter("fabric/member_evicted", 2)
    tel.counter("fabric/hedge_fired", 3)
    tel.counter("serve/requests", 5)
    telemetry.shutdown()
    summary = trep.aggregate(trep.load_events([str(tmp_path)]))
    table = trep.render_table(summary)
    assert "fabric health" in table
    idx = table.index("fabric health")
    block = table[idx:]
    assert "fabric/member_evicted" in block
    assert "fabric/breaker_open" in block      # zeros included
    assert "fabric/hedge_won" in block


# -- end-to-end chaos: real pool + router over real TCP subprocesses --------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _member_proc(port, index=0, env=None, params_file=""):
    argv = [sys.executable, WORKER, "--port", str(port),
            "--replica-index", str(index)]
    if params_file:
        argv += ["--params-file", params_file]
    return subprocess.Popen(
        argv, env={**os.environ, "JAX_PLATFORMS": "cpu", **(env or {})})


def _e2e_opts(**kw):
    base = dict(probe_interval_s=0.2, probe_timeout_s=2.0,
                evict_probes=2, start_timeout_s=120.0,
                backoff_base_s=0.2, backoff_max_s=1.0, stable_s=5.0,
                drain_timeout_s=15.0, reload_timeout_s=60.0)
    base.update(kw)
    return fb.FabricOptions(**base)


def _wait(cond, timeout=90.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


def _predict_body():
    doc = encode_image_payload(np.full((60, 100, 3), 50, np.uint8))
    return json.dumps(doc).encode()


def _cleanup(pool, procs):
    pool.stop()
    for p in procs:
        p.kill()
        p.wait(timeout=30)


def test_e2e_kill9_eviction_retry_and_readmission():
    """Kill -9 one of two REAL TCP members mid-burst: the router keeps
    availability over the survivor (retry-once), the pool EVICTS the
    corpse (no respawn authority over a remote host), and a restart on
    the same address is re-admitted by the probe loop alone."""
    ports = [_free_port(), _free_port()]
    procs = [_member_proc(ports[0], 0), _member_proc(ports[1], 1)]
    # a LONG probe interval keeps the corpse routable until the next
    # poll, guaranteeing requests land on it and exercise the retry
    # path (the same race test_replica's kill9 test closes)
    pool = fb.ReplicaPool(_e2e_opts(probe_interval_s=1.0))
    for port in ports:
        pool.register(f"127.0.0.1:{port}")
    pool.start()
    try:
        _wait(lambda: pool.ready_count() == 2, what="both members ready")
        router = fb.FabricRouter(pool, timeout_s=30.0)
        body = _predict_body()
        statuses = []
        for i in range(30):
            if i == 5:
                procs[0].kill()            # SIGKILL mid-burst
            status, _, _ = router.route_predict(body)
            statuses.append(status)
            time.sleep(0.02)
        # every response resolved to a 2xx or an honest shed — and the
        # availability floor holds over non-shed submits
        assert set(statuses) <= {200, 503}, statuses
        ok, shed = statuses.count(200), statuses.count(503)
        assert ok / max(len(statuses) - shed, 1) >= 0.9, statuses
        assert ok >= 20, statuses
        assert pool.counters["transport_error"] >= 1
        assert pool.counters["retry_ok"] >= 1
        _wait(lambda: pool.counters["member_evicted"] >= 1,
              what="eviction of the corpse")
        # restart on the SAME address: re-admission is the router's
        # re-probe loop, no re-register needed
        procs[0] = _member_proc(ports[0], 0)
        _wait(lambda: pool.ready_count() == 2, timeout=120.0,
              what="re-admission after restart")
        assert pool.counters["member_joined"] >= 3
    finally:
        _cleanup(pool, procs)


def test_e2e_net_reset_trips_breaker_then_closes():
    """``MXR_FAULT_NET_RESET`` on a member whose probes stay healthy:
    /predict connection resets must OPEN the per-member breaker (the
    readiness probe cannot see this failure mode), and once the reset
    range passes the half-open trial must CLOSE it again."""
    port = _free_port()
    procs = [_member_proc(port, 0,
                          env=net_fault_env(0, reset_from=1, reset_to=4))]
    pool = fb.ReplicaPool(_e2e_opts(breaker_failures=2,
                                    breaker_cooldown_s=0.5))
    pool.register(f"127.0.0.1:{port}")
    pool.start()
    try:
        _wait(lambda: pool.ready_count() == 1, what="member ready")
        m = pool.members[f"127.0.0.1:{port}"]
        router = fb.FabricRouter(pool, timeout_s=30.0)
        body = _predict_body()
        _wait(lambda: (router.route_predict(body),
                       pool.counters["breaker_open"] >= 1)[1],
              timeout=30.0, what="breaker to open on resets")
        assert pool.counters["transport_error"] >= 2
        # recovery: past the reset range a half-open trial lands a 200
        # and the breaker closes — the member is back in rotation
        def recovered():
            status, _, _ = router.route_predict(body)
            return (status == 200
                    and m.breaker.state == fb.CircuitBreaker.CLOSED)
        _wait(recovered, timeout=60.0, what="breaker to close again")
    finally:
        _cleanup(pool, procs)


def test_e2e_partition_flight_dump_and_degraded_serving(tmp_path):
    """``MXR_FAULT_NET_DROP`` blackholes 2 of 3 members (alive but
    unreachable — the partition shape): the pool evicts them off probe
    timeouts, declares ``fabric_partition`` (counter + flight dump),
    and the reachable subset KEEPS serving 200s."""
    telemetry.configure(str(tmp_path), run_meta={"driver": "t"})
    ports = [_free_port() for _ in range(3)]
    procs = [
        _member_proc(ports[0], 0, env=net_fault_env(0, drop_after=0)),
        _member_proc(ports[1], 1, env=net_fault_env(1, drop_after=0)),
        _member_proc(ports[2], 2),
    ]
    pool = fb.ReplicaPool(_e2e_opts(probe_timeout_s=0.5,
                                    partition_floor=0.5,
                                    backoff_max_s=0.5))
    for port in ports:
        pool.register(f"127.0.0.1:{port}")
    pool.start()
    try:
        _wait(lambda: pool.ready_count() == 3, what="all 3 ready")
        # a short forward timeout so requests that land on a member mid-
        # blackhole fail fast and retry instead of hanging the burst
        router = fb.FabricRouter(pool, timeout_s=2.0)
        body = _predict_body()
        # enough traffic that both faulted members cross their drop
        # threshold (first /predict each) and go dark
        for _ in range(8):
            router.route_predict(body)
            time.sleep(0.05)
        _wait(lambda: pool.partition, timeout=60.0,
              what="partition declared")
        assert pool.counters["partition"] >= 1
        assert pool.counters["member_evicted"] >= 2
        # the reachable subset serves: the survivor answers 200
        def survivor_200():
            status, _, _ = router.route_predict(body)
            return status == 200
        _wait(survivor_200, timeout=30.0, what="survivor serving 200s")
        flight = os.path.join(str(tmp_path), "flight_0.jsonl")
        assert os.path.exists(flight), "no flight dump"
        assert "fabric_partition" in open(flight).read()
    finally:
        _cleanup(pool, procs)
        telemetry.shutdown()


def test_e2e_rolling_remote_reload_zero_drops(tmp_path):
    """Roll a params swap across two REAL TCP members under open
    traffic: every request lands a 2xx, both members reach generation
    1, zero recompiles during either swap (registry-asserted via the
    reload response), no rollback."""
    pfile = str(tmp_path / "params.json")
    with open(pfile, "w") as f:
        json.dump({"scale": 1.0}, f)
    ports = [_free_port(), _free_port()]
    procs = [_member_proc(ports[i], i, params_file=pfile)
             for i in range(2)]
    pool = fb.ReplicaPool(_e2e_opts())
    for port in ports:
        pool.register(f"127.0.0.1:{port}")
    pool.start()
    try:
        _wait(lambda: pool.ready_count() == 2, what="both members ready")
        router = fb.FabricRouter(pool, timeout_s=30.0)
        body = _predict_body()
        statuses = []
        stop = threading.Event()

        def traffic():
            while not stop.is_set():
                status, _, _ = router.route_predict(body)
                statuses.append(status)
                time.sleep(0.03)

        th = threading.Thread(target=traffic, daemon=True)
        th.start()
        time.sleep(0.3)
        with open(pfile, "w") as f:
            json.dump({"scale": 2.0}, f)
        ok = pool.reload_to({"prefix": pfile, "kind": "file",
                             "epoch": 1, "consumed": 0})
        time.sleep(0.3)
        stop.set()
        th.join(timeout=30.0)
        assert ok and pool.generation == 1
        for m in pool.members.values():
            assert m.generation == 1
            assert m.last_reload["recompiles_during_swap"] == 0
        # THE zero-downtime claim, now cross-host: not one dropped
        assert statuses and set(statuses) == {200}, statuses
        assert pool.counters["reload"] == 2
        assert pool.counters["reload_rollback"] == 0
    finally:
        _cleanup(pool, procs)


def test_e2e_join_self_registration():
    """A member started with ``--join`` registers itself: the router
    needs no prior knowledge of its address."""
    router_port = _free_port()
    member_port = _free_port()
    pool = fb.ReplicaPool(_e2e_opts())
    router = fb.FabricRouter(pool, timeout_s=30.0)
    server = fb.make_fabric_server(router, port=router_port)
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    pool.start()
    argv = [sys.executable, WORKER, "--port", str(member_port),
            "--join", f"127.0.0.1:{router_port}"]
    proc = subprocess.Popen(argv, env={**os.environ,
                                       "JAX_PLATFORMS": "cpu"})
    try:
        _wait(lambda: pool.ready_count() == 1, what="joined member ready")
        assert f"127.0.0.1:{member_port}" in pool.members
        # the router front door serves through the joined member
        from mx_rcnn_tpu.serve import tcp_http_request
        status, doc = tcp_http_request(
            "127.0.0.1", router_port, "GET", "/readyz", timeout=10.0)
        assert status == 200 and doc["ready_members"] == 1
        status, doc = tcp_http_request(
            "127.0.0.1", router_port, "POST", "/predict",
            json.loads(_predict_body()), timeout=30.0)
        assert status == 200 and "detections" in doc
    finally:
        server.shutdown()
        _cleanup(pool, [proc])
