"""Independent numpy oracle implementations of the reference contracts.

These are written directly from the classic Faster R-CNN algorithm
descriptions (SURVEY.md §2 behavioral contracts) in plain numpy with
boolean indexing and python loops — deliberately *not* sharing any code
with mx_rcnn_tpu.ops — so that each jittable op is tested against an
independently-derived implementation.
"""

import numpy as np


def generate_anchors_oracle(base_size=16, ratios=(0.5, 1, 2), scales=(8, 16, 32)):
    anchors = []
    w0 = h0 = float(base_size)
    x_ctr = (base_size - 1) / 2.0
    y_ctr = (base_size - 1) / 2.0
    size = w0 * h0
    for r in ratios:
        ws = round(np.sqrt(size / r))
        hs = round(ws * r)
        for s in scales:
            w, h = ws * s, hs * s
            anchors.append([x_ctr - (w - 1) / 2.0, y_ctr - (h - 1) / 2.0,
                            x_ctr + (w - 1) / 2.0, y_ctr + (h - 1) / 2.0])
    return np.array(anchors, dtype=np.float32)


def iou_oracle(boxes, query):
    n, k = len(boxes), len(query)
    out = np.zeros((n, k), dtype=np.float64)
    for i in range(n):
        for j in range(k):
            ix1 = max(boxes[i, 0], query[j, 0])
            iy1 = max(boxes[i, 1], query[j, 1])
            ix2 = min(boxes[i, 2], query[j, 2])
            iy2 = min(boxes[i, 3], query[j, 3])
            iw = max(0.0, ix2 - ix1 + 1)
            ih = max(0.0, iy2 - iy1 + 1)
            inter = iw * ih
            a1 = (boxes[i, 2] - boxes[i, 0] + 1) * (boxes[i, 3] - boxes[i, 1] + 1)
            a2 = (query[j, 2] - query[j, 0] + 1) * (query[j, 3] - query[j, 1] + 1)
            out[i, j] = inter / (a1 + a2 - inter)
    return out


def bbox_transform_oracle(ex, gt):
    ew = ex[:, 2] - ex[:, 0] + 1.0
    eh = ex[:, 3] - ex[:, 1] + 1.0
    ecx = ex[:, 0] + 0.5 * (ew - 1)
    ecy = ex[:, 1] + 0.5 * (eh - 1)
    gw = gt[:, 2] - gt[:, 0] + 1.0
    gh = gt[:, 3] - gt[:, 1] + 1.0
    gcx = gt[:, 0] + 0.5 * (gw - 1)
    gcy = gt[:, 1] + 0.5 * (gh - 1)
    return np.stack([(gcx - ecx) / ew, (gcy - ecy) / eh,
                     np.log(gw / ew), np.log(gh / eh)], axis=1)


def bbox_pred_oracle(boxes, deltas):
    w = boxes[:, 2] - boxes[:, 0] + 1.0
    h = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * (w - 1)
    cy = boxes[:, 1] + 0.5 * (h - 1)
    preds = np.zeros_like(deltas)
    for k in range(deltas.shape[1] // 4):
        dx, dy, dw, dh = deltas[:, 4 * k], deltas[:, 4 * k + 1], deltas[:, 4 * k + 2], deltas[:, 4 * k + 3]
        pcx = dx * w + cx
        pcy = dy * h + cy
        pw = np.exp(dw) * w
        ph = np.exp(dh) * h
        preds[:, 4 * k] = pcx - 0.5 * (pw - 1)
        preds[:, 4 * k + 1] = pcy - 0.5 * (ph - 1)
        preds[:, 4 * k + 2] = pcx + 0.5 * (pw - 1)
        preds[:, 4 * k + 3] = pcy + 0.5 * (ph - 1)
    return preds


def nms_oracle(boxes, scores, thresh):
    """Greedy NMS; returns kept indices in score-descending order.

    Uses a precomputed IoU matrix (vectorized, still independent of the
    op under test) so the oracle doesn't dominate suite runtime.
    """
    n = len(boxes)
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    areas = (x2 - x1 + 1) * (y2 - y1 + 1)
    iw = np.maximum(0.0, np.minimum(x2[:, None], x2[None, :]) - np.maximum(x1[:, None], x1[None, :]) + 1)
    ih = np.maximum(0.0, np.minimum(y2[:, None], y2[None, :]) - np.maximum(y1[:, None], y1[None, :]) + 1)
    inter = iw * ih
    iou = inter / (areas[:, None] + areas[None, :] - inter)
    order = np.argsort(-scores, kind="stable")
    keep = []
    suppressed = np.zeros(n, dtype=bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(int(i))
        suppressed |= iou[i] > thresh
        suppressed[i] = True
    return keep


def assign_anchor_oracle(anchors, gt, im_h, im_w, pos=0.7, neg=0.3):
    """Labels only (no subsampling — subsampling is RNG-dependent):
    1 fg / 0 bg / -1 ignore, per the reference rules."""
    n = len(anchors)
    inside = ((anchors[:, 0] >= 0) & (anchors[:, 1] >= 0)
              & (anchors[:, 2] < im_w) & (anchors[:, 3] < im_h))
    labels = np.full(n, -1.0)
    if len(gt) == 0:
        labels[inside] = 0
        return labels
    ov = iou_oracle(anchors[inside], gt)
    max_ov = ov.max(axis=1)
    labels_in = np.full(inside.sum(), -1.0)
    labels_in[max_ov < neg] = 0
    gt_max = ov.max(axis=0)
    for g in range(len(gt)):
        if gt_max[g] > 0:
            labels_in[ov[:, g] == gt_max[g]] = 1
    labels_in[max_ov >= pos] = 1
    labels[inside] = labels_in
    return labels


def propose_oracle(scores, deltas, anchors, im_h, im_w, im_scale,
                   pre_nms, post_nms, nms_thresh, min_size):
    """Reference proposal pipeline, returns (rois, scores) kept in order."""
    boxes = bbox_pred_oracle(anchors, deltas)
    boxes[:, 0::4] = np.clip(boxes[:, 0::4], 0, im_w - 1)
    boxes[:, 1::4] = np.clip(boxes[:, 1::4], 0, im_h - 1)
    boxes[:, 2::4] = np.clip(boxes[:, 2::4], 0, im_w - 1)
    boxes[:, 3::4] = np.clip(boxes[:, 3::4], 0, im_h - 1)
    ws = boxes[:, 2] - boxes[:, 0] + 1
    hs = boxes[:, 3] - boxes[:, 1] + 1
    keep = np.where((ws >= min_size * im_scale) & (hs >= min_size * im_scale))[0]
    boxes, scores = boxes[keep], scores[keep]
    order = np.argsort(-scores, kind="stable")[:pre_nms]
    boxes, scores = boxes[order], scores[order]
    keep = nms_oracle(boxes, scores, nms_thresh)[:post_nms]
    return boxes[keep], scores[keep]


def roi_align_oracle(feat, rois, spatial_scale, pooled, sampling):
    """Loop-based ROIAlign (avg), half-pixel-free legacy-corner semantics
    matching ops/roi_align.py's documented coordinate contract."""
    h, w, c = feat.shape
    out = np.zeros((len(rois), pooled, pooled, c), dtype=np.float64)
    for r, roi in enumerate(rois):
        x1, y1, x2, y2 = [v * spatial_scale for v in roi]
        rw = max(x2 - x1, 1.0)
        rh = max(y2 - y1, 1.0)
        bw, bh = rw / pooled, rh / pooled
        for py in range(pooled):
            for px in range(pooled):
                acc = np.zeros(c)
                for iy in range(sampling):
                    for ix in range(sampling):
                        y = y1 + (py + (iy + 0.5) / sampling) * bh
                        x = x1 + (px + (ix + 0.5) / sampling) * bw
                        if y <= -1.0 or y >= h or x <= -1.0 or x >= w:
                            continue
                        yy = min(max(y, 0.0), h - 1.0)
                        xx = min(max(x, 0.0), w - 1.0)
                        y0, x0 = int(np.floor(yy)), int(np.floor(xx))
                        y1i, x1i = min(y0 + 1, h - 1), min(x0 + 1, w - 1)
                        ly, lx = yy - y0, xx - x0
                        acc += ((1 - ly) * (1 - lx) * feat[y0, x0]
                                + (1 - ly) * lx * feat[y0, x1i]
                                + ly * (1 - lx) * feat[y1i, x0]
                                + ly * lx * feat[y1i, x1i])
                out[r, py, px] = acc / (sampling * sampling)
    return out
