"""COCO eval + RLE oracle tests (the reference's vendored-pycocotools tier,
re-derived — these tests pin the behavioral contract)."""

import json

import numpy as np
import pytest

from mx_rcnn_tpu.eval import mask_rle as M
from mx_rcnn_tpu.eval.coco_eval import COCOEval, bbox_iou_xywh


# --- RLE ---------------------------------------------------------------------

def test_rle_roundtrip_random(rng):
    for _ in range(10):
        mask = (rng.rand(23, 17) > 0.5).astype(np.uint8)
        r = M.encode(mask)
        np.testing.assert_array_equal(M.decode(r), mask)
        assert M.area(r) == int(mask.sum())


def test_rle_string_roundtrip(rng):
    mask = (rng.rand(40, 30) > 0.7).astype(np.uint8)
    counts = M.encode(mask)["counts"]
    s = M.counts_to_string(counts)
    back = M.string_to_counts(s)
    assert back == counts


def test_rle_empty_and_full():
    z = np.zeros((5, 4), np.uint8)
    o = np.ones((5, 4), np.uint8)
    assert M.area(M.encode(z)) == 0
    assert M.area(M.encode(o)) == 20
    np.testing.assert_array_equal(M.decode(M.encode(z)), z)
    np.testing.assert_array_equal(M.decode(M.encode(o)), o)


def test_rle_iou_matches_dense(rng):
    masks = [(rng.rand(20, 20) > 0.6).astype(np.uint8) for _ in range(3)]
    rles = [M.encode(m) for m in masks]
    iou = M.rle_iou(rles[:2], rles[1:], np.zeros(2, bool))
    for i in range(2):
        for j in range(2):
            a, b = masks[i], masks[1 + j]
            inter = np.logical_and(a, b).sum()
            union = np.logical_or(a, b).sum()
            expect = inter / union if union else 0.0
            np.testing.assert_allclose(iou[i, j], expect, rtol=1e-12)


def test_poly_to_rle_rect():
    # axis-aligned rectangle polygon -> area ≈ w*h
    r = M.poly_to_rle([[2, 3, 12, 3, 12, 9, 2, 9]], 20, 20)
    m = M.decode(r)
    assert m[4, 5] == 1 and m[3, 2] == 1
    assert m[0, 0] == 0
    assert 60 <= M.area(r) <= 88  # 10x6 .. 11x7 depending on edge rule


def test_merge_union():
    a = np.zeros((6, 6), np.uint8); a[:3] = 1
    b = np.zeros((6, 6), np.uint8); b[:, :2] = 1
    merged = M.decode(M.merge([M.encode(a), M.encode(b)]))
    np.testing.assert_array_equal(merged, np.logical_or(a, b).astype(np.uint8))


# --- bbox IoU (xywh, no +1) --------------------------------------------------

def test_bbox_iou_xywh_basic():
    dt = np.array([[0, 0, 10, 10]], np.float64)
    gt = np.array([[0, 0, 10, 10], [5, 5, 10, 10]], np.float64)
    iou = bbox_iou_xywh(dt, gt, np.zeros(2, bool))
    assert np.isclose(iou[0, 0], 1.0)
    assert np.isclose(iou[0, 1], 25.0 / 175.0)
    # crowd: union = det area
    iou_c = bbox_iou_xywh(dt, gt, np.ones(2, bool))
    assert np.isclose(iou_c[0, 1], 25.0 / 100.0)


# --- COCOEval protocol -------------------------------------------------------

@pytest.fixture
def tiny_ann(tmp_path):
    """2 images, 2 categories, 3 gt (one small, one medium, one large-ish)."""
    ann = {
        "images": [{"id": 1, "file_name": "a.jpg", "height": 200, "width": 200},
                   {"id": 2, "file_name": "b.jpg", "height": 200, "width": 200}],
        "categories": [{"id": 1, "name": "cat"}, {"id": 2, "name": "dog"}],
        "annotations": [
            {"id": 1, "image_id": 1, "category_id": 1,
             "bbox": [10, 10, 20, 20], "area": 400, "iscrowd": 0},
            {"id": 2, "image_id": 1, "category_id": 2,
             "bbox": [50, 50, 60, 60], "area": 3600, "iscrowd": 0},
            {"id": 3, "image_id": 2, "category_id": 1,
             "bbox": [0, 0, 100, 100], "area": 10000, "iscrowd": 0},
        ],
    }
    p = tmp_path / "ann.json"
    p.write_text(json.dumps(ann))
    return str(p)


def _det(img, cat, bbox, score):
    return {"image_id": img, "category_id": cat, "bbox": bbox, "score": score}


def test_cocoeval_perfect(tiny_ann):
    results = [
        _det(1, 1, [10, 10, 20, 20], 0.9),
        _det(1, 2, [50, 50, 60, 60], 0.8),
        _det(2, 1, [0, 0, 100, 100], 0.95),
    ]
    stats = COCOEval(tiny_ann, results).evaluate()
    assert np.isclose(stats["AP"], 1.0)
    assert np.isclose(stats["AP50"], 1.0)
    assert np.isclose(stats["AR100"], 1.0)


def test_cocoeval_miss_and_fp(tiny_ann):
    # only one of two cat-1 gt found, plus one pure FP for cat 2
    results = [
        _det(1, 1, [10, 10, 20, 20], 0.9),
        _det(1, 2, [150, 150, 20, 20], 0.99),   # FP ranked above the TP
        _det(1, 2, [50, 50, 60, 60], 0.8),
    ]
    stats = COCOEval(tiny_ann, results).evaluate()
    assert 0.0 < stats["AP"] < 1.0
    # cat1: recall 0.5 with precision 1 -> AP ~0.5; cat2: TP at rank 2 ->
    # precision 0.5 at recall 1 -> AP ~0.5 (101-pt interp)
    assert 0.4 < stats["AP50"] < 0.6


def test_cocoeval_loose_box_only_counts_at_low_iou(tiny_ann):
    # IoU vs gt [10,10,20,20] of det [12,12,20,20]: inter 18*18=324,
    # union 400+400-324=476 -> 0.68: TP at thresholds .5-.65, FP above
    results = [
        _det(1, 1, [12, 12, 20, 20], 0.9),
        _det(1, 2, [50, 50, 60, 60], 0.8),
        _det(2, 1, [0, 0, 100, 100], 0.95),
    ]
    stats = COCOEval(tiny_ann, results).evaluate()
    assert np.isclose(stats["AP50"], 1.0)
    assert stats["AP75"] < 1.0
    assert 0.5 < stats["AP"] < 1.0


def test_cocoeval_crowd_not_counted(tmp_path):
    ann = {
        "images": [{"id": 1, "file_name": "a.jpg", "height": 100, "width": 100}],
        "categories": [{"id": 1, "name": "cat"}],
        "annotations": [
            {"id": 1, "image_id": 1, "category_id": 1,
             "bbox": [0, 0, 50, 50], "area": 2500, "iscrowd": 1},
            {"id": 2, "image_id": 1, "category_id": 1,
             "bbox": [60, 60, 20, 20], "area": 400, "iscrowd": 0},
        ],
    }
    p = tmp_path / "ann.json"
    p.write_text(json.dumps(ann))
    # det inside the crowd region: ignored (matched to crowd), not FP;
    # det on the real gt: TP -> AP 1
    results = [_det(1, 1, [10, 10, 30, 30], 0.9),
               _det(1, 1, [60, 60, 20, 20], 0.8)]
    stats = COCOEval(str(p), results).evaluate()
    assert np.isclose(stats["AP"], 1.0)


def test_cocoeval_area_breakdown(tiny_ann):
    results = [
        _det(1, 1, [10, 10, 20, 20], 0.9),     # small (400 < 32^2)
        _det(1, 2, [50, 50, 60, 60], 0.8),     # medium
        _det(2, 1, [0, 0, 100, 100], 0.95),    # large
    ]
    stats = COCOEval(tiny_ann, results).evaluate()
    assert np.isclose(stats["APs"], 1.0)
    assert np.isclose(stats["APm"], 1.0)
    assert np.isclose(stats["APl"], 1.0)


def test_cocoeval_segm_mode(tmp_path):
    rle1 = M.encode(np.pad(np.ones((20, 20), np.uint8), ((10, 70), (10, 70))))
    ann = {
        "images": [{"id": 1, "file_name": "a.jpg", "height": 100, "width": 100}],
        "categories": [{"id": 1, "name": "cat"}],
        "annotations": [
            {"id": 1, "image_id": 1, "category_id": 1,
             "bbox": [10, 10, 20, 20], "area": 400, "iscrowd": 0,
             "segmentation": {"size": [100, 100],
                              "counts": M.counts_to_string(rle1["counts"])}},
        ],
    }
    p = tmp_path / "ann.json"
    p.write_text(json.dumps(ann))
    results = [{"image_id": 1, "category_id": 1, "score": 0.9, "area": 400,
                "segmentation": rle1}]
    stats = COCOEval(str(p), results, iou_type="segm").evaluate()
    assert np.isclose(stats["AP"], 1.0)
