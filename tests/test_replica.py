"""Multi-replica serving plane tests (ISSUE 8).

Three layers, mirroring the subsystem split:

* **Supervisor state machine** — deterministic unit tests with injected
  clock (``poll(now=...)``, the ``SLOController.tick`` pattern), fake
  procs, fake probes, fake reloads: ready transitions, crash → backoff
  schedule, systemic respawn limit, hang detection, router retry budget,
  rolling reload + rollback, generation monotonicity under crash.
* **Replica-side machinery** — checkpoint scanning/watching, fault-env
  parsing, the zero-downtime swap with canary rollback on a live engine
  (fake predictor, so no XLA in the loop).
* **End-to-end chaos** — a REAL supervisor + router over REAL
  subprocesses (``tests/replica_worker.py``): kill -9 one of two
  replicas mid-burst and observe failover + respawn; roll a hot reload
  through the plane under traffic with zero dropped 2xx-eligible
  requests.  ``script/replica_smoke.sh`` repeats this with the real
  model.
"""

import dataclasses
import itertools
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from mx_rcnn_tpu.serve import replica as rp
from mx_rcnn_tpu.serve import supervisor as sv
from mx_rcnn_tpu.serve import (RejectedError, ReplicaRouter, ServeEngine,
                               ServeOptions, encode_image_payload, warmup)
from tests.faults import replica_fault_env
from tests.replica_worker import FakeServePredictor
from tests.test_serve import make_engine, raw_image, tiny_cfg

WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "replica_worker.py")


# -- fakes ------------------------------------------------------------------


class FakeProc:
    """subprocess.Popen stand-in the supervisor can poll/kill/wait."""

    _pids = itertools.count(1000)

    def __init__(self, stubborn=False):
        self.pid = next(FakeProc._pids)
        self.returncode = None
        self.killed = False
        self.terminated = False
        self.stubborn = stubborn  # ignores SIGTERM (needs the kill path)

    def poll(self):
        return self.returncode

    def die(self, rc=1):
        self.returncode = rc

    def kill(self):
        self.killed = True
        self.returncode = -9

    def terminate(self):
        self.terminated = True
        if not self.stubborn:
            self.returncode = -15

    def wait(self, timeout=None):
        if self.returncode is None:
            raise subprocess.TimeoutExpired("fake", timeout)
        return self.returncode


def _specs(n, sock_dir="/tmp/mxr_fake_socks"):
    return [sv.ReplicaSpec(argv=["serve.py"],
                           sock=os.path.join(sock_dir, f"r{i}.sock"),
                           index=i) for i in range(n)]


class Harness:
    """A supervisor over fake procs with scriptable probes/reloads."""

    def __init__(self, n=2, stubborn=False, specs=None, **opt_kw):
        self.procs = {}           # index -> [FakeProc, ...] (respawns)
        self.ready = {}           # index -> /readyz answers 200
        self.healthy = {}         # index -> /healthz status | Exception
        self.reloads = []         # (index, target) in call order
        self.reload_status = 200  # int, or callable(handle, target) -> int
        self._stubborn = stubborn

        def spawn(spec):
            p = FakeProc(stubborn=self._stubborn)
            self.procs.setdefault(spec.index, []).append(p)
            return p

        def probe(handle, path):
            if path == "/readyz":
                return (200 if self.ready.get(handle.index) else 503), {}
            st = self.healthy.get(handle.index, 200)
            if isinstance(st, Exception):
                raise st
            return st, {}

        def reload_fn(handle, target):
            self.reloads.append((handle.index, dict(target)))
            st = (self.reload_status(handle, target)
                  if callable(self.reload_status) else self.reload_status)
            if st == 200:
                return st, {"generation": target.get("generation"),
                            "recompiles_during_swap": 0}
            return st, {"error": "canary failed: injected"}

        self.sup = sv.ReplicaSupervisor(
            specs if specs is not None else _specs(n),
            sv.SupervisorOptions(**opt_kw),
            spawn_fn=spawn, probe_fn=probe, reload_fn=reload_fn)

    def proc(self, i):
        return self.procs[i][-1]

    def up(self, n=None, now=1.0):
        """spawn_all + mark every replica ready + one poll."""
        self.sup.spawn_all(now=0.0)
        for i in range(n if n is not None else len(self.sup.handles)):
            self.ready[i] = True
        self.sup.poll(now=now)


TARGET = {"prefix": "/ck", "kind": "epoch", "epoch": 3, "consumed": 0}


# -- supervisor state machine ----------------------------------------------


def test_token_bucket_budget_and_refill():
    tb = sv.TokenBucket(2, 1.0)
    assert tb.take(now=0.0) and tb.take(now=0.0)
    assert not tb.take(now=0.0)          # burst capacity spent
    assert tb.take(now=1.0)              # 1 token refilled
    assert not tb.take(now=1.0)
    assert tb.take(now=100.0) and tb.take(now=100.0)
    assert not tb.take(now=100.0)        # refill is capped at capacity


def test_build_child_argv_strips_parent_flags():
    argv = ["serve.py", "--model", "m.npz", "--port", "8000",
            "--host=0.0.0.0", "--replicas", "2",
            "--watch-checkpoints", "/ckpts", "--watch-interval-s", "2",
            "--replica-devices", "0;1", "--serve-batch", "4"]
    out = sv.build_child_argv(argv, "/tmp/r0.sock", 0)
    assert out[0] == sys.executable and out[1] == "serve.py"
    joined = " ".join(out)
    for flag in ("--port", "--host", "--watch-checkpoints",
                 "--watch-interval-s", "--replica-devices"):
        assert flag not in joined
    assert "--model m.npz" in joined          # model flags pass through
    assert "--replicas 2" in joined           # kept: obs world size
    assert "--serve-batch 4" in joined
    assert out[-4:] == ["--unix-socket", "/tmp/r0.sock",
                        "--replica-index", "0"]


def test_replica_specs_device_groups(tmp_path):
    sp = sv.replica_specs(["serve.py", "--model", "m"], 3, str(tmp_path),
                          devices="0,1;2,3")
    assert [s.index for s in sp] == [0, 1, 2]
    assert sp[0].env["MXR_REPLICA_DEVICES"] == "0,1"
    assert sp[1].env["MXR_REPLICA_DEVICES"] == "2,3"
    assert "MXR_REPLICA_DEVICES" not in sp[2].env  # no group for it
    assert sp[1].env["MXR_REPLICA_INDEX"] == "1"
    assert sp[0].sock.endswith("replica_0.sock")


def test_ready_transition_and_slow_starter_not_killed():
    hz = Harness(n=2)
    sup = hz.sup
    sup.spawn_all(now=0.0)
    sup.poll(now=1.0)  # alive, /readyz 503: warming, not dead
    assert all(h.state == sv.STARTING for h in sup.handles)
    assert sup.ready_count() == 0
    hz.ready[0] = True
    sup.poll(now=2.0)
    assert sup.handles[0].state == sv.READY and sup.handles[0].routable
    assert sup.handles[1].state == sv.STARTING  # still warming — alive
    assert sup.ready_count() == 1


def test_start_timeout_kills_and_backoffs():
    hz = Harness(n=1, start_timeout_s=10.0)
    hz.sup.spawn_all(now=0.0)
    hz.sup.poll(now=11.0)
    assert hz.proc(0).killed
    assert hz.sup.handles[0].state == sv.BACKOFF


def test_crash_respawn_exponential_backoff_schedule():
    hz = Harness(n=1, backoff_base_s=0.5, backoff_max_s=4.0,
                 max_respawns=100)
    sup, h = hz.sup, hz.sup.handles[0]
    now = 0.0
    sup.spawn_all(now=now)
    delays = []
    for _ in range(5):
        hz.proc(0).die(9)
        sup.poll(now=now)
        assert h.state == sv.BACKOFF
        delays.append(h.next_spawn_t - now)
        sup.poll(now=h.next_spawn_t - 0.01)   # not yet eligible
        assert h.state == sv.BACKOFF
        now = h.next_spawn_t
        sup.poll(now=now)                     # eligible: respawn
        assert h.state == sv.STARTING
    assert delays == [0.5, 1.0, 2.0, 4.0, 4.0]  # doubles, then capped
    assert sup.counters["respawn"] == 5


def test_systemic_limit_fails_replica_and_breaks_plane():
    hz = Harness(n=1, backoff_base_s=0.0, max_respawns=2)
    sup, h = hz.sup, hz.sup.handles[0]
    sup.spawn_all(now=0.0)
    now = 0.0
    while h.state != sv.FAILED:
        hz.proc(0).die(9)
        now += 1.0
        sup.poll(now=now)  # declare dead (and respawn if under the limit)
        now += 1.0
        sup.poll(now=now)
    assert len(hz.procs[0]) == 3               # initial + 2 respawns
    assert sup.counters["systemic"] == 1
    assert sup.broken.is_set()                 # every replica FAILED
    sup.poll(now=now + 100.0)                  # FAILED is terminal
    assert h.state == sv.FAILED and len(hz.procs[0]) == 3


def test_hang_detection_probe_timeouts_then_kill():
    hz = Harness(n=1, hang_probes=3)
    sup, h = hz.sup, hz.sup.handles[0]
    hz.up(now=1.0)
    assert h.state == sv.READY
    hz.healthy[0] = TimeoutError("probe timed out")
    sup.poll(now=2.0)
    sup.poll(now=3.0)
    assert h.state == sv.READY and h.probe_fails == 2  # not yet hung
    sup.poll(now=4.0)                                   # third miss
    assert hz.proc(0).killed and h.state == sv.BACKOFF
    assert sup.counters["hang_kill"] == 1


def test_stable_ready_resets_backoff_and_suspect_clears():
    hz = Harness(n=1, stable_s=10.0)
    sup, h = hz.sup, hz.sup.handles[0]
    sup.spawn_all(now=0.0)
    hz.proc(0).die(1)
    sup.poll(now=0.0)                 # failures = 1
    sup.poll(now=h.next_spawn_t)      # respawn
    hz.ready[0] = True
    sup.poll(now=1.0)                 # ready at t=1
    assert h.state == sv.READY and h.failures == 1
    sup.note_suspect(h)
    assert not h.routable
    sup.poll(now=2.0)                 # healthy probe clears the suspicion
    assert h.routable and h.failures == 1   # too soon to forgive backoff
    sup.poll(now=20.0)                # stable past stable_s
    assert h.failures == 0


def test_sweep_terminates_children_and_unlinks_sockets(tmp_path):
    specs = [sv.ReplicaSpec(argv=["x"],
                            sock=str(tmp_path / f"r{i}.sock"),
                            index=i) for i in range(2)]
    hz = Harness(specs=specs, stubborn=True)
    hz.up()
    for s in specs:
        open(s.sock, "w").close()
    hz.sup.sweep(graceful_timeout=0.0)
    for h in hz.sup.handles:
        assert h.state == sv.STOPPED and not h.routable
    for i in range(2):
        assert hz.proc(i).terminated          # graceful first...
        assert hz.proc(i).killed              # ...then the hard kill
        assert not os.path.exists(specs[i].sock)
    hz.sup.sweep(graceful_timeout=0.0)        # idempotent


# -- router: retry-once, budget, degradation -------------------------------


def test_router_no_ready_replicas_sheds_early():
    hz = Harness(n=2)  # spawned never → nothing routable
    router = ReplicaRouter(hz.sup, forward_fn=None)
    status, raw, ctype = router.route_predict(b"{}")
    assert status == 503 and b"no ready replicas" in raw
    assert hz.sup.counters["no_ready"] == 1


def test_router_retries_transport_error_on_alternate():
    hz = Harness(n=2)
    hz.up()
    calls = []

    def fwd(h, method, path, body, timeout):
        calls.append(h.index)
        if len(calls) == 1:
            raise ConnectionRefusedError("replica died")
        return 200, b'{"ok":1}', "application/json"

    router = ReplicaRouter(hz.sup, forward_fn=fwd)
    status, raw, _ = router.route_predict(b"{}")
    assert status == 200 and raw == b'{"ok":1}'
    assert len(calls) == 2 and calls[0] != calls[1]  # alternate replica
    c = hz.sup.counters
    assert c["transport_error"] == 1 and c["retry"] == 1
    assert c["retry_ok"] == 1
    # the failed replica was unrouted pending the next probe
    assert not hz.sup.handles[calls[0]].routable


def test_router_retries_shed_503_on_alternate():
    hz = Harness(n=2)
    hz.up()
    calls = []

    def fwd(h, method, path, body, timeout):
        calls.append(h.index)
        if len(calls) == 1:
            return 503, b'{"error":"draining"}', "application/json"
        return 200, b'{"ok":1}', "application/json"

    router = ReplicaRouter(hz.sup, forward_fn=fwd)
    status, _, _ = router.route_predict(b"{}")
    assert status == 200
    assert calls[0] != calls[1]
    assert hz.sup.counters["transport_error"] == 0  # shed, not a crash


def test_router_retry_budget_exhaustion_sheds():
    hz = Harness(n=2)
    hz.up()
    hz.sup.retry_bucket = sv.TokenBucket(0, 0.0)  # budget already spent

    def fwd(h, method, path, body, timeout):
        raise ConnectionRefusedError("dead")

    router = ReplicaRouter(hz.sup, forward_fn=fwd)
    status, raw, _ = router.route_predict(b"{}")
    assert status == 503 and b"retry budget" in raw
    assert hz.sup.counters["retry_budget_exhausted"] == 1
    assert hz.sup.counters["retry"] == 0


def test_router_both_replicas_fail_502():
    hz = Harness(n=2)
    hz.up()

    def fwd(h, method, path, body, timeout):
        raise ConnectionRefusedError("dead")

    router = ReplicaRouter(hz.sup, forward_fn=fwd)
    status, raw, _ = router.route_predict(b"{}")
    assert status == 502 and b"both replicas failed" in raw
    assert hz.sup.counters["transport_error"] == 2


def test_router_lone_replica_own_503_stands():
    hz = Harness(n=1)
    hz.up()
    router = ReplicaRouter(
        hz.sup,
        forward_fn=lambda *a: (503, b'{"error":"queue full"}',
                               "application/json"))
    status, raw, _ = router.route_predict(b"{}")
    assert status == 503 and raw == b'{"error":"queue full"}'


# -- rolling hot reload -----------------------------------------------------


def test_rolling_reload_advances_generation_one_at_a_time():
    hz = Harness(n=2)
    hz.up()
    assert hz.sup.reload_to(dict(TARGET))
    assert hz.sup.generation == 1
    assert [h.generation for h in hz.sup.handles] == [1, 1]
    assert [i for i, _ in hz.reloads] == [0, 1]        # one at a time
    assert all(t["generation"] == 1 for _, t in hz.reloads)
    assert hz.sup.counters["reload"] == 2
    assert hz.sup.ready_count() == 2                   # all re-routed
    assert hz.sup.reload_to(dict(TARGET, epoch=4))
    assert hz.sup.generation == 2                      # monotonic


def test_rolling_reload_rejection_rolls_back_swapped():
    hz = Harness(n=2)
    hz.up()
    assert hz.sup.reload_to(dict(TARGET))              # generation 1 live
    hz.reloads.clear()
    hz.reload_status = (
        lambda h, t: 409 if (h.index == 1 and t["epoch"] == 4) else 200)
    assert not hz.sup.reload_to(dict(TARGET, epoch=4))
    assert hz.sup.generation == 1                      # NOT advanced
    assert hz.sup.counters["reload_rollback"] == 1
    # replica 0 (already swapped) was rolled back to the prior target
    back_index, back_target = hz.reloads[-1]
    assert back_index == 0
    assert back_target["epoch"] == 3 and back_target["generation"] == 1
    assert [h.generation for h in hz.sup.handles] == [1, 1]
    assert hz.sup.ready_count() == 2                   # plane still serves


def test_crash_mid_roll_skips_victim_then_catches_up():
    hz = Harness(n=2, backoff_base_s=0.5)
    hz.up()

    def die_during_first_swap(h, target):
        if h.index == 0 and not hz.proc(1).poll():
            hz.proc(1).die(9)
            hz.sup.poll(now=10.0)  # monitor notices mid-roll
        return 200

    hz.reload_status = die_during_first_swap
    assert hz.sup.reload_to(dict(TARGET))
    assert hz.sup.generation == 1
    assert [i for i, _ in hz.reloads] == [0]  # dead replica skipped
    h1 = hz.sup.handles[1]
    assert h1.generation == 0                 # fresh boot = boot weights
    hz.sup.poll(now=h1.next_spawn_t)          # respawn
    hz.sup.poll(now=h1.next_spawn_t + 1.0)    # ready → catch-up reload
    assert h1.state == sv.READY
    assert hz.reloads[-1] == (1, dict(TARGET, generation=1))
    assert h1.generation == 1                 # plane is one generation


def test_respawned_replica_catches_up_to_plane_generation():
    hz = Harness(n=2, backoff_base_s=0.5)
    hz.up()
    assert hz.sup.reload_to(dict(TARGET))
    hz.reloads.clear()
    hz.proc(1).die(9)
    hz.sup.poll(now=5.0)
    h1 = hz.sup.handles[1]
    assert h1.state == sv.BACKOFF and h1.generation == 0
    hz.sup.poll(now=h1.next_spawn_t)          # respawn
    hz.sup.poll(now=h1.next_spawn_t + 1.0)    # ready → catch-up
    assert h1.state == sv.READY and h1.generation == 1
    assert hz.reloads and hz.reloads[-1][0] == 1
    assert hz.reloads[-1][1]["generation"] == 1


# -- replica-side: checkpoint discovery + watcher ---------------------------


def _committed_ckpt(path):
    """Fabricate a COMMITTED checkpoint dir: int-named with real
    payload, the post-atomic-rename shape scan_checkpoints selects."""
    path.mkdir()
    (path / "params.npz").write_bytes(b"x")
    return path


def test_scan_checkpoints_prefers_furthest_position(tmp_path):
    assert rp.scan_checkpoints(str(tmp_path / "missing")) is None
    assert rp.scan_checkpoints(str(tmp_path)) is None   # empty prefix
    _committed_ckpt(tmp_path / "1")
    _committed_ckpt(tmp_path / "2")
    # in-progress orbax tmp dirs never int-parse → invisible
    (tmp_path / "3.orbax-checkpoint-tmp-99").mkdir()
    t = rp.scan_checkpoints(str(tmp_path))
    assert (t["kind"], t["epoch"], t["consumed"]) == ("epoch", 2, 0)
    steps = tmp_path / "steps"
    steps.mkdir()
    _committed_ckpt(steps / str(2 * 10 ** 7 + 5))  # epoch 2, consumed 5
    t = rp.scan_checkpoints(str(tmp_path))
    assert (t["kind"], t["epoch"], t["consumed"]) == ("step", 2, 5)
    _committed_ckpt(tmp_path / "3")         # a finished epoch 3 beats it
    t = rp.scan_checkpoints(str(tmp_path))
    assert (t["kind"], t["epoch"], t["consumed"]) == ("epoch", 3, 0)


def test_checkpoint_watcher_dedup_badlist_and_no_backward():
    current = {"t": {"prefix": "p", "kind": "epoch",
                     "epoch": 1, "consumed": 0}}
    calls = []
    accept = {"v": True}

    def scan(prefix):
        return dict(current["t"])

    def reload_fn(target):
        calls.append(dict(target))
        return accept["v"]

    w = rp.CheckpointWatcher("p", reload_fn, scan_fn=scan)
    w.prime()                        # boot checkpoint = already served
    assert w.poll_once() is None and not calls
    current["t"] = dict(current["t"], epoch=2)
    _, ok = w.poll_once()
    assert ok and len(calls) == 1
    assert w.poll_once() is None and len(calls) == 1   # dedup
    accept["v"] = False
    current["t"] = dict(current["t"], epoch=3)
    _, ok = w.poll_once()
    assert not ok and len(calls) == 2
    # a rejected target is blacklisted, never retried (no flapping)
    assert w.poll_once() is None and len(calls) == 2
    accept["v"] = True
    current["t"] = dict(current["t"], epoch=4)          # newer save wins
    _, ok = w.poll_once()
    assert ok and len(calls) == 3
    current["t"] = dict(current["t"], epoch=2)          # stale listing
    assert w.poll_once() is None and len(calls) == 3    # never backward


# -- replica-side: chaos env + canary swap ----------------------------------


def test_replica_faults_env_parsing_and_composer():
    env = {}
    env.update(replica_fault_env(0, kill_after=5))
    env.update(replica_fault_env(1, hang_after=3, slow_start_s=2.5))
    env.update(replica_fault_env(2, corrupt_ckpt=True))
    f0 = rp.ReplicaFaults(0, env=env)
    assert f0.kill_after == 5 and f0.hang_after is None
    assert f0.slow_start_s == 0.0 and not f0.corrupt_ckpt
    f1 = rp.ReplicaFaults(1, env=env)
    assert f1.kill_after is None and f1.hang_after == 3
    assert f1.slow_start_s == 2.5
    f2 = rp.ReplicaFaults(2, env=env)
    assert f2.corrupt_ckpt and f2.kill_after is None
    # comma-joined multi-index tokens: each replica reads its own
    f = rp.ReplicaFaults(1, env={rp.ENV_KILL_AFTER: "0:9,1:4"})
    assert f.kill_after == 4
    # malformed tokens are ignored, never fatal
    f = rp.ReplicaFaults(0, env={rp.ENV_KILL_AFTER: "banana"})
    assert f.kill_after is None


def test_poison_params_nans_float_leaves_only():
    params = {"a": {"w": np.ones((2, 2), np.float32)},
              "idx": np.arange(3, dtype=np.int32), "n": 2}
    out = rp.poison_params(params)
    assert np.isnan(out["a"]["w"]).all()
    assert np.array_equal(out["idx"], params["idx"])    # ints untouched
    assert not np.isnan(params["a"]["w"]).any()         # input unharmed


def test_engine_readiness_drain_and_resume():
    engine = make_engine(tiny_cfg(), batch_size=4).start()
    try:
        assert not engine.is_ready()           # warmup hasn't finished
        doc = engine.readiness()
        assert doc["ready"] is False and doc["warmed"] is False
        engine.mark_ready()
        assert engine.is_ready() and engine.readiness()["ready"]
        futs = [engine.submit(raw_image(60, 100, 40)) for _ in range(4)]
        assert engine.drain(timeout=10.0)      # quiesces, doesn't drop
        doc = engine.readiness()
        assert doc["ready"] is False and doc["draining"] is True
        with pytest.raises(RejectedError):
            engine.submit(raw_image(60, 100, 40))   # draining sheds
        for f in futs:
            assert f.result(timeout=10.0) is not None  # drained = SERVED
        engine.resume()
        assert engine.is_ready()
        engine.submit(raw_image(60, 100, 40))
    finally:
        engine.stop()


def _live_engine(batch_size=2):
    cfg = tiny_cfg()
    pred = FakeServePredictor(cfg, {"scale": np.float32(1.0)})
    engine = ServeEngine(pred, cfg, ServeOptions(
        batch_size=batch_size, max_delay_ms=1.0, max_queue=8)).start()
    warmup(engine)
    return engine, pred, cfg


def test_reload_engine_params_swap_is_zero_recompile():
    engine, pred, cfg = _live_engine()
    try:
        base = engine.submit(raw_image(96, 128, 40)).result(timeout=30.0)
        ok, info = rp.reload_engine_params(
            engine, pred, cfg, dict(TARGET),
            load_params_fn=lambda t, c: {"scale": np.float32(2.0)})
        assert ok and engine.generation == 1
        assert info["recompiles_during_swap"] == 0     # PR-7 registry reuse
        assert float(pred.params["scale"]) == 2.0
        assert engine.is_ready()                       # resumed after swap
        # the new weights actually serve: same image, scores doubled
        dets = engine.submit(raw_image(96, 128, 40)).result(timeout=30.0)
        assert base and dets
        assert dets[0]["score"] == pytest.approx(2.0 * base[0]["score"],
                                                 rel=1e-5)
    finally:
        engine.stop()


def test_reload_canary_rejects_nan_weights_and_rolls_back():
    engine, pred, cfg = _live_engine()
    try:
        good = pred.params
        ok, info = rp.reload_engine_params(
            engine, pred, cfg, dict(TARGET),
            load_params_fn=lambda t, c: {"scale": np.float32("nan")})
        assert not ok and info["rolled_back"]
        assert "canary" in info["error"]
        assert engine.generation == 0                  # never advanced
        assert pred.params is good                     # exact old leaves
        assert engine.is_ready()                       # still serving
        engine.submit(raw_image(96, 128, 40)).result(timeout=30.0)
    finally:
        engine.stop()


def test_reload_corrupt_ckpt_fault_forces_rollback():
    engine, pred, cfg = _live_engine()
    try:
        faults = rp.ReplicaFaults(0, env={rp.ENV_CORRUPT_CKPT: "0"})
        assert faults.corrupt_ckpt
        ok, info = rp.reload_engine_params(
            engine, pred, cfg, dict(TARGET),
            load_params_fn=lambda t, c: {"scale": np.float32(2.0)},
            faults=faults)
        assert not ok and info["rolled_back"]          # canary caught it
        assert float(pred.params["scale"]) == 1.0
        assert engine.generation == 0
    finally:
        engine.stop()


def test_make_reloader_validates_target():
    engine, pred, cfg = _live_engine()
    try:
        reloader = rp.make_reloader(
            engine, pred, cfg,
            load_params_fn=lambda t, c: {"scale": np.float32(2.0)})
        status, doc = reloader({"kind": "epoch"})      # missing keys
        assert status == 400 and "consumed" in doc["error"]
        status, doc = reloader(dict(TARGET))
        assert status == 200 and doc["generation"] == 1
        status, doc = reloader(dict(TARGET, generation=5))
        assert status == 200 and doc["generation"] == 5
        assert engine.generation == 5
    finally:
        engine.stop()


def test_perf_gate_replica_linearity_and_availability_floors(tmp_path):
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(repo, "scripts", "perf_gate.py"))
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)

    def write(agg, per, n=2, **extra):
        doc = {"schema": "mxr_replica_report", "version": 1,
               "replicas": n, "aggregate_imgs_per_sec": agg,
               "per_replica_imgs_per_sec": per, **extra}
        (tmp_path / "REPLICA_r01.json").write_text(json.dumps(doc))

    write(18.0, 10.0)                        # linearity 0.9 ≥ 0.85 default
    assert pg.main(["--dir", str(tmp_path)]) == 0
    assert pg.main(["--dir", str(tmp_path), "--check-format"]) == 0
    write(12.0, 10.0)                        # 0.6 < 0.85 → gate fails
    assert pg.main(["--dir", str(tmp_path)]) == 1
    # the CPU smoke pins its own floor (replicas share one host's cores)
    write(12.0, 10.0, linearity_floor=0.5)
    assert pg.main(["--dir", str(tmp_path)]) == 0
    write(18.0, 10.0, availability=0.8, availability_floor=0.9)
    assert pg.main(["--dir", str(tmp_path)]) == 1
    write(18.0, 10.0, availability=0.95, availability_floor=0.9)
    assert pg.main(["--dir", str(tmp_path)]) == 0


# -- end-to-end chaos: real supervisor over real subprocesses ---------------


def _e2e_opts():
    return sv.SupervisorOptions(
        probe_interval_s=0.2, probe_timeout_s=5.0, hang_probes=3,
        start_timeout_s=120.0, backoff_base_s=0.2, backoff_max_s=1.0,
        stable_s=5.0, drain_timeout_s=15.0, reload_timeout_s=60.0)


def _worker_spec(i, sock_dir, env=None, params_file=""):
    sock = os.path.join(sock_dir, f"r{i}.sock")
    argv = [sys.executable, WORKER, "--unix-socket", sock,
            "--replica-index", str(i)]
    if params_file:
        argv += ["--params-file", params_file]
    return sv.ReplicaSpec(argv=argv, sock=sock, index=i,
                          env={"JAX_PLATFORMS": "cpu", **(env or {})})


def _wait(cond, timeout=90.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


def _predict_body():
    doc = encode_image_payload(np.full((60, 100, 3), 50, np.uint8))
    return json.dumps(doc).encode()


def test_e2e_kill9_failover_and_respawn(tmp_path):
    """Kill -9 one of two REAL replicas mid-burst: requests keep
    resolving (retry-once onto the survivor), the supervisor respawns
    the corpse, and the plane recovers to 2 ready."""
    specs = [_worker_spec(0, str(tmp_path),
                          env=replica_fault_env(0, kill_after=3)),
             _worker_spec(1, str(tmp_path))]
    # a LONG probe interval so the corpse stays routable until the next
    # monitor tick: with requests spaced well under it, some are
    # guaranteed to pick the dead replica and exercise the retry path
    # (0.2s probes can unroute the corpse before any request lands on
    # it — a race this test exists to close, not to rely on)
    opts = dataclasses.replace(_e2e_opts(), probe_interval_s=1.0)
    sup = sv.ReplicaSupervisor(specs, opts).start()
    try:
        _wait(lambda: sup.ready_count() == 2, what="both replicas ready")
        router = ReplicaRouter(sup)
        body = _predict_body()
        statuses = []
        for _ in range(30):
            status, _, _ = router.route_predict(body)
            statuses.append(status)
            time.sleep(0.02)
        # replica 0 SIGKILLed itself mid-burst (kill_after=3): every
        # request still resolved to a 2xx or an honest early shed — no
        # hangs, no hard 5xx escaping the retry
        assert set(statuses) <= {200, 503}, statuses
        assert statuses.count(200) >= 20, statuses
        assert sup.counters["transport_error"] >= 1
        assert sup.counters["retry_ok"] >= 1
        _wait(lambda: sup.counters["respawn"] >= 1, what="respawn")
        _wait(lambda: sup.ready_count() == 2, what="recovery to 2 ready")
    finally:
        sup.stop()


def test_e2e_rolling_reload_zero_dropped_requests(tmp_path):
    """Roll a hot reload through two REAL replicas under open traffic:
    every request lands a 2xx (drain sheds retry onto the other
    replica), the plane generation advances, zero recompiles."""
    pfile = str(tmp_path / "params.json")
    with open(pfile, "w") as f:
        json.dump({"scale": 1.0}, f)
    specs = [_worker_spec(i, str(tmp_path), params_file=pfile)
             for i in range(2)]
    sup = sv.ReplicaSupervisor(specs, _e2e_opts()).start()
    try:
        _wait(lambda: sup.ready_count() == 2, what="both replicas ready")
        router = ReplicaRouter(sup)
        body = _predict_body()
        statuses = []
        stop = threading.Event()

        def traffic():
            while not stop.is_set():
                status, _, _ = router.route_predict(body)
                statuses.append(status)
                time.sleep(0.03)

        th = threading.Thread(target=traffic, daemon=True)
        th.start()
        time.sleep(0.3)
        with open(pfile, "w") as f:
            json.dump({"scale": 2.0}, f)
        ok = sup.reload_to({"prefix": pfile, "kind": "file",
                            "epoch": 1, "consumed": 0})
        time.sleep(0.3)
        stop.set()
        th.join(timeout=30.0)
        assert ok and sup.generation == 1
        for h in sup.handles:
            assert h.generation == 1
        # THE zero-downtime claim: not one request dropped across the roll
        assert statuses and set(statuses) == {200}, statuses
        assert sup.counters["reload"] == 2
        assert sup.counters["reload_rollback"] == 0
    finally:
        sup.stop()
