"""Device-side preprocessing (``data/device_prep.py``): pixel-level parity
of the jitted resize/flip/normalize/pad program against the host path
(``data/image.py``), sidecar contract through the loader, zero
steady-state recompiles via the program registry, and workers ×
device-prep composition.

Parity tolerances are the measured story, not wishes: in-bucket cases
match cv2 to float32 rounding (~2e-7 on normalized pixels — the device
resamples with cv2's exact ``(dst+0.5)*ratio-0.5`` rule and normalize
commutes with bilinear because the weights sum to 1); the one documented
divergence is oversized raws, where the host pre-shrinks in uint8 before
staging (measured ~6e-3 normalized, bounded by uint8 rounding)."""

import dataclasses

import jax
import numpy as np
import pytest

from mx_rcnn_tpu.compile.registry import ProgramRegistry
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data.device_prep import DevicePrep, maybe_device_prep
from mx_rcnn_tpu.data.loader import AnchorLoader, TestLoader, _load_record, _stack
from mx_rcnn_tpu.data.synthetic import SyntheticDataset


def tiny_cfg(device_prep=False, workers=0, dtype="float32"):
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16,
        tpu__SCALES=((64, 96),), tpu__MAX_GT=4,
        tpu__LOADER_WORKERS=workers,
        tpu__DEVICE_PREP=device_prep, tpu__DEVICE_PREP_DTYPE=dtype,
    )
    return cfg.replace(network=dataclasses.replace(
        cfg.network, ANCHOR_SCALES=(2, 4), PIXEL_STDS=(127.0, 127.0, 127.0)))


def record(h, w, flipped=False, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "image_array": rng.randint(0, 255, (h, w, 3), np.uint8),
        "height": h, "width": w, "flipped": flipped,
        "boxes": np.asarray([[2.0, 3.0, min(w - 3, 30.0), min(h - 3, 25.0)]],
                            np.float32),
        "gt_classes": np.asarray([1], np.int32),
    }


def both_paths(rec, dtype="float32", prep=None):
    """(host batch, device-prepped batch) for one record."""
    scale = (64, 96)
    host = _stack([_load_record(rec, tiny_cfg(), scale)])
    raw = _stack([_load_record(rec, tiny_cfg(device_prep=True, dtype=dtype),
                               scale)])
    prep = prep or DevicePrep(tiny_cfg(device_prep=True, dtype=dtype))
    dev = {k: np.asarray(v) for k, v in prep.put(raw).items()}
    return host, dev


# (h, w, flipped): in-bucket landscape/portrait, flip both orientations,
# exact-bucket identity, fractional-scale long side, upscale
IN_BUCKET_CASES = [
    (50, 75, False), (75, 50, False), (50, 75, True), (75, 50, True),
    (64, 96, False), (64, 96, True), (51, 75, False), (51, 75, True),
    (33, 47, False),
]


@pytest.mark.parametrize("h,w,flipped", IN_BUCKET_CASES)
def test_device_prep_parity_in_bucket(h, w, flipped):
    """The acceptance pin: device output == host output to f32 rounding
    for every in-bucket geometry, both orientations, both flips; im_info
    and scaled gt are bit-identical (same compute_scale, same rounding)."""
    host, dev = both_paths(record(h, w, flipped, seed=h * 100 + w))
    assert sorted(host) == sorted(dev)
    np.testing.assert_array_equal(host["im_info"], dev["im_info"])
    np.testing.assert_array_equal(host["gt_boxes"], dev["gt_boxes"])
    np.testing.assert_array_equal(host["gt_valid"], dev["gt_valid"])
    assert dev["images"].dtype == np.float32
    np.testing.assert_allclose(dev["images"], host["images"], atol=1e-5,
                               rtol=0)


@pytest.mark.parametrize("flipped", [False, True])
def test_device_prep_parity_oversized(flipped):
    """Raw larger than the bucket: the host pre-shrinks in uint8 before
    staging (the documented divergence) — bounded by uint8 rounding of
    the resized pixels, far below normalize scale."""
    host, dev = both_paths(record(120, 200, flipped, seed=5))
    np.testing.assert_array_equal(host["im_info"], dev["im_info"])
    np.testing.assert_allclose(dev["images"], host["images"], atol=0.02,
                               rtol=0)


def test_device_prep_parity_bf16():
    """DEVICE_PREP_DTYPE=bfloat16: same transform, output cast to bf16 —
    parity within bf16 resolution of the ±~2 normalized range."""
    host, dev = both_paths(record(50, 75, False, seed=9), dtype="bfloat16")
    assert dev["images"].dtype == jax.numpy.bfloat16
    np.testing.assert_allclose(dev["images"].astype(np.float32),
                               host["images"], atol=0.05, rtol=0)


def test_device_prep_dtype_validated():
    with pytest.raises(ValueError, match="DEVICE_PREP_DTYPE"):
        DevicePrep(tiny_cfg(device_prep=True, dtype="float16"))


def test_put_stacked_matches_singles():
    """The k-group hook preps (k, B, ...) identically to k separate puts
    (one flat dispatch, folded back)."""
    prep = DevicePrep(tiny_cfg(device_prep=True))
    scale = (64, 96)
    cfg = tiny_cfg(device_prep=True)
    recs = [record(50, 75, False, seed=1), record(51, 75, True, seed=2)]
    batches = [_stack([_load_record(r, cfg, scale)]) for r in recs]
    singles = [np.asarray(prep.put(dict(b))["images"]) for b in batches]
    stacked = {k: np.stack([np.asarray(b[k]) for b in batches])
               for k in batches[0]}
    grouped = np.asarray(prep.put_stacked(stacked)["images"])
    np.testing.assert_array_equal(grouped[0], singles[0])
    np.testing.assert_array_equal(grouped[1], singles[1])


def test_zero_steady_state_recompiles(tmp_path):
    """One program per (batch, bucket) — the registry's first-seen count
    must not grow after the first epoch (recompile in steady state is the
    exact failure the registry exists to catch)."""
    cfg = tiny_cfg(device_prep=True)
    registry = ProgramRegistry(cfg, cache_base=str(tmp_path))
    prep = maybe_device_prep(cfg, registry=registry)
    assert prep is not None
    roidb = SyntheticDataset(num_images=6, num_classes=5,
                             height=64, width=96).gt_roidb()
    loader = AnchorLoader(roidb, cfg, batch_size=2, shuffle=True, seed=0)
    loader.put = prep.put
    for _ in loader:
        pass
    after_first = registry.snapshot()["counters"]["programs"]
    assert after_first == 1  # one orientation, one batch shape
    for _ in range(2):
        for _ in loader:
            pass
    assert registry.snapshot()["counters"]["programs"] == after_first


def test_workers_compose_with_device_prep():
    """workers=2 × device-prep raw batches (pixels + sidecars) are
    batch-for-batch identical to the serial producer at the same seed —
    the uint8 staging rides the same shm handover as host-prep floats."""
    roidb = SyntheticDataset(num_images=8, num_classes=5,
                             height=64, width=96).gt_roidb()

    def snap(workers):
        ld = AnchorLoader(roidb, tiny_cfg(device_prep=True, workers=workers),
                          batch_size=2, shuffle=True, seed=3)
        try:
            return [{k: np.copy(v) for k, v in b.items()} for b in ld]
        finally:
            ld.close_workers()

    serial, parallel = snap(0), snap(2)
    assert len(serial) == len(parallel)
    for i, (a, b) in enumerate(zip(serial, parallel)):
        assert sorted(a) == sorted(b), i
        assert a["images"].dtype == np.uint8
        for k in a:
            np.testing.assert_array_equal(a[k], b[k],
                                          err_msg=f"batch {i} key {k}")


def test_maybe_device_prep_gating():
    assert maybe_device_prep(tiny_cfg()) is None
    with pytest.raises(ValueError, match="mesh plan"):
        maybe_device_prep(tiny_cfg(device_prep=True), plan=object())


def test_test_loader_strips_device_prep():
    """Eval DEFAULT stays on the host path: TestLoader under a
    DEVICE_PREP config emits fully-prepped float batches, no raw
    sidecars, unless the driver opts in per loader
    (``device_prep=True`` ← test.py ``--device-prep``)."""
    roidb = SyntheticDataset(num_images=2, num_classes=5,
                             height=64, width=96).gt_roidb()
    loader = TestLoader(roidb, tiny_cfg(device_prep=True), batch_size=1)
    batch = next(iter(loader))
    assert "raw_hw" not in batch and "prep_ratio" not in batch
    assert batch["images"].dtype == np.float32


def test_eval_device_prep_batch_put_parity():
    """Eval opt-in (``--device-prep``): TestLoader keeps the staged
    sidecars and ``Predictor.batch_put`` runs the same jitted prep
    kernel train uses — batches leave the hook in exactly the host-path
    layout (float images on device, host-consumed keys still numpy)
    within the in-bucket parity pin.  Mesh plans keep the explicit
    ValueError."""
    from mx_rcnn_tpu.eval import Predictor
    from mx_rcnn_tpu.models import build_model

    cfg = tiny_cfg(device_prep=True)
    roidb = SyntheticDataset(num_images=3, num_classes=5,
                             height=64, width=96).gt_roidb()
    raw_batches = list(TestLoader(roidb, cfg, batch_size=2,
                                  device_prep=True))
    host_batches = list(TestLoader(roidb, tiny_cfg(), batch_size=2))
    model = build_model(cfg)
    # params are never applied here: batch_put only exercises the prep
    # program, so an empty tree keeps the test compile-light
    pred = Predictor(model, {}, cfg)
    assert pred._device_prep is not None
    assert len(raw_batches) == len(host_batches) == 2
    for raw, host in zip(raw_batches, host_batches):
        assert raw["images"].dtype == np.uint8 and "raw_hw" in raw
        out = pred.batch_put(dict(raw))
        assert "raw_hw" not in out and "prep_ratio" not in out
        assert isinstance(out["im_info"], np.ndarray)
        assert isinstance(out["batch_valid"], np.ndarray)
        np.testing.assert_array_equal(out["im_info"], host["im_info"])
        np.testing.assert_allclose(np.asarray(out["images"]),
                                   host["images"], atol=1e-5, rtol=0)
    with pytest.raises(ValueError, match="mesh plan"):
        Predictor(model, {}, cfg, plan=object())
