"""Fleet flywheel (ISSUE 17): chaos-certified continuous learning at
fabric scale.

Four layers, mirroring the subsystem split:

* **Fleet capture** — member+pid shard naming (cross-host collision
  pin), atomic per-member manifests, and a merge that tolerates absent
  members, torn manifests, and duplicate deliveries.
* **Distributed mine** — per-member ranking passes folded into one
  global top-K: cross-member dedup, a fold order-independent down to
  the manifest BYTES (rid tie-break + canonical dedup winner), and the
  single-host ``flywheel.py mine`` path pinned byte-for-byte unchanged.
* **Gated promotion** — held-out eval shards (corrupt capture pixels
  skipped, torn shards fail the gate CLOSED), the measured-quality
  promotion gate accepting a good candidate and rolling a regressed one
  back without advancing the generation, and windowed
  score-distribution drift detection.
* **Chaos e2e** — 2 REAL TCP members sharing a capture dir under
  router traffic, then the full fleet loop with a partition mid-mine, a
  trainer SIGKILLed mid-epoch, one corrupt capture shard, and duplicate
  manifest delivery — it must still converge to a promoted generation
  on every member; a quality-regressed generation is rejected and no
  member ever serves it.
"""

import importlib.util
import itertools
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.flywheel import (CaptureOptions, DriftDetector,
                                  FlywheelLoop, RequestCapture,
                                  build_eval_shard, detection_agreement,
                                  eval_shard_quality, fold_rankings,
                                  load_eval_shard, member_id,
                                  merge_manifests, mine_member,
                                  mine_shards, write_manifest)
from mx_rcnn_tpu.flywheel import capture as fcap
from mx_rcnn_tpu.flywheel import fleet as ffleet
from mx_rcnn_tpu.flywheel.fleet import FleetFlywheel, score_distribution
from mx_rcnn_tpu.serve import ServeEngine, ServeOptions
from mx_rcnn_tpu.serve import fabric as fb
from mx_rcnn_tpu.serve import replica as rp
from tests.faults import fleet_fault_env, flywheel_fault_env
from tests.replica_worker import FakeServePredictor, load_params
from tests.test_serve import raw_image
from tests.test_serve import tiny_cfg as serve_cfg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "fabric_worker.py")


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _restore_sink():
    yield
    telemetry.shutdown()


def synth_dets(rng, n, lo=0.35, hi=0.9):
    scores = np.sort(rng.uniform(lo, hi, n))[::-1]
    return [{"cls": 1, "score": float(s),
             "bbox": [4.0, 6.0, 60.0, 50.0]} for s in scores]


def fill_member_capture(capture_dir, member, seed=0, n=8,
                        shard_records=4, env=None):
    """Spill n records into a SHARED capture dir as one fleet member."""
    cap = RequestCapture(CaptureOptions(
        capture_dir=capture_dir, shard_records=shard_records,
        member=member), env=env or {})
    rng = np.random.RandomState(seed)
    for _ in range(n):
        px = rng.randint(0, 255, (64, 96, 3), dtype=np.uint8)
        cap.record_batch(
            [(px, (60, 90), (120, 180), synth_dets(rng, 4))], generation=1)
    cap.close()
    return cap


# -- fleet capture ---------------------------------------------------------


def test_shard_and_manifest_names_carry_member_and_pid(tmp_path):
    """Satellite 1: two members sharing one capture dir (same pid —
    the worst-case shared-pid-namespace view) never collide, because
    the member id sits in every shard and manifest name."""
    d = str(tmp_path)
    fill_member_capture(d, "m0", seed=0)
    fill_member_capture(d, "m1", seed=1)
    pid = os.getpid()
    shard_names = sorted(n for n in os.listdir(d)
                         if n.startswith("shard-") and n.endswith(".jsonl"))
    assert len(shard_names) == 4 and len(set(shard_names)) == 4
    for member in ("m0", "m1"):
        prefix = f"shard-{member}-{pid}-"
        assert sum(n.startswith(prefix) for n in shard_names) == 2
        assert os.path.exists(os.path.join(
            d, f"manifest-{member}-{pid}.json"))
    # the sanitizer keeps the name grammar unambiguous: no separators,
    # no dashes inside a member id
    assert member_id("host-1/evil name") == "host_1_evil_name"
    assert "-" not in member_id() and member_id() != ""


def test_member_manifest_atomic_and_lists_every_shard(tmp_path):
    d = str(tmp_path)
    cap = fill_member_capture(d, "m0", n=8, shard_records=4)
    docs = fcap.list_member_manifests(d)
    assert len(docs) == 1
    doc = docs[0]
    assert doc["schema"] == fcap.CAPTURE_MANIFEST_SCHEMA
    assert doc["member"] == "m0" and doc["pid"] == os.getpid()
    assert doc["seq"] == 2 and len(doc["shards"]) == 2
    for base in doc["shards"]:
        assert os.path.exists(os.path.join(d, base + ".jsonl"))
    assert doc["counters"]["captured"] == cap.counters["captured"] == 8
    assert not [n for n in os.listdir(d) if n.endswith(".tmp")]


def test_manifest_merge_tolerates_torn_absent_and_duplicate(tmp_path):
    """Merge tolerance: a torn manifest is skipped (member simply not
    published yet), an absent member just isn't merged, and the
    injected duplicate delivery folds to ONE member entry."""
    d = str(tmp_path)
    fill_member_capture(d, "m0", seed=0,
                        env=fleet_fault_env(dup_manifest="m0"))
    fill_member_capture(d, "m1", seed=1)
    # a torn third member's manifest: interrupted mid-write
    with open(os.path.join(d, "manifest-late-99.json"), "w") as fh:
        fh.write('{"schema": "mxr_capture_man')
    dup_names = [n for n in os.listdir(d) if n.endswith(".dup.json")]
    assert dup_names, "dup-manifest injection wrote nothing"
    merged = merge_manifests(d)
    members = sorted(doc["member"] for doc in merged["members"].values())
    assert members == ["m0", "m1"]
    assert merged["duplicates_dropped"] >= 1
    # absent/late member arriving later is merged next round
    fill_member_capture(d, "m2", seed=2)
    merged = merge_manifests(d)
    assert sorted(doc["member"] for doc in
                  merged["members"].values()) == ["m0", "m1", "m2"]


# -- distributed mine ------------------------------------------------------


def test_mine_member_scans_exactly_claimed_shards(tmp_path):
    d = str(tmp_path)
    fill_member_capture(d, "m0", seed=0, n=8)
    fill_member_capture(d, "m1", seed=1, n=8)
    doc = next(m for m in merge_manifests(d)["members"].values()
               if m["member"] == "m0")
    r = mine_member(d, doc, top_k=16, min_label_score=0.1)
    assert r["member"] == "m0" and r["scanned"] == 8
    assert all(e["member"] == "m0" for e in r["entries"])
    assert r["missing_shards"] == 0
    # a stale claim (rotated-out shard) costs coverage, never the mine
    doc2 = dict(doc, shards=doc["shards"] + ["shard-m0-0-000099"])
    r2 = mine_member(d, doc2, top_k=16, min_label_score=0.1)
    assert r2["missing_shards"] == 1 and r2["scanned"] == 8


def _entry(npz, key, rid, h, member):
    return {"npz": npz, "key": key, "rid": rid, "hardness": h,
            "member": member, "signals": {}, "generation": 1,
            "trace_id": None, "bucket": [64, 96], "raw_hw": [60, 90],
            "orig_hw": [120, 180], "detections": []}


def test_fold_dedup_and_rid_tiebreak_order_independent():
    """Cross-member dedup on (npz, key); equal-hardness ties break on
    rid then (npz, key); the dedup winner's member tag is canonical
    (smallest member id), never first-seen — fold order cannot leak
    into the result."""
    rA = {"member": "a", "scanned": 2, "skipped": 0, "entries": [
        _entry("a.npz", "r1", 0, 1.0, "a"),
        _entry("shared.npz", "rX", 7, 0.8, "a")]}
    rB = {"member": "b", "scanned": 2, "skipped": 0, "entries": [
        _entry("b.npz", "r1", 0, 1.0, "b"),
        _entry("shared.npz", "rX", 7, 0.8, "b")]}
    fwd, _, scanned, _ = fold_rankings([rA, rB], top_k=8)
    rev, _, _, _ = fold_rankings([rB, rA], top_k=8)
    assert fwd == rev and scanned == 4
    assert [e["npz"] for e in fwd] == ["a.npz", "b.npz", "shared.npz"]
    # the shared record ranked ONCE, tagged with the canonical member
    shared = [e for e in fwd if e["npz"] == "shared.npz"]
    assert len(shared) == 1 and shared[0]["member"] == "a"
    # rid asc breaks a pure hardness tie across members
    assert fwd[0]["rid"] == fwd[1]["rid"] == 0


def test_fold_determinism_byte_identical_manifest(tmp_path):
    """Satellite 3: folding the same per-member rankings in ANY member
    order lands on a byte-identical ``mined-<digest>.json``."""
    d = str(tmp_path / "cap")
    for i, m in enumerate(("ma", "mb", "mc")):
        fill_member_capture(d, m, seed=i, n=8)
    rankings = [mine_member(d, doc, top_k=8, min_label_score=0.1)
                for doc in merge_manifests(d)["members"].values()]
    blobs, names = set(), set()
    for i, perm in enumerate(itertools.permutations(rankings)):
        train, evals, scanned, _ = fold_rankings(
            list(perm), top_k=6, eval_every=3)
        out = str(tmp_path / f"out{i}")
        path = write_manifest(d, train, scanned, 6, out_dir=out,
                              min_label_score=0.1,
                              extra={"members": sorted(r["member"]
                                                       for r in perm),
                                     "eval_entries": evals})
        names.add(os.path.basename(path))
        with open(path, "rb") as fh:
            blobs.add(fh.read())
    assert len(names) == 1 and len(blobs) == 1


def test_write_manifest_extra_is_additive_only(tmp_path):
    d = str(tmp_path)
    fill_member_capture(d, "m0", n=4)
    entries, scanned, _ = mine_shards(d, top_k=4, min_label_score=0.1)
    path = write_manifest(d, entries, scanned, 4,
                          extra={"members": ["m0"], "eval_entries": []})
    with open(path) as fh:
        doc = json.load(fh)
    assert doc["members"] == ["m0"] and doc["eval_entries"] == []
    with pytest.raises(ValueError, match="shadows"):
        write_manifest(d, entries, scanned, 4, extra={"entries": []})


def test_single_host_mine_byte_for_byte_unchanged(tmp_path):
    """The acceptance pin: with fleet mode off, ``flywheel.py mine``
    produces the exact legacy manifest — same keys, no member tags, and
    the CLI and in-process paths land on identical bytes."""
    d = str(tmp_path / "cap")
    fill_member_capture(d, "solo", n=8)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "flywheel.py"), "mine",
         "--capture-dir", d, "--top-k", "4", "--min-label-score", "0.3"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO,
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    path = json.loads(out.stdout.strip().splitlines()[-1])["manifest"]
    with open(path, "rb") as fh:
        cli_bytes = fh.read()
    doc = json.loads(cli_bytes)
    assert set(doc) == {"schema", "version", "capture_dir", "top_k",
                        "total_scanned", "min_label_score", "entries"}
    assert doc["entries"] and all("member" not in e
                                  for e in doc["entries"])
    os.unlink(path)
    res = FlywheelLoop(d, top_k=4).run_round(0)
    assert res["manifest"] == path
    with open(path, "rb") as fh:
        assert fh.read() == cli_bytes


# -- checkpoint discovery under partial writes (satellite 2) ---------------


def test_scan_checkpoints_never_selects_half_written(tmp_path):
    """A trainer killed mid-save leaves an empty or tmp-only int dir;
    the watcher must never select it (never-rolls-backward holds under
    partial writes) and must pick it up once the save commits."""
    (tmp_path / "2").mkdir()                      # dir created, no payload
    assert rp.scan_checkpoints(str(tmp_path)) is None
    (tmp_path / "2" / "payload.tmp-77").write_bytes(b"")  # still staging
    assert rp.scan_checkpoints(str(tmp_path)) is None
    steps = tmp_path / "steps"
    steps.mkdir()
    (steps / str(10 ** 7 + 3)).mkdir()            # half-written step save
    assert rp.scan_checkpoints(str(tmp_path)) is None
    c = tmp_path / "1"
    c.mkdir()
    (c / "params.npz").write_bytes(b"x")
    t = rp.scan_checkpoints(str(tmp_path))
    assert (t["kind"], t["epoch"], t["consumed"]) == ("epoch", 1, 0)
    calls = []
    w = rp.CheckpointWatcher(str(tmp_path),
                             lambda tgt: calls.append(tgt) or True)
    w.prime()
    assert w.poll_once() is None and not calls    # husks never flap it
    (tmp_path / "2" / "weights.npz").write_bytes(b"y")  # save commits
    got = w.poll_once()
    assert got is not None and got[1]
    assert calls and calls[0]["epoch"] == 2


# -- eval shards + agreement + drift ---------------------------------------


def test_build_eval_shard_skips_corrupt_pixels(tmp_path):
    d = str(tmp_path)
    fill_member_capture(d, "m0", n=8, shard_records=4,
                        env=flywheel_fault_env(corrupt_shard=0))
    doc = next(iter(merge_manifests(d)["members"].values()))
    r = mine_member(d, doc, top_k=8, min_label_score=0.1)
    path, kept, skipped = build_eval_shard(d, r["entries"],
                                           str(tmp_path / "ev"))
    assert kept == 4 and skipped == 4             # shard 0's npz is garbage
    shard = load_eval_shard(path)
    assert len(shard["records"]) == kept
    for rec in shard["records"]:
        assert shard["pixels"][rec["key"]].dtype == np.uint8
        assert rec["labels"]
    # the gate fails CLOSED on anything torn
    bad = str(tmp_path / "torn.json")
    with open(bad, "w") as fh:
        fh.write('{"schema": "mxr_eval_shard", "records"')
    with pytest.raises(ValueError):
        load_eval_shard(bad)
    with open(bad, "w") as fh:
        json.dump({"schema": "something_else"}, fh)
    with pytest.raises(ValueError, match="mxr_eval_shard"):
        load_eval_shard(bad)


def test_detection_agreement_semantics():
    box = [0.0, 0.0, 16.0, 16.0]
    p = [{"cls": 1, "score": 0.8, "bbox": box}]
    g = [{"cls": 1, "score": 0.7, "bbox": box}]
    assert detection_agreement([], []) == 1.0     # nothing to disagree
    assert detection_agreement(p, []) == 0.0
    assert detection_agreement([], g) == 0.0
    assert detection_agreement(p, g) == 1.0
    wrong_cls = [{"cls": 2, "score": 0.7, "bbox": box}]
    assert detection_agreement(p, wrong_cls) == 0.0
    # a collapsed candidate's sub-floor scores count as NO predictions
    weak = [{"cls": 1, "score": 0.01, "bbox": box}]
    assert detection_agreement(weak, g) == 0.0
    shifted = [{"cls": 1, "score": 0.8,
                "bbox": [100.0, 100.0, 120.0, 120.0]}]
    assert detection_agreement(shifted, g) == 0.0  # IoU below threshold


def test_drift_detector_windowed_vs_snapshot():
    base = [{"mean_score": 0.7, "entropy": 0.2,
             "bands": {"0.3": 3, "0.5": 2, "0.7": 1}}] * 8
    dd = DriftDetector(threshold=0.2, window=8, min_observed=4)
    assert dd.check() == (False, 0.0)             # no snapshot yet
    dd.snapshot(base)
    for s in base:
        dd.observe(s)
    drifted, metric = dd.check()
    assert not drifted and metric < 0.01
    shifted = [{"mean_score": 0.2, "entropy": 0.8,
                "bands": {"0.3": 1, "0.5": 0, "0.7": 0}}] * 8
    for s in shifted:
        dd.observe(s)                             # window fully replaced
    drifted, metric = dd.check()
    assert drifted and metric > 0.2
    ref = score_distribution(base)
    assert ref["mean_score"] == pytest.approx(0.7)
    assert ref["bands"]["0.7"] == 1.0


def test_fleet_fault_env_composer_round_trips():
    env = fleet_fault_env(partition_mine=["m1", "m2"],
                          dup_manifest="m0", kill_train=(1, 0.5))
    assert env[ffleet.ENV_PARTITION_MINE] == "m1,m2"
    assert env[fcap.ENV_DUP_MANIFEST] == "m0"
    assert env[ffleet.ENV_KILL_TRAIN] == "1:0.5"
    fw = FleetFlywheel("/nonexistent", env=env)
    assert fw._partitioned == {"m1", "m2"}
    assert (fw._kill_round, fw._kill_after_s) == (1, 0.5)
    assert FleetFlywheel("/nonexistent", env={})._partitioned == set()


# -- the promotion gate, in-process ----------------------------------------


def _capture_engine_traffic(tmp_path, n=8):
    """Serve n requests through a REAL engine with capture on; returns
    (capture_dir, eval_shard_path) built from the mined hold-outs."""
    scfg = serve_cfg()
    d = str(tmp_path / "cap")
    pred = FakeServePredictor(scfg, {"scale": np.float32(1.0)})
    engine = ServeEngine(pred, scfg, ServeOptions(
        batch_size=2, max_delay_ms=1.0, max_queue=32))
    engine.capture = RequestCapture(CaptureOptions(
        capture_dir=d, shard_records=4, member="m0"))
    engine.start()
    try:
        futs = [engine.submit(raw_image(60 + i, 100 + i, 30 + 5 * i))
                for i in range(n)]
        for f in futs:
            assert f.result(timeout=30.0)
    finally:
        engine.stop()
    doc = next(iter(merge_manifests(d)["members"].values()))
    r = mine_member(d, doc, top_k=n, min_label_score=0.1)
    path, kept, _ = build_eval_shard(d, r["entries"][:4],
                                     str(tmp_path / "ev"))
    assert kept >= 1
    return d, path


def test_promotion_gate_accepts_beats_rejects_regression(tmp_path):
    """The PR-8 canary extended to a measured quality delta: a candidate
    matching the incumbent on the held-out shard promotes; a collapsed
    candidate is rolled back with the generation UNTOUCHED, and the
    engine keeps serving the incumbent's outputs."""
    telemetry.configure(str(tmp_path / "tel"), run_meta={"driver": "t"})
    _, eval_shard = _capture_engine_traffic(tmp_path)
    scfg = serve_cfg()
    pred = FakeServePredictor(scfg, {"scale": np.float32(1.0)})
    engine = ServeEngine(pred, scfg, ServeOptions(
        batch_size=2, max_delay_ms=1.0, max_queue=32)).start()
    try:
        good = str(tmp_path / "good.json")
        with open(good, "w") as fh:
            json.dump({"scale": 1.3}, fh)
        ok, info = rp.reload_engine_params(
            engine, pred, scfg,
            {"prefix": good, "kind": "file", "epoch": 1, "consumed": 0,
             "eval_shard": eval_shard, "quality_slack": 0.1},
            load_params_fn=load_params)
        assert ok, info
        assert info["quality_candidate"] >= info["quality_incumbent"] - 0.1
        assert info["quality_incumbent"] > 0.5    # incumbent agrees with
        gen = engine.generation                   # its own pseudo-labels
        assert gen >= 1
        before = engine.submit(raw_image(60, 100, 40)).result(timeout=30.0)
        bad = str(tmp_path / "bad.json")
        with open(bad, "w") as fh:
            json.dump({"scale": 0.004}, fh)       # quality-regressed save
        ok, info = rp.reload_engine_params(
            engine, pred, scfg,
            {"prefix": bad, "kind": "file", "epoch": 2, "consumed": 0,
             "eval_shard": eval_shard, "quality_slack": 0.0},
            load_params_fn=load_params)
        assert not ok and info["rolled_back"]
        assert info["quality_candidate"] < info["quality_incumbent"]
        assert engine.generation == gen           # never advanced
        after = engine.submit(raw_image(60, 100, 40)).result(timeout=30.0)
        assert after and after[0]["score"] == pytest.approx(
            before[0]["score"], abs=1e-5)         # incumbent still serving
        # fail CLOSED: an unreadable eval shard blocks the swap entirely
        ok, info = rp.reload_engine_params(
            engine, pred, scfg,
            {"prefix": good, "kind": "file", "epoch": 3, "consumed": 0,
             "eval_shard": str(tmp_path / "missing.json")},
            load_params_fn=load_params)
        assert not ok and "eval shard unreadable" in info["error"]
        assert not info["rolled_back"] and engine.generation == gen
    finally:
        engine.stop()
    telemetry.shutdown()
    flight = os.path.join(str(tmp_path / "tel"), "flight_0.jsonl")
    assert os.path.exists(flight)
    blob = open(flight).read()
    assert "promotion_rejected" in blob


def test_eval_shard_quality_scores_live_engine(tmp_path):
    _, eval_shard = _capture_engine_traffic(tmp_path)
    shard = load_eval_shard(eval_shard)
    scfg = serve_cfg()
    pred = FakeServePredictor(scfg, {"scale": np.float32(1.0)})
    engine = ServeEngine(pred, scfg, ServeOptions(
        batch_size=2, max_delay_ms=1.0, max_queue=32)).start()
    try:
        q = eval_shard_quality(engine, shard)
        assert q > 0.5                            # reproduces own labels
        pred.update_params({"scale": np.float32(0.004)})
        assert eval_shard_quality(engine, shard) < q
    finally:
        engine.stop()


# -- report / gate / loadgen plumbing --------------------------------------


def test_perf_gate_fleet_rows_additive():
    pg = _load_script("perf_gate")
    r01 = {"schema": "mxr_flywheel_report", "captured": 100, "mined": 10,
           "generation_before": 0, "generation_after": 1}
    rows = pg.flywheel_report_rows(r01)
    assert [r["metric"] for r in rows] == [
        "flywheel_mined_fraction", "flywheel_reload_generations"]
    r02 = dict(r01, generation_promoted=1, promotion_gate_pass=1,
               drift_detected=0)
    rows = pg.flywheel_report_rows(r02)
    by = {r["metric"]: r for r in rows}
    assert by["flywheel_generation_promoted"]["value"] == 1.0
    assert by["flywheel_generation_promoted"]["floor"] == \
        pg.FLYWHEEL_PROMOTED_FLOOR
    assert by["flywheel_promotion_gate_pass"]["value"] == 1.0
    assert "floor" not in by["flywheel_promotion_gate_pass"]
    assert by["flywheel_drift_detected"]["value"] == 0.0
    # a stalled loop fails the floor
    stalled = dict(r02, generation_promoted=0)
    row = {r["metric"]: r for r in pg.flywheel_report_rows(stalled)}[
        "flywheel_generation_promoted"]
    assert row["value"] < row["floor"]


def test_loadgen_folds_fabric_member_flywheel_sections():
    lg = _load_script("loadgen")
    single = {"flywheel": {"captured": 7, "sample_every": 2}}
    assert lg.fold_flywheel_sections(single) == {"captured": 7,
                                                 "sample_every": 2}
    fabric = {"engines": {
        "127.0.0.1:1": {"flywheel": {"captured": 3, "sample_every": 1}},
        "127.0.0.1:2": {"flywheel": {"captured": 5, "sample_every": 2}},
        "127.0.0.1:3": {"status": "evicted"}}}
    assert lg.fold_flywheel_sections(fabric) == {"captured": 8,
                                                 "sample_every": 2}
    assert lg.fold_flywheel_sections({"engines": {}}) == {}
    assert lg.fold_flywheel_sections({}) == {}


def test_flywheel_counters_table_has_fleet_rows():
    from mx_rcnn_tpu.telemetry.report import FLYWHEEL_COUNTERS
    for key in ("flywheel/manifest_dup_dropped", "flywheel/promoted",
                "flywheel/rejected", "flywheel/drift_detected",
                "flywheel/promotion_gate_pass",
                "flywheel/promotion_gate_reject"):
        assert key in FLYWHEEL_COUNTERS


# -- chaos e2e: the acceptance pin -----------------------------------------


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _member_proc(port, index, member, capture_dir, env=None):
    argv = [sys.executable, WORKER, "--port", str(port),
            "--replica-index", str(index),
            "--capture-dir", capture_dir, "--capture-member", member,
            "--capture-shard-records", "4"]
    return subprocess.Popen(
        argv, env={**os.environ, "JAX_PLATFORMS": "cpu", **(env or {})})


def _e2e_opts(**kw):
    base = dict(probe_interval_s=0.2, probe_timeout_s=2.0,
                evict_probes=2, start_timeout_s=120.0,
                backoff_base_s=0.2, backoff_max_s=1.0, stable_s=5.0,
                drain_timeout_s=15.0, reload_timeout_s=120.0)
    base.update(kw)
    return fb.FabricOptions(**base)


def _wait(cond, timeout=90.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


def _cleanup(pool, procs):
    pool.stop()
    for p in procs:
        p.kill()
        p.wait(timeout=30)


TRAINER_SRC = """\
import argparse, json, os, time
ap = argparse.ArgumentParser()
ap.add_argument("--params-file", required=True)
ap.add_argument("--sleep", type=float, default=1.0)
ap.add_argument("--replay-manifest", required=True)
a = ap.parse_args()
assert os.path.exists(a.replay_manifest)
time.sleep(a.sleep)
tmp = a.params_file + ".tmp"
with open(tmp, "w") as fh:
    json.dump({"scale": 2.0}, fh)
os.replace(tmp, a.params_file)
"""


def test_fleet_chaos_e2e_converges_and_rejects_regression(tmp_path):
    """THE acceptance pin: 2 real TCP members share a capture dir under
    router traffic; the fleet loop runs with a partition mid-mine (m1),
    the round-0 trainer SIGKILLed mid-epoch, m0's first capture shard
    corrupted, and m0's manifest duplicate-delivered — and still
    converges to a promoted generation served by ALL members.  Then a
    quality-regressed candidate is rejected by the member-side gate and
    every member stays on the incumbent."""
    from mx_rcnn_tpu.serve import encode_image_payload

    capdir = str(tmp_path / "cap")
    os.makedirs(capdir)
    pfile = str(tmp_path / "params.json")
    trainer = str(tmp_path / "trainer.py")
    with open(trainer, "w") as fh:
        fh.write(TRAINER_SRC)
    ports = [_free_port(), _free_port()]
    procs = [
        _member_proc(ports[0], 0, "m0", capdir,
                     env={**flywheel_fault_env(corrupt_shard=0),
                          **fleet_fault_env(dup_manifest="m0")}),
        _member_proc(ports[1], 1, "m1", capdir),
    ]
    pool = fb.ReplicaPool(_e2e_opts())
    for port in ports:
        pool.register(f"127.0.0.1:{port}")
    pool.start()
    try:
        _wait(lambda: pool.ready_count() == 2, what="both members ready")
        router = fb.FabricRouter(pool, timeout_s=30.0)
        body = json.dumps(encode_image_payload(
            np.full((60, 100, 3), 50, np.uint8))).encode()

        def both_members_spilled():
            status, _, _ = router.route_predict(body)
            assert status in (200, 503)
            docs = merge_manifests(capdir)["members"].values()
            per = {d["member"]: len(d["shards"]) for d in docs}
            return per.get("m0", 0) >= 2 and per.get("m1", 0) >= 2

        _wait(both_members_spilled, timeout=90.0,
              what="both members to spill 2+ capture shards")

        fleet = FleetFlywheel(
            capdir, top_k=12, min_label_score=0.1,
            train_cmd=[sys.executable, trainer, "--params-file", pfile,
                       "--sleep", "1.0"],
            candidate_fn=None, rollout_fn=pool.reload_to,
            eval_every=3, quality_slack=0.3,
            env=fleet_fault_env(partition_mine="m1",
                                kill_train=(0, 0.25)))
        epoch = {"n": 0}

        def candidate_fn():
            if not os.path.exists(pfile):
                return None
            epoch["n"] += 1
            return {"prefix": pfile, "kind": "file",
                    "epoch": epoch["n"], "consumed": 0}

        fleet.candidate_fn = candidate_fn
        results = fleet.run(max_rounds=3)
        # round 0: trainer SIGKILLed mid-epoch → negative rc, no promote
        assert results[0]["train_rc"] not in (None, 0)
        assert not results[0]["promoted"]
        # the partitioned member cost its ranking, never the round
        assert results[0]["mine_failed"] == ["m1"]
        assert results[0]["members"] == ["m0"]
        # duplicate delivery folded, not double-counted
        assert results[0]["duplicates_dropped"] >= 1
        # CONVERGENCE: a later round promotes fleet-wide anyway
        assert fleet.promoted_rounds == 1
        final = results[-1]
        assert final["promoted"] and final["train_rc"] == 0
        assert pool.generation >= 1
        gens = pool.member_generations()
        assert len(gens) == 2
        assert all(g == pool.generation for g in gens.values()), gens
        promoted_gen = pool.generation

        # REJECTION: a quality-regressed generation must never be
        # served by any member.  Gate on a hold-out shard built from
        # the mined entries (corrupt-shard records skipped).
        with open(final["manifest"]) as fh:
            entries = json.load(fh)["entries"]
        ev_path, kept, _ = build_eval_shard(capdir, entries,
                                            str(tmp_path / "reject-ev"))
        assert ev_path and kept >= 1
        badfile = str(tmp_path / "bad.json")
        with open(badfile, "w") as fh:
            json.dump({"scale": 0.004}, fh)
        ok = pool.reload_to({"prefix": badfile, "kind": "file",
                             "epoch": 99, "consumed": 0,
                             "eval_shard": ev_path,
                             "quality_slack": 0.0})
        assert not ok
        assert pool.counters["quality_rejected"] >= 1
        assert pool.generation == promoted_gen
        assert all(g == promoted_gen
                   for g in pool.member_generations().values())
        # every member still answers with the incumbent weights
        status, _, _ = router.route_predict(body)
        assert status == 200
    finally:
        _cleanup(pool, procs)
