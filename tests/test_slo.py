"""SLO layer tier-1 tests (CPU, no network): the histogram primitive,
the engine's latency/policy surface, the SLO controller's control law
(driven deterministically through the injectable-``now`` ``tick``), and
the loadgen/perf_gate SLO report contract.

The controller tests run against a real ``ServeEngine`` over the
``FakePredictor`` from ``test_serve`` — no model, no compile — and feed
the engine's own histograms directly, which is exactly the interface the
controller consumes in production.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.serve import ControllerOptions, RejectedError, SLOController
from mx_rcnn_tpu.telemetry import HIST_LE, Hist, quantile_from_counts
from mx_rcnn_tpu.telemetry.obs import engine_summary, prometheus_text
from mx_rcnn_tpu.telemetry.report import aggregate, load_events

from tests.test_serve import make_engine, raw_image, tiny_cfg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _restore_sink():
    yield
    telemetry.shutdown()


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- histogram primitive ---------------------------------------------------


def test_hist_bucket_boundaries():
    h = Hist()
    # a value exactly ON a boundary lands in that boundary's bucket
    # (le is an UPPER bound, Prometheus semantics), one just above it in
    # the next; the tiniest and hugest values hit the edge buckets
    h.observe(HIST_LE[0])          # == first upper bound
    h.observe(HIST_LE[5])
    h.observe(HIST_LE[5] * 1.0001)
    h.observe(1e-9)                # far below the first bound
    h.observe(1e9)                 # beyond the last bound: overflow
    assert h.buckets[0] == 2       # 1e-9 and the exact first bound
    assert h.buckets[5] == 1
    assert h.buckets[6] == 1
    assert h.buckets[-1] == 1      # the +Inf overflow bucket
    assert h.count == 5 and len(h.buckets) == len(HIST_LE) + 1
    # quantile interpolation stays inside the containing bucket
    mid = Hist()
    for _ in range(100):
        mid.observe(0.010)
    lo = HIST_LE[max(i for i, le in enumerate(HIST_LE) if le < 0.010)]
    hi = min(le for le in HIST_LE if le >= 0.010)
    assert lo < mid.quantile(0.5) <= hi
    # empty histogram has no quantile
    assert Hist().quantile(0.5) is None
    assert quantile_from_counts(HIST_LE, [0] * (len(HIST_LE) + 1), 0,
                                0.99) is None


def test_hist_merge_associative_across_ranks():
    rng = np.random.RandomState(0)
    parts = []
    for _ in range(3):  # three "ranks" with different distributions
        h = Hist()
        for v in rng.lognormal(-4, 1, 200):
            h.observe(float(v))
        parts.append(h)
    ab_c = Hist().merge(parts[0]).merge(parts[1]).merge(parts[2])
    c_ba = Hist().merge(parts[2]).merge(parts[1]).merge(parts[0])
    assert ab_c.buckets == c_ba.buckets
    assert ab_c.count == c_ba.count == 600
    assert abs(ab_c.sum - c_ba.sum) < 1e-9
    assert ab_c.quantile(0.99) == c_ba.quantile(0.99)
    # dict form merges identically (the snapshot-fold path)
    via_dict = Hist().merge(parts[0].to_dict()).merge(
        parts[1].to_dict()).merge(parts[2].to_dict())
    assert via_dict.buckets == ab_c.buckets
    # boundary-version mismatch is an error, not silent corruption
    bad = parts[0].to_dict()
    bad["le"] = bad["le"][:-1]
    with pytest.raises(ValueError):
        Hist().merge(bad)


def test_hist_window_quantile_sees_only_recent():
    h = Hist()
    for i in range(100):               # old regime: 1 ms
        h.observe(0.001, now=float(i))
    for i in range(100, 120):          # recent regime: 1 s
        h.observe(1.0, now=float(i))
    assert h.quantile(0.5) < 0.01      # lifetime: dominated by the old
    recent = h.window_quantile(0.5, 15.0, now=119.0)
    assert recent > 0.5                # window: the new regime only
    # a window longer than the run falls back to the whole history
    assert h.window_quantile(0.5, 1e6, now=119.0) == h.quantile(0.5)


def test_hist_prometheus_exposition_roundtrip():
    h = Hist()
    vals = [0.0005, 0.002, 0.002, 0.05, 2.0]
    for v in vals:
        h.observe(v)
    txt = prometheus_text({0: {"hists": {"serve/request_time": h.to_dict()},
                               "counters": {}, "gauges": {}, "spans": {}}})
    assert "# TYPE mxr_serve_request_time_seconds histogram" in txt
    # parse the family back: cumulative buckets, +Inf == _count, _sum
    buckets = {}
    total = None
    ssum = None
    for line in txt.splitlines():
        if line.startswith("mxr_serve_request_time_seconds_bucket"):
            le = line.split('le="')[1].split('"')[0]
            buckets[le] = int(line.rsplit(" ", 1)[1])
        elif line.startswith("mxr_serve_request_time_seconds_count"):
            total = int(line.rsplit(" ", 1)[1])
        elif line.startswith("mxr_serve_request_time_seconds_sum"):
            ssum = float(line.rsplit(" ", 1)[1])
    assert total == len(vals) and buckets["+Inf"] == total
    assert abs(ssum - sum(vals)) < 1e-9
    # cumulative counts are monotone and recover the per-bucket counts
    finite = [buckets[k] for k in buckets if k != "+Inf"]
    assert finite == sorted(finite)
    per_bucket = np.diff([0] + finite).tolist()
    assert per_bucket == h.buckets[:len(per_bucket)]


def test_sink_observe_jsonl_and_report_fold(tmp_path):
    tel = telemetry.configure(str(tmp_path), run_meta={"driver": "t"})
    for v in (0.001, 0.004, 0.2):
        tel.observe("serve/request_time", v)
    assert tel.hist_quantile("serve/request_time", 0.5) is not None
    assert tel.hist_quantile("nope", 0.5) is None
    summ = tel.summary()
    assert summ["hists"]["serve/request_time"]["count"] == 3
    telemetry.shutdown()
    events = load_events([str(tmp_path)])
    kinds = {e["kind"] for e in events}
    assert "hist" in kinds
    folded = aggregate(events)
    # the offline fold reproduces the live sink's distribution exactly
    assert folded["hists"]["serve/request_time"] == \
        summ["hists"]["serve/request_time"]


# -- engine latency/policy surface -----------------------------------------


def test_engine_records_latency_hists_and_metrics():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=2, max_delay_ms=1.0).start()
    try:
        futs = [engine.submit(raw_image(60, 100, 50)) for _ in range(4)]
        for f in futs:
            f.result(timeout=30)
    finally:
        engine.stop()
    assert engine.hists["serve/request_time"].count == 4
    assert engine.hists["serve/queue_wait"].count == 4
    assert engine.hists["serve/service_time"].count >= 1
    hists = engine.latency_hists()
    per_bucket = [k for k in hists if k.startswith("serve/request_time/")]
    assert per_bucket and hists[per_bucket[0]].count == 4
    m = engine.metrics()
    assert m["latency"]["request_time_p99_ms"] > 0
    assert m["latency"]["request_time_p50_ms"] <= \
        m["latency"]["request_time_p99_ms"]
    # the frontend's Prometheus registry carries the histogram family
    # with nonzero _count plus the engine counters
    summ = engine_summary(engine)
    assert summ["hists"]["serve/request_time"]["count"] == 4
    txt = prometheus_text({0: summ})
    assert "mxr_serve_request_time_seconds_bucket" in txt
    assert 'mxr_serve_request_time_seconds_count{rank="0"} 4' in txt


def test_bucket_policy_clamps_and_flush_threshold():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=4, max_delay_ms=300.0)
    fake = engine.predictor
    key = engine.bucket_key(60, 100)
    engine.set_bucket_policy(key, max_batch=99, max_delay_ms=-5)
    assert engine.bucket_policy(key) == (4, 0.0)  # clamped both ways
    engine.set_bucket_policy(key, max_batch=2, max_delay_ms=300.0)
    assert engine.bucket_policy(key) == (2, 300.0)
    # two requests now make a "full" flush despite batch_size=4 — and the
    # forward is still padded to the compiled batch of 4
    futs = [engine.submit(raw_image(60, 100, v)) for v in (40, 200)]
    engine.start()
    try:
        for f in futs:
            f.result(timeout=30)
    finally:
        engine.stop()
    assert len(fake.batches) == 1 and fake.batches[0][0] == 4
    assert engine.counters["served"] == 2


def test_admit_limit_sheds_distinct_from_queue_full():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=2, max_queue=8)
    engine.set_admit_limit(2)
    for _ in range(2):  # not started: nothing drains
        engine.submit(raw_image(60, 100, 50))
    with pytest.raises(RejectedError, match="load shed"):
        engine.submit(raw_image(60, 100, 50))
    assert engine.counters["shed"] == 1
    assert engine.counters["rejected"] == 0  # shed is its own counter
    engine.set_admit_limit(None)
    engine.submit(raw_image(60, 100, 50))    # back to max_queue rules
    assert engine.counters["requests"] == 3
    engine.stop()


# -- the SLO controller ----------------------------------------------------


def _controller(engine, **kw):
    kw.setdefault("target_p99_ms", 100.0)
    kw.setdefault("min_samples", 4)
    kw.setdefault("relax_after", 1)
    return SLOController(engine, ControllerOptions(**kw))


def test_controller_tightens_then_relaxes():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=4, max_delay_ms=20.0)
    key = engine.bucket_key(60, 100)
    engine.submit(raw_image(60, 100, 50))  # make the bucket known
    ctrl = _controller(engine, window_s=10.0)
    ctrl.engine.controller = ctrl  # what start() does, sans thread
    # breach: p99 far over target inside the window
    for i in range(10):
        engine.hists["serve/request_time"].observe(0.5, now=float(i))
    acted = ctrl.tick(now=10.0)
    assert any(a[0] == "tighten" for a in acted)
    b1, d1 = engine.bucket_policy(key)
    assert b1 == 3 and d1 == 10.0  # -1 batch, delay halved
    ctrl.tick(now=10.5)
    assert engine.bucket_policy(key)[0] == 2
    # repeated breaches converge to the floor, then stop acting
    for t in range(11, 30):
        engine.hists["serve/request_time"].observe(0.5, now=float(t))
        ctrl.tick(now=float(t))
    assert engine.bucket_policy(key) == (1, 0.0)
    assert ctrl.tick(now=30.0) == []  # at the floor: no decision spam
    # recovery: fast traffic far past the old window → healthy → relax
    # back toward the configured (4, 20.0)
    for t in range(100, 110):
        engine.hists["serve/request_time"].observe(0.001, now=float(t))
    for t in range(110, 140):
        ctrl.tick(now=float(t))
    assert engine.bucket_policy(key) == (4, 20.0)
    assert ctrl.decisions > 0 and ctrl.ticks > 0
    engine.stop()


def test_controller_sheds_on_queue_trend_and_recovers(tmp_path):
    telemetry.configure(str(tmp_path), run_meta={"driver": "t"})
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=2, max_queue=16)
    ctrl = _controller(engine, window_s=5.0)
    ctrl.engine.controller = ctrl
    # queue grows tick over tick with nothing draining (engine unstarted):
    # slope > 0, drain time infinite → predictive shed; once the cap is
    # on, the rest of the ramp is refused at submit
    shed_err = None
    for t in range(4):
        for _ in range(3):
            try:
                engine.submit(raw_image(60, 100, 50))
            except RejectedError as e:
                shed_err = e
        ctrl.tick(now=float(t))
    assert ctrl.state()["shedding"] is True
    assert engine.metrics()["admit_limit"] == 2  # max(batch_size, 0)
    assert shed_err is not None and "load shed" in str(shed_err)
    assert engine.counters["shed"] >= 1
    # the shed-on transition left a flight dump and slo/ telemetry
    assert (tmp_path / "flight_0.jsonl").exists()
    flight = [json.loads(ln) for ln in
              (tmp_path / "flight_0.jsonl").read_text().splitlines()]
    assert any(e["kind"] == "meta" and e["name"] == "flight_trigger"
               and e["fields"]["reason"] == "slo_shed" for e in flight)
    summ = telemetry.get().summary()
    assert summ["counters"]["slo/shed_on"] == 1
    assert summ["counters"]["slo/decisions"] >= 1
    # drain the queue; with a falling trend the controller lifts the cap
    with engine._lock:
        for q in engine._queues.values():
            q.clear()
    for t in range(100, 104):
        ctrl.tick(now=float(t))
    assert ctrl.state()["shedding"] is False
    assert engine.metrics()["admit_limit"] is None
    assert telemetry.get().summary()["counters"]["slo/shed_off"] == 1
    engine.submit(raw_image(60, 100, 50))  # admissions open again
    engine.stop()


def test_controller_decisions_are_telemetry_events(tmp_path):
    telemetry.configure(str(tmp_path), run_meta={"driver": "t"})
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=4, max_delay_ms=20.0)
    engine.submit(raw_image(60, 100, 50))
    ctrl = _controller(engine)
    ctrl.engine.controller = ctrl
    for i in range(10):
        engine.hists["serve/request_time"].observe(0.5, now=float(i))
    ctrl.tick(now=10.0)
    telemetry.shutdown()
    events = load_events([str(tmp_path)])
    decisions = [e for e in events
                 if e["kind"] == "meta" and e["name"] == "slo_decision"]
    assert decisions and decisions[0]["fields"]["action"] == "tighten"
    assert decisions[0]["fields"]["bucket"]  # names the adapted bucket
    folded = aggregate(events)
    assert folded["counters"]["slo/tighten"] >= 1
    assert "slo/p99_ms" in folded["gauges"]
    # live controller state rides the /metrics payloads
    m = engine.metrics()
    assert m["controller"]["ticks"] == 1
    assert m["controller"]["target_p99_ms"] == 100.0
    assert m["policy"]  # effective per-bucket policy is visible
    summ = engine_summary(engine)
    assert "slo/target_p99_ms" in summ["gauges"]
    assert any(k.startswith("slo/bucket_") for k in summ["gauges"])
    engine.stop()


def test_controller_start_stop_restores_policy():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=4, max_delay_ms=20.0)
    key = engine.bucket_key(60, 100)
    engine.submit(raw_image(60, 100, 50))
    ctrl = _controller(engine, interval_s=30.0).start()  # no tick fires
    assert engine.controller is ctrl
    engine.set_bucket_policy(key, max_batch=1, max_delay_ms=0.0)
    engine.set_admit_limit(2)
    ctrl.stop()
    assert engine.controller is None
    assert engine.bucket_policy(key) == (4, 20.0)
    with engine._lock:
        assert engine._admit_limit is None
    engine.stop()


# -- loadgen scenarios + the SLO report ------------------------------------


def test_loadgen_schedule_profiles():
    lg = _load_script("loadgen")
    steady = lg.schedule("steady", 8, 4.0)
    assert steady == pytest.approx([i / 4.0 for i in range(8)])
    bursty = lg.schedule("bursty", 8, 4.0, burst=4)
    assert bursty == pytest.approx([0.0] * 4 + [1.0] * 4)
    # same average rate: both finish their arrivals in the same span
    assert max(bursty) <= max(steady)
    assert lg.schedule("steady", 3, 0.0) == [0.0] * 3  # burst-everything


def test_loadgen_summarize_and_assert_2xx_message():
    lg = _load_script("loadgen")
    # (status, latency_s, queue_wait_ms, error_str, t_done_s)
    results = [(200, 0.010, 5.0, None, 0.10),
               (200, 0.020, 6.0, None, 0.90),
               (503, 0.001, None, None, 0.20),
               (0, 0.5, None, "ConnectionRefusedError: x", 0.50)]
    out = lg.summarize(results, wall=1.0)
    assert out["requests"] == 4 and out["error_rate"] == 0.5
    assert out["status"] == {"0": 1, "200": 2, "503": 1}
    assert out["p50_ms"] is not None and out["imgs_per_sec"] == 2.0
    # availability excludes the shed 503 from the denominator: 2/3
    assert out["availability"] == pytest.approx(2 / 3, abs=1e-4)
    # transport error at 0.50 → first 2xx completion after it at 0.90
    assert out["time_to_recover_s"] == pytest.approx(0.4, abs=1e-3)
    msg = lg.assert_2xx_failure(results)
    assert "2/4" in msg and "1x status 503" in msg
    assert "1x transport error" in msg and "ConnectionRefusedError" in msg
    assert lg.assert_2xx_failure([(200, 0.01, 1.0, None, 0.01)]) is None
    # never hard-failed → no recovery metric; all-2xx availability is 1.0
    clean = lg.summarize([(200, 0.01, 1.0, None, 0.01)], wall=1.0)
    assert clean["availability"] == 1.0
    assert clean["time_to_recover_s"] is None


def test_perf_gate_slo_rows(tmp_path):
    pg = _load_script("perf_gate")

    def write(i, p99, err):
        doc = {"schema": "mxr_slo_report", "version": 1, "scenarios": [
            {"name": "bursty", "requests": 64, "status": {"200": 64},
             "p50_ms": 20.0, "p99_ms": p99, "error_rate": err,
             "imgs_per_sec": 30.0, "wall_s": 2.0}]}
        (tmp_path / f"SLO_r0{i}.json").write_text(json.dumps(doc))

    write(1, 50.0, 0.0)
    write(2, 52.0, 0.01)          # within threshold + slack: fine
    assert pg.main(["--dir", str(tmp_path)]) == 0
    assert pg.main(["--dir", str(tmp_path), "--check-format"]) == 0
    write(3, 120.0, 0.30)         # p99 blowup + dropped bursts
    assert pg.main(["--dir", str(tmp_path)]) == 1
    # error_rate uses the absolute slack: 0 → 0.015 alone must NOT fail
    for f in tmp_path.glob("SLO_r*.json"):
        f.unlink()
    write(1, 50.0, 0.0)
    write(2, 50.0, 0.015)
    assert pg.main(["--dir", str(tmp_path)]) == 0
