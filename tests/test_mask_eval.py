"""Mask R-CNN eval path: paste_mask oracle, COCO segm results assembly,
and the full pred_eval(with_masks=True) loop on a tiny mask model."""

import dataclasses
import json

import jax
import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import SyntheticDataset, TestLoader
from mx_rcnn_tpu.eval import Predictor, pred_eval
from mx_rcnn_tpu.eval.mask_rle import decode, encode
from mx_rcnn_tpu.eval.tester import paste_mask
from mx_rcnn_tpu.models import build_model, init_params


def test_paste_mask_geometry():
    prob = np.ones((28, 28), np.float32)
    out = paste_mask(prob, np.asarray([10, 20, 29, 49]), h=60, w=50)
    assert out.shape == (60, 50)
    assert out[20:50, 10:30].all()
    assert out.sum() == 30 * 20
    # clipped at borders
    out2 = paste_mask(prob, np.asarray([-5, -5, 9, 9]), h=20, w=20)
    assert out2[:10, :10].all() and out2.sum() == 100
    # half-on mask: left half above threshold only
    half = np.zeros((28, 28), np.float32)
    half[:, :14] = 1.0
    out3 = paste_mask(half, np.asarray([0, 0, 27, 27]), h=28, w=28)
    assert out3[:, :12].all() and not out3[:, 16:].any()


@pytest.fixture
def coco_ds(tmp_path):
    from mx_rcnn_tpu.data.coco_dataset import COCODataset

    root = tmp_path / "coco"
    (root / "annotations").mkdir(parents=True)
    (root / "val2017").mkdir()
    gt_mask = np.zeros((100, 100), np.uint8)
    gt_mask[10:50, 10:50] = 1
    ann = {
        "images": [{"id": 1, "file_name": "a.jpg", "height": 100, "width": 100}],
        "categories": [{"id": 5, "name": "cat"}],
        "annotations": [{
            "id": 1, "image_id": 1, "category_id": 5,
            "bbox": [10, 10, 40, 40], "area": 1600, "iscrowd": 0,
            "segmentation": {"size": [100, 100],
                             "counts": encode(gt_mask)["counts"]},
        }],
    }
    (root / "annotations" / "instances_val2017.json").write_text(
        json.dumps(ann))
    return COCODataset("val2017", str(root), str(root)), gt_mask


def test_evaluate_sds_perfect_mask(coco_ds):
    ds, gt_mask = coco_ds
    all_boxes = [None, [np.asarray([[10, 10, 49, 49, 0.9]], np.float32)]]
    all_masks = [None, [[encode(gt_mask)]]]
    stats = ds.evaluate_sds(all_boxes, all_masks)
    assert np.isclose(stats["bbox"]["AP"], 1.0)
    assert np.isclose(stats["segm"]["AP"], 1.0)


def test_evaluate_sds_wrong_mask(coco_ds):
    ds, gt_mask = coco_ds
    wrong = np.zeros_like(gt_mask)
    wrong[60:90, 60:90] = 1
    all_boxes = [None, [np.asarray([[10, 10, 49, 49, 0.9]], np.float32)]]
    all_masks = [None, [[encode(wrong)]]]
    stats = ds.evaluate_sds(all_boxes, all_masks)
    assert np.isclose(stats["bbox"]["AP"], 1.0)
    assert stats["segm"]["AP"] == 0.0


def _tiny_mask_cfg():
    cfg = generate_config(
        "resnet101_fpn_mask", "PascalVOC",
        TEST__RPN_PRE_NMS_TOP_N=250, TEST__RPN_POST_NMS_TOP_N=32,
        TEST__MAX_PER_IMAGE=8,
    )
    net = dataclasses.replace(cfg.network, NETWORK="resnet50",
                              FPN_ANCHOR_SCALES=(4,),
                              PIXEL_STDS=(127.0, 127.0, 127.0))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4)
    return cfg.replace(network=net, tpu=tpu)


def _tiny_mask_predictor(cfg):
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    return Predictor(model, params, cfg)


def test_pred_eval_with_masks_smoke():
    cfg = _tiny_mask_cfg()
    ds = SyntheticDataset(num_images=2, num_classes=cfg.NUM_CLASSES,
                          height=64, width=96)
    roidb = ds.gt_roidb()
    pred = _tiny_mask_predictor(cfg)
    stats = pred_eval(pred, TestLoader(roidb, cfg, batch_size=1), ds,
                      with_masks=True)
    # synthetic evaluate_sds returns box stats only, but the mask branch
    # (predict_masks + device paste + RLE) must have executed without error
    assert "bbox" in stats and "mAP" in stats["bbox"]


# ---- device paste / packed RLE (round 4: on-device mask eval) --------------


def _pack_transposed(mask: np.ndarray, hp: int) -> np.ndarray:
    """Oracle packer: (h, w) mask → the (w, hp//8) transposed LSB-first
    layout ops/mask_paste.py emits."""
    h, w = mask.shape
    mt = np.zeros((w, hp), np.uint8)
    mt[:, :h] = mask.T
    return np.packbits(mt, axis=-1, bitorder="little")


def _unpack_transposed(packed: np.ndarray, h: int, w: int) -> np.ndarray:
    return np.unpackbits(packed[:w], axis=-1,
                         bitorder="little")[:, :h].T.astype(np.uint8)


def test_rle_encode_packed_matches_oracle():
    from mx_rcnn_tpu.native import rle_encode_packed

    rng = np.random.RandomState(0)
    for h, w in [(1, 1), (7, 5), (63, 96), (100, 70), (130, 97)]:
        mask = (rng.rand(h, w) < 0.4).astype(np.uint8)
        hp = -(-h // 64) * 64
        packed = _pack_transposed(mask, hp)
        # junk columns beyond w must never be read
        packed = np.concatenate(
            [packed, np.full((3, hp // 8), 255, np.uint8)])
        assert rle_encode_packed(packed, h, w) == encode(mask)["counts"]
    for val in (0, 1):  # empty / full masks (single giant runs)
        mask = np.full((60, 40), val, np.uint8)
        assert (rle_encode_packed(_pack_transposed(mask, 64), 60, 40)
                == encode(mask)["counts"])


def test_device_paste_matches_host_oracle():
    from mx_rcnn_tpu.eval.mask_rle import decode
    from mx_rcnn_tpu.native import rle_encode_packed
    from mx_rcnn_tpu.ops.mask_paste import paste_masks

    # exact geometry cases (0/1 probabilities: no threshold ambiguity)
    ones = np.ones((1, 1, 28, 28), np.float32)
    bx = np.asarray([[[10, 20, 29, 49]]], np.float32)
    dev = _unpack_transposed(
        np.asarray(paste_masks(ones, bx, 128, 128))[0, 0], 60, 50)
    np.testing.assert_array_equal(
        dev, paste_mask(ones[0, 0], bx[0, 0], h=60, w=50))
    half = np.zeros((1, 1, 28, 28), np.float32)
    half[..., :14] = 1.0
    dev = _unpack_transposed(
        np.asarray(paste_masks(half, np.asarray([[[0, 0, 27, 27]]],
                                                np.float32), 64, 128))[0, 0],
        28, 28)
    np.testing.assert_array_equal(
        dev, paste_mask(half[0, 0], np.asarray([0, 0, 27, 27]), h=28, w=28))

    # random probabilities + boxes: cv2's float resize and the MXU matmul
    # may disagree by ~1 ulp, flipping only pixels whose interpolated value
    # sits within that of 0.5 — allow a few per mask, nothing more
    rng = np.random.RandomState(1)
    h, w, hp, wp, R = 100, 130, 128, 256, 7
    probs = rng.rand(1, R, 28, 28).astype(np.float32)
    boxes = np.zeros((1, R, 4), np.float32)
    for r in range(R):
        x1, y1 = rng.uniform(-10, w - 20), rng.uniform(-10, h - 20)
        boxes[0, r] = (x1, y1, x1 + rng.uniform(3, w), y1 + rng.uniform(3, h))
    boxes[..., 0::2] = np.clip(boxes[..., 0::2], 0, w - 1)  # im_detect clips
    boxes[..., 1::2] = np.clip(boxes[..., 1::2], 0, h - 1)
    packed = np.asarray(paste_masks(probs, boxes, hp, wp, chunk=3))
    for r in range(R):
        dev = _unpack_transposed(packed[0, r], h, w)
        ref = paste_mask(probs[0, r], boxes[0, r], h, w)
        assert np.sum(dev != ref) <= 3, r
        # and the C++/fallback encoder reproduces the device mask EXACTLY
        rle = {"size": [h, w], "counts": rle_encode_packed(packed[0, r], h, w)}
        np.testing.assert_array_equal(decode(rle), dev)


def test_paste_rle_matches_oracle():
    """The fused C++ paste+RLE (native.paste_rle) against the cv2 oracle:
    identical masks up to ulp-at-threshold pixel flips, across upscale,
    downscale, clipped and degenerate boxes."""
    from mx_rcnn_tpu.eval.mask_rle import decode
    from mx_rcnn_tpu.native import paste_rle

    rng = np.random.RandomState(2)
    h, w = 100, 130
    cases = [
        np.asarray([10.3, 20.7, 60.2, 80.9], np.float32),   # upscale
        np.asarray([5.0, 5.0, 15.0, 12.0], np.float32),     # downscale
        np.asarray([0.0, 0.0, w - 1.0, h - 1.0], np.float32),  # full frame
        np.asarray([120.0, 90.0, 129.0, 99.0], np.float32),  # corner
        np.asarray([50.0, 50.0, 50.4, 50.4], np.float32),   # sub-pixel box
    ]
    for bi, box in enumerate(cases):
        prob = rng.rand(28, 28).astype(np.float32)
        counts = paste_rle(prob, box, h, w)
        if counts is None:
            pytest.skip("native library unavailable")
        ref = paste_mask(prob, box, h, w)
        got = decode({"size": [h, w], "counts": counts})
        assert np.sum(got != ref) <= 3, (bi, box)
    # 0/1 probabilities: no threshold ambiguity, exact equality
    ones = np.ones((28, 28), np.float32)
    box = np.asarray([10, 20, 29, 49], np.float32)
    got = decode({"size": [60, 50], "counts": paste_rle(ones, box, 60, 50)})
    np.testing.assert_array_equal(got, paste_mask(ones, box, 60, 50))


def test_mask_pass_modes_agree():
    """pred_eval's three mask strategies (native C++ paste+RLE, device
    MXU paste + packed RLE, host cv2 paste) must produce the same
    detections and near-identical RLEs on the same model/batches."""
    from mx_rcnn_tpu.eval.mask_rle import decode

    class CapSDS:
        def __init__(self, ds):
            self.num_classes, self.num_images = ds.num_classes, ds.num_images
            self.cap = {}

        def evaluate_sds(self, all_boxes, all_masks):
            self.cap["boxes"], self.cap["masks"] = all_boxes, all_masks
            return {"bbox": {"mAP": 0.0}}

    cfg = _tiny_mask_cfg()
    ds = SyntheticDataset(num_images=2, num_classes=cfg.NUM_CLASSES,
                          height=64, width=96)
    roidb = ds.gt_roidb()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    caps = {}
    for mode in ("native", "device", "host"):
        c = cfg.replace(TEST=dataclasses.replace(cfg.TEST, MASK_PASTE=mode))
        imdb = CapSDS(ds)
        pred_eval(Predictor(model, params, c),
                  TestLoader(roidb, c, batch_size=1), imdb, with_masks=True)
        caps[mode] = imdb.cap
    n_masks = 0
    for other in ("device", "native"):
        for k in range(1, ds.num_classes):
            for i in range(ds.num_images):
                np.testing.assert_array_equal(caps[other]["boxes"][k][i],
                                              caps["host"]["boxes"][k][i])
                mo = caps[other]["masks"][k][i]
                mh = caps["host"]["masks"][k][i]
                assert (mo is None) == (mh is None)
                for ro, rh in zip(mo or [], mh or []):
                    assert ro["size"] == rh["size"]
                    assert np.sum(decode(ro) != decode(rh)) <= 3
                    n_masks += 1
    assert n_masks > 0  # the comparison must actually have covered masks


def test_stale_pyramid_cache_raises():
    """predict_masks_* with a token from an earlier batch must fail loudly
    (round-3 VERDICT weakness 4: silent wrong masks on reordered callers)."""
    cfg = _tiny_mask_cfg()
    ds = SyntheticDataset(num_images=2, num_classes=cfg.NUM_CLASSES,
                          height=64, width=96)
    pred = _tiny_mask_predictor(cfg)
    it = iter(TestLoader(ds.gt_roidb(), cfg, batch_size=1))
    b1, b2 = next(it), next(it)
    pred.predict(b1["images"], b1["im_info"])
    tok1 = pred.feats_token
    pred.predict(b2["images"], b2["im_info"])
    boxes = np.zeros((1, 4, 4), np.float32)
    labels = np.zeros((1, 4), np.int32)
    with pytest.raises(AssertionError, match="stale pyramid cache"):
        pred.predict_masks_cached(boxes, labels, token=tok1)
    with pytest.raises(AssertionError, match="stale pyramid cache"):
        pred.predict_masks_packed(boxes, labels, boxes, 128, 128, token=tok1)
    # the current batch's token is accepted
    out = pred.predict_masks_cached(boxes, labels, token=pred.feats_token)
    assert np.asarray(out).shape == (1, 4, 28, 28)
