"""Mask R-CNN eval path: paste_mask oracle, COCO segm results assembly,
and the full pred_eval(with_masks=True) loop on a tiny mask model."""

import dataclasses
import json

import jax
import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import SyntheticDataset, TestLoader
from mx_rcnn_tpu.eval import Predictor, pred_eval
from mx_rcnn_tpu.eval.mask_rle import decode, encode
from mx_rcnn_tpu.eval.tester import paste_mask
from mx_rcnn_tpu.models import build_model, init_params


def test_paste_mask_geometry():
    prob = np.ones((28, 28), np.float32)
    out = paste_mask(prob, np.asarray([10, 20, 29, 49]), h=60, w=50)
    assert out.shape == (60, 50)
    assert out[20:50, 10:30].all()
    assert out.sum() == 30 * 20
    # clipped at borders
    out2 = paste_mask(prob, np.asarray([-5, -5, 9, 9]), h=20, w=20)
    assert out2[:10, :10].all() and out2.sum() == 100
    # half-on mask: left half above threshold only
    half = np.zeros((28, 28), np.float32)
    half[:, :14] = 1.0
    out3 = paste_mask(half, np.asarray([0, 0, 27, 27]), h=28, w=28)
    assert out3[:, :12].all() and not out3[:, 16:].any()


@pytest.fixture
def coco_ds(tmp_path):
    from mx_rcnn_tpu.data.coco_dataset import COCODataset

    root = tmp_path / "coco"
    (root / "annotations").mkdir(parents=True)
    (root / "val2017").mkdir()
    gt_mask = np.zeros((100, 100), np.uint8)
    gt_mask[10:50, 10:50] = 1
    ann = {
        "images": [{"id": 1, "file_name": "a.jpg", "height": 100, "width": 100}],
        "categories": [{"id": 5, "name": "cat"}],
        "annotations": [{
            "id": 1, "image_id": 1, "category_id": 5,
            "bbox": [10, 10, 40, 40], "area": 1600, "iscrowd": 0,
            "segmentation": {"size": [100, 100],
                             "counts": encode(gt_mask)["counts"]},
        }],
    }
    (root / "annotations" / "instances_val2017.json").write_text(
        json.dumps(ann))
    return COCODataset("val2017", str(root), str(root)), gt_mask


def test_evaluate_sds_perfect_mask(coco_ds):
    ds, gt_mask = coco_ds
    all_boxes = [None, [np.asarray([[10, 10, 49, 49, 0.9]], np.float32)]]
    all_masks = [None, [[encode(gt_mask)]]]
    stats = ds.evaluate_sds(all_boxes, all_masks)
    assert np.isclose(stats["bbox"]["AP"], 1.0)
    assert np.isclose(stats["segm"]["AP"], 1.0)


def test_evaluate_sds_wrong_mask(coco_ds):
    ds, gt_mask = coco_ds
    wrong = np.zeros_like(gt_mask)
    wrong[60:90, 60:90] = 1
    all_boxes = [None, [np.asarray([[10, 10, 49, 49, 0.9]], np.float32)]]
    all_masks = [None, [[encode(wrong)]]]
    stats = ds.evaluate_sds(all_boxes, all_masks)
    assert np.isclose(stats["bbox"]["AP"], 1.0)
    assert stats["segm"]["AP"] == 0.0


def test_pred_eval_with_masks_smoke():
    cfg = generate_config(
        "resnet101_fpn_mask", "PascalVOC",
        TEST__RPN_PRE_NMS_TOP_N=250, TEST__RPN_POST_NMS_TOP_N=32,
        TEST__MAX_PER_IMAGE=8,
    )
    net = dataclasses.replace(cfg.network, NETWORK="resnet50",
                              FPN_ANCHOR_SCALES=(4,),
                              PIXEL_STDS=(127.0, 127.0, 127.0))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4)
    cfg = cfg.replace(network=net, tpu=tpu)
    ds = SyntheticDataset(num_images=2, num_classes=cfg.NUM_CLASSES,
                          height=64, width=96)
    roidb = ds.gt_roidb()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    pred = Predictor(model, params, cfg)
    stats = pred_eval(pred, TestLoader(roidb, cfg, batch_size=1), ds,
                      with_masks=True)
    # synthetic evaluate_sds returns box stats only, but the mask branch
    # (predict_masks + paste + RLE) must have executed without error
    assert "bbox" in stats and "mAP" in stats["bbox"]
