"""Low-precision inference variants: bf16 parity vs f32, per-dtype
zero-steady-state-recompile, int8 structural sanity.

The bf16 "variant" casts float params to bfloat16 host-side and casts
outputs back to f32 in-program; compute is already COMPUTE_DTYPE (bf16
by default), so the only delta vs the f32 path is weight storage — the
parity tolerances below pin that delta.  Parity is detection-RECORD
matching, not tensor allclose: every confident f32 detection must have
a bf16 twin (same class, score within 0.04, box within 4 px) and vice
versa, the invariant a serving swap to ``--infer-dtype bfloat16``
actually relies on.
"""

import dataclasses

import jax
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import prepare_image
from mx_rcnn_tpu.eval import Predictor
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.ops.postprocess import (decode_image_boxes,
                                         detections_to_records,
                                         per_class_nms)
from mx_rcnn_tpu.serve import ServeEngine, ServeOptions, warmup
from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

SCORE_MARGIN = 0.03   # dets this close to THRESH may flip in/out — skip
SCORE_ATOL = 0.04
BBOX_ATOL_PX = 4.0


def tiny_cfg():
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TEST__RPN_PRE_NMS_TOP_N=300, TEST__RPN_POST_NMS_TOP_N=32,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((96, 128),), MAX_GT=8)
    return cfg.replace(network=net, tpu=tpu)


def records_for(pred, cfg, img):
    """Offline path on one image, self-padded to batch 2 (the serve
    batch shape, so the engine-warmed programs are reused)."""
    prepared, im_info = prepare_image(img, cfg, cfg.tpu.SCALES[0])
    rois, valid, scores, deltas, _ = [
        np.asarray(jax.device_get(x)) for x in pred.predict(
            np.stack([prepared, prepared]), np.stack([im_info, im_info]))]
    boxes = decode_image_boxes(rois[0], deltas[0], im_info)
    return detections_to_records(per_class_nms(
        scores[0], boxes, valid[0], cfg.NUM_CLASSES,
        cfg.TEST.THRESH, cfg.TEST.NMS, cfg.TEST.MAX_PER_IMAGE))


def assert_matched(src, dst, thresh, tag):
    """Every confident det in ``src`` has a twin in ``dst``."""
    for r in src:
        if r["score"] < thresh + SCORE_MARGIN:
            continue
        twins = [s for s in dst
                 if s["cls"] == r["cls"]
                 and abs(s["score"] - r["score"]) < SCORE_ATOL
                 and np.allclose(s["bbox"], r["bbox"], atol=BBOX_ATOL_PX)]
        assert twins, (tag, r, dst)


def test_bf16_parity_and_per_dtype_steady_state():
    cfg = tiny_cfg()
    model = build_model(cfg)
    params = denormalize_for_save(
        init_params(model, cfg, jax.random.PRNGKey(0), 2, (96, 128)), cfg)

    pred32 = Predictor(model, params, cfg)
    pred16 = Predictor(model, params, cfg, dtype="bfloat16")
    assert pred32.registry.dtype == "float32"
    assert pred16.registry.dtype == "bfloat16"

    # bf16 behind a real engine: warmup readies one program per
    # orientation, steady-state traffic must add zero — per dtype
    engine = ServeEngine(pred16, cfg, ServeOptions(
        batch_size=2, max_delay_ms=5.0, max_queue=16)).start()
    try:
        assert warmup(engine) == 2
        rng = np.random.RandomState(7)
        images = [rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
                  for h, w in ((60, 100), (100, 60))]
        for img in images:
            dets = engine.submit(img, deadline_ms=0).result(timeout=300.0)
            assert isinstance(dets, list)
        assert (engine.counters["recompiles"]
                == engine.counters["warmup_programs"] == 2)
        assert engine.counters["recompiles_bfloat16"] == 2
        assert engine.metrics()["dtype"] == "bfloat16"
        assert engine.metrics()["compile"]["dtype"] == "bfloat16"

        # parity on the warmed shapes: confident detections must match
        # 1:1 between the f32 and bf16 variants, both directions
        for img in images:
            r32 = records_for(pred32, cfg, img)
            r16 = records_for(pred16, cfg, img)
            assert_matched(r32, r16, cfg.TEST.THRESH, "f32->bf16")
            assert_matched(r16, r32, cfg.TEST.THRESH, "bf16->f32")
    finally:
        engine.stop()

    # the two dtypes were separate programs end to end
    assert pred16.registry.snapshot()["programs"]
    assert all(p["dtype"] == "bfloat16"
               for p in pred16.registry.snapshot()["programs"])
    assert all(p["dtype"] == "float32"
               for p in pred32.registry.snapshot()["programs"])


def test_int8_variant_runs_and_is_finite():
    cfg = tiny_cfg()
    model = build_model(cfg)
    params = denormalize_for_save(
        init_params(model, cfg, jax.random.PRNGKey(0), 2, (96, 128)), cfg)
    pred = Predictor(model, params, cfg, dtype="int8")
    assert pred.registry.dtype == "int8"

    img = np.random.RandomState(3).randint(0, 255, (60, 100, 3),
                                           dtype=np.uint8)
    prepared, im_info = prepare_image(img, cfg, cfg.tpu.SCALES[0])
    rois, valid, scores, deltas, _ = [
        np.asarray(jax.device_get(x)) for x in pred.predict(
            np.stack([prepared, prepared]), np.stack([im_info, im_info]))]
    # weight quantization must not produce NaN/Inf anywhere downstream
    for name, arr in (("rois", rois), ("scores", scores),
                      ("deltas", deltas)):
        assert np.isfinite(arr).all(), name
    assert scores.dtype == np.float32  # outputs cast back to f32
    assert rois.shape[-1] == 4 and valid.dtype == bool
