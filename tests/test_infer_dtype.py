"""Low-precision inference variants: bf16 parity vs f32, per-dtype
zero-steady-state-recompile, int8 structural sanity.

The bf16 "variant" casts float params to bfloat16 host-side and casts
outputs back to f32 in-program; compute is already COMPUTE_DTYPE (bf16
by default), so the only delta vs the f32 path is weight storage — the
parity tolerances below pin that delta.  Parity is detection-RECORD
matching, not tensor allclose: every confident f32 detection must have
a bf16 twin (same class, score within 0.04, box within 4 px) and vice
versa, the invariant a serving swap to ``--infer-dtype bfloat16``
actually relies on.
"""

import dataclasses
import os

import jax
import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import prepare_image
from mx_rcnn_tpu.eval import Predictor
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.ops.postprocess import (decode_image_boxes,
                                         detections_to_records,
                                         per_class_nms)
from mx_rcnn_tpu.serve import ServeEngine, ServeOptions, warmup
from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

SCORE_MARGIN = 0.03   # dets this close to THRESH may flip in/out — skip
SCORE_ATOL = 0.04
BBOX_ATOL_PX = 4.0


def tiny_cfg():
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TEST__RPN_PRE_NMS_TOP_N=300, TEST__RPN_POST_NMS_TOP_N=32,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((96, 128),), MAX_GT=8)
    return cfg.replace(network=net, tpu=tpu)


def records_for(pred, cfg, img):
    """Offline path on one image, self-padded to batch 2 (the serve
    batch shape, so the engine-warmed programs are reused)."""
    prepared, im_info = prepare_image(img, cfg, cfg.tpu.SCALES[0])
    rois, valid, scores, deltas, _ = [
        np.asarray(jax.device_get(x)) for x in pred.predict(
            np.stack([prepared, prepared]), np.stack([im_info, im_info]))]
    boxes = decode_image_boxes(rois[0], deltas[0], im_info)
    return detections_to_records(per_class_nms(
        scores[0], boxes, valid[0], cfg.NUM_CLASSES,
        cfg.TEST.THRESH, cfg.TEST.NMS, cfg.TEST.MAX_PER_IMAGE))


def assert_matched(src, dst, thresh, tag):
    """Every confident det in ``src`` has a twin in ``dst``."""
    for r in src:
        if r["score"] < thresh + SCORE_MARGIN:
            continue
        twins = [s for s in dst
                 if s["cls"] == r["cls"]
                 and abs(s["score"] - r["score"]) < SCORE_ATOL
                 and np.allclose(s["bbox"], r["bbox"], atol=BBOX_ATOL_PX)]
        assert twins, (tag, r, dst)


def test_bf16_parity_and_per_dtype_steady_state():
    cfg = tiny_cfg()
    model = build_model(cfg)
    params = denormalize_for_save(
        init_params(model, cfg, jax.random.PRNGKey(0), 2, (96, 128)), cfg)

    pred32 = Predictor(model, params, cfg)
    pred16 = Predictor(model, params, cfg, dtype="bfloat16")
    assert pred32.registry.dtype == "float32"
    assert pred16.registry.dtype == "bfloat16"

    # bf16 behind a real engine: warmup readies one program per
    # orientation, steady-state traffic must add zero — per dtype
    engine = ServeEngine(pred16, cfg, ServeOptions(
        batch_size=2, max_delay_ms=5.0, max_queue=16)).start()
    try:
        assert warmup(engine) == 2
        rng = np.random.RandomState(7)
        images = [rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
                  for h, w in ((60, 100), (100, 60))]
        for img in images:
            dets = engine.submit(img, deadline_ms=0).result(timeout=300.0)
            assert isinstance(dets, list)
        assert (engine.counters["recompiles"]
                == engine.counters["warmup_programs"] == 2)
        assert engine.counters["recompiles_bfloat16"] == 2
        assert engine.metrics()["dtype"] == "bfloat16"
        assert engine.metrics()["compile"]["dtype"] == "bfloat16"

        # parity on the warmed shapes: confident detections must match
        # 1:1 between the f32 and bf16 variants, both directions
        for img in images:
            r32 = records_for(pred32, cfg, img)
            r16 = records_for(pred16, cfg, img)
            assert_matched(r32, r16, cfg.TEST.THRESH, "f32->bf16")
            assert_matched(r16, r32, cfg.TEST.THRESH, "bf16->f32")
    finally:
        engine.stop()

    # the two dtypes were separate programs end to end
    assert pred16.registry.snapshot()["programs"]
    assert all(p["dtype"] == "bfloat16"
               for p in pred16.registry.snapshot()["programs"])
    assert all(p["dtype"] == "float32"
               for p in pred32.registry.snapshot()["programs"])


def _iou(a, b):
    ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    inter = ix * iy
    area = ((a[2] - a[0]) * (a[3] - a[1])
            + (b[2] - b[0]) * (b[3] - b[1]) - inter)
    return inter / area if area > 0 else 0.0


IOU_FLOOR = 0.3


def assert_matched_iou(src, dst, thresh, tag):
    """Every confident det in ``src`` has a same-class twin in ``dst``
    at the standard score delta whose box overlaps (IoU pin)."""
    for r in src:
        if r["score"] < thresh + SCORE_MARGIN:
            continue
        twins = [s for s in dst
                 if s["cls"] == r["cls"]
                 and abs(s["score"] - r["score"]) < SCORE_ATOL
                 and _iou(s["bbox"], r["bbox"]) >= IOU_FLOOR]
        assert twins, (tag, r, dst)


def test_int8_activation_calibration_parity_and_persistence(tmp_path):
    """The real quantized path (``--infer-dtype int8-activation``):
    calibration over a held-out shard yields a positive per-tensor scale
    for the network input, the manifest round-trips through the registry
    (persisted next to the AOT markers, keyed by config digest), a
    Predictor built without explicit scales auto-loads them, detections
    stay within the pinned int8 deltas of f32 (and of the weight-only
    int8 variant), and repeat dispatch on the warmed shape adds zero
    programs per dtype."""
    cfg = tiny_cfg()
    model = build_model(cfg)
    params = denormalize_for_save(
        init_params(model, cfg, jax.random.PRNGKey(0), 2, (96, 128)), cfg)

    from mx_rcnn_tpu.compile import ProgramRegistry
    from mx_rcnn_tpu.eval.tester import calibrate_activation_scales

    rng = np.random.RandomState(5)
    shard = [rng.randint(0, 255, (60, 100, 3), dtype=np.uint8)
             for _ in range(2)]
    with pytest.raises(ValueError, match="empty"):
        calibrate_activation_scales(model, params, cfg, [])
    scales = calibrate_activation_scales(model, params, cfg, shard,
                                         max_images=1)
    assert scales["images"]["scale"] > 0.0
    assert scales["images"]["absmax"] > 0.0

    # persistence round-trip, digest-keyed next to the AOT manifest
    reg = ProgramRegistry(cfg, dtype="int8-activation",
                          cache_base=str(tmp_path))
    path = reg.save_act_scales(scales)
    assert path and os.path.exists(path)
    assert ProgramRegistry(cfg, dtype="int8-activation",
                           cache_base=str(tmp_path)).load_act_scales() \
        == scales

    # auto-load: no explicit act_scales, same cache + config digest
    pred8a = Predictor(model, params, cfg, dtype="int8-activation",
                       cache_base=str(tmp_path))
    assert pred8a.act_scales == scales
    assert pred8a.registry.dtype == "int8-activation"

    pred8 = Predictor(model, params, cfg, dtype="int8")
    img = shard[0]
    r8 = records_for(pred8, cfg, img)
    r8a = records_for(pred8a, cfg, img)
    # the fake-quant must actually engage: with a calibrated scale the
    # activation path cannot be byte-identical to weight-only int8
    assert any(abs(a["score"] - b["score"]) > 0
               for a, b in zip(r8, r8a)) or \
        any(not np.allclose(a["bbox"], b["bbox"])
            for a, b in zip(r8, r8a))
    # the pin isolates exactly what this variant ADDS: activation
    # fake-quant on top of the shared weight quantization.  Scores hold
    # the standard (bf16-grade) delta; boxes are pinned by IoU, not
    # corner atol — on RANDOM-init weights the in-graph exp(dh) box
    # regression amplifies a one-step input perturbation into tens of
    # px on a single corner while the object region (and every score)
    # stays put.  (Weight quant vs f32 flips proposal top-k outright,
    # so that pair stays the structural finiteness test below.)
    assert_matched_iou(r8, r8a, cfg.TEST.THRESH, "int8->int8a")
    assert_matched_iou(r8a, r8, cfg.TEST.THRESH, "int8a->int8")

    # zero steady-state recompiles per dtype: the warmed shape re-serves
    # from the same program
    n_prog = len(pred8a.registry.snapshot()["programs"])
    records_for(pred8a, cfg, img)
    snap = pred8a.registry.snapshot()
    assert len(snap["programs"]) == n_prog
    assert all(p["dtype"] == "int8-activation" for p in snap["programs"])


def test_int8_variant_runs_and_is_finite():
    cfg = tiny_cfg()
    model = build_model(cfg)
    params = denormalize_for_save(
        init_params(model, cfg, jax.random.PRNGKey(0), 2, (96, 128)), cfg)
    pred = Predictor(model, params, cfg, dtype="int8")
    assert pred.registry.dtype == "int8"

    img = np.random.RandomState(3).randint(0, 255, (60, 100, 3),
                                           dtype=np.uint8)
    prepared, im_info = prepare_image(img, cfg, cfg.tpu.SCALES[0])
    rois, valid, scores, deltas, _ = [
        np.asarray(jax.device_get(x)) for x in pred.predict(
            np.stack([prepared, prepared]), np.stack([im_info, im_info]))]
    # weight quantization must not produce NaN/Inf anywhere downstream
    for name, arr in (("rois", rois), ("scores", scores),
                      ("deltas", deltas)):
        assert np.isfinite(arr).all(), name
    assert scores.dtype == np.float32  # outputs cast back to f32
    assert rois.shape[-1] == 4 and valid.dtype == bool
