"""Unit coverage for the multi-host partition logic that doesn't need a
second process (the live two-process run is tests/test_multiprocess.py):
loader ``num_parts`` slicing vs the full loader, row-range math on the
single-process mesh, and the init_distributed argument guard.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import AnchorLoader, SyntheticDataset
from mx_rcnn_tpu.parallel import (assert_loader_partition, init_distributed,
                                  local_row_range, make_mesh)


def _cfg():
    cfg = generate_config("resnet50", "PascalVOC", TRAIN__FLIP=False)
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4)
    return cfg.replace(tpu=tpu)


def _batches(loader):
    return [{k: np.asarray(v) for k, v in b.items()} for b in loader]


def _assert_parts_slice_global(make_loader, n_batches: int,
                               expect_key: str = None):
    """Core partition contract: two part-loaders with the same seed yield
    exactly the row halves of the full loader's batches, in the same
    order — the lockstep-schedule invariant multi-host training rests
    on.  ``make_loader(**part_kwargs)`` builds the loader under test."""
    full = _batches(make_loader())
    p0 = _batches(make_loader(num_parts=2, part_index=0))
    p1 = _batches(make_loader(num_parts=2, part_index=1))
    assert len(full) == len(p0) == len(p1) == n_batches
    for bf, b0, b1 in zip(full, p0, p1):
        if expect_key is not None:
            assert expect_key in bf
        for k in bf:
            h = bf[k].shape[0] // 2
            np.testing.assert_array_equal(bf[k][:h], b0[k])
            np.testing.assert_array_equal(bf[k][h:], b1[k])


def test_loader_parts_slice_the_global_batches():
    cfg = _cfg()
    roidb = SyntheticDataset(num_images=12, num_classes=cfg.NUM_CLASSES,
                             height=64, width=96, seed=3).gt_roidb()
    _assert_parts_slice_global(
        lambda **kw: AnchorLoader(roidb, cfg, 4, shuffle=True, seed=7, **kw),
        n_batches=3)


def test_loader_part_validation():
    cfg = _cfg()
    roidb = SyntheticDataset(num_images=4, num_classes=cfg.NUM_CLASSES,
                             height=64, width=96, seed=0).gt_roidb()
    with pytest.raises(ValueError, match="divide"):
        AnchorLoader(roidb, cfg, 4, num_parts=3)
    with pytest.raises(ValueError, match="part_index"):
        AnchorLoader(roidb, cfg, 4, num_parts=2, part_index=2)


def test_local_row_range_single_process_covers_everything():
    plan = make_mesh(data=8)
    assert local_row_range(plan, 16) == (0, 16)
    # num_parts=1 partition trivially matches
    assert_loader_partition(plan, 16, 1, 0)
    with pytest.raises(ValueError, match="does not divide"):
        local_row_range(plan, 12)


def test_init_distributed_rejects_partial_triple():
    with pytest.raises(ValueError, match="partial --dist"):
        init_distributed(process_id=1)
    with pytest.raises(ValueError, match="partial --dist"):
        init_distributed(num_processes=2, process_id=0)
    with pytest.raises(ValueError, match="cannot be combined"):
        init_distributed(coordinator_address="h:1", num_processes=2,
                         process_id=0, auto=True)


def test_roiiter_parts_slice_the_global_batches():
    """ROIIter (the Fast-RCNN loader) partitions like AnchorLoader —
    including the per-record proposals payload."""
    from mx_rcnn_tpu.data import ROIIter

    cfg = _cfg()
    roidb = SyntheticDataset(num_images=8, num_classes=cfg.NUM_CLASSES,
                             height=64, width=96, seed=1).gt_roidb()
    rng = np.random.RandomState(0)
    for r in roidb:
        r["proposals"] = rng.rand(5, 4).astype(np.float32) * 30
    _assert_parts_slice_global(
        lambda **kw: ROIIter(roidb, cfg, 4, shuffle=True, seed=9, **kw),
        n_batches=2, expect_key="rois")


def test_global_from_local_matches_fast_path():
    """Per-shard assembly (the multi-process branch of shard_batch /
    shard_stacked_batch) must place exactly what the single-process
    device_put fast path places — checked for both the plain and the
    stacked (steps_per_dispatch) layouts on the local 8-device mesh,
    where one process owns every shard and both paths are runnable."""
    from mx_rcnn_tpu.parallel import shard_batch, shard_stacked_batch
    from mx_rcnn_tpu.parallel.distributed import global_from_local

    plan = make_mesh(data=8)
    rng = np.random.RandomState(2)
    batch = {"images": rng.rand(8, 16, 24, 3).astype(np.float32),
             "gt_boxes": rng.rand(8, 4, 4).astype(np.float32)}
    a = global_from_local(plan, batch)
    b = shard_batch(plan, batch)
    for k in batch:
        assert a[k].sharding == b[k].sharding
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))

    stacked = {k: np.stack([v, v + 1.0]) for k, v in batch.items()}
    a = global_from_local(plan, stacked, stacked=True)
    b = shard_stacked_batch(plan, stacked)
    for k in stacked:
        assert a[k].sharding == b[k].sharding
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


def test_sync_and_warm_collectives_single_process_noop():
    """sync() returns immediately single-process (without consuming
    barrier ids), and warm_collectives on a local mesh is a cached
    no-op — both sit on the fit path for every plan."""
    from mx_rcnn_tpu.parallel.distributed import (_sync_counter,
                                                  _warm_collectives_impl,
                                                  sync, warm_collectives)

    before = _sync_counter[0]
    sync("unit_test")
    assert _sync_counter[0] == before  # no-op must not advance the
    # lockstep counter: a rank-dependent advance would desync real jobs
    plan = make_mesh(data=8)
    warm_collectives(plan)
    # the cache lives on the (plan, process_count)-keyed impl since the
    # round-5 advisor fix; the public wrapper adds the count key per call
    hits_before = _warm_collectives_impl.cache_info().hits
    warm_collectives(plan)
    assert _warm_collectives_impl.cache_info().hits == hits_before + 1
