"""Spatial parallelism (sp): image height sharded over a ``space`` mesh
axis — GSPMD halo-exchanges the conv borders, the proposal/RoI stages
gather where propagation requires.  The math is mesh-layout invariant, so
a (data=2, space=4) step must match the flat (data=2) step on the same
global batch.  f32 compute: the two programs compile differently and bf16
re-fusion jitter would swamp the comparison (same rationale as
tests/test_eval_mesh.py)."""

import dataclasses

import jax
import numpy as np

from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.parallel import make_mesh, shard_batch
from mx_rcnn_tpu.train import create_train_state, make_train_step
from tests.test_train import make_batch, tiny_cfg


def test_spatial_step_matches_flat_dp():
    cfg = tiny_cfg()
    cfg = cfg.replace(tpu=dataclasses.replace(cfg.tpu,
                                              COMPUTE_DTYPE="float32"))
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    batch = make_batch(B=2)

    losses = {}
    for name, plan in (
        ("dp", make_mesh(jax.devices()[:2], data=2)),
        ("dp_sp", make_mesh(data=2, space=4)),
    ):
        state, tx, mask = create_train_state(cfg, params, steps_per_epoch=10)
        step = make_train_step(model, tx, plan=plan, trainable_mask=mask)
        state = jax.device_put(state, plan.replicated())
        run = []
        for i in range(2):
            sb = shard_batch(plan, batch)
            if plan.n_space > 1:
                # the images really are height-sharded over the space axis
                spec = sb["images"].sharding.spec
                assert "space" in str(spec), spec
            state, metrics = step(state, sb, jax.random.PRNGKey(i))
            run.append(float(jax.device_get(metrics["total_loss"])))
        losses[name] = run

    np.testing.assert_allclose(losses["dp"], losses["dp_sp"], rtol=1e-4)


def test_fpn_spatial_step_matches_flat_dp():
    """sp over the PYRAMID graph (round-3 VERDICT weakness 6): P6's extra
    downsample and the RoI one-hot level select interact with a sharded H
    axis — exactly where spatial sharding would break if any stage were
    layout-sensitive.  Same harness as the classic test: (data=2, space=4)
    must match flat (data=2) on the same global batch, f32."""
    from tests.test_fpn_mask import batch as fpn_batch, fpn_cfg

    cfg = fpn_cfg()
    # H=128: the smallest height satisfying check_spatial's thin-shard rule
    # for FPN at space=4 (C4 = H/16 must keep >= 2 rows per shard)
    cfg = cfg.replace(tpu=dataclasses.replace(cfg.tpu,
                                              COMPUTE_DTYPE="float32",
                                              SCALES=((128, 96),)))
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (128, 96))
    imgs, im_info, gtb, gtc, gtv = fpn_batch(B=2, H=128)
    batch = dict(images=np.asarray(imgs), im_info=np.asarray(im_info),
                 gt_boxes=np.asarray(gtb), gt_classes=np.asarray(gtc),
                 gt_valid=np.asarray(gtv))

    losses = {}
    for name, plan in (
        ("dp", make_mesh(jax.devices()[:2], data=2)),
        ("dp_sp", make_mesh(data=2, space=4)),
    ):
        state, tx, mask = create_train_state(cfg, params, steps_per_epoch=10)
        step = make_train_step(model, tx, plan=plan, trainable_mask=mask)
        state = jax.device_put(state, plan.replicated())
        run = []
        for i in range(2):
            sb = shard_batch(plan, batch)
            if plan.n_space > 1:
                spec = sb["images"].sharding.spec
                assert "space" in str(spec), spec
            state, metrics = step(state, sb, jax.random.PRNGKey(i))
            run.append(float(jax.device_get(metrics["total_loss"])))
        losses[name] = run

    np.testing.assert_allclose(losses["dp"], losses["dp_sp"], rtol=1e-4)


def test_check_spatial_rejects_thin_shards():
    """FPN at H=64 over space=4 would put 1 row/shard at stage 5's
    stride-2 input — the measured XLA SPMD miscompile zone; both fit()
    and Predictor must refuse the plan loudly."""
    import pytest

    from mx_rcnn_tpu.parallel import check_spatial
    from tests.test_fpn_mask import fpn_cfg

    cfg = fpn_cfg()  # SCALES ((64, 96),)
    plan = make_mesh(data=2, space=4)
    with pytest.raises(ValueError, match="image height >= 128"):
        check_spatial(plan, cfg)
    # classic body (deepest stride-2 input C3 at stride 8): H=64 admits
    # space=4, and any plan without a space axis is exempt
    check_spatial(plan, tiny_cfg())
    check_spatial(make_mesh(jax.devices()[:2], data=2), cfg)


def test_spatial_eval_matches_single_device():
    """Spatial-parallel eval: Predictor on a (data=2, space=4) mesh (image
    height sharded, params replicated) must reproduce the single-device
    im_detect outputs — the oversized-input eval path."""
    from mx_rcnn_tpu.data import SyntheticDataset, TestLoader
    from mx_rcnn_tpu.eval import Predictor, im_detect

    cfg = tiny_cfg()
    cfg = cfg.replace(
        TEST=dataclasses.replace(cfg.TEST, RPN_PRE_NMS_TOP_N=300,
                                 RPN_POST_NMS_TOP_N=32),
        tpu=dataclasses.replace(cfg.tpu, COMPUTE_DTYPE="float32",
                                SCALES=((64, 96),)))
    ds = SyntheticDataset(num_images=2, height=64, width=96)
    roidb = ds.gt_roidb()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))

    plan = make_mesh(data=2, space=4)
    single = Predictor(model, params, cfg)
    sharded = Predictor(model, params, cfg, plan=plan)

    loader = TestLoader(roidb, cfg, batch_size=2)
    batch = next(iter(loader))
    sb = sharded.batch_put(batch)
    assert "space" in str(sb["images"].sharding.spec), sb["images"].sharding
    d1 = im_detect(single, batch)
    dsp = im_detect(sharded, sb)
    for (s1, b1, v1), (s2, b2, v2) in zip(d1, dsp):
        np.testing.assert_allclose(s1, s2, rtol=2e-5, atol=2e-6)
        # 0.02 px: f32 re-association through the halo-exchanged conv path
        # (measured max 0.006 px on 1/25k coords)
        np.testing.assert_allclose(b1, b2, rtol=2e-5, atol=2e-2)
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
