"""Mesh-sharded eval (VERDICT round-2 item 8): pred_eval with a data-axis
``MeshPlan`` must match the single-device loop — the forward is SPMD over
batch rows, everything after device_get is the same host numpy.  Runs on
the 8-device virtual CPU mesh (conftest).  f32 compute: the sharded and
unsharded programs compile to different fusions, and under bf16 that
rounding jitter blows up through the head softmax (measured 0.007 score
diffs with random params); in f32 the two programs agree to ~1e-6."""

import dataclasses

import jax
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import SyntheticDataset, TestLoader
from mx_rcnn_tpu.eval import Predictor, im_detect, pred_eval
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.parallel import make_mesh


def tiny_cfg():
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TEST__RPN_PRE_NMS_TOP_N=300, TEST__RPN_POST_NMS_TOP_N=32,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((96, 128),), MAX_GT=8,
                              COMPUTE_DTYPE="float32")
    return cfg.replace(network=net, tpu=tpu)


def test_mesh_eval_matches_single_device():
    cfg = tiny_cfg()
    ds = SyntheticDataset(num_images=10, height=96, width=128)
    roidb = ds.gt_roidb()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (96, 128))

    plan = make_mesh(data=8)
    single = Predictor(model, params, cfg)
    sharded = Predictor(model, params, cfg, plan=plan)

    # per-batch forward parity: same rows, mesh vs one device
    loader = TestLoader(roidb, cfg, batch_size=8)
    batch = next(iter(loader))
    d1 = im_detect(single, batch)
    d8 = im_detect(sharded, sharded.batch_put(batch))
    assert len(d1) == len(d8) == 8
    for (s1, b1, v1), (s8, b8, v8) in zip(d1, d8):
        np.testing.assert_allclose(s1, s8, rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(b1, b8, rtol=2e-5, atol=5e-3)
        np.testing.assert_array_equal(np.asarray(v1), np.asarray(v8))

    # full pred_eval through the mesh (batch 8 = one row per device;
    # 10 images -> padded tail batch exercises batch_valid masking)
    stats1 = pred_eval(single, TestLoader(roidb, cfg, batch_size=8), ds)
    stats8 = pred_eval(sharded, TestLoader(roidb, cfg, batch_size=8), ds)
    assert abs(stats1["mAP"] - stats8["mAP"]) < 1e-6


def test_mesh_eval_mask_config_runs():
    """Mask-config pred_eval over the mesh: the sharded predict_with_feats
    + masks_from_feats path (feats pyramid sharded on batch rows, boxes/
    labels auto-placed) must run the full chunk-drain + paste + RLE loop
    and produce the same bbox stats as the single-device loop."""
    cfg = generate_config(
        "resnet101_fpn_mask", "PascalVOC",
        TEST__RPN_PRE_NMS_TOP_N=250, TEST__RPN_POST_NMS_TOP_N=32,
        TEST__MAX_PER_IMAGE=8,
    )
    net = dataclasses.replace(cfg.network, NETWORK="resnet50",
                              FPN_ANCHOR_SCALES=(4,),
                              PIXEL_STDS=(127.0, 127.0, 127.0))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4,
                              COMPUTE_DTYPE="float32")
    cfg = cfg.replace(network=net, tpu=tpu)
    ds = SyntheticDataset(num_images=4, num_classes=cfg.NUM_CLASSES,
                          height=64, width=96)
    roidb = ds.gt_roidb()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))

    plan = make_mesh(jax.devices()[:4], data=4)
    stats1 = pred_eval(Predictor(model, params, cfg),
                       TestLoader(roidb, cfg, batch_size=4), ds,
                       with_masks=True)
    stats4 = pred_eval(Predictor(model, params, cfg, plan=plan),
                       TestLoader(roidb, cfg, batch_size=4), ds,
                       with_masks=True)
    assert abs(stats1["bbox"]["mAP"] - stats4["bbox"]["mAP"]) < 1e-6

    # regression (round 3): on a SPACE mesh predict() caches a height-
    # sharded pyramid; masks_from_feats must inherit that sharding rather
    # than pin feats to batch() and reject the mismatch at dispatch.
    # space=2: the widest FPN space axis check_spatial admits at H=64
    # (thin-shard rule, parallel/mesh.py)
    sp_plan = make_mesh(jax.devices()[:4], data=2, space=2)
    stats_sp = pred_eval(Predictor(model, params, cfg, plan=sp_plan),
                         TestLoader(roidb, cfg, batch_size=2), ds,
                         with_masks=True)
    assert abs(stats1["bbox"]["mAP"] - stats_sp["bbox"]["mAP"]) < 1e-6
