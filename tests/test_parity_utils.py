"""Parity utilities: roidb bbox-target stats, proposal cache loading,
VOC result writeout, detection visualization."""

import os
import pickle

import numpy as np

from mx_rcnn_tpu.data import SyntheticDataset
from mx_rcnn_tpu.data.bbox_stats import (add_bbox_regression_targets,
                                         compute_bbox_regression_targets)
from mx_rcnn_tpu.eval.tester import vis_all_detection
from mx_rcnn_tpu.utils.load_data import load_proposals, merge_roidb


def test_compute_bbox_targets_identity():
    gt = np.asarray([[10, 10, 50, 50]], np.float32)
    cls = np.asarray([3], np.int32)
    t = compute_bbox_regression_targets(gt.copy(), gt, cls)
    assert t[0, 0] == 3
    np.testing.assert_allclose(t[0, 1:], 0.0, atol=1e-6)
    # distant roi: below fg thresh -> class 0, zero target
    far = np.asarray([[200, 200, 240, 240]], np.float32)
    t2 = compute_bbox_regression_targets(far, gt, cls)
    assert t2[0, 0] == 0 and np.all(t2[0, 1:] == 0)


def test_add_bbox_regression_targets_stats():
    ds = SyntheticDataset(num_images=8, height=120, width=160)
    roidb = ds.gt_roidb()
    rng = np.random.RandomState(0)
    for rec in roidb:
        jitter = rng.randn(*rec["boxes"].shape).astype(np.float32) * 3
        rec["proposals"] = np.clip(rec["boxes"] + jitter, 0, 159)
    means, stds = add_bbox_regression_targets(roidb, ds.num_classes)
    assert means.shape == (4,) and stds.shape == (4,)
    assert np.all(stds > 0)
    assert np.abs(means).max() < 0.5  # small jitter -> near-zero means
    for rec in roidb:
        assert "bbox_targets" in rec
        assert rec["bbox_targets"].shape[1] == 5


def test_load_proposals_roundtrip(tmp_path):
    ds = SyntheticDataset(num_images=3, height=100, width=100)
    roidb = ds.gt_roidb()
    props = [rec["boxes"] + 1.0 for rec in roidb]
    p = str(tmp_path / "props.pkl")
    with open(p, "wb") as f:
        pickle.dump(props, f)
    out = load_proposals(roidb, p)
    np.testing.assert_allclose(out[1]["proposals"], roidb[1]["boxes"] + 1.0)
    merged = merge_roidb([roidb, roidb])
    assert len(merged) == 6


def test_voc_write_results(tmp_path):
    from mx_rcnn_tpu.data.pascal_voc import PascalVOC, VOC_CLASSES

    # minimal VOCdevkit: 1 image, 1 annotation
    devkit = tmp_path / "VOCdevkit" / "VOC2007"
    (devkit / "ImageSets" / "Main").mkdir(parents=True)
    (devkit / "Annotations").mkdir()
    (devkit / "JPEGImages").mkdir()
    (devkit / "ImageSets" / "Main" / "test.txt").write_text("000001\n")
    (devkit / "Annotations" / "000001.xml").write_text("""
<annotation><size><width>100</width><height>100</height></size>
<object><name>car</name><difficult>0</difficult>
<bndbox><xmin>11</xmin><ymin>11</ymin><xmax>51</xmax><ymax>51</ymax></bndbox>
</object></annotation>""")
    import cv2
    cv2.imwrite(str(devkit / "JPEGImages" / "000001.jpg"),
                np.zeros((100, 100, 3), np.uint8))

    ds = PascalVOC("2007_test", str(tmp_path), str(tmp_path / "VOCdevkit"))
    assert ds.num_images == 1
    dets = [np.zeros((0, 5), np.float32) for _ in VOC_CLASSES]
    k_car = list(VOC_CLASSES).index("car")
    dets[k_car] = [np.asarray([[10, 10, 50, 50, 0.9]], np.float32)]
    stats = ds.evaluate_detections(dets, out_dir=str(tmp_path / "results"))
    assert np.isclose(stats["car"], 1.0)
    out = (tmp_path / "results" / "comp4_det_2007_test_car.txt").read_text()
    assert out.startswith("000001 0.900 11.0 11.0 51.0 51.0")


def test_vis_all_detection(tmp_path):
    ds = SyntheticDataset(num_images=1, num_classes=5, height=80, width=80)
    rec = ds.gt_roidb()[0]
    dets = [None] + [[np.asarray([[5, 5, 40, 40, 0.8]], np.float32)]
                     if k == 1 else np.zeros((0, 5), np.float32)
                     for k in range(1, 5)]
    dets[1] = np.asarray([[5, 5, 40, 40, 0.8]], np.float32)
    out = str(tmp_path / "vis.jpg")
    vis_all_detection(rec, dets, ds.classes, out)
    assert os.path.exists(out)
