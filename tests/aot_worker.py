"""Worker for tests/test_warmstart.py — ONE server boot over a
shared persistent program cache.

Invoked as ``python tests/aot_worker.py <cache_base>`` (mp_worker.py
pattern: env before the jax import, parseable stdout lines).  Builds the
tiny synthetic-weight serve stack from tests/test_serve.py, runs warmup
through a real ServeEngine, and prints one line the test parses:

    WARM programs=P aot_hit=H aot_miss=M warmup_programs=W wall=S

Run twice over the same ``cache_base`` this is the whole AOT warm-start
claim: the first process misses every program (cold compile, markers +
XLA executables written), the second reports ``aot_hit ==
warmup_programs`` and zero misses — every warmup "compile" was a disk
load from the cache dir the first process populated.
"""

from __future__ import annotations

import os
import sys
import time


def main(cache_base: str) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["MXR_PROGRAM_CACHE"] = cache_base
    import jax

    jax.config.update("jax_platforms", "cpu")
    import dataclasses

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.eval import Predictor
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.serve import ServeEngine, ServeOptions, warmup
    from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

    # tests/test_serve.py's tiny_cfg — MUST be identical between the two
    # boots (the config digest is part of every program key)
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TEST__RPN_PRE_NMS_TOP_N=300, TEST__RPN_POST_NMS_TOP_N=32,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((96, 128),), MAX_GT=8)
    cfg = cfg.replace(network=net, tpu=tpu)

    model = build_model(cfg)
    params = denormalize_for_save(
        init_params(model, cfg, jax.random.PRNGKey(0), 1, (96, 128)), cfg)
    pred = Predictor(model, params, cfg)
    assert pred.registry.owns_cache, "MXR_PROGRAM_CACHE should be honored"

    t0 = time.perf_counter()
    engine = ServeEngine(pred, cfg, ServeOptions(
        batch_size=1, max_delay_ms=1.0, max_queue=8)).start()
    try:
        warmup(engine)
    finally:
        engine.stop()
    wall = time.perf_counter() - t0

    c = pred.registry.counters
    print(f"WARM programs={c['programs']} aot_hit={c['aot_hit']} "
          f"aot_miss={c['aot_miss']} "
          f"warmup_programs={engine.counters['warmup_programs']} "
          f"wall={wall:.3f}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1])
