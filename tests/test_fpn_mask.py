"""FPN + Mask R-CNN graph tests and mask-target oracle tests."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.ops.mask_target import mask_targets_for_rois


def fpn_cfg(mask=False):
    cfg = generate_config(
        "resnet101_fpn_mask" if mask else "resnet50_fpn", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=500, TRAIN__RPN_POST_NMS_TOP_N=64,
        TRAIN__BATCH_ROIS=16,
        TEST__RPN_PRE_NMS_TOP_N=250, TEST__RPN_POST_NMS_TOP_N=32,
    )
    net = dataclasses.replace(cfg.network, FPN_ANCHOR_SCALES=(4,),
                              NETWORK="resnet50",
                              PIXEL_STDS=(127.0, 127.0, 127.0))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4)
    return cfg.replace(network=net, tpu=tpu)


def batch(B=2, H=64, W=96, G=4, seed=0, masks=False):
    rng = np.random.RandomState(seed)
    imgs = jnp.asarray(rng.randn(B, H, W, 3), jnp.float32)
    im_info = jnp.tile(jnp.asarray([[H, W, 1.0]], jnp.float32), (B, 1))
    gtb = np.zeros((B, G, 4), np.float32)
    gtv = np.zeros((B, G), bool)
    gtc = np.zeros((B, G), np.int32)
    for b in range(B):
        for g in range(2):
            x1, y1 = rng.randint(0, W - 40), rng.randint(0, H - 40)
            gtb[b, g] = (x1, y1, x1 + rng.randint(16, 39), y1 + rng.randint(16, 39))
            gtc[b, g] = rng.randint(1, 21)
            gtv[b, g] = True
    out = [imgs, im_info, jnp.asarray(gtb), jnp.asarray(gtc), jnp.asarray(gtv)]
    if masks:
        gm = np.zeros((B, G, 112, 112), np.float32)
        gm[:, :, :, :56] = 1.0  # left half of every gt box
        out.append(jnp.asarray(gm))
    return out


def test_fpn_train_graph_and_grads():
    cfg = fpn_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 2, (64, 96))
    assert "neck" in params and "lateral2" in params["neck"]
    imgs, im_info, gtb, gtc, gtv = batch()

    def loss_fn(p, k):
        return model.apply({"params": p}, imgs, im_info, gtb, gtc, gtv, k,
                           rngs={"dropout": k})

    (tot, aux), grads = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))(
        params, jax.random.PRNGKey(1))
    assert np.isfinite(float(tot))
    labels = np.asarray(aux["rpn_label"])
    assert (labels == 1).any() and (labels == 0).any()
    gn = float(jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2)
                            for g in jax.tree.leaves(grads))))
    assert np.isfinite(gn) and gn > 0


def test_fpn_predict_shapes():
    cfg = fpn_cfg()
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 2, (64, 96))
    imgs, im_info, *_ = batch()
    rois, valid, cls_prob, deltas, scores = jax.jit(
        lambda p: model.apply({"params": p}, imgs, im_info,
                              method=model.predict))(params)
    R, K = cfg.TEST.RPN_POST_NMS_TOP_N, cfg.NUM_CLASSES
    assert rois.shape == (2, R, 4)
    assert cls_prob.shape == (2, R, K)
    assert deltas.shape == (2, R, 4 * K)
    assert np.asarray(valid).any()
    np.testing.assert_allclose(np.asarray(cls_prob).sum(-1), 1.0, atol=1e-3)


def test_mask_train_graph():
    cfg = fpn_cfg(mask=True)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 2, (64, 96))
    assert "mask_head" in params
    imgs, im_info, gtb, gtc, gtv, gm = batch(masks=True)

    tot, aux = jax.jit(lambda p, k: model.apply(
        {"params": p}, imgs, im_info, gtb, gtc, gtv, k, gt_masks=gm,
        rngs={"dropout": k}))(params, jax.random.PRNGKey(1))
    assert np.isfinite(float(tot))
    assert "mask_loss" in aux and np.isfinite(float(aux["mask_loss"]))

    # predict_masks path
    boxes = gtb
    labels = gtc
    probs = jax.jit(lambda p: model.apply(
        {"params": p}, imgs, im_info, boxes, labels,
        method=model.predict_masks))(params)
    assert probs.shape == (2, 4, 28, 28)
    p = np.asarray(probs)
    assert (p >= 0).all() and (p <= 1).all()


def test_fpn_stage_graphs():
    """Alternate-training stage graphs on the FPN model (rpn_train /
    predict_rpn / rcnn_train)."""
    cfg = fpn_cfg()
    model = build_model(cfg)
    key = jax.random.PRNGKey(2)
    params = init_params(model, cfg, key, 2, (64, 96))
    imgs, im_info, gtb, gtc, gtv = batch()

    tot, aux = jax.jit(lambda p, k: model.apply(
        {"params": p}, imgs, im_info, gtb, gtv, k,
        method=model.rpn_train))(params, key)
    assert np.isfinite(float(tot)) and float(aux["rpn_cls_loss"]) > 0

    rois, _, rvalid = jax.jit(lambda p: model.apply(
        {"params": p}, imgs, im_info, method=model.predict_rpn))(params)
    tot2, aux2 = jax.jit(lambda p, k: model.apply(
        {"params": p}, imgs, im_info, rois, rvalid, gtb, gtc, gtv, k,
        rngs={"dropout": k}, method=model.rcnn_train))(params, key)
    assert np.isfinite(float(tot2)) and float(aux2["rcnn_cls_loss"]) > 0


# --- mask target oracle ------------------------------------------------------

def test_mask_targets_identity_roi():
    """RoI == gt box → target is the (downsampled) gt mask."""
    gm = np.zeros((2, 112, 112), np.float32)
    gm[0, :, :56] = 1.0          # left half
    gt_boxes = jnp.asarray([[10., 10., 50., 50.], [0., 0., 20., 20.]])
    rois = jnp.asarray([[10., 10., 50., 50.]])
    t = mask_targets_for_rois(jnp.asarray(gm), gt_boxes, rois,
                              jnp.asarray([0]), out_size=28)
    t = np.asarray(t[0])
    assert t[:, :13].mean() > 0.95     # left ~half on
    assert t[:, 15:].mean() < 0.05     # right ~half off


def test_mask_targets_shifted_roi():
    """RoI covering only the right half of the gt box → all zeros."""
    gm = np.zeros((1, 112, 112), np.float32)
    gm[0, :, :56] = 1.0
    gt_boxes = jnp.asarray([[0., 0., 100., 100.]])
    rois = jnp.asarray([[50., 0., 100., 100.]])   # right half
    t = mask_targets_for_rois(jnp.asarray(gm), gt_boxes, rois,
                              jnp.asarray([0]), out_size=28)
    assert np.asarray(t).mean() < 0.05
    rois2 = jnp.asarray([[0., 0., 50., 100.]])    # left half: all ones
    t2 = mask_targets_for_rois(jnp.asarray(gm), gt_boxes, rois2,
                               jnp.asarray([0]), out_size=28)
    assert np.asarray(t2).mean() > 0.9


def test_mask_targets_outside_gt_box():
    """RoI fully outside the gt box samples nothing."""
    gm = np.ones((1, 112, 112), np.float32)
    gt_boxes = jnp.asarray([[0., 0., 20., 20.]])
    rois = jnp.asarray([[60., 60., 90., 90.]])
    t = mask_targets_for_rois(jnp.asarray(gm), gt_boxes, rois,
                              jnp.asarray([0]), out_size=28)
    assert np.asarray(t).sum() == 0


def test_mask_targets_separable_matches_gather_oracle():
    """The round-4 einsum form must reproduce the original per-pixel
    4-gather sampler (kept as `_sample_gather`) — float values to ulp
    noise and thresholded binaries exactly (random data puts nothing at
    the 0.5 boundary)."""
    from mx_rcnn_tpu.ops.mask_target import _lerp_weights, _sample_gather

    rng = np.random.RandomState(7)
    G, S, R, OUT = 5, 112, 24, 28
    gm = (rng.rand(G, S, S) > 0.4).astype(np.float32)
    gtb = np.stack([rng.uniform(0, 80, G), rng.uniform(0, 60, G),
                    rng.uniform(90, 180, G), rng.uniform(70, 120, G)],
                   axis=1).astype(np.float32)
    rois = np.stack([rng.uniform(-20, 100, R), rng.uniform(-20, 80, R),
                     rng.uniform(110, 220, R), rng.uniform(90, 160, R)],
                    axis=1).astype(np.float32)
    gi = rng.randint(0, G, R)

    # re-derive the shared grid exactly as mask_targets_for_rois does
    box = gtb[gi]
    bw = np.maximum(box[:, 2] - box[:, 0], 1e-3)
    bh = np.maximum(box[:, 3] - box[:, 1], 1e-3)
    ys = (np.arange(OUT, dtype=np.float32) + 0.5) / OUT
    gy = rois[:, 1:2] + ys[None, :] * (rois[:, 3:4] - rois[:, 1:2])
    gx = rois[:, 0:1] + ys[None, :] * (rois[:, 2:3] - rois[:, 0:1])
    my = (gy - box[:, 1:2]) / bh[:, None] * S - 0.5
    mx = (gx - box[:, 0:1]) / bw[:, None] * S - 0.5
    masks = jnp.asarray(gm[gi])

    want = np.asarray(_sample_gather(masks, jnp.asarray(my), jnp.asarray(mx),
                                     OUT, S))
    wy = _lerp_weights(jnp.asarray(my), S)
    wx = _lerp_weights(jnp.asarray(mx), S)
    got = np.asarray(jnp.einsum("rqx,rpx->rpq", wx,
                                jnp.einsum("rpy,ryx->rpx", wy, masks)))
    np.testing.assert_allclose(got, want, atol=1e-5)
    np.testing.assert_array_equal(got >= 0.5, want >= 0.5)

    full = np.asarray(mask_targets_for_rois(
        jnp.asarray(gm), jnp.asarray(gtb), jnp.asarray(rois),
        jnp.asarray(gi), out_size=OUT))
    np.testing.assert_array_equal(full, (want >= 0.5).astype(np.float32))
