"""cfg.tpu.REMAT_BACKBONE (the B>=16 HBM lever): nn.remat on the ResNet
stages must be numerically transparent — identical param tree, identical
forward, matching gradients — so the bench A/B measures memory-system
effects only."""

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.models.backbones import ResNetConv


def test_remat_backbone_is_transparent():
    x = np.random.RandomState(0).randn(1, 64, 96, 3).astype(np.float32)
    base = ResNetConv(depth="resnet50", dtype=jnp.float32)
    rem = ResNetConv(depth="resnet50", dtype=jnp.float32, remat=True)
    v0 = base.init(jax.random.PRNGKey(0), x)
    v1 = rem.init(jax.random.PRNGKey(0), x)
    # identical tree structure AND values (remat is a lifted transform —
    # scope names pass through, init draws the same keys)
    jax.tree.map(np.testing.assert_array_equal, v0, v1)

    y0 = base.apply(v0, x)
    y1 = rem.apply(v0, x)
    np.testing.assert_array_equal(np.asarray(y0), np.asarray(y1))

    def loss(variables, model):
        return jnp.sum(model.apply(variables, x) ** 2)

    g0 = jax.grad(loss)(v0, base)
    g1 = jax.grad(loss)(v0, rem)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-5, atol=1e-6),
        g0, g1)
