"""Proposal op and ROIAlign vs oracles."""

import jax
import numpy as np
import jax.numpy as jnp

from mx_rcnn_tpu.ops.anchors import generate_anchors, all_anchors
from mx_rcnn_tpu.ops.proposal import propose
from mx_rcnn_tpu.ops.roi_align import roi_align, roi_pool
from tests import oracles


def test_propose_matches_oracle(rng):
    fh, fw, stride = 6, 8, 16
    anchors = all_anchors(fh, fw, stride, generate_anchors())
    n = len(anchors)
    scores = rng.rand(n).astype(np.float32)
    deltas = (rng.randn(n, 4) * 0.1).astype(np.float32)
    im_h, im_w, im_scale = fh * stride, fw * stride, 1.0

    rois, rscores, valid = propose(
        jnp.asarray(scores), jnp.asarray(deltas), jnp.asarray(anchors),
        jnp.float32(im_h), jnp.float32(im_w), jnp.float32(im_scale),
        pre_nms_top_n=200, post_nms_top_n=50, nms_thresh=0.7, min_size=16)

    want_boxes, want_scores = oracles.propose_oracle(
        scores, deltas, anchors, im_h, im_w, im_scale, 200, 50, 0.7, 16)

    got_boxes = np.asarray(rois)[np.asarray(valid)]
    got_scores = np.asarray(rscores)[np.asarray(valid)]
    assert len(got_boxes) == len(want_boxes)
    np.testing.assert_allclose(got_scores, want_scores, rtol=1e-5)
    np.testing.assert_allclose(got_boxes, want_boxes, rtol=1e-3, atol=1e-2)


def test_propose_min_size_filters_everything():
    anchors = all_anchors(4, 4, 16, generate_anchors())
    n = len(anchors)
    scores = np.ones(n, np.float32)
    # shrink every box to a point
    deltas = np.zeros((n, 4), np.float32)
    deltas[:, 2:] = -10.0  # log-space shrink
    rois, rscores, valid = propose(
        jnp.asarray(scores), jnp.asarray(deltas), jnp.asarray(anchors),
        jnp.float32(64), jnp.float32(64), jnp.float32(1.0),
        pre_nms_top_n=100, post_nms_top_n=10, nms_thresh=0.7, min_size=16)
    assert not np.asarray(valid).any()


def test_roi_align_matches_oracle(rng):
    feat = rng.rand(16, 20, 3).astype(np.float32)
    rois = np.array([
        [0, 0, 100, 100],
        [32, 16, 200, 150],
        [10, 10, 40, 250],
    ], np.float32)
    got = roi_align(jnp.asarray(feat), jnp.asarray(rois),
                    spatial_scale=1 / 16, pooled_size=7, sampling_ratio=2)
    want = oracles.roi_align_oracle(feat, rois, 1 / 16, 7, 2)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_roi_align_constant_feature(rng):
    feat = np.full((10, 10, 1), 3.5, np.float32)
    rois = np.array([[16, 16, 120, 120]], np.float32)
    got = np.asarray(roi_align(jnp.asarray(feat), jnp.asarray(rois),
                               spatial_scale=1 / 16, pooled_size=7))
    np.testing.assert_allclose(got, 3.5, rtol=1e-5)


def test_roi_pool_max_ge_avg(rng):
    feat = rng.rand(12, 12, 4).astype(np.float32)
    rois = np.array([[0, 0, 100, 100], [30, 30, 160, 160]], np.float32)
    avg = np.asarray(roi_align(jnp.asarray(feat), jnp.asarray(rois), spatial_scale=1 / 16))
    mx = np.asarray(roi_pool(jnp.asarray(feat), jnp.asarray(rois), spatial_scale=1 / 16))
    assert (mx >= avg - 1e-5).all()


def test_roi_align_separable_matches_gather(rng):
    """The separable-einsum formulation (production avg path) must equal the
    dense-gather formulation for every sampling ratio, including RoIs that
    hang off the feature map (out-of-range samples contribute 0) and
    degenerate boxes (min-1px clamp)."""
    from mx_rcnn_tpu.ops.roi_align import _roi_align_gather

    feat = jnp.asarray(rng.randn(24, 32, 8), jnp.float32)
    rois = jnp.asarray(
        [[0, 0, 100, 100], [37, 21, 300, 240], [450, 350, 520, 400],
         [-40, -40, 5, 5], [100, 100, 101, 101], [-500, -500, -400, -400]],
        jnp.float32)
    for sampling in (1, 2, 3):
        got = roi_align(feat, rois, spatial_scale=1 / 16.0, pooled_size=7,
                        sampling_ratio=sampling, mode="avg")
        want = _roi_align_gather(feat, rois, 1 / 16.0, 7, sampling, "avg")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)


def test_roi_align_separable_grad_matches_gather(rng):
    """Backward parity: d(sum(crop²))/d(feat) of the einsum path must match
    the gather path's scatter-add gradient."""
    from mx_rcnn_tpu.ops.roi_align import _roi_align_gather

    feat = jnp.asarray(rng.randn(16, 20, 4), jnp.float32)
    rois = jnp.asarray([[0, 0, 100, 100], [37, 21, 300, 240],
                        [-20, -20, 10, 10]], jnp.float32)

    def loss(fn):
        return lambda f: jnp.sum(fn(f) ** 2)

    g_new = jax.grad(loss(lambda f: roi_align(
        f, rois, spatial_scale=1 / 16.0, pooled_size=7, sampling_ratio=2)))(feat)
    g_old = jax.grad(loss(lambda f: _roi_align_gather(
        f, rois, 1 / 16.0, 7, 2, "avg")))(feat)
    np.testing.assert_allclose(np.asarray(g_new), np.asarray(g_old),
                               rtol=1e-4, atol=1e-5)


def test_roi_align_sampling_ratio_1_matches_general_path(rng):
    """The sampling_ratio==1 fast path (the production default,
    ROI_SAMPLING_RATIO=1) must equal the general grid-then-reduce path."""
    import jax.numpy as jnp

    from mx_rcnn_tpu.ops.roi_align import _bilinear, _roi_sample_grid, roi_align

    feat = jnp.asarray(rng.randn(24, 32, 8), jnp.float32)
    rois = jnp.asarray(
        [[0, 0, 100, 100], [37, 21, 300, 240], [450, 350, 520, 400],
         [-10, -10, 5, 5]], jnp.float32)
    fast = roi_align(feat, rois, spatial_scale=1 / 16.0, pooled_size=7,
                     sampling_ratio=1)

    def general_one(roi):  # the pre-fast-path computation, inlined
        ys, xs = _roi_sample_grid(roi, 1 / 16.0, 7, 1)
        return _bilinear(feat, ys, xs).mean(axis=(2, 3))

    ref = jax.vmap(general_one)(rois)
    # jitted vs non-jitted f32 fusion rounding differs by ~2e-6
    np.testing.assert_allclose(np.asarray(fast), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    # and max mode is identical at one sample per bin
    fast_max = roi_align(feat, rois, spatial_scale=1 / 16.0, pooled_size=7,
                         sampling_ratio=1, mode="max")
    np.testing.assert_allclose(np.asarray(fast), np.asarray(fast_max))


def _roi_pool_exact_oracle(feat, rois, spatial_scale, pooled):
    """Direct numpy transcription of the reference integer-binned max
    ROIPooling loop (MXNet roi_pooling.cu semantics: rounded inclusive
    corners, floor/ceil integer bins, plain max, empty bin -> 0)."""
    H, W, C = feat.shape
    out = np.zeros((len(rois), pooled, pooled, C), feat.dtype)

    def rnd(v):  # C roundf: half away from zero, f32 operand
        v = np.float32(v)
        return int(np.sign(v) * np.floor(np.abs(v) + np.float32(0.5)))

    for r, roi in enumerate(rois):
        x1 = rnd(roi[0] * np.float32(spatial_scale))
        y1 = rnd(roi[1] * np.float32(spatial_scale))
        x2 = rnd(roi[2] * np.float32(spatial_scale))
        y2 = rnd(roi[3] * np.float32(spatial_scale))
        rw = max(x2 - x1 + 1, 1)
        rh = max(y2 - y1 + 1, 1)
        # exact integer bins (the kernel's f32 arithmetic agrees except
        # for its last-bin ulp quirk — documented non-reproduced
        # deviation, see ops/roi_align.py:_exact_axis_mask)
        for p in range(pooled):
            hs = min(max(p * rh // pooled + y1, 0), H)
            he = min(max(-((-(p + 1) * rh) // pooled) + y1, 0), H)
            for q in range(pooled):
                ws = min(max(q * rw // pooled + x1, 0), W)
                we = min(max(-((-(q + 1) * rw) // pooled) + x1, 0), W)
                if he > hs and we > ws:
                    out[r, p, q] = feat[hs:he, ws:we].reshape(-1, C).max(axis=0)
    return out


def test_roi_pool_exact_matches_reference_loop(rng):
    feat = rng.randn(19, 31, 8).astype(np.float32)
    rois = np.stack([
        rng.uniform(0, 31 * 16, 40), rng.uniform(0, 19 * 16, 40),
        rng.uniform(0, 31 * 16, 40), rng.uniform(0, 19 * 16, 40),
    ], axis=1).astype(np.float32)
    rois[:, 2:] = np.maximum(rois[:, 2:], rois[:, :2])  # x2>=x1, y2>=y1
    rois[0] = [5.0, 5.0, 5.0, 5.0]            # degenerate 1-cell box
    rois[1] = [-200.0, -200.0, -50.0, -50.0]  # fully clipped -> zeros
    rois[2] = [0.0, 0.0, 30.0, 30.0]          # tiny: overlapping bins
    got = np.asarray(roi_align(jnp.asarray(feat), jnp.asarray(rois),
                               spatial_scale=1.0 / 16, pooled_size=7,
                               mode="exact"))
    want = _roi_pool_exact_oracle(feat, rois, 1.0 / 16, 7)
    np.testing.assert_array_equal(got, want)
    assert (got[1] == 0).all()  # clipped RoI: every bin empty -> 0


def test_roi_pool_exact_through_detector_cfg():
    """ROI_MODE='exact' flows through the generate_config override path
    (the CLI's --cfg syntax) and the full train graph runs with it (the
    transplant escape hatch is usable end-to-end, not just as a bare op)."""
    import dataclasses

    from tests.test_detector import tiny_cfg, batch
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.config import generate_config

    # the string-override route the CLI uses
    assert generate_config("resnet50", "PascalVOC",
                           tpu__ROI_MODE="exact").tpu.ROI_MODE == "exact"
    cfg = tiny_cfg()
    cfg = cfg.replace(tpu=dataclasses.replace(cfg.tpu, ROI_MODE="exact"))
    model = build_model(cfg)
    imgs, im_info, gtb, gtc, gtv = batch()
    params = init_params(model, cfg, jax.random.PRNGKey(0), 2, (128, 192))
    total, aux = model.apply({"params": params}, imgs, im_info, gtb, gtc,
                             gtv, jax.random.PRNGKey(1),
                             rngs={"dropout": jax.random.PRNGKey(2)})
    assert np.isfinite(float(total))
