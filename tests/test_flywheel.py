"""Data flywheel (ISSUE 13): capture → mine → replay → hot reload.

Four layers, mirroring the subsystem split:

* **Capture** — sampling stride exactness, atomic shard pairs, ring
  bound, byte-budget rotation, and the NULL-sink zero-overhead pin (a
  capture-off engine that ever reaches the sink RAISES).
* **Mine** — hardness ranking, top-K manifest with provenance, digest
  idempotence, SIGTERM-mid-mine atomicity (only a ``.tmp`` left behind).
* **Replay** — ReplayDataset coordinate/threshold contract, loader
  mixing that is bit-reproducible at a seed including mid-epoch
  ``--auto-resume``, and chaos: a corrupt/truncated shard lands in the
  PR-2 bad-record substitution path (counted, bounded by the systemic
  limit).
* **Closed loop** — serve traffic through a real engine with capture on,
  mine it, train one replay-mixed epoch to a checkpoint, and hot-reload
  a serving engine off that checkpoint with a strictly increasing
  generation — the whole loop on CPU, no accelerator.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.data import AnchorLoader, SyntheticDataset
from mx_rcnn_tpu.data.replay import ReplayDataset, load_replay_pixels
from mx_rcnn_tpu.flywheel import (NULL_CAPTURE, CaptureOptions, FlywheelLoop,
                                  RequestCapture, load_manifest, mine_shards,
                                  write_manifest)
from mx_rcnn_tpu.flywheel.capture import list_shards, score_stats
from mx_rcnn_tpu.flywheel.miner import ENV_MINE_PAUSE_S, hardness
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.serve import ServeEngine, ServeOptions
from mx_rcnn_tpu.serve import replica as rp
from mx_rcnn_tpu.telemetry.report import (FLYWHEEL_COUNTERS, aggregate,
                                          load_events, render_table)
from mx_rcnn_tpu.train import fit
from tests.faults import flywheel_fault_env
from tests.replica_worker import FakeServePredictor
from tests.test_loader_workers import (assert_batches_equal, snapshot,
                                       tiny_cfg as loader_cfg,
                                       tiny_roidb)
from tests.test_serve import make_engine, raw_image
from tests.test_serve import tiny_cfg as serve_cfg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def synth_dets(rng, n, lo=0.1, hi=0.9):
    """n score-sorted detection records in ORIGINAL image coords."""
    scores = np.sort(rng.uniform(lo, hi, n))[::-1]
    return [{"cls": 1, "score": float(s),
             "bbox": [4.0, 6.0, 60.0, 50.0]} for s in scores]


def fill_capture(tmp_path, n=10, shard_records=4, sample_every=1,
                 env=None, **opts):
    """A capture dir with n submitted records, spilled and closed."""
    d = str(tmp_path / "capture")
    cap = RequestCapture(CaptureOptions(
        capture_dir=d, sample_every=sample_every,
        shard_records=shard_records, **opts), env=env)
    rng = np.random.RandomState(0)
    for i in range(n):
        px = rng.randint(0, 255, (64, 96, 3), dtype=np.uint8)
        cap.record_batch(
            [(px, (60, 90), (120, 180), synth_dets(rng, 4))], generation=3)
    cap.close()
    return d, cap


# -- capture ---------------------------------------------------------------


def test_null_capture_raises_and_capture_off_engine_never_records():
    """The zero-overhead pin: the NULL sink raises on record, and a
    capture-off engine serves a full batch without ever reaching it —
    surviving the round trip IS the proof the hot path did no capture
    work."""
    with pytest.raises(RuntimeError, match="disabled"):
        NULL_CAPTURE.record_batch([], 0)
    engine = make_engine(serve_cfg()).start()
    try:
        assert engine.capture is NULL_CAPTURE
        dets = engine.submit(raw_image(60, 100, 40)).result(timeout=30.0)
        assert dets
        assert "flywheel" not in engine.metrics()
    finally:
        engine.stop()


def test_capture_sampling_stride_and_shard_pairs(tmp_path):
    """sample_every=3 over 10 submits captures exactly ceil(10/3)=4
    records (counter stride, not probabilistic) and spills complete
    npz+jsonl pairs whose rows name their pixel keys."""
    d, cap = fill_capture(tmp_path, n=10, shard_records=2, sample_every=3)
    m = cap.metrics()
    assert m["captured"] == 4 and m["sampled_out"] == 6
    assert m["sample_every"] == 3 and m["dropped"] == 0
    shards = list_shards(d)
    assert len(shards) == 2 and m["shards"] == 2
    rows = []
    for sh in shards:
        with open(sh["jsonl"]) as fh:
            rows.extend(json.loads(line) for line in fh)
        with np.load(sh["npz"]) as npz:
            for row in rows[-1:]:
                px = npz[row["key"]]
                assert px.dtype == np.uint8 and px.shape == (64, 96, 3)
    assert [r["rid"] for r in rows] == [0, 1, 2, 3]
    for r in rows:
        assert r["raw_hw"] == [60, 90] and r["orig_hw"] == [120, 180]
        assert r["generation"] == 3
        assert r["stats"]["count"] == 4
        assert len(r["detections"]) == 4


def test_capture_byte_budget_rotates_oldest(tmp_path):
    """A tiny byte budget keeps only the newest shard pairs; rotation
    never deletes the shard just written."""
    one_shard = fill_capture(tmp_path / "probe", n=4, shard_records=4)[1]
    nbytes = one_shard.metrics()["spilled_bytes"]
    d, cap = fill_capture(tmp_path, n=16, shard_records=4,
                          byte_budget=2 * nbytes)
    shards = list_shards(d)
    assert 1 <= len(shards) <= 2          # 4 spilled, oldest rotated out
    assert cap.metrics()["shards"] == 4
    # the newest shard survived and still parses
    with open(shards[-1]["jsonl"]) as fh:
        assert [json.loads(ln)["rid"] for ln in fh] == [12, 13, 14, 15]


def test_score_stats_and_hardness_signals():
    flat = score_stats([{"score": 0.5}, {"score": 0.5}, {"score": 0.5}])
    peaked = score_stats([{"score": 0.9}, {"score": 0.01}, {"score": 0.01}])
    assert flat["entropy"] == pytest.approx(1.0)       # maximally confused
    assert peaked["entropy"] < flat["entropy"]
    assert flat["bands"]["0.3"] == 3 and flat["bands"]["0.7"] == 0
    h_flat, sig = hardness(flat)
    h_peak, _ = hardness(peaked)
    assert h_flat > h_peak                              # flat scores = hard
    assert sig["disagreement"] == pytest.approx(1.0)    # all die at 0.7
    assert score_stats([]) == {"count": 0, "max_score": 0.0,
                               "mean_score": 0.0, "entropy": 0.0,
                               "bands": {"0.3": 0, "0.5": 0, "0.7": 0}}


# -- mine ------------------------------------------------------------------


def test_mine_ranks_topk_with_provenance_and_idempotent_digest(tmp_path):
    d, _ = fill_capture(tmp_path, n=10, shard_records=4)
    entries, scanned, skipped = mine_shards(d, top_k=5, min_label_score=0.3)
    assert scanned == 10 and len(entries) == 5
    scores = [e["hardness"] for e in entries]
    assert scores == sorted(scores, reverse=True)       # hardest first
    for e in entries:
        assert e["shard"].endswith(".jsonl") and e["key"].startswith("r")
        assert e["generation"] == 3
        assert set(e["signals"]) == {"entropy", "disagreement", "low_max"}
    p1 = write_manifest(d, entries, scanned, 5, min_label_score=0.3)
    p2 = write_manifest(d, entries, scanned, 5, min_label_score=0.3)
    assert p1 == p2 and os.path.basename(p1).startswith("mined-")
    doc = load_manifest(p1)
    assert doc["schema"] == "mxr_mined_manifest"
    assert doc["total_scanned"] == 10 and len(doc["entries"]) == 5


def test_trace_id_provenance_capture_to_manifest_round_trip(tmp_path):
    """ISSUE-16 provenance: a trace id riding the capture entry (the
    engine's 5-tuple with tracing on) lands in the shard row's meta and
    survives mining into the manifest entry — so a mined hard example
    points back at its originating request's span tree."""
    d = str(tmp_path / "capture")
    cap = RequestCapture(CaptureOptions(capture_dir=d, sample_every=1,
                                        shard_records=2))
    rng = np.random.RandomState(0)
    px = rng.randint(0, 255, (64, 96, 3), dtype=np.uint8)
    tid = "ab" * 16
    cap.record_batch([(px, (60, 90), (120, 180), synth_dets(rng, 4), tid)],
                     generation=3)
    # untraced entries (the 4-tuple back-compat shape) stay untagged
    cap.record_batch([(px, (60, 90), (120, 180), synth_dets(rng, 4))],
                     generation=3)
    cap.close()
    rows = []
    for sh in list_shards(d):
        with open(sh["jsonl"]) as fh:
            rows.extend(json.loads(line) for line in fh)
    assert rows[0]["trace_id"] == tid
    assert "trace_id" not in rows[1]
    entries, scanned, _ = mine_shards(d, top_k=2, min_label_score=0.3)
    assert scanned == 2
    by_key = {e["key"]: e for e in entries}
    assert by_key[rows[0]["key"]]["trace_id"] == tid
    assert by_key[rows[1]["key"]]["trace_id"] is None
    doc = load_manifest(write_manifest(d, entries, scanned, 2))
    assert {e.get("trace_id") for e in doc["entries"]} == {tid, None}


def test_mine_skips_unlabeled_and_torn_rows(tmp_path, monkeypatch):
    d, _ = fill_capture(tmp_path, n=4, shard_records=4)
    # append a torn row + an unlabeled (all-low-score) row to the shard
    sh = list_shards(d)[0]
    with open(sh["jsonl"]) as fh:
        template = json.loads(fh.readline())
    unlabeled = dict(template, rid=99, key="r00000099",
                     detections=[{"cls": 1, "score": 0.05,
                                  "bbox": [0, 0, 10, 10]}])
    with open(sh["jsonl"], "a") as fh:
        fh.write(json.dumps(unlabeled) + "\n")
        fh.write("{torn json row\n")
    telemetry.configure(str(tmp_path / "tel"), rank=0, world=1)
    try:
        entries, scanned, skipped = mine_shards(d, top_k=10,
                                                min_label_score=0.3)
    finally:
        telemetry.shutdown()
    assert scanned == 6 and skipped == 2 and len(entries) == 4
    counters = aggregate(load_events([str(tmp_path / "tel")]))["counters"]
    assert counters["flywheel/skipped_unlabeled"] == 1
    assert counters["flywheel/skipped_bad_row"] == 1
    assert counters["flywheel/mined"] == 4


def test_sigterm_mid_mine_leaves_no_partial_manifest(tmp_path):
    """The manifest rename is the commit point: SIGTERM between tmp write
    and rename leaves only ``*.tmp`` behind, never a readable
    ``mined-*.json`` (driven through the real driver subprocess)."""
    d, _ = fill_capture(tmp_path, n=4, shard_records=4)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env[ENV_MINE_PAUSE_S] = "60"
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "flywheel.py"), "mine",
         "--capture-dir", d, "--top-k", "4"], env=env, cwd=REPO)
    try:
        deadline = time.time() + 60
        while time.time() < deadline:       # wait for the tmp to appear
            if any(n.endswith(".tmp") for n in os.listdir(d)):
                break
            if proc.poll() is not None:
                pytest.fail("miner exited before writing the tmp manifest")
            time.sleep(0.05)
        else:
            pytest.fail("tmp manifest never appeared")
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) != 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    names = os.listdir(d)
    assert not [n for n in names if n.startswith("mined-")
                and n.endswith(".json")]
    assert [n for n in names if n.endswith(".tmp")]


def test_flywheel_loop_round_and_driver_json(tmp_path):
    d, _ = fill_capture(tmp_path, n=8, shard_records=4)
    res = FlywheelLoop(d, top_k=4).run_round(0)
    assert res["mined"] == 4 and res["scanned"] == 8
    assert res["manifest"] and os.path.exists(res["manifest"])
    assert res["train_rc"] is None
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "flywheel.py"), "mine",
         "--capture-dir", d, "--top-k", "4"],
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO,
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["cmd"] == "mine" and doc["mined"] == 4
    assert doc["manifest"] and doc["train_rc"] is None


# -- replay ----------------------------------------------------------------


def replay_roidb_from(tmp_path, n=10, min_score=0.1, env=None):
    d, _ = fill_capture(tmp_path, n=n, shard_records=4, env=env)
    entries, scanned, _ = mine_shards(d, top_k=n, min_label_score=0.1)
    path = write_manifest(d, entries, scanned, n)
    ds = ReplayDataset(path, num_classes=5, min_score=min_score)
    return ds.gt_roidb()


def test_replay_dataset_scales_clips_and_filters(tmp_path):
    roidb = replay_roidb_from(tmp_path, n=6, min_score=0.5)
    assert roidb
    for rec in roidb:
        # captured raw extent 60x90, original 120x180 → boxes halved
        assert rec["height"] == 60 and rec["width"] == 90
        np.testing.assert_allclose(rec["boxes"][0], [2.0, 3.0, 30.0, 25.0])
        assert (rec["gt_classes"] > 0).all()
        assert rec["flipped"] is False
        assert rec["image"].startswith("replay://")
        px = load_replay_pixels(rec)
        assert px.shape == (60, 90, 3) and px.dtype == np.uint8
    # every pseudo-label respects the threshold: a min_score above every
    # synthetic det drops all entries
    assert replay_roidb_from(tmp_path / "hi", n=6, min_score=0.95) == []


def test_replay_mix_deterministic_across_loaders(tmp_path):
    """Two loaders at the same seed + ratio produce bit-identical batch
    streams across two epochs, and the mix actually replays records."""
    replay = replay_roidb_from(tmp_path, n=10)
    roidb = tiny_roidb()
    mk = lambda: AnchorLoader(roidb, loader_cfg(0), batch_size=2,
                              shuffle=True, seed=3, replay_roidb=replay,
                              replay_ratio=0.5)
    a, b = mk(), mk()
    assert_batches_equal(snapshot(a, epochs=2), snapshot(b, epochs=2))
    assert a.replay_substituted == b.replay_substituted > 0
    # the schedule length never changes: replay substitutes slots, it
    # does not extend the epoch
    assert a.steps_per_epoch == AnchorLoader(
        roidb, loader_cfg(0), batch_size=2, shuffle=True,
        seed=3).steps_per_epoch


def test_replay_mix_mid_epoch_resume_equality(tmp_path):
    """The --auto-resume pin across a replay-mixed epoch: fast-forward
    (advance_epochs + skip_next) reproduces the uninterrupted tail batch
    for batch, replay substitutions included."""
    replay = replay_roidb_from(tmp_path, n=10)
    roidb = tiny_roidb()
    mk = lambda: AnchorLoader(roidb, loader_cfg(0), batch_size=2,
                              shuffle=True, seed=11, replay_roidb=replay,
                              replay_ratio=0.5)
    serial = snapshot(mk(), epochs=2)
    steps = len(serial) // 2
    ld = mk()
    ld.advance_epochs(1)                  # resume inside epoch 1 (0-based)
    ld.skip_next(2)
    assert_batches_equal(serial[steps + 2:], snapshot(ld))


def test_corrupt_replay_shard_hits_bad_record_substitution(tmp_path):
    """Chaos: a shard corrupted post-spill (env-injected torn disk) makes
    its replay records unloadable; the loader substitutes them via PR-2,
    counts loader/bad_record, and the epoch completes full-length."""
    env = flywheel_fault_env(corrupt_shard=0)
    assert env == {"MXR_FAULT_FLYWHEEL_CORRUPT_SHARD": "0"}
    replay = replay_roidb_from(tmp_path, n=4, env=env)
    assert replay                         # jsonl intact: records mined
    with pytest.raises(Exception):
        load_replay_pixels(replay[0])     # npz garbage: load raises
    roidb = tiny_roidb()
    telemetry.configure(str(tmp_path / "tel"), rank=0, world=1)
    try:
        ld = AnchorLoader(roidb, loader_cfg(0), batch_size=2, shuffle=True,
                          seed=3, replay_roidb=replay, replay_ratio=0.5)
        batches = snapshot(ld)
    finally:
        telemetry.shutdown()
    assert len(batches) == ld.steps_per_epoch
    for b in batches:
        assert np.isfinite(b["images"]).all()
    counters = aggregate(load_events([str(tmp_path / "tel")]))["counters"]
    assert counters["loader/bad_record"] >= 1
    assert counters["flywheel/replayed"] == ld.replay_substituted > 0


def test_truncated_spill_is_systemic_when_everything_is_corrupt(tmp_path):
    """The PR-2 bound: a loader whose records ALL point at one truncated
    shard cannot substitute its way out — it raises the systemic error
    instead of looping forever."""
    replay = replay_roidb_from(tmp_path, n=4,
                               env=flywheel_fault_env(truncate_spill=0))
    assert replay
    ld = AnchorLoader(replay, loader_cfg(0), batch_size=2, shuffle=False,
                      seed=0)
    with pytest.raises(RuntimeError, match="systemic"):
        list(ld)


def test_flywheel_counters_render_as_report_table(tmp_path):
    telemetry.configure(str(tmp_path), rank=0, world=1)
    try:
        tel = telemetry.get()
        tel.counter("flywheel/captured", 8)
        tel.counter("flywheel/mined", 4)
        tel.counter("flywheel/replayed", 2)
    finally:
        telemetry.shutdown()
    summary = aggregate(load_events([str(tmp_path)]))
    table = render_table(summary)
    assert "flywheel" in table and "flywheel/mined" in table
    for name in ("flywheel/captured", "flywheel/mined", "flywheel/replayed"):
        assert name in FLYWHEEL_COUNTERS


# -- loadgen capture check + perf gate rows --------------------------------


def test_loadgen_capture_check_failure_logic():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from loadgen import capture_check_failure
    finally:
        sys.path.pop(0)
    # exact match, strided sampling, and within-tolerance all pass
    assert capture_check_failure({"captured": 0}, {"captured": 10,
                                 "sample_every": 1}, 10, 0.1) is None
    assert capture_check_failure({"captured": 5}, {"captured": 9,
                                 "sample_every": 3}, 12, 0.1) is None
    # silent capture loss fails loudly
    msg = capture_check_failure({"captured": 0}, {"captured": 2,
                                "sample_every": 1}, 10, 0.1)
    assert msg and "captured delta 2" in msg
    # a capture-off target is itself a smoke-script bug
    assert "no flywheel section" in capture_check_failure({}, {}, 10, 0.1)


def test_perf_gate_flywheel_floor_rows(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import perf_gate as pg
    finally:
        sys.path.pop(0)
    doc = {"schema": "mxr_flywheel_report", "captured": 40, "mined": 8,
           "generation_before": 0, "generation_after": 1}
    rows = {r["metric"]: r for r in pg.flywheel_report_rows(doc)}
    assert rows["flywheel_mined_fraction"]["value"] == pytest.approx(0.2)
    assert rows["flywheel_mined_fraction"]["floor"] == 0.01
    assert rows["flywheel_reload_generations"]["value"] == 1.0
    assert rows["flywheel_reload_generations"]["floor"] == 1.0
    path = tmp_path / "FLYWHEEL_r01.json"
    path.write_text(json.dumps(doc))
    assert {r["metric"] for r in pg.load_rows(str(path))} == set(rows)
    # a stalled loop (no generation advance) sits under the floor
    stalled = pg.flywheel_report_rows(dict(doc, generation_after=0))
    gen = [r for r in stalled if r["metric"] == "flywheel_reload_generations"]
    assert gen[0]["value"] < gen[0]["floor"]


# -- closed loop -----------------------------------------------------------


def test_closed_loop_serve_capture_mine_train_reload(tmp_path):
    """The acceptance pin, end to end on CPU: serve traffic → captured
    shards → mined manifest → ReplayDataset mixed into one training
    epoch → checkpoint → CheckpointWatcher-driven hot reload on a live
    engine with a strictly increasing generation."""
    scfg = serve_cfg()
    cap_dir = str(tmp_path / "capture")
    pred = FakeServePredictor(scfg, {"scale": np.float32(1.0)})
    engine = ServeEngine(pred, scfg, ServeOptions(
        batch_size=4, max_delay_ms=1.0, max_queue=32))
    engine.capture = RequestCapture(CaptureOptions(
        capture_dir=cap_dir, sample_every=1, shard_records=4))
    engine.start()
    try:
        futs = [engine.submit(raw_image(60 + i, 100 + i, 30 + 5 * i))
                for i in range(8)]
        for f in futs:
            assert f.result(timeout=30.0)
        m = engine.metrics()
        assert m["flywheel"]["captured"] == 8
    finally:
        engine.stop()                       # close() spills the remainder

    entries, scanned, _ = mine_shards(cap_dir, top_k=6,
                                      min_label_score=0.1)
    assert scanned == 8 and len(entries) == 6
    manifest = write_manifest(cap_dir, entries, scanned, 6)
    replay = ReplayDataset(manifest, num_classes=21,
                           min_score=0.1).gt_roidb()
    assert replay

    tcfg = loader_cfg(0)
    base = SyntheticDataset(num_images=4, num_classes=tcfg.NUM_CLASSES,
                            height=64, width=96).gt_roidb()
    loader = AnchorLoader(base, tcfg, batch_size=2, shuffle=True, seed=0,
                          replay_roidb=replay, replay_ratio=0.5)
    model = build_model(tcfg)
    params = init_params(model, tcfg, jax.random.PRNGKey(0), 1, (64, 96))
    prefix = str(tmp_path / "ckpt")
    fit(tcfg, model, params, loader, begin_epoch=0, end_epoch=1,
        prefix=prefix, frequent=100)
    assert loader.replay_substituted > 0    # the epoch actually mixed

    target = rp.scan_checkpoints(prefix)
    assert target and target["epoch"] == 1

    pred2 = FakeServePredictor(scfg, {"scale": np.float32(1.0)})
    engine2 = ServeEngine(pred2, scfg, ServeOptions(
        batch_size=2, max_delay_ms=1.0, max_queue=8)).start()
    try:
        gen_before = engine2.generation
        reloads = []

        def reload_fn(t):
            ok, info = rp.reload_engine_params(
                engine2, pred2, scfg, dict(t, prefix=prefix),
                load_params_fn=lambda _t, _c: {"scale": np.float32(2.0)})
            reloads.append(info)
            return ok

        watcher = rp.CheckpointWatcher(prefix, reload_fn)
        got = watcher.poll_once()           # sees the replay-trained save
        assert got is not None and got[1]
        assert engine2.generation > gen_before
        assert watcher.poll_once() is None  # dedup: no flapping
        dets = engine2.submit(raw_image(60, 100, 40)).result(timeout=30.0)
        assert dets                         # new generation serves
    finally:
        engine2.stop()
