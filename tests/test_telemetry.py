"""Telemetry subsystem tests: span/counter/gauge math, the no-op sink's
zero-allocation path, the JSONL schema round-trip (live aggregates ==
re-folded event stream), the report fold, and fit()/pred_eval() smoke
runs asserting the step-time breakdown and per-bucket recompile
accounting."""

import dataclasses
import json
import logging

import pytest

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.telemetry import NULL, Telemetry
from mx_rcnn_tpu.telemetry.report import (aggregate, bench_rows, load_events,
                                          render_table)
from mx_rcnn_tpu.telemetry.sink import _NULL_SPAN


@pytest.fixture(autouse=True)
def _restore_sink():
    """Every test leaves the module-global sink as it found it: NULL."""
    yield
    telemetry.shutdown()


def test_span_counter_gauge_math(tmp_path):
    tel = Telemetry(str(tmp_path), rank=0)
    tel.add("s", 1.0)
    tel.add("s", 3.0)
    tel.add("s", 2.0, n=4)  # one record standing for 4 occurrences
    tel.counter("c")
    tel.counter("c", inc=5)
    for v in (2.0, 8.0, 5.0):
        tel.gauge("g", v)
    doc = tel.summary()
    tel.close()

    s = doc["spans"]["s"]
    assert s["count"] == 6
    assert s["total_s"] == pytest.approx(6.0)
    assert s["mean_s"] == pytest.approx(1.0)
    assert s["min_s"] == pytest.approx(1.0)
    assert s["max_s"] == pytest.approx(3.0)
    assert doc["counters"]["c"] == 6
    g = doc["gauges"]["g"]
    assert g["count"] == 3
    assert g["mean"] == pytest.approx(5.0)
    assert (g["min"], g["max"], g["last"]) == (2.0, 8.0, 5.0)


def test_span_context_manager_times(tmp_path):
    import time

    tel = Telemetry(str(tmp_path))
    with tel.span("block"):
        time.sleep(0.01)
    s = tel.summary()["spans"]["block"]
    tel.close()
    assert s["count"] == 1
    assert 0.005 < s["total_s"] < 5.0


def test_null_sink_is_allocation_free():
    """The disabled path: one attribute check, one cached context manager
    — no per-call object creation, no state growth."""
    assert not NULL.enabled
    assert NULL.span("a") is _NULL_SPAN
    assert NULL.span("b") is NULL.span("c")
    with NULL.span("x"):
        pass
    NULL.add("s", 1.0)
    NULL.counter("c", 3)
    NULL.gauge("g", 2.0)
    NULL.meta("m", k=1)
    assert NULL.summary() == {}
    assert NULL.write_summary() is None
    NULL.close()
    assert not vars(NULL)  # truly stateless: nothing accumulated


def test_unconfigured_get_is_null():
    assert telemetry.get() is NULL


def test_configure_shutdown_cycle(tmp_path):
    tel = telemetry.configure(str(tmp_path), rank=0, world=1,
                              run_meta={"driver": "test"})
    assert telemetry.get() is tel and tel.enabled
    tel.counter("c")
    telemetry.shutdown()
    assert telemetry.get() is NULL


def test_jsonl_schema_roundtrip(tmp_path):
    """Every event line is schema-versioned JSON with the kind-specific
    field, and re-folding the stream reproduces the live aggregates."""
    tel = Telemetry(str(tmp_path), rank=0, run_meta={"driver": "unit"})
    tel.add("train/dispatch", 0.5)
    tel.add("train/dispatch", 0.25, n=2)
    tel.counter("train/recompile")
    tel.gauge("loader/queue_depth", 3)
    live = tel.summary()
    tel.close()

    events = load_events([str(tmp_path)])
    required = {"span": "dur_s", "counter": "inc", "gauge": "value",
                "meta": "fields"}
    for e in events:
        assert e["v"] == telemetry.SCHEMA_VERSION
        assert e["rank"] == 0
        assert isinstance(e["t"], float)
        assert required[e["kind"]] in e
    folded = aggregate(events)
    assert folded["spans"] == live["spans"]
    assert folded["counters"] == live["counters"]
    assert folded["gauges"] == live["gauges"]
    assert folded["meta"] == {"world": 1, "driver": "unit"}


def test_report_multi_rank_fold_and_render(tmp_path):
    """Two ranks' event files fold into one cross-rank aggregate; the
    table renders and rate gauges become BENCH-compatible rows."""
    for rank in (0, 1):
        tel = Telemetry(str(tmp_path), rank=rank, world=2)
        tel.add("train/dispatch", 1.0 + rank)
        tel.counter("train/recompile", 2)
        tel.gauge("train/imgs_per_sec", 100.0 * (rank + 1))
        tel.close()
    summary = aggregate(load_events([str(tmp_path)]))
    assert summary["ranks"] == [0, 1]
    assert summary["spans"]["train/dispatch"]["count"] == 2
    assert summary["spans"]["train/dispatch"]["total_s"] == pytest.approx(3.0)
    assert summary["counters"]["train/recompile"] == 4
    table = render_table(summary)
    assert "train/dispatch" in table and "train/recompile" in table
    rows = bench_rows(summary)
    assert rows == [{"metric": "train_imgs_per_sec", "value": 150.0,
                     "unit": "imgs/sec", "samples": 2}]


def test_write_summary_file(tmp_path):
    tel = Telemetry(str(tmp_path), rank=0)
    tel.add("s", 1.0)
    path = tel.write_summary(extra={"note": "x"})
    tel.close()
    with open(path) as f:
        doc = json.load(f)
    assert doc["spans"]["s"]["count"] == 1
    assert doc["note"] == "x"


def test_report_missing_dir_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="telemetry-dir"):
        load_events([str(tmp_path)])


def _train_tiny_cfg():
    # test_train.py's tiny fit() recipe: 64×96 bucket, FLIP off, unit stds
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16,
    )
    cfg = cfg.replace(TRAIN=dataclasses.replace(cfg.TRAIN, FLIP=False))
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4),
                              PIXEL_STDS=(127.0, 127.0, 127.0))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4)
    return cfg.replace(network=net, tpu=tpu)


def test_fit_telemetry_smoke(tmp_path):
    """fit(telemetry_dir=...) over a mixed-bucket synthetic epoch: the
    summary JSON carries the step-time breakdown, its phases sum to within
    10% of the measured epoch wall time, the recompile counter reads
    exactly one per bucket shape, and telemetry_report folds the stream
    without error."""
    import jax

    from mx_rcnn_tpu.data import AnchorLoader, SyntheticDataset
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.train import fit

    cfg = _train_tiny_cfg()
    land = SyntheticDataset(num_images=4, num_classes=cfg.NUM_CLASSES,
                            height=64, width=96, seed=0).gt_roidb()
    port = SyntheticDataset(num_images=2, num_classes=cfg.NUM_CLASSES,
                            height=96, width=64, seed=1).gt_roidb()
    loader = AnchorLoader(land + port, cfg, batch_size=1, shuffle=True,
                          seed=0)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))

    tdir = str(tmp_path / "tel")
    fit(cfg, model, params, loader, begin_epoch=0, end_epoch=1, frequent=2,
        telemetry_dir=tdir)
    assert telemetry.get() is NULL  # fit owned the sink and shut it down

    with open(f"{tdir}/summary.json") as f:
        doc = json.load(f)
    spans = doc["spans"]
    for key in ("train/loader_wait", "train/dispatch", "train/fetch_stall",
                "train/epoch"):
        assert key in spans, key
    # per-step phase counts: one loader-wait and one dispatch per step
    assert spans["train/dispatch"]["count"] == loader.steps_per_epoch
    assert spans["train/loader_wait"]["count"] == loader.steps_per_epoch
    assert doc["counters"]["train/steps"] == loader.steps_per_epoch
    # k=1: one program per bucket shape, so one recompile per bucket
    assert doc["counters"]["train/recompile"] == 2
    assert doc["meta"]["driver"] == "fit"
    # the breakdown accounts for the epoch: phases sum to within 10% of
    # the measured wall time (the untimed remainder is python loop + rng
    # splits; compile lives inside the dispatch span)
    wall = spans["train/epoch"]["total_s"]
    accounted = sum(spans[k]["total_s"]
                    for k in ("train/loader_wait", "train/dispatch",
                              "train/fetch_stall"))
    assert accounted <= wall * 1.01
    assert accounted >= wall * 0.9, (accounted, wall)
    # loader stream landed in the same run: queue gauge + producer spans
    assert "loader/queue_depth" in doc["gauges"]
    assert "loader/produce" in spans
    # the report CLI's fold renders the same stream without error
    folded = aggregate(load_events([tdir]))
    assert folded["counters"]["train/recompile"] == 2
    assert render_table(folded)


def test_pred_eval_phase_telemetry(tmp_path):
    """The eval loop emits forward/readback/decode/nms spans into an
    active sink (same schema as train)."""
    import jax

    from mx_rcnn_tpu.data import SyntheticDataset, TestLoader
    from mx_rcnn_tpu.eval import Predictor, pred_eval
    from mx_rcnn_tpu.models import build_model, init_params

    cfg = generate_config(
        "resnet50", "PascalVOC",
        TEST__RPN_PRE_NMS_TOP_N=300, TEST__RPN_POST_NMS_TOP_N=32,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((96, 128),), MAX_GT=8)
    cfg = cfg.replace(network=net, tpu=tpu)
    ds = SyntheticDataset(num_images=2, height=96, width=128)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (96, 128))
    pred = Predictor(model, params, cfg)

    telemetry.configure(str(tmp_path), run_meta={"driver": "unit-eval"})
    pred_eval(pred, TestLoader(ds.gt_roidb(), cfg, batch_size=1), ds)
    doc = telemetry.get().summary()
    telemetry.shutdown()
    for key in ("eval/loader_wait", "eval/forward", "eval/readback",
                "eval/decode", "eval/nms"):
        assert key in doc["spans"], key
    assert doc["counters"]["eval/images"] == 2


def test_speedometer_perf_counter_and_gauge(tmp_path, monkeypatch):
    """Speedometer times on perf_counter (immune to wall-clock slew) and
    feeds each computed rate into the active sink."""
    import time

    from mx_rcnn_tpu.train.callback import Speedometer

    telemetry.configure(str(tmp_path))
    clock = [0.0]
    monkeypatch.setattr(time, "perf_counter", lambda: clock[0])
    speedo = Speedometer(batch_size=4, frequent=2, n_chips=2)
    speeds = []
    for _ in range(5):
        clock[0] += 0.5
        s = speedo(0, 0)
        if s is not None:
            speeds.append(s)
    doc = telemetry.get().summary()
    telemetry.shutdown()
    # 2 steps * 4 imgs per 1.0s window = 8 imgs/s, every `frequent` calls
    assert speeds == [pytest.approx(8.0), pytest.approx(8.0)]
    g = doc["gauges"]["train/imgs_per_sec"]
    assert g["count"] == 2 and g["last"] == pytest.approx(8.0)


def test_logger_setup_idempotent_and_rank_aware():
    """setup_logging owns exactly one handler across repeated calls,
    rank=N swaps in the rank-prefixed formatter, and a pre-configured
    root logger (application- or pytest-owned) is never stomped."""
    from mx_rcnn_tpu import logger as logmod

    root = logging.getLogger()
    saved_handlers = root.handlers[:]
    saved_handler = logmod._handler
    saved_level = root.level
    try:
        for h in root.handlers[:]:
            root.removeHandler(h)
        logmod._handler = None
        logmod.setup_logging()
        assert logmod._handler is not None
        assert root.handlers == [logmod._handler]
        logmod.setup_logging()  # idempotent: still exactly one handler
        assert root.handlers == [logmod._handler]
        logmod.setup_logging(rank=3)
        assert root.handlers == [logmod._handler]
        assert "rank3" in logmod._handler.formatter._fmt
        logmod.setup_logging()  # rankless again
        assert "rank3" not in logmod._handler.formatter._fmt

        # an application's own configuration is never stomped
        for h in root.handlers[:]:
            root.removeHandler(h)
        logmod._handler = None
        foreign = logging.NullHandler()
        root.addHandler(foreign)
        logmod.setup_logging()
        assert logmod._handler is None
        assert root.handlers == [foreign]
    finally:
        for h in root.handlers[:]:
            root.removeHandler(h)
        for h in saved_handlers:
            root.addHandler(h)
        logmod._handler = saved_handler
        root.setLevel(saved_level)


def test_prefetcher_telemetry_counts(tmp_path):
    """The loader's producer thread emits produce/put/queue spans and the
    consumer samples queue depth — one of each per batch."""
    from mx_rcnn_tpu.data.loader import _Prefetcher

    telemetry.configure(str(tmp_path))
    items = list(_Prefetcher((dict(i=i) for i in range(5)), depth=2,
                             put=lambda b: b))
    doc = telemetry.get().summary()
    telemetry.shutdown()
    assert [it["i"] for it in items] == list(range(5))
    assert doc["spans"]["loader/produce"]["count"] == 5
    assert doc["spans"]["loader/put_transfer"]["count"] == 5
    assert doc["spans"]["loader/queue_full_wait"]["count"] == 5
    assert doc["gauges"]["loader/queue_depth"]["count"] >= 5


def test_prefetcher_disabled_sink_untouched():
    """With telemetry off the prefetcher must not record anywhere (the
    zero-overhead contract of the NULL sink)."""
    from mx_rcnn_tpu.data.loader import _Prefetcher

    assert telemetry.get() is NULL
    items = list(_Prefetcher((dict(i=i) for i in range(3)), depth=1))
    assert len(items) == 3
    assert NULL.summary() == {} and not vars(NULL)
