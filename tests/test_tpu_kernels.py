"""On-chip Pallas kernel gate (VERDICT round-1 item 5).

The pytest suite itself runs on the forced CPU mesh (tests/conftest.py),
where ``nms_pallas`` silently delegates to the pure-JAX oracle — a Mosaic
kernel regression would be invisible to every other test.  This module
closes that hole: it runs ``scripts/check_pallas.py`` (kernel-vs-oracle
equivalence across shapes, adversarial structures, and the batched vmap
path) in a SUBPROCESS with the CPU-forcing env stripped, so the kernel
actually lowers on the real chip.

Skips — rather than fails — when no TPU is attached (laptop/CI without the
tunnel), so the suite stays green off-chip while any machine with the chip
gets the regression gate automatically.
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.tpu
def test_pallas_nms_matches_oracle_on_chip():
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            env=env, capture_output=True, text=True, timeout=120, cwd=REPO)
    except subprocess.TimeoutExpired:
        # dead tunnel: the axon sitecustomize blocks interpreter start
        # retrying the backend (verify-skill gotcha) — that is "no TPU",
        # not a kernel regression
        pytest.skip("no TPU attached (backend probe timed out — tunnel down)")
    if "tpu" not in probe.stdout:
        pytest.skip(f"no TPU attached (backend: {probe.stdout.strip() or probe.stderr[-200:]})")

    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "check_pallas.py")],
        env=env, capture_output=True, text=True, timeout=900, cwd=REPO)
    assert res.returncode == 0, (
        f"Pallas kernel-vs-oracle check failed:\n{res.stdout[-3000:]}\n"
        f"{res.stderr[-2000:]}")
    assert "equivalence: OK" in res.stdout
