"""Detector assembly tests — train graph, gradients, predict graph.

Small images + small anchor scales so RPN fg/bg anchors exist (the standard
(8,16,32) scales at stride 16 produce zero inside-image anchors below
~300 px — itself a behavior inherited from the reference's inside-image
filter in assign_anchor).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import build_model, init_params


def tiny_cfg(network="resnet50"):
    cfg = generate_config(
        network, "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=600, TRAIN__RPN_POST_NMS_TOP_N=64,
        TRAIN__BATCH_ROIS=32,
        TEST__RPN_PRE_NMS_TOP_N=300, TEST__RPN_POST_NMS_TOP_N=50,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((128, 192),), MAX_GT=8)
    return cfg.replace(network=net, tpu=tpu)


def batch(B=2, H=128, W=192, G=8, seed=0):
    rng = np.random.RandomState(seed)
    imgs = jnp.asarray(rng.randn(B, H, W, 3), jnp.float32)
    im_info = jnp.tile(jnp.asarray([[H, W, 1.0]], jnp.float32), (B, 1))
    gtb = np.zeros((B, G, 4), np.float32)
    gtv = np.zeros((B, G), bool)
    gtc = np.zeros((B, G), np.int32)
    for b in range(B):
        for g in range(3):
            x1, y1 = rng.randint(0, W - 40), rng.randint(0, H - 40)
            gtb[b, g] = (x1, y1, x1 + rng.randint(20, 39), y1 + rng.randint(20, 39))
            gtc[b, g] = rng.randint(1, 21)
            gtv[b, g] = True
    return imgs, im_info, jnp.asarray(gtb), jnp.asarray(gtc), jnp.asarray(gtv)


@pytest.mark.parametrize("network", ["resnet50", "vgg16"])
def test_train_graph_losses_and_grads(network):
    cfg = tiny_cfg(network)
    model = build_model(cfg)
    key = jax.random.PRNGKey(0)
    params = init_params(model, cfg, key, batch_size=2, image_hw=(128, 192))
    imgs, im_info, gtb, gtc, gtv = batch()

    def loss_fn(p, k):
        return model.apply({"params": p}, imgs, im_info, gtb, gtc, gtv, k,
                           rngs={"dropout": k})

    (tot, aux), grads = jax.jit(jax.value_and_grad(loss_fn, has_aux=True))(params, key)
    assert np.isfinite(float(tot))
    # with 20-40 px gt and 32/64 px anchors, RPN must find fg/bg anchors
    assert float(aux["rpn_cls_loss"]) > 0
    assert float(aux["rcnn_cls_loss"]) > 0
    labels = np.asarray(aux["rpn_label"])
    assert (labels == 1).any() and (labels == 0).any()
    gn = float(jnp.sqrt(sum(jnp.sum(g * g) for g in jax.tree.leaves(grads))))
    assert np.isfinite(gn) and gn > 0


def test_predict_shapes_and_validity():
    cfg = tiny_cfg()
    model = build_model(cfg)
    key = jax.random.PRNGKey(1)
    params = init_params(model, cfg, key, batch_size=2, image_hw=(128, 192))
    imgs, im_info, *_ = batch()
    rois, valid, cls_prob, deltas, scores = jax.jit(
        lambda p: model.apply({"params": p}, imgs, im_info, method=model.predict)
    )(params)
    R = cfg.TEST.RPN_POST_NMS_TOP_N
    K = cfg.NUM_CLASSES
    assert rois.shape == (2, R, 4)
    assert cls_prob.shape == (2, R, K)
    assert deltas.shape == (2, R, 4 * K)
    assert np.asarray(valid).any()
    p = np.asarray(cls_prob)
    np.testing.assert_allclose(p.sum(-1), 1.0, atol=1e-3)
    # rois inside the image
    r = np.asarray(rois)
    assert (r[..., 0] >= 0).all() and (r[..., 2] <= 192 - 1).all()


def test_rpn_and_rcnn_stage_graphs():
    """Alternate-training stage graphs (rpn_train / rcnn_train) run and
    produce finite losses."""
    cfg = tiny_cfg()
    model = build_model(cfg)
    key = jax.random.PRNGKey(2)
    params = init_params(model, cfg, key, batch_size=2, image_hw=(128, 192))
    imgs, im_info, gtb, gtc, gtv = batch()

    tot, aux = jax.jit(lambda p, k: model.apply(
        {"params": p}, imgs, im_info, gtb, gtv, k, method=model.rpn_train))(params, key)
    assert np.isfinite(float(tot)) and float(aux["rpn_cls_loss"]) > 0

    rois, _, rvalid = jax.jit(lambda p: model.apply(
        {"params": p}, imgs, im_info, method=model.predict_rpn))(params)
    tot2, aux2 = jax.jit(lambda p, k: model.apply(
        {"params": p}, imgs, im_info, rois, rvalid, gtb, gtc, gtv, k,
        rngs={"dropout": k}, method=model.rcnn_train))(params, key)
    assert np.isfinite(float(tot2)) and float(aux2["rcnn_cls_loss"]) > 0
