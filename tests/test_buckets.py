"""Mixed-orientation scale buckets: one train step function serves both
(landscape, portrait) compiled programs — the MutableModule replacement
(SURVEY §5 long-context row: resolution buckets instead of rebinding)."""

import dataclasses

import jax
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import AnchorLoader, SyntheticDataset
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.train import create_train_state, make_train_step
from mx_rcnn_tpu.utils import merge_roidb


def test_mixed_orientation_buckets_train():
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16,
    )
    cfg = cfg.replace(
        network=dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4),
                                    PIXEL_STDS=(127.0, 127.0, 127.0)),
        tpu=dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4))
    land = SyntheticDataset(num_images=2, num_classes=5, height=64, width=96,
                            seed=0)
    port = SyntheticDataset(num_images=2, num_classes=5, height=96, width=64,
                            seed=1)
    roidb = merge_roidb([land.gt_roidb(), port.gt_roidb()])
    loader = AnchorLoader(roidb, cfg, batch_size=2, shuffle=False, seed=0)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 2, (64, 96))
    state, tx, mask = create_train_state(cfg, params, steps_per_epoch=2)
    step = make_train_step(model, tx, trainable_mask=mask)

    shapes = set()
    key = jax.random.PRNGKey(0)
    for batch in loader:
        shapes.add(batch["images"].shape[1:3])
        # aspect grouping: a batch never mixes orientations
        key, sub = jax.random.split(key)
        state, m = step(state, batch, sub)
        assert np.isfinite(float(jax.device_get(m["total_loss"])))
    # images ship host-s2d'd: (64, 96) / (96, 64) buckets halve
    assert shapes == {(32, 48), (48, 32)}


def test_multi_scale_buckets_train():
    """Multi-scale training (len(SCALES) > 1): the loader samples one scale
    bucket per batch; each (scale, orientation) shape is its own compiled
    program through the same step fn."""
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16,
    )
    cfg = cfg.replace(
        network=dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4),
                                    PIXEL_STDS=(127.0, 127.0, 127.0)),
        tpu=dataclasses.replace(cfg.tpu, SCALES=((64, 96), (96, 128)),
                                MAX_GT=4))
    ds = SyntheticDataset(num_images=8, num_classes=5, height=64, width=96,
                          seed=0)
    loader = AnchorLoader(ds.gt_roidb(), cfg, batch_size=2, shuffle=True,
                          seed=3)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 2, (64, 96))
    state, tx, mask = create_train_state(cfg, params, steps_per_epoch=4)
    step = make_train_step(model, tx, trainable_mask=mask)

    shapes = set()
    key = jax.random.PRNGKey(0)
    for _ in range(3):  # several epochs so both scales get sampled
        for batch in loader:
            shapes.add(batch["images"].shape[1:3])
            key, sub = jax.random.split(key)
            state, m = step(state, batch, sub)
            assert np.isfinite(float(jax.device_get(m["total_loss"])))
        if len(shapes) > 1:
            break
    assert len(shapes) == 2, shapes
    # gt must be scaled into each batch's own resized frame: load the same
    # record at both scale buckets and check boxes == original * im_scale
    from mx_rcnn_tpu.data.loader import _load_record

    rec = ds.gt_roidb()[0]
    orig = np.asarray(rec["boxes"], np.float32)
    for scale in cfg.tpu.SCALES:
        sample = _load_record(rec, cfg, scale)
        s = sample["im_info"][2]
        n = int(sample["gt_valid"].sum())
        np.testing.assert_allclose(sample["gt_boxes"][:n], orig[:n] * s,
                                   rtol=1e-5, atol=1e-4)
    s_small = _load_record(rec, cfg, cfg.tpu.SCALES[0])["im_info"][2]
    s_large = _load_record(rec, cfg, cfg.tpu.SCALES[1])["im_info"][2]
    assert s_large > s_small  # the two buckets genuinely differ
