"""Native C++ tier vs numpy-oracle equivalence (host code, runs anywhere
the toolchain builds; falls back — and the test then still passes on the
fallback path, flagged by ``available``)."""

import numpy as np

from mx_rcnn_tpu import native
from mx_rcnn_tpu.eval import mask_rle as M
from mx_rcnn_tpu.ops.boxes import bbox_overlaps as jax_overlaps
from mx_rcnn_tpu.ops.nms import nms as py_nms


def test_native_builds():
    assert native.available(), "g++ toolchain present but native build failed"


def test_native_bbox_overlaps_matches(rng):
    boxes = (rng.rand(40, 4) * 100).astype(np.float32)
    boxes[:, 2:] += boxes[:, :2]
    query = (rng.rand(17, 4) * 100).astype(np.float32)
    query[:, 2:] += query[:, :2]
    got = native.bbox_overlaps(boxes, query)
    want = np.asarray(jax_overlaps(boxes, query))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_native_nms_matches(rng):
    for seed in range(3):
        r = np.random.RandomState(seed)
        ctr = r.rand(200, 2) * 300
        wh = r.rand(200, 2) * 80 + 5
        dets = np.concatenate(
            [ctr - wh / 2, ctr + wh / 2, r.rand(200, 1)], axis=1
        ).astype(np.float32)
        got = native.nms(dets, 0.5)
        want = py_nms(dets, 0.5)
        assert got == want


def test_native_rle_iou_matches(rng):
    masks = [(rng.rand(30, 25) > 0.6).astype(np.uint8) for _ in range(4)]
    # leading-set-pixel masks: RLE counts start with 0 (regression for the
    # zero-length-run desync) — plus a solid mask
    m0 = masks[0].copy()
    m0[0, 0] = 1
    masks[0] = m0
    masks[2] = np.ones((30, 25), np.uint8)
    rles = [M.encode(m) for m in masks]
    assert rles[0]["counts"][0] == 0  # the regression precondition
    crowd = np.asarray([False, True], bool)
    got = native.rle_iou(rles[:2], rles[2:], crowd)
    want = M.rle_iou(rles[:2], rles[2:], crowd)
    np.testing.assert_allclose(got, want, rtol=1e-12)
