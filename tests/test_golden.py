"""Golden-runway rehearsal (VERDICT round-3 item 7): the probe → convert →
run → compare path of ``scripts/golden.py`` must work end-to-end TODAY, on
generated mini fixtures, so the day real VOC/COCO + weights appear the
golden run is one command with no bitrot risk."""

from __future__ import annotations

import os

import numpy as np


def test_probe_empty(tmp_path):
    from scripts.golden import probe

    avail = probe(str(tmp_path / "data"), str(tmp_path / "model"))
    assert avail["datasets"] == {"voc07": False, "coco": False}
    assert all(v is None for v in avail["weights"].values())


def test_probe_finds_pth_and_converts(tmp_path):
    """A torchvision-shaped .pth on disk is found and converted to the
    overlay npz through the real converter."""
    import torch

    from scripts.golden import ensure_npz, probe
    from tests.test_convert import fake_vgg_sd

    model_dir = tmp_path / "model"
    model_dir.mkdir()
    sd = {k: torch.from_numpy(v) for k, v in fake_vgg_sd().items()}
    torch.save(sd, str(model_dir / "vgg16-397923af.pth"))

    avail = probe(str(tmp_path / "data"), str(model_dir))
    kind, path = avail["weights"]["vgg16"]
    assert kind == "pth"
    npz = ensure_npz("vgg16", (kind, path), str(model_dir))
    data = np.load(npz)
    assert "backbone/conv1_1/kernel" in data.files
    assert data["head_body/fc6/kernel"].shape == (25088, 4096)


def test_golden_fixture_end_to_end(tmp_path):
    """Full rehearsal: mini-VOC on disk + stand-in npz → probe → train via
    train_end2end → eval via test.py → GOLDEN.md row with the fixture
    anchor.  Uses the same tiny shapes as the CLI integration test."""
    from scripts.golden import main

    row = main(["--fixture", str(tmp_path)])
    assert row["config"] == "fixture_voc"
    assert row["anchor"] == 20.0
    assert row["value"] > 20.0, row   # fixture classes are learnable
    golden_md = tmp_path / "GOLDEN.md"
    assert golden_md.exists()
    assert "fixture_voc" in golden_md.read_text()
