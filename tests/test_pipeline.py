"""Pipeline composer + autotuner (``train/pipeline.py``): sweep mechanics
over injected fake step functions (no model build — the real-model path
is covered by script/pipeline_smoke.sh), per-cell breakdown fields, the
sweep JSONL → telemetry-report round trip, tuned-cell persistence, and
``--tuned-pipeline`` boot precedence (explicit user flags win)."""

import argparse
import dataclasses
import json

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data.synthetic import SyntheticDataset
from mx_rcnn_tpu.telemetry.report import aggregate, load_events, render_table
from mx_rcnn_tpu.train.pipeline import (PipelineCell, PipelineSweep,
                                        apply_tuned_to_args, cell_config,
                                        load_tuned, parse_cells,
                                        pipeline_digest, save_tuned)


def tiny_cfg():
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16,
        tpu__SCALES=((64, 96),), tpu__MAX_GT=4,
    )
    return cfg.replace(network=dataclasses.replace(
        cfg.network, ANCHOR_SCALES=(2, 4), PIXEL_STDS=(127.0, 127.0, 127.0)))


def tiny_roidb(n=6):
    return SyntheticDataset(num_images=n, num_classes=5,
                            height=64, width=96).gt_roidb()


def fake_build():
    """Step functions with the fit dispatch contract but no model: state
    is a step counter, metrics a host scalar."""
    def steps(k):
        def step(state, batch, key):
            return state + 1, {"total_loss": np.float32(0.0)}

        def multi(state, batch, key):
            return state + k, {"total_loss": np.float32(0.0)}

        return step, (multi if k > 1 else None)

    return 0, steps


BREAKDOWN_FIELDS = ("imgs_per_sec", "loader_wait_s", "dispatch_s",
                    "fetch_stall_s", "assembly_wait_s", "loader_wait_frac",
                    "loader_wait_ok")


def test_parse_cells_k_major_product():
    cells = parse_cells([1, 2], [0, 2], [2], device_prep=(False, True))
    assert len(cells) == 8
    assert cells[0] == PipelineCell(1, 0, 2, False)
    assert cells[1] == PipelineCell(1, 0, 2, True)
    assert cells[-1] == PipelineCell(2, 2, 2, True)
    assert cells[0].label == "k1_w0_p2"
    assert cells[1].label == "k1_w0_p2_dp"


def test_sweep_breakdown_and_jsonl_roundtrip(tmp_path, monkeypatch):
    """Every cell reports the full wait breakdown; the sweep JSONL is
    telemetry-meta-shaped and folds into the report's pipeline table."""
    monkeypatch.setenv("MXR_PROGRAM_CACHE", str(tmp_path))
    sweep = PipelineSweep(tiny_cfg(), tiny_roidb(), batch=2,
                          build_steps=fake_build)
    cells = parse_cells([1, 2], [0], [2])
    out_jsonl = str(tmp_path / "sweep.jsonl")
    res = sweep.sweep(cells, epochs=1, warmup_epochs=1,
                      sweep_jsonl=out_jsonl)
    assert len(res["cells"]) == 2
    for row in res["cells"]:
        for f in BREAKDOWN_FIELDS:
            assert f in row, f
        assert row["steps"] * 2 == row["imgs"]
    assert res["best"] == max(res["cells"],
                              key=lambda r: r["imgs_per_sec"])
    # a fake-step sweep is never loader-bound in dispatch terms, but the
    # tripwire fields must be present and consistent either way
    for row in res["cells"]:
        assert row["loader_wait_ok"] == (row["loader_wait_frac"] <= 0.10)

    summary = aggregate(load_events([out_jsonl]))
    assert [r["cell"] for r in summary["pipeline"]] == \
        [r["cell"] for r in res["cells"]]
    table = render_table(summary)
    assert "pipeline cell" in table
    for row in res["cells"]:
        assert row["cell"] in table


def test_group_cells_count_all_steps(tmp_path, monkeypatch):
    """k>1 cells go through the tagged group wrap: the per-cell step count
    must equal the roidb coverage (groups counted by n, remainder as
    singles), not the dispatch count."""
    monkeypatch.setenv("MXR_PROGRAM_CACHE", str(tmp_path))
    sweep = PipelineSweep(tiny_cfg(), tiny_roidb(6), batch=1,
                          build_steps=fake_build)
    res = sweep.run_cell(PipelineCell(k=4, workers=0, prefetch=2), epochs=1)
    assert res["steps"] == 6
    assert res["imgs"] == 6


def test_auto_tune_persist_and_load(tmp_path, monkeypatch):
    monkeypatch.setenv("MXR_PROGRAM_CACHE", str(tmp_path))
    cfg = tiny_cfg()
    sweep = PipelineSweep(cfg, tiny_roidb(), batch=1,
                          build_steps=fake_build)
    res = sweep.sweep(parse_cells([1], [0], [2, 4]), auto_tune=True)
    assert res["tuned_file"] == str(tmp_path / "pipeline_tuned.json")
    tuned = load_tuned(cfg)
    assert tuned is not None
    best = res["best"]
    assert (tuned["k"], tuned["workers"], tuned["prefetch"]) == \
        (best["k"], best["workers"], best["prefetch"])
    with open(res["tuned_file"]) as f:
        doc = json.load(f)
    assert doc["schema"] == "mxr-pipeline-tuned-v1"
    assert pipeline_digest(cfg) in doc["tuned"]


def test_digest_invariant_under_tuned_fields():
    """Applying a tuned cell to the config must not change the lookup key
    — otherwise a tuned boot could never find its own tuning."""
    cfg = tiny_cfg()
    cell = PipelineCell(k=4, workers=2, prefetch=6, device_prep=True)
    assert pipeline_digest(cfg) == pipeline_digest(cell_config(cfg, cell))


def boot_args(**kw):
    defaults = dict(loader_workers=None, prefetch=None, device_prep=False,
                    steps_per_dispatch=1)
    defaults.update(kw)
    return argparse.Namespace(**defaults)


def test_apply_tuned_all_defaults(tmp_path):
    cfg = tiny_cfg()
    path = str(tmp_path / "pipeline_tuned.json")
    save_tuned(cfg, PipelineCell(4, 2, 6, True),
               {"imgs_per_sec": 10.0, "loader_wait_frac": 0.01}, path=path)
    args = boot_args()
    out = apply_tuned_to_args(args, cfg, path=path)
    assert args.steps_per_dispatch == 4
    assert out.tpu.LOADER_WORKERS == 2
    assert out.tpu.PREFETCH == 6
    assert out.tpu.DEVICE_PREP is True


def test_apply_tuned_user_flags_win(tmp_path):
    """Per-field precedence: only fields left at parser defaults are
    overridden by the persisted cell."""
    cfg = tiny_cfg().replace(tpu=dataclasses.replace(
        tiny_cfg().tpu, LOADER_WORKERS=1))
    path = str(tmp_path / "pipeline_tuned.json")
    save_tuned(cfg, PipelineCell(4, 2, 6, True),
               {"imgs_per_sec": 10.0, "loader_wait_frac": 0.01}, path=path)
    args = boot_args(loader_workers=1, steps_per_dispatch=2)
    out = apply_tuned_to_args(args, cfg, path=path)
    assert args.steps_per_dispatch == 2          # user's k kept
    assert out.tpu.LOADER_WORKERS == 1           # user's workers kept
    assert out.tpu.PREFETCH == 6                 # tuned applied
    assert out.tpu.DEVICE_PREP is True           # tuned applied


def test_apply_tuned_missing_is_soft(tmp_path):
    cfg = tiny_cfg()
    args = boot_args()
    out = apply_tuned_to_args(args, cfg,
                              path=str(tmp_path / "nope.json"))
    assert out == cfg
    assert args.steps_per_dispatch == 1
