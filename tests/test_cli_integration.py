"""End-to-end CLI integration over REAL files (VERDICT round-1 item 2).

Drives the actual drivers — ``train_end2end.py``/``test.py``/
``train_alternate.py`` argv surface included — over a generated
mini-VOCdevkit and mini-COCO on disk, so the full real-data pipeline
(JPEG decode → resize/bucket → train → orbax checkpoint → eval →
official per-class writeout / result json) is exercised with zero real
data available.  Train reaches a real mAP on the held-out split: the
fixture classes are learnable (class-colored rectangles), and 6 epochs
from scratch measured ~0.53 mean AP over the 3 fixture classes on CPU —
asserted > 0.2 for margin.

The drivers run in-process (import module, set sys.argv, call main) —
that IS the CLI code path (parse_args included) without paying a fresh
jax init + jit cache per subprocess.
"""

from __future__ import annotations

import importlib

import numpy as np
import pytest

from tests.fixtures import (FIXTURE_CLASSES, make_mini_coco, make_mini_voc,
                            run_tool)

TINY = [
    "--cfg", "tpu__SCALES=((64,96),)",
    "--cfg", "tpu__MAX_GT=8",
    "--cfg", "network__ANCHOR_SCALES=(2,4)",
    "--cfg", "network__PIXEL_STDS=(127.0,127.0,127.0)",
]
TINY_TRAIN = TINY + [
    "--cfg", "TRAIN__RPN_PRE_NMS_TOP_N=200",
    "--cfg", "TRAIN__RPN_POST_NMS_TOP_N=32",
    "--cfg", "TRAIN__BATCH_ROIS=16",
]
TINY_TEST = TINY + [
    "--cfg", "TEST__RPN_PRE_NMS_TOP_N=200",
    "--cfg", "TEST__RPN_POST_NMS_TOP_N=32",
]


_MAINS = {"train_end2end": "train_net", "test": "test_rcnn",
          "train_alternate": "alternate_train", "demo": "demo_net"}


def run_cli(module: str, argv: list):
    mod = importlib.import_module(module)
    return run_tool(mod, getattr(mod, _MAINS[module]), argv)


@pytest.fixture(scope="module")
def mini_voc(tmp_path_factory):
    root = tmp_path_factory.mktemp("minivoc")
    make_mini_voc(str(root / "VOCdevkit"))
    return root


def test_voc_train_eval_cli(mini_voc):
    """cv2/PIL load → bucket → 6 training epochs → checkpoint → test.py →
    mAP over the fixture classes beats 0.2; official VOC writeout lands."""
    common = ["--network", "resnet50", "--dataset", "PascalVOC",
              "--root_path", str(mini_voc / "data"),
              "--dataset_path", str(mini_voc / "VOCdevkit"),
              "--prefix", str(mini_voc / "model" / "e2e"),
              "--devices", "1"]
    run_cli("train_end2end", common + [
        "--image_set", "2007_trainval", "--end_epoch", "6",
        "--batch_images", "2", "--lr", "0.005", "--frequent", "8",
    ] + TINY_TRAIN)

    dets_pkl = str(mini_voc / "dets.pkl")
    stats = run_cli("test", common + [
        "--image_set", "2007_minitest", "--epoch", "6",
        "--dets_cache", dets_pkl,
    ] + TINY_TEST)
    fixture_map = float(np.mean([stats[c] for c in FIXTURE_CLASSES]))
    assert fixture_map > 0.2, stats

    # reeval re-scores the cached detections to the same mAP, model-free
    from mx_rcnn_tpu.tools import reeval as reeval_mod

    re_stats = run_tool(
        reeval_mod, reeval_mod.reeval,
        common + ["--image_set", "2007_minitest", "--detections", dets_pkl]
        + TINY_TEST)
    assert abs(re_stats["mAP"] - stats["mAP"]) < 1e-6
    # absent classes must score 0 (no spurious credit)
    absent = [v for k, v in stats.items()
              if k not in FIXTURE_CLASSES and k != "mAP"]
    assert max(absent) == 0.0

    # the official per-class writeout (write_results) through the real path
    out_dir = mini_voc / "results"
    from mx_rcnn_tpu.data.pascal_voc import PascalVOC

    imdb = PascalVOC("2007_minitest", str(mini_voc / "data"),
                     str(mini_voc / "VOCdevkit"))
    # re-evaluate from files via the imdb round trip: parse the comp4 files
    # back and check they contain detections for the fixture classes
    dets = [[np.zeros((0, 5), np.float32)] * imdb.num_images
            for _ in range(imdb.num_classes)]
    imdb.write_results(dets, str(out_dir))
    for cls in FIXTURE_CLASSES:
        assert (out_dir / f"comp4_det_2007_minitest_{cls}.txt").exists()


def test_demo_cli(mini_voc):
    """demo.py: single JPEG → detections → visualization written.  Reuses
    test_voc_train_eval_cli's checkpoint when the module ran in file order;
    selected alone, it trains its own 1-epoch checkpoint (round-2 advisor:
    the skip-when-alone ordering coupling was an implicit contract)."""
    import os

    prefix, epoch = mini_voc / "model" / "e2e", 6
    if not prefix.exists():
        prefix, epoch = mini_voc / "model" / "demo_own", 1
        run_cli("train_end2end", [
            "--network", "resnet50", "--dataset", "PascalVOC",
            "--root_path", str(mini_voc / "data"),
            "--dataset_path", str(mini_voc / "VOCdevkit"),
            "--prefix", str(prefix), "--devices", "1",
            "--image_set", "2007_trainval", "--end_epoch", "1",
            "--batch_images", "2", "--lr", "0.005",
        ] + TINY_TRAIN)
    img = str(mini_voc / "VOCdevkit" / "VOC2007" / "JPEGImages" /
              "001000.jpg")  # a test-split image the train never saw
    out = str(mini_voc / "demo_out.jpg")
    dets = run_cli("demo", [
        "--network", "resnet50", "--dataset", "PascalVOC",
        "--prefix", str(prefix), "--epoch", str(epoch),
        "--image", img, "--out", out, "--thresh", "0.3",
    ] + TINY_TEST)
    assert os.path.exists(out)
    assert isinstance(dets, list)  # (label, (5,)) pairs; may be empty


def test_voc_train_alternate_smoke(mini_voc):
    """The 7-stage alternate pipeline runs over files end-to-end (capped
    steps; exercises train_rpn → generate_proposals → train_rcnn ×2 +
    combine_model)."""
    run_cli("train_alternate", [
        "--network", "resnet50", "--dataset", "PascalVOC",
        "--image_set", "2007_trainval",
        "--root_path", str(mini_voc / "data"),
        "--dataset_path", str(mini_voc / "VOCdevkit"),
        "--prefix", str(mini_voc / "model" / "alt"),
        "--devices", "1", "--batch_images", "2",
        "--end_epoch", "1", "--num-steps", "2",
    ] + TINY_TRAIN)
    import os

    assert os.path.isdir(str(mini_voc / "model"))


def _coco_eval_setup(tmp_path, network: str, n_images: int,
                     max_per_image: int):
    """Shared mini-COCO-on-disk eval harness: fixture files → imdb/roidb →
    random-weight Predictor + TestLoader (mechanics, not accuracy)."""
    import dataclasses

    import jax

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.data import TestLoader
    from mx_rcnn_tpu.data.coco_dataset import COCODataset
    from mx_rcnn_tpu.eval import Predictor
    from mx_rcnn_tpu.models import build_model, init_params
    from mx_rcnn_tpu.train.checkpoint import denormalize_for_save

    make_mini_coco(str(tmp_path / "coco"), image_set="minitrain",
                   n=n_images, with_masks=True)
    cfg = generate_config(
        network, "coco",
        TEST__RPN_PRE_NMS_TOP_N=200, TEST__RPN_POST_NMS_TOP_N=16,
        TEST__MAX_PER_IMAGE=max_per_image,
    )
    cfg = cfg.replace(
        network=dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4)),
        tpu=dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=8))
    imdb = COCODataset("minitrain", str(tmp_path / "data"),
                       str(tmp_path / "coco"))
    roidb = imdb.gt_roidb()
    model = build_model(cfg)
    params = denormalize_for_save(
        init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96)), cfg)
    return cfg, imdb, roidb, Predictor(model, params, cfg)


def test_coco_pipeline_files(tmp_path):
    """mini-COCO on disk: json parse → roidb → TestLoader → pred_eval →
    result-json writeout + COCOeval stats (random weights — the assertion
    is the file pipeline's mechanics, accuracy is VOC's job above)."""
    from mx_rcnn_tpu.eval import pred_eval

    from mx_rcnn_tpu.data import TestLoader

    cfg, imdb, roidb, pred = _coco_eval_setup(
        tmp_path, "resnet50", n_images=4, max_per_image=10)
    assert imdb.num_images == 4
    assert imdb.num_classes == 1 + len(FIXTURE_CLASSES)
    assert all(r["boxes"].shape[1] == 4 for r in roidb)
    stats = pred_eval(pred, TestLoader(roidb, cfg, batch_size=2), imdb,
                      thresh=1e-3)
    # COCOeval protocol keys present (AP may legitimately be ~0 at random
    # weights); the writeout file must exist
    assert "AP" in stats or any("AP" in k for k in stats)


def test_coco_segm_eval_files(tmp_path):
    """Mask config over mini-COCO FILES: polygon segmentations parse into
    the roidb, the mask branch runs at eval, masks paste into full-image
    RLEs, and ``evaluate_sds`` scores bbox AND segm through the COCOeval
    protocol (random weights — mechanics, not accuracy)."""
    from mx_rcnn_tpu.eval import pred_eval

    from mx_rcnn_tpu.data import TestLoader

    cfg, imdb, roidb, pred = _coco_eval_setup(
        tmp_path, "resnet101_fpn_mask", n_images=2, max_per_image=5)
    assert any(r.get("segmentation") for r in roidb), "polygons must load"
    stats = pred_eval(pred, TestLoader(roidb, cfg, batch_size=1), imdb,
                      thresh=1e-3, with_masks=True)
    assert "bbox" in stats and "segm" in stats, stats
    assert "AP" in stats["segm"]
