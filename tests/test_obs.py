"""Live observability plane tier-1 tests (CPU).

Covers the three tentpole pieces end to end without network flakiness:
Prometheus rendering + the rank-0 obs server folding peer snapshot files
(the cross-rank scrape contract, emulated with a second rank's sink
publishing through the same snapshot files a real peer process would),
the flight recorder through real ``fit`` runs (NaN halt and SIGTERM via
``tests/faults.py``), and the Chrome trace export (nesting + JSON round
trip).  Satellites ride along: gauge min/max/last exposure, the serve
frontend's content negotiation, and ``scripts/perf_gate.py``.
"""

import glob
import importlib.util
import json
import os
import pathlib
import socket
import subprocess
import sys
import threading
import urllib.request

import pytest

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.telemetry import RING_SIZE, Telemetry
from mx_rcnn_tpu.telemetry.obs import (ObsPlane, ObsServer, prometheus_text,
                                       read_peer_snapshots, write_snapshot)
from mx_rcnn_tpu.telemetry.trace import chrome_trace
from mx_rcnn_tpu.train import NonFiniteLossError, ResilienceOptions, fit

from .faults import NanBatchLoader, SignalAtBatchLoader
from .test_resilience import tiny_data, tiny_model

REPO = pathlib.Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _restore_sink():
    """Every test leaves the module-global sink as it found it: NULL."""
    yield
    telemetry.shutdown()


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def http_get(port, path, timeout=10.0):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=timeout) as r:
        return r.status, r.read().decode()


# -- prometheus rendering --------------------------------------------------


def test_prometheus_text_rendering():
    per_rank = {
        0: {"counters": {"train/steps": 7, "train/recompile": 2},
            "spans": {"train/dispatch": {"count": 3, "total_s": 1.5,
                                         "mean_s": 0.5, "min_s": 0.25,
                                         "max_s": 0.75}},
            "gauges": {"loader/queue_depth": {"count": 4, "mean": 2.5,
                                              "min": 0.0, "max": 9.0,
                                              "last": 2.0}}},
        1: {"counters": {"train/steps": 5}},
    }
    text = prometheus_text(per_rank, ages={1: 1.5})
    assert text.endswith("\n")
    lines = text.splitlines()
    # counters, labeled per rank, family TYPE declared once
    assert 'mxr_train_steps_total{rank="0"} 7' in lines
    assert 'mxr_train_steps_total{rank="1"} 5' in lines
    assert lines.count("# TYPE mxr_train_steps_total counter") == 1
    # spans → seconds/calls counters + max gauge
    assert 'mxr_train_dispatch_seconds_total{rank="0"} 1.5' in lines
    assert 'mxr_train_dispatch_calls_total{rank="0"} 3' in lines
    assert 'mxr_train_dispatch_seconds_max{rank="0"} 0.75' in lines
    # gauges expose the extremes, not just the final sample
    assert 'mxr_loader_queue_depth{rank="0",stat="last"} 2.0' in lines
    assert 'mxr_loader_queue_depth{rank="0",stat="min"} 0.0' in lines
    assert 'mxr_loader_queue_depth{rank="0",stat="max"} 9.0' in lines
    assert 'mxr_loader_queue_depth{rank="0",stat="mean"} 2.5' in lines
    # liveness + snapshot staleness
    assert 'mxr_up{rank="0"} 1' in lines and 'mxr_up{rank="1"} 1' in lines
    assert 'mxr_snapshot_age_seconds{rank="1"} 1.5' in lines


def test_gauge_summary_extremes_feed_the_endpoint(tmp_path):
    # the /metrics gauge stats come straight from Telemetry.summary():
    # min/max/last must survive the sink → summary → render path
    tel = Telemetry(str(tmp_path), rank=0)
    for v in (3.0, 9.0, 1.0):
        tel.gauge("loader/queue_depth", v)
    text = prometheus_text({0: tel.summary()})
    tel.close()
    assert 'mxr_loader_queue_depth{rank="0",stat="min"} 1.0' in text
    assert 'mxr_loader_queue_depth{rank="0",stat="max"} 9.0' in text
    assert 'mxr_loader_queue_depth{rank="0",stat="last"} 1.0' in text


def _lint_exposition(text):
    """Prometheus exposition lint (ISSUE 20 satellite): every sampled
    ``mxr_*`` family must declare ``# HELP`` then ``# TYPE`` exactly
    once, both before the family's first sample."""
    helped, typed, sampled = set(), set(), set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            fam = line.split()[2]
            assert fam not in helped, f"duplicate HELP for {fam}"
            assert fam not in sampled, f"HELP after samples for {fam}"
            helped.add(fam)
        elif line.startswith("# TYPE "):
            fam = line.split()[2]
            assert fam not in typed, f"duplicate TYPE for {fam}"
            assert fam in helped, f"TYPE before HELP for {fam}"
            typed.add(fam)
        elif not line.startswith("#"):
            fam = line.split("{", 1)[0].split(" ", 1)[0]
            if fam not in typed:
                # histogram samples hang off the base family's TYPE
                base = fam.rsplit("_", 1)[0]
                assert (fam.endswith(("_bucket", "_sum", "_count"))
                        and base in typed), \
                    f"sample before TYPE for {fam}"
                fam = base
            sampled.add(fam)
    assert sampled, "exposition rendered no samples at all"


def test_exposition_lint_every_family_has_help_and_type(tmp_path):
    tel = Telemetry(str(tmp_path), rank=0)
    tel.counter("train/steps", 7)
    tel.counter("serve/requests", 3)
    tel.gauge("loader/queue_depth", 2.0)
    tel.observe("serve/request_time", 0.05)
    with tel.span("train/dispatch"):
        pass
    text = prometheus_text({0: tel.summary()}, ages={0: 0.5})
    tel.close()
    _lint_exposition(text)
    # the appended mxr_alert_state family (serve_prometheus /
    # fabric_prometheus with a watchtower attached) lints the same way
    from mx_rcnn_tpu.telemetry.watch import Watchtower, alert_state_lines

    wt = Watchtower(rules=[{"name": "hot", "kind": "threshold",
                            "metric": "m", "op": ">", "value": 1.0}],
                    summary_fn=lambda: {"gauges": {"m": {"last": 5.0}}})
    wt.tick(now=0.0)
    _lint_exposition(text + "\n".join(alert_state_lines(wt, now=0.0))
                     + "\n")


# -- obs server + cross-rank fold ------------------------------------------


def test_obs_server_scrape_folds_both_ranks(tmp_path):
    """The acceptance contract: one rank-0 scrape returns metrics labeled
    for every rank.  Rank 1 publishes through the same snapshot file a
    real peer process drops under --telemetry-dir."""
    d = str(tmp_path)
    peer = Telemetry(d, rank=1, world=2)
    peer.counter("train/steps", 5)
    peer.gauge("loader/queue_depth", 3.0)
    assert write_snapshot(peer) == os.path.join(d, "snapshot_rank1.json")
    peer.close()

    telemetry.configure(d, rank=0, world=2)
    telemetry.get().counter("train/steps", 7)
    srv = ObsServer(0, telemetry_dir=d)  # port 0 → ephemeral
    try:
        status, body = http_get(srv.port, "/metrics")
        assert status == 200
        assert 'mxr_train_steps_total{rank="0"} 7' in body
        assert 'mxr_train_steps_total{rank="1"} 5' in body
        assert 'mxr_snapshot_age_seconds{rank="1"}' in body
        status, health = http_get(srv.port, "/healthz")
        assert status == 200 and json.loads(health)["status"] == "ok"
        with pytest.raises(urllib.error.HTTPError) as ei:
            http_get(srv.port, "/nope")
        assert ei.value.code == 404
    finally:
        srv.close()


def test_obs_scrape_with_real_peer_process(tmp_path):
    """mp_worker.py-style: rank 1 is a REAL second OS process publishing
    its snapshot over the shared telemetry dir; the rank-0 scrape in this
    process sees both ranks.  The peer imports only the telemetry
    subpackage (no jax), so this costs one interpreter startup."""
    d = str(tmp_path)
    peer_prog = (
        "import sys\n"
        "from mx_rcnn_tpu import telemetry\n"
        "from mx_rcnn_tpu.telemetry.obs import write_snapshot\n"
        "telemetry.configure(sys.argv[1], rank=1, world=2)\n"
        "telemetry.get().counter('train/steps', 11)\n"
        "assert write_snapshot() is not None\n"
        "telemetry.shutdown()\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", peer_prog, d],
                       capture_output=True, text=True, env=env,
                       timeout=120)
    assert r.returncode == 0, r.stderr

    telemetry.configure(d, rank=0, world=2)
    telemetry.get().counter("train/steps", 13)
    srv = ObsServer(0, telemetry_dir=d)
    try:
        _, body = http_get(srv.port, "/metrics")
        assert 'mxr_train_steps_total{rank="0"} 13' in body
        assert 'mxr_train_steps_total{rank="1"} 11' in body
    finally:
        srv.close()


def test_peer_snapshot_reader_skips_own_rank_and_garbage(tmp_path):
    d = str(tmp_path)
    peer = Telemetry(d, rank=1, world=2, stream=False)
    peer.counter("c", 1)
    write_snapshot(peer)
    peer.close()
    with open(os.path.join(d, "snapshot_rank2.json"), "w") as f:
        f.write("{half a json")  # a peer dying mid-publish must not 500
    per_rank, ages = read_peer_snapshots(d, skip_rank=1)
    assert per_rank == {} and ages == {}
    per_rank, _ = read_peer_snapshots(d)
    assert list(per_rank) == [1]


def test_obs_plane_lifecycle_and_inertness(tmp_path):
    # port unset → fully inert: no sink, no threads, no excepthook swap
    hook = sys.excepthook
    plane = ObsPlane(port=0, telemetry_dir="", rank=0, world=1)
    assert not plane.active and plane.server is None
    assert not telemetry.get().enabled
    assert sys.excepthook is hook
    plane.close()

    # port set → owns an in-stream sink, serves, writes summary on close
    plane = ObsPlane(port=free_port(), telemetry_dir=str(tmp_path),
                     rank=0, world=1, run_meta={"driver": "test_obs"})
    try:
        assert plane.owns_sink and telemetry.get().enabled
        assert sys.excepthook is not hook
        telemetry.get().counter("train/steps", 3)
        _, body = http_get(plane.server.port, "/metrics")
        assert 'mxr_train_steps_total{rank="0"} 3' in body
    finally:
        plane.close()
    assert not telemetry.get().enabled  # plane shut its own sink down
    assert sys.excepthook is hook
    summary = json.load(open(tmp_path / "summary.json"))
    assert summary["counters"]["train/steps"] == 3
    # the final snapshot from the writer's stop() is on disk too
    assert (tmp_path / "snapshot_rank0.json").exists()


# -- flight recorder -------------------------------------------------------


def flight_events(path):
    events = [json.loads(line) for line in open(path)]  # all valid JSONL
    assert all("kind" in e and "t" in e for e in events)
    return events


def test_flight_ring_bound_and_trigger(tmp_path):
    tel = Telemetry(str(tmp_path), rank=0, ring_size=8)
    for i in range(40):
        tel.counter("c")
    path = tel.dump_flight("test_reason", detail=7)
    tel.close()
    assert path == str(tmp_path / "flight_0.jsonl")
    events = flight_events(path)
    assert len(events) <= 8  # ring bound holds (trigger included)
    last = events[-1]
    assert last["kind"] == "meta" and last["name"] == "flight_trigger"
    assert last["fields"] == {"reason": "test_reason", "detail": 7}


def test_flight_dump_without_dir_is_none():
    tel = Telemetry("", rank=0, stream=False)
    tel.counter("c")
    assert tel.dump_flight("nowhere") is None
    tel.close()
    assert telemetry.NULL.dump_flight("ignored") is None


def test_nan_halt_dumps_flight(tmp_path):
    cfg, _, loader = tiny_data(n_images=8)
    model, params = tiny_model(cfg)
    tel_dir = tmp_path / "tel"
    with pytest.raises(NonFiniteLossError, match="policy=halt"):
        fit(cfg, model, params, NanBatchLoader(loader, 1),
            begin_epoch=0, end_epoch=1, prefix=str(tmp_path / "ck"),
            frequent=1, telemetry_dir=str(tel_dir),
            resilience=ResilienceOptions(nan_policy="halt"))
    events = flight_events(tel_dir / "flight_0.jsonl")
    assert len(events) <= RING_SIZE
    last = events[-1]
    assert last["name"] == "flight_trigger"
    assert last["fields"]["reason"] == "nan_detected"
    assert last["fields"]["policy"] == "halt"
    # the ring holds the run's tail: the nan counter/meta land just before
    names = [e["name"] for e in events]
    assert "nan_detected" in names and "train/nan_detected" in names


def test_sigterm_dumps_flight(tmp_path):
    cfg, _, loader = tiny_data(n_images=8)
    model, params = tiny_model(cfg)
    tel_dir = tmp_path / "tel"
    fit(cfg, model, params, SignalAtBatchLoader(loader, 2),
        begin_epoch=0, end_epoch=2, prefix=str(tmp_path / "ck"),
        frequent=1, telemetry_dir=str(tel_dir),
        resilience=ResilienceOptions(auto_resume=True,
                                     save_every_n_steps=100))
    events = flight_events(tel_dir / "flight_0.jsonl")
    assert len(events) <= RING_SIZE
    # the handler's immediate dump is superseded by the step-boundary one,
    # so the final events explain the shutdown in order: signal → boundary
    last = events[-1]
    assert last["name"] == "flight_trigger"
    assert last["fields"]["reason"] == "preempted"
    sigs = [e for e in events if e["name"] == "flight_trigger"
            and e["fields"]["reason"] == "preempt_signal"]
    assert sigs and sigs[0]["fields"]["signal"] == "SIGTERM"


# -- trace export ----------------------------------------------------------


def test_trace_export_nested_spans_roundtrip(tmp_path):
    tel = Telemetry(str(tmp_path), rank=0, trace=True)
    with tel.span("train/epoch"):
        with tel.span("train/dispatch"):
            pass
        with tel.span("train/dispatch"):
            pass
    tel.counter("train/steps", 2)
    tel.gauge("loader/queue_depth", 4.0)
    tel.add("loader/worker0/produce", 0.01)
    tel.meta("flight_trigger", reason="unit")
    tel.close()
    events = [json.loads(line)
              for line in open(tmp_path / "events_rank0.jsonl")]
    doc = json.loads(json.dumps(chrome_trace(events)))  # round-trips
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    outer = next(e for e in xs if e["name"] == "train/epoch")
    inners = [e for e in xs if e["name"] == "train/dispatch"]
    assert len(inners) == 2
    for e in inners:  # nested inside the epoch span, same track
        assert e["pid"] == outer["pid"] and e["tid"] == outer["tid"]
        assert outer["ts"] <= e["ts"]
        assert e["ts"] + e["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    # worker spans get their own named track
    worker = next(e for e in xs if e["name"] == "loader/worker0/produce")
    assert worker["tid"] != outer["tid"]
    meta_names = {m["args"]["name"] for m in evs if m["ph"] == "M"}
    assert "rank 0" in meta_names and "worker0" in meta_names
    # counters/gauges plot; meta becomes an instant crash marker
    assert any(e["ph"] == "C" and e["name"] == "train/steps" for e in evs)
    assert any(e["ph"] == "i" and e["name"] == "flight_trigger"
               for e in evs)


def test_trace_spans_without_ts_derive_start(tmp_path):
    tel = Telemetry(str(tmp_path), rank=0)  # trace off: no "ts" field
    tel.add("train/dispatch", 2.0)
    tel.close()
    events = [json.loads(line)
              for line in open(tmp_path / "events_rank0.jsonl")]
    assert all("ts" not in e for e in events if e["kind"] == "span")
    xs = [e for e in chrome_trace(events)["traceEvents"]
          if e["ph"] == "X"]
    assert len(xs) == 1 and xs[0]["dur"] == pytest.approx(2e6)


def test_report_cli_trace_flag(tmp_path):
    tel = Telemetry(str(tmp_path / "tel"), rank=0)
    with tel.span("eval/forward"):
        pass
    tel.counter("eval/images", 4)
    tel.close()
    out = tmp_path / "trace.json"
    r = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "telemetry_report.py"),
         str(tmp_path / "tel"), "--trace", str(out)],
        capture_output=True, text=True, cwd=str(REPO), timeout=120)
    assert r.returncode == 0, r.stderr
    doc = json.load(open(out))
    assert len(doc["traceEvents"]) > 0


# -- perf gate -------------------------------------------------------------


def _perf_gate():
    spec = importlib.util.spec_from_file_location(
        "perf_gate", str(REPO / "scripts" / "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_file(path, n, vs, metric="m", **extra):
    row = {"metric": metric, "value": 10.0 * n, "unit": "imgs/sec",
           "vs_baseline": vs, **extra}
    with open(path, "w") as f:
        json.dump({"n": n, "cmd": "bench", "rc": 0, "tail": "",
                   "parsed": row}, f)


def test_perf_gate_passes_and_fails(tmp_path):
    pg = _perf_gate()
    for i, vs in enumerate([1.0, 1.2, 1.19], 1):  # within 10% of best
        _bench_file(tmp_path / f"BENCH_r0{i}.json", i, vs)
    assert pg.main(["--dir", str(tmp_path)]) == 0
    _bench_file(tmp_path / "BENCH_r04.json", 4, 1.0)  # >10% below 1.2
    assert pg.main(["--dir", str(tmp_path)]) == 1


def test_perf_gate_skips_baseline_recorded_and_methods(tmp_path):
    pg = _perf_gate()
    _bench_file(tmp_path / "BENCH_r01.json", 1, 1.5)
    _bench_file(tmp_path / "BENCH_r02.json", 2, None,
                baseline_recorded=True)  # null ratio: recorded, not scored
    # a method switch resets the comparison group — 1.0 after a
    # cross-method 1.5 is not a regression
    _bench_file(tmp_path / "BENCH_r03.json", 3, 1.0,
                baseline_method="chain")
    assert pg.main(["--dir", str(tmp_path)]) == 0


def test_perf_gate_check_format(tmp_path):
    pg = _perf_gate()
    _bench_file(tmp_path / "BENCH_r01.json", 1, 1.0)
    assert pg.main(["--check-format", "--dir", str(tmp_path)]) == 0
    with open(tmp_path / "BENCH_r02.json", "w") as f:
        json.dump({"rc": 0, "tail": "no parsed row"}, f)
    assert pg.main(["--check-format", "--dir", str(tmp_path)]) == 1


def test_perf_gate_checked_in_trajectory():
    # the repo's own BENCH_*.json must stay gate- and format-clean
    pg = _perf_gate()
    assert pg.main(["--check-format", "--dir", str(REPO)]) == 0
    assert pg.main(["--dir", str(REPO)]) == 0


# -- serve frontend content negotiation ------------------------------------


def test_serve_metrics_content_negotiation(tmp_path):
    from mx_rcnn_tpu.serve import make_server, unix_http_request

    from .test_serve import make_engine, tiny_cfg

    engine = make_engine(tiny_cfg()).start()
    sock = str(tmp_path / "serve.sock")
    server = make_server(engine, unix_socket=sock)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        # default stays JSON for existing callers
        status, doc = unix_http_request(sock, "GET", "/metrics")
        assert status == 200 and isinstance(doc, dict)
        assert "counters" in doc and "queue_depth" in doc
        # ?format=prom negotiates the text exposition
        status, text = unix_http_request(sock, "GET",
                                         "/metrics?format=prom")
        assert status == 200 and isinstance(text, str)
        assert 'mxr_serve_requests_total{rank="0"} 0' in text
        assert 'mxr_serve_queue_depth{rank="0",stat="last"} 0' in text
        # Accept: text/plain too
        status, text2 = unix_http_request(
            sock, "GET", "/metrics", headers={"Accept": "text/plain"})
        assert status == 200 and "mxr_serve_requests_total" in text2
        # /predict and /healthz untouched by the negotiation change
        status, health = unix_http_request(sock, "GET", "/healthz")
        assert status == 200 and health["status"] == "ok"
    finally:
        server.shutdown()
        server.server_close()
        engine.stop()
