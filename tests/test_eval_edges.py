"""pred_eval edge cases (VERDICT round-1 item 8): the max_per_image cap
under score ties at the threshold boundary, and the mask chunk-drain loop
when detections exceed the static chunk size R.  Driven through the REAL
``pred_eval`` loop with a stub predictor whose outputs are hand-crafted.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.eval.tester import pred_eval


class StubPredictor:
    """Emits R fixed, well-separated boxes per image with crafted
    per-class scores; optionally a mask branch with call accounting."""

    def __init__(self, cfg, scores, boxes):
        self.cfg = cfg
        self._scores = scores            # (B, R, K)
        self._boxes = boxes              # (B, R, 4)
        self.mask_calls = 0
        self._feats = object()

    def predict(self, images, im_info):
        B, R, K = self._scores.shape
        rois = jnp.asarray(self._boxes)
        deltas = jnp.zeros((B, R, 4 * K), jnp.float32)  # identity decode
        return (rois, jnp.ones((B, R), bool), jnp.asarray(self._scores),
                deltas, None)

    def predict_masks_cached(self, boxes, labels, token=None):
        self.mask_calls += 1
        B, R = labels.shape
        return np.full((B, R, 28, 28), 0.9, np.float32)

    def predict_masks_packed(self, boxes, labels, orig_boxes, hp, wp,
                             token=None):
        # the real device-paste op over the stub's constant probabilities
        # (cfg.TEST.MASK_PASTE == "device" mode)
        from mx_rcnn_tpu.ops.mask_paste import paste_masks

        probs = self.predict_masks_cached(boxes, labels, token)
        return paste_masks(probs, orig_boxes, hp, wp)


class StubLoader:
    def __init__(self, batch, roidb):
        self._batch = batch
        self.roidb = roidb

    def __iter__(self):
        return iter([self._batch])


class RecordingIMDB:
    """Captures what pred_eval hands to evaluation."""

    def __init__(self, num_classes, num_images, with_sds=False):
        self.num_classes = num_classes
        self.num_images = num_images
        self.captured = {}
        if with_sds:
            self.evaluate_sds = self._evaluate_sds

    def evaluate_detections(self, all_boxes):
        self.captured["boxes"] = all_boxes
        return {"mAP": 0.0}

    def _evaluate_sds(self, all_boxes, all_masks):
        self.captured["boxes"] = all_boxes
        self.captured["masks"] = all_masks
        return {"bbox": {"mAP": 0.0}}


def _setup(num_classes=3, R=12, B=1, H=64, W=96, mask=False):
    cfg = generate_config("resnet101_fpn_mask" if mask else "resnet101",
                          "PascalVOC")
    batch = dict(
        images=np.zeros((B, H, W, 3), np.float32),
        im_info=np.tile(np.asarray([[H, W, 1.0]], np.float32), (B, 1)),
        indices=np.arange(B, dtype=np.int32),
        batch_valid=np.ones((B,), bool),
    )
    # R well-separated 8x8 boxes on a grid: NMS at 0.3 keeps all of them
    boxes = np.zeros((B, R, 4), np.float32)
    for r in range(R):
        x, y = 10 * (r % 6), 20 * (r // 6)
        boxes[:, r] = (x, y, x + 8, y + 8)
    roidb = [{"height": H, "width": W} for _ in range(B)]
    return cfg, batch, boxes, roidb


def test_max_per_image_cap_keeps_threshold_ties():
    """12 detections, cap 4.  Scores: two at 0.9, then SIX tied exactly at
    0.5, rest at 0.2.  The cap threshold is the 4th-highest score (0.5);
    the reference keeps every det >= threshold, so ALL six ties survive
    → 8 detections, not 4.  (Reference semantics: tester.py max_per_image
    block uses >=; silently truncating ties would be a behavior change.)"""
    cfg, batch, boxes, roidb = _setup()
    K = 3
    scores = np.zeros((1, 12, K), np.float32)
    scores[0, :, 0] = 1.0  # background column, ignored
    fg = np.array([0.9, 0.9, 0.5, 0.5, 0.5, 0.5, 0.5, 0.5, 0.2, 0.2, 0.2,
                   0.2], np.float32)
    scores[0, :, 1] = fg
    imdb = RecordingIMDB(num_classes=K, num_images=1)
    pred = StubPredictor(cfg, scores, boxes)
    pred_eval(pred, StubLoader(batch, roidb), imdb, max_per_image=4,
              thresh=0.05)
    kept = imdb.captured["boxes"][1][0]
    assert len(kept) == 8, kept[:, 4]
    assert (kept[:, 4] >= 0.5).all()
    # and class 2 (no dets above threshold after cap) is an empty array,
    # not None
    assert len(imdb.captured["boxes"][2][0]) == 0


def test_max_per_image_cap_across_classes():
    """The cap pools scores across classes before thresholding (reference:
    np.sort over the hstack of all classes' scores)."""
    cfg, batch, boxes, roidb = _setup()
    K = 3
    scores = np.zeros((1, 12, K), np.float32)
    scores[0, :6, 1] = [0.9, 0.8, 0.7, 0.2, 0.15, 0.1]
    scores[0, 6:, 2] = [0.85, 0.75, 0.3, 0.12, 0.11, 0.1]
    imdb = RecordingIMDB(num_classes=K, num_images=1)
    pred_eval(StubPredictor(cfg, scores, boxes), StubLoader(batch, roidb),
              imdb, max_per_image=4, thresh=0.05)
    c1 = imdb.captured["boxes"][1][0][:, 4]
    c2 = imdb.captured["boxes"][2][0][:, 4]
    # top-4 pooled = {0.9, 0.85, 0.8, 0.75} → 2 from each class
    assert len(c1) == 2 and len(c2) == 2
    np.testing.assert_allclose(
        np.sort(np.concatenate([c1, c2])), [0.75, 0.8, 0.85, 0.9], atol=1e-6)


def test_vis_all_detection_writes_file(tmp_path):
    """pred_eval(vis=True)'s drawing path: vis_all_detection renders the
    per-class detections onto the image array and writes a jpg."""
    from mx_rcnn_tpu.eval.tester import vis_all_detection

    rec = {"image_array": np.full((64, 96, 3), 127, np.uint8),
           "height": 64, "width": 96}
    dets = [None,
            np.asarray([[5, 5, 40, 40, 0.9]], np.float32),
            np.asarray([[50, 10, 90, 60, 0.4]], np.float32)]
    out = tmp_path / "vis.jpg"
    vis_all_detection(rec, dets, ["bg", "a", "b"], str(out), thresh=0.3)
    assert out.exists() and out.stat().st_size > 0


def test_mask_chunk_drain_exceeds_chunk():
    """Mask pass with cap 4 but 10 surviving detections per image: the
    static chunk is R=4, so the drain loop must run 3 passes and every
    detection row must get an RLE (no silent drops)."""
    cfg, batch, boxes, roidb = _setup(mask=True)
    K = 3
    scores = np.zeros((1, 12, K), np.float32)
    # ten tied scores at 0.5 → cap threshold 0.5 keeps all ten (tie rule)
    scores[0, :10, 1] = 0.5
    imdb = RecordingIMDB(num_classes=K, num_images=1, with_sds=True)
    pred = StubPredictor(cfg, scores, boxes)
    stats = pred_eval(pred, StubLoader(batch, roidb), imdb, max_per_image=4,
                      thresh=0.05, with_masks=True)
    assert "bbox" in stats
    kept = imdb.captured["boxes"][1][0]
    masks = imdb.captured["masks"][1][0]
    assert len(kept) == 10
    assert len(masks) == 10 and all(m is not None for m in masks)
    assert pred.mask_calls == 3  # ceil(10 / 4) chunks
    # RLE decodes back to a mask covering the box area
    from mx_rcnn_tpu.eval.mask_rle import decode

    m0 = decode(masks[0])
    assert m0.shape == (64, 96)
    assert m0.sum() > 0
