"""Watchtower tests (ISSUE 20).

Three layers, mirroring tests/test_autoscale.py:

* **Rule pack + history** — validation errors that name the offending
  rule, the shipped default pack, fingerprint stability, and the
  raw → 10s → 60s downsampling tiers (bounded memory, one stitched
  timeline, runaway-cardinality drop).
* **Lifecycle control loop** — deterministic fake-clock ``tick(now=)``
  tests over injected providers for all four rule kinds: threshold
  hold/fire/resolve (plus rate mode and the guard clause), burn-rate
  dual-window math against a real :class:`Hist` (and THE no-traffic
  pin: windowed quantiles never decay, so only the advance gate lets a
  burn alert resolve), absence arming (a series that never ran cannot
  fire its stall alert; parked fleet members are skipped), trend
  warmup, silences (mute the page, keep the record), and the
  ``alerts_<member>.jsonl`` / meta-event / flight-dump transition
  fan-out.
* **End-to-end** — a REAL router watchtower over REAL localhost-TCP
  members: killing one fires ``member_stale`` on the router with the
  tail-sampled trace ids attached, and a restart on the same address
  resolves it — the full arc persisted in ``alerts_router.jsonl``.

Plus the satellite pins: ``mxr_alert_state`` exposition format,
perf_gate ``mxr_watch_report`` rows, loadgen ``--watch-check``
semantics, and dormancy (watch off = fabric metrics, exposition and
telemetry JSONL byte-for-byte unchanged).
"""

import glob
import json
import os

import pytest

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.serve import fabric as fb
from mx_rcnn_tpu.telemetry import tracectx
from mx_rcnn_tpu.telemetry.sink import Hist
from mx_rcnn_tpu.telemetry.watch import (MetricHistory, RuleError,
                                         WatchOptions, Watchtower,
                                         alert_state_lines, default_rules,
                                         fingerprint, fleet_from_pool,
                                         load_rules, validate_rules)
from tests.test_fabric import (A, B, _cleanup, _e2e_opts, _free_port,
                               _load_script, _member_proc, _predict_body,
                               _ready_pool, _wait)


@pytest.fixture(autouse=True)
def _restore_sink():
    yield
    telemetry.shutdown()
    tracectx.shutdown()


# -- options + rule pack ----------------------------------------------------


def test_watch_options_validation():
    with pytest.raises(ValueError):
        WatchOptions(interval_s=0.0)
    with pytest.raises(ValueError):
        WatchOptions(raw_keep=1)
    with pytest.raises(ValueError):
        WatchOptions(mid_step_s=60.0, coarse_step_s=10.0)
    with pytest.raises(ValueError):
        WatchOptions(max_series=0)


def _rule(**kw):
    base = {"name": "r", "kind": "threshold", "metric": "m",
            "op": ">", "value": 1.0}
    base.update(kw)
    return base


def test_rule_validation_errors_name_the_rule():
    cases = [
        ([{"kind": "threshold"}], "rule 0: missing required key 'name'"),
        ([_rule(kind="nope")], "rule 0 ('r')"),
        ([_rule(), _rule()], "rule 1 ('r'): duplicate"),
        ([_rule(bogus=1)], "unknown keys"),
        ([_rule(op="!=")], "op must be"),
        ([_rule(labels={"k": 1})], "labels must map strings"),
        ([_rule(scope="galaxy")], "scope must be"),
        ([_rule(kind="burn_rate", op=None, value=None, target_ms=100,
                fast_window_s=60, slow_window_s=30)],
         "slow_window_s must be >= fast_window_s"),
        ([_rule(guard={"metric": "g", "op": "=", "value": 0})],
         "guard.op"),
        ([_rule(for_s=-1)], "for_s must be >= 0"),
    ]
    for rules, needle in cases:
        rules = [{k: v for k, v in r.items() if v is not None}
                 for r in rules]
        with pytest.raises(RuleError) as ei:
            validate_rules(rules)
        assert needle in str(ei.value), (rules, str(ei.value))
    with pytest.raises(RuleError, match="version"):
        validate_rules({"version": 2, "rules": []})


def test_rule_defaults_filled_in():
    (r,) = validate_rules([{"name": "b", "kind": "burn_rate",
                            "metric": "m", "target_ms": 100}])
    assert r["quantile"] == 0.99 and r["budget"] == 0.05
    assert (r["fast_window_s"], r["slow_window_s"]) == (60.0, 300.0)
    assert (r["fast_burn"], r["slow_burn"]) == (6.0, 2.0)
    assert r["for_s"] == 0.0 and r["severity"] == "warning"
    assert r["scope"] == "local" and r["labels"] == {}


def test_default_pack_loads_and_names():
    names = {r["name"] for r in default_rules()}
    assert names == {"serve_p99_burn", "fabric_p99_burn", "shed_rate",
                     "steady_state_recompile", "member_stale",
                     "parked_fleet_under_load",
                     "flywheel_generation_stall"}


def test_load_rules_bad_file_names_the_path(tmp_path):
    p = tmp_path / "rules.json"
    p.write_text("{not json")
    with pytest.raises(RuleError, match="rules.json"):
        load_rules(str(p))
    with pytest.raises(RuleError, match="missing.json"):
        load_rules(str(tmp_path / "missing.json"))
    p.write_text(json.dumps([_rule(op="!=")]))
    with pytest.raises(RuleError, match="rule 0 \\('r'\\)"):
        load_rules(str(p))


def test_fingerprint_stable_and_label_sensitive():
    fp = fingerprint("a", {"x": "1", "y": "2"})
    assert fp == fingerprint("a", {"y": "2", "x": "1"})
    assert fp != fingerprint("a", {"x": "2", "y": "2"})
    assert fp != fingerprint("b", {"x": "1", "y": "2"})


# -- metric history ---------------------------------------------------------


def test_history_tiers_bound_memory_and_stitch_one_timeline():
    opts = WatchOptions(raw_keep=16, mid_keep=8, coarse_keep=8,
                        mid_step_s=10.0, coarse_step_s=60.0)
    h = MetricHistory(opts)
    for t in range(1200):                      # 20 min at 1 Hz
        h.record("m", float(t), float(t))
    pts = h.series("m", 1200.0, 1199.0)
    ts = [t for t, _ in pts]
    # one merged timeline: strictly increasing, no tier overlap, and
    # bounded far below the 1200 samples recorded
    assert ts == sorted(ts) and len(ts) == len(set(ts))
    assert len(pts) <= 16 + 8 + 8 + 2
    assert pts[-1] == (1199.0, 1199.0)         # newest raw point intact
    # the trailing window filter trims the coarse tail
    short = h.series("m", 100.0, 1199.0)
    assert all(t >= 1099.0 for t, _ in short) and short[-1][0] == 1199.0


def test_history_max_series_cap_drops_and_counts():
    h = MetricHistory(WatchOptions(max_series=2))
    for name in ("a", "b", "c", "c"):
        h.record(name, 1.0, 0.0)
    assert h.names() == ["a", "b"]
    assert h.stats() == {"series": 2, "dropped": 2}


def test_history_to_doc_stats():
    h = MetricHistory()
    for t, v in enumerate((3.0, 9.0, 1.0)):
        h.record("q", v, float(t))
    doc = h.to_doc("q", 60.0, 3.0)
    assert doc["metric"] == "q" and len(doc["points"]) == 3
    assert (doc["last"], doc["min"], doc["max"]) == (1.0, 1.0, 9.0)
    assert abs(doc["mean"] - 13.0 / 3) < 1e-9
    assert "last" not in h.to_doc("missing", 60.0, 3.0)


def test_last_change_age_arms_only_after_a_change():
    h = MetricHistory()
    for t in range(5):
        h.record("g", 7.0, float(t))
    age, changed = h.last_change_age("g", 10.0)
    assert not changed                         # constant series: unarmed
    h.record("g", 8.0, 5.0)
    age, changed = h.last_change_age("g", 11.0)
    assert changed and age == 6.0


# -- threshold lifecycle ----------------------------------------------------


class _Feed:
    """Scriptable summary provider: set gauges/counters per tick."""

    def __init__(self):
        self.counters = {}
        self.gauges = {}

    def summary(self):
        return {"counters": dict(self.counters),
                "gauges": {k: {"last": v}
                           for k, v in self.gauges.items()}}


def test_threshold_hold_fire_resolve_and_jsonl(tmp_path):
    feed = _Feed()
    rule = _rule(name="hot", for_s=2, severity="page")
    wt = Watchtower(rules=[rule], member="t", out_dir=str(tmp_path),
                    summary_fn=feed.summary)
    feed.gauges["m"] = 5.0
    recs = wt.tick(now=0.0)
    assert [r["state"] for r in recs] == ["pending"]
    assert wt.tick(now=1.0) == []              # hold not yet satisfied
    recs = wt.tick(now=2.0)
    assert [r["state"] for r in recs] == ["firing"]
    assert recs[0]["held_s"] == 2.0 and recs[0]["severity"] == "page"
    assert [i["alert"] for i in wt.firing(now=2.0)] == ["hot"]
    feed.gauges["m"] = 0.0
    recs = wt.tick(now=3.0)
    assert [r["state"] for r in recs] == ["resolved"]
    assert recs[0]["firing_s"] == 1.0
    assert wt.firing(now=3.0) == []
    # refire dedups onto the same fingerprint
    feed.gauges["m"] = 5.0
    fp2 = wt.tick(now=4.0)[0]["fingerprint"]
    assert fp2 == recs[0]["fingerprint"]
    # the atomic transition log holds the full arc
    path = tmp_path / "alerts_t.jsonl"
    logged = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["state"] for r in logged] == ["pending", "firing",
                                           "resolved", "pending"]
    assert all(r["kind"] == "alert" and r["member"] == "t"
               and r["alert"] == "hot" for r in logged)
    doc = wt.alerts_doc(now=4.0)
    assert [r["alert"] for r in doc["resolved"]] == ["hot"]
    assert doc["counters"]["fired"] == 1
    assert doc["counters"]["resolved"] == 1


def test_threshold_pending_that_clears_is_not_an_incident():
    feed = _Feed()
    wt = Watchtower(rules=[_rule(name="blip", for_s=10)],
                    summary_fn=feed.summary)
    feed.gauges["m"] = 5.0
    assert [r["state"] for r in wt.tick(now=0.0)] == ["pending"]
    feed.gauges["m"] = 0.0
    assert wt.tick(now=1.0) == []              # no resolved record
    doc = wt.alerts_doc(now=1.0)
    assert doc["resolved"] == [] and doc["counters"]["fired"] == 0


def test_threshold_rate_mode_with_guard():
    feed = _Feed()
    rule = _rule(name="shedding", metric="c", mode="rate",
                 window_s=10.0, value=0.5,
                 guard={"metric": "g", "op": ">", "value": 0.0})
    wt = Watchtower(rules=[rule], summary_fn=feed.summary)
    feed.gauges["g"] = 0.0
    for t in range(6):                         # counter rises 1/s
        feed.counters["c"] = float(t)
        wt.tick(now=float(t))
    assert wt.firing(now=5.0) == []            # guard blocks the rate
    feed.gauges["g"] = 1.0
    feed.counters["c"] = 6.0
    recs = wt.tick(now=6.0)
    assert [r["state"] for r in recs] == ["pending", "firing"]
    assert recs[1]["value"] == pytest.approx(1.0)  # the measured rate


# -- burn rate --------------------------------------------------------------


def _burn_rule(**kw):
    base = {"name": "burn", "kind": "burn_rate", "metric": "lat",
            "quantile": 0.99, "target_ms": 100, "budget": 0.5,
            "fast_window_s": 5, "slow_window_s": 10,
            "fast_burn": 1.0, "slow_burn": 1.0}
    base.update(kw)
    return base


def test_burn_rate_fires_under_breach_and_resolves_when_traffic_stops():
    h = Hist()
    wt = Watchtower(rules=[_burn_rule()], hists_fn=lambda: {"lat": h})
    states = []
    for t in range(8):                         # sustained 1s >> 100ms
        h.observe(1.0, now=float(t))
        states += [r["state"] for r in wt.tick(now=float(t))]
    assert states[:2] == ["pending", "firing"]
    assert wt.firing(now=7.0)[0]["alert"] == "burn"
    # traffic stops: the hist never decays, but the advance gate zeroes
    # the violation bit and the window means drain the budget burn
    for t in range(8, 20):
        states += [r["state"] for r in wt.tick(now=float(t))]
    assert states[-1] == "resolved"
    assert wt.firing(now=19.0) == []


def test_burn_rate_no_traffic_burns_no_budget():
    h = Hist()
    rule = _burn_rule(fast_burn=2.0)           # needs an all-ones window
    wt = Watchtower(rules=[rule], hists_fn=lambda: {"lat": h})
    wt.tick(now=0.0)                           # empty hist: bit 0
    for _ in range(3):
        h.observe(10.0, now=0.5)               # one old terrible burst
    for t in range(1, 12):
        wt.tick(now=float(t))
    # the windowed quantile STILL reports the breach (hists don't
    # decay) — only the advance gate keeps the idle hist from burning
    assert h.window_quantile(0.99, 5.0, now=11.0) * 1000.0 > 100.0
    assert wt.history.value("alert/burn/violation") == 0.0
    assert wt.alerts_doc(now=11.0)["counters"]["fired"] == 0


def test_burn_rate_fleet_scope_labels_the_member():
    ha, hb = Hist(), Hist()

    def summaries():
        return {"rankA": {"hists": {"lat": ha.to_dict()}},
                "rankB": {"hists": {"lat": hb.to_dict()}}}

    wt = Watchtower(rules=[_burn_rule(scope="fleet")],
                    summaries_fn=summaries)
    for t in range(6):
        ha.observe(1.0, now=float(t))          # only rankA is burning
        wt.tick(now=float(t))
    firing = wt.firing(now=5.0)
    assert [i["labels"]["member"] for i in firing] == ["rankA"]
    assert firing[0]["labels"] != {} and len(firing) == 1


# -- absence ----------------------------------------------------------------


def test_absence_local_arms_only_after_first_change():
    feed = _Feed()
    rule = {"name": "stall", "kind": "absence", "metric": "gen",
            "value": 5}
    wt = Watchtower(rules=[rule], summary_fn=feed.summary)
    feed.gauges["gen"] = 1.0
    for t in range(20):                        # constant forever: quiet
        assert wt.tick(now=float(t)) == []
    feed.gauges["gen"] = 2.0                   # ran once → now armed
    wt.tick(now=20.0)
    for t in range(21, 26):
        assert wt.tick(now=float(t)) == []     # age <= 5 still fine
    recs = wt.tick(now=26.0)
    assert [r["state"] for r in recs] == ["pending", "firing"]
    feed.gauges["gen"] = 3.0                   # progress again
    assert [r["state"] for r in wt.tick(now=27.0)] == ["resolved"]


def _member(ready=True, parked=False, age=1.0):
    return {"state": "ready" if ready else "failed", "ready": ready,
            "parked": parked, "age_s": age, "queue_depth": 0.0,
            "inflight": 0.0, "generation": 0.0}


def test_absence_fleet_scope_stale_member_parked_skipped():
    members = {"m1": _member(), "m2": _member(),
               "m3": _member(ready=False, parked=True),
               "m4": _member(ready=False)}     # cold boot, never ready
    fleet = {"members": members, "fleet/members": 4.0, "fleet/ready": 2.0,
             "fleet/parked": 1.0, "fleet/demand": 0.0,
             "fleet/generation": 0.0}
    rule = {"name": "member_stale", "kind": "absence", "scope": "fleet",
            "metric": "member", "value": 15, "severity": "page"}
    wt = Watchtower(rules=[rule], fleet_fn=lambda: fleet)
    assert wt.tick(now=0.0) == []              # m2 arms (seen ready)
    members["m2"] = _member(ready=False, age=99.0)   # ...then goes dark
    wt.tick(now=1.0)
    firing = wt.firing(now=1.0)
    # m2 fires; parked m3 is intentionally idle and the never-yet-ready
    # m4 is a warm-up in progress — neither is a stale member
    assert [i["labels"]["member"] for i in firing] == ["m2"]
    members["m2"] = _member()                  # recovery
    recs = wt.tick(now=2.0)
    assert [r["state"] for r in recs] == ["resolved"]
    assert wt.firing(now=2.0) == []


# -- trend ------------------------------------------------------------------


def test_trend_warmup_gate_then_slope_fires_and_flattens_out():
    feed = _Feed()
    rule = {"name": "ramp", "kind": "trend", "metric": "c",
            "window_s": 10, "slope_gt": 0.5, "warmup_s": 5,
            "min_points": 3}
    wt = Watchtower(rules=[rule], summary_fn=feed.summary)
    for t in range(5):                         # rising 1/s, but warming
        feed.counters["c"] = float(t)
        assert wt.tick(now=float(t)) == []
    feed.counters["c"] = 5.0
    recs = wt.tick(now=5.0)                    # warm: slope 1.0 > 0.5
    assert [r["state"] for r in recs] == ["pending", "firing"]
    states = []
    for t in range(6, 20):                     # plateau: slope decays
        states += [r["state"] for r in wt.tick(now=float(t))]
    assert states == ["resolved"]


# -- silences ---------------------------------------------------------------


def test_silence_mutes_the_page_but_keeps_the_record(tmp_path):
    feed = _Feed()
    wt = Watchtower(rules=[_rule(name="noisy")], member="s",
                    out_dir=str(tmp_path), summary_fn=feed.summary)
    wt.silence("noisy", 50.0, now=0.0)
    feed.gauges["m"] = 5.0
    recs = wt.tick(now=0.0)
    # full lifecycle still runs and still logs, marked silenced
    assert [r["state"] for r in recs] == ["pending", "firing"]
    assert all(r["silenced"] for r in recs)
    assert wt.firing(now=0.0) == []
    doc = wt.alerts_doc(now=0.0)
    assert [i["alert"] for i in doc["silenced"]] == ["noisy"]
    assert doc["firing"] == []
    assert doc["silences"][0]["alertname"] == "noisy"
    assert doc["silences"][0]["expires_in_s"] == 50.0
    assert doc["counters"]["silenced"] == 1
    assert len(alert_state_lines(wt, now=0.0)) == 2  # header only
    logged = [json.loads(l)
              for l in (tmp_path / "alerts_s.jsonl").read_text()
              .splitlines()]
    assert all(r.get("silenced") for r in logged)
    # expiry: the still-active instance surfaces again, no re-fire
    assert wt.tick(now=60.0) == []
    assert [i["alert"] for i in wt.firing(now=60.0)] == ["noisy"]
    # a fresh silence can be lifted early
    sid = wt.silence("noisy", 100.0, now=60.0)
    assert wt.firing(now=61.0) == []
    assert wt.unsilence(sid) and not wt.unsilence(sid)
    assert [i["alert"] for i in wt.firing(now=61.0)] == ["noisy"]


# -- transition fan-out: meta events + flight dump --------------------------


def test_firing_fans_out_meta_event_and_flight_dump(tmp_path):
    telemetry.configure(str(tmp_path), run_meta={"driver": "t"})
    feed = _Feed()
    wt = Watchtower(rules=[_rule(name="hot")], member="rank0",
                    out_dir=str(tmp_path), summary_fn=feed.summary)
    feed.gauges["m"] = 5.0
    wt.tick(now=0.0)
    feed.gauges["m"] = 0.0
    wt.tick(now=1.0)
    telemetry.shutdown()
    events = [json.loads(l)
              for l in (tmp_path / "events_rank0.jsonl").read_text()
              .splitlines()]
    trans = [e for e in events if e.get("kind") == "meta"
             and e.get("name") == "alert_transition"]
    assert [e["fields"]["state"] for e in trans] == ["pending", "firing",
                                                    "resolved"]
    trigger = [e for e in events if e.get("kind") == "meta"
               and e.get("name") == "flight_trigger"]
    assert trigger and trigger[0]["fields"]["reason"] == "alert_firing"
    assert trigger[0]["fields"]["alert"] == "hot"
    assert "trace_ids" in trigger[0]["fields"]
    assert glob.glob(str(tmp_path / "flight_*.jsonl"))


# -- prometheus exposition --------------------------------------------------


def test_alert_state_lines_format():
    assert alert_state_lines(None) == []       # watch off: byte parity
    feed = _Feed()
    rules = [_rule(name="fast", severity="page", labels={"slo": "d"}),
             _rule(name="slow", metric="m2", for_s=100)]
    wt = Watchtower(rules=rules, member="r0", summary_fn=feed.summary)
    feed.gauges.update(m=5.0, m2=5.0)
    wt.tick(now=0.0)                           # fast fires, slow pends
    lines = alert_state_lines(wt, now=0.0)
    assert lines[0].startswith("# HELP mxr_alert_state ")
    assert lines[1] == "# TYPE mxr_alert_state gauge"
    samples = {l.rsplit(" ", 1)[0]: l.rsplit(" ", 1)[1]
               for l in lines[2:]}
    key = ('mxr_alert_state{alertname="fast",severity="page",'
           'member="r0",slo="d"}')
    assert samples[key] == "1"
    assert samples['mxr_alert_state{alertname="slow",'
                   'severity="warning",member="r0"}'] == "0.5"
    feed.gauges["m"] = 0.0
    wt.tick(now=1.0)                           # fast resolves → 0
    lines = alert_state_lines(wt, now=1.0)
    assert any(l == key + " 0" for l in lines)


# -- dormant by default: watch off = fabric unchanged -----------------------


def _echo_forward(member, method, path, body, timeout):
    return 200, b"{}", "application/json"


def test_watch_off_fabric_is_byte_inert(tmp_path):
    telemetry.configure(str(tmp_path), run_meta={"driver": "t"})
    hz = _ready_pool({A: 1, B: 2})
    router = fb.FabricRouter(hz.pool, forward_fn=_echo_forward)
    status, _, _ = router.route_predict(b"{}")
    assert status == 200
    # no watch pane, no route-latency hist, no alert family: the
    # watch-less fabric surfaces are exactly the PR-19 ones
    assert "watch" not in router.metrics()
    assert "fabric/route_time" not in telemetry.get().live_hists()
    assert "mxr_alert_state" not in fb.fabric_prometheus(router)
    summary = telemetry.get().summary()
    assert not any(k.startswith("watch/")
                   for k in (summary.get("counters") or {}))
    # attaching the watchtower opt-in grows all three
    router.watchtower = Watchtower(rules=[], member="router")
    status, _, _ = router.route_predict(b"{}")
    assert status == 200
    assert "fabric/route_time" in telemetry.get().live_hists()
    assert "watch" in router.metrics()
    assert "# TYPE mxr_alert_state gauge" in fb.fabric_prometheus(router)


def test_watchtower_constructed_but_never_ticked_is_dormant(tmp_path):
    telemetry.configure(str(tmp_path), run_meta={"driver": "t"})
    wt = Watchtower(rules=default_rules(), member="x")
    assert wt.history.stats() == {"series": 0, "dropped": 0}
    assert wt.state()["ticks"] == 0
    summary = telemetry.get().summary()
    assert not any(k.startswith("watch/")
                   for k in (summary.get("counters") or {}))
    assert not glob.glob(str(tmp_path / "alerts_*.jsonl"))


def test_history_doc_shape():
    feed = _Feed()
    wt = Watchtower(rules=[], summary_fn=feed.summary)
    feed.gauges["q"] = 2.0
    for t in range(5):
        wt.tick(now=float(t))
    doc = wt.history_doc("q", window_s=10.0, now=5.0)
    assert doc["metric"] == "q" and doc["window_s"] == 10.0
    assert doc["points"] and doc["last"] == 2.0
    assert wt.history_doc("nope", now=5.0)["points"] == []


def test_fleet_from_pool_normalizes_the_member_view():
    hz = _ready_pool({A: 3, B: 1}, now=100.0)
    doc = fleet_from_pool(hz.pool, now=100.0)
    assert doc["fleet/members"] == 2.0 and doc["fleet/ready"] == 2.0
    assert doc["fleet/parked"] == 0.0
    m = doc["members"][A]
    assert m["ready"] is True and m["queue_depth"] == 3.0


# -- satellite: perf_gate mxr_watch_report rows -----------------------------


def _watch_doc(**kw):
    base = {"schema": "mxr_watch_report", "version": 1,
            "clean_fired": 0, "firing_at_end": 0, "rule_errors": 0,
            "fault_fired": 2, "fault_resolved": 2, "fault_trace_ids": 3,
            "transitions": 9}
    base.update(kw)
    return base


def test_perf_gate_watch_report_rows(tmp_path):
    pg = _load_script("perf_gate")
    path = tmp_path / "WATCH_r01.json"
    path.write_text(json.dumps(_watch_doc()))
    rows = {r["metric"]: r for r in pg.load_rows(str(path))}
    assert rows["watch_clean_fired"]["ceiling"] == 0.0
    assert rows["watch_firing_at_end"]["ceiling"] == 0.0
    assert rows["watch_rule_errors"]["ceiling"] == 0.0
    assert rows["watch_fault_fired"]["floor"] == 1.0
    assert rows["watch_fault_resolved"]["floor"] == 1.0
    assert rows["watch_fault_trace_ids"]["floor"] == 1.0
    assert rows["watch_transitions"]["value"] == 9.0
    assert "floor" not in rows["watch_transitions"]
    assert pg.main(["--dir", str(tmp_path)]) == 0
    assert pg.main(["--dir", str(tmp_path), "--check-format"]) == 0
    # an alert fired under clean traffic → the gate fails
    path.write_text(json.dumps(_watch_doc(clean_fired=1)))
    assert pg.main(["--dir", str(tmp_path)]) == 1
    # the injected fault never fired / never carried traces → fails
    path.write_text(json.dumps(_watch_doc(fault_fired=0,
                                          fault_trace_ids=0)))
    assert pg.main(["--dir", str(tmp_path)]) == 1
    # a stuck alert at run end → fails
    path.write_text(json.dumps(_watch_doc(firing_at_end=1)))
    assert pg.main(["--dir", str(tmp_path)]) == 1


# -- satellite: loadgen --watch-check ---------------------------------------


def test_loadgen_watch_check_semantics():
    lg = _load_script("loadgen")
    doc = {"firing": [{"alert": "a"}],
           "resolved": [{"alert": "b"}],
           "silenced": [{"alert": "c", "state": "firing"},
                        {"alert": "d", "state": "pending"}]}
    firing, fired = lg.watch_alert_names(doc)
    assert firing == ["a"]
    # fired covers resolved and silenced-while-firing — a silence
    # hides the page, not the fact
    assert fired == ["a", "b", "c"]
    # a watch-off target fails loudly
    assert "no /alerts route" in lg.watch_check_failure({}, [])
    # clean contract: nothing may have fired at all
    clean = {"firing": [], "resolved": [], "silenced": []}
    assert lg.watch_check_failure(clean, []) is None
    assert "expected a clean pass" in lg.watch_check_failure(doc, [])
    # expectations: every named alert fired, nothing stray still firing
    assert lg.watch_check_failure(doc, ["a", "b", "c"]) is None
    assert "expected ['z']" in lg.watch_check_failure(doc, ["z", "a"])
    assert "still firing" in lg.watch_check_failure(doc, ["b"])


# -- end-to-end: kill a REAL member, the router watchtower pages ------------


def test_e2e_member_kill_fires_member_stale_with_traces_then_resolves(
        tmp_path):
    """Two REAL TCP members behind a router watchtower: SIGKILL one and
    ``member_stale`` must fire on the router labeled with that member
    and carrying >=1 tail-sampled trace id; restarting the member on
    the same address must resolve it — the full arc persisted in
    ``alerts_router.jsonl``."""
    ports = [_free_port(), _free_port()]
    procs = [_member_proc(ports[0], 0), _member_proc(ports[1], 1)]
    # evict_probes high: the corpse must stay IN the pool as a stale
    # member (the alert's subject) instead of being evicted out of it
    pool = fb.ReplicaPool(_e2e_opts(probe_interval_s=0.2,
                                    evict_probes=100000))
    for port in ports:
        pool.register(f"127.0.0.1:{port}")
    pool.start()
    # tail_quantile 0 keeps every completed route tree: the firing
    # alert must have forensics to attach
    tracectx.configure(str(tmp_path), member="router", sample=1.0,
                       tail_quantile=0.0)
    victim = f"127.0.0.1:{ports[0]}"
    try:
        _wait(lambda: pool.ready_count() == 2, what="both members ready")
        router = fb.FabricRouter(pool, timeout_s=30.0)
        rules = [{"name": "member_stale", "kind": "absence",
                  "scope": "fleet", "metric": "member", "value": 15,
                  "severity": "page"}]
        wt = Watchtower(rules=rules, member="router",
                        out_dir=str(tmp_path),
                        fleet_fn=lambda: fleet_from_pool(pool))
        router.watchtower = wt
        body = _predict_body()
        for _ in range(4):
            status, _, _ = router.route_predict(body)
            assert status == 200
        wt.tick()
        assert wt.firing() == []               # healthy fleet: quiet
        procs[0].kill()
        procs[0].wait(timeout=30)

        def fired():
            wt.tick()
            return any(i["alert"] == "member_stale"
                       for i in wt.firing())

        _wait(fired, timeout=60.0, what="member_stale firing")
        inst = [i for i in wt.firing()
                if i["alert"] == "member_stale"][0]
        assert inst["labels"]["member"] == victim
        assert len(inst["trace_ids"]) >= 1
        procs[0] = _member_proc(ports[0], 0)   # same address, reborn

        def resolved():
            wt.tick()
            return any(r["alert"] == "member_stale"
                       for r in wt.alerts_doc()["resolved"])

        _wait(resolved, timeout=150.0, what="member_stale resolved")
        assert not any(i["alert"] == "member_stale"
                       for i in wt.firing())
        logged = [json.loads(l)
                  for l in (tmp_path / "alerts_router.jsonl")
                  .read_text().splitlines()]
        arc = [r["state"] for r in logged
               if r["alert"] == "member_stale"
               and r["labels"].get("member") == victim]
        assert arc == ["pending", "firing", "resolved"]
        fire_rec = [r for r in logged if r["state"] == "firing"][0]
        assert len(fire_rec["trace_ids"]) >= 1
    finally:
        _cleanup(pool, procs)
