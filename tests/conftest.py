"""Test config: force an 8-device virtual CPU platform.

Mirrors SURVEY.md §4's rebuild test pyramid: all unit/sharding tests run on
CPU with xla_force_host_platform_device_count=8 so the data-parallel mesh is
exercised without a TPU pod.  Bench (bench.py) runs on the real chip outside
pytest.

NOTE: this environment pre-imports jax at interpreter startup (axon platform
hook), so env vars alone are too late — the platform must be forced through
``jax.config`` before the backend initializes (first device query).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert jax.devices()[0].platform == "cpu", jax.devices()

import numpy as np  # noqa: E402
import pytest  # noqa: E402

import pathlib  # noqa: E402
import sys  # noqa: E402

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
from __graft_entry__ import machine_cache_dir  # noqa: E402

# persistent compile cache (full-model CPU compiles dominate suite
# runtime), keyed by machine fingerprint: entries AOT-compiled on a
# different host are rejected at load (and risk SIGILL) — the round-4
# driver run was poisoned exactly this way.  machine_cache_dir reads
# JAX_TEST_CACHE for the base dir; __graft_entry__'s import already set
# this config, re-stated here so the suite does not depend on that
# module-level side effect.
jax.config.update("jax_compilation_cache_dir", machine_cache_dir())
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


@pytest.fixture
def rng():
    return np.random.RandomState(42)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "tpu: drives the real TPU chip via a subprocess "
        "(auto-skips when no chip is attached)")
