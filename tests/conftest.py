"""Test config: force an 8-device virtual CPU platform.

Mirrors SURVEY.md §4's rebuild test pyramid: all unit/sharding tests run on
CPU with xla_force_host_platform_device_count=8 so the data-parallel mesh is
exercised without a TPU pod.  Bench (bench.py) runs on the real chip outside
pytest.

NOTE: this environment pre-imports jax at interpreter startup (axon platform
hook), so env vars alone are too late — the platform must be forced through
``jax.config`` before the backend initializes (first device query).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# persistent compile cache: full-model CPU compiles dominate suite runtime
cache_dir = os.environ.get("JAX_TEST_CACHE", "/tmp/jax_test_cache")
jax.config.update("jax_compilation_cache_dir", cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

assert jax.devices()[0].platform == "cpu", jax.devices()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "tpu: drives the real TPU chip via a subprocess "
        "(auto-skips when no chip is attached)")
