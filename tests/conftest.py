"""Test config: force an 8-device virtual CPU platform before jax imports.

Mirrors SURVEY.md §4's rebuild test pyramid: all unit/sharding tests run on
CPU with XLA_FLAGS=--xla_force_host_platform_device_count=8 so the data-
parallel mesh is exercised without a TPU pod.  Bench (bench.py) runs on the
real chip outside pytest.
"""

import os

# unconditional: the shell may export JAX_PLATFORMS=<tpu backend>; unit tests
# must always run on the virtual 8-device CPU mesh, never the real chip
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.RandomState(42)
