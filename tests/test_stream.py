"""Streaming serving contract tests (CPU).

The tentpole guarantees, each pinned here: with the skip gate OFF a
stream is byte-for-byte the ``/predict`` path; a skip answers from the
reference frame's cache with ZERO engine counter/hist deltas (the SLO
controller never sees it); scene cuts, bucket changes, and the
``max_skip`` budget always force the full path; per-stream response
order survives cross-stream batch coalescing; ``frame_delta`` programs
are ordinary registry citizens (kind-labeled, first-seen accounting,
no engine ``recompiles`` pollution); and the ``/stream`` NDJSON + stdio
transports speak ``/predict``'s status vocabulary (400/409/503/504).
Runs against the shape-faithful FakePredictor — the gate's jit is the
only compiled program, tiny on CPU.
"""

import importlib.util
import io
import json
import os

import numpy as np

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.compile.registry import ProgramRegistry
from mx_rcnn_tpu.data import prepare_image
from mx_rcnn_tpu.serve import (StaleSeqError, StreamManager, StreamOptions,
                               encode_image_payload, make_server,
                               run_stream_stdio, unix_http_request)
from mx_rcnn_tpu.serve.frontend import unix_http_request_raw
from tests.test_serve import FakePredictor, make_engine, raw_image, tiny_cfg

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mgr(engine, **opts):
    return StreamManager(engine, StreamOptions(**opts))


# -- gate off: pure coalescing, byte-identical results ----------------------


def test_gate_off_stream_byte_identical_to_predict():
    cfg = tiny_cfg()
    rng = np.random.RandomState(3)
    frames = [rng.randint(0, 255, (60, 100, 3), dtype=np.uint8)
              for _ in range(4)]

    plain = make_engine(cfg).start()
    try:
        expect = [plain.submit(f).result(timeout=60) for f in frames]
    finally:
        plain.stop()

    engine = make_engine(cfg).start()
    mgr = _mgr(engine)  # skip_thresh 0 → gate off
    try:
        assert not mgr.gate_enabled
        assert mgr.warmup() == 0  # no gate → no programs
        results = [mgr.submit_frame("cam", i + 1, f)
                   for i, f in enumerate(frames)]
        got = [r.result(timeout=60) for r in results]
    finally:
        engine.stop()

    # byte-identical, not merely close: the serialized responses agree
    assert (json.dumps(got, sort_keys=True)
            == json.dumps(expect, sort_keys=True))
    assert all(r.skipped is False and r.delta is None for r in results)
    assert mgr.counters["forwarded"] == len(frames)
    assert mgr.counters["skipped"] == 0
    assert mgr.metrics()["skip_fraction"] == 0.0


def test_stale_or_duplicate_seq_rejected():
    cfg = tiny_cfg()
    engine = make_engine(cfg).start()
    mgr = _mgr(engine)
    try:
        mgr.submit_frame("cam", 5, raw_image(60, 100, 80)).result(timeout=60)
        for bad in (5, 3):  # duplicate, then regression
            try:
                mgr.submit_frame("cam", bad, raw_image(60, 100, 80))
                raise AssertionError("stale seq accepted")
            except StaleSeqError:
                pass
        # the high-water mark survives the rejections
        mgr.submit_frame("cam", 6, raw_image(60, 100, 80)).result(timeout=60)
    finally:
        engine.stop()
    assert mgr.counters["stale_seq"] == 2
    assert mgr.counters["frames"] == 2  # only accepted frames count


# -- the skip fast path -----------------------------------------------------


def test_skip_serves_cached_with_zero_engine_deltas():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=2).start()
    mgr = _mgr(engine, skip_thresh=3.0, max_skip=8)
    try:
        base_img = raw_image(60, 100, 100)
        first = mgr.submit_frame("cam", 1, base_img)
        ref = first.result(timeout=60)
        assert first.skipped is False

        base = dict(engine.counters)
        svc = engine.hists["serve/service_time"].count
        req = engine.hists["serve/request_time"].count

        noisy = base_img.copy()
        noisy[::2, ::2, 0] += 1  # sensor noise: mean |delta| ≪ thresh
        res = mgr.submit_frame("cam", 2, noisy)
        assert res.skipped is True
        assert res.delta is not None and res.delta < 3.0
        assert res.queue_wait_s is None
        assert res.result(timeout=60) == ref  # the cached detections

        # the subsystem's core guarantee: a skip is invisible to the
        # engine — no request, no batch, no dispatch, no readback, and
        # no service_time/request_time observation for the SLO
        # controller to mistake for a fast forward
        assert {k: engine.counters[k] - base[k]
                for k in base if engine.counters[k] != base[k]} == {}
        assert engine.hists["serve/service_time"].count == svc
        assert engine.hists["serve/request_time"].count == req
    finally:
        engine.stop()
    assert mgr.counters["skipped"] == 1
    assert mgr.hists["stream/skip_time"].count == 1
    m = mgr.metrics()
    assert m["skip_fraction"] == 0.5
    assert m["counters"]["delta_dispatches"] >= 1


def test_scene_cut_always_takes_full_path():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=2).start()
    mgr = _mgr(engine, skip_thresh=3.0)
    fake = engine.predictor
    try:
        a = raw_image(60, 100, 10)
        cut = raw_image(60, 100, 220)  # hard cut: huge mean delta
        r1 = mgr.submit_frame("cam", 1, a)
        d1 = r1.result(timeout=60)
        r2 = mgr.submit_frame("cam", 2, cut)
        d2 = r2.result(timeout=60)
        assert r2.skipped is False
        assert r2.delta is not None and r2.delta >= 3.0
        # the cut frame's OWN detections, not the reference's
        prepared, _ = prepare_image(cut, cfg, cfg.tpu.SCALES[0])
        assert abs(d2[0]["score"] - fake.row_score(prepared)) < 1e-5
        assert d2[0]["score"] != d1[0]["score"]
    finally:
        engine.stop()
    assert mgr.counters["forwarded"] == 2
    assert mgr.counters["skipped"] == 0


def test_max_skip_budget_and_bucket_switch_force_refresh():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=2).start()
    mgr = _mgr(engine, skip_thresh=5.0, max_skip=2)
    try:
        land = raw_image(60, 100, 100)
        seqs = []
        for seq in (1, 2, 3, 4):
            seqs.append(mgr.submit_frame("cam", seq, land))
            seqs[-1].result(timeout=60)
        # 1 forwards, 2–3 skip, 4 exhausts the budget → forced refresh
        assert [r.skipped for r in seqs] == [False, True, True, False]
        assert seqs[3].delta is None  # refreshed before the gate ran
        assert mgr.counters["refreshes"] == 1

        # orientation flip: new bucket → full path, then skipping resumes
        port = raw_image(100, 60, 100)
        r5 = mgr.submit_frame("cam", 5, port)
        r5.result(timeout=60)
        r6 = mgr.submit_frame("cam", 6, port)
        r6.result(timeout=60)
        assert r5.skipped is False and r5.delta is None
        assert r6.skipped is True
        assert mgr.counters["bucket_switches"] == 1
    finally:
        engine.stop()


def test_hot_reload_generation_invalidates_reference():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=2).start()
    mgr = _mgr(engine, skip_thresh=5.0)
    try:
        img = raw_image(60, 100, 100)
        mgr.submit_frame("cam", 1, img).result(timeout=60)
        engine.generation += 1  # what /admin/reload does on swap
        r2 = mgr.submit_frame("cam", 2, img)
        r2.result(timeout=60)
        # identical pixels, but stale-generation detections must not serve
        assert r2.skipped is False
    finally:
        engine.stop()


# -- cross-stream coalescing ------------------------------------------------


def test_cross_stream_coalescing_preserves_per_stream_order():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=2, max_delay_ms=50.0)
    mgr = _mgr(engine)
    fake = engine.predictor
    values = {"a": (30, 90, 150), "b": (60, 120, 210)}
    results = {"a": [], "b": []}
    # interleave two streams' frames pre-start: each full same-bucket
    # batch must mix both streams
    for seq in range(3):
        for sid in ("a", "b"):
            img = raw_image(60, 100, values[sid][seq])
            results[sid].append(mgr.submit_frame(sid, seq + 1, img))
    engine.start()
    try:
        dets = {sid: [r.result(timeout=60) for r in rs]
                for sid, rs in results.items()}
    finally:
        engine.stop()

    # every batch was full and cross-stream
    assert all(b[0] == 2 for b in fake.batches)
    assert engine.counters["stream_batches"] == 3
    assert engine.counters["stream_batch_frames"] == 6
    assert engine.counters["stream_coalesced_batches"] == 3

    # per-stream order: response i carries frame i's OWN score
    for sid in ("a", "b"):
        for seq in range(3):
            img = raw_image(60, 100, values[sid][seq])
            prepared, _ = prepare_image(img, cfg, cfg.tpu.SCALES[0])
            assert abs(dets[sid][seq][0]["score"]
                       - fake.row_score(prepared)) < 1e-5

    m = mgr.metrics()
    assert m["counters"]["coalesced_batches"] == 3
    assert m["batch_occupancy"] == 1.0


# -- frame_delta as a registry citizen -------------------------------------


def test_frame_delta_is_a_registry_citizen():
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=2).start()
    reg = ProgramRegistry()  # standalone: FakePredictor carries none
    mgr = StreamManager(engine, StreamOptions(skip_thresh=3.0),
                        registry=reg)
    try:
        # warmup compiles one delta program per orientation bucket —
        # registry-level accounting only, NEVER the engine's
        # recompiles/warmup_programs (those count forward programs)
        assert mgr.warmup() == 2
        assert engine.counters["recompiles"] == 0
        assert engine.counters["warmup_programs"] == 0
        assert reg.counters["programs"] == 2
        rows = reg.snapshot()["programs"]
        assert len(rows) == 2
        assert all(p["kind"] == "frame_delta" for p in rows)

        # steady-state traffic reuses them — no growth, and the gate
        # dispatch adds nothing to the engine's compile accounting
        img = raw_image(60, 100, 100)
        mgr.submit_frame("cam", 1, img).result(timeout=60)
        rec = engine.counters["recompiles"]  # the forward's own shape
        assert mgr.submit_frame("cam", 2, img).skipped is True
        assert reg.counters["programs"] == 2
        assert engine.counters["recompiles"] == rec
        assert mgr.counters["delta_dispatches"] == 3  # 2 warmup + 1 gate
    finally:
        engine.stop()


# -- transports: /stream NDJSON + stdio -------------------------------------


def test_stream_http_ndjson_pipelined_statuses_and_metrics(tmp_path):
    cfg = tiny_cfg()
    engine = make_engine(cfg, batch_size=2).start()
    mgr = _mgr(engine, skip_thresh=3.0)
    sock = str(tmp_path / "stream.sock")
    server = make_server(engine, unix_socket=sock, stream=mgr)
    import threading
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    try:
        img = raw_image(60, 100, 100)
        frame = dict(encode_image_payload(img), stream_id="cam")
        lines = [
            json.dumps(dict(frame, seq=1)),        # forward
            json.dumps(dict(frame, seq=2)),        # identical → skip
            "not json {",                          # 400
            json.dumps(dict(frame, seq=2)),        # duplicate → 409
            json.dumps({"seq": 3, "image_b64": "x"}),  # no stream_id → 400
        ]
        status, raw, ctype = unix_http_request_raw(
            sock, "POST", "/stream", "\n".join(lines).encode())
        assert status == 200 and "ndjson" in ctype
        replies = [json.loads(ln) for ln in raw.decode().splitlines()]
        assert [r["status"] for r in replies] == [200, 200, 400, 409, 400]
        assert replies[0]["skipped"] is False
        assert replies[1]["skipped"] is True
        assert replies[1]["detections"] == replies[0]["detections"]
        assert replies[1]["delta"] < 3.0

        # /metrics grows the stream section, and the Prometheus view
        # renders without choking on it
        status, m = unix_http_request(sock, "GET", "/metrics")
        assert status == 200
        st = m["stream"]
        assert st["active_streams"] == 1
        assert st["counters"]["skipped"] == 1
        assert st["counters"]["frames"] == 2
        assert st["options"]["skip_thresh"] == 3.0
        status, prom = unix_http_request(sock, "GET", "/metrics?format=prom")
        assert status == 200 and "stream" in prom
    finally:
        server.shutdown()
        server.server_close()
        engine.stop()


def test_stream_http_404_when_streaming_disabled(tmp_path):
    cfg = tiny_cfg()
    engine = make_engine(cfg).start()
    sock = str(tmp_path / "plain.sock")
    server = make_server(engine, unix_socket=sock)  # no StreamManager
    import threading
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    try:
        status, resp = unix_http_request(
            sock, "POST", "/stream",
            dict(encode_image_payload(raw_image(60, 100, 9)),
                 stream_id="cam", seq=1))
        assert status == 404
        assert "--stream" in resp["error"]
    finally:
        server.shutdown()
        server.server_close()
        engine.stop()


def test_run_stream_stdio_round_trip():
    cfg = tiny_cfg()
    engine = make_engine(cfg).start()
    mgr = _mgr(engine)
    img = raw_image(60, 100, 70)
    frame = dict(encode_image_payload(img), stream_id="cam")
    inp = io.StringIO("\n".join([
        json.dumps(dict(frame, seq=1)),
        json.dumps(dict(frame, seq=1)),  # duplicate → 409
        json.dumps(dict(frame, seq=2)),
    ]) + "\n")
    out = io.StringIO()
    try:
        run_stream_stdio(mgr, inp=inp, out=out)
    finally:
        engine.stop()
    replies = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert [r["status"] for r in replies] == [200, 409, 200]
    assert replies[0]["detections"] == replies[2]["detections"]
    assert replies[0]["seq"] == 1 and replies[2]["seq"] == 2


# -- satellite gates: perf_gate rows + telemetry report section -------------


def test_perf_gate_stream_rows_floor_and_ceiling(tmp_path):
    pg = _load_script("perf_gate")
    doc = {"schema": "mxr_stream_report", "version": 1, "scenarios": [
        {"name": "static", "streams": 4, "frames_sent": 128,
         "p99_ms": 120.0, "error_rate": 0.0, "frames_dropped": 0,
         "dispatches_per_frame": 0.2, "skip_fraction": 0.8,
         "skip_fraction_floor": 0.5, "p99_ceiling_ms": 500.0},
        {"name": "pan", "streams": 4, "frames_sent": 128,
         "p99_ms": 150.0, "error_rate": 0.0, "frames_dropped": 1,
         "dispatches_per_frame": 1.0},
    ]}
    rows = {r["metric"]: r for r in pg.stream_report_rows(doc)}
    assert rows["stream_static_p99_ms"]["ceiling"] == 500.0
    assert rows["stream_static_skip_fraction"]["floor"] == 0.5
    assert rows["stream_static_dispatches_per_frame"]["direction"] == "down"
    assert (rows["stream_static_dispatches_per_frame"]["abs_slack"]
            == pg.STREAM_DPF_ABS_SLACK)
    # no ceiling pinned → ordinary trend row, scored against history
    assert rows["stream_pan_p99_ms"]["direction"] == "down"
    assert "skip_fraction" not in {m.rsplit("_", 1)[-1] for m in rows
                                   if m.startswith("stream_pan")}

    path = tmp_path / "STREAM_r01.json"
    path.write_text(json.dumps(doc))
    assert pg.main(["--dir", str(tmp_path)]) == 0
    assert pg.main(["--dir", str(tmp_path), "--check-format"]) == 0

    # ceiling is scored on the newest run ALONE — one bad run fails
    doc["scenarios"][0]["p99_ms"] = 600.0
    path.write_text(json.dumps(doc))
    assert pg.main(["--dir", str(tmp_path)]) == 1

    # so is the skip_fraction floor
    doc["scenarios"][0]["p99_ms"] = 120.0
    doc["scenarios"][0]["skip_fraction"] = 0.3
    path.write_text(json.dumps(doc))
    assert pg.main(["--dir", str(tmp_path)]) == 1


def test_perf_gate_bench_stream_series_are_separate(tmp_path):
    """bench --mode serve stream metrics ride as their OWN series —
    never scored against the request/response imgs_per_sec rows."""
    pg = _load_script("perf_gate")
    doc = {"n": 1, "cmd": "bench --mode serve --serve-stream", "rc": 0,
           "parsed": {"mode": "serve", "metric": "serve_fused",
                      "imgs_per_sec": 10.0, "p50_ms": 90.0, "p99_ms": 120.0,
                      "dispatches_per_frame": 0.3, "skip_fraction": 0.9,
                      "vs_baseline": None}}
    (tmp_path / "BENCH_r08.json").write_text(json.dumps(doc))
    rows = pg.load_rows(str(tmp_path / "BENCH_r08.json"))
    metrics = {r["metric"]: r for r in rows}
    dpf = metrics["serve_fused_dispatches_per_frame"]
    assert dpf["direction"] == "down" and "vs_baseline" not in dpf
    sf = metrics["serve_fused_skip_fraction"]
    assert sf["floor"] == pg.BENCH_SKIP_FRACTION_FLOOR
    assert pg.main(["--dir", str(tmp_path)]) == 0
    doc["parsed"]["skip_fraction"] = 0.2  # below the floor
    (tmp_path / "BENCH_r08.json").write_text(json.dumps(doc))
    assert pg.main(["--dir", str(tmp_path)]) == 1


def test_telemetry_report_streaming_section(tmp_path):
    from mx_rcnn_tpu.telemetry import report as trep
    tel = telemetry.configure(str(tmp_path), run_meta={"driver": "t"})
    tel.counter("stream/frames", 8)
    tel.counter("stream/skipped", 5)
    tel.counter("serve/requests", 3)
    telemetry.shutdown()
    summary = trep.aggregate(trep.load_events([str(tmp_path)]))
    table = trep.render_table(summary)
    assert "streaming" in table
    block = table[table.index("streaming"):]
    assert "stream/skipped" in block
    assert "stream/coalesced_batches" in block  # zeros included
