"""Program-registry unit tests (host-side: no model, no XLA compile).

Covers the marker-manifest protocol the AOT warm start rests on —
first-dispatch accounting, cross-instance (simulating cross-process)
hit/miss, the dtype/digest/sharding key axes, forged-marker collision
handling — and the LRU bound on built callables.  The cross-PROCESS
half of the story (a real second server boot loading executables from
the persistent XLA cache) lives in tests/test_warmstart.py.
"""

import json
import os

import jax
import pytest

from mx_rcnn_tpu.compile import (ProgramKey, ProgramRegistry, config_digest,
                                 registry_cache_dir)
from mx_rcnn_tpu.compile.registry import CACHE_SCHEMA


@pytest.fixture
def jax_cache_guard():
    """ProgramRegistry(cache_base=...) OWNS the process-global jax
    compilation cache config — restore the suite's machine-dir cache
    afterwards so later tests keep their warm compiles."""
    from jax.experimental.compilation_cache import compilation_cache

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        yield
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_min)
        # configure_jax_cache reset the live cache instance; reset again
        # so the suite re-initializes against its machine dir
        compilation_cache.reset_cache()


def test_note_dispatch_first_seen_once_and_markers(tmp_path,
                                                   jax_cache_guard):
    reg = ProgramRegistry(dtype="float32", cache_base=str(tmp_path))
    assert reg.owns_cache and reg.cache_dir.startswith(str(tmp_path))

    # first sighting: True (the "this dispatch compiles" signal), no
    # marker on disk yet → aot_miss
    assert reg.note_dispatch("predict", (2, 96, 128, 3)) is True
    assert reg.note_dispatch("predict", (2, 96, 128, 3)) is False
    assert reg.note_dispatch("predict", (2, 128, 96, 3)) is True
    assert reg.counters == {"programs": 2, "aot_hit": 0, "aot_miss": 2,
                            "key_collisions": 0, "evictions": 0}

    # each first dispatch left a marker manifest entry
    markers = os.listdir(os.path.join(reg.cache_dir, "programs"))
    assert len(markers) == 2 and all(m.endswith(".json") for m in markers)
    key = reg.key_for("predict", (2, 96, 128, 3))
    with open(reg._marker_path(key)) as f:
        assert json.load(f) == key.fields()

    # a second registry over the SAME base (the "second process"):
    # matching markers are AOT hits, a new shape is still a miss
    reg2 = ProgramRegistry(dtype="float32", cache_base=str(tmp_path))
    assert reg2.note_dispatch("predict", (2, 96, 128, 3)) is True
    assert reg2.note_dispatch("predict", (2, 128, 96, 3)) is True
    assert reg2.note_dispatch("predict", (4, 96, 128, 3)) is True
    assert reg2.counters["aot_hit"] == 2
    assert reg2.counters["aot_miss"] == 1
    assert reg2.counters["key_collisions"] == 0


def test_key_axes_separate_cache_namespaces(tmp_path, jax_cache_guard):
    # dtype is folded into the FINGERPRINT DIR, not just the key: a bf16
    # replica and an f32 replica over one base never share entries
    d_f32 = registry_cache_dir(str(tmp_path), "float32")
    d_bf16 = registry_cache_dir(str(tmp_path), "bfloat16")
    assert d_f32 != d_bf16

    reg = ProgramRegistry(dtype="float32", cache_base=str(tmp_path))
    reg.note_dispatch("predict", (2, 96, 128, 3))
    reg_b = ProgramRegistry(dtype="bfloat16", cache_base=str(tmp_path))
    assert reg_b.note_dispatch("predict", (2, 96, 128, 3)) is True
    assert reg_b.counters["aot_miss"] == 1  # disjoint dir: no hit

    # kind / shape / digest each change the key hash within one dir
    k = reg.key_for("predict", (2, 96, 128, 3))
    assert reg.key_for("predict_rpn", (2, 96, 128, 3)).hash() != k.hash()
    assert reg.key_for("predict", (4, 96, 128, 3)).hash() != k.hash()
    other = ProgramKey("deadbeefdeadbeef", k.kind, k.shape, k.batch,
                       k.dtype, k.sharding)
    assert other.hash() != k.hash()
    assert k.fields()["schema"] == CACHE_SCHEMA


def test_forged_marker_counts_collision_and_is_overwritten(tmp_path,
                                                           jax_cache_guard):
    reg = ProgramRegistry(dtype="float32", cache_base=str(tmp_path))
    key = reg.key_for("predict", (2, 96, 128, 3))
    path = reg._marker_path(key)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    forged = dict(key.fields(), digest="0000000000000000")
    with open(path, "w") as f:
        json.dump(forged, f)

    # same hash path, different fields: a collision — counted, treated
    # as a miss (never trusted), and overwritten with the true fields
    assert reg.note_dispatch("predict", (2, 96, 128, 3)) is True
    assert reg.counters["key_collisions"] == 1
    assert reg.counters["aot_miss"] == 1 and reg.counters["aot_hit"] == 0
    with open(path) as f:
        assert json.load(f) == key.fields()

    # unreadable marker is also a collision, not a crash
    key2 = reg.key_for("predict_rpn", (2, 96, 128, 3))
    path2 = reg._marker_path(key2)
    with open(path2, "w") as f:
        f.write("{not json")
    assert reg.note_dispatch("predict_rpn", (2, 96, 128, 3)) is True
    assert reg.counters["key_collisions"] == 2


def test_lookup_lru_eviction_and_rebuild():
    # no cache_base: piggyback mode, global jax config untouched
    reg = ProgramRegistry(max_programs=2)
    calls = []

    def builder(*static):
        calls.append(static)
        return lambda: static

    reg.register("fn", builder)
    a = reg.lookup("fn", ("a",))
    b = reg.lookup("fn", ("b",))
    assert reg.lookup("fn", ("a",)) is a  # cached, LRU-refreshed
    assert calls == [("a",), ("b",)]

    c = reg.lookup("fn", ("c",))  # evicts LRU entry ("b")
    assert reg.counters["evictions"] == 1
    assert reg.lookup("fn", ("a",)) is a and reg.lookup("fn", ("c",)) is c
    assert calls == [("a",), ("b",), ("c",)]

    assert reg.lookup("fn", ("b",)) is not b  # evicted: rebuilt
    assert calls == [("a",), ("b",), ("c",), ("b",)]
    assert reg.counters["evictions"] == 2

    with pytest.raises(KeyError):
        reg.lookup("nope")


def test_multimodel_lru_pressure_pinned_registry_never_evicts():
    """The model-pool contract on the registry: each model owns its own
    registry (so one model's pressure never evicts a sibling's
    programs), LRU eviction under pressure increments the counter and
    an evicted callable is rebuilt on next lookup, and a PINNED
    registry — the pool pins the hot model's — never evicts no matter
    how far past ``max_programs`` it grows."""
    hot = ProgramRegistry(max_programs=2, pinned=True)
    cold = ProgramRegistry(max_programs=2)
    built = {"hot": [], "cold": []}

    def make_builder(name):
        def builder(*static):
            built[name].append(static)
            return lambda: (name, static)
        return builder

    hot.register("fn", make_builder("hot"))
    cold.register("fn", make_builder("cold"))

    # pinned: four distinct programs live in a max_programs=2 registry
    hot_fns = [hot.lookup("fn", (s,)) for s in "abcd"]
    assert hot.counters["evictions"] == 0
    assert len(hot._fns) == 4
    for s, fn in zip("abcd", hot_fns):
        assert hot.lookup("fn", (s,)) is fn  # all still cached
    assert built["hot"] == [("a",), ("b",), ("c",), ("d",)]
    assert hot.snapshot()["pinned"] is True

    # the cold sibling under identical pressure evicts...
    cold_a = cold.lookup("fn", ("a",))
    for s in "bcd":
        cold.lookup("fn", (s,))
    assert cold.counters["evictions"] == 2
    assert len(cold._fns) == 2
    # ...and an evicted program is rebuilt, not lost
    assert cold.lookup("fn", ("a",)) is not cold_a
    assert built["cold"].count(("a",)) == 2
    # cross-model isolation: cold's churn never touched hot's cache
    assert hot.counters["evictions"] == 0 and len(hot._fns) == 4

    # pinning is mutable at runtime (pool re-pins on policy change):
    # unpinning re-enables the bound on the NEXT insert
    hot.pinned = False
    hot.lookup("fn", ("e",))
    assert hot.counters["evictions"] == 3  # trimmed 5 -> 2
    assert len(hot._fns) == 2


def test_snapshot_shape_and_digest_stability():
    from mx_rcnn_tpu.config import generate_config

    cfg = generate_config("resnet50", "PascalVOC")
    assert config_digest(cfg) == config_digest(cfg)
    assert config_digest(cfg) != config_digest(
        generate_config("resnet50", "PascalVOC", TEST__NMS=0.11))
    assert config_digest(None) == "none"

    reg = ProgramRegistry(cfg, dtype="bfloat16")
    reg.note_dispatch("predict", (2, 96, 128, 3))
    reg.record_compile_seconds("predict", (2, 96, 128, 3), 0.25)
    snap = reg.snapshot()
    assert snap["dtype"] == "bfloat16"
    assert snap["digest"] == config_digest(cfg)
    assert snap["counters"]["programs"] == 1
    (prog,) = snap["programs"]
    assert prog["kind"] == "predict" and prog["compile_s"] == 0.25
    assert snap["compile_seconds"]["count"] == 1
