"""torch→flax converter: build a synthetic torchvision-shaped state_dict
(correct names + shapes, random values — torchvision itself is not
installed) and check every converted leaf lands on a matching init-param
path with a matching shape."""

import numpy as np

import jax

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.utils.convert_torch import RESNET_UNITS, convert


def fake_resnet_sd(depth="resnet50"):
    rng = np.random.RandomState(0)
    sd = {}

    def bn(prefix, c):
        sd[prefix + ".weight"] = rng.randn(c).astype(np.float32)
        sd[prefix + ".bias"] = rng.randn(c).astype(np.float32)
        sd[prefix + ".running_mean"] = rng.randn(c).astype(np.float32)
        sd[prefix + ".running_var"] = np.abs(rng.randn(c)).astype(np.float32)

    sd["conv1.weight"] = rng.randn(64, 3, 7, 7).astype(np.float32)
    bn("bn1", 64)
    widths = (64, 128, 256, 512)
    in_ch = 64
    for li, n in enumerate(RESNET_UNITS[depth], start=1):
        w = widths[li - 1]
        for u in range(n):
            p = f"layer{li}.{u}"
            c_in = in_ch if u == 0 else w * 4
            sd[p + ".conv1.weight"] = rng.randn(w, c_in, 1, 1).astype(np.float32)
            bn(p + ".bn1", w)
            sd[p + ".conv2.weight"] = rng.randn(w, w, 3, 3).astype(np.float32)
            bn(p + ".bn2", w)
            sd[p + ".conv3.weight"] = rng.randn(w * 4, w, 1, 1).astype(np.float32)
            bn(p + ".bn3", w * 4)
            if u == 0:
                sd[p + ".downsample.0.weight"] = rng.randn(
                    w * 4, c_in, 1, 1).astype(np.float32)
                bn(p + ".downsample.1", w * 4)
        in_ch = w * 4
    return sd


def fake_vgg_sd():
    rng = np.random.RandomState(0)
    sd = {}
    cfg = [(64, 3), (64, 64), (128, 64), (128, 128), (256, 128), (256, 256),
           (256, 256), (512, 256), (512, 512), (512, 512), (512, 512),
           (512, 512), (512, 512)]
    idxs = [0, 2, 5, 7, 10, 12, 14, 17, 19, 21, 24, 26, 28]
    for idx, (o, i) in zip(idxs, cfg):
        sd[f"features.{idx}.weight"] = rng.randn(o, i, 3, 3).astype(np.float32)
        sd[f"features.{idx}.bias"] = rng.randn(o).astype(np.float32)
    sd["classifier.0.weight"] = rng.randn(4096, 25088).astype(np.float32)
    sd["classifier.0.bias"] = rng.randn(4096).astype(np.float32)
    sd["classifier.3.weight"] = rng.randn(4096, 4096).astype(np.float32)
    sd["classifier.3.bias"] = rng.randn(4096).astype(np.float32)
    return sd


def _param_shapes(params, prefix=""):
    out = {}
    for k, v in params.items():
        if isinstance(v, dict):
            out.update(_param_shapes(v, prefix + k + "/"))
        else:
            out[prefix + k] = tuple(v.shape)
    return out


def _check(network, flat):
    cfg = generate_config(network, "PascalVOC")
    import dataclasses
    cfg = cfg.replace(tpu=dataclasses.replace(cfg.tpu, SCALES=((64, 96),),
                                              MAX_GT=4))
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    shapes = _param_shapes(params)
    missing = [k for k in flat if k not in shapes]
    mismatched = [k for k in flat
                  if k in shapes and tuple(flat[k].shape) != shapes[k]]
    assert not missing, f"paths not in model: {missing[:5]}"
    assert not mismatched, f"shape mismatches: {mismatched[:5]}"
    # every backbone conv kernel covered
    backbone_kernels = [k for k in shapes
                        if k.startswith("backbone/") and k.endswith("kernel")]
    uncovered = [k for k in backbone_kernels if k not in flat]
    assert not uncovered, f"backbone kernels not covered: {uncovered[:5]}"


def test_convert_resnet50_covers_model():
    _check("resnet50", convert(fake_resnet_sd("resnet50"), "resnet50"))


def test_convert_resnet101_covers_model():
    _check("resnet101", convert(fake_resnet_sd("resnet101"), "resnet101"))


def test_convert_resnet152_covers_model():
    _check("resnet152", convert(fake_resnet_sd("resnet152"), "resnet152"))


def test_resnet_units_tables_agree():
    """convert_torch keeps its own RESNET_UNITS so it stays importable in a
    torch-only env; this pins it to the backbone's table (the two drifted
    once — resnet152 landed in backbones first)."""
    from mx_rcnn_tpu.models.backbones import RESNET_UNITS as model_units
    from mx_rcnn_tpu.utils.convert_torch import RESNET_UNITS as conv_units

    assert conv_units == model_units


def test_convert_vgg16_covers_model():
    _check("vgg16", convert(fake_vgg_sd(), "vgg16"))
