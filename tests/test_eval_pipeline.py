"""Overlapped-eval pipeline pins (eval/pipeline.py + --device-postprocess).

Three contracts guard the tentpole:

* BIT-IDENTITY: the pipelined loop fills the exact same ``all_boxes`` /
  ``all_masks`` as the serial reference loop at ANY in-flight depth —
  results are index-addressed, so overlap can change timing only, never
  content.  Exercised including the repeat-padded tail batch and the
  mask pass.
* DEVICE-POSTPROCESS PARITY: the fused decode+NMS program keeps the same
  detections as the host path (ops-level exact on tie-free inputs;
  end-to-end within float tolerance on a real model).
* STALE-CACHE SAFETY: under overlap the pyramid cache belongs to the
  NEWEST dispatch; the captured ``(feats, token)`` handle keeps batch N's
  mask pass correct, and the token assert still fails loudly without it.
"""

from __future__ import annotations

import dataclasses
import pickle

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.eval.tester import _Progress, pred_eval


class BatchVaryingStub:
    """Duck-typed predictor whose outputs differ per predict() call — a
    pipeline that mixed up batch→index mapping cannot pass the identity
    test with it.  predict() is always called from the main thread in
    loader order (the pipeline dispatches in order), so the call counter
    is deterministic on both paths."""

    def __init__(self, cfg, num_classes=3, R=12, mask=False):
        self.cfg = cfg
        self.K = num_classes
        self.R = R
        self._calls = 0
        self.mask_calls = 0
        self._mask = mask

    def predict(self, images, im_info):
        import jax.numpy as jnp

        B = images.shape[0]
        rng = np.random.RandomState(1000 + self._calls)
        self._calls += 1
        boxes = np.zeros((B, self.R, 4), np.float32)
        for r in range(self.R):
            x, y = 10 * (r % 6), 20 * (r // 6)
            boxes[:, r] = (x, y, x + 8, y + 8)
        scores = rng.uniform(0.05, 1.0, (B, self.R, self.K)).astype(
            np.float32)
        deltas = jnp.zeros((B, self.R, 4 * self.K), jnp.float32)
        return (jnp.asarray(boxes), jnp.ones((B, self.R), bool),
                jnp.asarray(scores), deltas, None)

    def predict_masks_cached(self, boxes, labels, token=None):
        self.mask_calls += 1
        B, R = labels.shape
        return np.full((B, R, 28, 28), 0.9, np.float32)

    def predict_masks_packed(self, boxes, labels, orig_boxes, hp, wp,
                             token=None):
        from mx_rcnn_tpu.ops.mask_paste import paste_masks

        probs = self.predict_masks_cached(boxes, labels, token)
        return paste_masks(probs, orig_boxes, hp, wp)


class MultiBatchLoader:
    """num_images images at batch_size, sequential, repeat-padded tail —
    the TestLoader batching contract without the image decode."""

    def __init__(self, num_images, batch_size, H=64, W=96):
        self.roidb = [{"height": H, "width": W} for _ in range(num_images)]
        self.batch_size = batch_size
        self.H, self.W = H, W

    def __iter__(self):
        n = len(self.roidb)
        bs = self.batch_size
        out = []
        for start in range(0, n, bs):
            idx = list(range(start, min(start + bs, n)))
            pad = bs - len(idx)
            out.append(dict(
                images=np.zeros((bs, self.H, self.W, 3), np.float32),
                im_info=np.tile(np.asarray([[self.H, self.W, 1.0]],
                                           np.float32), (bs, 1)),
                indices=np.asarray(idx + [idx[-1]] * pad, np.int32),
                batch_valid=np.asarray([True] * len(idx) + [False] * pad),
            ))
        return iter(out)


class RecordingIMDB:
    def __init__(self, num_classes, num_images, with_sds=False):
        self.num_classes = num_classes
        self.num_images = num_images
        self.captured = {}
        if with_sds:
            self.evaluate_sds = self._evaluate_sds

    def evaluate_detections(self, all_boxes):
        self.captured["boxes"] = all_boxes
        return {"mAP": 0.0}

    def _evaluate_sds(self, all_boxes, all_masks):
        self.captured["boxes"] = all_boxes
        self.captured["masks"] = all_masks
        return {"bbox": {"mAP": 0.0}}


def _run(inflight, mask=False, host_workers=2, num_images=5, batch_size=2):
    cfg = generate_config("resnet101_fpn_mask" if mask else "resnet101",
                          "PascalVOC")
    K = 3
    imdb = RecordingIMDB(K, num_images, with_sds=mask)
    pred = BatchVaryingStub(cfg, num_classes=K, mask=mask)
    pred_eval(pred, MultiBatchLoader(num_images, batch_size), imdb,
              max_per_image=6, thresh=0.05, with_masks=mask,
              inflight=inflight, host_workers=host_workers)
    return imdb.captured


def _assert_boxes_identical(a, b):
    assert len(a) == len(b)
    for k in range(1, len(a)):
        for i in range(len(a[k])):
            ax, bx = a[k][i], b[k][i]
            assert (ax is None) == (bx is None), (k, i)
            if ax is not None:
                # bit-identity, not allclose: same numpy math on the same
                # readback must produce the same bytes
                np.testing.assert_array_equal(ax, bx, err_msg=f"{k},{i}")


@pytest.mark.parametrize("depth", [1, 2, 4])
def test_pipelined_matches_serial_any_depth(depth):
    """all_boxes bit-identical between the serial oracle (inflight=0) and
    the pipelined loop at depths 1/2/4 — including the repeat-padded
    tail batch (5 images at batch_size 2)."""
    serial = _run(inflight=0)
    piped = _run(inflight=depth)
    _assert_boxes_identical(serial["boxes"], piped["boxes"])


def test_pipelined_matches_serial_with_masks():
    """Mask pass rides the pipeline: RLEs land on the same rows with the
    same contents, tail batch included."""
    serial = _run(inflight=0, mask=True)
    piped = _run(inflight=2, mask=True)
    _assert_boxes_identical(serial["boxes"], piped["boxes"])
    sm, pm = serial["masks"], piped["masks"]
    for k in range(1, len(sm)):
        for i in range(len(sm[k])):
            assert (sm[k][i] is None) == (pm[k][i] is None)
            if sm[k][i] is not None:
                assert sm[k][i] == pm[k][i], (k, i)


def test_pipelined_det_cache_identical(tmp_path):
    """The det_cache pickle is path-agnostic too (tools/reeval.py input)."""
    cfg = generate_config("resnet101", "PascalVOC")
    outs = []
    for inflight in (0, 2):
        imdb = RecordingIMDB(3, 5)
        path = tmp_path / f"dets_{inflight}.pkl"
        pred_eval(BatchVaryingStub(cfg, num_classes=3),
                  MultiBatchLoader(5, 2), imdb, max_per_image=6,
                  thresh=0.05, inflight=inflight, det_cache=str(path))
        with open(path, "rb") as f:
            outs.append(pickle.load(f))
    _assert_boxes_identical(outs[0], outs[1])


def test_progress_monotonic_thresholds():
    """The old gauge fired on ``done % 100 < len(dets)`` — it could fire
    several batches in a row (done=102,105 with batch 3... no: 102 then
    205) or skip a century when a large batch strode past it.  The
    replacement fires exactly once per crossed threshold, monotonically."""
    fired = []

    class Tel:
        def gauge(self, name, value):
            fired.append(name)

    p = _Progress(total=1000, n_chips=1, every=100)
    tel = Tel()
    for done in (40, 99, 100, 102, 150, 199, 200, 201, 550):
        p.update(done, tel)
    # fires at 100, 200 and 550 (crossing 300/400/500 in one leap fires
    # once, then re-arms at 600) — never twice inside one century
    assert len(fired) == 3


def test_registry_key_accepts_static_string_tokens():
    """predict_detections folds its baked-in statics into the shape key as
    strings ("mpi=100") — the key must stay hashable, keep batch
    extraction from the leading int dims, and round-trip the tokens."""
    from mx_rcnn_tpu.compile.registry import ProgramRegistry

    cfg = generate_config("resnet101", "PascalVOC")
    reg = ProgramRegistry(cfg)
    key = reg.key_for("predict_post", (4, 96, 128, 3, "mpi=100",
                                       "th=0.001"))
    assert key.batch == 4
    assert key.shape == (4, 96, 128, 3, "mpi=100", "th=0.001")
    assert hash(key) == hash(reg.key_for("predict_post",
                                         (4, 96, 128, 3, "mpi=100",
                                          "th=0.001")))
    # distinct statics are distinct programs
    assert key != reg.key_for("predict_post", (4, 96, 128, 3, "mpi=50",
                                               "th=0.001"))


def _grid_inputs(B=2, R=12, K=3, seed=0):
    """Well-separated boxes (NMS keeps everything) + tie-free scores →
    the host and device paths must agree EXACTLY (same selections, same
    order), leaving only the float math to compare."""
    rng = np.random.RandomState(seed)
    rois = np.zeros((B, R, 4), np.float32)
    for r in range(R):
        x, y = 30 * (r % 4), 25 * (r // 4)
        rois[:, r] = (x, y, x + 8, y + 8)
    deltas = np.zeros((B, R, 4 * K), np.float32)
    scores = rng.permutation(np.linspace(0.1, 0.95, B * R * K)).reshape(
        B, R, K).astype(np.float32)
    valid = np.ones((B, R), bool)
    im_info = np.tile(np.asarray([[100, 120, 1.0]], np.float32), (B, 1))
    return rois, valid, scores, deltas, im_info


def test_device_postprocess_parity_ops_level():
    """device_postprocess + device_dets_to_per_class == decode_image_boxes
    + per_class_nms on tie-free, well-separated inputs."""
    import jax

    from mx_rcnn_tpu.ops.postprocess import (decode_image_boxes,
                                             device_dets_to_per_class,
                                             device_postprocess,
                                             per_class_nms)

    rois, valid, scores, deltas, im_info = _grid_inputs()
    K = 3
    dets, dvalid = jax.device_get(device_postprocess(
        rois, valid, scores, deltas, im_info, num_classes=K, thresh=0.3,
        nms_thresh=0.3, max_per_image=10))
    for b in range(rois.shape[0]):
        dev = device_dets_to_per_class(dets[b], dvalid[b], K)
        boxes = decode_image_boxes(rois[b], deltas[b], im_info[b])
        host = per_class_nms(scores[b], boxes, valid[b], K, 0.3, 0.3, 10)
        for k in range(1, K):
            assert dev[k].shape == host[k].shape, (b, k)
            np.testing.assert_allclose(dev[k], host[k], atol=1e-4,
                                       err_msg=f"{b},{k}")


def test_device_postprocess_respects_cap_and_order():
    """The fused path honors max_per_image exactly and returns rows
    score-descending with the class id in column 5."""
    import jax

    from mx_rcnn_tpu.ops.postprocess import device_postprocess

    rois, valid, scores, deltas, im_info = _grid_inputs()
    dets, dvalid = jax.device_get(device_postprocess(
        rois, valid, scores, deltas, im_info, num_classes=3, thresh=0.05,
        nms_thresh=0.3, max_per_image=4))
    for b in range(rois.shape[0]):
        rows = dets[b][np.asarray(dvalid[b], bool)]
        assert len(rows) == 4
        s = rows[:, 4]
        assert (s[:-1] >= s[1:]).all()
        assert set(np.unique(rows[:, 5])) <= {1.0, 2.0}


def _tiny_predictor(mask=False):
    import jax

    from mx_rcnn_tpu.eval import Predictor
    from mx_rcnn_tpu.models import build_model, init_params

    cfg = generate_config(
        "resnet101_fpn_mask" if mask else "resnet50", "PascalVOC",
        TEST__RPN_PRE_NMS_TOP_N=300, TEST__RPN_POST_NMS_TOP_N=32)
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((96, 128),), MAX_GT=8)
    cfg = cfg.replace(network=net, tpu=tpu)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (96, 128))
    return Predictor(model, params, cfg), cfg


def test_device_postprocess_end_to_end_parity():
    """Real model: pred_eval with --device-postprocess keeps the same
    detections as the host-NMS path (per-class counts equal, boxes/scores
    within float tolerance), and pipelined devpost == serial devpost
    exactly."""
    from mx_rcnn_tpu.data import SyntheticDataset, TestLoader

    pred, cfg = _tiny_predictor()
    ds = SyntheticDataset(num_images=3, height=96, width=128)
    roidb = ds.gt_roidb()

    def run(devpost, inflight):
        imdb = RecordingIMDB(ds.num_classes, ds.num_images)
        pred_eval(pred, TestLoader(roidb, cfg, batch_size=1), imdb,
                  device_postprocess=devpost, inflight=inflight)
        return imdb.captured["boxes"]

    host = run(False, 0)
    dev_serial = run(True, 0)
    dev_piped = run(True, 2)
    # same fused program, same inputs → pipelining is bit-invisible
    _assert_boxes_identical(dev_serial, dev_piped)
    for k in range(1, ds.num_classes):
        for i in range(ds.num_images):
            h, d = host[k][i], dev_serial[k][i]
            assert len(h) == len(d), (k, i)
            if len(h):
                np.testing.assert_allclose(d, h, atol=1e-3,
                                           err_msg=f"{k},{i}")


def test_stale_pyramid_cache_under_overlap():
    """The overlap hazard the capture API exists for: after batch N+1's
    forward overwrites the cache, batch N's token must fail loudly, and
    the captured (feats, token) pair must keep N's mask pass correct."""
    import jax
    import numpy as np

    pred, cfg = _tiny_predictor(mask=True)
    B, H, W = 1, 96, 128
    rng = np.random.RandomState(0)
    img1 = rng.uniform(0, 1, (B, H, W, 3)).astype(np.float32)
    img2 = rng.uniform(0, 1, (B, H, W, 3)).astype(np.float32)
    info = np.asarray([[H, W, 1.0]], np.float32)
    boxes = np.asarray([[[10, 10, 60, 60]]], np.float32)
    labels = np.ones((B, 1), np.int32)

    pred.predict(img1, info)
    feats1, tok1 = pred.capture_feats()
    want = np.asarray(jax.device_get(
        pred.predict_masks_cached(boxes, labels, token=tok1)))
    pred.predict(img2, info)  # overwrites the cache (the overlap hazard)
    with pytest.raises(AssertionError, match="stale pyramid cache"):
        pred.predict_masks_cached(boxes, labels, token=tok1)
    # the captured handle still addresses batch 1's pyramid
    got = np.asarray(jax.device_get(
        pred.predict_masks_cached(boxes, labels, token=tok1,
                                  feats=feats1)))
    np.testing.assert_array_equal(got, want)
    # and batch 2's own token works against the live cache
    pred.predict_masks_cached(boxes, labels, token=pred.feats_token)
