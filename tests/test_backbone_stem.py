"""StemConvS2D must be numerically identical to the direct 7×7/2 conv it
replaces (reference: ``rcnn/symbol/symbol_resnet.py`` conv0/conv1 — the
space-to-depth regrouping is a TPU layout optimization, not a model change),
and keep the reference's checkpoint-compatible (7, 7, 3, 64) kernel layout.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mx_rcnn_tpu.models.backbones import StemConvS2D


@pytest.mark.parametrize("hw", [(64, 96), (63, 97), (62, 95), (61, 96)])
def test_s2d_stem_matches_direct_conv(rng, hw):
    h, w = hw
    x = jnp.asarray(rng.randn(2, h, w, 3), jnp.float32)
    mod = StemConvS2D(dtype=jnp.float32)
    params = mod.init(jax.random.PRNGKey(1), x)
    assert params["params"]["kernel"].shape == (7, 7, 3, 64)

    direct = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3)] * 2,
                     use_bias=False, dtype=jnp.float32)
    y_s2d = mod.apply(params, x)
    y_ref = direct.apply({"params": {"kernel": params["params"]["kernel"]}}, x)
    assert y_s2d.shape == y_ref.shape
    np.testing.assert_allclose(np.asarray(y_s2d), np.asarray(y_ref),
                               atol=1e-4, rtol=1e-4)


def test_s2d_stem_grad_matches(rng):
    x = jnp.asarray(rng.randn(1, 64, 96, 3), jnp.float32)
    mod = StemConvS2D(dtype=jnp.float32)
    params = mod.init(jax.random.PRNGKey(1), x)
    direct = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3)] * 2,
                     use_bias=False, dtype=jnp.float32)

    g1 = jax.grad(lambda p: jnp.sum(mod.apply(p, x) ** 2))(params)
    g2 = jax.grad(lambda p: jnp.sum(direct.apply(p, x) ** 2))(
        {"params": {"kernel": params["params"]["kernel"]}})
    np.testing.assert_allclose(np.asarray(g1["params"]["kernel"]),
                               np.asarray(g2["params"]["kernel"]),
                               atol=2e-2, rtol=1e-4)
