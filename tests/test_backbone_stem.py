"""StemConvS2D must be numerically identical to the direct 7×7/2 conv it
replaces (reference: ``rcnn/symbol/symbol_resnet.py`` conv0/conv1 — the
space-to-depth regrouping is a TPU layout optimization, not a model change),
and keep the reference's checkpoint-compatible (7, 7, 3, 64) kernel layout.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mx_rcnn_tpu.models.backbones import StemConvS2D


@pytest.mark.parametrize("hw", [(64, 96), (63, 97), (62, 95), (61, 96)])
def test_s2d_stem_matches_direct_conv(rng, hw):
    h, w = hw
    x = jnp.asarray(rng.randn(2, h, w, 3), jnp.float32)
    mod = StemConvS2D(dtype=jnp.float32)
    params = mod.init(jax.random.PRNGKey(1), x)
    assert params["params"]["kernel"].shape == (7, 7, 3, 64)

    direct = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3)] * 2,
                     use_bias=False, dtype=jnp.float32)
    y_s2d = mod.apply(params, x)
    y_ref = direct.apply({"params": {"kernel": params["params"]["kernel"]}}, x)
    assert y_s2d.shape == y_ref.shape
    np.testing.assert_allclose(np.asarray(y_s2d), np.asarray(y_ref),
                               atol=1e-4, rtol=1e-4)


def test_s2d_stem_grad_matches(rng):
    x = jnp.asarray(rng.randn(1, 64, 96, 3), jnp.float32)
    mod = StemConvS2D(dtype=jnp.float32)
    params = mod.init(jax.random.PRNGKey(1), x)
    direct = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3)] * 2,
                     use_bias=False, dtype=jnp.float32)

    g1 = jax.grad(lambda p: jnp.sum(mod.apply(p, x) ** 2))(params)
    g2 = jax.grad(lambda p: jnp.sum(direct.apply(p, x) ** 2))(
        {"params": {"kernel": params["params"]["kernel"]}})
    np.testing.assert_allclose(np.asarray(g1["params"]["kernel"]),
                               np.asarray(g2["params"]["kernel"]),
                               atol=2e-2, rtol=1e-4)


def test_bottleneck_bn_fold_matches_explicit(rng):
    """Folded conv+FrozenBN (ScaledConv) must equal the explicit
    conv -> affine sequence.  Run at highest matmul precision: at default
    precision this build rounds conv operands to bf16, where scaling the
    kernel before vs after the conv differs by ~1e-2 — the model's normal
    bf16 noise floor, not a fold error."""
    import flax

    from mx_rcnn_tpu.models.backbones import Bottleneck

    x = jnp.asarray(rng.randn(2, 16, 24, 64), jnp.float32)
    mod = Bottleneck(16, strides=2, project=True, dtype=jnp.float32)
    params = mod.init(jax.random.PRNGKey(0), x)
    flat = flax.traverse_util.flatten_dict(params["params"])
    for k in list(flat):  # nontrivial BN params so the fold is exercised
        if k[-1] in ("gamma", "beta", "mean"):
            flat[k] = jnp.asarray(rng.randn(*flat[k].shape) * 0.5 +
                                  (1.0 if k[-1] == "gamma" else 0.0),
                                  jnp.float32)
        elif k[-1] == "var":
            flat[k] = jnp.asarray(np.abs(rng.randn(*flat[k].shape)) + 0.5,
                                  jnp.float32)
    params = {"params": flax.traverse_util.unflatten_dict(flat)}

    def bn(h, pre):
        s = flat[(pre, "gamma")] / jnp.sqrt(flat[(pre, "var")] + 2e-5)
        b = flat[(pre, "beta")] - flat[(pre, "mean")] * s
        return h * s + b

    def conv(h, pre, stride, k):
        return jax.lax.conv_general_dilated(
            h, flat[(pre, "kernel")], (stride, stride), [(k // 2, k // 2)] * 2,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    with jax.default_matmul_precision("highest"):
        y_folded = mod.apply(params, x)
        out = jax.nn.relu(bn(conv(x, "conv1", 1, 1), "bn1"))
        out = jax.nn.relu(bn(conv(out, "conv2", 2, 3), "bn2"))
        out = bn(conv(out, "conv3", 1, 1), "bn3")
        sc = bn(conv(x, "sc_conv", 2, 1), "sc_bn")
        y_ref = jax.nn.relu(out + sc)
    np.testing.assert_allclose(np.asarray(y_folded), np.asarray(y_ref),
                               atol=1e-4, rtol=1e-4)


def test_scaled_conv_extra_batch_dims(rng):
    """ScaledConv folds leading batch dims like nn.Conv (stage-5 RoI heads
    run over (B, R, h, w, C) features)."""
    from mx_rcnn_tpu.models.backbones import ScaledConv

    x = jnp.asarray(rng.randn(2, 3, 8, 8, 16), jnp.float32)
    mod = ScaledConv(8, 3, 1, dtype=jnp.float32)
    p = mod.init(jax.random.PRNGKey(0), x)
    y = mod.apply(p, x)
    assert y.shape == (2, 3, 8, 8, 8)
    y_flat = mod.apply(p, x.reshape(6, 8, 8, 16))
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_flat).reshape(y.shape),
                               rtol=1e-5, atol=1e-5)


def test_s2d_stem_accepts_host_s2d_input(rng):
    """StemConvS2D((H, W, 3)) must equal StemConvS2D(space_to_depth2(x)) —
    the loader's HOST_S2D path ships the latter with the same params."""
    from mx_rcnn_tpu.data.image import space_to_depth2

    x = np.asarray(rng.randn(64, 96, 3), np.float32)
    mod = StemConvS2D(dtype=jnp.float32)
    params = mod.init(jax.random.PRNGKey(1), jnp.asarray(x[None]))
    y_dev = mod.apply(params, jnp.asarray(x[None]))
    y_host = mod.apply(params, jnp.asarray(space_to_depth2(x)[None]))
    assert y_dev.shape == y_host.shape
    np.testing.assert_allclose(np.asarray(y_dev), np.asarray(y_host),
                               rtol=1e-5, atol=1e-5)
