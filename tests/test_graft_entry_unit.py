"""Driver-entry plumbing that must not regress silently: the
machine-fingerprinted compile-cache key and the dryrun case registry's
structural invariants (round-5 redesign — see __graft_entry__ docstring
for the rc=124 history these encode)."""

import os

from __graft_entry__ import _CASES, machine_cache_dir


def test_machine_cache_dir_is_deterministic_and_keyed():
    a = machine_cache_dir("/tmp/base")
    b = machine_cache_dir("/tmp/base")
    assert a == b, "fingerprint must be stable within a machine"
    assert a.startswith("/tmp/base" + os.sep)
    leaf = os.path.basename(a)
    assert len(leaf) == 12 and all(c in "0123456789abcdef" for c in leaf)
    # a different base relocates, same fingerprint
    assert os.path.basename(machine_cache_dir("/tmp/other")) == leaf


def test_case_registry_invariants():
    names = [c[0] for c in _CASES]
    assert len(set(names)) == len(names)
    # flat_dp must stay first: it always runs (budget check exempts it)
    # and multislice asserts against its loss
    assert names[0] == "flat_dp"
    assert names.index("multislice") > 0
    for name, fn, min_dev, need_even, units in _CASES:
        assert callable(fn), name
        assert min_dev >= 1 and units > 0, name
    # priority order is the VERDICT-prescribed certification order
    assert names[1:3] == ["fpn_dp*sp", "mask_dp*tp"], names
