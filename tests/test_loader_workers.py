"""Multi-worker host input pipeline (data/workers.py + loader wiring):
workers=N must be batch-for-batch identical to the serial producer at the
same seed (including mid-epoch auto-resume), isolate worker crashes the
way PR-2 isolates bad records, reuse its shared-memory ring across
epochs, and preserve order under worker skew."""

import dataclasses

import numpy as np
import pytest

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import SyntheticDataset
from mx_rcnn_tpu.data.loader import AnchorLoader, ROIIter, prepare_image
from mx_rcnn_tpu.data import workers as workers_mod


def tiny_cfg(n_workers=0):
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16,
        tpu__SCALES=((64, 96),), tpu__MAX_GT=4,
        tpu__LOADER_WORKERS=n_workers,
    )
    return cfg.replace(network=dataclasses.replace(
        cfg.network, ANCHOR_SCALES=(2, 4), PIXEL_STDS=(127.0, 127.0, 127.0)))


def tiny_roidb(n_images=10, proposals=False):
    ds = SyntheticDataset(num_images=n_images, num_classes=5,
                          height=64, width=96)
    roidb = ds.gt_roidb()
    if proposals:
        rng = np.random.RandomState(7)
        for rec in roidb:
            k = rng.randint(1, 5)
            x1 = rng.randint(0, 40, size=(k, 1)).astype(np.float32)
            y1 = rng.randint(0, 30, size=(k, 1)).astype(np.float32)
            rec["proposals"] = np.concatenate(
                [x1, y1, x1 + 20, y1 + 20], axis=1)
    return roidb


def snapshot(loader, epochs=1):
    out = []
    for _ in range(epochs):
        out.extend({k: v.copy() for k, v in b.items()} for b in loader)
    return out


def assert_batches_equal(a, b):
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        assert sorted(x) == sorted(y), i
        for k in x:
            np.testing.assert_array_equal(x[k], y[k],
                                          err_msg=f"batch {i} key {k}")


def test_workers_match_serial_batches():
    """The acceptance pin: workers=2 output is batch-for-batch identical
    to workers=0 at the same seed, across epochs (epoch k's plan depends
    on epoch k-1's RNG draws)."""
    roidb = tiny_roidb()
    serial = snapshot(AnchorLoader(roidb, tiny_cfg(0), batch_size=2,
                                   shuffle=True, seed=3), epochs=2)
    ld = AnchorLoader(roidb, tiny_cfg(2), batch_size=2, shuffle=True, seed=3)
    try:
        parallel = snapshot(ld, epochs=2)
    finally:
        ld.close_workers()
    assert_batches_equal(serial, parallel)


def test_roiiter_workers_match_serial():
    """Same pin for the proposal loader: pixels come from the pool, rois
    attach in the parent from the ACTUAL (possibly substituted) index."""
    roidb = tiny_roidb(proposals=True)
    serial = snapshot(ROIIter(roidb, tiny_cfg(0), batch_size=2,
                              shuffle=True, seed=5))
    it = ROIIter(roidb, tiny_cfg(2), batch_size=2, shuffle=True, seed=5)
    try:
        parallel = snapshot(it)
    finally:
        it.close_workers()
    assert any("rois" in b for b in serial)
    assert_batches_equal(serial, parallel)


def test_mid_epoch_resume_with_workers():
    """auto-resume's exact mid-epoch fast-forward (advance_epochs +
    skip_next) with workers on: the resumed tail equals the uninterrupted
    serial epoch's tail, batch for batch."""
    roidb = tiny_roidb()
    serial = snapshot(AnchorLoader(roidb, tiny_cfg(0), batch_size=2,
                                   shuffle=True, seed=11), epochs=2)
    steps = len(serial) // 2
    ld = AnchorLoader(roidb, tiny_cfg(2), batch_size=2, shuffle=True,
                      seed=11)
    try:
        ld.advance_epochs(1)  # resume inside epoch 1 (0-based)
        ld.skip_next(2)
        resumed = snapshot(ld)
    finally:
        ld.close_workers()
    assert_batches_equal(serial[steps + 2:], resumed)


def test_worker_crash_respawn(monkeypatch, tmp_path):
    """A worker hard-crashing (os._exit) mid-task is respawned, its
    in-flight tasks reissued, and the epoch still comes out identical to
    the serial run — PR-2's isolation contract at process granularity."""
    roidb = tiny_roidb()
    serial = snapshot(AnchorLoader(roidb, tiny_cfg(0), batch_size=2,
                                   shuffle=True, seed=2))
    monkeypatch.setenv("MXR_FAULT_WORKER_CRASH_IDX", "3")
    monkeypatch.setenv("MXR_FAULT_WORKER_CRASH_ONCE",
                       str(tmp_path / "crashed.marker"))
    ld = AnchorLoader(roidb, tiny_cfg(2), batch_size=2, shuffle=True, seed=2)
    try:
        parallel = snapshot(ld)
        assert ld._pool is not None and ld._pool.respawns >= 1
    finally:
        ld.close_workers()
    assert_batches_equal(serial, parallel)


def test_worker_crash_systemic_limit(monkeypatch):
    """A worker that dies on EVERY attempt must not respawn forever:
    crossing the pool's respawn budget surfaces a RuntimeError through
    the prefetcher instead of silently grinding."""
    monkeypatch.setenv("MXR_FAULT_WORKER_CRASH_IDX", "3")  # no ONCE marker
    monkeypatch.setattr(workers_mod, "MAX_WORKER_RESPAWNS", 2)
    ld = AnchorLoader(tiny_roidb(), tiny_cfg(2), batch_size=2,
                      shuffle=True, seed=2)
    try:
        with pytest.raises(RuntimeError, match="respawn"):
            snapshot(ld)
    finally:
        ld.close_workers()


def test_shm_slot_reuse_across_epochs():
    """The pool (and its shm segment) persists across epochs; every ring
    slot returns to the free list after each epoch — no slot leak, no
    per-epoch reallocation."""
    ld = AnchorLoader(tiny_roidb(), tiny_cfg(2), batch_size=2,
                      shuffle=True, seed=4)
    try:
        snapshot(ld)
        pool = ld._pool
        assert pool is not None
        name = pool._shm.name
        snapshot(ld)
        assert ld._pool is pool  # reused, not rebuilt
        assert pool._shm.name == name
        assert pool._free.qsize() == pool.n_slots  # all slots back
        assert not pool._pending
    finally:
        ld.close_workers()


def test_order_preserved_under_slow_worker(monkeypatch):
    """Deliberate worker skew (one worker sleeps per task) must not
    reorder samples: the collector hands results back in task order."""
    roidb = tiny_roidb(n_images=8)
    serial = snapshot(AnchorLoader(roidb, tiny_cfg(0), batch_size=2,
                                   shuffle=True, seed=6))
    monkeypatch.setenv("MXR_FAULT_WORKER_SLOW", "0:0.05")
    ld = AnchorLoader(roidb, tiny_cfg(2), batch_size=2, shuffle=True, seed=6)
    try:
        parallel = snapshot(ld)
    finally:
        ld.close_workers()
    assert_batches_equal(serial, parallel)


def test_serve_prepare_parity():
    """The serving ingest path through the pool is byte-identical to the
    caller-thread prepare_image it replaces."""
    cfg = tiny_cfg()
    pool = workers_mod.WorkerPool(cfg, num_workers=1)
    try:
        rng = np.random.RandomState(0)
        for shape in [(50, 70, 3), (70, 50, 3)]:  # both orientations
            img = rng.randint(0, 255, shape, np.uint8)
            got, got_info = pool.prepare(img, cfg.tpu.SCALES[0])
            want, want_info = prepare_image(img, cfg, cfg.tpu.SCALES[0])
            np.testing.assert_array_equal(got, want)
            np.testing.assert_array_equal(got_info, want_info)
    finally:
        pool.close()


def test_bad_record_isolated_inside_worker(monkeypatch):
    """A record that fails to LOAD (not crash) inside a worker follows
    the PR-2 substitution contract: next record substituted, epoch
    completes, same shapes."""
    roidb = tiny_roidb()
    bad = dict(roidb[3])
    bad["image_array"] = None  # load raises TypeError in the worker
    roidb_bad = list(roidb)
    roidb_bad[3] = bad
    ld = AnchorLoader(roidb_bad, tiny_cfg(2), batch_size=2, shuffle=False,
                      seed=0)
    try:
        batches = snapshot(ld)
    finally:
        ld.close_workers()
    assert len(batches) == len(roidb) // 2
    for b in batches:
        assert b["images"].shape[0] == 2
