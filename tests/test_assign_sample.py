"""assign_anchor + sample_rois contract tests (SURVEY §2 rows rpn.py/rcnn.py)."""

import numpy as np
import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.anchors import generate_anchors, all_anchors
from mx_rcnn_tpu.ops.assign_anchor import assign_anchor
from mx_rcnn_tpu.ops.sample_rois import sample_rois
from tests import oracles

MAX_GT = 8


def _setup(rng, n_gt=3, fh=10, fw=12, stride=16):
    # small scales so a useful fraction of anchors is inside the tiny test image
    anchors = all_anchors(fh, fw, stride, generate_anchors(scales=(1, 2, 4)))
    im_h, im_w = fh * stride, fw * stride
    gt = np.zeros((MAX_GT, 4), np.float32)
    for i in range(n_gt):
        x1, y1 = rng.rand(2) * np.array([im_w - 80, im_h - 80])
        gt[i] = [x1, y1, x1 + 20 + rng.rand() * 60, y1 + 20 + rng.rand() * 60]
    valid = np.arange(MAX_GT) < n_gt
    return anchors, gt, valid, im_h, im_w


def test_assign_anchor_labels_match_oracle(rng):
    anchors, gt, valid, im_h, im_w = _setup(rng)
    out = assign_anchor(
        jnp.asarray(anchors), jnp.asarray(gt), jnp.asarray(valid),
        jnp.float32(im_h), jnp.float32(im_w), jax.random.PRNGKey(0),
        batch_size=100000, fg_fraction=1.0,
    )  # huge batch → no subsampling, raw labels comparable
    got = np.asarray(out["label"])
    want = oracles.assign_anchor_oracle(anchors, gt[valid], im_h, im_w)
    np.testing.assert_array_equal(got, want.astype(np.int32))


def test_assign_anchor_subsampling_counts(rng):
    anchors, gt, valid, im_h, im_w = _setup(rng, n_gt=5)
    out = assign_anchor(
        jnp.asarray(anchors), jnp.asarray(gt), jnp.asarray(valid),
        jnp.float32(im_h), jnp.float32(im_w), jax.random.PRNGKey(1),
        batch_size=256, fg_fraction=0.5,
    )
    label = np.asarray(out["label"])
    n_fg = (label == 1).sum()
    n_bg = (label == 0).sum()
    assert n_fg <= 128
    assert n_fg + n_bg <= 256
    # plenty of bg anchors exist in a 120-cell grid → batch should fill
    assert n_fg + n_bg == 256


def test_assign_anchor_weights_only_on_fg(rng):
    anchors, gt, valid, im_h, im_w = _setup(rng)
    out = assign_anchor(
        jnp.asarray(anchors), jnp.asarray(gt), jnp.asarray(valid),
        jnp.float32(im_h), jnp.float32(im_w), jax.random.PRNGKey(2),
    )
    label = np.asarray(out["label"])
    w = np.asarray(out["bbox_weight"])
    assert (w[label == 1] == 1.0).all()
    assert (w[label != 1] == 0.0).all()


def test_assign_anchor_targets_decode_to_gt(rng):
    anchors, gt, valid, im_h, im_w = _setup(rng)
    out = assign_anchor(
        jnp.asarray(anchors), jnp.asarray(gt), jnp.asarray(valid),
        jnp.float32(im_h), jnp.float32(im_w), jax.random.PRNGKey(3),
    )
    label = np.asarray(out["label"])
    tgt = np.asarray(out["bbox_target"])
    fg = np.where(label == 1)[0]
    assert len(fg) > 0
    from mx_rcnn_tpu.ops.boxes import bbox_pred
    dec = np.asarray(bbox_pred(jnp.asarray(anchors[fg]), jnp.asarray(tgt[fg])))
    ious = oracles.iou_oracle(dec, gt[valid])
    assert (ious.max(axis=1) > 0.99).all()


def test_assign_anchor_no_gt(rng):
    anchors, gt, valid, im_h, im_w = _setup(rng, n_gt=0)
    out = assign_anchor(
        jnp.asarray(anchors), jnp.asarray(gt), jnp.asarray(np.zeros(MAX_GT, bool)),
        jnp.float32(im_h), jnp.float32(im_w), jax.random.PRNGKey(4),
    )
    label = np.asarray(out["label"])
    assert (label != 1).all()
    assert (label == 0).sum() == 256  # all-bg batch


def test_assign_anchor_iou_bf16_close_to_f32(rng):
    """cfg.TRAIN.RPN_ASSIGN_IOU_BF16 (divergence-ledger lever): bf16 IoU
    storage may flip only threshold-marginal anchors.  With no subsampling
    (huge batch) the raw label fields must agree except where the f32 IoU
    sits within one bf16 ulp (~0.004) of the 0.7/0.3 thresholds or of a
    per-gt-max tie; targets on agreeing fg rows stay bit-identical (the
    coordinate path never leaves f32)."""
    anchors, gt, valid, im_h, im_w = _setup(rng, n_gt=5)
    kw = dict(batch_size=100000, fg_fraction=1.0)
    args = (jnp.asarray(anchors), jnp.asarray(gt), jnp.asarray(valid),
            jnp.float32(im_h), jnp.float32(im_w), jax.random.PRNGKey(7))
    ref = assign_anchor(*args, **kw)
    got = assign_anchor(*args, iou_bf16=True, **kw)
    l_ref = np.asarray(ref["label"])
    l_got = np.asarray(got["label"])

    from mx_rcnn_tpu.ops.boxes import bbox_overlaps

    ov = np.asarray(bbox_overlaps(jnp.asarray(anchors), jnp.asarray(gt)))
    ov = np.where(valid[None, :], ov, -1.0)
    mx = ov.max(axis=1)
    gt_max = ov.max(axis=0)
    tol = 0.004  # one bf16 ulp at ~0.5-1.0
    # tie-distance only over VALID gt columns: padded columns carry the
    # sentinel -1.0 in both ov and gt_max, whose distance-0 match would
    # mark every anchor marginal and make the assertion vacuous
    tie_dist = np.abs(ov[:, valid] - gt_max[valid][None, :]).min(axis=1)
    marginal = (np.abs(mx - 0.7) < tol) | (np.abs(mx - 0.3) < tol) | (
        tie_dist < tol)
    disagree = l_ref != l_got
    assert not (disagree & ~marginal).any(), (
        f"{(disagree & ~marginal).sum()} non-marginal label flips")
    # target equality needs a stable argmax gt: exclude rows whose top-2
    # gt IoUs are within one bf16 ulp (bf16 may break the near-tie the
    # other way; the coordinates it then encodes are a different gt's)
    top2 = np.sort(ov, axis=1)[:, -2:]
    argmax_stable = (top2[:, 1] - top2[:, 0]) > tol
    both_fg = (l_ref == 1) & (l_got == 1) & argmax_stable
    np.testing.assert_array_equal(np.asarray(ref["bbox_target"])[both_fg],
                                  np.asarray(got["bbox_target"])[both_fg])


def _sample_setup(rng, n_rois=300, n_gt=4, num_classes=21):
    rois = rng.rand(n_rois, 4).astype(np.float32) * 200
    rois[:, 2:] = rois[:, :2] + 10 + rng.rand(n_rois, 2) * 100
    gt = np.zeros((MAX_GT, 4), np.float32)
    cls = np.zeros(MAX_GT, np.int32)
    for i in range(n_gt):
        gt[i] = [20 + 40 * i, 30, 20 + 40 * i + 35, 90]
        cls[i] = rng.randint(1, num_classes)
    # append gt to rois (the ProposalTarget contract)
    rois[:n_gt] = gt[:n_gt]
    valid = np.ones(n_rois, bool)
    gt_valid = np.arange(MAX_GT) < n_gt
    return rois, valid, gt, cls, gt_valid


def test_sample_rois_counts_and_labels(rng):
    rois, valid, gt, cls, gt_valid = _sample_setup(rng)
    out = sample_rois(
        jnp.asarray(rois), jnp.asarray(valid), jnp.asarray(gt),
        jnp.asarray(cls), jnp.asarray(gt_valid), jax.random.PRNGKey(0),
        num_classes=21, batch_rois=128, fg_fraction=0.25)
    label = np.asarray(out["label"])
    assert label.shape == (128,)
    n_fg = (label > 0).sum()
    assert 1 <= n_fg <= 32
    # every fg-sampled roi really has IoU >= 0.5 with a gt of that class
    srois = np.asarray(out["rois"])
    for i in np.where(label > 0)[0]:
        ious = oracles.iou_oracle(srois[i:i + 1], gt[gt_valid])[0]
        assert ious.max() >= 0.5
        assert cls[ious.argmax()] == label[i]


def test_sample_rois_bbox_layout(rng):
    rois, valid, gt, cls, gt_valid = _sample_setup(rng)
    out = sample_rois(
        jnp.asarray(rois), jnp.asarray(valid), jnp.asarray(gt),
        jnp.asarray(cls), jnp.asarray(gt_valid), jax.random.PRNGKey(1),
        num_classes=21)
    label = np.asarray(out["label"])
    w = np.asarray(out["bbox_weight"])
    t = np.asarray(out["bbox_target"])
    assert w.shape == (128, 84)
    for i in range(128):
        l = label[i]
        if l > 0:
            want = np.zeros(84)
            want[4 * l:4 * l + 4] = 1
            np.testing.assert_array_equal(w[i], want)
        else:
            assert (w[i] == 0).all()
            assert (t[i] == 0).all()


def test_sample_rois_targets_decode(rng):
    rois, valid, gt, cls, gt_valid = _sample_setup(rng)
    means, stds = (0.0, 0.0, 0.0, 0.0), (0.1, 0.1, 0.2, 0.2)
    out = sample_rois(
        jnp.asarray(rois), jnp.asarray(valid), jnp.asarray(gt),
        jnp.asarray(cls), jnp.asarray(gt_valid), jax.random.PRNGKey(2),
        num_classes=21, bbox_means=means, bbox_stds=stds)
    label = np.asarray(out["label"])
    t = np.asarray(out["bbox_target"])
    srois = np.asarray(out["rois"])
    from mx_rcnn_tpu.ops.boxes import bbox_pred
    for i in np.where(label > 0)[0][:5]:
        l = label[i]
        d = t[i, 4 * l:4 * l + 4] * np.asarray(stds) + np.asarray(means)
        dec = np.asarray(bbox_pred(jnp.asarray(srois[i:i + 1]), jnp.asarray(d[None])))
        ious = oracles.iou_oracle(dec, gt[gt_valid])[0]
        assert ious.max() > 0.99


def test_sample_rois_no_gt(rng):
    rois, valid, gt, cls, gt_valid = _sample_setup(rng, n_gt=0)
    out = sample_rois(
        jnp.asarray(rois), jnp.asarray(valid), jnp.asarray(gt),
        jnp.asarray(cls), jnp.asarray(np.zeros(MAX_GT, bool)), jax.random.PRNGKey(3),
        num_classes=21)
    label = np.asarray(out["label"])
    assert (label == 0).all()
    assert (np.asarray(out["bbox_weight"]) == 0).all()
