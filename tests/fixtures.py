"""On-disk dataset fixtures: a mini-VOCdevkit and a mini-COCO, generated
from synthetic learnable images (solid class-colored rectangles on noise —
the SyntheticDataset recipe, but written through the real file formats).

These exist so the ACTUAL file pipelines run under test: cv2/PIL JPEG
decode → resize/bucket → train → checkpoint → eval → official writeout
(VERDICT round-1 item 2: rehearse the real-data path end-to-end through
files so the day VOC/COCO appears nothing new can break).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np


def run_tool(mod, main, argv):
    """Invoke a CLI module's parse_args + main under a temporary sys.argv
    (the shared argv-juggling for driving real drivers in-process)."""
    old = sys.argv
    sys.argv = [mod.__name__ + ".py"] + list(argv)
    try:
        return main(mod.parse_args())
    finally:
        sys.argv = old

# three visually distinct classes; names must be real VOC classes so the
# PascalVOC name→index mapping applies unchanged
FIXTURE_CLASSES = ("aeroplane", "bicycle", "bird")
_COLORS = {"aeroplane": (220, 40, 40), "bicycle": (40, 220, 40),
           "bird": (40, 40, 220)}


def _make_image(rng, h, w, max_objects=3):
    """-> (uint8 RGB image, [(name, x1, y1, x2, y2)])."""
    img = (rng.randn(h, w, 3) * 12 + 127).clip(0, 255).astype(np.uint8)
    n = rng.randint(1, max_objects + 1)
    objs = []
    for _ in range(n):
        name = FIXTURE_CLASSES[rng.randint(len(FIXTURE_CLASSES))]
        bw = rng.randint(w // 4, w // 2)
        bh = rng.randint(h // 4, h // 2)
        x1 = rng.randint(0, w - bw)
        y1 = rng.randint(0, h - bh)
        img[y1:y1 + bh, x1:x1 + bw] = _COLORS[name]
        objs.append((name, x1, y1, x1 + bw - 1, y1 + bh - 1))
    return img, objs


def _save_jpeg(path, img):
    from PIL import Image

    Image.fromarray(img).save(path, quality=95)


def make_mini_voc(dataset_path: str, n_train: int = 16, n_test: int = 8,
                  size=(120, 160), year: str = "2007", seed: int = 0):
    """Write a mini VOCdevkit under ``dataset_path`` (JPEGImages +
    Annotations + ImageSets/Main/{trainval,test}.txt).  Returns
    (train_ids, test_ids)."""
    rng = np.random.RandomState(seed)
    h, w = size
    devkit = os.path.join(dataset_path, f"VOC{year}")
    for sub in ("JPEGImages", "Annotations", os.path.join("ImageSets", "Main")):
        os.makedirs(os.path.join(devkit, sub), exist_ok=True)

    # "minitest" is deliberately NOT a standard VOC split name: test-mode
    # drivers must route --image_set through TEST_IMAGE_SET (the field
    # get_imdb(test=True) reads) — a standard name would mask a regression
    # by coinciding with the preset default
    splits = {"trainval": [f"{i:06d}" for i in range(n_train)],
              "minitest": [f"{1000 + i:06d}" for i in range(n_test)]}
    for split, ids in splits.items():
        with open(os.path.join(devkit, "ImageSets", "Main", split + ".txt"),
                  "w") as f:
            f.write("\n".join(ids) + "\n")
        for idx in ids:
            img, objs = _make_image(rng, h, w)
            _save_jpeg(os.path.join(devkit, "JPEGImages", idx + ".jpg"), img)
            xml = [f"<annotation><filename>{idx}.jpg</filename>",
                   f"<size><width>{w}</width><height>{h}</height>"
                   "<depth>3</depth></size>"]
            for name, x1, y1, x2, y2 in objs:
                # VOC pixels are 1-indexed in the XML
                xml.append(
                    f"<object><name>{name}</name><difficult>0</difficult>"
                    f"<bndbox><xmin>{x1 + 1}</xmin><ymin>{y1 + 1}</ymin>"
                    f"<xmax>{x2 + 1}</xmax><ymax>{y2 + 1}</ymax></bndbox>"
                    "</object>")
            xml.append("</annotation>")
            with open(os.path.join(devkit, "Annotations", idx + ".xml"),
                      "w") as f:
                f.write("\n".join(xml))
    return splits["trainval"], splits["minitest"]


def make_mini_coco(dataset_path: str, image_set: str = "minitrain",
                   n: int = 12, size=(120, 160), seed: int = 0,
                   with_masks: bool = True):
    """Write a mini COCO split: ``{dataset_path}/{image_set}/*.jpg`` +
    ``{dataset_path}/annotations/instances_{image_set}.json`` (sparse
    category ids, polygon segmentations covering the boxes)."""
    rng = np.random.RandomState(seed)
    h, w = size
    img_dir = os.path.join(dataset_path, image_set)
    os.makedirs(img_dir, exist_ok=True)
    os.makedirs(os.path.join(dataset_path, "annotations"), exist_ok=True)

    # sparse ids on purpose (the real COCO ids are sparse)
    categories = [{"id": 3 * i + 1, "name": n_}
                  for i, n_ in enumerate(FIXTURE_CLASSES)]
    name_to_cat = {c["name"]: c["id"] for c in categories}

    images, annotations = [], []
    aid = 1
    for i in range(n):
        img, objs = _make_image(rng, h, w)
        fname = f"{i:012d}.jpg"
        _save_jpeg(os.path.join(img_dir, fname), img)
        images.append({"id": i + 1, "file_name": fname,
                       "height": h, "width": w})
        for name, x1, y1, x2, y2 in objs:
            bw = x2 - x1 + 1
            bh = y2 - y1 + 1
            ann = {"id": aid, "image_id": i + 1,
                   "category_id": name_to_cat[name],
                   "bbox": [float(x1), float(y1), float(bw), float(bh)],
                   "area": float(bw * bh), "iscrowd": 0}
            if with_masks:
                ann["segmentation"] = [[float(x1), float(y1), float(x2 + 1),
                                        float(y1), float(x2 + 1),
                                        float(y2 + 1), float(x1),
                                        float(y2 + 1)]]
            annotations.append(ann)
            aid += 1

    path = os.path.join(dataset_path, "annotations",
                        f"instances_{image_set}.json")
    with open(path, "w") as f:
        json.dump({"images": images, "annotations": annotations,
                   "categories": categories}, f)
    return path
