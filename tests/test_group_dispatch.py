"""Producer-thread group assembly for ``fit(steps_per_dispatch=k)``
(round-4 VERDICT weakness 2: consumer-side stacking shipped each k-group
synchronously, giving up the transfer overlap the ``put`` hook exists
for).  Covers the ``_make_group_wrap`` generator contract directly and
the full loader→wrap→fit seam on the 8-device CPU mesh.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import AnchorLoader, SyntheticDataset
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.parallel import make_mesh
from mx_rcnn_tpu.train import fit
from mx_rcnn_tpu.train.trainer import _make_group_wrap


def _batch(shape_hw, tag):
    h, w = shape_hw
    return dict(images=np.full((1, h, w, 3), tag, np.float32),
                im_info=np.asarray([[h, w, 1.0]], np.float32))


def test_group_wrap_stacks_and_flushes():
    """k=2 over shapes [A, A, B, A, A, A]: the bucket change at B flushes
    it as a single, the trailing odd batch flushes at epoch end, and the
    two homogeneous pairs arrive stacked."""
    A, B = (64, 96), (96, 64)
    wrap = _make_group_wrap(2, None)  # plan=None → plain device_put
    seq = [_batch(A, 0), _batch(A, 1), _batch(B, 2), _batch(A, 3),
           _batch(A, 4), _batch(A, 5)]
    items = list(wrap(iter(seq)))

    kinds = [(kind, n) for kind, n, _ in items]
    assert kinds == [("group", 2), ("single", 1), ("group", 2),
                     ("single", 1)], kinds
    g0 = jax.device_get(items[0][2])
    assert g0["images"].shape == (2, 1, 64, 96, 3)
    # stack preserves loader order: tags 0, 1
    np.testing.assert_array_equal(g0["images"][0, 0, 0, 0, 0], 0.0)
    np.testing.assert_array_equal(g0["images"][1, 0, 0, 0, 0], 1.0)
    s_b = jax.device_get(items[1][2])
    assert s_b["images"].shape == (1, 96, 64, 3)
    np.testing.assert_array_equal(s_b["images"][0, 0, 0, 0], 2.0)
    assert jax.device_get(items[3][2])["images"][0, 0, 0, 0] == 5.0


def test_group_wrap_exact_multiple_no_tail():
    wrap = _make_group_wrap(3, None)
    items = list(wrap(iter([_batch((64, 96), i) for i in range(6)])))
    assert [(k, n) for k, n, _ in items] == [("group", 3), ("group", 3)]


def _mesh_cfg():
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16, TRAIN__FLIP=False,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4),
                              PIXEL_STDS=(127.0, 127.0, 127.0))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4)
    return cfg.replace(network=net, tpu=tpu)


def test_fit_k2_mesh_prefetch_stacked(monkeypatch):
    """fit(steps_per_dispatch=2) with a REAL AnchorLoader on the 8-device
    mesh: the group assembler is installed on the loader's ``wrap`` hook
    (so stacking + stacked transfer run on the prefetch thread), groups
    are shipped through shard_stacked_batch, the mixed-orientation roidb
    forces a bucket-change flush through the single-step program, and the
    step count still equals steps_per_epoch."""
    import threading

    import mx_rcnn_tpu.train.trainer as trainer_mod

    cfg = _mesh_cfg()
    land = SyntheticDataset(num_images=20, num_classes=cfg.NUM_CLASSES,
                            height=64, width=96, seed=0).gt_roidb()
    port = SyntheticDataset(num_images=6, num_classes=cfg.NUM_CLASSES,
                            height=96, width=64, seed=1).gt_roidb()
    loader = AnchorLoader(land + port, cfg, batch_size=8, shuffle=True,
                          seed=0)
    # 20 landscape → 3 batches (wrap-padded), 6 portrait → 1: with k=2,
    # EVERY shuffle order of LLLP forms at least one landscape group AND
    # at least one single flush (bucket boundary or odd remainder), so
    # the assertions below cannot depend on the shuffle seed
    assert loader.steps_per_epoch == 4

    consumer = threading.get_ident()
    calls = {"stacked": [], "single": []}
    real_stacked = trainer_mod.shard_stacked_batch
    real_single = trainer_mod.shard_batch

    def spy_stacked(plan, batch):
        calls["stacked"].append(threading.get_ident())
        return real_stacked(plan, batch)

    def spy_single(plan, batch):
        calls["single"].append(threading.get_ident())
        return real_single(plan, batch)

    monkeypatch.setattr(trainer_mod, "shard_stacked_batch", spy_stacked)
    monkeypatch.setattr(trainer_mod, "shard_batch", spy_single)

    # data=2, not 8: the k=2 scanned train step's CPU compile cost grows
    # pathologically with SPMD partition count (the 8-way version alone
    # took >10 min on the 1-core host), and every seam this test covers —
    # wrap install, producer-thread transfer, bucket flush, step count —
    # is partition-count-independent
    plan = make_mesh(jax.devices()[:2], data=2)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    before = np.asarray(params["rpn"]["rpn_conv_3x3"]["kernel"]).copy()

    state = fit(cfg, model, params, loader, begin_epoch=0, end_epoch=2,
                plan=plan, frequent=1, steps_per_dispatch=2)

    assert loader.wrap is not None, "fit did not install the group wrap"
    assert int(jax.device_get(state.step)) == 8  # 4 steps × 2 epochs
    after = np.asarray(jax.device_get(
        state.params["rpn"]["rpn_conv_3x3"]["kernel"]))
    assert np.isfinite(after).all()
    assert not np.allclose(after, before)
    # groups formed, singles flushed, and EVERY transfer ran off the
    # consumer thread — the whole point of the producer-thread assembler
    assert calls["stacked"], "no stacked group was shipped"
    assert calls["single"], "no bucket-change/remainder flush happened"
    assert consumer not in calls["stacked"] + calls["single"], (
        "a transfer ran on the consumer thread")
