"""fit() on the 8-device CPU mesh with a REAL AnchorLoader (VERDICT
round-1 item 7): the loader × data-parallel seam — shard_batch on loader
output, per-bucket compiled programs under one fit loop, and the
wrap-padded epoch tail — none of which the step-level mesh tests touch.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.data import AnchorLoader, SyntheticDataset
from mx_rcnn_tpu.models import build_model, init_params
from mx_rcnn_tpu.parallel import make_mesh
from mx_rcnn_tpu.train import fit


def mesh_cfg():
    cfg = generate_config(
        "resnet50", "PascalVOC",
        TRAIN__RPN_PRE_NMS_TOP_N=200, TRAIN__RPN_POST_NMS_TOP_N=32,
        TRAIN__BATCH_ROIS=16, TRAIN__FLIP=False,
    )
    net = dataclasses.replace(cfg.network, ANCHOR_SCALES=(2, 4),
                              PIXEL_STDS=(127.0, 127.0, 127.0))
    tpu = dataclasses.replace(cfg.tpu, SCALES=((64, 96),), MAX_GT=4)
    return cfg.replace(network=net, tpu=tpu)


def test_fit_loader_on_mesh():
    """Global batch 8 over 8 devices, mixed-orientation roidb (landscape +
    portrait → TWO bucket programs inside one fit), epoch not divisible by
    the batch (wrap-padded tail batch)."""
    cfg = mesh_cfg()
    # 10 landscape + 6 portrait images: neither bucket divides batch 8, so
    # both epoch tails wrap; orientations land in different buckets
    land = SyntheticDataset(num_images=10, num_classes=cfg.NUM_CLASSES,
                            height=64, width=96, seed=0).gt_roidb()
    port = SyntheticDataset(num_images=6, num_classes=cfg.NUM_CLASSES,
                            height=96, width=64, seed=1).gt_roidb()
    roidb = land + port
    loader = AnchorLoader(roidb, cfg, batch_size=8, shuffle=True, seed=0)

    # the loader must actually emit both bucket shapes (the per-bucket
    # program seam this test exists for)
    shapes = {b["images"].shape[1:3] for b in loader}
    assert len(shapes) == 2, shapes

    plan = make_mesh(data=8)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 1, (64, 96))
    before = np.asarray(params["rpn"]["rpn_conv_3x3"]["kernel"]).copy()
    frozen_before = np.asarray(params["backbone"]["conv1"]["kernel"]).copy()

    state = fit(cfg, model, params, loader, begin_epoch=0, end_epoch=2,
                plan=plan, frequent=1)

    got = jax.device_get(state.params)
    after = np.asarray(got["rpn"]["rpn_conv_3x3"]["kernel"])
    assert np.isfinite(after).all()
    assert not np.allclose(after, before), "trainable params did not move"
    np.testing.assert_array_equal(
        np.asarray(got["backbone"]["conv1"]["kernel"]), frozen_before)
    # both epochs' steps ran: 2 buckets × ceil(10/8 + 6/8) = 2 + 1 = 3
    # steps/epoch × 2 epochs
    assert int(jax.device_get(state.step)) == 6


def test_multi_step_on_mesh_matches_single():
    """make_multi_train_step over the 8-device DP mesh (stacked batch
    shardings + shard_stacked_batch) at k=1: parity with the single-step
    mesh program — the inductive contract; k>1 numeric parity is chaotic
    (see test_train.test_multi_step_matches_sequential docstring).  The
    k=2 real-loader path is covered structurally by
    test_train.test_fit_steps_per_dispatch_smoke."""
    from mx_rcnn_tpu.parallel import shard_batch, shard_stacked_batch
    from mx_rcnn_tpu.train import (create_train_state, make_multi_train_step,
                                   make_train_step)
    from tests.test_train import make_batch

    cfg = mesh_cfg()
    plan = make_mesh(data=8)
    model = build_model(cfg)
    params = init_params(model, cfg, jax.random.PRNGKey(0), 8, (64, 96))
    state0, tx, mask = create_train_state(cfg, params, steps_per_epoch=10)
    state0 = jax.device_put(state0, plan.replicated())
    batch = make_batch(8, seed=0)
    key = jax.random.PRNGKey(7)

    step = make_train_step(model, tx, plan=plan, trainable_mask=mask,
                           donate=False)
    seq, _ = step(state0, shard_batch(plan, batch),
                  jax.random.fold_in(key, 0))

    multi = make_multi_train_step(model, tx, 1, plan=plan,
                                  trainable_mask=mask, donate=False)
    stacked = shard_stacked_batch(
        plan, jax.tree.map(lambda x: np.stack([x]), batch))
    got, _ = multi(state0, stacked, key)

    assert int(jax.device_get(got.step)) == 1
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(jax.device_get(a), np.float32),
            np.asarray(jax.device_get(b), np.float32),
            rtol=1e-4, atol=1e-5),
        got.params, seq.params)
