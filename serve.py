#!/usr/bin/env python
"""Online serving driver: checkpoint → warmed ServeEngine → HTTP frontend.

The online counterpart of test.py's offline loop (ROADMAP north star:
"serves heavy traffic"): load a checkpoint (or ``--synthetic`` random
weights for smoke/CI), pre-compile every (bucket, batch) program, then
serve ``/predict`` with bucket-aware dynamic batching until SIGTERM/SIGINT.

    # smoke: synthetic weights, tiny buckets, TCP on 8321
    python serve.py --network resnet50 --synthetic --port 8321 \
        --cfg "tpu__SCALES=((96,128),)" --serve-batch 4 --max-delay-ms 20

    # production-shaped: real checkpoint, telemetry on
    python serve.py --network resnet101 --prefix model/e2e --epoch 10 \
        --port 8321 --serve-batch 8 --max-delay-ms 10 --telemetry-dir /tmp/t

    # self-healing plane: 2 supervised replicas behind a router, rolling
    # checkpoint hot-reload as training writes new saves
    python serve.py --network resnet101 --prefix model/e2e --epoch 10 \
        --port 8321 --replicas 2 --watch-checkpoints model/e2e

    # cross-host fabric (ISSUE 12): a router that members join over TCP
    python serve.py --fabric --port 8320                  # the router
    python serve.py --network resnet50 --synthetic --port 8321 \
        --join 127.0.0.1:8320                             # a member

Scale-out contract (``--replicas N``): the parent builds NO model — it
runs the ReplicaSupervisor + ReplicaRouter (serve/supervisor.py) over N
child processes of this same script (``--replica-index I``, internal),
each a full Predictor→engine→HTTP stack on its own Unix socket.  Replica
failure is a 503-shed + retry-on-alternate + backoff respawn; SIGTERM
drains gracefully and a SECOND SIGTERM hard-aborts (flight dump +
SIGKILL the children) so a wedged drain can never hang shutdown.  At
``--replicas 1`` (default) behavior is unchanged from before the plane
existed.
"""

from __future__ import annotations

import argparse
import atexit
import os
import signal
import tempfile
import threading

from mx_rcnn_tpu import telemetry
from mx_rcnn_tpu.logger import logger
from mx_rcnn_tpu.tools.common import (add_common_args, apply_program_cache,
                                      config_from_args,
                                      eval_params_from_args,
                                      start_observability)


def parse_args():
    parser = argparse.ArgumentParser(
        description="Serve a Faster R-CNN network over HTTP")
    add_common_args(parser, train=False)
    parser.add_argument("--port", type=int, default=0,
                        help="TCP port for the HTTP frontend")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--unix-socket", default="", dest="unix_socket",
                        help="serve HTTP over this Unix socket instead of "
                             "TCP (tests, local sidecars)")
    parser.add_argument("--serve-batch", type=int, default=4,
                        dest="serve_batch",
                        help="images per forward — every batch is padded "
                             "to exactly this size (one program per "
                             "bucket)")
    parser.add_argument("--max-delay-ms", type=float, default=10.0,
                        dest="max_delay_ms",
                        help="flush a partial batch once its oldest "
                             "request has waited this long; THE latency/"
                             "throughput knob (0 = no coalescing wait)")
    parser.add_argument("--serve-e2e", action="store_true",
                        dest="serve_e2e",
                        help="single-dispatch serving: stage raw uint8 on "
                             "the caller thread and run device prep + "
                             "forward + decode/NMS as ONE fused program "
                             "per (bucket, batch, dtype) — one host→device"
                             " transfer, one dispatch, one (B, cap, 6) "
                             "readback per batch.  Off (default) "
                             "reproduces the classic host-prep path "
                             "byte-for-byte")
    parser.add_argument("--stream", action="store_true",
                        help="enable POST /stream sequenced-frame "
                             "streaming (single-process mode only): "
                             "per-stream state over the same batcher, so "
                             "same-bucket frames from different streams "
                             "coalesce into shared dispatches")
    parser.add_argument("--stream-skip-thresh", type=float, default=0.0,
                        dest="stream_skip_thresh",
                        help="frame-delta skip gate: mean absolute uint8 "
                             "pixel delta (on-device, vs the stream's "
                             "reference frame) below which a frame "
                             "answers with cached detections and no "
                             "forward.  0 (default) disables the gate — "
                             "gate-off streaming is byte-identical to "
                             "per-frame /predict")
    parser.add_argument("--stream-max-skip", type=int, default=30,
                        dest="stream_max_skip",
                        help="force a full forward after this many "
                             "consecutive skips, bounding detection "
                             "staleness on static scenes")
    parser.add_argument("--max-queue", type=int, default=64,
                        dest="max_queue",
                        help="bounded-queue backpressure: submits beyond "
                             "this many pending requests get 503")
    parser.add_argument("--deadline-ms", type=float, default=30000.0,
                        dest="deadline_ms",
                        help="default per-request deadline (504 when "
                             "exceeded; requests may override; <=0 "
                             "disables)")
    parser.add_argument("--target-p99-ms", type=float, default=0.0,
                        dest="target_p99_ms",
                        help="enable the SLO controller: adapt per-bucket "
                             "flush batch/delay toward this end-to-end "
                             "request-time p99 and shed load (503) when "
                             "the queue trend predicts misses (0 = off)")
    parser.add_argument("--slo-interval-ms", type=float, default=500.0,
                        dest="slo_interval_ms",
                        help="SLO controller tick period")
    parser.add_argument("--slo-window-s", type=float, default=10.0,
                        dest="slo_window_s",
                        help="trailing window the controller's p99 is "
                             "computed over")
    parser.add_argument("--replicas", type=int, default=1,
                        help="run N supervised engine replicas behind a "
                             "router (1 = the classic single-process "
                             "server, unchanged)")
    parser.add_argument("--replica-index", type=int, default=-1,
                        dest="replica_index",
                        help=argparse.SUPPRESS)  # internal: child mode
    parser.add_argument("--replica-devices", default="",
                        dest="replica_devices",
                        help="semicolon-separated device groups, one per "
                             "replica (group i lands in child env "
                             "MXR_REPLICA_DEVICES for the deployment "
                             "image to map onto TPU_VISIBLE_CHIPS / "
                             "CUDA_VISIBLE_DEVICES)")
    parser.add_argument("--watch-checkpoints", default="",
                        dest="watch_checkpoints",
                        help="poll this checkpoint prefix (PR-2 layout: "
                             "epoch dirs + steps/) and hot-reload new "
                             "generations with zero downtime — rolling "
                             "across replicas, canary-gated, rollback on "
                             "non-finite outputs")
    parser.add_argument("--watch-interval-s", type=float, default=5.0,
                        dest="watch_interval_s",
                        help="checkpoint watcher poll period")
    # -- cross-host fabric (ISSUE 12) — all opt-in; the fork-based
    # --replicas path is untouched when none of these are passed
    parser.add_argument("--fabric", action="store_true",
                        help="run the cross-host fabric router: remote "
                             "members join via --join/--pool-file//admin/"
                             "register; with --replicas N local fork "
                             "children serve alongside them")
    parser.add_argument("--pool-file", default="", dest="pool_file",
                        help="seed fabric membership from this file (one "
                             "HOST:PORT or unix socket path per line; "
                             "implies --fabric)")
    parser.add_argument("--join", default="",
                        help="run as a fabric MEMBER: serve on --port and "
                             "register with the fabric router at this "
                             "HOST:PORT once warm")
    parser.add_argument("--advertise", default="",
                        help="address to advertise to the router on "
                             "--join (default: --host:--port — set this "
                             "when members sit behind NAT/containers)")
    parser.add_argument("--hedge-after-ms", type=float, default=0.0,
                        dest="hedge_after_ms",
                        help="router tail hedging: duplicate a request "
                             "still unanswered after this long to a "
                             "second member and take the first 2xx "
                             "(0 = off)")
    parser.add_argument("--partition-floor", type=float, default=0.5,
                        dest="partition_floor",
                        help="ready-member fraction below which the "
                             "router flight-dumps fabric_partition (it "
                             "keeps serving the reachable subset "
                             "regardless)")
    parser.add_argument("--probe-interval-s", type=float, default=1.0,
                        dest="probe_interval_s",
                        help="fabric membership probe period")
    # -- elastic autoscaling (ISSUE 18) — OFF by default: without
    # --autoscale no CapacityAuthority is ever constructed and the
    # fabric serves the fixed fleet byte-for-byte as before
    parser.add_argument("--autoscale", action="store_true",
                        help="run the capacity authority on the fabric "
                             "router: forecast demand from queue-depth "
                             "trends and scale the fleet between "
                             "--autoscale-min/--autoscale-max by "
                             "unparking drained members, admitting "
                             "standbys, or forking local replicas — "
                             "never recompiling (capacity warms from "
                             "the shared AOT cache)")
    parser.add_argument("--autoscale-min", type=int, default=1,
                        dest="autoscale_min",
                        help="fleet floor: never drain below this many "
                             "capacity members")
    parser.add_argument("--autoscale-max", type=int, default=4,
                        dest="autoscale_max",
                        help="fleet ceiling: never grow past this many "
                             "capacity members")
    parser.add_argument("--autoscale-target-depth", type=float,
                        default=4.0, dest="autoscale_target_depth",
                        help="target utilization: forecast demand "
                             "(queue depth + inflight) per ready member "
                             "above which the fleet grows; scale-down "
                             "needs sustained load below half of it")
    parser.add_argument("--autoscale-interval-s", type=float, default=1.0,
                        dest="autoscale_interval_s",
                        help="capacity authority tick period")
    parser.add_argument("--autoscale-standby", default="",
                        dest="autoscale_standby",
                        help="comma-separated member addresses the "
                             "authority may admit when demand outgrows "
                             "the registered fleet (parked members are "
                             "always preferred — they are already warm)")
    # -- data flywheel request capture (ISSUE 13) — OFF by default: the
    # engine keeps its NULL capture sink (zero hot-path work) unless a
    # capture dir is configured
    parser.add_argument("--capture-dir", default="", dest="capture_dir",
                        help="spill sampled request captures (staged "
                             "pixels + detections + score stats, PII-free)"
                             " as atomic JSONL+npz shards here for the "
                             "flywheel miner (off when unset)")
    parser.add_argument("--capture-sample", type=int, default=1,
                        dest="capture_sample",
                        help="capture every Nth served request")
    parser.add_argument("--capture-bytes", type=int, default=256 << 20,
                        dest="capture_bytes",
                        help="capture-dir byte budget: oldest shard pairs "
                             "rotate out beyond this")
    parser.add_argument("--capture-shard-records", type=int, default=32,
                        dest="capture_shard_records",
                        help="records per spilled shard pair")
    parser.add_argument("--capture-member", default=None,
                        dest="capture_member",
                        help="fleet member id folded into shard/manifest "
                             "names when several members share one "
                             "capture dir (default: hostname)")
    # -- multi-model serving (ISSUE 15) — all opt-in; without --models
    # the single-model boot path is byte-for-byte unchanged
    parser.add_argument("--models", default="",
                        help="serve SEVERAL models from one process: "
                             "comma-separated ID=NETWORK entries (e.g. "
                             "'box=resnet50,mask=resnet101').  Requests "
                             "route with /predict?model=ID (default: the "
                             "first entry); each model gets its own "
                             "config, Predictor, program registry/AOT "
                             "subtree, bucket queues, and SLO controller. "
                             "Single-process mode only")
    parser.add_argument("--model-arg", action="append", default=[],
                        dest="model_arg", metavar="ID:KEY=VALUE",
                        help="per-model override, repeatable.  KEYs: "
                             "prefix, epoch (checkpoint source), "
                             "cfg (an extra --cfg style PATH=VALUE), "
                             "pin (1 = never page this model's weights "
                             "out), weight (scheduling/SLO class, "
                             "default 1.0), target-p99-ms (per-model SLO "
                             "controller target; overrides the global "
                             "--target-p99-ms), fidelity ('cascade' "
                             "[default] gates through --cascade, 'full' "
                             "pins the tenant to the big model "
                             "unconditionally)")
    # -- cascade serving (ISSUE 19) — opt-in; without --cascade no router
    # is built and the --models pool serves byte-for-byte as before
    parser.add_argument("--cascade", default="", metavar="SMALL:BIG",
                        help="accuracy-aware model cascade over two "
                             "--models entries: every gated request "
                             "first hits SMALL; frames whose on-device "
                             "confidence-gate hardness (the flywheel "
                             "miner's definition) clears --cascade-thresh "
                             "escalate to BIG — the staged pixels are "
                             "reused, never re-staged, and escalated "
                             "frames feed the capture ring tagged "
                             "cascade_escalated.  Requires --models and "
                             "--serve-e2e")
    parser.add_argument("--cascade-thresh", type=float, default=0.5,
                        dest="cascade_thresh",
                        help="escalation threshold in [0, 1] of the "
                             "hardness scale: 0 escalates every frame "
                             "(big-only answers), 1 none (small-only). "
                             "Calibrate against the live hardness "
                             "histogram on /metrics (cascade.latency."
                             "hardness_p50)")
    parser.add_argument("--weight-budget-mb", type=float, default=0.0,
                        dest="weight_budget_mb",
                        help="device weight-residency byte budget for "
                             "--models: param trees beyond it are paged "
                             "host<->device (LRU by last dispatch, "
                             "pinned models exempt).  0 = unbounded")
    # -- distributed request tracing (ISSUE 16) — OFF by default: every
    # hop keeps the NULL tracer (one attribute check, zero span work)
    parser.add_argument("--trace", action="store_true",
                        help="enable distributed request tracing: mint/"
                             "accept X-Mxr-Trace contexts at the frontend,"
                             " record per-hop spans (router pick/hedge/"
                             "retry, pool sched, stream gate, engine "
                             "batch-causality) to spans_<member>.jsonl "
                             "under --trace-dir, tail-sample slow/errored "
                             "trees to trace_tail_<member>.jsonl; query "
                             "with scripts/trace_query.py")
    parser.add_argument("--trace-dir", default="", dest="trace_dir",
                        help="span-file directory (default: "
                             "--telemetry-dir; one of the two is required "
                             "with --trace)")
    parser.add_argument("--trace-sample", type=float, default=1.0,
                        dest="trace_sample",
                        help="fraction of frontend-minted traces that are "
                             "sampled (client-sent contexts keep their "
                             "own sampled flag)")
    parser.add_argument("--trace-tail-budget", type=int, default=256,
                        dest="trace_tail_budget",
                        help="kept slow/errored span trees in the tail "
                             "ring (oldest evicted beyond this)")
    # -- watchtower alerting (ISSUE 20) — OFF by default: without
    # --watch/--alert-rules no Watchtower is ever constructed — no
    # monitor thread, no metric-history ring, and /metrics + the
    # telemetry JSONL stream are byte-for-byte the watch-off output
    parser.add_argument("--watch", action="store_true",
                        help="run the watchtower: evaluate the alert-rule "
                             "pack (telemetry/rules_default.json unless "
                             "--alert-rules) against live telemetry every "
                             "--watch-tick-s — SLO error-budget burn "
                             "rates, thresholds, absence, trends; alerts "
                             "surface on /alerts, /metrics "
                             "(mxr_alert_state), and alerts_<member>."
                             "jsonl, and a newly-firing alert "
                             "flight-dumps with recent tail trace ids "
                             "attached.  On the fabric router, rules with "
                             "scope=fleet evaluate per member")
    parser.add_argument("--alert-rules", default="", dest="alert_rules",
                        help="alert-rule pack JSON to evaluate (implies "
                             "--watch); a bad pack is a clean boot error "
                             "naming the offending rule")
    parser.add_argument("--watch-tick-s", type=float, default=1.0,
                        dest="watch_tick_s",
                        help="watchtower evaluation tick period")
    return parser.parse_args()


def _configure_tracing(args, member: str, rank: int = 0) -> None:
    """--trace → an active tracer for this process; without the flag,
    honor the MXR_TRACE_DIR env opt-in (subprocess members inherit it),
    else leave the NULL tracer in place.  Closed via atexit so the tail
    ring and spans stream land on every normal exit path."""
    from mx_rcnn_tpu.telemetry import tracectx

    if getattr(args, "trace", False):
        out_dir = args.trace_dir or args.telemetry_dir
        if not out_dir:
            raise SystemExit("--trace needs --trace-dir or "
                             "--telemetry-dir")
        tracectx.configure(out_dir, member=member, rank=rank,
                           sample=args.trace_sample,
                           tail_budget=args.trace_tail_budget)
        atexit.register(tracectx.shutdown)
        logger.info("tracing: spans_%s.jsonl under %s (sample=%.2f)",
                    member, out_dir, args.trace_sample)
    elif tracectx.configure_from_env(member=member, rank=rank) is not None:
        atexit.register(tracectx.shutdown)


def _build_watch(args, member: str, **providers):
    """--watch/--alert-rules → a started :class:`Watchtower` for this
    process, else None — and None means NOTHING was constructed: no
    monitor thread, no history ring, no alert log.  ``providers`` are
    the per-mode sampling closures (summary_fn/hists_fn on an engine
    process, fleet_fn/summaries_fn on the fabric router).  A bad rule
    pack is a clean boot error naming the offending rule."""
    if not (getattr(args, "watch", False) or
            getattr(args, "alert_rules", "")):
        return None
    from mx_rcnn_tpu.telemetry.watch import (RuleError, WatchOptions,
                                             Watchtower, load_rules)

    try:
        rules = (load_rules(args.alert_rules) if args.alert_rules
                 else None)
        watch = Watchtower(
            rules=rules, member=member,
            opts=WatchOptions(interval_s=args.watch_tick_s),
            out_dir=args.telemetry_dir or None, **providers)
    except (RuleError, ValueError, OSError) as e:
        raise SystemExit(f"--alert-rules: {e}")
    watch.start()
    return watch


def parse_model_specs(models: str, model_args) -> list:
    """``--models a=resnet50,b=vgg16`` + repeated ``--model-arg
    ID:KEY=VALUE`` → ordered spec dicts (first entry = default model)."""
    specs = []
    by_id = {}
    for entry in models.split(","):
        entry = entry.strip()
        if not entry:
            continue
        mid, _, network = entry.partition("=")
        mid, network = mid.strip(), network.strip()
        if not mid or not network:
            raise SystemExit(f"--models entries are ID=NETWORK, got "
                             f"{entry!r}")
        if mid in by_id:
            raise SystemExit(f"--models: duplicate model id {mid!r}")
        spec = {"id": mid, "network": network, "prefix": None,
                "epoch": None, "cfg": [], "pin": False, "weight": 1.0,
                "target_p99_ms": None, "fidelity": "cascade"}
        by_id[mid] = spec
        specs.append(spec)
    for arg in model_args or []:
        mid, sep, kv = arg.partition(":")
        key, sep2, val = kv.partition("=")
        if not sep or not sep2 or mid.strip() not in by_id:
            raise SystemExit(f"--model-arg is ID:KEY=VALUE with ID from "
                             f"--models, got {arg!r}")
        spec, key = by_id[mid.strip()], key.strip().replace("-", "_")
        if key == "cfg":
            spec["cfg"].append(val)
        elif key == "pin":
            spec["pin"] = val.strip().lower() in ("1", "true", "yes")
        elif key == "weight":
            spec["weight"] = float(val)
        elif key == "target_p99_ms":
            spec["target_p99_ms"] = float(val)
        elif key == "fidelity":
            spec["fidelity"] = val.strip()
        elif key in ("prefix", "epoch"):
            spec[key] = int(val) if key == "epoch" else val
        else:
            raise SystemExit(f"--model-arg: unknown key {key!r}")
    if not specs:
        raise SystemExit("--models parsed to zero entries")
    return specs


def _install_signals(done: threading.Event, hard_cleanup=None):
    """First SIGTERM/SIGINT = graceful drain (flight-record + set
    ``done``); the SECOND = hard abort — flight dump, SIGKILL any child
    replicas, ``os._exit`` — so a wedged drain can't hang shutdown."""
    state = {"armed": False}

    def _on_signal(signum, frame):
        name = signal.Signals(signum).name
        if state["armed"]:
            telemetry.get().dump_flight("hard_abort", signal=name)
            logger.error("second %s: hard abort", name)
            if hard_cleanup is not None:
                try:
                    hard_cleanup()
                except Exception:  # noqa: BLE001 — exiting anyway
                    pass
            os._exit(130)
        state["armed"] = True
        telemetry.get().dump_flight("preempt_signal", signal=name)
        done.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _on_signal)


def _build_engine(args, cfg, external: bool = False):
    """checkpoint → Predictor → started ServeEngine (single + replica
    paths share this; the supervisor parent never builds one).
    ``external=True`` (multi-model pool mode) skips the engine's own
    dispatcher thread — the ModelPool flushes it instead."""
    from mx_rcnn_tpu.eval import Predictor
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.serve import ServeEngine, ServeOptions
    from mx_rcnn_tpu.tools.common import calibrate_from_args

    apply_program_cache(args)  # before the Predictor builds its registry
    model = build_model(cfg)
    params = eval_params_from_args(args, cfg, model)
    # --calibrate-shard: activation scales from the FLOAT params, persisted
    # next to the AOT markers BEFORE the Predictor quantizes its copy
    act_scales = calibrate_from_args(args, cfg, model, params)
    predictor = Predictor(model, params, cfg, dtype=args.infer_dtype,
                          act_scales=act_scales)
    engine = ServeEngine(predictor, cfg, ServeOptions(
        batch_size=args.serve_batch, max_delay_ms=args.max_delay_ms,
        max_queue=args.max_queue, deadline_ms=args.deadline_ms,
        # the common --loader-workers flag doubles as the serving prep
        # pool size (same data/workers.py pool, image-only tasks)
        prep_workers=args.loader_workers or 0,
        serve_e2e=getattr(args, "serve_e2e", False)))
    if getattr(args, "capture_dir", ""):
        from mx_rcnn_tpu.flywheel import CaptureOptions, RequestCapture

        engine.capture = RequestCapture(CaptureOptions(
            capture_dir=args.capture_dir,
            sample_every=args.capture_sample,
            shard_records=args.capture_shard_records,
            byte_budget=args.capture_bytes,
            member=getattr(args, "capture_member", None)))
    engine.start(external=external)
    return predictor, engine


def _build_pool(args):
    """--models → a started :class:`ModelPool`: per model, its own
    config/Predictor/engine (external-dispatch) + per-model warmup, one
    cross-model dispatcher, LRU weight residency under
    --weight-budget-mb, and a per-model SLO controller when a p99 target
    is set.  Returns (pool, streams, cascade) — streams only under
    --stream, cascade (a warmed CascadeRouter) only under --cascade."""
    from mx_rcnn_tpu.serve import (CascadeRouter, ControllerOptions,
                                   ModelPool, SLOController, StreamManager,
                                   StreamOptions, warmup)

    specs = parse_model_specs(args.models, args.model_arg)
    pool = ModelPool(
        budget_bytes=int(args.weight_budget_mb * (1 << 20)))
    pool.start()
    streams = {}
    for i, spec in enumerate(specs):
        margs = argparse.Namespace(**vars(args))
        margs.network = spec["network"]
        margs.cfg = list(args.cfg) + list(spec["cfg"])
        if spec["prefix"] is not None:
            margs.prefix = spec["prefix"]
        if spec["epoch"] is not None:
            margs.epoch = spec["epoch"]
        if i > 0:
            # one capture sink per process: shard files are not
            # model-namespaced, so only the default model captures
            margs.capture_dir = ""
        cfg = config_from_args(margs, train=False)
        predictor, engine = _build_engine(margs, cfg, external=True)
        target = spec["target_p99_ms"]
        if target is None and args.target_p99_ms > 0:
            target = args.target_p99_ms
        controller = None
        if target:
            controller = SLOController(engine, ControllerOptions(
                target_p99_ms=target,
                interval_s=args.slo_interval_ms / 1e3,
                window_s=args.slo_window_s, label=spec["id"]))
        pool.add_model(spec["id"], cfg, predictor, engine,
                       controller=controller, pinned=spec["pin"],
                       weight=spec["weight"], fidelity=spec["fidelity"])
        # warm THIS model before building the next: the most recent
        # owning registry points the process-global jax compilation
        # cache at its dtype dir, so compiles must land while their
        # model's registry is the active one for AOT markers to agree
        # with where the executables persisted
        warmup(engine)
        if args.stream:
            sm = StreamManager(engine, StreamOptions(
                skip_thresh=args.stream_skip_thresh,
                max_skip=args.stream_max_skip))
            sm.warmup()
            streams[spec["id"]] = sm
        if controller is not None:
            controller.start()
    cascade = None
    if getattr(args, "cascade", ""):
        small, sep, big = args.cascade.partition(":")
        small, big = small.strip(), big.strip()
        if not sep or not small or not big:
            raise SystemExit(f"--cascade is SMALL:BIG with ids from "
                             f"--models, got {args.cascade!r}")
        try:
            cascade = CascadeRouter(pool, small, big,
                                    thresh=args.cascade_thresh)
        except (KeyError, ValueError) as e:
            raise SystemExit(f"--cascade: {e}")
        # ready the gate program now, after the per-model warmups — a
        # cascade boot compiles everything before mark_ready, so the
        # steady state (and the zero-recompile contract) covers the gate
        cascade.warmup()
        pool.cascade = cascade
        if small in streams:
            # cascade-route the small model's streams: hard frames of a
            # camera escalate exactly like hard /predict images
            streams[small].cascade = cascade
        logger.info("cascade: %s -> %s at thresh %.3f (gate program "
                    "warm)", small, big, args.cascade_thresh)
    return pool, streams, cascade


def main_single(args):
    """The classic single-process server (--replicas 1), plus optional
    in-process checkpoint hot-reload when --watch-checkpoints is set."""
    from mx_rcnn_tpu.serve import (CheckpointWatcher, ControllerOptions,
                                   SLOController, StreamManager,
                                   StreamOptions, make_server,
                                   reload_engine_params, warmup)

    if not args.unix_socket and not args.port:
        raise SystemExit("pass --port or --unix-socket")
    cfg = config_from_args(args, train=False)
    # the plane owns the sink (configure → summary → shutdown) and, with
    # --obs-port, the live Prometheus endpoint; the frontend's own
    # /metrics keeps serving regardless (JSON + ?format=prom)
    obs = start_observability(args, "serve",
                              run_meta={"network": args.network,
                                        "serve_batch": args.serve_batch,
                                        "max_delay_ms": args.max_delay_ms},
                              configure_telemetry=True)
    _configure_tracing(args, "server")
    predictor, engine = _build_engine(args, cfg)
    warmup(engine)
    stream = None
    if args.stream:
        stream = StreamManager(engine, StreamOptions(
            skip_thresh=args.stream_skip_thresh,
            max_skip=args.stream_max_skip))
        # gate on: ready the per-bucket frame_delta programs now, like
        # warmup() readied the forwards — steady-state streaming never
        # compiles, and a warm AOT cache covers the gate too
        stream.warmup()
    controller = None
    if args.target_p99_ms > 0:
        controller = SLOController(engine, ControllerOptions(
            target_p99_ms=args.target_p99_ms,
            interval_s=args.slo_interval_ms / 1e3,
            window_s=args.slo_window_s)).start()

    watcher = None
    if args.watch_checkpoints:
        def _reload(target):
            ok, info = reload_engine_params(
                engine, predictor, cfg,
                dict(target, generation=engine.generation + 1))
            return ok

        watcher = CheckpointWatcher(args.watch_checkpoints, _reload,
                                    interval_s=args.watch_interval_s)
        watcher.start()

    # watchtower over THIS engine: summary counters/gauges feed the
    # history ring, the engine's live latency hists feed burn rules
    from mx_rcnn_tpu.telemetry.obs import engine_summary
    watch = _build_watch(
        args, "server",
        summary_fn=lambda: engine_summary(engine),
        hists_fn=lambda: {**telemetry.get().live_hists(),
                          **engine.latency_hists()})

    server = make_server(engine, port=args.port or None, host=args.host,
                         unix_socket=args.unix_socket or None,
                         stream=stream, watch=watch)
    # serve_forever on a worker thread; the main thread parks on an event
    # the signal handlers set — shutdown() called from the serving thread
    # itself would deadlock its poll loop
    done = threading.Event()
    _install_signals(done)
    t = threading.Thread(target=server.serve_forever, name="serve-http",
                         daemon=True)
    t.start()
    where = args.unix_socket or f"http://{args.host}:{args.port}"
    logger.info("serving %s on %s (batch=%d, max_delay=%.0fms, "
                "max_queue=%d)", args.network, where, args.serve_batch,
                args.max_delay_ms, args.max_queue)
    done.wait()
    logger.info("shutting down: %s", engine.metrics()["counters"])
    server.shutdown()
    if watch is not None:
        watch.stop()  # no alert churn from the drain itself
    if watcher is not None:
        watcher.stop()
    if controller is not None:
        controller.stop()
    engine.stop()
    extra = {"serve": engine.metrics()}
    if watch is not None:
        extra["watch"] = watch.state()
    obs.close(extra=extra)


def main_multimodel(args):
    """One process, N models (--models): a ModelPool behind the single
    frontend — zero-recompile per-model routing, cross-model batch
    interleaving, bounded weight residency, per-model SLO isolation."""
    from mx_rcnn_tpu.serve import make_server

    if not args.unix_socket and not args.port:
        raise SystemExit("pass --port or --unix-socket")
    obs = start_observability(args, "serve",
                              run_meta={"models": args.models,
                                        "serve_batch": args.serve_batch,
                                        "max_delay_ms": args.max_delay_ms},
                              configure_telemetry=True)
    _configure_tracing(args, "server")
    pool, streams, cascade = _build_pool(args)
    default = pool.default_model
    server = make_server(pool.engine_for(default),
                         port=args.port or None, host=args.host,
                         unix_socket=args.unix_socket or None,
                         stream=streams.get(default), pool=pool,
                         streams=streams, cascade=cascade)
    done = threading.Event()
    _install_signals(done)
    t = threading.Thread(target=server.serve_forever, name="serve-http",
                         daemon=True)
    t.start()
    where = args.unix_socket or f"http://{args.host}:{args.port}"
    logger.info("serving %d model(s) %s on %s (batch=%d, weight budget "
                "%.0f MB)", len(pool.model_ids()), pool.model_ids(),
                where, args.serve_batch, args.weight_budget_mb)
    done.wait()
    logger.info("shutting down: %s", pool.metrics()["pool"])
    server.shutdown()
    pool.stop()
    obs.close(extra={"serve": pool.metrics()})


def main_replica(args):
    """One supervised replica child (--replica-index I, internal): the
    full engine stack over the supervisor-assigned Unix socket, folding
    its telemetry as rank I+1 of a (replicas+1)-world so the parent's
    obs plane aggregates per-replica snapshots (the PR-5 mechanism)."""
    from mx_rcnn_tpu.serve import serve_replica

    assert args.unix_socket, "--replica-index requires --unix-socket"
    cfg = config_from_args(args, train=False)
    obs = start_observability(args, "serve",
                              rank=args.replica_index + 1,
                              world=max(args.replicas, 1) + 1,
                              run_meta={"network": args.network,
                                        "replica": args.replica_index},
                              configure_telemetry=True)
    _configure_tracing(args, f"member{args.replica_index}",
                       rank=args.replica_index + 1)
    predictor, engine = _build_engine(args, cfg)
    done = threading.Event()
    _install_signals(done)
    try:
        serve_replica(engine, cfg, args.unix_socket,
                      index=args.replica_index, predictor=predictor,
                      done=done)
    finally:
        obs.close(extra={"serve": engine.metrics()})


def main_plane(args):
    """The supervisor parent (--replicas N > 1): no model, no device —
    spawn N replica children, route /predict across the ready ones,
    respawn the dead, roll checkpoint generations through them."""
    import sys

    from mx_rcnn_tpu.serve import (CheckpointWatcher, ReplicaRouter,
                                   ReplicaSupervisor, make_router_server,
                                   replica_specs)

    if not args.unix_socket and not args.port:
        raise SystemExit("pass --port or --unix-socket")
    obs = start_observability(args, "serve", rank=0,
                              world=args.replicas + 1,
                              run_meta={"network": args.network,
                                        "replicas": args.replicas},
                              configure_telemetry=True)
    _configure_tracing(args, "router")
    sock_dir = tempfile.mkdtemp(prefix="mxr_replicas_")
    specs = replica_specs(sys.argv, args.replicas, sock_dir,
                          devices=args.replica_devices)
    sup = ReplicaSupervisor(specs)
    # no orphans: children die with the parent on EVERY exit path —
    # normal drain, exception, or the hard-abort signal escalation
    atexit.register(sup.sweep)
    done = threading.Event()
    _install_signals(done, hard_cleanup=lambda: sup.sweep(0.0))
    sup.start()
    router = ReplicaRouter(sup)
    server = make_router_server(router, port=args.port or None,
                                host=args.host,
                                unix_socket=args.unix_socket or None)
    watcher = None
    if args.watch_checkpoints:
        watcher = CheckpointWatcher(args.watch_checkpoints, sup.reload_to,
                                    interval_s=args.watch_interval_s)
        watcher.start()
    t = threading.Thread(target=server.serve_forever, name="router-http",
                         daemon=True)
    t.start()
    where = args.unix_socket or f"http://{args.host}:{args.port}"
    logger.info("serving plane: %d replica(s) behind %s (sockets under "
                "%s)", args.replicas, where, sock_dir)
    # park until a signal OR systemic failure (every replica FAILED)
    while not done.is_set():
        if sup.broken.wait(timeout=0.5):
            break
        if done.wait(timeout=0.5):
            break
    broken = sup.broken.is_set() and not done.is_set()
    logger.info("plane shutting down: %s", sup.metrics()["counters"])
    server.shutdown()
    if watcher is not None:
        watcher.stop()
    sup.stop()
    obs.close(extra={"replica_plane": sup.metrics()})
    if broken:
        raise SystemExit("serving plane is down: every replica crossed "
                         "the respawn limit (see flight dumps)")


def main_member(args):
    """A standalone fabric member (--join): the full engine stack over
    TCP, self-registering with the fabric router once warm.  Reloads
    arrive from the ROUTER's rolling ``/admin/reload`` — a member never
    watches checkpoints itself, or a roll would double-swap it."""
    import sys  # noqa: F401 — parallel to the other mains

    from mx_rcnn_tpu.serve import serve_replica

    if not args.unix_socket and not args.port:
        raise SystemExit("pass --port (or --unix-socket) for a fabric "
                         "member")
    cfg = config_from_args(args, train=False)
    index = int(os.environ.get("MXR_REPLICA_INDEX", "0"))
    obs = start_observability(args, "serve",
                              run_meta={"network": args.network,
                                        "join": args.join,
                                        "member_index": index},
                              configure_telemetry=True)
    _configure_tracing(args, f"member{index}", rank=index)
    predictor, engine = _build_engine(args, cfg)
    done = threading.Event()
    _install_signals(done)
    try:
        serve_replica(engine, cfg,
                      sock_path=args.unix_socket or None,
                      port=args.port or None, host=args.host,
                      index=index, predictor=predictor, done=done,
                      join=args.join, advertise=args.advertise or None)
    finally:
        obs.close(extra={"serve": engine.metrics()})


def main_fabric(args):
    """The fabric router (--fabric / --pool-file): probe-driven
    membership over remote TCP members (plus local fork children when
    --replicas N > 1), least-loaded routing, breakers, hedging, and
    rolling cross-member hot reload."""
    import sys

    from mx_rcnn_tpu.serve import (CheckpointWatcher, FabricOptions,
                                   FabricRouter, ReplicaPool,
                                   ReplicaSupervisor, make_fabric_server,
                                   replica_specs)

    if not args.unix_socket and not args.port:
        raise SystemExit("pass --port or --unix-socket")
    obs = start_observability(args, "serve",
                              run_meta={"network": args.network,
                                        "fabric": True,
                                        "replicas": args.replicas},
                              configure_telemetry=True)
    _configure_tracing(args, "router")
    pool = ReplicaPool(FabricOptions(
        probe_interval_s=args.probe_interval_s,
        hedge_after_ms=args.hedge_after_ms,
        partition_floor=args.partition_floor))
    done = threading.Event()
    sup = None
    if args.replicas > 1:
        sock_dir = tempfile.mkdtemp(prefix="mxr_replicas_")
        specs = replica_specs(sys.argv, args.replicas, sock_dir,
                              devices=args.replica_devices)
        sup = ReplicaSupervisor(specs)
        atexit.register(sup.sweep)
        _install_signals(done, hard_cleanup=lambda: sup.sweep(0.0))
        sup.start()
        pool.adopt_supervisor(sup)
    else:
        _install_signals(done)
    if args.pool_file:
        n = pool.load_pool_file(args.pool_file)
        logger.info("fabric: seeded %d member address(es) from %s",
                    n, args.pool_file)
    pool.start()
    router = FabricRouter(pool)
    authority = None
    if args.autoscale:
        from mx_rcnn_tpu.serve import AutoscalerOptions, CapacityAuthority
        standby = [a.strip()
                   for a in args.autoscale_standby.split(",") if a.strip()]
        authority = CapacityAuthority(
            pool, supervisor=sup, standby=standby,
            opts=AutoscalerOptions(
                min_members=args.autoscale_min,
                max_members=args.autoscale_max,
                target_depth=args.autoscale_target_depth,
                interval_s=args.autoscale_interval_s)).start()
        router.autoscaler = authority
    # watchtower over the FLEET: the pool folds to the per-member view
    # (absence/threshold rules), peer telemetry snapshots feed
    # fleet-scoped burn rules, and the router's own fabric/route_time
    # hist (observed only while a watchtower is attached) feeds local
    # burn rules on routed latency
    watch = None
    if args.watch or args.alert_rules:
        from mx_rcnn_tpu.telemetry.obs import read_peer_snapshots
        from mx_rcnn_tpu.telemetry.watch import fleet_from_pool

        summaries_fn = None
        if args.telemetry_dir:
            tdir = args.telemetry_dir
            summaries_fn = (lambda: {
                f"rank{r}": s
                for r, s in read_peer_snapshots(tdir)[0].items()})
        watch = _build_watch(args, "router",
                             fleet_fn=lambda: fleet_from_pool(pool),
                             summaries_fn=summaries_fn)
        router.watchtower = watch
    server = make_fabric_server(router, port=args.port or None,
                                host=args.host,
                                unix_socket=args.unix_socket or None)
    watcher = None
    if args.watch_checkpoints:
        watcher = CheckpointWatcher(args.watch_checkpoints,
                                    pool.reload_to,
                                    interval_s=args.watch_interval_s)
        watcher.start()
    t = threading.Thread(target=server.serve_forever, name="fabric-http",
                         daemon=True)
    t.start()
    where = args.unix_socket or f"http://{args.host}:{args.port}"
    logger.info("fabric router on %s (%d seeded member(s), %d local "
                "replica(s))", where, len(pool.members),
                args.replicas if sup is not None else 0)
    done.wait()
    logger.info("fabric shutting down: %s", pool.counters)
    server.shutdown()
    if watch is not None:
        watch.stop()  # no alert churn from the drain itself
    if authority is not None:
        authority.stop()  # no scale decisions during teardown
    if watcher is not None:
        watcher.stop()
    pool.stop()
    if sup is not None:
        sup.stop()
    extra = {"fabric": pool.metrics()}
    if authority is not None:
        extra["autoscale"] = authority.state()
    if watch is not None:
        extra["watch"] = watch.state()
    obs.close(extra=extra)


def choose_mode(args) -> str:
    """argv → serving mode.  Order is a contract: child replicas first
    (never recurse into a plane), then the opt-in fabric paths, then the
    PR-8 fork plane, else the classic single server.  With none of the
    fabric flags set, dispatch is EXACTLY the pre-fabric decision tree —
    the fork path cannot be perturbed by dormant fabric code."""
    if args.replica_index >= 0:
        return "replica"
    if getattr(args, "fabric", False) or getattr(args, "pool_file", ""):
        return "fabric"
    if getattr(args, "join", ""):
        return "member"
    if args.replicas > 1:
        return "plane"
    return "single"


def main(args):
    mode = choose_mode(args)
    if getattr(args, "cascade", "") and not getattr(args, "models", ""):
        raise SystemExit("--cascade routes between two --models entries; "
                         "pass --models SMALL=...,BIG=... (and "
                         "--serve-e2e)")
    if getattr(args, "models", ""):
        # the pool shares one device owner (its dispatcher thread); the
        # multi-process planes each bind a full device stack per child,
        # so --models composes with none of them (yet)
        if mode != "single":
            raise SystemExit(f"--models requires single-process mode "
                             f"(got mode {mode!r})")
        return main_multimodel(args)
    if getattr(args, "stream", False) and mode != "single":
        # stream state (reference frames, seq high-water marks) lives in
        # ONE engine's process; routing frames of a stream across
        # replicas/members would silently break the skip gate and seq
        # ordering, so refuse rather than degrade
        raise SystemExit(
            f"--stream requires single-process mode (got mode "
            f"{mode!r}: drop --replicas/--fabric/--join/--pool-file "
            f"or run one streaming server per device)")
    return {"replica": main_replica, "fabric": main_fabric,
            "member": main_member, "plane": main_plane,
            "single": main_single}[choose_mode(args)](args)


if __name__ == "__main__":
    main(parse_args())
